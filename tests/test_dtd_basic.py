"""DTD front-end tests.

Mirrors reference tests/dsl/dtd: task insertion, dependency chaining
(RAW/WAR/WAW), read fan-out, NEW tiles, window backpressure, multiple
schedulers (ref: dtd_test_task_insertion.c, dtd_test_war.c, Testings.cmake).
"""
import threading

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu import dtd
from parsec_tpu.dsl.dtd import INOUT, INPUT, OUTPUT, VALUE, unpack_args


def test_empty_taskpool_completes(ctx):
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    tp.wait()
    assert tp.completed


def test_single_task_runs(ctx):
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    ran = []

    def body(es, task):
        ran.append(task.snprintf())

    tp.insert_task(body)
    tp.wait()
    assert len(ran) == 1


def test_value_args(ctx):
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    got = []

    def body(es, task):
        got.append(unpack_args(task))

    tp.insert_task(body, (7, VALUE), "hello")
    tp.wait()
    assert got == [[7, "hello"]]


def test_raw_chain_order(ctx):
    """A chain of INOUT tasks on one tile must serialize in insert order."""
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    arr = np.zeros(1, dtype=np.int64)
    tile = tp.tile_of_array(arr)
    N = 50

    def body(es, task):
        (a, k) = unpack_args(task)
        assert a[0] == k, f"task {k} saw {a[0]}"
        a[0] += 1

    for k in range(N):
        tp.insert_task(body, (tile, INOUT), (k, VALUE))
    tp.wait()
    assert arr[0] == N


def test_read_fanout_then_war(ctx4):
    """Readers run concurrently after a write; next writer waits for all
    readers (ref: overlap_strategies.c WAR resolution)."""
    tp = dtd.taskpool_new()
    ctx4.add_taskpool(tp)
    arr = np.array([10.0])
    tile = tp.tile_of_array(arr)
    reads = []
    lock = threading.Lock()

    def writer(es, task):
        (a,) = unpack_args(task)
        a[0] = 99.0

    def reader(es, task):
        (a, i) = unpack_args(task)
        with lock:
            reads.append((i, a[0]))

    for i in range(8):
        tp.insert_task(reader, (tile, INPUT), (i, VALUE))
    tp.insert_task(writer, (tile, INOUT))
    tp.wait()
    assert len(reads) == 8
    # every reader must have seen the pre-write value
    assert all(v == 10.0 for _, v in reads)
    assert arr[0] == 99.0


def test_two_tile_diamond(ctx):
    """t1 writes A; t2,t3 read A write B/C; t4 reads B,C."""
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    A = tp.tile_of_array(np.zeros(1))
    B = tp.tile_of_array(np.zeros(1))
    C = tp.tile_of_array(np.zeros(1))
    out = []

    def t1(es, task):
        (a,) = unpack_args(task)
        a[0] = 1.0

    def t2(es, task):
        a, b = unpack_args(task)
        b[0] = a[0] + 10

    def t3(es, task):
        a, c = unpack_args(task)
        c[0] = a[0] + 20

    def t4(es, task):
        b, c = unpack_args(task)
        out.append(b[0] + c[0])

    tp.insert_task(t1, (A, INOUT))
    tp.insert_task(t2, (A, INPUT), (B, INOUT))
    tp.insert_task(t3, (A, INPUT), (C, INOUT))
    tp.insert_task(t4, (B, INPUT), (C, INPUT))
    tp.wait()
    assert out == [32.0]


def test_new_tile(ctx):
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    t = tp.tile_new((4,), dtype=np.float64)

    def init(es, task):
        (a,) = unpack_args(task)
        a[:] = 3.0

    def check(es, task):
        (a,) = unpack_args(task)
        assert np.all(a == 3.0)

    tp.insert_task(init, (t, INOUT))
    tp.insert_task(check, (t, INPUT))
    tp.wait()


def test_many_independent_tasks_all_run(ctx4):
    tp = dtd.taskpool_new()
    ctx4.add_taskpool(tp)
    counter = [0]
    lock = threading.Lock()

    def body(es, task):
        with lock:
            counter[0] += 1

    for _ in range(500):
        tp.insert_task(body)
    tp.wait()
    assert counter[0] == 500


def test_window_backpressure():
    """Insertion must not grow unbounded past the window (ref:
    insert_function.c:69-70 window/threshold)."""
    parsec_tpu.params.reset()
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        tp = dtd.taskpool_new()
        tp.window_size = 50
        tp.threshold_size = 25
        ctx.add_taskpool(tp)
        tile = tp.tile_of_array(np.zeros(1))

        def body(es, task):
            (a, _k) = unpack_args(task)
            a[0] += 1

        for k in range(300):
            tp.insert_task(body, (tile, INOUT), (k, VALUE))
            assert tp._outstanding <= 51
        tp.wait()
        assert tp._tiles is not None
    finally:
        ctx.fini()


@pytest.mark.parametrize("sched", ["lfq", "gd", "ap", "ip", "ll", "rnd",
                                   "spq", "pbq", "ltq", "lhq"])
def test_all_schedulers_run_dag(sched):
    """The full DAG correctness across every scheduler module
    (ref: tests/runtime/sched semantics tests)."""
    ctx = parsec_tpu.Context(nb_cores=2, scheduler=sched)
    try:
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        arr = np.zeros(1)
        tile = tp.tile_of_array(arr)

        def body(es, task):
            (a, k) = unpack_args(task)
            assert a[0] == k
            a[0] += 1

        for k in range(30):
            tp.insert_task(body, (tile, INOUT), (k, VALUE))
        tp.wait()
        assert arr[0] == 30
    finally:
        ctx.fini()


def test_flush_and_multiple_taskpools(ctx):
    tp1 = dtd.taskpool_new("one")
    tp2 = dtd.taskpool_new("two")
    ctx.add_taskpool(tp1)
    ctx.add_taskpool(tp2)
    a1 = np.zeros(1)
    a2 = np.zeros(1)
    t1 = tp1.tile_of_array(a1)
    t2 = tp2.tile_of_array(a2)

    def inc(es, task):
        (a,) = unpack_args(task)
        a[0] += 1

    tp1.insert_task(inc, (t1, INOUT))
    tp2.insert_task(inc, (t2, INOUT))
    tp1.data_flush_all()
    tp2.data_flush_all()
    tp1.wait()
    tp2.wait()
    assert a1[0] == 1 and a2[0] == 1
