"""Checkpoint/resume of collections (SURVEY.md §5.4 — absent in the
reference; here: quiescent-point tile snapshots per rank).
"""
import numpy as np
import pytest

import parsec_tpu
from conftest import spmd
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.utils import checkpoint as ckpt


def test_roundtrip_single_rank(tmp_path):
    rng = np.random.RandomState(0)
    M = rng.rand(96, 96).astype(np.float32)
    A = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32).from_numpy(M)
    prefix = str(tmp_path / "ck")
    path = ckpt.save_collection(A, prefix)
    B = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32)
    n = ckpt.restore_collection(B, prefix)
    assert n == 9
    np.testing.assert_array_equal(B.to_numpy(), M)
    assert path.endswith(".rank0.npz")


def test_restore_rejects_incompatible_geometry(tmp_path):
    A = TwoDimBlockCyclic(64, 64, 32, 32).from_numpy(
        np.ones((64, 64), np.float32))
    prefix = str(tmp_path / "ck")
    ckpt.save_collection(A, prefix)
    wrong = TwoDimBlockCyclic(64, 64, 16, 16)
    with pytest.raises(ValueError, match="incompatible"):
        ckpt.restore_collection(wrong, prefix)


def test_restore_rejects_wrong_rank_count_and_grid(tmp_path):
    """A snapshot written on a 4-rank 2x2 grid must fail FAST (clear
    manifest-mismatch error) when restored onto a 2-rank 2x1 grid —
    each shard holds only the tiles its writer owned under ITS
    distribution, so loading the wrong shard set would silently drop
    tiles."""
    nb_ranks, n, nb = 4, 128, 32
    prefix = str(tmp_path / "grid")

    def save_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=2, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        return ckpt.save_collection(d, prefix)

    spmd(nb_ranks, save_rank)

    wrong = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=1, nodes=2, rank=0,
                              dtype=np.float32)
    with pytest.raises(ckpt.CheckpointMismatchError) as ei:
        ckpt.restore_collection(wrong, prefix)
    msg = str(ei.value)
    # names every mismatched field and both grids, so the operator sees
    # WHAT diverged without replaying the save
    assert "nodes" in msg and "Q" in msg
    assert "4 rank(s), grid 2x2" in msg
    assert "2 rank(s), grid 2x1" in msg

    # a single-rank collection can't swallow a 4-rank shard either
    single = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    with pytest.raises(ckpt.CheckpointMismatchError):
        ckpt.restore_collection(single, prefix)


def test_mismatch_error_aggregates_all_keys(tmp_path):
    """One error listing EVERY divergent key (tile size and dtype here)
    beats a fix-one-rerun loop."""
    A = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(
        np.ones((64, 64), np.float32))
    prefix = str(tmp_path / "agg")
    ckpt.save_collection(A, prefix)
    wrong = TwoDimBlockCyclic(64, 64, 16, 16, dtype=np.float64)
    with pytest.raises(ckpt.CheckpointMismatchError) as ei:
        ckpt.restore_collection(wrong, prefix)
    msg = str(ei.value)
    assert "mb" in msg and "dtype" in msg


def test_restore_accepts_pre_ft_manifest(tmp_path):
    """Snapshots written before the manifest carried nodes/rank (the
    pre-ft format) still restore: those keys are only compared when the
    snapshot recorded them."""
    import json

    rng = np.random.RandomState(3)
    M = rng.rand(64, 64).astype(np.float32)
    A = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(M)
    prefix = str(tmp_path / "oldfmt")
    path = ckpt.save_collection(A, prefix)
    # rewrite the manifest without the new keys (the old writer)
    with np.load(path, allow_pickle=False) as z:
        man = json.loads(str(z["__manifest__"]))
        tiles = {k: z[k] for k in z.files if k.startswith("t")}
    for k in ("nodes", "rank"):
        man.pop(k, None)
    np.savez(path, __manifest__=json.dumps(man), **tiles)
    B = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32)
    assert ckpt.restore_collection(B, prefix) == 4
    np.testing.assert_array_equal(B.to_numpy(), M)


def test_checkpoint_resume_mid_computation(ctx, tmp_path):
    """Factor, checkpoint at the quiescent point, clobber, restore, and
    continue with a solve — the resume path a failed run would take."""
    from parsec_tpu.ops import (dpotrf_taskpool, dtrsm_lower_taskpool,
                                dtrsm_lower_trans_taskpool, make_spd)
    n, nb = 96, 32
    M = make_spd(n)
    rng = np.random.RandomState(1)
    Bm = (rng.rand(n, 16) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    ctx.add_taskpool(dpotrf_taskpool(A))
    ctx.wait()
    prefix = str(tmp_path / "factored")
    ckpt.save_collection(A, prefix, context=ctx)

    # "restart": fresh collection restored from the checkpoint
    A2 = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    assert ckpt.restore_collection(A2, prefix) == 9
    B = TwoDimBlockCyclic(n, 16, nb, nb, dtype=np.float32).from_numpy(Bm)
    ctx.add_taskpool(dtrsm_lower_taskpool(A2, B))
    ctx.wait()
    ctx.add_taskpool(dtrsm_lower_trans_taskpool(A2, B))
    ctx.wait()
    ref = np.linalg.solve(M.astype(np.float64), Bm.astype(np.float64))
    np.testing.assert_allclose(B.to_numpy(), ref, atol=5e-3)


def test_spmd_per_rank_shards(tmp_path):
    """Each rank writes only its own tiles; restore on the same grid
    reads them back rank-locally."""
    nb_ranks, n, nb = 4, 128, 32
    rng = np.random.RandomState(2)
    M = rng.rand(n, n).astype(np.float32)
    prefix = str(tmp_path / "shards")

    def save_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=2, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        for (i, j) in d.local_tiles():
            np.copyto(d.tile(i, j),
                      M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
        return ckpt.save_collection(d, prefix)

    paths, _ = spmd(nb_ranks, save_rank)
    assert len(set(paths)) == nb_ranks

    def restore_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=2, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        count = ckpt.restore_collection(d, prefix)
        ok = all(np.array_equal(
            d.tile(i, j), M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
            for (i, j) in d.local_tiles())
        return count, ok

    results, _ = spmd(nb_ranks, restore_rank)
    assert sum(c for c, _ in results) == 16
    assert all(ok for _, ok in results)


def test_loose_array_roundtrip(tmp_path):
    prefix = str(tmp_path / "state")
    ckpt.save_arrays(prefix, step=np.int64(7),
                     w=np.arange(6.0).reshape(2, 3))
    back = ckpt.load_arrays(prefix)
    assert back["step"] == 7
    np.testing.assert_array_equal(back["w"], np.arange(6.0).reshape(2, 3))
