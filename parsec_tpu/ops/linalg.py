"""Tile linear-algebra kernels: the BODY payloads of dense tile algorithms.

The reference delegates tile kernels to BLAS/LAPACK (DPLASMA sits on top of
the runtime; tests use hand-rolled GEMMs, e.g. dtd_test_simple_gemm.c).
Here each kernel is a jax-jit executable — XLA fuses scale/add into the
matmul and keeps the MXU fed; jit caches one executable per (shape, dtype)
so steady-state dispatch is a cache hit.

All kernels are functional (return new arrays) to match the device module's
stage-out convention; bf16 accumulation is avoided by pinning
``preferred_element_type`` to f32. Matmul *input* precision follows jax's
``jax_default_matmul_precision`` (TPU default: bf16-input MXU passes, ~2e-3
relative error on f32 tiles); set it to "highest" for LAPACK-grade f32
accuracy at ~3x the MXU cost.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular as _solve_tri


@jax.jit
def potrf(t: Any) -> Any:
    """Cholesky of one diagonal tile: T = chol_L(T)."""
    return jnp.linalg.cholesky(t)


@jax.jit
def trsm_panel(t: Any, c: Any) -> Any:
    """Right-looking panel solve: C <- C * T^{-T} with T lower triangular
    (L[m,k] = A[m,k] L[k,k]^{-T})."""
    return _solve_tri(t, c.T, lower=True).T


@jax.jit
def syrk_ln(t: Any, a: Any) -> Any:
    """T <- T - A A^T (lower, no-transpose SYRK)."""
    return t - jnp.dot(a, a.T, preferred_element_type=jnp.float32)


@jax.jit
def gemm_nt(c: Any, a: Any, b: Any) -> Any:
    """C <- C - A B^T."""
    return c - jnp.dot(a, b.T, preferred_element_type=jnp.float32)


@jax.jit
def gemm_nn(c: Any, a: Any, b: Any) -> Any:
    """C <- C + A B."""
    return c + jnp.dot(a, b, preferred_element_type=jnp.float32)


@jax.jit
def gemm_nn_sub(c: Any, a: Any, b: Any) -> Any:
    """C <- C - A B (trailing update of LU)."""
    return c - jnp.dot(a, b, preferred_element_type=jnp.float32)


@jax.jit
def gemm(c: Any, a: Any, b: Any, alpha: float = 1.0, beta: float = 1.0) -> Any:
    """C <- beta*C + alpha*A@B (general tile GEMM). alpha/beta are traced
    scalars: one cached executable serves every scaling."""
    return beta * c + alpha * jnp.dot(a, b, preferred_element_type=jnp.float32)


@jax.jit
def geqrt(a: Any) -> Any:
    """QR of one diagonal tile: returns (R, Q) with A = Q R.

    The reference's GEQRT produces Householder vectors V in the lower part
    plus a block-reflector T; on TPU the compact-WY form would serialize
    into nb small reflector applications, so the explicit orthogonal factor
    Q (one extra nb x nb matmul per consumer, MXU-friendly) plays the role
    of (V, T)."""
    q, r = jnp.linalg.qr(a, mode="complete")
    return r, q


@jax.jit
def geqrt_r(a: Any) -> Any:
    """Last-panel geqrt: no Q consumers exist, so skip forming the
    orthogonal factor (mode="r") and return a zero placeholder."""
    return jnp.linalg.qr(a, mode="r"), jnp.zeros_like(a)


@jax.jit
def unmqr(q: Any, c: Any) -> Any:
    """Apply Q^T from geqrt to a tile right of the diagonal: C <- Q^T C."""
    return jnp.dot(q.T, c, preferred_element_type=jnp.float32)


@jax.jit
def tsqrt(r: Any, a: Any) -> Any:
    """Triangle-on-top-of-square QR: factor [R; A] (R upper triangular).

    Returns (R', Z, Q2): the updated nb x nb triangle, the zeroed-out
    square block (tile (m,k) of the final R is zero), and the orthogonal
    factor Q2 of the stacked system for tsmqr consumers."""
    nb = r.shape[0]
    q2, rf = jnp.linalg.qr(jnp.concatenate([r, a], axis=0), mode="complete")
    return rf[:nb, :], jnp.zeros_like(a), q2


@jax.jit
def tsqrt_r(r: Any, a: Any) -> Any:
    """Last-panel tsqrt: R-only factorization of [R; A], zero Q2
    placeholder (no tsmqr consumers on the final panel)."""
    nb = r.shape[0]
    rf = jnp.linalg.qr(jnp.concatenate([r, a], axis=0), mode="r")
    n2 = r.shape[0] + a.shape[0]
    return rf[:nb, :], jnp.zeros_like(a), jnp.zeros((n2, n2), r.dtype)


@jax.jit
def tsmqr(q2: Any, a1: Any, a2: Any) -> Any:
    """Apply Q2^T from tsqrt to a stacked tile pair: [A1; A2] <- Q2^T [A1; A2]."""
    top = a1.shape[0]
    s = jnp.dot(q2.T, jnp.concatenate([a1, a2], axis=0),
                preferred_element_type=jnp.float32)
    return s[:top], s[top:]


@jax.jit
def getrf_nopiv(a: Any) -> Any:
    """LU without pivoting of one square diagonal tile (in-place storage:
    unit-lower L below the diagonal, U on and above).

    Full-shape masked rank-1 updates inside a fori_loop keep shapes static
    for XLA (no dynamic slicing). Each of the n steps does a full m x n
    outer-product update (masked lanes compute zeros), ~3x the flops of a
    true unblocked LU — the price of one cached executable with no
    dynamic shapes."""
    n = min(a.shape)
    rows = jnp.arange(a.shape[0])
    cols = jnp.arange(a.shape[1])

    def step(k, acc):
        col = acc[:, k]
        piv = acc[k, k]
        l = jnp.where(rows > k, col / piv, 0.0)
        row = jnp.where(cols > k, acc[k, :], 0.0)
        acc = acc - jnp.outer(l, row)
        return acc.at[:, k].set(jnp.where(rows > k, l, col))

    return jax.lax.fori_loop(0, n, step, a)


@jax.jit
def trsm_lower_unit(t: Any, c: Any) -> Any:
    """Row-panel update for LU: C <- L^{-1} C, L = unit-lower of T."""
    return _solve_tri(t, c, lower=True, unit_diagonal=True)


@jax.jit
def trsm_lower(t: Any, c: Any) -> Any:
    """C <- L^{-1} C, L = (non-unit) lower of T (forward substitution)."""
    return _solve_tri(t, c, lower=True)


@jax.jit
def trsm_lower_trans(t: Any, c: Any) -> Any:
    """C <- L^{-T} C, L = lower of T (backward substitution)."""
    return _solve_tri(t, c, lower=True, trans="T")


@jax.jit
def gemm_tn_sub(c: Any, a: Any, b: Any) -> Any:
    """C <- C - A^T B (backward-substitution update)."""
    return c - jnp.dot(a.T, b, preferred_element_type=jnp.float32)


@jax.jit
def trsm_upper_right(t: Any, c: Any) -> Any:
    """Column-panel update for LU: C <- C U^{-1}, U = upper of T
    (solved as U^T X^T = C^T)."""
    return _solve_tri(t, c.T, lower=False, trans="T").T


@jax.jit
def axpy(y: Any, x: Any, alpha: float = 1.0) -> Any:
    return y + alpha * x


@jax.jit
def scal(x: Any, alpha: float) -> Any:
    return alpha * x


@jax.jit
def transpose(x: Any) -> Any:
    return x.T
