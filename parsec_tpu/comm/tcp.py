"""TCP transport: the cross-process/cross-host comm engine.

Reference behavior being replaced: the funnelled MPI engine is the only
in-tree transport and carries both the control plane (activations, GET
requests) and the data plane over two-sided MPI
(parsec/parsec_mpi_funnelled.c). Here the same activation/GET/PUT
emulation (inherited from LocalCommEngine) rides framed pickle messages
over TCP sockets — one duplex connection per rank pair, receiver
threads feeding a local inbox, callbacks dispatched from progress() on
the caller's thread (funnelled semantics preserved).

The wire fast path (framing in comm/wire.py):

- each peer has a SEND QUEUE drained by a dedicated writer thread;
  ``send_am`` serializes on the caller's thread (copy-at-enqueue for
  everything below the chunk threshold — the historical snapshot
  semantics) and returns as soon as the message fits the bounded
  per-peer send buffer (``comm_send_buffer_bytes`` — backpressure
  toward a slow link, so producers stall instead of queueing an
  epoch's traffic in RAM);
- queued small messages COALESCE into one multi-message frame per
  syscall (``comm_coalesce_max_bytes``), so on a slow DCN the control
  plane pays one syscall + one wakeup for a burst of activations;
- buffers >= ``comm_chunk_bytes`` stream as bounded CHUNK frames with
  pickle-5 zero-copy views; control messages interleave between chunks
  instead of head-of-line blocking behind a multi-MB tile (callers on
  the bulk path — GET rendezvous, wave tiles — snapshot their payloads
  already, so zero-copy is safe there);
- per-link COMPRESSION (zlib, lz4 when installed) is negotiated at the
  connection handshake and engages only when the measured link
  bandwidth EWMA drops below ``comm_compress_threshold_mbps`` (default
  0 = never) AND a sample probe shows the traffic compresses; a peer
  that never advertises codecs (HELLO missing or no common codec)
  stays uncompressed. The v2 framing itself is a breaking wire change:
  every rank of a job must run the same framing version.

This is the DCN control-plane story of SURVEY.md §5.8 made concrete: on
a multi-host TPU deployment the small latency-bound messages travel this
engine while bulk tile payloads ride the ICI data plane (comm/mesh.py);
single-host multi-process runs (the tests) carry both over TCP.

Connection setup: rank r listens on ``endpoints[r]``; r dials every rank
s < r and accepts from every s > r (one connection per unordered pair),
with a rank-identifying handshake byte frame followed by a HELLO
capability frame.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core.lists import Fifo
from .engine import RankFailedError, TAG_USER_BASE
from ..utils import logging as plog
from .local import LocalCommEngine, _wire_copy
from . import wire
from .wire import GOODBYE

TAG_BARRIER = TAG_USER_BASE - 1  # reserved by the transport for sync()

#: bandwidth EWMA smoothing and the minimum send size that counts as a
#: bandwidth sample (smaller sends measure syscall latency, not the link)
_BW_ALPHA = 0.2
_BW_SAMPLE_MIN = 1 << 15
#: compression: re-probe cadence (frames) and the engage ratio
_PROBE_EVERY = 256
_PROBE_RATIO = 0.9
#: smallest body worth compressing
_COMP_MIN_BYTES = 512
#: iovec safety cap for one sendmsg (IOV_MAX is 1024 on linux)
_MAX_BATCH_MSGS = 256
#: anti-starvation: after this many consecutive ctrl frames with bulk
#: chunks waiting, one chunk is interleaved regardless — a sustained
#: control stream must not stall an in-flight bulk transfer forever
_CTRL_STREAK_MAX = 8

#: declared lock discipline, enforced by the concurrency lint
#: (parsec_tpu/analysis/lock_check.py): per-peer send queues belong to
#: the peer's condition (writer thread vs. every sender), the peer map
#: to the connection condition (accept thread vs. everyone), wire
#: counters and barrier state to their dedicated locks.  The same lint
#: verifies no socket send/recv or sleep ever runs while one of these
#: is held — the writer drains OUTSIDE peer.cond by construction.
_GUARDED_BY = {
    "_Peer.ctrl": "cond",
    "_Peer.bulk": "cond",
    "_Peer.queued_bytes": "cond",
    "TCPCommEngine._peers": "_conn_cond",
    "TCPCommEngine.wire_stats": "_stat_lock",
    "TCPCommEngine._rx_pending": "_stat_lock",
    "TCPCommEngine._xfer_iter": "_stat_lock",
    "TCPCommEngine._barrier_arrived": "_barrier_lock",
    "TCPCommEngine._barrier_release": "_barrier_lock",
}


# RankFailedError moved to comm/engine.py (every transport raises it
# now, not just this one); re-exported here for back-compat importers.


def free_ports(n: int) -> List[int]:
    """Reserve n distinct free localhost ports (test/launcher helper)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _sendall_vec(sock: socket.socket, pieces: List[Any]) -> None:
    """Scatter-gather sendall: one syscall per iteration over the whole
    piece list (the coalescing win — a batch of frames leaves in ONE
    sendmsg instead of one syscall per message)."""
    views = [memoryview(p) for p in pieces]
    while views:
        sent = sock.sendmsg(views)
        while sent:
            if len(views[0]) <= sent:
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


class _FabricShim:
    """Satisfies the tiny surface LocalCommEngine expects of a fabric."""

    def __init__(self, nb_ranks: int) -> None:
        self.nb_ranks = nb_ranks
        self.msg_count = 0
        self.bytes_count = 0


class _Peer:
    """Per-peer send state: the queues the writer thread drains.

    ``ctrl`` holds coalescible message segments and standalone frames
    (chunked-transfer headers, hello); ``bulk`` holds chunk items. The
    writer always prefers ctrl, so control traffic interleaves between
    the bounded chunks of an in-flight bulk payload."""

    __slots__ = ("rank", "sock", "ctrl", "bulk", "cond", "writer",
                 "goodbye", "bw_mbps", "codec", "engaged", "frames",
                 "probe_ratio", "done", "queued_bytes", "hb_ok", "el_ok")

    def __init__(self, rank: int, sock: socket.socket) -> None:
        self.rank = rank
        self.sock = sock
        self.ctrl: deque = deque()
        self.bulk: deque = deque()
        self.queued_bytes = 0      # backpressure accounting
        self.cond = threading.Condition()
        self.writer: Optional[threading.Thread] = None
        self.goodbye = False       # enqueue-side: shutdown requested
        self.done = False          # writer exited
        self.bw_mbps: Optional[float] = None   # send-side link EWMA
        self.codec: Optional[str] = None       # negotiated at HELLO
        self.engaged = False                   # compression live now
        self.frames = 0                        # frames sent (probe clock)
        self.probe_ratio: Optional[float] = None
        self.hb_ok = False         # HELLO advertised heartbeat support
        self.el_ok = False         # HELLO advertised elastic membership


class TCPCommEngine(LocalCommEngine):
    #: a TCP probe only leaves when the peer's HELLO was processed
    #: (hb_ok) — its receiver thread was alive then and answers pings
    #: with no progress pumping, so probed-but-silent = genuinely dead
    ft_probe_baseline = True

    def __init__(self, rank: int, endpoints: List[Tuple[str, int]],
                 connect_timeout: float = 30.0,
                 coalesce_max_bytes: Optional[int] = None,
                 chunk_bytes: Optional[int] = None,
                 compress_threshold_mbps: Optional[float] = None) -> None:
        from ..utils.params import params
        self._inbox: Fifo = Fifo()
        self._peers: Dict[int, _Peer] = {}
        self._recv_threads: List[threading.Thread] = []
        self._closing = False
        # dead_peers / on_peer_failure live on the CommEngine base now
        # (uniform across transports); finished_peers is TCP's record of
        # clean GOODBYEs received
        self.finished_peers: set = set()
        self._barrier_arrived: set = set()
        self._barrier_release = 0
        self._barrier_lock = threading.Lock()
        self._stat_lock = threading.Lock()
        self._conn_cond = threading.Condition()
        self._xfer_iter = 0
        self._rx_pending: Dict[int, int] = {}  # peer -> incomplete rx xfers
        # wire knobs (constructor overrides beat the MCA layer — bench
        # and tests compare configurations inside one process)
        self.coalesce_max_bytes = (
            coalesce_max_bytes if coalesce_max_bytes is not None
            else params.get_or("comm_coalesce_max_bytes", "sizet", 1 << 16))
        self.chunk_bytes = max(
            1, chunk_bytes if chunk_bytes is not None
            else params.get_or("comm_chunk_bytes", "sizet", 1 << 17))
        self.compress_threshold_mbps = (
            compress_threshold_mbps if compress_threshold_mbps is not None
            else params.get_or("comm_compress_threshold_mbps", "int", 0))
        self.send_buffer_bytes = max(
            1, params.get_or("comm_send_buffer_bytes", "sizet", 1 << 26))
        self._codecs = wire.available_codecs()
        #: wire fast-path counters (plain dict: obs polls it when
        #: telemetry is on, nothing on the hot path otherwise)
        self.wire_stats = {
            "frames_sent": 0, "msgs_sent": 0, "coalesced_msgs": 0,
            "batches": 0, "chunks_sent": 0, "chunk_bytes_sent": 0,
            "frames_compressed": 0, "bytes_precompress": 0,
            "bytes_postcompress": 0, "msgs_chunked": 0,
        }
        super().__init__(_FabricShim(len(endpoints)), rank)
        self.endpoints = endpoints
        self.connect_timeout = connect_timeout
        self.tag_register(TAG_BARRIER, self._on_barrier)

        host, port = endpoints[rank]
        self._listener = socket.create_server((host, port), backlog=len(endpoints))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tcp-accept-r{rank}")
        self._accept_thread.start()
        # dial lower ranks (they accept); retry while peers boot
        deadline = time.time() + connect_timeout
        for peer in range(rank):
            self._dial(peer, deadline)

    # -- connection management ------------------------------------------
    def _dial(self, peer: int, deadline: float) -> None:
        host, port = self.endpoints[peer]
        while True:
            try:
                sock = socket.create_connection((host, port), timeout=2.0)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rank {self.rank}: cannot reach rank {peer} at "
                        f"{host}:{port}")
                time.sleep(0.05)
        sock.settimeout(None)  # create_connection left timeout mode on
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall(struct.pack("<I", self.rank))
        self._register_conn(peer, sock)

    def _accept_loop(self) -> None:
        try:
            while not self._closing:
                sock, _addr = self._listener.accept()
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # bounded handshake: a stray connection that never sends
                # its rank must not starve accepts from real peers
                sock.settimeout(5.0)
                try:
                    hdr = self._recv_exact(sock, 4)
                except OSError:
                    hdr = None
                if hdr is None:
                    sock.close()
                    continue
                sock.settimeout(None)
                (peer,) = struct.unpack("<I", hdr)
                with self._conn_cond:
                    known = peer in self._peers
                if peer >= self.nb_ranks or peer == self.rank or known:
                    # stray/duplicate connection: never displace a real
                    # peer's socket
                    sock.close()
                    continue
                self._register_conn(peer, sock)
        except OSError:
            return  # listener closed during fini

    def _register_conn(self, peer: int, sock: socket.socket) -> None:
        p = _Peer(peer, sock)
        with self._conn_cond:
            self._peers[peer] = p
            self._conn_cond.notify_all()
        p.writer = threading.Thread(
            target=self._writer_loop, args=(p,), daemon=True,
            name=f"tcp-send-r{self.rank}p{peer}")
        p.writer.start()
        t = threading.Thread(target=self._recv_loop, args=(peer, sock),
                             daemon=True, name=f"tcp-recv-r{self.rank}p{peer}")
        t.start()
        self._recv_threads.append(t)
        # capability advertisement: the receiving end only ever
        # compresses toward us after seeing this (mixed-version peers
        # never send one and stay on the uncompressed path)
        hello = wire.pack_hello({"ver": wire.WIRE_VERSION,
                                 "rank": self.rank,
                                 "codecs": self._codecs,
                                 "hb": True,
                                 "el": True})
        with p.cond:
            p.ctrl.append(("frame", hello))
            p.queued_bytes += len(hello)
            p.cond.notify()

    def _peer_to(self, peer: int) -> _Peer:
        with self._conn_cond:
            ok = self._conn_cond.wait_for(lambda: peer in self._peers,
                                          timeout=self.connect_timeout)
            if not ok:
                raise TimeoutError(
                    f"rank {self.rank}: no connection from rank {peer}")
            return self._peers[peer]

    # kept for tests/back-compat: peer -> socket view
    @property
    def _conns(self) -> Dict[int, socket.socket]:
        with self._conn_cond:
            return {r: p.sock for r, p in self._peers.items()}

    def link_bw_mbps(self, peer: int) -> Optional[float]:
        """Send-side bandwidth EWMA toward ``peer`` in MB/s (None until
        a large-enough send has been measured). Feeds the adaptive
        eager/rendezvous cutoff (remote_dep) and the LINK_BW gauges."""
        with self._conn_cond:
            p = self._peers.get(peer)
        return p.bw_mbps if p is not None else None

    def chunks_inflight(self) -> int:
        """Queued-but-unsent chunk SEGMENTS plus receive-side
        incomplete TRANSFERS (the CHUNKS_INFLIGHT gauge; transfer
        headers riding the bulk lane are not counted)."""
        n = 0
        with self._conn_cond:
            peers = list(self._peers.values())
        for p in peers:
            # under p.cond: the writer mutates the deque concurrently,
            # and iterating a mutating deque raises RuntimeError
            with p.cond:
                n += sum(1 for it in p.bulk if it[0] == "chunk")
        with self._stat_lock:
            n += sum(self._rx_pending.values())
        return n

    def compress_ratio(self) -> Optional[float]:
        """Cumulative post/pre compression byte ratio (None: nothing
        was ever compressed)."""
        with self._stat_lock:
            pre = self.wire_stats["bytes_precompress"]
            post = self.wire_stats["bytes_postcompress"]
        return (post / pre) if pre else None

    # -- fault tolerance ------------------------------------------------
    def ft_ping(self, peer: int, seq: int, t_ns: int) -> bool:
        """Wire-level heartbeat probe (K_PING): enqueued straight onto
        the peer's ctrl lane and answered by the peer's receiver
        thread. Never sent toward a peer whose HELLO did not advertise
        heartbeat support — a mixed-version peer is never probed, so
        the detector can never (wrongly) declare it dead."""
        if self._ft_silenced or peer in self.dead_peers \
                or peer in self.finished_peers:
            return False
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None or not p.hb_ok or p.done:
            return False
        # probe frames bypass _transport_post, so consult the chaos
        # layer here too — ft_inject directives with hb=1 must be able
        # to drop/duplicate heartbeats on this transport as well
        from .engine import TAG_HEARTBEAT
        copies = self.ft_outbound(peer, TAG_HEARTBEAT)
        if copies == 0:
            return False
        frame = wire.pack_ping(seq, t_ns)
        with p.cond:
            for _ in range(copies):
                p.ctrl.append(("frame", frame))
                p.queued_bytes += len(frame)
            p.cond.notify()
        return True

    def ft_elastic_send(self, peer: int, payload) -> bool:
        """Wire-level elastic membership frame (K_ELASTIC): like
        ``ft_ping``, enqueued on the ctrl lane and delivered by the
        peer's receiver thread — a resize proposal or join
        announcement lands even while every worker is wedged in a long
        kernel. Gated on the HELLO ``el`` capability: a pre-elastic
        peer is never drawn into an agreement it cannot answer.
        Exempt from the chaos layer (control plane, like heartbeats
        without ``hb=1``); the coordinator's resend tick covers real
        frame loss."""
        if self._ft_silenced or peer in self.dead_peers \
                or peer in self.finished_peers:
            return False
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None or not p.el_ok or p.done:
            return False
        frame = wire.pack_elastic(dict(payload))
        with p.cond:
            p.ctrl.append(("frame", frame))
            p.queued_bytes += len(frame)
            p.cond.notify()
        return True

    def report_peer_failure(self, peer: int, reason: str) -> None:
        """Uniform failure funnel (base-class API): a proactive
        (heartbeat) eviction is unconditional — the peer is SILENT, so
        unlike a torn connection there is no may-have-finished
        ambiguity for the reporting policy to weigh."""
        self._peer_died(peer, reason, lost_sends=True)

    def ft_silence(self) -> None:
        """Injected kill: beyond the base flag, wake every writer so it
        exits WITHOUT flushing its queue — a real SIGKILL drops queued
        frames, and survivors must not observe a message sequence that
        is impossible under a real crash."""
        super().ft_silence()
        with self._conn_cond:
            peers = list(self._peers.values())
        for p in peers:
            with p.cond:
                p.cond.notify_all()

    def peer_finished(self, peer: int) -> bool:
        return peer in self.finished_peers

    # -- send path ------------------------------------------------------
    def send_am(self, dst: int, tag: int, payload: Any) -> None:
        # remote sends serialize via pickle (its own copy); only loopback
        # needs the anti-aliasing wire copy the local fabric applies
        if dst == self.rank:
            payload = _wire_copy(payload)
        obs = self._obs
        if obs is None:
            self._transport_post(dst, self.rank, tag, payload)
            return
        t0 = time.monotonic_ns()
        self._transport_post(dst, self.rank, tag, payload)
        obs.am_sent(self.rank, dst, tag, payload, t0)

    def _transport_post(self, dst: int, src: int, tag: int, payload: Any) -> None:
        for _ in range(self.ft_outbound(dst, tag)):
            self._transport_post_live(dst, src, tag, payload)

    def _transport_post_live(self, dst: int, src: int, tag: int,
                             payload: Any) -> None:
        self._check_live(dst)
        if dst == self.rank:
            with self._stat_lock:
                self.fabric.msg_count += 1
            self._inbox.push((src, tag, payload))
            self._notify_arrival()
            return
        # protocol-5 out-of-band pickling: ndarray payloads are NOT
        # serialized into the frame — their buffers are collected as
        # views. Buffers below the chunk threshold are COPIED into the
        # queued segment here, on the caller's thread (the historical
        # copy-at-send snapshot semantics: inline activation payloads
        # may be mutated by a local successor right after this call
        # returns). Buffers >= the threshold stream as chunks; they
        # stay zero-copy ONLY when provably immutable (a read-only
        # buffer export — the rendezvous/wave producers mark their
        # snapshots so), else they too are copied at enqueue: the
        # writer drains asynchronously, and a live host tile mutated
        # after send_am returns must not tear on the wire.
        raw_bufs: list = []
        frame = pickle.dumps((src, tag, payload), protocol=5,
                             buffer_callback=raw_bufs.append)
        try:
            views = [b.raw() for b in raw_bufs]
        except BufferError:
            # a custom buffer-exporting type emitted a discontiguous
            # PickleBuffer (numpy in-bands those itself): fall back to
            # fully in-band pickling for this message
            frame = pickle.dumps((src, tag, payload), protocol=4)
            views = []
        nbytes = len(frame) + sum(v.nbytes for v in views)
        with self._stat_lock:
            self.fabric.msg_count += 1
            self.fabric.bytes_count += nbytes
        peer = self._peer_to(dst)
        chunk = self.chunk_bytes
        if all(v.nbytes < chunk for v in views):
            seg = wire.pack_segment(frame, views)  # copies the views
            with peer.cond:
                self._backpressure_wait(peer, dst, len(seg))
                peer.ctrl.append(("msg", seg))
                peer.queued_bytes += len(seg)
                peer.cond.notify()
            return
        # chunked path: the header (pickle + small buffers) leads the
        # BULK lane, followed by each large buffer as bounded chunk
        # frames — the hdr-before-first-chunk invariant is structural
        # (bulk is FIFO), never a property of lane priorities.
        with self._stat_lock:
            self._xfer_iter += 1
            xid = (self.rank << 40) | self._xfer_iter
            self.wire_stats["msgs_chunked"] += 1
        views = [v if v.nbytes < chunk or v.readonly
                 else memoryview(bytes(v))  # snapshot mutable bulk now
                 for v in views]
        specs = [(v.nbytes >= chunk, v.nbytes,
                  None if v.nbytes >= chunk else v) for v in views]
        hdr = wire.pack_xfer_hdr(xid, frame, specs)
        items = [("frame", hdr)]
        qbytes = len(hdr)
        for bidx, v in enumerate(views):
            if v.nbytes < chunk:
                continue
            for off in range(0, v.nbytes, chunk):
                items.append(("chunk", xid, bidx, off,
                              v[off:off + chunk]))
                qbytes += min(chunk, v.nbytes - off)
        with peer.cond:
            self._backpressure_wait(peer, dst, qbytes)
            peer.bulk.extend(items)
            peer.queued_bytes += qbytes
            peer.cond.notify()

    def _check_live(self, dst: int) -> None:
        if dst in self.dead_peers:
            raise RankFailedError(dst, "send to failed rank")
        if dst in self.finished_peers:
            raise RankFailedError(dst, "send to peer after its clean shutdown")

    def _backpressure_wait(self, peer: _Peer, dst: int,
                           nbytes: int) -> None:  # holds: peer.cond
        """Bounded send buffer (call with ``peer.cond`` held): block
        while the peer's queued bytes would exceed
        ``comm_send_buffer_bytes`` — the v1 synchronous-sendall
        backpressure with a buffer instead of O(one message), so a
        producer outpacing a slow link stalls instead of queueing an
        epoch's traffic in RAM. A message larger than the whole buffer
        is admitted alone into an empty queue. Aborts with
        RankFailedError when the peer dies while we wait."""
        limit = self.send_buffer_bytes
        while peer.queued_bytes > 0 \
                and peer.queued_bytes + nbytes > limit:
            self._check_live(dst)
            if peer.done:
                raise RankFailedError(dst, "send to failed rank")
            peer.cond.wait(0.1)
        self._check_live(dst)

    # -- writer thread --------------------------------------------------
    def _writer_loop(self, peer: _Peer) -> None:
        """Drain one peer's queues: coalesce ctrl messages into batch
        frames (one syscall each), interleave one bulk chunk whenever
        the ctrl lane is idle, send the GOODBYE sentinel last."""
        coalesce = self.coalesce_max_bytes
        ctrl_streak = 0
        try:
            while True:
                pieces: Optional[List[Any]] = None
                nmsgs = 0
                deq_bytes = 0
                is_goodbye = False
                with peer.cond:
                    while not peer.ctrl and not peer.bulk \
                            and not peer.goodbye \
                            and not self._ft_silenced \
                            and peer.rank not in self.dead_peers:
                        peer.cond.wait()
                    if peer.rank in self.dead_peers or self._ft_silenced:
                        return   # _peer_died/ft_silence notified us:
                        #          stop (finally drops whatever is
                        #          still queued — a crash sends nothing)
                    take_ctrl = bool(peer.ctrl) and (
                        not peer.bulk or ctrl_streak < _CTRL_STREAK_MAX)
                    if take_ctrl:
                        kind = peer.ctrl[0][0]
                        if kind == "msg":
                            segs = [peer.ctrl.popleft()[1]]
                            total = len(segs[0])
                            while (peer.ctrl
                                   and peer.ctrl[0][0] == "msg"
                                   and len(segs) < _MAX_BATCH_MSGS
                                   and total + len(peer.ctrl[0][1])
                                   <= coalesce):
                                seg = peer.ctrl.popleft()[1]
                                segs.append(seg)
                                total += len(seg)
                            pieces = wire.pack_batch(segs)
                            nmsgs = len(segs)
                            deq_bytes = total
                        else:  # standalone frame (hello)
                            body = peer.ctrl.popleft()[1]
                            pieces = [body]
                            deq_bytes = len(body)
                        # the streak only counts ctrl frames sent WHILE
                        # bulk was waiting (the starvation being bounded)
                        ctrl_streak = ctrl_streak + 1 if peer.bulk else 0
                    elif peer.bulk:
                        item = peer.bulk.popleft()
                        ctrl_streak = 0
                        if item[0] == "frame":  # chunked-transfer header
                            pieces = [item[1]]
                            deq_bytes = len(item[1])
                        else:
                            _k, xid, bidx, off, view = item
                            pieces = [wire.pack_chunk_hdr(xid, bidx, off),
                                      view]
                            deq_bytes = view.nbytes
                            with self._stat_lock:
                                self.wire_stats["chunks_sent"] += 1
                                self.wire_stats["chunk_bytes_sent"] += \
                                    view.nbytes
                    else:  # goodbye, and both queues drained
                        is_goodbye = True
                if is_goodbye:
                    try:
                        peer.sock.sendall(struct.pack("<Q", GOODBYE))
                    except OSError:
                        pass
                    return
                pieces = self._maybe_compress(peer, pieces)
                body_len = sum(len(p) if isinstance(p, (bytes, bytearray))
                               else p.nbytes for p in pieces)
                t0 = time.monotonic()
                _sendall_vec(peer.sock,
                             [struct.pack("<Q", body_len)] + pieces)
                dt = time.monotonic() - t0
                with peer.cond:  # release the backpressure budget
                    peer.queued_bytes -= deq_bytes
                    peer.cond.notify_all()
                if body_len >= _BW_SAMPLE_MIN and dt > 0:
                    inst = body_len / dt / 1e6
                    peer.bw_mbps = (inst if peer.bw_mbps is None else
                                    (1 - _BW_ALPHA) * peer.bw_mbps
                                    + _BW_ALPHA * inst)
                with self._stat_lock:
                    peer.frames += 1
                    self.wire_stats["frames_sent"] += 1
                    if nmsgs:
                        self.wire_stats["msgs_sent"] += nmsgs
                        self.wire_stats["batches"] += 1
                        if nmsgs > 1:
                            self.wire_stats["coalesced_msgs"] += nmsgs
        except OSError as exc:
            # the send side can see the crash before the receiver thread
            # does — later sends raise RankFailedError via dead_peers.
            # send_am already returned for the frame that just failed
            # (and anything still queued): an ACCEPTED send was LOST, so
            # the death is reported to the runtime unconditionally
            # (lost_sends) — the v1 path raised RankFailedError to the
            # caller here, and a silent drop would trade that loud abort
            # for a termdet hang.
            self._peer_died(peer.rank, f"send failed: {exc}",
                            lost_sends=True)
        finally:
            peer.done = True
            with peer.cond:
                dropped = len(peer.ctrl) + len(peer.bulk)
                peer.ctrl.clear()
                peer.bulk.clear()
                peer.queued_bytes = 0
                peer.cond.notify_all()
            if dropped and not self._closing and not self._ft_silenced:
                plog.warning(
                    "tcp rank %d: dropped %d queued frame(s)/chunk(s) "
                    "to dead peer %d", self.rank, dropped, peer.rank)

    def _maybe_compress(self, peer: _Peer, pieces: List[Any]) -> List[Any]:
        """Engage per-link compression when (a) the peer advertised a
        common codec, (b) the measured bandwidth EWMA sits below the
        MCA threshold (default 0 = never), and (c) a sample probe shows
        the traffic actually compresses. Re-probes periodically so a
        shift to incompressible payloads backs off."""
        threshold = self.compress_threshold_mbps
        codec = peer.codec
        if not threshold or codec is None:
            return pieces
        bw = peer.bw_mbps
        if bw is None or bw >= threshold:
            return pieces
        body_len = sum(len(p) if isinstance(p, (bytes, bytearray))
                       else p.nbytes for p in pieces)
        if body_len < _COMP_MIN_BYTES:
            return pieces
        probing = (peer.probe_ratio is None
                   or peer.frames % _PROBE_EVERY == 0)
        if not probing and not peer.engaged:
            return pieces   # before the join: no copy between probes
        body = b"".join(bytes(p) for p in pieces)
        out = wire.compress_body(body, codec)
        if probing:
            # the probe IS this frame's compression — measured once,
            # reused as the payload when it engages
            peer.probe_ratio = (sum(len(p) for p in out) / len(body)
                                if out is not None else 1.0)
            peer.engaged = peer.probe_ratio <= _PROBE_RATIO
            if not peer.engaged:
                return pieces
        if out is None:
            return pieces
        with self._stat_lock:
            self.wire_stats["frames_compressed"] += 1
            self.wire_stats["bytes_precompress"] += len(body)
            self.wire_stats["bytes_postcompress"] += \
                sum(len(p) for p in out)
        return out

    # -- receive path ---------------------------------------------------
    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _recv_loop(self, peer: int, sock: socket.socket) -> None:
        xfers: Dict[int, wire.RxXfer] = {}  # this connection's partials
        try:
            while True:
                hdr = self._recv_exact(sock, 8)
                if hdr is None:
                    self._peer_died(peer, "peer closed the connection")
                    return
                (size,) = struct.unpack("<Q", hdr)
                if size == GOODBYE:
                    with self._lock:
                        owes_us = peer in self._get_srcs.values()
                    if owes_us or xfers:
                        # "clean" exit while owing rendezvous data or
                        # mid-chunked-transfer is a protocol violation —
                        # treat as a failure
                        self._peer_died(
                            peer, "shut down owing rendezvous data")
                        return
                    # orderly shutdown: the peer fini'd after completing
                    # its work — not a failure, no scary warnings
                    self.finished_peers.add(peer)
                    return
                body = self._recv_exact(sock, size)
                if body is None:
                    self._peer_died(peer, "connection truncated mid-frame")
                    return
                self._dispatch_body(peer, memoryview(body), xfers)
        except OSError as exc:
            self._peer_died(peer, f"socket error: {exc}")
            return
        except Exception as exc:  # frame desync / unpickle failure: a
            # silent receiver death would hang both ranks — make it loud
            self._peer_died(peer, f"receiver died: {exc!r}")
            return
        finally:
            if xfers:
                with self._stat_lock:
                    self._rx_pending.pop(peer, None)

    def _dispatch_body(self, peer: int, body: memoryview,
                       xfers: Dict[int, wire.RxXfer]) -> None:
        if self._ft_silenced:
            return   # injected kill: inbound traffic is never delivered
        kind = body[0]
        if kind == wire.K_BATCH:
            for frame, bufs in wire.parse_batch(body):
                # out-of-band buffers alias the received body (zero
                # extra copy); arrays reconstructed over them are
                # read-only — host mutators copy-on-write via
                # Data.materialize_host
                src, tag, payload = wire.load_message(frame, bufs)
                self._inbox.push((src, tag, payload))
                self._notify_arrival()  # wake a parked worker now
        elif kind == wire.K_XFER_HDR:
            xid, frame, specs = wire.parse_xfer_hdr(body)
            rx = wire.RxXfer(frame, specs)
            if rx.remaining <= 0:
                src, tag, payload = rx.message()
                self._inbox.push((src, tag, payload))
                self._notify_arrival()
                return
            xfers[xid] = rx
            with self._stat_lock:
                self._rx_pending[peer] = len(xfers)
        elif kind == wire.K_CHUNK:
            xid, bidx, off, data = wire.parse_chunk(body)
            rx = xfers.get(xid)
            if rx is None:
                raise ValueError(f"chunk for unknown transfer {xid}")
            if rx.feed(bidx, off, data):
                del xfers[xid]
                with self._stat_lock:
                    self._rx_pending[peer] = len(xfers)
                src, tag, payload = rx.message()
                self._inbox.push((src, tag, payload))
                self._notify_arrival()
        elif kind == wire.K_HELLO:
            info = wire.parse_hello(body)
            with self._conn_cond:
                p = self._peers.get(peer)
            if p is not None:
                p.codec = wire.negotiate_codec(
                    self._codecs, info.get("codecs", ()))
                p.hb_ok = bool(info.get("hb"))
                p.el_ok = bool(info.get("el"))
        elif kind == wire.K_PING:
            # answered HERE, on the receiver thread (like K_HELLO): a
            # rank whose workers are all stuck in a long kernel still
            # proves liveness — the detector judges the TRANSPORT, not
            # the progress cadence
            seq, t_ns = wire.parse_ping(body)
            det = self.ft_detector
            if det is not None:
                det.note_alive(peer)
            with self._conn_cond:
                p = self._peers.get(peer)
            if p is not None and not p.done:
                pong = wire.pack_ping(seq, t_ns, pong=True)
                with p.cond:
                    p.ctrl.append(("frame", pong))
                    p.queued_bytes += len(pong)
                    p.cond.notify()
        elif kind == wire.K_PONG:
            seq, t_ns = wire.parse_ping(body)
            det = self.ft_detector
            if det is not None:
                det.note_alive(peer,
                               rtt=(time.monotonic_ns() - t_ns) / 1e9)
        elif kind == wire.K_ELASTIC:
            # delivered HERE, on the receiver thread (like K_PING): a
            # resize proposal or join announcement must reach the
            # coordinator even while every worker is wedged in a long
            # kernel — elastic agreement is progress-cadence-free on TCP
            self._on_elastic(peer, wire.parse_elastic(body))
        elif kind == wire.K_COMP:
            self._dispatch_body(peer, memoryview(
                wire.decompress_body(body)), xfers)
        else:
            raise ValueError(f"unknown frame kind {kind}")

    def _peer_died(self, peer: int, reason: str,
                   lost_sends: bool = False) -> None:
        """Failure detector: a torn connection while we're live marks the
        peer dead (SURVEY.md §5.3 — the reference has nothing; a dead MPI
        rank hangs the job). Reporting policy:

        - any later SEND to the peer raises RankFailedError (always);
        - the death is reported to the runtime immediately when the peer
          provably owes us data (a pending rendezvous GET), when
          accepted-but-unsent frames were LOST with it (``lost_sends``
          — the writer path; the caller already returned believing the
          send succeeded), or always under ``comm_failure_strict`` —
          strict is off by default because with local termination
          detection a peer may legitimately fini before our local tail
          work finishes."""
        if self._closing or peer in self.dead_peers \
                or peer in self.finished_peers:
            return  # clean teardown (ours or theirs), or already reported
        self.dead_peers.add(peer)
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is not None:
            with p.cond:  # unblock anything parked on the writer
                p.cond.notify_all()
        plog.warning("tcp rank %d: peer %d presumed FAILED (%s)",
                     self.rank, peer, reason)
        cb = self.on_peer_failure
        if cb is None:
            return
        from ..utils.params import params
        with self._lock:
            owes_us = peer in self._get_srcs.values()
        if owes_us or lost_sends or params.get("comm_failure_strict"):
            cb(peer, reason)

    def _transport_drain(self):
        while True:
            item = self._inbox.pop()
            if item is None:
                return
            yield item

    # -- barrier over AMs (ref: ce.sync) --------------------------------
    def _on_barrier(self, src: int, payload: Any) -> None:
        # progress() runs on every scheduler thread: updates must be
        # atomic or arrivals are lost and sync() deadlocks
        with self._barrier_lock:
            if payload == "arrive":
                self._barrier_arrived.add(src)
            else:
                self._barrier_release += 1

    def _barrier_wait(self, check_and_consume, required_fn) -> None:
        """Spin on progress() until ``check_and_consume`` succeeds; raise
        RankFailedError when a still-required participant is gone
        (crashed OR cleanly fini'd without arriving) — a barrier can
        never complete then, and spinning until an external timeout is
        the hang this detector exists to eliminate. A peer that already
        arrived may fini freely; its flag is set by the recv thread only
        AFTER every preceding frame was queued, so one extra drain before
        raising rules out a queued-but-unprocessed barrier message."""
        while True:
            if check_and_consume():
                return
            if self.progress():
                continue
            gone = [p for p in required_fn()
                    if p in self.dead_peers or p in self.finished_peers]
            if gone:
                self.progress()  # final drain (see docstring)
                if check_and_consume():
                    return
                peer = gone[0]
                reason = ("rank failed during barrier"
                          if peer in self.dead_peers else
                          "rank shut down without joining the barrier")
                raise RankFailedError(peer, reason)
            time.sleep(0.001)

    def sync(self) -> None:
        if self.nb_ranks == 1:
            return
        if self.rank == 0:
            everyone = set(range(1, self.nb_ranks))

            def got_all_arrivals() -> bool:
                with self._barrier_lock:
                    if self._barrier_arrived >= everyone:
                        self._barrier_arrived -= everyone
                        return True
                    return False

            def still_missing():
                with self._barrier_lock:
                    return everyone - self._barrier_arrived

            self._barrier_wait(got_all_arrivals, still_missing)
            for peer in range(1, self.nb_ranks):
                self.send_am(peer, TAG_BARRIER, "release")
        else:
            self.send_am(0, TAG_BARRIER, "arrive")

            def got_release() -> bool:
                with self._barrier_lock:
                    if self._barrier_release >= 1:
                        self._barrier_release -= 1
                        return True
                    return False

            self._barrier_wait(got_release, lambda: (0,))

    def fini(self) -> None:
        self._closing = True
        if self._ft_silenced:
            # injected kill: die WITHOUT a goodbye and WITHOUT flushing
            # — peers must learn of the death proactively (heartbeat) or
            # reactively (torn socket), exactly like a real crash
            try:
                self._listener.close()
            except OSError:
                pass
            with self._conn_cond:
                peers = dict(self._peers)
            for p in peers.values():
                try:
                    p.sock.close()
                except OSError:
                    pass
            return
        # clean goodbye so live peers see an orderly shutdown, not a
        # crash. The writer sends it only after BOTH queues drain (the
        # final results / termdet messages must precede it), so fini
        # waits for the writers to flush before tearing sockets down.
        with self._conn_cond:
            peers = dict(self._peers)
        for rank_, p in peers.items():
            if rank_ in self.dead_peers or rank_ in self.finished_peers:
                continue
            with p.cond:
                p.goodbye = True
                p.cond.notify()
        # progress-aware flush: a slow link draining a large bulk
        # backlog gets as long as it keeps moving bytes (the links this
        # wire targets run at single-digit MB/s); only a STALLED writer
        # (15 s with zero queue progress) is abandoned
        live = [p for r, p in peers.items()
                if r not in self.dead_peers
                and r not in self.finished_peers and p.writer is not None]
        prev = None
        stall = time.time() + 15.0
        while True:
            live = [p for p in live if p.writer.is_alive()]
            if not live:
                break
            cur = 0
            for p in live:
                with p.cond:
                    cur += len(p.ctrl) + len(p.bulk)
            if prev is None or cur < prev:
                prev = cur
                stall = time.time() + 15.0
            if time.time() > stall:
                plog.warning(
                    "tcp rank %d: %d writer(s) stalled with %d queued "
                    "frame(s) at shutdown", self.rank, len(live), cur)
                break
            time.sleep(0.02)
        try:
            self._listener.close()
        except OSError:
            pass
        for p in peers.values():
            try:
                p.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                p.sock.close()
            except OSError:
                pass
