"""collections subpackage."""
