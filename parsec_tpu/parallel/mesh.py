"""Device mesh construction with the five canonical parallel axes.

TPU-native scaling model (SURVEY.md §5.8): pick a mesh, annotate shardings,
let XLA insert collectives over ICI. Axes: dp (data), pp (pipeline stages),
tp (tensor/heads), sp (sequence/context), ep (experts). Any axis may be
size 1 — the sharding code paths stay identical.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXES = ("dp", "pp", "tp", "sp", "ep")


def _factor(n: int, order: Sequence[str]) -> Dict[str, int]:
    """Greedy power-of-small-primes factoring of n over the axes in
    ``order`` (round-robin halving keeps the mesh balanced)."""
    sizes = {a: 1 for a in AXES}
    remaining = n
    # round-robin: repeatedly give the next axis the smallest prime factor
    i = 0
    while remaining > 1:
        p = _smallest_prime(remaining)
        sizes[order[i % len(order)]] *= p
        remaining //= p
        i += 1
    return sizes


def _smallest_prime(n: int) -> int:
    for p in (2, 3, 5, 7, 11, 13):
        if n % p == 0:
            return p
    return n


def make_mesh(n_devices: Optional[int] = None,
              sizes: Optional[Dict[str, int]] = None,
              devices: Optional[List] = None,
              order: Sequence[str] = ("dp", "tp", "sp", "pp", "ep")):
    """Build a 5-axis jax Mesh over ``n_devices`` (or explicit devices).

    With explicit ``sizes`` missing axes default to 1; otherwise n_devices
    is factored over ``order``.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devs = jax.devices()
        if n_devices is not None and len(devs) < n_devices:
            # a tunneled accelerator plugin may shadow the virtual CPU
            # mesh (xla_force_host_platform_device_count); fall back to it
            try:
                cpu = jax.devices("cpu")
                if len(cpu) >= n_devices:
                    devs = cpu
            except RuntimeError:
                pass
        if n_devices is not None:
            assert len(devs) >= n_devices, \
                f"need {n_devices} devices, have {len(devs)}"
            devs = devs[:n_devices]
    else:
        devs = list(devices)
    n = len(devs)
    if sizes is None:
        sizes = _factor(n, order)
    else:
        sizes = {**{a: 1 for a in AXES}, **sizes}
    total = int(np.prod([sizes[a] for a in AXES]))
    assert total == n, f"mesh sizes {sizes} != {n} devices"
    arr = np.array(devs).reshape([sizes[a] for a in AXES])
    return Mesh(arr, AXES)


def spec(*axes) -> "object":
    """PartitionSpec shorthand."""
    from jax.sharding import PartitionSpec as P
    return P(*axes)


def sync_axes(leaf_spec, mesh_axes: Sequence[str] = AXES) -> Tuple[str, ...]:
    """Mesh axes a parameter is REPLICATED over (its gradients must be
    psum'd across exactly these after manual-collective backprop)."""
    used = set()
    for entry in tuple(leaf_spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return tuple(a for a in mesh_axes if a not in used)


def _vma_of(x):
    import jax
    try:
        return set(jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return None


def _pcast_varying(x, axes):
    from jax import lax
    try:
        return lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):  # older jax spelling
        return lax.pvary(x, axes)


def match_vma(x, ref):
    """Promote ``x``'s varying-manual-axes (VMA) to cover ``ref``'s.

    Under check_vma=True, lax.scan requires carry input/output types to
    match exactly — fresh-zeros initial carries are 'unvarying' while the
    loop body makes them varying. Promote initials with this before scan.
    """
    cur, want_src = _vma_of(x), _vma_of(ref)
    if cur is None or want_src is None:
        return x
    want = tuple(sorted(want_src - cur))
    return _pcast_varying(x, want) if want else x


def vary_on(x, axes, like=None):
    """Promote ``x`` to be varying on ``axes`` (plus ``like``'s VMA)."""
    cur = _vma_of(x)
    if cur is None:
        return x
    target = set(axes)
    if like is not None:
        target |= _vma_of(like) or set()
    want = tuple(sorted(target - cur))
    return _pcast_varying(x, want) if want else x


def shard_map_fwd(f, mesh, in_specs, out_specs):
    """Forward-only shard_map for DISPATCH (no autodiff through it):
    prefers the VMA-tracking ``jax.shard_map``, falls back to the
    ``jax.experimental`` spelling on older builds.

    The fallback is correct here precisely because nothing
    differentiates through a device dispatch — the two spellings only
    diverge in how psum transposes under grad (see
    :func:`shard_map_compat`, which therefore never falls back).
    Raises when neither spelling exists; callers treat that as
    "no mesh" and stay on the single-chip path."""
    import jax
    if hasattr(jax, "shard_map"):
        return shard_map_compat(f, mesh, in_specs, out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def has_shard_map() -> bool:
    """True when SOME shard_map spelling exists (the gate for
    forward-only mesh dispatch; gradient-correct code must instead
    check ``hasattr(jax, "shard_map")`` — see shard_map_compat)."""
    import jax
    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map  # noqa: F401
        return True
    except ImportError:
        return False


def shard_map_compat(f, mesh, in_specs, out_specs):
    """jax.shard_map with VMA (varying-manual-axes) tracking ON.

    check_vma=True is load-bearing for gradient correctness, not just
    checking: with it, psum transposes via the replication-aware rule and
    jax.grad of a REPLICATED leaf comes out already psum'd over exactly
    the axes its contributions were partial on — including the subtle
    cases (axes the forward never touches produce identity, mixed
    redundant+partial paths split correctly). With check_vma=False, psum
    transposes to psum and no per-leaf psum/pmean recipe is exact.
    """
    import jax
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=True)
    except TypeError:  # older jax spelling
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=True)
