"""Tiled triangular solves (dtrsm) and the Cholesky solver (dposv).

The DPLASMA-style triangular solve DAGs on the runtime: forward
substitution ``L Y = B`` and backward substitution ``L^T X = Y`` over a
tiled lower factor and a tiled right-hand-side panel. L tiles reach their
consumers via owner-placed reader tasks broadcasting over task edges (the
SUMMA pattern of pdgemm.py; reference analog: remote_dep bcast
topologies) so the graphs are distribution-correct. Every update is one
MXU matmul; diagonal solves are triangular solves on the nb x nb tile.

dposv = dpotrf (ops/dpotrf.py) + forward + backward: solves A X = B for
SPD A, in place in B.
"""
from __future__ import annotations

from ..collections.matrix import TiledMatrix
from ..dsl import ptg

# forward substitution: Y(k) = L(k,k)^{-1} (B(k) - sum_{j<k} L(k,j) Y(j))
FWD_JDF = """
descL [ type="collection" ]
descB [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]

RDIAG(k)

k = 0 .. MT-1

: descL( k, k )

READ T <- descL( k, k )
       -> T TRSM( k, 0 .. NT-1 )

BODY
{
    pass
}
END

RPANEL(m, k)

k = 0 .. MT-2
m = k+1 .. MT-1

: descL( m, k )

READ P <- descL( m, k )
       -> A GEMM( k, m, 0 .. NT-1 )

BODY
{
    pass
}
END

TRSM(k, n)

k = 0 .. MT-1
n = 0 .. NT-1

: descB( k, n )

READ T <- T RDIAG( k )
RW   X <- (k == 0) ? descB( k, n ) : C GEMM( k-1, k, n )
       -> descB( k, n )
       -> B GEMM( k, k+1 .. MT-1, n )

; (MT - k) * 10

BODY [type=tpu]
{
    X = ops.trsm_lower(T, X)
}
END

GEMM(k, m, n)

k = 0 .. MT-2
m = k+1 .. MT-1
n = 0 .. NT-1

: descB( m, n )

READ A <- P RPANEL( m, k )
READ B <- X TRSM( k, n )
RW   C <- (k == 0) ? descB( m, n ) : C GEMM( k-1, m, n )
       -> (m == k+1) ? X TRSM( m, n ) : C GEMM( k+1, m, n )

; MT - k

BODY [type=tpu]
{
    C = ops.gemm_nn_sub(C, A, B)
}
END
"""

# backward substitution: X(k) = L(k,k)^{-T} (Y(k) - sum_{m>k} L(m,k)^T X(m))
BWD_JDF = """
descL [ type="collection" ]
descB [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]

RDIAG(k)

k = 0 .. MT-1

: descL( k, k )

READ T <- descL( k, k )
       -> T TRSM( k, 0 .. NT-1 )

BODY
{
    pass
}
END

RPANEL(m, k)

k = 0 .. MT-2
m = k+1 .. MT-1

: descL( m, k )

READ P <- descL( m, k )
       -> A GEMM( k, m, 0 .. NT-1 )

BODY
{
    pass
}
END

TRSM(k, n)

k = 0 .. MT-1
n = 0 .. NT-1

: descB( k, n )

READ T <- T RDIAG( k )
RW   X <- (k == MT-1) ? descB( k, n ) : C GEMM( k, k+1, n )
       -> descB( k, n )
       -> B GEMM( 0 .. k-1, k, n )

; (k + 1) * 10

BODY [type=tpu]
{
    X = ops.trsm_lower_trans(T, X)
}
END

GEMM(k, m, n)

k = 0 .. MT-2
m = k+1 .. MT-1
n = 0 .. NT-1

: descB( k, n )

READ A <- P RPANEL( m, k )
READ B <- X TRSM( m, n )
RW   C <- (m == MT-1) ? descB( k, n ) : C GEMM( k, m+1, n )
       -> (m == k+1) ? X TRSM( k, n ) : C GEMM( k, m-1, n )

; k + 1

BODY [type=tpu]
{
    C = ops.gemm_tn_sub(C, A, B)
}
END
"""

_fwd = _bwd = None


def _factories():
    global _fwd, _bwd
    if _fwd is None:
        _fwd = ptg.compile_jdf(FWD_JDF, name="dtrsm_fwd")
        _bwd = ptg.compile_jdf(BWD_JDF, name="dtrsm_bwd")
    return _fwd, _bwd


def _tp(factory, L: TiledMatrix, B: TiledMatrix, rank: int, nb_ranks: int):
    from .. import ops as ops_module
    if L.mt != L.nt or L.mt != B.mt:
        raise ValueError(f"dtrsm: L tile grid {L.mt}x{L.nt} does not "
                         f"conform with B {B.mt}x{B.nt}")
    tp = factory.new(descL=L, descB=B, MT=B.mt, NT=B.nt,
                     rank=rank, nb_ranks=nb_ranks)
    tp.global_env["ops"] = ops_module
    return tp


def dtrsm_lower_taskpool(L, B, rank=0, nb_ranks=1):
    """Forward substitution L Y = B, Y written into B."""
    return _tp(_factories()[0], L, B, rank, nb_ranks)


def dtrsm_lower_trans_taskpool(L, B, rank=0, nb_ranks=1):
    """Backward substitution L^T X = B, X written into B."""
    return _tp(_factories()[1], L, B, rank, nb_ranks)


def dposv(context, A: TiledMatrix, B: TiledMatrix,
          rank: int = 0, nb_ranks: int = 1) -> None:
    """Solve A X = B for SPD A: Cholesky factor in place in A, then
    forward + backward substitution in place in B.

    With ``stage_compile`` (+ ``stage_compile_chain``) on, the three
    pools are declared as a chained sequence first (stagec/chain.py):
    fusable pool boundaries — provably memory-fed first stages whose
    every input writer is fused — then execute inside ONE chained
    program instead of flushing to host between pools.  Ineligible
    boundaries (multirank dataflow, residue writers) simply run
    unchained; the add/wait composition below is unchanged either way."""
    from ..utils.params import params
    from .dpotrf import dpotrf_taskpool
    pools = [dpotrf_taskpool(A, rank=rank, nb_ranks=nb_ranks),
             dtrsm_lower_taskpool(A, B, rank=rank, nb_ranks=nb_ranks),
             dtrsm_lower_trans_taskpool(A, B, rank=rank,
                                        nb_ranks=nb_ranks)]
    if params.get("stage_compile") and params.get("stage_compile_chain"):
        from ..stagec.chain import declare_chain
        declare_chain(context, pools)
    for tp in pools:
        context.add_taskpool(tp)
        context.wait()
