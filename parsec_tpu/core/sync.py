"""Readers-writer lock + value array.

Reference behavior: ``parsec_rwlock`` — a compact atomic readers-writer
lock used around shared runtime tables (ref: parsec/class/parsec_rwlock.c)
— and ``parsec_value_array_t`` — a growable array of fixed-size elements
(ref: parsec/class/value_array.h).

TPU-native re-design: both are implemented in C++ in the native core
(``native/_native.cpp`` RWLock/ValueArray — write-preferring atomic lock
that releases the GIL while spinning, spinlocked byte array) and rebound
over the pure-Python versions below when the extension builds; the
Python classes remain the documented fallbacks (``PARSEC_TPU_NATIVE=0``)
and the reference implementations for the contention tests.
"""
from __future__ import annotations

import threading


class RWLock:
    """Write-preferring readers-writer lock (fallback: condition-based)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def read_lock(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def read_unlock(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def write_lock(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def write_unlock(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    def nreaders(self) -> int:
        with self._cond:
            return self._readers


class ValueArray:
    """Growable array of fixed-size byte elements (zero-filled growth)."""

    def __init__(self, item_size: int) -> None:
        if item_size <= 0:
            raise ValueError("item_size must be positive")
        self._item = item_size
        self._buf = bytearray()
        self._n = 0
        self._lock = threading.Lock()

    def set_size(self, n: int) -> None:
        if n < 0:
            raise ValueError("negative size")
        with self._lock:
            need = n * self._item
            if need > len(self._buf):
                self._buf.extend(b"\0" * (need - len(self._buf)))
            else:
                del self._buf[need:]
            self._n = n

    def get(self, i: int) -> bytes:
        with self._lock:
            if not 0 <= i < self._n:
                raise IndexError("ValueArray index out of range")
            return bytes(self._buf[i * self._item:(i + 1) * self._item])

    def set(self, i: int, data) -> None:
        data = bytes(data)
        if len(data) != self._item:
            raise ValueError(f"expected {self._item} bytes per item")
        with self._lock:
            if not 0 <= i < self._n:
                raise IndexError("ValueArray index out of range")
            self._buf[i * self._item:(i + 1) * self._item] = data

    def push_back(self, data) -> int:
        data = bytes(data)
        if len(data) != self._item:
            raise ValueError(f"expected {self._item} bytes per item")
        with self._lock:
            idx = self._n
            self._buf.extend(data)
            self._n += 1
            return idx

    def item_size(self) -> int:
        return self._item

    def __len__(self) -> int:
        with self._lock:
            return self._n


# keep the pure-Python implementations importable under stable names
PyRWLock, PyValueArray = RWLock, ValueArray

try:  # rebind to the native C++ core when it is available
    from ..native import native as _native
    if _native is not None:
        RWLock = _native.RWLock          # type: ignore[misc,assignment]
        ValueArray = _native.ValueArray  # type: ignore[misc,assignment]
except ImportError:  # pragma: no cover
    pass
