"""Comm engine (CE) abstraction: transport-neutral messaging.

Reference behavior: ``parsec_comm_engine_t`` — tagged active messages
(callback per tag), ``mem_register/unregister``, one-sided put/get with
local+remote completion callbacks, pack/unpack, sync, capabilities
(ref: parsec/parsec_comm_engine.h:139-166). The only in-tree transport is
funnelled MPI emulating one-sided ops over two-sided sends
(parsec/parsec_mpi_funnelled.c).

TPU-native re-design: the data plane between ranks ultimately rides
ICI/DCN (XLA collectives / PJRT transfers — comm/collectives.py); the CE
here is the *control* plane and host-memory data plane. Transports:
LocalFabric (in-process ranks, the test fabric standing in for
oversubscribed mpiexec, SURVEY.md §4) and, on real deployments, a DCN
socket transport with the same interface.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class Capabilities:
    def __init__(self, sided: int = 1, noncontig: bool = True,
                 multithread: bool = False) -> None:
        self.sided = sided
        self.supports_noncontiguous_datatypes = noncontig
        self.multithreaded = multithread


class MemHandle:
    """Registered memory region handle (ref: parsec_ce_mem_reg_handle_t —
    wraps {ptr, count, datatype}); here it wraps a host array + metadata."""

    _iter = 0
    _lock = threading.Lock()

    def __init__(self, array: Any, meta: Any = None) -> None:
        with MemHandle._lock:
            MemHandle._iter += 1
            self.handle_id = MemHandle._iter
        self.array = array
        self.meta = meta


class CommEngine:
    """Transport interface (ref: parsec_comm_engine_t function table)."""

    def __init__(self, rank: int, nb_ranks: int) -> None:
        self.rank = rank
        self.nb_ranks = nb_ranks
        self.capabilities = Capabilities()
        self._tag_cbs: Dict[int, Callable] = {}
        self._mem: Dict[int, MemHandle] = {}
        self.on_get_served: Optional[Callable[[int], None]] = None
        # transports invoke this when a message lands in the inbox so a
        # parked worker wakes instead of finishing its backoff sleep
        self.on_arrival: Optional[Callable[[], None]] = None
        # late-bound tags: a message can land before its handler exists
        # (e.g. a fast peer's wave exchange reaching a rank that has not
        # built its runner yet — MPI's posted-recv semantics give this
        # for free); such messages wait here and replay at registration
        self._deferred: List[Tuple[int, int, Any]] = []
        self._deferred_lock = threading.Lock()
        self._deferred_warned: set = set()
        # telemetry sink (obs.spans.CommObs) — None keeps every
        # instrumented site on the one-attribute-check fast path
        # (the PINS ``_active == 0`` pattern)
        self._obs: Optional[Any] = None

    def _notify_arrival(self) -> None:
        cb = self.on_arrival
        if cb is not None:
            cb()

    MAX_DEFERRED = 4096

    # -- active messages ----------------------------------------------------
    def tag_register(self, tag: int, cb: Callable[[int, Any], None]) -> None:
        """cb(src_rank, payload) runs during progress() on the receiver."""
        # handler install and deferred drain are one atomic step against
        # deliver_message's check-then-defer: without the shared lock a
        # message checked before the install but deferred after the
        # drain would strand forever
        with self._deferred_lock:
            self._tag_cbs[tag] = cb
            pending = [m for m in self._deferred if m[1] == tag]
            if pending:
                self._deferred = [m for m in self._deferred if m[1] != tag]
        for src, _tag, payload in pending if pending else ():
            cb(src, payload)

    def deliver_message(self, src: int, tag: int, payload: Any) -> bool:
        """Route one drained message to its handler, or hold it if the
        tag is not bound yet (replayed by tag_register — MPI's
        posted-recv semantics). Returns True when handled now.

        A tag that never gets a handler is a bug: warn once, and fail
        loudly if the hold queue grows past MAX_DEFERRED instead of
        leaking quietly."""
        obs = self._obs
        if obs is not None:
            # counted at ARRIVAL (deferred or not) so sent/received
            # totals balance across ranks
            obs.am_arrived(src, tag, payload)
        with self._deferred_lock:
            cb = self._tag_cbs.get(tag)
            if cb is None:
                if len(self._deferred) >= self.MAX_DEFERRED:
                    raise RuntimeError(
                        f"rank {self.rank}: {len(self._deferred)} messages "
                        f"deferred for unregistered tags (first tags: "
                        f"{sorted({m[1] for m in self._deferred[:50]})}) — "
                        f"a handler was never registered")
                self._deferred.append((src, tag, payload))
        if cb is None:
            if tag not in self._deferred_warned:
                self._deferred_warned.add(tag)
                from ..utils import logging as plog
                plog.debug.verbose(
                    1, "rank %d: deferring message(s) for unregistered "
                    "tag %d", self.rank, tag)
            return False
        if obs is not None:
            t0 = time.monotonic_ns()
            cb(src, payload)
            obs.delivered(src, self.rank, tag, t0)
            return True
        cb(src, payload)
        return True

    def tag_unregister(self, tag: int) -> None:
        self._tag_cbs.pop(tag, None)

    def send_am(self, dst: int, tag: int, payload: Any) -> None:
        raise NotImplementedError

    # -- registered memory + one-sided emulation ----------------------------
    def mem_register(self, array: Any, meta: Any = None) -> MemHandle:
        h = MemHandle(array, meta)
        self._mem[h.handle_id] = h
        return h

    def mem_unregister(self, handle: MemHandle) -> None:
        self._mem.pop(handle.handle_id, None)

    def get(self, src_rank: int, remote_handle_id: int,
            on_complete: Callable[[Any], None]) -> None:
        """One-sided get: fetch the remote registered region
        (emulated with a GET-request AM + data reply, like the funnelled
        MPI engine, parsec_mpi_funnelled.c:245-365).

        Aggregation contract: gets issued from message handlers during
        one progress() drain MAY be batched per peer into a single
        request/reply frame — on_complete still fires once per get,
        but callers must not assume one wire message per call."""
        raise NotImplementedError

    def put(self, dst_rank: int, remote_handle_id: int, array: Any,
            on_complete: Optional[Callable] = None) -> None:
        raise NotImplementedError

    # -- progress -----------------------------------------------------------
    def progress(self) -> int:
        """Drain incoming messages; returns #messages handled."""
        raise NotImplementedError

    def sync(self) -> None:
        """Barrier across ranks."""
        raise NotImplementedError

    def fini(self) -> None:
        pass


# wire tags (ref: parsec/remote_dep.h:41-48)
TAG_ACTIVATE = 1
TAG_GET_REQ = 2
TAG_GET_DATA = 3
TAG_PUT_DATA = 4
TAG_TERMDET = 5
TAG_DTD_DATA = 6
TAG_MEM_PUT = 7
TAG_USER_BASE = 16
