"""models subpackage."""
