"""Ulysses-style sequence parallelism: all-to-all head<->sequence reshard.

The second canonical long-context strategy (besides the ring): attention
wants full sequence per head, the rest of the model wants full heads per
sequence chunk. ``lax.all_to_all`` over the sp axis converts
[B, H, T/sp, D] <-> [B, H/sp, T, D] in one fused ICI collective, attention
runs locally on full sequences, then the inverse all-to-all restores the
layout (ref capability mapping: SURVEY.md §5.7).
"""
from __future__ import annotations

from typing import Any

from jax import lax

from .mesh import axis_size

from .ring_attention import local_attention


def heads_to_sequence(x: Any, axis_name: str = "sp") -> Any:
    """[B, H, T_local, Dh] -> [B, H_local, T, Dh]: scatter heads, gather seq."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def sequence_to_heads(x: Any, axis_name: str = "sp") -> Any:
    """[B, H_local, T, Dh] -> [B, H, T_local, Dh]: inverse reshard."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q: Any, k: Any, v: Any, axis_name: str = "sp",
                      causal: bool = True) -> Any:
    """Sequence-parallel attention via all-to-all resharding.

    q/k/v: [B, H, T_local, Dh] (H divisible by the sp axis size).
    """
    sp = axis_size(axis_name)
    assert q.shape[1] % sp == 0, \
        f"ulysses needs heads ({q.shape[1]}) divisible by sp ({sp})"
    qg = heads_to_sequence(q, axis_name)
    kg = heads_to_sequence(k, axis_name)
    vg = heads_to_sequence(v, axis_name)
    out = local_attention(qg, kg, vg, causal=causal)
    return sequence_to_heads(out, axis_name)
