"""Wire framing for the TCP transport: the comm-engine fast path.

Frame format (v2). Every frame on a connection is::

    <u64 body_len> <body>

``body_len == GOODBYE`` (2**64-1) is the clean-shutdown sentinel (no
body follows). Otherwise the body's first byte is a *kind*:

- ``K_BATCH``: one or more complete active messages coalesced into a
  single frame (ONE syscall per batch on the send side). Each message
  segment is ``<u32 pickle_len> <u32 nbufs> [<u64 size>]*nbufs
  <pickle> <buf bytes>*`` — the pickle-5 frame plus its out-of-band
  buffers, copied in-band at enqueue time (all are below the chunk
  threshold by construction, so the copy is small and preserves the
  historical copy-at-send snapshot semantics).
- ``K_XFER_HDR``: header of a chunked message — a message whose
  payload carries at least one buffer >= the chunk threshold. The
  pickle frame and the small buffers ride in the header; each large
  buffer is announced (size only) and its bytes follow as ``K_CHUNK``
  frames, interleavable with control traffic.
- ``K_CHUNK``: one bounded segment of one announced buffer
  (``<u64 xfer_id> <u32 buf_index> <u64 offset> <bytes>``). The
  receiver reassembles; the message is delivered when every announced
  byte has landed. Chunks of one transfer are FIFO; *other* frames may
  interleave between them — that is the point (no head-of-line
  blocking of small control AMs behind a multi-MB payload).
- ``K_HELLO``: capability advertisement sent once per connection right
  after the rank handshake (``{"ver", "codecs", "rank"}``). A peer
  that never sends one (mixed version) simply never negotiates a
  codec, so compression silently stays off toward it.
- ``K_COMP``: a compressed *body* (kind byte included) of any of the
  above: ``<u8 codec_id> <u64 raw_len> <compressed>``. Only emitted
  toward peers that advertised the codec.

Quantized tile codecs (the ``"qz"`` HELLO capability — ISSUE 14):
LOSSY blockwise encodings for bulk float tile payloads, registered in
the same ``CODECS`` table as the lossless byte codecs but with
``lossless=False`` — they never ride ``K_COMP`` (a lossy transform of
a pickled body would corrupt it). Instead they apply per BUFFER on the
chunk lane: a pickle-5 out-of-band float buffer >= the chunk threshold
may be encoded before it is announced in the transfer header, its
bufspec flag gains the ``BUF_QUANT`` bit, the encoded bytes stream as
normal ``K_CHUNK`` frames, and the receiver dequantizes the
reassembled buffer back to the original dtype/length before the
message unpickles — transparent to every handler. Because the
encoding happens at ENQUEUE (before the K_SEQ envelope), the reliable
session's replay window retains the encoded bytes and a post-flap
replay is bit-identical for free. Only ever emitted toward peers whose
HELLO advertised the codec under ``"qz"`` (both ends must enable
``comm_quantize``); a mixed-version peer stays lossless.

- ``qbf16``: round-to-nearest-even bfloat16 (f64 narrows through f32)
  — 2 bytes/element, ~2x (f32) / 4x (f64) fewer payload bytes.
- ``qint8``: int8 with one f32 scale per ``QUANT_BLOCK``-element block
  (``scale = absmax/127``) — ~4x/8x fewer payload bytes.

Encoded buffer layout: ``<u8 codec_id> <u8 dtype_code> <u64 raw_len>
<u32 block_elems>`` then the codec payload (qbf16: u16 little-endian
elements; qint8: f32 scales[nblocks] + i8 elements).
- ``K_ELASTIC``: one elastic-membership message (ft/elastic.py — grid
  resize views, join announcements, welcomes) as a pickled dict.
  Handled directly by the receiver THREAD like ``K_PING``: a joiner's
  announcement or a resize proposal must land even while every worker
  is stuck in a long kernel. Only sent toward peers whose HELLO
  advertised ``"el"`` — a pre-elastic peer is never drawn into a
  resize agreement it cannot answer.
- ``K_PING`` / ``K_PONG``: heartbeat probe and its echo
  (``<u32 seq> <u64 t_ns>``, the sender's monotonic clock — the pong
  echoes it back so the sender computes the round trip). Handled
  directly by the receiver THREAD (like K_HELLO), never queued through
  the inbox: a rank stuck in a long kernel still answers, so TCP
  liveness judgment (ft/detector.py) is independent of the progress
  cadence. Only sent toward peers whose HELLO advertised ``"hb"`` — a
  mixed-version peer is never probed and therefore never declared dead
  by the proactive detector.

Reliable-session framing (the ``"rs"`` HELLO capability — transient
link faults recover by reconnect + replay instead of rank eviction,
comm/tcp.py):

- ``K_SEQ``: envelope around any DATA frame body (``<u32 epoch>
  <u64 seq> <inner body>``). Each direction numbers its data frames
  (batches, transfer headers, chunks) with a per-link monotonically
  increasing ``seq``; the receiver delivers in order exactly once —
  a replayed frame it already delivered is dropped by seq (idempotent
  re-delivery: no active message ever runs twice). Session-less
  control frames (hello, ping/pong, ack, resume, elastic) are never
  wrapped: they are regenerated, not replayed.
- ``K_ACK``: cumulative delivery acknowledgment (``<u32 epoch>
  <u64 seq>``) — everything up to ``seq`` landed, so the sender may
  drop those frames from its bounded replay window.
- ``K_RESUME``: reconnect handshake (a pickled dict), sent right
  after the rank-identifying handshake on a RE-dialed connection:
  carries the proposed session ``epoch``, the last-delivered ``ack``
  both ways, and optionally a ``partial`` claim — how many bytes of
  the next expected frame already landed before the link tore, so the
  sender resumes that frame mid-body instead of resending it.
- ``K_FRAG``: the byte-level resume of one torn frame
  (``<u32 epoch> <u64 seq> <u64 offset> <bytes>``): the remainder of
  the frame the receiver holds a partial body of; receiver stitches
  partial + remainder and dispatches the whole as a normal K_SEQ
  frame.

All integers little-endian, matching the v1 framing.
"""
from __future__ import annotations

import pickle
import struct
import zlib
from typing import (Any, Dict, Iterator, List, NamedTuple, Optional,
                    Sequence, Tuple)

import numpy as np

GOODBYE = (1 << 64) - 1  # frame-size sentinel: clean shutdown, not a crash

K_BATCH = 0
K_XFER_HDR = 1
K_CHUNK = 2
K_HELLO = 3
K_COMP = 4
K_PING = 5
K_PONG = 6
K_ELASTIC = 7
K_SEQ = 8
K_ACK = 9
K_RESUME = 10
K_FRAG = 11
K_TUNE = 12

WIRE_VERSION = 2

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_SEG = struct.Struct("<II")          # pickle_len, nbufs
_BATCH = struct.Struct("<BI")        # kind, nmsgs
_XFER = struct.Struct("<BQII")       # kind, xfer_id, pickle_len, nbufs
_BUFSPEC = struct.Struct("<BQ")      # chunked?, size
_CHUNK = struct.Struct("<BQIQ")      # kind, xfer_id, buf_index, offset
_COMP = struct.Struct("<BBQ")        # kind, codec_id, raw_len
_PING = struct.Struct("<BIQ")        # kind, seq, t_ns (sender monotonic)
_PINGX = struct.Struct("<BIQQ")      # + responder clock (the "tr" ext)
_SEQHDR = struct.Struct("<BIQ")      # kind, epoch, seq (K_SEQ / K_ACK)
_FRAGHDR = struct.Struct("<BIQQ")    # kind, epoch, seq, byte offset


# -- codecs -------------------------------------------------------------
def _lz4_mod():
    try:
        import lz4.frame as _lz4
        return _lz4
    except ImportError:
        return None


class Codec(NamedTuple):
    """One registered wire codec. ``lossless`` entries are byte codecs
    (``comp``/``dec`` map bytes to bytes, the K_COMP path); lossy
    entries are QUANTIZED tile codecs applied per float buffer on the
    chunk lane (see the module docstring) and are excluded from the
    lossless negotiation paths by construction."""

    cid: int
    comp: Any
    dec: Any
    lossless: bool = True


#: name -> Codec; lz4 is optional — absent installs simply don't
#: advertise it at the handshake. The quantized (lossy) tile codecs
#: live in the same table under distinct wire ids; the HELLO
#: advertises them separately (``"qz"`` vs ``"codecs"``), so the two
#: families can never cross-negotiate.
CODECS: Dict[str, Codec] = {
    "zlib": Codec(1, lambda b: zlib.compress(b, 1), zlib.decompress),
}
if _lz4_mod() is not None:
    _l = _lz4_mod()
    CODECS["lz4"] = Codec(2, _l.compress, _l.decompress)

_CODEC_BY_ID = {c.cid: (name, c) for name, c in CODECS.items()}

#: preference order when both ends support several
_CODEC_PREF = ("lz4", "zlib")


def available_codecs() -> List[str]:
    """Lossless byte codecs (the HELLO ``"codecs"`` capability)."""
    return sorted(n for n, c in CODECS.items() if c.lossless)


def available_quant_codecs() -> List[str]:
    """Quantized tile codecs (the HELLO ``"qz"`` capability)."""
    return sorted(n for n, c in CODECS.items() if not c.lossless)


def negotiate_codec(mine: Sequence[str],
                    theirs: Sequence[str]) -> Optional[str]:
    """Pick the preferred codec both ends advertised (None: no common
    codec — e.g. a mixed-version peer that never sent a HELLO)."""
    common = set(mine) & set(theirs)
    for name in _CODEC_PREF:
        if name in common:
            return name
    return sorted(common)[0] if common else None


def normalize_quant_codec(name: str) -> Optional[str]:
    """Map a ``comm_quantize`` knob value to a registered quantized
    codec name (``bf16``/``int8`` shorthands accepted); None when the
    knob is empty. Raises on an unknown or lossless codec name."""
    name = (name or "").strip().lower()
    if not name or name in ("0", "off", "none"):
        return None
    if not name.startswith("q"):
        name = "q" + name
    ent = CODECS.get(name)
    if ent is None or ent.lossless:
        raise ValueError(
            f"comm_quantize={name!r}: not a registered quantized codec "
            f"(have {available_quant_codecs()})")
    return name


def negotiate_quant_codec(requested: Optional[str],
                          theirs: Sequence[str]) -> Optional[str]:
    """The quantized codec to use toward a peer: the locally requested
    one when the peer's HELLO advertised it under ``"qz"``, else None
    (mixed-version or knob-unset peers negotiate down to lossless)."""
    if requested is None or requested not in (theirs or ()):
        return None
    return requested


# -- message segments (K_BATCH) -----------------------------------------
def pack_segment(frame: bytes, bufs: Sequence[Any]) -> bytes:
    """One in-band message segment: pickle frame + copied buffers."""
    parts = [_SEG.pack(len(frame), len(bufs))]
    parts += [_U64.pack(len(b) if isinstance(b, (bytes, bytearray))
                        else b.nbytes) for b in bufs]
    parts.append(frame)
    parts += [bytes(b) for b in bufs]
    return b"".join(parts)


def pack_batch(segments: Sequence[bytes]) -> List[bytes]:
    """Body pieces of a K_BATCH frame holding ``segments`` messages."""
    return [_BATCH.pack(K_BATCH, len(segments)), *segments]


def parse_batch(body: memoryview) -> Iterator[Tuple[memoryview,
                                                    List[memoryview]]]:
    """Yield (pickle_frame, [buffers]) per coalesced message. The
    yielded views alias ``body`` — zero extra copy on the receive
    side; arrays reconstructed over them are read-only."""
    _kind, nmsgs = _BATCH.unpack_from(body, 0)
    off = _BATCH.size
    for _ in range(nmsgs):
        flen, nbufs = _SEG.unpack_from(body, off)
        off += _SEG.size
        sizes = [_U64.unpack_from(body, off + 8 * i)[0]
                 for i in range(nbufs)]
        off += 8 * nbufs
        frame = body[off:off + flen]
        off += flen
        bufs = []
        for sz in sizes:
            bufs.append(body[off:off + sz])
            off += sz
        yield frame, bufs
    if off != len(body):
        raise ValueError(
            f"batch frame desync: parsed {off} of {len(body)} bytes")


# -- chunked transfers (K_XFER_HDR / K_CHUNK) ---------------------------
def pack_xfer_hdr(xfer_id: int, frame: bytes,
                  bufspecs: Sequence[Tuple[int, int, Optional[Any]]]
                  ) -> bytes:
    """Header of a chunked message. ``bufspecs``: per pickle-5 buffer,
    (flags, size, inline_bytes-or-None) in buffer order; ``flags`` is
    a BUF_CHUNKED|BUF_QUANT bitmask (plain bools read as BUF_CHUNKED,
    the pre-quantization spelling). Chunked buffers announce size
    only, their bytes follow as K_CHUNK frames; a BUF_QUANT size is
    the ENCODED byte count (the self-describing raw length travels
    inside the encoding)."""
    parts = [_XFER.pack(K_XFER_HDR, xfer_id, len(frame), len(bufspecs))]
    parts += [_BUFSPEC.pack(int(flags), size)
              for (flags, size, _b) in bufspecs]
    parts.append(frame)
    parts += [bytes(b) for (flags, _s, b) in bufspecs
              if not int(flags) & BUF_CHUNKED]
    return b"".join(parts)


def parse_xfer_hdr(body: memoryview) -> Tuple[int, memoryview,
                                              List[Tuple[int, int,
                                                         Optional[memoryview]]]]:
    _kind, xfer_id, flen, nbufs = _XFER.unpack_from(body, 0)
    off = _XFER.size
    specs = []
    for i in range(nbufs):
        flags, size = _BUFSPEC.unpack_from(body, off)
        specs.append([int(flags), size, None])
        off += _BUFSPEC.size
    frame = body[off:off + flen]
    off += flen
    for spec in specs:
        if not spec[0] & BUF_CHUNKED:
            spec[2] = body[off:off + spec[1]]
            off += spec[1]
    if off != len(body):
        raise ValueError(
            f"xfer header desync: parsed {off} of {len(body)} bytes")
    return xfer_id, frame, [tuple(s) for s in specs]


def pack_chunk_hdr(xfer_id: int, buf_index: int, offset: int) -> bytes:
    return _CHUNK.pack(K_CHUNK, xfer_id, buf_index, offset)


def parse_chunk(body: memoryview) -> Tuple[int, int, int, memoryview]:
    _kind, xfer_id, buf_index, offset = _CHUNK.unpack_from(body, 0)
    return xfer_id, buf_index, offset, body[_CHUNK.size:]


class RxXfer:
    """Receive-side reassembly of one chunked message."""

    __slots__ = ("frame", "bufs", "remaining", "nbytes", "quant")

    def __init__(self, frame: memoryview,
                 bufspecs: Sequence[Tuple[int, int, Optional[memoryview]]]
                 ) -> None:
        # the pickle frame must outlive the enclosing frame body
        self.frame = bytes(frame)
        self.bufs: List[Any] = []
        self.quant: List[bool] = []     # buffer needs dequantization
        self.remaining = 0
        self.nbytes = len(self.frame)
        for (flags, size, inline) in bufspecs:
            self.nbytes += size
            self.quant.append(bool(int(flags) & BUF_QUANT))
            if int(flags) & BUF_CHUNKED:
                self.bufs.append(bytearray(size))
                self.remaining += size
            else:
                self.bufs.append(bytes(inline))

    def feed(self, buf_index: int, offset: int, data: memoryview) -> bool:
        """Land one chunk; True when the whole message has arrived."""
        buf = self.bufs[buf_index]
        if not isinstance(buf, bytearray):
            raise ValueError(f"chunk for non-chunked buffer {buf_index}")
        n = len(data)
        if offset + n > len(buf):
            raise ValueError(
                f"chunk overruns buffer {buf_index}: "
                f"{offset}+{n} > {len(buf)}")
        buf[offset:offset + n] = data
        self.remaining -= n
        return self.remaining <= 0

    def message(self) -> Any:
        bufs = [dequantize_buffer(b) if q else b
                for b, q in zip(self.bufs, self.quant)]
        return pickle.loads(self.frame, buffers=bufs)


def load_message(frame: memoryview, bufs: Sequence[Any]) -> Any:
    """Unpickle one (src, tag, payload) message segment."""
    return pickle.loads(frame, buffers=list(bufs))


# -- heartbeats (ft/detector.py) ----------------------------------------
def pack_ping(seq: int, t_ns: int, pong: bool = False,
              clock_ns: Optional[int] = None) -> bytes:
    """One heartbeat frame; the pong echoes the ping's (seq, t_ns).

    ``clock_ns`` is the clock-alignment extension (the ``"tr"`` HELLO
    capability — ISSUE 15): when not None the frame grows a trailing
    u64 carrying the SENDER's monotonic clock.  An extended PING marks
    the exchange (the value itself is unused, 0 by convention); the
    answering pong stamps its responder clock there, which is the
    midpoint-method sample the receiver folds into its per-peer offset
    EWMA.  ``clock_ns=None`` keeps the original 13-byte frame
    bit-for-bit, so a knob-unset build and every frame toward a
    mixed-version peer are byte-identical; old parsers read the
    leading fields positionally and ignore the trailing u64."""
    kind = K_PONG if pong else K_PING
    if clock_ns is None:
        return _PING.pack(kind, seq & 0xFFFFFFFF, t_ns)
    return _PINGX.pack(kind, seq & 0xFFFFFFFF, t_ns, clock_ns)


def parse_ping(body: memoryview) -> Tuple[int, int]:
    """-> (seq, t_ns); same layout for K_PING and K_PONG (extended
    frames carry a trailing clock word read via :func:`ping_clock`)."""
    _kind, seq, t_ns = _PING.unpack_from(body, 0)
    return seq, t_ns


def ping_clock(body: memoryview) -> Optional[int]:
    """The clock-alignment extension word of a K_PING/K_PONG frame
    (None on a plain 13-byte frame — a mixed-version or knob-unset
    peer never sends the extension)."""
    if len(body) < _PINGX.size:
        return None
    return _PINGX.unpack_from(body, 0)[3]


# -- reliable session (comm/tcp.py "rs" capability) ---------------------
SEQ_HDR_LEN = _SEQHDR.size


def pack_seq(epoch: int, seq: int) -> bytes:
    """Envelope header prepended to one data frame body."""
    return _SEQHDR.pack(K_SEQ, epoch & 0xFFFFFFFF, seq)


def parse_seq(body: memoryview) -> Tuple[int, int, memoryview]:
    """-> (epoch, seq, inner body)."""
    _kind, epoch, seq = _SEQHDR.unpack_from(body, 0)
    return epoch, seq, body[_SEQHDR.size:]


def parse_seq_prefix(buf) -> Optional[Tuple[int, int]]:
    """(epoch, seq) when ``buf`` begins with a complete K_SEQ header
    (the partial-frame resume claim), else None."""
    if len(buf) < _SEQHDR.size or buf[0] != K_SEQ:
        return None
    _kind, epoch, seq = _SEQHDR.unpack_from(buf, 0)
    return epoch, seq


def pack_ack(epoch: int, seq: int) -> bytes:
    """Cumulative ack: every seq up to ``seq`` was delivered."""
    return _SEQHDR.pack(K_ACK, epoch & 0xFFFFFFFF, seq)


def parse_ack(body: memoryview) -> Tuple[int, int]:
    _kind, epoch, seq = _SEQHDR.unpack_from(body, 0)
    return epoch, seq


def pack_resume(info: Dict[str, Any]) -> bytes:
    """Reconnect handshake frame ({"rank", "epoch", "ack", "partial"})."""
    return bytes([K_RESUME]) + pickle.dumps(info, protocol=4)


def parse_resume(body: memoryview) -> Dict[str, Any]:
    return pickle.loads(body[1:])


def pack_frag(epoch: int, seq: int, offset: int) -> bytes:
    """Header of a byte-level frame resume (remainder bytes follow)."""
    return _FRAGHDR.pack(K_FRAG, epoch & 0xFFFFFFFF, seq, offset)


def parse_frag(body: memoryview) -> Tuple[int, int, int, memoryview]:
    _kind, epoch, seq, offset = _FRAGHDR.unpack_from(body, 0)
    return epoch, seq, offset, body[_FRAGHDR.size:]


# -- elastic membership (ft/elastic.py) ---------------------------------
def pack_elastic(payload: Dict[str, Any]) -> bytes:
    """One membership frame (view / join / welcome dict)."""
    return bytes([K_ELASTIC]) + pickle.dumps(payload, protocol=4)


def parse_elastic(body: memoryview) -> Dict[str, Any]:
    return pickle.loads(body[1:])


# -- runtime tuning (tune/controller.py; the "tn" HELLO capability) -----
def pack_tune(payload: Dict[str, Any]) -> bytes:
    """One runtime-tuning control frame (e.g. a per-link quantized
    codec renegotiation, ``{"op": "codec", "codec": name-or-None}``).
    Session-less like K_ELASTIC: handled on the receiver THREAD, never
    wrapped in K_SEQ (a renegotiation is regenerated, not replayed —
    and quantization happens at enqueue, so the replay window already
    holds bytes encoded under the codec active at enqueue time)."""
    return bytes([K_TUNE]) + pickle.dumps(payload, protocol=4)


def parse_tune(body: memoryview) -> Dict[str, Any]:
    return pickle.loads(body[1:])


# -- multi-tenant serving (serve/; the "sv" HELLO capability) -----------
#: serve control protocol version — bumped when the envelope grows
#: fields an old server cannot ignore
SERVE_PROTO_VERSION = 1


def serve_request(op: str, req: int, tenant: Optional[str] = None,
                  **kw: Any) -> Dict[str, Any]:
    """Envelope of one serve control request (open/submit/wait/stats).
    Serve control rides TAG_SERVE active messages — the AM layer
    already frames and pickles dict payloads, so no new frame kind is
    needed; the envelope just pins the field names and a version so
    ServeClient and SessionServer agree across builds."""
    msg: Dict[str, Any] = {"sv": SERVE_PROTO_VERSION, "op": str(op),
                           "req": int(req)}
    if tenant is not None:
        msg["tenant"] = str(tenant)
    msg.update(kw)
    return msg


def serve_reply(req: int, ok: bool, **kw: Any) -> Dict[str, Any]:
    """Envelope of one serve control reply, correlated by ``req``."""
    msg: Dict[str, Any] = {"sv": SERVE_PROTO_VERSION, "req": int(req),
                           "ok": bool(ok)}
    msg.update(kw)
    return msg


def parse_serve(payload: Any) -> Dict[str, Any]:
    """Validate one serve envelope (either direction); raises
    ValueError on a malformed dict or an unsupported version so the
    endpoint can reply with a loud error instead of misbehaving."""
    if not isinstance(payload, dict) or "sv" not in payload:
        raise ValueError("not a serve envelope")
    v = int(payload.get("sv") or 0)
    if v < 1 or v > SERVE_PROTO_VERSION:
        raise ValueError(f"unsupported serve protocol version {v}")
    if "req" not in payload:
        raise ValueError("serve envelope missing req id")
    return payload


# -- hello / compression ------------------------------------------------
def pack_hello(info: Dict[str, Any]) -> bytes:
    return bytes([K_HELLO]) + pickle.dumps(info, protocol=4)


def parse_hello(body: memoryview) -> Dict[str, Any]:
    return pickle.loads(body[1:])


def compress_body(body: bytes, codec: str) -> Optional[List[bytes]]:
    """K_COMP pieces for ``body``, or None when compression does not
    pay (the compressed form is not smaller)."""
    ent = CODECS[codec]
    if not ent.lossless:
        raise ValueError(
            f"{codec}: quantized codecs never compress frame BODIES "
            f"(a lossy transform of a pickled body would corrupt it)")
    out = ent.comp(body)
    if len(out) + _COMP.size >= len(body):
        return None
    return [_COMP.pack(K_COMP, ent.cid, len(body)), out]


def decompress_body(body: memoryview) -> bytes:
    _kind, cid, raw_len = _COMP.unpack_from(body, 0)
    ent = _CODEC_BY_ID.get(cid)
    if ent is None or not ent[1].lossless:
        raise ValueError(f"unknown compression codec id {cid}")
    out = ent[1].dec(bytes(body[_COMP.size:]))
    if len(out) != raw_len:
        raise ValueError(
            f"decompressed length {len(out)} != announced {raw_len}")
    return out


# -- quantized tile codecs (lossy; the "qz" HELLO capability) -----------
#: elements per int8 scale block (one f32 scale each); a pure function
#: of the codec version — both ends derive block counts from it
QUANT_BLOCK = 512

#: flags of a transfer-header bufspec (``pack_xfer_hdr``): bit 0 = the
#: buffer's bytes follow as K_CHUNK frames, bit 1 = the announced bytes
#: are a quantized encoding the receiver must decode before unpickling
BUF_CHUNKED = 1
BUF_QUANT = 2

_QHDR = struct.Struct("<BBQI")   # codec_id, dtype_code, raw_len, block
_QDTYPES = {"d": (0, np.float64), "f": (1, np.float32)}
_QDTYPE_BY_CODE = {0: np.float64, 1: np.float32}


def _enc_bf16(x: np.ndarray) -> bytes:
    """Round-to-nearest-even bfloat16 of a float array (f64 narrows
    through f32 first, like an XLA bf16 cast would)."""
    u = np.ascontiguousarray(x, np.float32).view(np.uint32)
    # RNE: add 0x7FFF + the current LSB of the kept half, then truncate
    return (((u + np.uint32(0x7FFF) + ((u >> np.uint32(16))
                                       & np.uint32(1)))
             >> np.uint32(16)).astype(np.uint16)).tobytes()


def _dec_bf16(payload: memoryview, n: int, dt) -> bytes:
    u16 = np.frombuffer(payload, np.uint16, count=n)
    f32 = (u16.astype(np.uint32) << np.uint32(16)).view(np.float32)
    return np.ascontiguousarray(f32, dt).tobytes()


def _enc_int8(x: np.ndarray) -> bytes:
    """Blockwise int8: per QUANT_BLOCK-element block one f32 scale
    (absmax/127); values quantize to round(x/scale) in [-127, 127]."""
    n = x.size
    nblocks = max(1, (n + QUANT_BLOCK - 1) // QUANT_BLOCK)
    xp = np.zeros(nblocks * QUANT_BLOCK, np.float32)
    xp[:n] = np.ascontiguousarray(x, np.float32)
    xb = xp.reshape(nblocks, QUANT_BLOCK)
    scales = (np.abs(xb).max(axis=1) / 127.0).astype(np.float32)
    inv = np.zeros_like(scales)
    np.divide(1.0, scales, out=inv, where=scales > 0)
    q = np.clip(np.rint(xb * inv[:, None]), -127, 127).astype(np.int8)
    return scales.tobytes() + q.reshape(-1)[:n].tobytes()


def _dec_int8(payload: memoryview, n: int, dt) -> bytes:
    nblocks = max(1, (n + QUANT_BLOCK - 1) // QUANT_BLOCK)
    scales = np.frombuffer(payload, np.float32, count=nblocks)
    q = np.frombuffer(payload, np.int8, count=n, offset=4 * nblocks)
    xp = np.zeros(nblocks * QUANT_BLOCK, np.float32)
    xp[:n] = q
    out = (xp.reshape(nblocks, QUANT_BLOCK)
           * scales[:, None]).reshape(-1)[:n]
    return np.ascontiguousarray(out, dt).tobytes()


CODECS["qbf16"] = Codec(16, _enc_bf16, _dec_bf16, lossless=False)
CODECS["qint8"] = Codec(17, _enc_int8, _dec_int8, lossless=False)
_CODEC_BY_ID = {c.cid: (name, c) for name, c in CODECS.items()}


def quantize_buffer(view: Any, fmt: str, codec: str) -> bytes:
    """Encode one flat float buffer (``fmt`` = 'd'/'f', the buffer
    protocol format of the ORIGINAL array) with a quantized codec.
    The returned bytes are self-describing (``_QHDR`` leads them)."""
    ent = CODECS[codec]
    dcode, dt = _QDTYPES[fmt]
    x = np.frombuffer(view, dtype=dt)
    return _QHDR.pack(ent.cid, dcode, x.nbytes, QUANT_BLOCK) \
        + ent.comp(x)


def dequantize_buffer(buf: Any) -> bytes:
    """Decode one quantized buffer back to the exact raw bytes of the
    original dtype/length (lossy in VALUE, exact in layout — the
    unpickler reconstructs the array over them unchanged)."""
    mv = memoryview(buf)
    cid, dcode, raw_len, block = _QHDR.unpack_from(mv, 0)
    ent = _CODEC_BY_ID.get(cid)
    if ent is None or ent[1].lossless:
        raise ValueError(f"unknown quantized codec id {cid}")
    if block != QUANT_BLOCK:
        raise ValueError(
            f"quantized block size {block} != local {QUANT_BLOCK}")
    dt = _QDTYPE_BY_CODE.get(dcode)
    if dt is None:
        raise ValueError(f"unknown quantized dtype code {dcode}")
    n = raw_len // np.dtype(dt).itemsize
    out = ent[1].dec(mv[_QHDR.size:], n, dt)
    if len(out) != raw_len:
        raise ValueError(
            f"dequantized length {len(out)} != announced {raw_len}")
    return out


def quant_raw_len(buf: Any) -> int:
    """Raw (decoded) byte count a quantized buffer stands for, read
    from its self-describing header without decoding the payload."""
    return _QHDR.unpack_from(memoryview(buf), 0)[2]


def qdq_array(arr: np.ndarray, codec: str) -> np.ndarray:
    """Quantize-dequantize round trip of an array: exactly the values
    a quantized wire transfer would deliver (shared by the reduced-
    precision collective lane so wire and lane quantize identically)."""
    a = np.ascontiguousarray(arr)
    fmt = {"float64": "d", "float32": "f"}.get(a.dtype.name)
    if fmt is None:
        return arr
    raw = dequantize_buffer(
        quantize_buffer(memoryview(a).cast("B"), fmt, codec))
    return np.frombuffer(raw, dtype=a.dtype).reshape(a.shape).copy()
