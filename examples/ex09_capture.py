"""Ex09: graph capture — the whole taskpool as ONE XLA executable.

Teaches: ``ptg.capture`` (topo-sort + trace a single-rank PTG DAG into
one jitted program; ~0.2 ms for a 20-task dpotrf at N=8192 on a TPU vs
per-task dispatch), ``capture_sequence`` (fuse a sequential composition
— here the full dposv solve), and ``sharded_fn`` (pin every tile to a
``jax.sharding`` Mesh for SPMD multi-chip execution, letting GSPMD
insert the collectives). No reference analog: this is TPU-first design
(SURVEY.md §7.3 — "fuse tile ops into large-enough XLA executables").
"""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.ops import dpotrf_taskpool, make_spd
from parsec_tpu.ops.dtrsm import (dtrsm_lower_taskpool,
                                  dtrsm_lower_trans_taskpool)


def main(n: int = 256, nb: int = 64) -> int:
    M = make_spd(n)
    rng = np.random.RandomState(0)
    Bn = rng.rand(n, 8).astype(np.float32)

    # 1. capture one taskpool: the Cholesky DAG becomes one dispatch
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    cg = ptg.capture(dpotrf_taskpool(A))
    print(f"captured dpotrf: {cg.nb_tasks} tasks -> 1 XLA executable")
    cg.run()
    L = np.tril(A.to_numpy())
    print("||L L^T - M|| / ||M|| =",
          np.linalg.norm(L @ L.T - M) / np.linalg.norm(M))

    # 2. capture a sequential composition: dposv = potrf ; trsm ; trsm^T
    A2 = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    B2 = TwoDimBlockCyclic(n, 8, nb, 8, dtype=np.float32).from_numpy(Bn)
    A2.name, B2.name = "descA", "descB"
    seq = ptg.capture_sequence([
        dpotrf_taskpool(A2),
        dtrsm_lower_taskpool(A2, B2),
        dtrsm_lower_trans_taskpool(A2, B2),
    ])
    seq.run()
    X = B2.to_numpy()
    ref = np.linalg.solve(M.astype(np.float64), Bn.astype(np.float64))
    print(f"captured dposv ({seq.nb_tasks} tasks): max |X - ref| =",
          float(np.abs(X - ref).max()))

    # 3. multi-chip: pin tiles to a mesh sharding; GSPMD partitions
    import jax
    if len(jax.devices()) >= 2:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        ndev = 2 * (len(jax.devices()) // 2)
        mesh = Mesh(np.array(jax.devices()[:ndev]).reshape(2, ndev // 2),
                    ("x", "y"))
        sh = NamedSharding(mesh, P("x", "y"))
        A3 = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        cg3 = ptg.capture(dpotrf_taskpool(A3))
        tiles = {"descA": {c: jax.device_put(A3.tile(*c), sh)
                           for c in A3.tiles()}}
        out = cg3.sharded_fn(sh)(tiles)
        jax.block_until_ready(out)
        print(f"sharded capture ran SPMD over {ndev} devices; "
              f"output tile sharding: {next(iter(out['descA'].values())).sharding.spec}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
