"""Repo tooling scripts.  This package marker makes them importable
(``from tools import dagenum``) when the repo root is on sys.path — the
static verifier's cycle pass reuses dagenum's enumeration core."""
