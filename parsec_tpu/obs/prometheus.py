"""Prometheus text exposition (version 0.0.4) for the metrics registry.

``render(metrics)`` turns a :class:`obs.metrics.MetricsRegistry` into the
plain-text format every Prometheus scraper understands: the SDE owned
counters become ``counter`` samples, poll gauges become ``gauge``
samples, histograms become the ``_bucket``/``_sum``/``_count`` triple.
``PARSEC::COMM::BYTES_SENT`` exposes as ``parsec_comm_bytes_sent``.

``parse_exposition`` is the line-format validator used by the test
suite and by tools that round-trip the output — intentionally strict on
the grammar (names, label blocks, float values) so a malformed render
fails loudly in CI rather than silently at scrape time.

``fleet_to_prometheus`` renders an aggregator-server fleet snapshot
(per-rank last values) so ``tools/aggregator_server.py`` can serve a
real ``GET /metrics`` endpoint for a running job.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, Optional, Tuple

__all__ = ["sanitize_name", "render", "parse_exposition",
           "fleet_to_prometheus"]

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r"\s+(?P<value>[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|Inf|NaN))"
    r"(?:\s+[0-9]+)?$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def sanitize_name(name: str) -> str:
    """``PARSEC::COMM::BYTES_SENT`` -> ``parsec_comm_bytes_sent``."""
    out = re.sub(r"[^a-zA-Z0-9_]", "_", name.replace("::", "_")).lower()
    out = re.sub(r"_+", "_", out).strip("_")
    if not out or out[0].isdigit():
        out = "m_" + out
    return out


def _fmt_value(v: Any) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_str(labels: Optional[Dict[str, str]],
                extra: Optional[Dict[str, str]] = None) -> str:
    merged: Dict[str, str] = {}
    if labels:
        merged.update(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(merged.items()))
    return "{" + body + "}"


def render(metrics: Any, labels: Optional[Dict[str, str]] = None,
           extra_sde: Any = None) -> str:
    """Text exposition of a MetricsRegistry (counters, gauges,
    histograms). ``labels`` are attached to every sample (e.g.
    ``{"rank": "3"}``). ``extra_sde`` merges a second SDE registry —
    e.g. the process-global one carrying PARSEC::MEMPOOL::* and
    contextless user counters — with the registry's own names winning
    on collision."""
    counters, gauges = metrics.sde.snapshot_typed()
    if extra_sde is not None:
        xc, xg = extra_sde.snapshot_typed()
        counters = {**xc, **counters}
        gauges = {**xg, **gauges}
    # a name must expose as exactly ONE kind: duplicate metric names
    # with conflicting # TYPE lines make Prometheus reject the whole
    # exposition. Cross-kind collisions (same name owned in one
    # registry, polled in another) resolve to the counter.
    gauges = {k: v for k, v in gauges.items() if k not in counters}
    lines = []
    for name in sorted(counters):
        m = sanitize_name(name)
        lines.append(f"# HELP {m} {name}")
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m}{_labels_str(labels)} {_fmt_value(counters[name])}")
    for name in sorted(gauges):
        m = sanitize_name(name)
        lines.append(f"# HELP {m} {name}")
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{_labels_str(labels)} {_fmt_value(gauges[name])}")
    for name, hist in sorted(metrics.histograms().items()):
        m = sanitize_name(name)
        snap = hist.snapshot()
        lines.append(f"# HELP {m} {name}")
        lines.append(f"# TYPE {m} histogram")
        for le, cum in snap["buckets"]:
            le_s = "+Inf" if math.isinf(le) else _fmt_value(le)
            lines.append(
                f"{m}_bucket{_labels_str(labels, {'le': le_s})} {cum}")
        lines.append(f"{m}_sum{_labels_str(labels)} {_fmt_value(snap['sum'])}")
        lines.append(f"{m}_count{_labels_str(labels)} {snap['count']}")
    return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Strict line-format check. Returns {(metric, labels): value};
    raises ValueError on any malformed line."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: malformed comment {line!r}")
            if not _NAME_OK.match(parts[2]):
                raise ValueError(f"line {lineno}: bad metric name {parts[2]!r}")
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        labels: Tuple[Tuple[str, str], ...] = ()
        lbl = m.group("labels")
        if lbl:
            body = lbl[1:-1].rstrip(",")
            if body:
                found = _LABEL.findall(body)
                rebuilt = ",".join(f'{k}="{v}"' for k, v in found)
                if rebuilt != body:
                    raise ValueError(
                        f"line {lineno}: malformed labels {lbl!r}")
                labels = tuple(found)
        v = m.group("value")
        out[(m.group("name"), labels)] = float(
            v.replace("Inf", "inf").replace("NaN", "nan"))
    return out


def fleet_to_prometheus(fleet: Dict[str, Any]) -> str:
    """Render an AggregatorServer.fleet() snapshot: each counter's last
    value per rank as a gauge sample labeled ``rank="<r>"``."""
    lines = []
    for name, agg in sorted(fleet.get("counters", {}).items()):
        m = sanitize_name(name)
        lines.append(f"# HELP {m} {name}")
        lines.append(f"# TYPE {m} gauge")
        for rank, cell in sorted(agg.get("ranks", {}).items()):
            lines.append(
                f'{m}{{rank="{rank}"}} {_fmt_value(cell.get("last"))}')
    lines.append("# HELP parsec_aggregator_pushes_total pushes received")
    lines.append("# TYPE parsec_aggregator_pushes_total counter")
    lines.append(f"parsec_aggregator_pushes_total {fleet.get('nb_pushes', 0)}")
    return "\n".join(lines) + "\n"
