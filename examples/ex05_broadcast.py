"""Ex05: broadcast — one producer, a range of consumers.

Teaches: range fan-out in an output dep (``-> A TaskRecv( 0 .. NB )``):
one task's output becomes the input of many tasks in a single dep line.
Across ranks this is what triggers the dynamic bcast topologies
(star/chain/binomial, ref: examples/Ex05_Broadcast.jdf;
parsec/remote_dep.c:272-358).
"""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu
from parsec_tpu.collections import LocalArrayCollection
from parsec_tpu.dsl import ptg

BCAST_JDF = """
mydata [ type="collection" ]
NB     [ type="int" ]

TaskSend(k)

k = 0 .. 0

: mydata( 0 )

RW  A <- mydata( 0 )
      -> A TaskRecv( 0 .. NB )

BODY
{
    A[...] = 42
    print("send 42")
}
END

TaskRecv(k)

k = 0 .. NB

: mydata( k )

READ A <- A TaskSend( 0 )

BODY
{
    print(f"recv {int(A.ravel()[0])} at {k}")
}
END
"""


def main(NB: int = 7) -> int:
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        mydata = LocalArrayCollection(np.zeros((NB + 1, 1), dtype=np.int64),
                                      NB + 1)
        tp = ptg.compile_jdf(BCAST_JDF, name="bcast").new(mydata=mydata, NB=NB)
        ctx.add_taskpool(tp)
        ctx.wait()
        assert tp.nb_local_tasks == NB + 2
    finally:
        ctx.fini()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
