"""Run every tutorial example end to end (the reference builds and runs
examples/Ex00-Ex07 as part of its test tree; ref: examples/CMakeLists.txt).
"""
import importlib

import pytest


@pytest.mark.parametrize("mod", [
    "examples.ex00_start_stop",
    "examples.ex01_hello_world",
    "examples.ex02_chain",
    "examples.ex03_chain_multirank",
    "examples.ex04_chain_data",
    "examples.ex05_broadcast",
    "examples.ex06_raw",
    "examples.ex07_raw_ctl",
    "examples.ex08_dposv_checkpoint",
    "examples.ex09_capture",
    "examples.ex10_dposv_multiprocess",
    "examples.ex11_wave_distributed",
    "examples.ex12_turbo_dispatch",
    "examples.ex13_elastic_shrink",
    "examples.ex14_link_flap",
])
def test_example_runs(mod):
    m = importlib.import_module(mod)
    assert m.main() == 0
