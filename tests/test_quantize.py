"""Quantized wire codecs + reduced-precision collectives (ISSUE 14).

Three layers under test:

- the error-feedback machinery (parallel/mesh.py): an iterative
  all-reduce whose contributions fall below the quantization quantum
  LOSES them forever without feedback (100% drift) and converges with
  it — the EQuARX recipe, the acceptance-gate differential;
- the reduced-precision collective lane (``wave_reduce_dtype`` on
  dsl/ptg/wave_dist._CollectiveLane): contributions quantize at the
  boundary through the SAME codec the wire uses, full-precision when
  the knob is unset (bit-for-bit differential against the plain lane);
- per-flow eligibility (comm/remote_dep.py): only float tile payloads
  quantize; pools that declare ``wire_lossless`` (checkpoint-reshard
  redistribution) never do.
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic, redistribute
from parsec_tpu.comm import wire
from parsec_tpu.comm.remote_dep import RemoteDepEngine
from parsec_tpu.dsl import ptg
from parsec_tpu.ops import dpotrf_taskpool, make_spd
from parsec_tpu.parallel.mesh import (ErrorFeedback, reduced_precision_sum,
                                      two_level_allreduce)
from parsec_tpu.utils.params import params

from test_comm_multirank import spmd
from test_wave_dist import _gather_owned


# --------------------------------------------------------------------- #
# error feedback (the EQuARX differential)                              #
# --------------------------------------------------------------------- #
def test_error_feedback_converges_iterative_allreduce():
    """Contributions carry one large element (pinning the int8 block
    scale) plus many sub-quantum small ones. Without error feedback
    the small signal quantizes to zero EVERY round — the accumulated
    reduction diverges from the truth by 100% of it, forever. With
    feedback the residual accumulates until it crosses the quantum and
    is emitted: the total converges to within one quantum."""
    big = np.zeros(wire.QUANT_BLOCK, np.float32)
    big[0] = 100.0                       # scale = 100/127 per block
    small = np.full(wire.QUANT_BLOCK, 0.01, np.float32)
    small[0] = 0.0                       # 0.01 << quantum (~0.39)
    contrib = big + small
    K = 500
    ef = ErrorFeedback()
    tot_no = np.zeros_like(contrib)
    tot_ef = np.zeros_like(contrib)
    for _ in range(K):
        tot_no += wire.qdq_array(contrib, "qint8")
        tot_ef += ef.compensate("grad", contrib, "qint8",
                                wire.qdq_array)
    true = contrib * K
    rel_no = float(np.abs(tot_no[1:] - true[1:]).max() / true[1])
    rel_ef = float(np.abs(tot_ef[1:] - true[1:]).max() / true[1])
    assert rel_no > 0.99, rel_no     # diverged: the signal is GONE
    assert rel_ef < 0.1, rel_ef      # converged: within one quantum
    assert ef.keys() == ["grad"]


def test_error_feedback_shape_change_starts_fresh():
    ef = ErrorFeedback()
    a = np.full(8, 0.3, np.float32)
    ef.compensate("k", a, "qbf16", wire.qdq_array)
    # a different shape under the same key must not fold the stale
    # residual (it names a different buffer now)
    b = np.full(16, 0.3, np.float32)
    out = ef.compensate("k", b, "qbf16", wire.qdq_array)
    np.testing.assert_array_equal(out, wire.qdq_array(b, "qbf16"))
    ef.reset("k")
    assert ef.keys() == []


def test_reduced_precision_sum_unset_is_exact():
    rng = np.random.RandomState(0)
    xs = [rng.randn(257).astype(np.float64) for _ in range(5)]
    exact = np.zeros_like(xs[0])
    for x in xs:
        exact = exact + x
    out = reduced_precision_sum(xs, None)
    np.testing.assert_array_equal(out, exact)   # bit-for-bit
    np.testing.assert_array_equal(reduced_precision_sum(xs, ""), exact)


def test_reduced_precision_sum_quantizes_each_contribution():
    rng = np.random.RandomState(1)
    xs = [rng.randn(1000).astype(np.float32) for _ in range(3)]
    out = reduced_precision_sum(xs, "bf16")
    manual = sum(wire.qdq_array(x, "qbf16") for x in xs)
    np.testing.assert_array_equal(out, manual)
    exact = sum(xs)
    rel = np.abs(out - exact).max() / np.abs(exact).max()
    assert 0 < rel < 0.02, rel


def test_two_level_allreduce_boundary_quantization():
    """Level 1 (intra-group) stays full precision; only each group's
    boundary partial quantizes — the two-level win: one quantization
    per GROUP, not per contributor."""
    rng = np.random.RandomState(2)
    xs = [rng.randn(512).astype(np.float32) for _ in range(4)]
    exact = (xs[0] + xs[1]) + (xs[2] + xs[3])
    lossless = two_level_allreduce(xs, 2, None)
    np.testing.assert_array_equal(lossless, exact)
    q = two_level_allreduce(xs, 2, "int8")
    manual = (wire.qdq_array(xs[0] + xs[1], "qint8")
              + wire.qdq_array(xs[2] + xs[3], "qint8"))
    np.testing.assert_array_equal(q, manual)
    # error feedback across repeated calls of the same logical buffer
    ef = ErrorFeedback()
    t1 = two_level_allreduce(xs, 2, "int8", feedback=ef, key="g")
    np.testing.assert_array_equal(t1, q)   # first round: no residual yet
    assert sorted(ef.keys()) == [("g", 0), ("g", 1)]
    t2 = two_level_allreduce(xs, 2, "int8", feedback=ef, key="g")
    assert not np.array_equal(t2, t1)      # residual folded in


# --------------------------------------------------------------------- #
# the collective lane under wave_reduce_dtype                           #
# --------------------------------------------------------------------- #
def _single_rank_lane(reduce_dtype):
    import threading
    from parsec_tpu.dsl.ptg.wave_dist import _CollectiveLane
    rdv = ({}, {}, threading.Condition())
    return _CollectiveLane("inproc", 1, 0, rendezvous=rdv,
                           reduce_dtype=reduce_dtype)


def test_lane_quantizes_contribution_at_boundary():
    lane = _single_rank_lane("int8")
    x = np.random.RandomState(3).randn(4, 8, 8).astype(np.float32)
    out = np.asarray(lane.reduce(("p", 1, 0, 0), x))
    np.testing.assert_array_equal(out, wire.qdq_array(x, "qint8"))
    assert lane.quantized_reduces == 1


def test_lane_rejects_unknown_reduce_dtype():
    """A typo'd wave_reduce_dtype must fail LOUDLY (at lane/runner
    setup), never silently disable the lane under mode=auto."""
    with pytest.raises(ValueError):
        _single_rank_lane("fp16")


def test_lane_unset_keeps_full_precision():
    lane = _single_rank_lane("")
    assert lane._qcodec is None
    x = np.random.RandomState(4).randn(2, 8).astype(np.float32)
    out = np.asarray(lane.reduce(("p", 1, 0, 1), x))
    np.testing.assert_array_equal(out, x)   # bit-for-bit
    assert lane.quantized_reduces == 0


def test_lane_error_feedback_needs_stable_key():
    """Without ``fb_key`` the lane quantizes WITHOUT feedback (wave
    broadcast steps carry different tiles every wave — folding one
    wave's residual into the next would corrupt unrelated data); with
    a stable key the residual carries into the next contribution."""
    lane = _single_rank_lane("int8")
    big = np.zeros((1, wire.QUANT_BLOCK), np.float32)
    big[0, 0] = 100.0
    c = big.copy()
    c[0, 1] = 0.01    # sub-quantum
    out1 = np.asarray(lane.reduce(("p", 1, 0, 0), c))
    out2 = np.asarray(lane.reduce(("p", 1, 1, 0), c))
    np.testing.assert_array_equal(out1, out2)   # no feedback: identical
    tot = np.zeros_like(c)
    for w in range(60):
        tot += np.asarray(lane.reduce(("q", 1, w, 0), c, fb_key="buf"))
    assert tot[0, 1] > 0, "feedback never emitted the accumulated signal"


def test_lane_two_level_reduction_engages_and_differs_from_flat():
    """``xfer_collective_redist`` (ISSUE 19): deposits stay FULL
    precision and the issuer reduces hierarchically — full-precision
    partial sums inside each ``xfer_group_size`` group, ONE jit-native
    qdq per group at the boundary. Crafted input where flat
    per-contribution quantize and two-level round DIFFERENTLY:
    256 + 1 accumulates exactly inside a group, but 257 is not a bf16
    value, so the boundary hop rounds each partial to 256 (total 512)
    while the flat path delivers 514. Every member picks up the
    bit-identical replicated result; TWO_LEVEL_REDUCES accounting
    fires once per member and the per-contribution counter stays 0."""
    pytest.importorskip("jax")
    import threading
    from parsec_tpu.dsl.ptg.wave_dist import _CollectiveLane
    n = 4
    contribs = [np.full((2, 8), v, np.float32)
                for v in (256.0, 1.0, 256.0, 1.0)]
    params.set_cmdline("xfer_collective_redist", "1")
    params.set_cmdline("xfer_group_size", "2")
    try:
        rdv = ({}, {}, threading.Condition())
        efb = ErrorFeedback()
        stats = [{"two_level_reduces": 0} for _ in range(n)]
        lanes = [_CollectiveLane("inproc", n, r, rendezvous=rdv,
                                 reduce_dtype="bf16",
                                 shared_feedback=efb, stats=stats[r])
                 for r in range(n)]
        outs = [None] * n
        errs = []

        def run(r):
            try:
                outs[r] = np.asarray(
                    lanes[r].reduce(("p", 1, 0, 0), contribs[r]))
            except BaseException as e:
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs, errs
    finally:
        params.unset_cmdline("xfer_collective_redist")
        params.unset_cmdline("xfer_group_size")
    exp = two_level_allreduce(contribs, 2, "bf16")
    flat = reduced_precision_sum(contribs, "bf16")
    assert not np.array_equal(exp, flat), "input must discriminate"
    for r in range(n):
        np.testing.assert_array_equal(outs[r], exp)
    assert all(ln.two_level_reduces == 1 for ln in lanes)
    assert all(ln.quantized_reduces == 0 for ln in lanes)
    assert all(s["two_level_reduces"] == 1 for s in stats)


def test_lane_two_level_group_size_gates_engagement():
    """len(members) must EXCEED the group size for the hierarchy to
    buy anything — at group_size >= member count the lane keeps the
    flat per-contribution quantize (and its counter)."""
    pytest.importorskip("jax")
    import threading
    from parsec_tpu.dsl.ptg.wave_dist import _CollectiveLane
    params.set_cmdline("xfer_collective_redist", "1")
    params.set_cmdline("xfer_group_size", "4")
    try:
        rdv = ({}, {}, threading.Condition())
        lane = _CollectiveLane("inproc", 1, 0, rendezvous=rdv,
                               reduce_dtype="bf16")
        x = np.full((2, 4), 256.0, np.float32) + 1.0
        out = np.asarray(lane.reduce(("p", 1, 0, 0), x))
        np.testing.assert_array_equal(out, wire.qdq_array(x, "qbf16"))
        assert lane.two_level_reduces == 0
        assert lane.quantized_reduces == 1
    finally:
        params.unset_cmdline("xfer_collective_redist")
        params.unset_cmdline("xfer_group_size")


def test_wave_reduce_dtype_dpotrf_within_bound(nb_ranks=4):
    """End to end: the 4-rank row-cyclic dist-wave dpotrf whose panel
    broadcasts ride the compiled collective lane, with the lane
    quantizing at bf16 — the factor must stay within a declared
    residual bound of numpy cholesky (not bit-exact: the wire is lossy
    by contract), with quantized reduces really counted. The unset
    knob keeps today's bit-exact lane (covered by
    test_dist_wave_collective_lane_dpotrf_matches)."""
    n, nb = 256, 32
    M = make_spd(n, dtype=np.float64)

    def rank_fn(r, f):
        ce = f.engine(r)
        coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                 P=nb_ranks, Q=1,
                                 nodes=nb_ranks, rank=r)
        coll.name = "descA"
        coll.from_numpy(M.copy())
        tp = dpotrf_taskpool(coll, rank=r, nb_ranks=nb_ranks)
        w = ptg.wave(tp, comm=ce)
        w.run()
        return w.stats, _gather_owned(coll, rank=r)

    params.set_cmdline("wave_dist_collective", "on")
    params.set_cmdline("wave_reduce_dtype", "bf16")
    try:
        results, _ = spmd(nb_ranks, rank_fn, timeout=180)
    finally:
        params.unset_cmdline("wave_dist_collective")
        params.unset_cmdline("wave_reduce_dtype")
    L = np.zeros((n, n))
    for (_st, owned) in results:
        for (m, k), t in owned.items():
            L[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = t
    L = np.tril(L)
    stats = [st for (st, _o) in results]
    assert all(s["collective_reduce_dtype"] == "qbf16" for s in stats)
    assert sum(s["collective_quantized"] for s in stats) > 0, stats
    ref = np.linalg.cholesky(M)
    resid = np.abs(L - ref).max() / np.abs(ref).max()
    assert resid < 1e-2, resid   # lossy but bounded (measured ~1e-3)


# --------------------------------------------------------------------- #
# per-flow eligibility                                                  #
# --------------------------------------------------------------------- #
class _FakeTp:
    pass


def test_quantize_eligibility_per_flow():
    el = RemoteDepEngine._quantize_eligible
    tp = _FakeTp()
    assert el(tp, np.zeros(4, np.float32))
    assert el(tp, np.zeros(4, np.float64))
    assert not el(tp, np.zeros(4, np.int32))     # non-float: lossless
    assert not el(tp, None)                      # release-only
    lossless_tp = _FakeTp()
    lossless_tp.wire_lossless = True
    assert not el(lossless_tp, np.zeros(4, np.float64))


def test_redistribute_pool_is_wire_lossless(ctx):
    """Checkpoint-reshard restores ride redistribute(); its pool must
    mark itself lossless so reshard traffic NEVER quantizes whatever
    the knobs say — golden reshards stay bit-identical."""
    rng = np.random.RandomState(5)
    src = rng.rand(8, 8)
    Y = TwoDimBlockCyclic(8, 8, 4, 4,
                          dtype=np.float64).from_numpy(src)
    T = TwoDimBlockCyclic(8, 8, 2, 2,
                          dtype=np.float64).from_numpy(np.zeros((8, 8)))
    tp = redistribute(Y, T, 8, 8, context=ctx)
    assert getattr(tp, "wire_lossless", False) is True
    np.testing.assert_array_equal(T.to_numpy(), src)


def test_qdq_matches_wire_delivery_layout():
    """qdq_array is EXACTLY what a quantized wire transfer delivers:
    same codec functions, same block layout — asserted here so the
    lane and the wire can never round differently."""
    rng = np.random.RandomState(6)
    for dt, fmt in ((np.float64, "d"), (np.float32, "f")):
        arr = (rng.randn(1030) * 3).astype(dt)   # non-multiple of block
        for codec in wire.available_quant_codecs():
            enc = wire.quantize_buffer(
                memoryview(np.ascontiguousarray(arr)).cast("B"),
                fmt, codec)
            raw = wire.dequantize_buffer(enc)
            via_wire = np.frombuffer(raw, dtype=dt)
            np.testing.assert_array_equal(
                via_wire, wire.qdq_array(arr, codec))


def test_native_qdq_bit_parity_with_numpy():
    """The jit-native quantize hop (ISSUE 17): ``qdq_jax`` lowered
    through XLA must deliver BIT-FOR-BIT the values the numpy wire
    codec delivers — every dtype, every shape class (block multiples,
    remainders, multi-dim), every magnitude, all-zero blocks included
    (the 1/scale guard).  Without this, a ``native=True`` reduction
    would round differently from the wire and the lane/wire identity
    contract of ISSUE 14 would silently break."""
    pytest.importorskip("jax")
    from parsec_tpu.parallel.mesh import _qdq_native
    rng = np.random.RandomState(7)
    for codec in wire.available_quant_codecs():
        for dt in (np.float32, np.float64):
            for shape in ((7,), (512,), (513,), (64, 33), (3, 5, 7)):
                for scale in (1e-6, 1.0, 1e4):
                    x = (rng.randn(*shape) * scale).astype(dt)
                    a = wire.qdq_array(x, codec)
                    b = _qdq_native(x, codec)
                    assert a.dtype == b.dtype and a.shape == b.shape
                    np.testing.assert_array_equal(a, b)
        z = np.zeros(600, np.float32)   # zero-scale blocks
        np.testing.assert_array_equal(wire.qdq_array(z, codec),
                                      _qdq_native(z, codec))


def test_native_two_level_allreduce_bit_parity():
    """two_level_allreduce's DEFAULT boundary quantize is now the
    XLA-lowered native hop (ISSUE 19 satellite: no host-side numpy
    quantize left on the default path) — it must stay bit-identical to
    the eager wire codec (``native=False``), with and without error
    feedback across iterations (the residual carry must see the exact
    same quantized values, or feedback states diverge)."""
    pytest.importorskip("jax")
    rng = np.random.RandomState(8)
    shards = [rng.randn(300).astype(np.float32) for _ in range(8)]
    for rd in wire.available_quant_codecs():
        np.testing.assert_array_equal(
            two_level_allreduce(shards, 4, rd, native=False),
            two_level_allreduce(shards, 4, rd))
        fb_np, fb_jx = ErrorFeedback(), ErrorFeedback()
        for _ in range(3):
            r_np = two_level_allreduce(shards, 4, rd, feedback=fb_np,
                                       key="k", native=False)
            r_jx = two_level_allreduce(shards, 4, rd,
                                       feedback=fb_jx, key="k")
            np.testing.assert_array_equal(r_np, r_jx)
    # no codec: the native default must not disturb the exact sum
    np.testing.assert_array_equal(
        two_level_allreduce(shards, 4, None, native=False),
        two_level_allreduce(shards, 4, None))
