"""Lowerability pass: partition an instantiated PTG DAG into compilable
stages vs interpreted residue (ISSUE 12 tentpole, part 1).

Reuses the verdicts the static verifier already computes — the
PTG1xx dataflow checks (:mod:`..analysis.ptg_check`) and the BDY2xx
trace-safety predicates (:mod:`..analysis.body_check`) — plus the
capture planner's symbolic DAG enumeration (``dsl/ptg/capture.plan``,
the importable core behind ``tools/dagenum.py``).  A task CLASS is
lowerable when its accelerator body is provably traceable and
deterministic and its dependency edges carry no release-time datatype
conversions; a task INSTANCE additionally needs straight-line per-tile
dataflow (no ranged data inputs) and memory writebacks that land on
tiles this rank owns.  Everything else is residue and keeps the
interpreted per-task/batched dispatch (PR 5/7) — semantics are never
at risk, only the dispatch amortization.

Stage grouping: local compilable instances are merged across
consecutive dependence levels into one stage as long as no path from a
stage member leaves the stage and re-enters it (the condensed
stage/residue graph must stay acyclic — a residue or remote task both
consuming from and feeding a stage would deadlock it).  The ``taint``
walk below tracks exactly that: non-member instances transitively
downstream of the current stage; a candidate with a tainted
predecessor closes the stage.  ``wavefront=True`` instead emits one
stage per (dependence level, task class) — the grouping the
mesh-sharded variant (stagec/sharded.py) can spread across chips.

Reason codes: BDY2xx / PTG1xx findings are surfaced verbatim; stagec
adds STG3xx for conditions that only matter to the stage compiler:

- ``STG300`` no-accelerator-body: every BODY is cpu/recursive — the
  host interpreter owns the class.
- ``STG302`` edge-reshape: a dependency carries a ``[type*=...]``
  property — the interpreted release path converts datatypes per edge,
  which a fused trace does not reproduce.
- ``STG303`` masked-writeback: a memory out-dep declares a region-
  masked writeback type; the fused scatter writes whole tiles.
- ``STG304`` ranged-data-input: a data flow's in-dep expands a range
  (multi-producer binding is arrival-order-defined — not traceable).
- ``STG305`` new-without-shape: a NEW input has no evaluable
  ``[shape=...]`` property, so the trace cannot allocate it.
- ``STG306`` operator-excluded: the class is named in the
  ``stage_compile_exclude`` MCA param — a debugging / measurement knob
  (the residue-heavy bench leg rides it).

ISSUE 13 relaxation: a host-only class whose body is a NO-OP (``pass``
— the reader/broadcast classes dtrsm places on tile owners) is
lowerable after all: inside a fused trace the class contributes
nothing but dataflow (its flow values forward untouched), which is
exactly what the interpreted cpu hook does for a ``pass`` body.  Only
pure forwarders qualify (every non-CTL flow READ): a no-op body behind
a WRITE flow still version-bumps through the interpreted path and is
left alone.

The pass also pre-plans the **residue schedule** (ISSUE 13): residue
instances with an accelerator body are grouped per (dependence level,
class) at plan time, so the runtime can hand each group to the device
batching pipeline as one burst with zero per-task scheduler
round-trips (see stagec/runtime.StageCompiler.on_residue_ready).
Level-1 (startup) residue keeps the chunked startup hand-off — it is
already scheduled as one burst.
"""
from __future__ import annotations

import ast as pyast
import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

from ..analysis import body_check, ptg_check
from ..dsl.ptg.ast import JDFFile, RangeExpr

#: BDY findings that disqualify a class from stage lowering (203 is
#: included: nondeterminism breaks the bit-exact compiled-vs-interpreted
#: contract the runtime integration gates on)
_BDY_DISQUALIFYING = ("BDY200", "BDY201", "BDY202", "BDY203")


@dataclasses.dataclass
class ClassVerdict:
    """Per-task-class lowerability: ``ok`` or the finding that blocks.
    ``note`` annotates an ok verdict (e.g. the no-op forwarder
    relaxation) without changing it."""
    name: str
    ok: bool
    code: Optional[str] = None
    reason: Optional[str] = None
    note: Optional[str] = None

    def __str__(self) -> str:
        if self.ok:
            return (f"{self.name}: compilable"
                    + (f" ({self.note})" if self.note else ""))
        return f"{self.name}: fallback [{self.code}] {self.reason}"


class Stage:
    """One compilable stage: an ordered set of local task instances
    lowered into a single fused jitted callable."""

    __slots__ = ("index", "members", "member_keys", "level_lo", "level_hi")

    def __init__(self, index: int) -> None:
        self.index = index
        self.members: List[Any] = []       # capture._Instance, topo order
        self.member_keys: Set[Tuple] = set()
        self.level_lo = self.level_hi = 0

    def add(self, inst, level: int) -> None:
        if not self.members:
            self.level_lo = level
        self.members.append(inst)
        self.member_keys.add(inst.key)
        self.level_hi = level

    @property
    def n_tasks(self) -> int:
        return len(self.members)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"<Stage#{self.index} {self.n_tasks} tasks "
                f"levels {self.level_lo}..{self.level_hi}>")


class StagePlan:
    """The lowerability pass's output for one instantiated taskpool."""

    __slots__ = ("order", "stages", "member_stage", "verdicts",
                 "inst_by_key", "n_local", "n_residue", "prepared",
                 "levels", "residue_groups", "residue_groups_host",
                 "mem_writers", "local_keys", "startup_goal0",
                 "startup_mem_puts", "xwaves", "xwave_report")

    def __init__(self, order, stages, member_stage, verdicts,
                 n_local: int, n_residue: int) -> None:
        #: [(stage, StageLayout, priority)] — filled by the runtime's
        #: cached prepare step (stagec/runtime.try_install)
        self.prepared: List[Tuple] = []
        self.order = order                  # global topo instance order
        self.stages: List[Stage] = stages
        #: (class_name, locals) -> stage index
        self.member_stage: Dict[Tuple, int] = member_stage
        self.verdicts: Dict[str, ClassVerdict] = verdicts
        self.inst_by_key = {i.key: i for i in order}
        self.n_local = n_local
        self.n_residue = n_residue
        #: instance key -> dependence level (1 = no task preds)
        self.levels: Dict[Tuple, int] = {}
        #: compiled residue schedule (ISSUE 13): pre-planned
        #: per-(level, class) groups of LOCAL residue instance keys at
        #: levels >= 2 — the runtime buffers each group's ready tasks
        #: and hands the complete group to the device batching pipeline
        #: as one burst (zero per-task scheduler round-trips)
        self.residue_groups: List[List[Tuple]] = []
        #: host-bodied residue groups (ISSUE 20b): same per-(level,
        #: class) pre-planning for classes the HOST interpreter owns —
        #: the runtime schedules each complete group as one pre-planned
        #: burst instead of a per-task activate/schedule round-trip
        self.residue_groups_host: List[List[Tuple]] = []
        #: cross-rank SPMD waves (ISSUE 20, stagec/xrank.py): filled by
        #: plan_xwaves when stage_compile_xrank is on and nb_ranks > 1
        self.xwaves: List[Any] = []
        #: [(level, class, text)] — per-(level, class) cross-rank
        #: eligibility verdicts (the parsec_lint --lower-report column)
        self.xwave_report: List[Tuple] = []
        #: (collection name, coords) -> ordered instance keys with a
        #: memory out-dep landing on that tile, over the FULL (all-rank)
        #: instance order — the chain planner's dataflow proof and the
        #: prestager's final-value check both read it
        self.mem_writers: Dict[Tuple, List[Tuple]] = {}
        #: instance keys local to this rank (plan_stages' rank_of walk)
        self.local_keys: Set[Tuple] = set()
        #: plan-cached startup enumeration (ISSUE 13): the goal-0 LOCAL
        #: residue instances and the foreign mem-put expectation, so a
        #: stagec _startup skips the per-instance iteration-space walk
        #: (a pure function of the plan identity — filled by
        #: stagec/runtime.prepared_plan)
        self.startup_goal0: List[Tuple] = []
        self.startup_mem_puts = 0

    @property
    def n_staged(self) -> int:
        return sum(s.n_tasks for s in self.stages)


def _finding_class(f) -> str:
    """The task class a body_check finding names (its messages lead
    with the class name: '<cls> BODY[dev]: ...' / '<cls>: ...')."""
    head = f.message.split(None, 1)[0] if f.message else ""
    return head.rstrip(":")


def _class_edge_reshape(tc) -> bool:
    for f in tc.flows:
        for d in f.deps:
            for k in ("type", "type_remote"):
                if k in d.properties:
                    return True
    return False


def _class_masked_writeback(tc) -> bool:
    for f in tc.flows:
        for d in f.deps_out():
            targets = [x for x in (d.target, d.alt_target) if x is not None]
            if not any(x.kind == "memory" for x in targets):
                continue
            nm = d.properties.get("type_data") or d.properties.get("type")
            if nm is not None and nm != "full":
                return True
    return False


def _class_ranged_data_input(tc) -> bool:
    for f in tc.flows:
        if f.is_ctl:
            continue
        for d in f.deps_in():
            for t in (d.target, d.alt_target):
                if t is None or t.kind != "task":
                    continue
                if any(isinstance(a, RangeExpr) for a in t.args):
                    return True
    return False


def _noop_forwarder(tc) -> bool:
    """ISSUE 13 STG300 relaxation: a host-only class whose body is a
    no-op (``pass`` / docstring only) and whose non-CTL flows are all
    READ forwards its inputs untouched — inside a fused trace it is
    pure dataflow, identical to what the interpreted cpu hook does."""
    if any(f.access != "READ" for f in tc.flows if not f.is_ctl):
        return False
    body = tc.bodies[0]
    try:
        tree = pyast.parse(body.code)
    except SyntaxError:
        return False
    return all(isinstance(node, pyast.Pass)
               or (isinstance(node, pyast.Expr)
                   and isinstance(node.value, pyast.Constant))
               for node in tree.body)


def _excluded_classes() -> Tuple[str, ...]:
    """Operator-excluded classes (``stage_compile_exclude``)."""
    from ..utils.params import params
    raw = str(params.get_or("stage_compile_exclude", "string", "") or "")
    return tuple(sorted(s.strip() for s in raw.split(",") if s.strip()))


class IdKey:
    """Hashable identity wrapper: keys a cache by object IDENTITY while
    holding a strong reference, so a recycled id can never alias a dead
    object's entries (JDFFile is an eq-dataclass — unhashable itself).
    Shared by the verdict memo below and the spec token in lower.py."""

    __slots__ = ("obj",)

    def __init__(self, obj: Any) -> None:
        self.obj = obj

    def __hash__(self) -> int:
        return id(self.obj)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, IdKey) and other.obj is self.obj


#: verdict memo per (parsed-spec identity, exclusion set) — verdicts
#: are a pure function of the AST plus the ``stage_compile_exclude``
#: knob (a knob change must never hit a stale verdict); re-deriving
#: them per taskpool would tax every repeat run's startup.  Bounded: a
#: long-lived process parsing specs dynamically must not pin every
#: dead AST forever.
_verdict_memo: Dict[Tuple, Dict[str, ClassVerdict]] = {}
_VERDICT_MEMO_MAX = 64


def class_verdicts(jdf: JDFFile) -> Dict[str, ClassVerdict]:
    """Per-task-class lowerability over a parsed JDF, reusing the
    analysis/ verdicts (PR 8): PTG1xx dataflow errors poison the whole
    spec (an unsound graph is not worth fusing), BDY2xx trace-safety
    findings disqualify their class, and the STG3xx structural checks
    cover what only the stage compiler cares about."""
    excluded = _excluded_classes()
    memo_key = (IdKey(jdf), excluded)
    memo = _verdict_memo.get(memo_key)
    if memo is not None:
        return memo
    out: Dict[str, ClassVerdict] = {}
    ptg_findings = [f for f in ptg_check.verify_jdf(jdf)
                    if f.severity == "error"]
    body_findings = body_check.check_jdf_bodies(jdf)
    by_class: Dict[str, Any] = {}
    for f in body_findings:
        if f.code in _BDY_DISQUALIFYING:
            by_class.setdefault(_finding_class(f), f)
    for tc in jdf.task_classes:
        if ptg_findings:
            f = ptg_findings[0]
            out[tc.name] = ClassVerdict(tc.name, False, f.code, f.message)
            continue
        if tc.name in excluded:
            out[tc.name] = ClassVerdict(
                tc.name, False, "STG306",
                f"{tc.name}: excluded by the stage_compile_exclude knob")
            continue
        bf = by_class.get(tc.name)
        if bf is not None:
            out[tc.name] = ClassVerdict(tc.name, False, bf.code, bf.message)
            continue
        forwarder = False
        if not any(b.device_type not in ("cpu", "recursive")
                   for b in tc.bodies):
            if not _noop_forwarder(tc):
                out[tc.name] = ClassVerdict(
                    tc.name, False, "STG300",
                    f"{tc.name}: no accelerator BODY — the host "
                    f"interpreter owns this class")
                continue
            # no-op forwarder (reader/broadcast class): pure dataflow
            # inside a fused trace — lowerable despite the cpu BODY
            forwarder = True
        if _class_edge_reshape(tc):
            out[tc.name] = ClassVerdict(
                tc.name, False, "STG302",
                f"{tc.name}: a dependency declares a [type*=...] "
                f"datatype conversion — release-time reshapes are not "
                f"reproduced by a fused trace")
            continue
        if _class_masked_writeback(tc):
            out[tc.name] = ClassVerdict(
                tc.name, False, "STG303",
                f"{tc.name}: a memory out-dep declares a region-masked "
                f"writeback type — the fused scatter writes whole tiles")
            continue
        if _class_ranged_data_input(tc):
            out[tc.name] = ClassVerdict(
                tc.name, False, "STG304",
                f"{tc.name}: a data flow's in-dep expands a range — "
                f"multi-producer bindings are arrival-order-defined")
            continue
        out[tc.name] = ClassVerdict(
            tc.name, True,
            note="no-op forwarder body" if forwarder else None)
    while len(_verdict_memo) >= _VERDICT_MEMO_MAX:
        _verdict_memo.pop(next(iter(_verdict_memo)))
    _verdict_memo[memo_key] = out
    return out


def _instance_compilable(tp, inst, verdict: ClassVerdict,
                         rank: int) -> bool:
    """Instance-level residue checks on top of the class verdict:
    memory writebacks must land on tiles this rank owns (a foreign
    writeback rides the comm engine's mem_writeback protocol, which
    the fused scatter does not speak) and NEW inputs need an evaluable
    shape (STG305)."""
    if not verdict.ok:
        return False
    from ..dsl.ptg.runtime import scratch_shape
    tc_ast = inst.tc.ast
    for i, f in enumerate(tc_ast.flows):
        if f.is_ctl:
            continue
        for d in f.deps_out():
            t = d.resolve(inst.env)
            if t is None or t.kind != "memory":
                continue
            coll = tp.global_env[t.collection]
            if coll.rank_of(*[a(inst.env) for a in t.args]) != rank:
                return False
        for d in f.deps_in():
            t = d.resolve(inst.env)
            if t is not None and t.kind == "new" \
                    and scratch_shape(f, inst.env) is None:
                return False
    return True


def plan_stages(tp, rank: int = 0, max_tasks: int = 256,
                wavefront: bool = False) -> StagePlan:
    """Partition ``tp``'s instantiated DAG into compilable stages plus
    interpreted residue for this rank.  Raises whatever the capture
    planner raises on an unenumerable spec (callers treat that as
    "no stages")."""
    from ..dsl.ptg.capture import plan as _capture_plan
    order = _capture_plan(tp)
    verdicts = class_verdicts(tp.jdf)

    level: Dict[Tuple, int] = {}
    for inst in order:  # topo: preds resolved first
        level[inst.key] = 1 + max((level[p] for p in inst.preds), default=0)

    local = {inst.key for inst in order
             if inst.tc.rank_of_instance(inst.env) == rank}
    ok = {inst.key for inst in order
          if inst.key in local and _instance_compilable(
              tp, inst, verdicts[inst.tc.ast.name], rank)}

    by_level: Dict[int, List[Any]] = {}
    for inst in order:
        by_level.setdefault(level[inst.key], []).append(inst)

    stages: List[Stage] = []
    member_stage: Dict[Tuple, int] = {}

    def close(stage: Optional[Stage]) -> None:
        if stage is not None and stage.members:
            stages.append(stage)

    if wavefront:
        # one stage per (level, class): the grouping the mesh-sharded
        # variant can spread over chips (same-class uniform rows);
        # always condensation-safe — a level is an antichain, so no
        # residue at the same level can sit between two stages
        for lv in sorted(by_level):
            per_class: Dict[str, Stage] = {}
            for inst in by_level[lv]:
                if inst.key not in ok:
                    continue
                st = per_class.get(inst.tc.ast.name)
                if st is None or st.n_tasks >= max_tasks:
                    st = Stage(len(stages))
                    stages.append(st)
                    per_class[inst.tc.ast.name] = st
                st.add(inst, lv)
                member_stage[inst.key] = st.index
    else:
        cur: Optional[Stage] = None
        tainted: Set[Tuple] = set()   # non-members downstream of cur
        for lv in sorted(by_level):
            cands = [i for i in by_level[lv] if i.key in ok]
            others = [i for i in by_level[lv] if i.key not in ok]
            cur_keys = cur.member_keys if cur is not None else set()
            for o in others:
                if any(p in cur_keys or p in tainted for p in o.preds):
                    tainted.add(o.key)
            blocked = any(p in tainted for c in cands for p in c.preds)
            if cur is not None and cands and (
                    blocked or cur.n_tasks + len(cands) > max_tasks):
                close(cur)
                cur, tainted = None, set()
            while len(cands) > max_tasks:   # an antichain splits freely
                st = Stage(len(stages))
                for i in cands[:max_tasks]:
                    st.add(i, lv)
                    member_stage[i.key] = st.index
                close(st)
                cands = cands[max_tasks:]
            if cands:
                if cur is None:
                    cur = Stage(len(stages))
                for i in cands:
                    cur.add(i, lv)
                    member_stage[i.key] = cur.index
        close(cur)

    n_residue = len(local) - len(member_stage)
    plan = StagePlan(order, stages, member_stage, verdicts,
                     n_local=len(local), n_residue=n_residue)
    plan.levels = level
    plan.local_keys = local

    # memory-writeback map over the FULL order (chain proof + prestage
    # final-value checks): tile -> ordered writer instance keys
    for inst in order:
        env = inst.env
        for f in inst.tc.ast.flows:
            if f.is_ctl:
                continue
            for d in f.deps_out():
                t = d.resolve(env)
                if t is not None and t.kind == "memory":
                    coords = tuple(int(a(env)) for a in t.args)
                    plan.mem_writers.setdefault(
                        (t.collection, coords), []).append(inst.key)

    # compiled residue schedule (ISSUE 13): pre-plan per-(level, class)
    # groups of device-bodied local residue at levels >= 2 (level-1
    # residue is startup — already handed off as one chunked burst).
    # Groups of one save nothing; they keep the per-task path.
    device_cls = {tc.ast.name for tc in tp.task_classes
                  if any(b.device_type not in ("cpu", "recursive")
                         for b in tc.ast.bodies)}
    # host-bodied residue joins the same pre-planning (ISSUE 20b): a
    # complete (level, class) group of HOST tasks schedules as one
    # pre-planned burst instead of per-task scheduler round-trips
    per_group: Dict[Tuple, List[Tuple]] = {}
    per_group_host: Dict[Tuple, List[Tuple]] = {}
    for inst in order:
        k = inst.key
        if k not in local or k in member_stage or level[k] < 2:
            continue
        tgt = per_group if k[0] in device_cls else per_group_host
        tgt.setdefault((level[k], k[0]), []).append(k)
    for gk in sorted(per_group):
        keys = per_group[gk]
        if len(keys) >= 2:
            plan.residue_groups.append(keys)
    for gk in sorted(per_group_host):
        keys = per_group_host[gk]
        if len(keys) >= 2:
            plan.residue_groups_host.append(keys)
    return plan


def lower_report(jdf: JDFFile) -> List[str]:
    """Human-readable per-task-class lowerability report (the
    ``parsec_lint --lower-report`` payload): compilable / fallback plus
    the BDY2xx/PTG1xx/STG3xx reason, so a spec author sees why a class
    won't fuse before the first run."""
    verdicts = class_verdicts(jdf)
    lines = [f"{jdf.name}: stage-compile lowerability"]
    for tc in jdf.task_classes:
        lines.append(f"  {verdicts[tc.name]}")
    n_ok = sum(1 for v in verdicts.values() if v.ok)
    lines.append(f"  -- {n_ok}/{len(verdicts)} class(es) compilable")
    return lines


def stage_report(tp, rank: int = 0, max_tasks: int = 256,
                 wavefront: bool = False,
                 plan: Optional[StagePlan] = None) -> List[str]:
    """Per-STAGE verdicts over an instantiated taskpool (the
    ``parsec_lint --lower-report`` per-stage payload, ISSUE 13): how
    the partition actually falls — each stage's size, level span, and
    class mix, plus the residue split and the pre-planned residue
    groups the compiled residue schedule will ride.  ``plan`` reuses
    an already-computed partition (the lint plans each spec once for
    both this report and the chain verdicts)."""
    if plan is None:
        plan = plan_stages(tp, rank=rank, max_tasks=max_tasks,
                           wavefront=wavefront)
    lines: List[str] = []
    for st in plan.stages:
        per_cls: Dict[str, int] = {}
        for m in st.members:
            per_cls[m.tc.ast.name] = per_cls.get(m.tc.ast.name, 0) + 1
        mix = ", ".join(f"{c} x{n}" for c, n in sorted(per_cls.items()))
        lines.append(f"  stage#{st.index}: {st.n_tasks} task(s), "
                     f"levels {st.level_lo}..{st.level_hi} [{mix}]")
    n_grouped = sum(len(g) for g in plan.residue_groups)
    lines.append(
        f"  -- {len(plan.stages)} stage(s) covering {plan.n_staged}/"
        f"{plan.n_local} local task(s), {plan.n_residue} residue"
        + (f" ({len(plan.residue_groups)} residue group(s) pre-planned "
           f"over {n_grouped} task(s))" if plan.residue_groups else ""))
    if plan.residue_groups_host:
        n_host = sum(len(g) for g in plan.residue_groups_host)
        lines.append(
            f"  -- {len(plan.residue_groups_host)} host residue "
            f"group(s) pre-planned over {n_host} task(s)")
    # cross-rank eligibility column (ISSUE 20): one line per (level,
    # class) wave group — spanning ranks + boundary edges + collective
    # kind, or the reason it stays rank-local
    for (lv, cls, text) in plan.xwave_report:
        lines.append(f"  xrank level {lv} {cls}: {text}")
    return lines
