"""Online per-task-class profile driving critical-path scheduler
priorities (ISSUE 7).

The reference's schedulers order ready tasks by the JDF's *static*
priority expression alone; nothing in the runtime reacts to where the
time actually goes.  ``ClassProfile`` closes that loop with two cheap
online signals:

- a **duration-weighted per-class EWMA** fed from the device module's
  dispatch timings (``dispatch_ns``) and the workers' CPU exec
  timings — where each class's time goes;
- the **class-level successor graph** read off the PTG ASTs at enqueue
  (``POTRF -> TRSM -> {SYRK, GEMM}`` for dpotrf) — where each class
  sits in the dataflow.

From these it computes an **upward-rank boost** per class (the HEFT
upward rank at class granularity):

1. the class digraph is condensed into strongly connected components
   (iterative Tarjan) — iterative workloads make the class graph
   cyclic (``SYRK -> POTRF(k+1)``), so the plain longest-path recursion
   would not terminate;
2. each condensation node gets the classic upward rank
   ``rank[scc] = weight[scc] + max(rank[succ])`` over the (acyclic)
   condensation, with ``weight`` = the summed member EWMAs (one pass
   through the cycle);
3. *within* an SCC, classes are ordered by **scarcity**: ascending
   duration-weighted share (instances seen x EWMA us).  The class with
   the smallest total share is the sequential bottleneck of the cycle —
   for dpotrf that ranks POTRF (NT instances) above TRSM/SYRK (~NT^2)
   above GEMM (~NT^3), exactly the chain the critical path follows —
   while the abundant classes have enough parallelism to fill in
   behind.

``effective(cls, static)`` packs the boost above the JDF's static
priority expression, which stays as the tiebreak (so ``(NT - k)``-style
depth ordering still decides among instances of one class).  Classes
the profile has never seen (DTD bodies, foreign pools) get boost 0 and
keep their static priority unchanged — enabling the profile never
reorders workloads it knows nothing about.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Set, Tuple

__all__ = ["ClassProfile", "TENANT_PRIO_SCALE"]

#: the static priority rides in the low bits; one boost step dominates
#: any static value inside the clamp window
_STATIC_CLAMP = (1 << 21) - 1
_PRIO_SCALE = 1 << 22
#: tenant fairness boosts (serve/fairness.py, ISSUE 18) pack ABOVE the
#: class-profile band: ``effective()`` yields at most boost*2^22+base
#: with boost < 2^18 in any realistic condensation, so one fairness
#: step dominates every critical-path boost while the class boost (and
#: under it the static expression) stays the within-tenant tiebreak
TENANT_PRIO_SCALE = 1 << 44


class ClassProfile:
    """Thread-safe online class profile + upward-rank boosts."""

    #: EWMA smoothing for the per-instance duration (us)
    ALPHA = 0.2
    #: recompute the cached boosts at most every this many notes
    RECOMPUTE_EVERY = 128

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._succ: Dict[str, Set[str]] = {}      # class -> successor classes
        self._ewma_us: Dict[str, float] = {}      # class -> us/instance EWMA
        self._count: Dict[str, int] = {}          # class -> instances seen
        self._warm: Set[str] = set()              # classes past sample #1
        self._boost: Dict[str, int] = {}          # cached ranks (read lock-free)
        self._dirty = True
        self._notes = 0

    # ------------------------------------------------------------------ #
    # feeding                                                            #
    # ------------------------------------------------------------------ #
    def observe_taskpool(self, tp: Any) -> None:
        """Merge a PTG taskpool's class-level dataflow into the graph
        (DTD pools carry no static classes and are skipped)."""
        changed = False
        with self._lock:
            for tc in getattr(tp, "task_classes", ()):
                ast = getattr(tc, "ast", None)
                if ast is None:
                    continue
                succs = self._succ.setdefault(ast.name, set())
                for f in ast.flows:
                    for d in f.deps:
                        for t in (d.target, d.alt_target):
                            if t is None or t.kind != "task" \
                                    or not t.task_class:
                                continue
                            if d.direction == "out":
                                if t.task_class not in succs:
                                    succs.add(t.task_class)
                                    changed = True
                            else:   # in-dep: producer -> this class
                                ps = self._succ.setdefault(
                                    t.task_class, set())
                                if ast.name not in ps:
                                    ps.add(ast.name)
                                    changed = True
            if changed:
                self._dirty = True

    def add_edges(self, cls: str, succs: Any = ()) -> None:
        """Register ``cls`` (and its successor classes) directly — the
        embedder/test-facing alternative to ``observe_taskpool``."""
        with self._lock:
            s = self._succ.setdefault(cls, set())
            for t in succs:
                s.add(t)
                self._succ.setdefault(t, set())
            self._dirty = True

    def note(self, cls: str, us_per_task: float, n: int = 1) -> None:
        """One measured dispatch/exec sample: ``n`` instances of ``cls``
        at ``us_per_task`` microseconds each.  The FIRST sample of a
        class is counted but not duration-weighted — it pays the
        one-time jit trace/compile, which would otherwise dominate the
        EWMA for the whole (short) run."""
        with self._lock:
            if cls not in self._succ:
                return   # unknown class: never boosted, don't track
            self._count[cls] = self._count.get(cls, 0) + n
            self._notes += 1
            if cls not in self._warm:
                self._warm.add(cls)
                self._dirty = True   # a class came online: re-rank now
            else:
                cur = self._ewma_us.get(cls)
                self._ewma_us[cls] = (us_per_task if cur is None else
                                      (1 - self.ALPHA) * cur
                                      + self.ALPHA * us_per_task)
            if self._notes >= self.RECOMPUTE_EVERY:
                self._dirty = True

    # ------------------------------------------------------------------ #
    # consuming                                                          #
    # ------------------------------------------------------------------ #
    def boost_of(self, cls: str) -> int:
        """The class's upward-rank boost (0 for unknown classes)."""
        if self._dirty:
            self._recompute()
        return self._boost.get(cls, 0)

    def effective(self, cls: str, static: int) -> int:
        """The effective scheduling priority: boost in the high bits,
        the (clamped) static JDF priority as the tiebreak."""
        base = max(-_STATIC_CLAMP, min(int(static), _STATIC_CLAMP))
        b = self.boost_of(cls)
        return b * _PRIO_SCALE + base if b else base

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Debug/report view: per-class EWMA, count, and boost."""
        if self._dirty:
            self._recompute()
        with self._lock:
            return {c: {"ewma_us": round(self._ewma_us.get(c, 0.0), 3),
                        "count": self._count.get(c, 0),
                        "boost": self._boost.get(c, 0)}
                    for c in self._succ}

    # ------------------------------------------------------------------ #
    # rank computation                                                   #
    # ------------------------------------------------------------------ #
    def _recompute(self) -> None:
        with self._lock:
            if not self._dirty:
                return
            self._dirty = False
            self._notes = 0
            succ = {c: set(s) for c, s in self._succ.items()}
            for s in list(succ.values()):
                for t in s:
                    succ.setdefault(t, set())
            ewma = dict(self._ewma_us)
            count = dict(self._count)
        sccs = _tarjan_sccs(succ)
        scc_of = {c: i for i, scc in enumerate(sccs) for c in scc}
        # condensation DAG + upward rank (weight = one pass through the
        # component; unmeasured classes weigh a nominal 1 us so the
        # pure-depth rank exists before the first sample lands)
        weight = [sum(ewma.get(c, 1.0) for c in scc) for scc in sccs]
        cond_succ: List[Set[int]] = [set() for _ in sccs]
        for c, ss in succ.items():
            for t in ss:
                if scc_of[c] != scc_of[t]:
                    cond_succ[scc_of[c]].add(scc_of[t])
        rank = [0.0] * len(sccs)
        for i in _reverse_topo(cond_succ):
            rank[i] = weight[i] + max(
                (rank[j] for j in cond_succ[i]), default=0.0)
        # dense-rank the SCC levels so boosts stay small stable ints
        levels = {r: li for li, r in enumerate(sorted(set(rank)))}
        boost: Dict[str, int] = {}
        for i, scc in enumerate(sccs):
            members = sorted(
                scc, key=lambda c: (-(count.get(c, 0)
                                      * ewma.get(c, 1.0)), c))
            # descending duration-weighted share: the scarcest class
            # (least total time — the cycle's sequential bottleneck)
            # lands last and gets the highest within-SCC ordinal
            for o, c in enumerate(members):
                boost[c] = levels[rank[i]] * 256 + min(o, 255)
        with self._lock:
            self._boost = boost


def _tarjan_sccs(succ: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]
    for root in succ:
        if root in index:
            continue
        work: List[Tuple[str, Any]] = [(root, iter(sorted(succ[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for child in it:
                if child not in index:
                    index[child] = low[child] = counter[0]
                    counter[0] += 1
                    stack.append(child)
                    on_stack.add(child)
                    work.append((child, iter(sorted(succ[child]))))
                    advanced = True
                    break
                if child in on_stack:
                    low[node] = min(low[node], index[child])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                out.append(scc)
    return out


def _reverse_topo(cond_succ: List[Set[int]]) -> List[int]:
    """Condensation nodes ordered successors-first (Tarjan already
    emits SCCs in reverse topological order, but recompute defensively
    from the edges so the rank loop never reads an unset successor)."""
    n = len(cond_succ)
    indeg = [0] * n
    for ss in cond_succ:
        for t in ss:
            indeg[t] += 1
    order: List[int] = [i for i in range(n) if indeg[i] == 0]
    i = 0
    while i < len(order):
        for t in cond_succ[order[i]]:
            indeg[t] -= 1
            if indeg[t] == 0:
                order.append(t)
        i += 1
    return list(reversed(order))
