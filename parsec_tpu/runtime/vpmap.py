"""Virtual-process map: work-stealing domains and thread layout.

Reference behavior: virtual processes partition compute threads into
work-stealing domains; layouts come from flat/hwloc/file/parameters init
(ref: parsec/vpmap.c, parsec/parsec.c:549-592). Thread→core binding is in
parsec/bindthread.c — reproduced here with os.sched_setaffinity (Linux),
opt-in via the ``bind_threads`` MCA param ("rr" round-robin over the
allowed cores, or an explicit core list "0,2,4,..." like --parsec_bind).
"""
from __future__ import annotations

import os
from typing import List, Optional


class VPMap:
    """nb_vp virtual processes, each with nb_threads[i] workers."""

    def __init__(self, nb_threads_per_vp: List[int]) -> None:
        assert nb_threads_per_vp and all(n > 0 for n in nb_threads_per_vp)
        self.nb_threads_per_vp = nb_threads_per_vp

    @property
    def nb_vp(self) -> int:
        return len(self.nb_threads_per_vp)

    @property
    def nb_total_threads(self) -> int:
        return sum(self.nb_threads_per_vp)

    def vp_of_thread(self, th_id: int) -> int:
        acc = 0
        for vp, n in enumerate(self.nb_threads_per_vp):
            acc += n
            if th_id < acc:
                return vp
        raise IndexError(th_id)

    @staticmethod
    def from_flat(nb_cores: int) -> "VPMap":
        """ref: vpmap_init_from_flat — one VP with all threads."""
        return VPMap([max(1, nb_cores)])

    @staticmethod
    def from_parameters(nb_vp: int, threads_per_vp: int) -> "VPMap":
        return VPMap([threads_per_vp] * nb_vp)

    @staticmethod
    def from_file(path: str) -> "VPMap":
        """One line per VP: number of threads (ref: vpmap_init_from_file)."""
        counts = []
        with open(path) as fh:
            for line in fh:
                line = line.split("#")[0].strip()
                if line:
                    counts.append(int(line))
        if not counts:
            raise ValueError(f"vpmap file {path} defines no virtual process")
        return VPMap(counts)


class VirtualProcess:
    """ref: parsec_vp_t — holds this domain's execution streams."""

    def __init__(self, vp_id: int, nb_threads: int) -> None:
        self.vp_id = vp_id
        self.nb_threads = nb_threads
        self.execution_streams: List = []


def default_nb_cores() -> int:
    env = os.environ.get("PARSEC_NB_CORES")
    if env:
        return max(1, int(env))
    return max(1, os.cpu_count() or 1)


def bind_current_thread(core: int) -> bool:
    """Pin the CALLING thread to one core (ref: parsec_bindthread,
    bindthread.c). Returns False where unsupported (non-Linux) or the
    core is not in the process's allowed set."""
    try:
        os.sched_setaffinity(0, {core})
        return True
    except (AttributeError, OSError, ValueError):
        return False


def binding_for(th_id: int, nb_threads: int) -> Optional[int]:
    """The core th_id should pin to under the ``bind_threads`` MCA param,
    or None when binding is off (the default)."""
    from ..utils.params import params
    spec = params.get("bind_threads")
    if not spec:
        return None
    try:
        allowed = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return None
    if spec == "rr":
        return allowed[th_id % len(allowed)]
    cores = []
    for part in str(spec).split(","):
        part = part.strip()
        if part.isdigit() and int(part) in allowed:
            cores.append(int(part))
    if not cores:
        return None
    return cores[th_id % len(cores)]
