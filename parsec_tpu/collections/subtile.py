"""Sub-tiling of one tile for recursive task calls.

Reference behavior: the ``subtile`` descriptor views a single tile of a
parent matrix as a smaller tiled matrix so a nested taskpool can run tile
algorithms inside it (ref: parsec/data_dist/matrix/subtile.c, used by the
recursive-tasks machinery, parsec/recursive.h).

``SubtileView`` wraps a host ndarray (typically one tile's payload) without
copying: sub-tiles are numpy views, so the nested computation updates the
parent tile in place — exactly the recursive dpotrf/potrf-on-diagonal use.
All sub-tiles are local (``rank_of == rank``): recursion never crosses
ranks, matching the reference (a subtile descriptor lives on the rank that
owns the parent tile).
"""
from __future__ import annotations

import numpy as np

from ..data.data import Data, data_new_with_payload
from .matrix import TiledMatrix

__all__ = ["SubtileView"]


class SubtileView(TiledMatrix):
    def __init__(self, array: np.ndarray, mb: int, nb: int,
                 uplo: str = "full") -> None:
        assert array.ndim == 2, "SubtileView wraps a 2-D tile"
        super().__init__(array.shape[0], array.shape[1], mb, nb,
                         dtype=array.dtype, nodes=1, rank=0, uplo=uplo)
        self.array = array

    def rank_of(self, m: int, n: int) -> int:
        return self.rank

    def pull_home(self, devices=None) -> None:
        """Fold the newest version of every sub-tile back into the parent
        array (the reference analog: the subtile descriptor unwinds into
        the parent tile when the nested taskpool finishes). Needed because
        device stage-out replaces host payload objects, breaking the view
        aliasing."""
        with self._tlock:
            items = list(self._tiles.items())
        for (m, n), d in items:
            host = d.sync_to_host(devices)
            if host.payload is None:
                continue
            tm, tn = self.tile_shape(m, n)
            region = self.array[m * self.mb:m * self.mb + tm,
                                n * self.nb:n * self.nb + tn]
            if host.payload is not region:
                np.copyto(region, np.asarray(host.payload))

    def data_of(self, m: int, n: int) -> Data:
        assert 0 <= m < self.mt and 0 <= n < self.nt, \
            f"subtile ({m},{n}) out of range"
        with self._tlock:
            d = self._tiles.get((m, n))
            if d is None:
                tm, tn = self.tile_shape(m, n)
                view = self.array[m * self.mb:m * self.mb + tm,
                                  n * self.nb:n * self.nb + tn]
                d = data_new_with_payload(view, device_id=0,
                                          key=(id(self), m, n))
                d.collection = self
                self._tiles[(m, n)] = d
            return d
