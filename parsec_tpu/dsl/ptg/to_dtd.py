"""Replay a PTG taskpool through the DTD engine.

Reference behavior: the ``ptg_to_dtd`` PINS module intercepts a PTG
taskpool and re-executes it with the dynamic-task-discovery front end —
a cross-DSL consistency check and a migration aid (ref:
parsec/mca/pins/ptg_to_dtd/).

TPU-native re-design: instead of intercepting at the scheduler, we
*compile* the PTG's instance graph into a DTD insertion stream:

1. enumerate every task instance of every class;
2. build the dependency edges with the same resolution logic the PTG
   runtime uses (input deps that resolve to task sources);
3. topologically order the instances (DTD discovers deps from the
   *sequential* insertion order, so the stream must be a valid sequential
   schedule);
4. map each data flow to its *memory anchor* — the collection tile the
   flow chain ultimately originates from / writes back to — by walking
   input-dep chains backwards; that tile becomes the DTD tracked datum
   with IN/INOUT access derived from the flow access.

Flows with no memory anchor (NEW scratch, CTL) carry no data dependency —
same restriction as the reference module. Bodies run the JDF's host BODY
code with flow names bound to the DTD tile payloads.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...data.data import FlowAccess
from .runtime import PTGTaskpool, PTGTaskClass

__all__ = ["ptg_to_dtd"]



def _instances(tp: PTGTaskpool):
    for tc in tp.task_classes:
        for locals_ in tc.iter_space():
            yield (tc, locals_)


def _producer_edges(tc: PTGTaskClass, locals_: Tuple):
    """(producer_class_name, producer_locals) for each task-sourced input."""
    env = tc.env_of(locals_)
    for f in tc.ast.flows:
        for d in f.deps_in():
            t = d.resolve(env)
            if t is not None and t.kind == "task":
                args = tuple(a(env) for a in t.args)
                yield (t.task_class, args)


def _memory_anchor(tp: PTGTaskpool, tc: PTGTaskClass, locals_: Tuple,
                   flow_name: str, memo: Dict) -> Optional[Tuple[str, Tuple]]:
    """The (collection, indices) a flow's data chain originates from,
    following task-sourced inputs backwards (the same datatype-lookup walk
    the reference does on the receiver side, remote_dep_mpi.c:766)."""
    key = (tc.task_class_id, locals_, flow_name)
    if key in memo:
        return memo[key]
    memo[key] = None  # cycle guard; RW chains terminate at memory
    env = tc.env_of(locals_)
    fl = tc.ast.flow_by_name(flow_name)
    anchor = None
    for d in fl.deps_in():
        t = d.resolve(env)
        if t is None:
            continue
        if t.kind == "memory":
            anchor = (t.collection, tuple(a(env) for a in t.args))
        elif t.kind == "task":
            args = tuple(a(env) for a in t.args)
            anchor = _memory_anchor(tp, tp.class_by_name(t.task_class),
                                    args, t.flow, memo)
        break  # first resolving dep defines the chain, as in prepare_input
    if anchor is None:
        for d in fl.deps_out():
            t = d.resolve(env)
            if t is not None and t.kind == "memory":
                anchor = (t.collection, tuple(a(env) for a in t.args))
                break
    memo[key] = anchor
    return anchor


def ptg_to_dtd(ptg_tp: PTGTaskpool, context) -> Any:
    """Execute ``ptg_tp``'s DAG through a fresh DTD taskpool on ``context``
    (blocking). The PTG pool itself is never enqueued. Returns the DTD pool
    (already waited)."""
    from ..dtd import (AccessMode, taskpool_new)

    assert ptg_tp.context is None, "ptg_to_dtd wants a non-enqueued PTG pool"

    # 1-2: instances + edges
    nodes: List[Tuple[PTGTaskClass, Tuple]] = list(_instances(ptg_tp))
    index = {(tc.name, loc): i for i, (tc, loc) in enumerate(nodes)}
    indeg = [0] * len(nodes)
    succs: List[List[int]] = [[] for _ in nodes]
    for i, (tc, loc) in enumerate(nodes):
        for pname, plocals in _producer_edges(tc, loc):
            j = index.get((pname, plocals))
            if j is not None:
                succs[j].append(i)
                indeg[i] += 1

    # 3: Kahn topological order (deterministic: FIFO over definition order)
    order: List[int] = []
    q = deque(i for i in range(len(nodes)) if indeg[i] == 0)
    while q:
        i = q.popleft()
        order.append(i)
        for s in succs[i]:
            indeg[s] -= 1
            if indeg[s] == 0:
                q.append(s)
    assert len(order) == len(nodes), "PTG dependency graph has a cycle"

    # 4: insert in topo order with memory-anchored tiles
    dtd_tp = taskpool_new(name=f"{ptg_tp.name}_as_dtd")
    context.add_taskpool(dtd_tp)
    memo: Dict = {}
    for i in order:
        tc, locals_ = nodes[i]
        flow_binds: List[Tuple[str, Optional[Any], str]] = []
        args = []
        for f in tc.ast.flows:
            if f.is_ctl:
                continue
            anchor = _memory_anchor(ptg_tp, tc, locals_, f.name, memo)
            if anchor is None:
                flow_binds.append((f.name, None, f.access))
                continue
            coll = ptg_tp.global_env[anchor[0]]
            # the DTD tile registry keys by collection name; default-named
            # collections ride their (unique) PTG global name on the wire
            # without mutating the caller's object
            wire = None
            if getattr(coll, "name", None) == type(coll).__name__:
                wire = f"{ptg_tp.name}.{anchor[0]}"
            tile = dtd_tp.tile_of(coll, coll.data_key(*anchor[1]),
                                  wire_name=wire)
            mode = AccessMode.INPUT if f.access == "READ" else AccessMode.INOUT
            flow_binds.append((f.name, tile, f.access))
            args.append((tile, mode))

        host_bodies = [b for b in tc.ast.bodies
                       if b.device_type in ("cpu", "recursive")]
        body_src = (host_bodies[0] if host_bodies else tc.ast.bodies[0]).code
        code = compile(body_src, f"<ptg_to_dtd:{tc.name}>", "exec")

        def make_body(tc=tc, locals_=locals_, code=code, flow_binds=flow_binds):
            def body(es, task):
                env = tc.env_of(locals_)
                payloads = {}
                for fname, tile, access in flow_binds:
                    if tile is None:
                        env[fname] = None
                        continue
                    arr = tile.data.sync_to_host(es.context.devices).payload
                    env[fname] = arr
                    payloads[fname] = arr
                env["np"] = np
                try:
                    import jax.numpy as jnp
                    env.setdefault("jnp", jnp)
                except Exception:
                    pass
                exec(code, env)
                for fname, tile, access in flow_binds:
                    if tile is None or access == "READ":
                        continue
                    new_val = env.get(fname)
                    old = payloads[fname]
                    if new_val is not None and new_val is not old:
                        np.copyto(old, np.asarray(new_val))
            return body

        dtd_tp.insert_task(make_body(), *args,
                           name=f"{tc.name}{locals_}")
    dtd_tp.data_flush_all()
    dtd_tp.wait()
    return dtd_tp
