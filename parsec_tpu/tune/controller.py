"""The self-tuning controller: health-window digests in, knob moves out.

One :class:`Controller` per context (constructed by ``ContextObs`` when
``tune_auto`` is set), subscribed to :meth:`LiveHealth.tick`'s window
digest.  All decision logic runs on the monitor thread — one digest at
a time, no internal locking needed; the counters the gauges poll are
plain ints (atomic reads under the GIL).

Decision families
-----------------
codec   The wire-codec ladder ``(None, qbf16, qint8)`` with declared
        relative-residual costs ``(0, 1e-2, 1e-1)``; the budget param
        caps how high the ladder may go.  Two directions per peer:
        *rx* (this rank's inbound link looks bandwidth-bound — window
        exposed-wait z above threshold — so ask the SENDER to quantize
        via a K_TUNE frame) and *tx* (this rank's own send-bandwidth
        EWMA toward the peer collapsed below the floor, so quantize
        locally).  De-escalation: a requested codec that moves no
        quantized bytes for ``2*hysteresis`` windows, or compresses
        worse than ``no_win_ratio``, shows no win and steps back down.
        Mixed-version peers (no "tn" HELLO capability) are never
        renegotiated.
device  Hill-climb on ``batch_max`` / ``prefetch_depth`` /
        ``flush_segments`` from per-window deltas of the device stats.
        One move per device at a time; a move's effect is judged after
        ``hysteresis`` windows against the us/task dispatch-objective
        EWMA and ROLLED BACK if the objective regressed by more than
        ``regress_pct`` — the revert memory that keeps a bad step from
        sticking.
stagec  A rank whose exec-busy keeps collapsing while compiled stages
        are live (the self-straggler detector firing
        ``straggler_windows`` windows in a row) gets the dominant
        compiled class appended to ``stage_compile_exclude`` — the
        prepared-plan cache keys on the exclusion set, so the NEXT
        taskpool over the same spec replans without it.

Every committed move bumps ``PARSEC::TUNE::DECISIONS`` and emits one
``tune:<family>`` instant annotation on the health stream; every
rollback bumps ``PARSEC::TUNE::REVERTS`` and emits ``tune:revert``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.spans import (TUNE_ACTIVE_CODEC_PREFIX, TUNE_DECISIONS,
                         TUNE_OBJECTIVE_US, TUNE_REVERTS)

#: The codec ladder, lossless first; index == the ACTIVE_CODEC gauge
#: value and the rung the escalation logic climbs one step at a time.
CODEC_LADDER: Tuple[Optional[str], ...] = (None, "qbf16", "qint8")

#: Declared relative-residual cost of each rung (what one hop through
#: the codec may spend of ``tune_residual_budget``): bf16 keeps 8
#: mantissa bits (~1e-2 relative), int8 blockwise ~1e-1.  A rung is
#: reachable only while its cost fits the budget.
CODEC_COST: Dict[Optional[str], float] = {None: 0.0,
                                          "qbf16": 1e-2,
                                          "qint8": 1e-1}

# device knob bounds the hill-climber may not leave
_BATCH_MAX_CAP = 1024
_PREFETCH_CAP = 16
_FLUSH_SEG_CAP = 16
_EXCLUDE_CAP = 4       # never exclude more classes than this


def _ladder_index(codec: Optional[str]) -> int:
    try:
        return CODEC_LADDER.index(codec)
    except ValueError:   # unknown codec string from a newer peer
        return 0


class Controller:
    """Closed-loop tuner over one rank's live-health window digests."""

    def __init__(self, rank: int, live: Any, *,
                 engine: Any = None,
                 devices: Tuple[Any, ...] = (),
                 residual_budget: float = 1e-2,
                 hysteresis: int = 2,
                 z_thresh: float = 3.0,
                 bw_floor_mbps: float = 32.0,
                 no_win_ratio: float = 0.95,
                 occupancy_hi: float = 0.85,
                 occupancy_lo: float = 0.3,
                 prefetch_lo: float = 0.5,
                 overlap_lo: float = 0.5,
                 regress_pct: float = 0.05,
                 straggler_windows: int = 3,
                 overlap_fn: Optional[Callable[[], float]] = None,
                 stage_classes_fn: Optional[Callable[[], List[str]]] = None,
                 ) -> None:
        self.rank = int(rank)
        self.live = live
        # the transport is optional (in-process fabrics have no wire
        # codecs) and must expose the tuning seams to participate
        self.engine = engine if engine is not None and \
            hasattr(engine, "tune_send") else None
        self.devices = list(devices)
        self.hysteresis = max(1, int(hysteresis))
        self.z_thresh = float(z_thresh)
        self.bw_floor_mbps = float(bw_floor_mbps)
        self.no_win_ratio = float(no_win_ratio)
        self.occupancy_hi = float(occupancy_hi)
        self.occupancy_lo = float(occupancy_lo)
        self.prefetch_lo = float(prefetch_lo)
        self.overlap_lo = float(overlap_lo)
        self.regress_pct = float(regress_pct)
        self.straggler_windows = max(1, int(straggler_windows))
        self.overlap_fn = overlap_fn
        self.stage_classes_fn = stage_classes_fn
        # the highest ladder rung the residual budget admits
        budget = max(0.0, float(residual_budget))
        self.max_rung = max(i for i, c in enumerate(CODEC_LADDER)
                            if CODEC_COST[c] <= budget)
        self.counts = {"decisions": 0, "reverts": 0,
                       "codec_moves": 0, "device_moves": 0,
                       "stagec_moves": 0}
        self._peers: Dict[int, Dict[str, Any]] = {}
        self._devs: Dict[int, Dict[str, Any]] = {}
        self._objective: Optional[float] = None   # us/task EWMA
        self._strag_streak = 0
        self._excluded: List[str] = []
        self._sde: Any = None
        self._gauged_peers: set = set()

    # ------------------------------------------------------------------ #
    # plumbing                                                           #
    # ------------------------------------------------------------------ #
    def objective_us(self) -> float:
        return round(self._objective, 1) if self._objective is not None \
            else 0.0

    def codec_index(self, peer: int) -> int:
        """The ACTIVE_CODEC gauge: the ladder rung of the codec this
        rank actually applies on its send side toward ``peer``."""
        eng = self.engine
        if eng is None:
            return 0
        return _ladder_index(eng.active_quant_codec(peer))

    def _annotate(self, name: str, args: Dict[str, Any]) -> None:
        try:
            self.live.annotate(name, args)
        except Exception:   # noqa: BLE001 - telemetry must not raise
            pass

    def _ensure_codec_gauge(self, peer: int) -> None:
        sde = self._sde
        if sde is None or peer in self._gauged_peers:
            return
        self._gauged_peers.add(peer)
        sde.register_poll(f"{TUNE_ACTIVE_CODEC_PREFIX}::R{peer}",
                          lambda p=peer: self.codec_index(p))

    def _peer_state(self, peer: int) -> Dict[str, Any]:
        st = self._peers.get(peer)
        if st is None:
            st = {"rx_rung": 0, "rx_up": 0, "rx_idle": 0,
                  "tx_rung": 0, "tx_up": 0, "tx_idle": 0,
                  "cool": 0, "last_rx": (0, 0)}
            self._peers[peer] = st
            self._ensure_codec_gauge(peer)
        return st

    # ------------------------------------------------------------------ #
    # the window tick                                                    #
    # ------------------------------------------------------------------ #
    def on_window(self, dg: Dict[str, Any]) -> None:
        """One health window folded: run every decision family.  Called
        on the monitor thread (LiveHealth subscriber seam); exceptions
        are swallowed by the caller, but decision logic is defensive
        anyway — a sick family must not starve the others."""
        try:
            self._codec_step(dg)
        except Exception:   # noqa: BLE001
            pass
        try:
            self._device_step(dg)
        except Exception:   # noqa: BLE001
            pass
        try:
            self._stagec_step(dg)
        except Exception:   # noqa: BLE001
            pass

    # ------------------------------------------------------------------ #
    # family 1: the wire-codec ladder                                    #
    # ------------------------------------------------------------------ #
    def _codec_step(self, dg: Dict[str, Any]) -> None:
        eng = self.engine
        if eng is None or self.max_rung == 0:
            return
        win = int(dg.get("window", 0))
        # rx direction: inbound links R<src>->R<me> whose window
        # exposed-wait z crossed the straggler threshold are
        # bandwidth-bound — ask the sender to climb one rung
        for link, info in (dg.get("links") or {}).items():
            try:
                src = int(link.split("->")[0][1:])
            except (ValueError, IndexError):
                continue
            if src == self.rank:
                continue
            st = self._peer_state(src)
            hot = bool(info.get("warm")) and \
                float(info.get("z", 0.0)) > self.z_thresh
            st["rx_up"] = st["rx_up"] + 1 if hot else 0
            if (st["cool"] == 0 and st["rx_up"] >= self.hysteresis
                    and st["rx_rung"] < self.max_rung
                    and eng.tune_to(src)):
                self._move_rx(eng, src, st, st["rx_rung"] + 1, win,
                              why=f"exposed z={info.get('z')}")
        # tx direction: this rank's own send-bandwidth EWMA toward a
        # peer collapsed below the floor — quantize locally
        for peer, bw in (dg.get("bw") or {}).items():
            peer = int(peer)
            if peer == self.rank or bw is None:
                continue
            st = self._peer_state(peer)
            slow = 0.0 < float(bw) < self.bw_floor_mbps
            st["tx_up"] = st["tx_up"] + 1 if slow else 0
            if (st["cool"] == 0 and st["tx_up"] >= self.hysteresis
                    and st["tx_rung"] < self.max_rung
                    and eng.tune_to(peer)):
                new = st["tx_rung"] + 1
                if eng.set_quant_codec(peer, CODEC_LADDER[new]):
                    st["tx_rung"] = new
                    st["tx_up"] = 0
                    st["cool"] = self.hysteresis
                    self.counts["decisions"] += 1
                    self.counts["codec_moves"] += 1
                    self._annotate("tune:codec", {
                        "dir": "tx", "peer": peer, "window": win,
                        "codec": CODEC_LADDER[new] or "lossless",
                        "why": f"send bw {float(bw):.1f}MB/s < "
                               f"{self.bw_floor_mbps:.0f}"})
        # de-escalation: a requested rx codec that lands no quantized
        # bytes (or compresses worse than no_win_ratio) shows no win
        for peer, st in self._peers.items():
            if st["cool"] > 0:
                st["cool"] -= 1
            if st["rx_rung"] <= 0:
                continue
            pre, post = eng.rx_quant_ratio(peer)
            d_pre = pre - st["last_rx"][0]
            d_post = post - st["last_rx"][1]
            st["last_rx"] = (pre, post)
            no_win = d_pre == 0 or \
                (d_pre > 0 and d_post / d_pre > self.no_win_ratio)
            st["rx_idle"] = st["rx_idle"] + 1 if no_win else 0
            if (st["rx_idle"] >= 2 * self.hysteresis
                    and eng.tune_to(peer)):
                self._move_rx(eng, peer, st, st["rx_rung"] - 1,
                              int(dg.get("window", 0)), why="no win")

    def _move_rx(self, eng: Any, peer: int, st: Dict[str, Any],
                 rung: int, win: int, why: str) -> None:
        codec = CODEC_LADDER[rung]
        if not eng.tune_send(peer, {"op": "codec", "codec": codec}):
            return
        st["rx_rung"] = rung
        st["rx_up"] = 0
        st["rx_idle"] = 0
        st["cool"] = self.hysteresis
        self.counts["decisions"] += 1
        self.counts["codec_moves"] += 1
        self._annotate("tune:codec", {
            "dir": "rx", "peer": peer, "window": win,
            "codec": codec or "lossless", "why": why})

    # ------------------------------------------------------------------ #
    # family 2: device pipeline-shape hill-climb                         #
    # ------------------------------------------------------------------ #
    def _device_step(self, dg: Dict[str, Any]) -> None:
        win = int(dg.get("window", 0))
        tot_ns = tot_tasks = 0
        for i, dev in enumerate(self.devices):
            stats = getattr(dev, "stats", None)
            if not isinstance(stats, dict) or "dispatch_ns" not in stats:
                continue
            st = self._devs.setdefault(i, {
                "cool": 0, "pend": None, "streak": {},
                "last": dict(stats)})
            last = st["last"]
            d = {k: stats.get(k, 0) - last.get(k, 0) for k in
                 ("batches", "batched_tasks", "dispatch_ns",
                  "dispatch_tasks", "prefetch_issued", "prefetch_hits",
                  "segmented_flushes")}
            st["last"] = dict(stats)
            tot_ns += d["dispatch_ns"]
            tot_tasks += d["dispatch_tasks"]
            self._climb(dev, i, st, d, win)
        if tot_tasks > 0:
            sample = (tot_ns / 1e3) / tot_tasks
            self._objective = sample if self._objective is None \
                else 0.5 * self._objective + 0.5 * sample

    def _climb(self, dev: Any, idx: int, st: Dict[str, Any],
               d: Dict[str, int], win: int) -> None:
        name = getattr(dev, "name", None) or f"dev{idx}"
        pend = st["pend"]
        if pend is not None:
            # a move is on probation: judge it after hysteresis windows
            # against the objective EWMA it was taken at
            pend["age"] += 1
            if pend["age"] < self.hysteresis:
                return
            obj = self._objective
            base = pend["baseline"]
            if (obj is not None and base is not None
                    and obj > base * (1.0 + self.regress_pct)):
                setattr(dev, pend["knob"], pend["old"])
                self.counts["reverts"] += 1
                self._annotate("tune:revert", {
                    "dev": name, "knob": pend["knob"], "window": win,
                    "to": pend["old"],
                    "why": f"objective {obj:.1f}us/task > "
                           f"{base:.1f} +{self.regress_pct:.0%}"})
                st["cool"] = self.hysteresis
            st["pend"] = None
            return
        if st["cool"] > 0:
            st["cool"] -= 1
            return
        move = self._propose(dev, d)
        if move is None:
            st["streak"] = {}
            return
        knob, new, why = move
        # hysteresis = the SAME move re-proposed this many times: a
        # contradictory proposal on the same knob (halve one window,
        # double the next) restarts that knob's count, while a window
        # won by a DIFFERENT knob leaves it intact — priority
        # interleaving is not oscillation (a clean window still clears
        # everything above)
        key = (knob, new)
        streak = {k: v for k, v in st["streak"].items()
                  if k == key or k[0] != knob}
        streak[key] = streak.get(key, 0) + 1
        st["streak"] = streak
        if streak[key] < self.hysteresis:
            return
        old = getattr(dev, knob)
        setattr(dev, knob, new)
        st["pend"] = {"knob": knob, "old": old, "age": 0,
                      "baseline": self._objective}
        st["streak"] = {}
        self.counts["decisions"] += 1
        self.counts["device_moves"] += 1
        self._annotate("tune:device", {
            "dev": name, "knob": knob, "window": win,
            "from": old, "to": new, "why": why})

    def _propose(self, dev: Any,
                 d: Dict[str, int]) -> Optional[Tuple[str, int, str]]:
        """The single highest-priority knob move this window's stats
        deltas support, or None when the shape looks right."""
        bmax = int(getattr(dev, "batch_max", 1))
        if d["batches"] > 0 and bmax > 0:
            occ = d["batched_tasks"] / d["batches"]
            if occ >= self.occupancy_hi * bmax and bmax < _BATCH_MAX_CAP:
                return ("batch_max", min(_BATCH_MAX_CAP, bmax * 2),
                        f"occupancy {occ:.1f}/{bmax} saturated")
            if bmax > 1 and occ <= self.occupancy_lo * bmax:
                return ("batch_max", max(1, bmax // 2),
                        f"occupancy {occ:.1f}/{bmax} sparse")
        if d["prefetch_issued"] > 0:
            hit = d["prefetch_hits"] / d["prefetch_issued"]
            depth = int(getattr(dev, "prefetch_depth", 0))
            if hit < self.prefetch_lo and depth < _PREFETCH_CAP:
                return ("prefetch_depth", depth + 1,
                        f"prefetch hit-rate {hit:.2f}")
        if d["segmented_flushes"] > 0 and self.overlap_fn is not None:
            try:
                ov = float(self.overlap_fn())
            except Exception:   # noqa: BLE001
                ov = 1.0
            segs = int(getattr(dev, "flush_segments", 1))
            if ov < self.overlap_lo and segs < _FLUSH_SEG_CAP:
                return ("flush_segments", segs + 1,
                        f"overlap fraction {ov:.2f}")
        return None

    # ------------------------------------------------------------------ #
    # family 3: stage-compile exclusion                                  #
    # ------------------------------------------------------------------ #
    def _stagec_step(self, dg: Dict[str, Any]) -> None:
        if self.stage_classes_fn is None or \
                len(self._excluded) >= _EXCLUDE_CAP:
            return
        fired = any(f.get("kind") == "straggler"
                    and f.get("suspect") == self.rank
                    and f.get("link") is None
                    for f in (dg.get("fired") or ()))
        self._strag_streak = self._strag_streak + 1 if fired else 0
        if self._strag_streak < self.straggler_windows:
            return
        self._strag_streak = 0
        try:
            classes = list(self.stage_classes_fn() or ())
        except Exception:   # noqa: BLE001
            return
        from ..utils.params import params
        cur = str(params.get_or("stage_compile_exclude", "string", "")
                  or "")
        have = {c.strip() for c in cur.split(",") if c.strip()}
        victim = next((c for c in classes
                       if c and c not in have), None)
        if victim is None:
            return
        params.set_cmdline("stage_compile_exclude",
                           f"{cur},{victim}" if cur else victim)
        self._excluded.append(victim)
        self.counts["decisions"] += 1
        self.counts["stagec_moves"] += 1
        self._annotate("tune:stagec", {
            "exclude": victim, "window": int(dg.get("window", 0)),
            "why": f"self-straggler x{self.straggler_windows} with "
                   f"compiled stages live"})


def register_tune_gauges(sde: Any, ctl: Controller) -> None:
    """Register the PARSEC::TUNE::* poll gauges for one controller
    (per-peer ACTIVE_CODEC gauges self-register as peers appear)."""
    ctl._sde = sde
    sde.register_poll(TUNE_DECISIONS, lambda: ctl.counts["decisions"])
    sde.register_poll(TUNE_REVERTS, lambda: ctl.counts["reverts"])
    sde.register_poll(TUNE_OBJECTIVE_US, ctl.objective_us)
    for peer in list(ctl._peers):
        ctl._ensure_codec_gauge(peer)
