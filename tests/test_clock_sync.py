"""Clock-offset estimation (ISSUE 15): the NTP-style midpoint method
piggybacked on K_PING/K_PONG — wire round-trips of the extension,
near-zero estimates on a shared clock, the asymmetric-delay error
bound via the existing ft_inject delay directive, mixed-version peers
staying on plain pings, and the gauges.
"""
import threading
import time

import pytest

from parsec_tpu.comm import wire
from parsec_tpu.utils.params import params


def _tcp_pair(flow=(True, True), inject=""):
    from contextlib import ExitStack

    from parsec_tpu.comm.tcp import TCPCommEngine, free_ports

    eps = [("127.0.0.1", p) for p in free_ports(2)]
    engines = [None, None]
    with ExitStack() as st:
        if inject:
            st.enter_context(params.cmdline_override("ft_inject", inject))

        def boot(r):
            engines[r] = TCPCommEngine(r, eps, obs_flow=flow[r])
        ts = [threading.Thread(target=boot, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
    return engines


def _wait_offsets(eng, peer, n_min=3, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with eng._stat_lock:
            n = eng._clock_n.get(peer, 0)
        if n >= n_min:
            return eng.clock_offset_us(peer)
        time.sleep(0.02)
    return eng.clock_offset_us(peer)


# ---------------------------------------------------------------------- #
# wire framing                                                           #
# ---------------------------------------------------------------------- #
def test_ping_extension_roundtrip_and_back_compat():
    plain = wire.pack_ping(3, 12345)
    assert len(plain) == 13           # <BIQ — the pre-ISSUE-15 frame
    assert wire.parse_ping(memoryview(plain)) == (3, 12345)
    assert wire.ping_clock(memoryview(plain)) is None

    ext = wire.pack_ping(3, 12345, clock_ns=0)
    assert len(ext) == 21             # + the trailing clock word
    # old parsers read the leading fields positionally and ignore the
    # trailing clock word — the mixed-version contract
    assert wire.parse_ping(memoryview(ext)) == (3, 12345)
    assert wire.ping_clock(memoryview(ext)) == 0

    pong = wire.pack_ping(3, 12345, pong=True, clock_ns=999)
    assert wire.ping_clock(memoryview(pong)) == 999
    assert memoryview(pong)[0] == wire.K_PONG


# ---------------------------------------------------------------------- #
# the estimator over real sockets                                        #
# ---------------------------------------------------------------------- #
def test_offsets_near_zero_on_shared_clock():
    """Both engines live in one process (one monotonic clock): the
    estimate must be bounded by the loopback round trip — a handful of
    ms even on a loaded CI host, nowhere near a real cross-host skew."""
    e0, e1 = _tcp_pair()
    try:
        off0 = _wait_offsets(e0, 1)
        off1 = _wait_offsets(e1, 0)
        assert off0 is not None and off1 is not None, \
            "clock sampler produced no estimate"
        assert abs(off0) < 10_000, off0
        assert abs(off1) < 10_000, off1
        assert e0.clock_offsets_us() == {1: off0}
    finally:
        e0.fini()
        e1.fini()


def test_asymmetric_delay_bounds_estimate_error():
    """ISSUE 15 satellite: an injected asymmetric link delay (rank 0's
    outbound probes sleep ``d`` ms via the existing ft_inject delay
    directive with ``hb=1``) must bound the estimate error: the true
    offset is 0 (shared clock), the midpoint method's error is half
    the path asymmetry, so rank 0's estimate lands near +d/2 — within
    (0, d] — while rank 1's (symmetric legs) stays near zero."""
    d_ms = 40.0
    e0, e1 = _tcp_pair(inject=f"delay:rank=0:pct=100:ms={d_ms}:hb=1")
    try:
        off0 = _wait_offsets(e0, 1, timeout=20.0)
        off1 = _wait_offsets(e1, 0, timeout=20.0)
        assert off0 is not None and off1 is not None
        # the delayed request leg shows up as ~+d/2; bounded by d
        assert d_ms * 1e3 * 0.2 < off0 <= d_ms * 1e3, off0
        # the undelayed direction stays an order of magnitude tighter
        assert abs(off1) < d_ms * 1e3 * 0.25, off1
    finally:
        e0.fini()
        e1.fini()


def test_mixed_version_peer_never_gets_the_extension():
    """A peer whose HELLO lacks "tr" (knob unset there) receives plain
    13-byte pings only, so neither side ever estimates an offset —
    byte-identical wire toward old builds."""
    e0, e1 = _tcp_pair(flow=(True, False))
    try:
        # give the sampler time to (not) produce anything
        time.sleep(0.5)
        assert e0.clock_offset_us(1) is None
        assert e1.clock_offset_us(0) is None
        assert e0.clock_offsets_us() == {}
        # and the negotiation really declined (not just a silent race)
        p = e0._peer_to(1)
        deadline = time.time() + 5
        while time.time() < deadline and not p.hello_seen:
            time.sleep(0.01)
        assert p.hello_seen and not p.tr_ok
    finally:
        e0.fini()
        e1.fini()


def test_flow_knob_off_means_no_sampler_thread():
    e0, e1 = _tcp_pair(flow=(False, False))
    try:
        assert e0._clock_thread is None and e1._clock_thread is None
        assert e0.clock_offsets_us() == {}
    finally:
        e0.fini()
        e1.fini()


def test_detector_probes_feed_the_estimator():
    """ft_ping itself sends the extension toward tr-peers: detector
    probes contribute midpoint samples without the sampler thread."""
    e0, e1 = _tcp_pair()
    try:
        p = e0._peer_to(1)
        deadline = time.time() + 5
        while time.time() < deadline and not p.tr_ok:
            time.sleep(0.01)
        assert p.tr_ok
        assert e0.ft_ping(1, 7, time.monotonic_ns())
        off = _wait_offsets(e0, 1, n_min=1)
        assert off is not None
    finally:
        e0.fini()
        e1.fini()


# ---------------------------------------------------------------------- #
# gauges + metadata export                                               #
# ---------------------------------------------------------------------- #
def test_clock_offset_gauges_registered_under_the_knob():
    from parsec_tpu.comm import LocalFabric
    from parsec_tpu.obs import (CommObs, MetricsRegistry,
                                OBS_CLOCK_OFFSET_PREFIX)

    name = f"{OBS_CLOCK_OFFSET_PREFIX}::R1"
    with params.cmdline_override("obs_flow", "1"):
        fab = LocalFabric(2)
        eng = fab.engine(0)
        m = MetricsRegistry()
        CommObs(m).register_engine_gauges(eng)
    # in-process fabrics are same-clock: the gauge exists and reads 0
    assert m.read(name) == 0.0
    assert eng.clock_offset_us(1) == 0.0
    assert eng.clock_offsets_us() == {1: 0.0}
    # knob off: a big fleet's metrics sampling must not pay per-peer
    # polls for a disabled feature — the gauge is not registered
    fab2 = LocalFabric(2)
    m2 = MetricsRegistry()
    CommObs(m2).register_engine_gauges(fab2.engine(0))
    assert name not in m2.sde.snapshot()


def test_offsets_land_in_trace_metadata():
    import json as _json

    import parsec_tpu
    from parsec_tpu.comm import LocalFabric, RemoteDepEngine

    fab = LocalFabric(2)
    eng = RemoteDepEngine(fab.engine(0))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, profile=True)
    try:
        ctx._stamp_profile_meta()
        doc = ctx.profile.to_chrome_trace()
        assert doc["metadata"]["rank"] == 0
        assert "trace_t0_ns" in doc["metadata"]
        offs = _json.loads(doc["metadata"]["clock_offsets_us"])
        assert offs == {"1": 0.0}
    finally:
        ctx.fini()
