"""Native (C++) runtime core: lock-free containers, hash table, zone-malloc.

The reference implements its entire hot host path in C (SURVEY.md §2.1);
this package is the equivalent layer for the TPU framework. On import it
lazily compiles ``_native.cpp`` with g++ and loads the extension. Pure-
Python fallbacks remain in ``parsec_tpu.core`` — set ``PARSEC_TPU_NATIVE=0``
to force them (useful for debugging).

Exports: ``native`` (the extension module or None) and ``available``.
"""
from __future__ import annotations

import importlib
import os
import sys

native = None
available = False

if os.environ.get("PARSEC_TPU_NATIVE", "1") != "0":
    try:
        # build() is mtime-cached: it recompiles only when _native.cpp is
        # newer than the .so. Running it BEFORE the import means a stale
        # prebuilt extension from an older checkout is refreshed rather
        # than silently loaded without the newer types.
        try:
            from . import build as _build
            _build.build()
        except Exception as build_exc:
            # fall through to importing a prebuilt .so, but say why the
            # rebuild failed: silently loading a stale extension hides
            # compile errors from native development
            print(f"parsec_tpu: native rebuild failed ({build_exc}); "
                  "importing prebuilt extension", file=sys.stderr)
        native = importlib.import_module("parsec_tpu.native._parsec_native")
        available = True
    except Exception as exc:  # pragma: no cover - toolchain-dependent
        print(f"parsec_tpu: native core unavailable ({exc}); "
              "using pure-Python containers", file=sys.stderr)
        native = None
        available = False
