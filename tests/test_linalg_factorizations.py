"""Tile QR / LU / GEMM PTG correctness (the widened DPLASMA slice).

References: DPLASMA's zgeqrf/zgetrf_nopiv/zgemm JDFs running on the
reference runtime; verification patterns follow the reference's check
programs (factor, then reconstruct and compare).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.ops import (dgeqrf_taskpool, dgetrf_nopiv_taskpool,
                            make_diag_dominant, pdgemm_taskpool)


def _run(ctx, tp):
    ctx.add_taskpool(tp)
    ctx.wait()
    assert tp.completed


# --------------------------------------------------------------------- #
# QR                                                                    #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("m,n,nb", [(96, 96, 32), (64, 64, 64),
                                    (128, 64, 32), (96, 128, 32)])
def test_dgeqrf_rtr_identity(ctx, m, n, nb):
    """R^T R == A^T A characterizes the QR triangle independently of the
    per-row sign convention (and of Q, which dgeqrf discards)."""
    rng = np.random.RandomState(7)
    M = (rng.rand(m, n) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(m, n, nb, nb, dtype=np.float32).from_numpy(M)
    _run(ctx, dgeqrf_taskpool(A))
    R = np.triu(A.to_numpy())
    np.testing.assert_allclose(
        R.T @ R, M.astype(np.float64).T @ M.astype(np.float64), atol=2e-3)


def test_dgeqrf_residual_gate(ctx):
    """The dgeqrf RESIDUAL gate (ISSUE 12 satellite): the second
    workload holds a strict relative residual bound at a bench-like
    sizing, mirroring bench.py's BENCH_MODE=geqrf check — the absolute
    tolerances above pass long after relative accuracy rots."""
    n, nb = 256, 64
    rng = np.random.RandomState(7)
    M = rng.rand(n, n).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    _run(ctx, dgeqrf_taskpool(A))
    R = np.triu(A.to_numpy()).astype(np.float64)
    G = M.astype(np.float64).T @ M.astype(np.float64)
    resid = np.abs(R.T @ R - G).max() / np.abs(G).max()
    assert resid < 1e-5, f"dgeqrf relative residual {resid:.2e}"


def test_dgeqrf_below_diagonal_zeroed(ctx):
    rng = np.random.RandomState(3)
    M = (rng.rand(96, 96) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32).from_numpy(M)
    _run(ctx, dgeqrf_taskpool(A))
    out = A.to_numpy()
    np.testing.assert_allclose(np.tril(out, -1), 0.0, atol=1e-5)


def test_dgeqrf_single_tile_matches_numpy(ctx):
    rng = np.random.RandomState(11)
    M = (rng.rand(48, 48) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(48, 48, 48, 48, dtype=np.float32).from_numpy(M)
    _run(ctx, dgeqrf_taskpool(A))
    Rref = np.linalg.qr(M.astype(np.float64))[1]
    np.testing.assert_allclose(np.abs(np.triu(A.to_numpy())),
                               np.abs(Rref), atol=2e-3)


def test_dgeqrf_partial_edge_tiles(ctx):
    """Ragged edges factor correctly (Q scratch shapes are computed per
    instance from the tile geometry)."""
    rng = np.random.RandomState(13)
    M = (rng.rand(100, 100) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(100, 100, 32, 32, dtype=np.float32).from_numpy(M)
    _run(ctx, dgeqrf_taskpool(A))
    R = np.triu(A.to_numpy())
    np.testing.assert_allclose(
        R.T @ R, M.astype(np.float64).T @ M.astype(np.float64), atol=2e-3)


def test_dgeqrf_rejects_nonsquare_diag_tiles(ctx):
    # trailing diagonal tile 32x26: not factorable panel-wise
    with pytest.raises(ValueError):
        dgeqrf_taskpool(TwoDimBlockCyclic(100, 90, 32, 32, dtype=np.float32))
    with pytest.raises(ValueError):
        dgeqrf_taskpool(TwoDimBlockCyclic(64, 64, 32, 16, dtype=np.float32))


# --------------------------------------------------------------------- #
# LU (no pivoting)                                                      #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("m,n,nb", [(96, 96, 32), (64, 64, 64), (100, 100, 32)])
def test_dgetrf_nopiv_reconstructs(ctx, m, n, nb):
    M = make_diag_dominant(m, n)
    A = TwoDimBlockCyclic(m, n, nb, nb, dtype=np.float32).from_numpy(M)
    _run(ctx, dgetrf_nopiv_taskpool(A))
    out = A.to_numpy().astype(np.float64)
    L = np.tril(out, -1) + np.eye(m, n)
    U = np.triu(out)
    np.testing.assert_allclose(L @ U, M.astype(np.float64),
                               rtol=0, atol=5e-3)


def test_dgetrf_nopiv_batched_dispatch_bit_exact():
    """Batched (unroll) device dispatch must be bit-exact vs per-task
    for the LU task classes too (ISSUE 5 acceptance)."""
    import parsec_tpu
    from parsec_tpu.utils.params import params

    M = make_diag_dominant(128, 128)

    def run(batch_max):
        with params.cmdline_override("device_batch_max", str(batch_max)), \
             params.cmdline_override("device_tpu_max", "1"):
            c = parsec_tpu.init(nb_cores=2)
            try:
                A = TwoDimBlockCyclic(128, 128, 32, 32,
                                      dtype=np.float32).from_numpy(M.copy())
                _run(c, dgetrf_nopiv_taskpool(A))
                return A.to_numpy()
            finally:
                c.fini()

    np.testing.assert_array_equal(run(16), run(1))


def test_dgetrf_nopiv_single_tile_matches_scipy(ctx):
    import scipy.linalg
    M = make_diag_dominant(40)
    A = TwoDimBlockCyclic(40, 40, 40, 40, dtype=np.float32).from_numpy(M)
    _run(ctx, dgetrf_nopiv_taskpool(A))
    out = A.to_numpy().astype(np.float64)
    # diagonally dominant => scipy's pivoted LU does not permute
    P, L, U = scipy.linalg.lu(M.astype(np.float64))
    np.testing.assert_allclose(P, np.eye(40))
    np.testing.assert_allclose(np.tril(out, -1), np.tril(L, -1), atol=1e-3)
    np.testing.assert_allclose(np.triu(out), U, atol=1e-3)


# --------------------------------------------------------------------- #
# GEMM                                                                  #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("m,n,k,nb", [(96, 64, 128, 32), (64, 64, 64, 64),
                                      (100, 60, 84, 32)])
def test_pdgemm_matches_numpy(ctx, m, n, k, nb):
    rng = np.random.RandomState(5)
    Am = (rng.rand(m, k) - 0.5).astype(np.float32)
    Bm = (rng.rand(k, n) - 0.5).astype(np.float32)
    Cm = (rng.rand(m, n) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(m, k, nb, nb, dtype=np.float32).from_numpy(Am)
    B = TwoDimBlockCyclic(k, n, nb, nb, dtype=np.float32).from_numpy(Bm)
    C = TwoDimBlockCyclic(m, n, nb, nb, dtype=np.float32).from_numpy(Cm)
    _run(ctx, pdgemm_taskpool(A, B, C, alpha=2.0, beta=-1.0))
    ref = 2.0 * (Am.astype(np.float64) @ Bm.astype(np.float64)) - Cm
    np.testing.assert_allclose(C.to_numpy(), ref, atol=2e-3)


def test_pdgemm_shape_mismatch_rejected(ctx):
    A = TwoDimBlockCyclic(64, 64, 32, 32)
    B = TwoDimBlockCyclic(32, 64, 32, 32)
    C = TwoDimBlockCyclic(64, 64, 32, 32)
    with pytest.raises(ValueError):
        pdgemm_taskpool(A, B, C)
    # grids conform but element extents don't (last k-tile 20 vs 26)
    A2 = TwoDimBlockCyclic(64, 84, 32, 32)
    B2 = TwoDimBlockCyclic(90, 64, 32, 32)
    with pytest.raises(ValueError):
        pdgemm_taskpool(A2, B2, C)


def test_dgetrf_rejects_nonsquare_diag_tiles(ctx):
    with pytest.raises(ValueError):
        dgetrf_nopiv_taskpool(TwoDimBlockCyclic(100, 90, 32, 32))
    with pytest.raises(ValueError):
        dgetrf_nopiv_taskpool(TwoDimBlockCyclic(64, 64, 32, 16))


def test_pdgemm_multirank_distributed():
    """SUMMA across 4 ranks over the in-process fabric: each rank owns only
    its block-cyclic tiles; A/B tiles reach consumers via READ_A/READ_B
    broadcast task edges (no cross-rank memory reads)."""
    from conftest import spmd
    from parsec_tpu.comm import RemoteDepEngine
    from parsec_tpu.ops import pdgemm_factory
    from parsec_tpu import ops as ops_module

    nb_ranks, P, Q = 4, 2, 2
    m, n, k, nb = 128, 96, 64, 32
    rng = np.random.RandomState(9)
    Am = (rng.rand(m, k) - 0.5).astype(np.float32)
    Bm = (rng.rand(k, n) - 0.5).astype(np.float32)
    Cm = (rng.rand(m, n) - 0.5).astype(np.float32)

    def rank_fn(rank, fabric):
        import parsec_tpu
        eng = RemoteDepEngine(fabric.engine(rank))
        c = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            def dist(lm, ln, M):
                d = TwoDimBlockCyclic(lm, ln, nb, nb, P=P, Q=Q,
                                      nodes=nb_ranks, rank=rank,
                                      dtype=np.float32)
                # populate only locally-owned tiles (true distribution)
                for i in range(d.mt):
                    for j in range(d.nt):
                        if d.rank_of(i, j) == rank:
                            np.copyto(
                                d.tile(i, j),
                                M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
                return d
            A, B, C = dist(m, k, Am), dist(k, n, Bm), dist(m, n, Cm)
            A.name, B.name, C.name = "descA", "descB", "descC"
            tp = pdgemm_factory().new(
                descA=A, descB=B, descC=C, MT=C.mt, NT=C.nt, KT=A.nt,
                ALPHA=1.0, BETA=1.0, rank=rank, nb_ranks=nb_ranks)
            tp.global_env["ops"] = ops_module
            c.add_taskpool(tp)
            c.wait()
            local = {}
            for i in range(C.mt):
                for j in range(C.nt):
                    if C.rank_of(i, j) == rank:
                        local[(i, j)] = np.array(C.tile(i, j))
            return local
        finally:
            c.fini()

    out, _fabric = spmd(nb_ranks, rank_fn)
    ref = Am.astype(np.float64) @ Bm.astype(np.float64) + Cm
    got = np.zeros((m, n))
    for local in out:
        for (i, j), tile in local.items():
            got[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb] = tile
    np.testing.assert_allclose(got, ref, atol=2e-3)


# --------------------------------------------------------------------- #
# triangular solves + dposv                                             #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("n,nrhs,nb", [(96, 32, 32), (64, 64, 64),
                                       (128, 96, 32)])
def test_dposv_solves(ctx, n, nrhs, nb):
    from parsec_tpu.ops import dposv, make_spd
    M = make_spd(n)
    rng = np.random.RandomState(1)
    Bm = (rng.rand(n, nrhs) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    B = TwoDimBlockCyclic(n, nrhs, nb, nb, dtype=np.float32).from_numpy(Bm)
    dposv(ctx, A, B)
    ref = np.linalg.solve(M.astype(np.float64), Bm.astype(np.float64))
    np.testing.assert_allclose(B.to_numpy(), ref, atol=5e-3)


def test_dtrsm_forward_matches_scipy(ctx):
    import scipy.linalg
    from parsec_tpu.ops import dtrsm_lower_taskpool
    rng = np.random.RandomState(2)
    Lm = np.tril(rng.rand(96, 96).astype(np.float32)) + 4 * np.eye(96,
                                                                   dtype=np.float32)
    Bm = (rng.rand(96, 64) - 0.5).astype(np.float32)
    L = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32).from_numpy(Lm)
    B = TwoDimBlockCyclic(96, 64, 32, 32, dtype=np.float32).from_numpy(Bm)
    _run(ctx, dtrsm_lower_taskpool(L, B))
    ref = scipy.linalg.solve_triangular(Lm.astype(np.float64),
                                        Bm.astype(np.float64), lower=True)
    np.testing.assert_allclose(B.to_numpy(), ref, atol=2e-3)


def test_dtrsm_backward_matches_scipy(ctx):
    import scipy.linalg
    from parsec_tpu.ops import dtrsm_lower_trans_taskpool
    rng = np.random.RandomState(3)
    Lm = np.tril(rng.rand(96, 96).astype(np.float32)) + 4 * np.eye(96,
                                                                   dtype=np.float32)
    Bm = (rng.rand(96, 32) - 0.5).astype(np.float32)
    L = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32).from_numpy(Lm)
    B = TwoDimBlockCyclic(96, 32, 32, 32, dtype=np.float32).from_numpy(Bm)
    _run(ctx, dtrsm_lower_trans_taskpool(L, B))
    ref = scipy.linalg.solve_triangular(Lm.astype(np.float64).T,
                                        Bm.astype(np.float64), lower=False)
    np.testing.assert_allclose(B.to_numpy(), ref, atol=2e-3)


def test_dtrsm_shape_mismatch(ctx):
    from parsec_tpu.ops import dtrsm_lower_taskpool
    with pytest.raises(ValueError):
        dtrsm_lower_taskpool(TwoDimBlockCyclic(64, 96, 32, 32),
                             TwoDimBlockCyclic(64, 32, 32, 32))


def test_dposv_multirank_distributed():
    """dposv across 4 ranks: the factorization writes affinity tiles only
    and the solves' L tiles travel via RDIAG/RPANEL broadcast reader
    edges — no cross-rank memory reads."""
    from conftest import spmd
    from parsec_tpu.comm import RemoteDepEngine
    from parsec_tpu.ops import dposv, make_spd

    nb_ranks, n, nrhs, nb = 4, 128, 32, 32
    M = make_spd(n)
    rng = np.random.RandomState(4)
    Bm = (rng.rand(n, nrhs) - 0.5).astype(np.float32)

    def rank_fn(rank, fabric):
        import parsec_tpu
        eng = RemoteDepEngine(fabric.engine(rank))
        c = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            def dist(lm, ln, src, P, Q):
                d = TwoDimBlockCyclic(lm, ln, nb, nb, P=P, Q=Q,
                                      nodes=nb_ranks, rank=rank,
                                      dtype=np.float32)
                for (i, j) in d.local_tiles():
                    np.copyto(d.tile(i, j),
                              src[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
                return d
            A = dist(n, n, M, 2, 2)
            B = dist(n, nrhs, Bm, 4, 1)
            A.name, B.name = "descA", "descB"
            dposv(c, A, B, rank=rank, nb_ranks=nb_ranks)
            return {(i, j): np.array(B.tile(i, j))
                    for (i, j) in B.local_tiles()}
        finally:
            c.fini()

    results, fabric = spmd(nb_ranks, rank_fn)
    got = np.zeros((n, nrhs))
    for local in results:
        for (i, j), t in local.items():
            got[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb] = t
    ref = np.linalg.solve(M.astype(np.float64), Bm.astype(np.float64))
    np.testing.assert_allclose(got, ref, atol=5e-3)
    assert fabric.msg_count > 0


@pytest.mark.parametrize("transa,transb,m,n,k,nb", [
    ("t", "n", 96, 64, 80, 16), ("n", "t", 96, 64, 80, 16),
    ("t", "t", 96, 64, 80, 16),
    # ragged edge tiles under transposition
    ("t", "n", 100, 60, 84, 32), ("n", "t", 100, 60, 84, 32),
    ("t", "t", 100, 60, 84, 32)])
def test_pdgemm_transposes(ctx, transa, transb, m, n, k, nb):
    rng = np.random.RandomState(6)
    Am = (rng.rand(*((k, m) if transa == "t" else (m, k))) - 0.5).astype(
        np.float32)
    Bm = (rng.rand(*((n, k) if transb == "t" else (k, n))) - 0.5).astype(
        np.float32)
    Cm = (rng.rand(m, n) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(*Am.shape, nb, nb, dtype=np.float32).from_numpy(Am)
    B = TwoDimBlockCyclic(*Bm.shape, nb, nb, dtype=np.float32).from_numpy(Bm)
    C = TwoDimBlockCyclic(m, n, nb, nb, dtype=np.float32).from_numpy(Cm)
    _run(ctx, pdgemm_taskpool(A, B, C, alpha=1.5, beta=0.5,
                              transa=transa, transb=transb))
    opA = Am.T if transa == "t" else Am
    opB = Bm.T if transb == "t" else Bm
    ref = 1.5 * (opA.astype(np.float64) @ opB.astype(np.float64)) + 0.5 * Cm
    np.testing.assert_allclose(C.to_numpy(), ref, atol=2e-3)


def test_pdgemm_bad_trans_rejected(ctx):
    A = TwoDimBlockCyclic(64, 64, 32, 32)
    with pytest.raises(ValueError, match="transa"):
        pdgemm_taskpool(A, A, A, transa="x")


def _spmd_factor(taskpool_factory, M, n, nb, nb_ranks=4):
    """Scatter M block-cyclically over nb_ranks, run the factorization
    SPMD over the in-process fabric, gather the local tiles back."""
    from conftest import spmd
    from parsec_tpu.comm import RemoteDepEngine

    # largest P with P | nb_ranks and P <= sqrt: a valid PxQ grid for any
    # rank count (4 -> 2x2, 2 -> 1x2, 6 -> 2x3)
    P = max(p for p in range(1, int(nb_ranks ** 0.5) + 1) if nb_ranks % p == 0)
    Q = nb_ranks // P

    def rank_fn(rank, fabric):
        import parsec_tpu
        eng = RemoteDepEngine(fabric.engine(rank))
        c = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            A = TwoDimBlockCyclic(n, n, nb, nb, P=P, Q=Q, nodes=nb_ranks,
                                  rank=rank, dtype=np.float32)
            A.name = "descA"
            for (i, j) in A.local_tiles():
                np.copyto(A.tile(i, j),
                          M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
            tp = taskpool_factory(A, rank=rank, nb_ranks=nb_ranks)
            c.add_taskpool(tp)
            c.wait()
            return {(i, j): np.array(A.tile(i, j))
                    for (i, j) in A.local_tiles()}
        finally:
            c.fini()

    results, _ = spmd(nb_ranks, rank_fn)
    got = np.zeros((n, n), np.float64)
    for local in results:
        for (i, j), t in local.items():
            got[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb] = t
    return got


def test_dgeqrf_multirank_distributed():
    """QR across 4 ranks. The R triangle returns to descA(k,k) from the
    END of each TSQRT chain — a cross-rank memory writeback."""
    rng = np.random.RandomState(21)
    M = (rng.rand(128, 128) - 0.5).astype(np.float32)
    got = _spmd_factor(dgeqrf_taskpool, M, 128, 32)
    R = np.triu(got)
    ref = M.astype(np.float64).T @ M.astype(np.float64)
    np.testing.assert_allclose(R.T @ R, ref, atol=2e-3)


def test_dgetrf_multirank_distributed():
    """LU across 4 ranks (all writes are affinity-local; panels travel
    task edges)."""
    n = 128
    M = make_diag_dominant(n)
    got = _spmd_factor(dgetrf_nopiv_taskpool, M, n, 32)
    L = np.tril(got, -1) + np.eye(n)
    U = np.triu(got)
    np.testing.assert_allclose(L @ U, M.astype(np.float64), atol=5e-3)


def test_dgetrf_partial_pivoting():
    """Pivoted blocked LU (ops.dgetrf): A[piv] == L U for a general
    (non-diagonally-dominant) matrix the nopiv variant cannot factor
    stably."""
    from parsec_tpu.ops import dgetrf

    n, nb = 192, 64
    rng = np.random.RandomState(11)
    A = (rng.rand(n, n) - 0.5).astype(np.float32)  # no dominance
    LU, piv = dgetrf(A, nb=nb)
    LU = np.asarray(LU)
    L = np.tril(LU, -1) + np.eye(n, dtype=np.float32)
    U = np.triu(LU)
    assert np.linalg.norm(A[np.asarray(piv)] - L @ U) \
        / np.linalg.norm(A) < 1e-5
    # pivoting actually happened (a random matrix always needs swaps)
    assert not np.array_equal(np.asarray(piv), np.arange(n))


def test_dgetrf_rectangular():
    from parsec_tpu.ops import dgetrf

    m, n, nb = 160, 96, 64
    rng = np.random.RandomState(12)
    A = (rng.rand(m, n) - 0.5).astype(np.float32)
    LU, piv = dgetrf(A, nb=nb)
    LU = np.asarray(LU)
    L = np.tril(LU, -1)[:, :n] + np.eye(m, n, dtype=np.float32)
    U = np.triu(LU)[:n]
    assert np.linalg.norm(A[np.asarray(piv)] - L @ U) \
        / np.linalg.norm(A) < 1e-5


def test_dgetrf_wide():
    from parsec_tpu.ops import dgetrf

    m, n, nb = 96, 160, 64
    rng = np.random.RandomState(13)
    A = (rng.rand(m, n) - 0.5).astype(np.float32)
    LU, piv = dgetrf(A, nb=nb)
    LU = np.asarray(LU)
    L = np.tril(LU, -1)[:, :m] + np.eye(m, dtype=np.float32)
    U = np.triu(LU)
    assert np.linalg.norm(A[np.asarray(piv)] - L @ U) \
        / np.linalg.norm(A) < 1e-5


# --------------------------------------------------------------------- #
# inverses / solves (the potri family + gesv)                           #
# --------------------------------------------------------------------- #
def test_dtrtri_inverse():
    from parsec_tpu.ops import dtrtri

    n = 96
    rng = np.random.RandomState(21)
    L = np.tril(rng.rand(n, n).astype(np.float32)) + 2 * np.eye(
        n, dtype=np.float32)
    Linv = np.asarray(dtrtri(L, lower=True))
    np.testing.assert_allclose(Linv @ L, np.eye(n), atol=2e-4)
    U = L.T.copy()
    Uinv = np.asarray(dtrtri(U, lower=False))
    np.testing.assert_allclose(U @ Uinv, np.eye(n), atol=2e-4)


def test_dpotri_spd_inverse_from_cholesky(ctx):
    """potrf (PTG) then potri: the full DPLASMA zpotri pipeline."""
    from parsec_tpu.ops import dpotri, dpotrf_taskpool, make_spd

    n, nb = 128, 64
    M = make_spd(n, seed=22)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    _run(ctx, dpotrf_taskpool(A))
    L = np.tril(A.to_numpy()).astype(np.float32)
    Ainv = np.asarray(dpotri(L))
    np.testing.assert_allclose(Ainv @ M, np.eye(n), atol=5e-3)


def test_dgesv_general_solve():
    from parsec_tpu.ops import dgesv

    n, nrhs = 160, 8
    rng = np.random.RandomState(23)
    A = (rng.rand(n, n) - 0.5).astype(np.float32)
    B = rng.rand(n, nrhs).astype(np.float32)
    X = np.asarray(dgesv(A, B, nb=64))
    ref = np.linalg.solve(A.astype(np.float64), B.astype(np.float64))
    assert np.abs(X - ref).max() < 5e-2
