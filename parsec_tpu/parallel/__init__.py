"""parallel subpackage."""
