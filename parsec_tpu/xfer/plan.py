"""Redistribution planner: compile a source→target distribution pair
into a deterministic schedule of portable collective steps.

The DTD path in :func:`~parsec_tpu.collections.redistribute.redistribute`
moves a whole-matrix same-grid reshard as one task per target tile —
at the wire that is a per-tile GET/activation storm: every cross-rank
tile pays its own control round-trip and pickle envelope.  The
reference's own redistribution literature (arxiv 2112.01075) plans the
same movement as collectives: the (src, dst) pair set IS an all-to-all
over the member set, so this module compiles the tile walk into
alltoall-style ROUNDS (round r carries every pair with
``(dst - src) % P == r`` — each rank sends to at most one peer per
round and receives from at most one), coalescing all same-(src, dst)
tiles into ONE transfer each.  The schedule is a pure function of the
two distributions and the tile set — byte-identical across runs and
ranks — and :func:`RedistPlan.digest` is exchanged and asserted before
any data moves (the PR 2 lane-config-digest idiom), so a divergent
plan fails loudly instead of deadlocking.

Execution rides whichever transport the link negotiated: the session
TCP wire by default (lossless — planner traffic is never quantized, so
reshards stay bit-identical and flap replay reproduces the exact
bytes), or the device plane (``xfer_dplane`` + HELLO ``"dp"``) for the
bulk payload with only the descriptor/ack control half on the session
envelope.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..comm.engine import TAG_USER_BASE

# Every reserved below-base slot (-1 barrier, -2/-3 device plane,
# -4/-5 wave) is taken and -6 would collide with TAG_SERVE — the
# planner claims a high user tag instead, far above the small literals
# (100/101) the in-tree harnesses use.
TAG_REDIST = TAG_USER_BASE + 111

# concurrency contract checked by tools/lock_check (LCK3xx)
_GUARDED_BY = {
    "_Inbox.msgs": "lock",
}


class Transfer(NamedTuple):
    """One coalesced move: every ``tiles`` coord rides a single wire
    transfer from ``src`` to ``dst`` (flattened, concatenated in the
    listed order — ragged edge tiles coalesce fine)."""
    src: int
    dst: int
    tiles: Tuple[Tuple[int, int], ...]


class RedistPlan(NamedTuple):
    nb_ranks: int
    local: Tuple[Tuple[int, int], ...]           # src == dst: host copy
    rounds: Tuple[Tuple[Transfer, ...], ...]     # alltoall-style rounds

    @property
    def n_rounds(self) -> int:
        return len(self.rounds)

    @property
    def n_transfers(self) -> int:
        return sum(len(r) for r in self.rounds)

    @property
    def tile_moves(self) -> int:
        """Cross-rank tile count — what the GET storm would pay one
        transfer each for."""
        return sum(len(t.tiles) for r in self.rounds for t in r)

    def digest(self) -> str:
        return hashlib.sha1(repr(self).encode()).hexdigest()


def build_plan(source: Any, target: Any,
               tiles: Optional[Sequence[Tuple[int, int]]] = None
               ) -> RedistPlan:
    """Deterministic schedule for a whole-matrix same-grid reshard:
    walk the (sorted) tile set once, bucket cross-rank tiles by their
    (source owner, target owner) pair, and lay the pairs out in
    alltoall rounds.  Pure function of the distributions — no rank or
    runtime state — so every SPMD caller builds the identical plan."""
    coords = sorted(tiles) if tiles is not None else sorted(target.tiles())
    nb = max(int(getattr(source, "nodes", 1)),
             int(getattr(target, "nodes", 1)), 1)
    local: List[Tuple[int, int]] = []
    pairs: Dict[Tuple[int, int], List[Tuple[int, int]]] = {}
    for (m, n) in coords:
        s = source.rank_of(m, n)
        d = target.rank_of(m, n)
        if s == d:
            local.append((m, n))
        else:
            pairs.setdefault((s, d), []).append((m, n))
    rounds: List[Tuple[Transfer, ...]] = []
    for r in range(1, nb):
        rnd = tuple(Transfer(s, d, tuple(ts))
                    for (s, d), ts in sorted(pairs.items())
                    if (d - s) % nb == r)
        if rnd:
            rounds.append(rnd)
    return RedistPlan(nb, tuple(local), tuple(rounds))


# --------------------------------------------------------------------- #
# execution
# --------------------------------------------------------------------- #
class _Inbox:
    """Per-engine landing zone for TAG_REDIST messages.  Keyed by
    (src, seq, kind, pair) so concurrent/successive redistributions on
    one engine never cross-talk (``seq`` is an SPMD-consistent per-call
    counter).  Acks are handled inline (they release device-plane
    parks), everything else parks here until the executor collects it."""

    def __init__(self, ce: Any) -> None:
        self.ce = ce
        self.msgs: Dict[Tuple, Any] = {}
        self.lock = threading.Lock()

    def on_msg(self, src: int, payload: Dict) -> None:
        kind = payload.get("kind")
        if kind == "ack":
            plane = getattr(self.ce, "device_plane", None)
            if plane is not None:
                plane.release(payload["uuid"])
            return
        key = (src, payload["seq"], kind, payload.get("pair"))
        with self.lock:
            self.msgs[key] = payload

    def take(self, key: Tuple) -> Optional[Dict]:
        with self.lock:
            return self.msgs.pop(key, None)


def _inbox_of(ce: Any) -> _Inbox:
    box = getattr(ce, "_redist_inbox", None)
    if box is None:
        box = _Inbox(ce)
        ce._redist_inbox = box
        ce.tag_register(TAG_REDIST, box.on_msg)
    return box


def _wait_take(ce: Any, box: _Inbox, key: Tuple, timeout: float) -> Dict:
    t0 = time.monotonic()
    while True:
        msg = box.take(key)
        if msg is not None:
            return msg
        ce.progress()
        if time.monotonic() - t0 > timeout:
            raise TimeoutError(
                f"rank {ce.rank}: no redistribution message {key} within "
                f"{timeout}s")
        time.sleep(0.0005)


class PlannedRedistribution:
    """What :func:`run_redistribution` returns — duck-types the slice
    of the DTD taskpool surface redistribute() callers consume
    (``redist_bytes``; ``wait()`` is a no-op: execution completed
    synchronously), plus the planner observables the gate asserts."""

    def __init__(self, plan: RedistPlan, redist_bytes: int) -> None:
        self.plan = plan
        self.redist_bytes = redist_bytes        # cross-rank payload bytes
        self.redist_rounds = plan.n_rounds
        self.redist_transfers = plan.n_transfers
        self.redist_tile_moves = plan.tile_moves
        self.plan_digest = plan.digest()
        self.wire_lossless = True

    def wait(self) -> None:
        pass


def _pack(source: Any, tiles: Sequence[Tuple[int, int]]) -> np.ndarray:
    return np.concatenate(
        [np.ascontiguousarray(source.tile(m, n)).ravel()
         for (m, n) in tiles])


def _unpack(target: Any, tiles: Sequence[Tuple[int, int]],
            flat: np.ndarray) -> None:
    off = 0
    for (m, n) in tiles:
        tm, tn = target.tile_shape(m, n)
        target.set_tile(m, n, flat[off:off + tm * tn].reshape(tm, tn))
        off += tm * tn


def run_redistribution(source: Any, target: Any, ce: Any,
                       tiles: Optional[Sequence[Tuple[int, int]]] = None,
                       timeout: float = 120.0) -> PlannedRedistribution:
    """SPMD-execute the planned reshard over ``ce`` (call on every
    rank).  Each round: enqueue every owned outgoing transfer (sends
    never block), then collect the round's incoming transfers — so no
    rank ever waits on a peer that is itself waiting.  The digest
    handshake up front turns any cross-rank plan divergence into an
    immediate error instead of a wedged collective."""
    plan = build_plan(source, target, tiles)
    me, nb = ce.rank, ce.nb_ranks
    seq = getattr(ce, "_redist_seq_no", 0)
    ce._redist_seq_no = seq + 1
    box = _inbox_of(ce)
    dig = plan.digest()
    for r in range(nb):
        if r != me:
            ce.send_am(r, TAG_REDIST,
                       {"seq": seq, "kind": "cfg", "digest": dig})
    for r in range(nb):
        if r == me:
            continue
        msg = _wait_take(ce, box, (r, seq, "cfg", None), timeout)
        if msg["digest"] != dig:
            raise RuntimeError(
                f"rank {me}: redistribution plan diverges from rank {r} "
                f"({dig[:12]} != {msg['digest'][:12]}) — source/target "
                f"distributions are not SPMD-consistent")

    itemsize = np.dtype(target.dtype).itemsize
    redist_bytes = 0
    for rnd in plan.rounds:
        for t in rnd:
            for (m, n) in t.tiles:
                tm, tn = target.tile_shape(m, n)
                redist_bytes += tm * tn * itemsize

    for (m, n) in plan.local:
        if target.rank_of(m, n) == me:
            target.set_tile(m, n, source.tile(m, n))

    plane = getattr(ce, "device_plane", None)
    dp_to = getattr(ce, "dplane_to", None)
    my_parks: List[int] = []
    for rnd in plan.rounds:
        for t in rnd:
            if t.src != me:
                continue
            payload = _pack(source, t.tiles)
            if (plane is not None and dp_to is not None and dp_to(t.dst)):
                import jax
                # ship the RAW BYTES (uint8 view): device_put of an f64
                # payload under default-x64-off jax would silently land
                # f32 — reshards must stay bit-identical for any dtype,
                # independent of the x64 mode
                wire = payload.view(np.uint8)
                desc = plane.register(jax.device_put(wire, plane.device))
                my_parks.append(desc[0])
                ce.send_am(t.dst, TAG_REDIST,
                           {"seq": seq, "kind": "dp", "pair": t[:2],
                            "desc": desc, "dt": str(payload.dtype)})
            else:
                ce.send_am(t.dst, TAG_REDIST,
                           {"seq": seq, "kind": "data", "pair": t[:2],
                            "data": payload})
        for t in rnd:
            if t.dst != me:
                continue
            key_dp = (t.src, seq, "dp", t[:2])
            key_data = (t.src, seq, "data", t[:2])
            t0 = time.monotonic()
            while True:
                msg = box.take(key_dp) or box.take(key_data)
                if msg is not None:
                    break
                ce.progress()
                if time.monotonic() - t0 > timeout:
                    raise TimeoutError(
                        f"rank {me}: transfer {t.src}->{t.dst} of round "
                        f"never arrived within {timeout}s")
                time.sleep(0.0005)
            if msg["kind"] == "dp":
                uuid, shape, dt = msg["desc"]
                flat = np.asarray(plane.pull(t.src, uuid, shape, dt)) \
                    .view(np.dtype(msg["dt"]))
                ce.send_am(t.src, TAG_REDIST,
                           {"seq": seq, "kind": "ack", "uuid": uuid})
            else:
                flat = np.asarray(msg["data"])
            _unpack(target, t.tiles, flat.ravel())

    # drain our consumers' acks so no park outlives the call (the park
    # keep-alive pins producer memory until the pull is confirmed)
    if my_parks:
        t0 = time.monotonic()
        while any(plane.is_parked(u) for u in my_parks):
            ce.progress()
            if time.monotonic() - t0 > timeout:
                from ..utils import logging as plog
                plog.debug.verbose(
                    1, "rank %d: %d device-plane park(s) unreleased after "
                    "%.0fs", me, sum(plane.is_parked(u) for u in my_parks),
                    timeout)
                break
            time.sleep(0.0005)

    stats = getattr(ce, "dplane_stats", None)
    if stats is not None:
        stats["redist_rounds"] += plan.n_rounds
    return PlannedRedistribution(plan, redist_bytes)
