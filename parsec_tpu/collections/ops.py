"""Collection-wide operations as PTG task graphs.

Reference behavior: elementwise ``apply`` over the tiles of a (possibly
triangular) matrix (ref: parsec/data_dist/matrix/apply.jdf), binary-tree
reductions by column / row / whole matrix (ref:
parsec/data_dist/matrix/reduce_col.jdf:31-70, reduce_row.jdf, reduce.jdf),
one-datum broadcast to all consumers (ref:
parsec/data_dist/matrix/broadcast.jdf), and the generic two-collection tile
map (ref: parsec/data_dist/matrix/map_operator.c).

All are expressed as JDF task graphs executed by the PTG runtime, so
multi-rank runs inherit the remote-dep machinery (chain/binomial broadcast
topologies for fan-out edges) for free — exactly how the reference builds
its collective operations out of ordinary task graphs rather than runtime
primitives (SURVEY.md §2.8: "reductions are expressed as task graphs").

The reduction trees handle non-power-of-two tile counts: a node with no
right child passes its value through unchanged (the reference's reduce
JDFs assume power-of-two extents; the guard-based pass-through here lifts
that restriction).
"""
from __future__ import annotations

import math

import numpy as np
from typing import Any, Callable, Optional

from ..dsl import ptg
from .matrix import TiledMatrix, TwoDimBlockCyclic

__all__ = ["apply", "apply_taskpool", "map_operator", "map_operator_taskpool",
           "reduce_col", "reduce_row", "reduce_all",
           "reduce_col_taskpool", "reduce_row_taskpool", "reduce_all_taskpool",
           "broadcast", "broadcast_taskpool", "band_to_rect_taskpool",
           "allreduce", "allreduce_taskpool"]

# --------------------------------------------------------------------------
# apply: elementwise unary operation over (triangular) tile sets
# ref: apply.jdf APPLY_L / APPLY_U / APPLY_DIAG task classes
# --------------------------------------------------------------------------

_APPLY_JDF = """
descA [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
LOWER [ type="int" ]
UPPER [ type="int" ]

APPLY_L(m, n)

m = 1 .. (0 if UPPER else MT-1)
n = 0 .. (m-1 if m < NT else NT-1)

: descA( m, n )

RW A <- descA( m, n )
     -> descA( m, n )

BODY
{
    A = operation(A, "full", m, n, op_args)
}
END

APPLY_U(m, n)

m = 0 .. MT-1
n = m+1 .. (0 if LOWER else NT-1)

: descA( m, n )

RW A <- descA( m, n )
     -> descA( m, n )

BODY
{
    A = operation(A, "full", m, n, op_args)
}
END

APPLY_DIAG(k)

k = 0 .. (MT-1 if MT < NT else NT-1)

: descA( k, k )

RW A <- descA( k, k )
     -> descA( k, k )

BODY
{
    A = operation(A, uplo_region, k, k, op_args)
}
END
"""

_apply_factory: Optional[Any] = None


def apply_taskpool(A: TiledMatrix, operation: Callable, uplo: str = "full",
                   op_args: Any = None, rank: int = 0, nb_ranks: int = 1):
    """``operation(tile, region, m, n, op_args) -> new tile`` applied to
    every stored tile of ``A``; ``uplo`` restricts to a triangle (incl. the
    diagonal, which gets ``region=uplo`` so the op can mask)."""
    global _apply_factory
    assert uplo in ("full", "lower", "upper")
    if _apply_factory is None:
        _apply_factory = ptg.compile_jdf(_APPLY_JDF, name="apply")
    tp = _apply_factory.new(descA=A, MT=A.mt, NT=A.nt,
                            LOWER=int(uplo == "lower"),
                            UPPER=int(uplo == "upper"),
                            rank=rank, nb_ranks=nb_ranks)
    tp.global_env["operation"] = operation
    tp.global_env["op_args"] = op_args
    tp.global_env["uplo_region"] = uplo
    return tp


def apply(context, A: TiledMatrix, operation: Callable, uplo: str = "full",
          op_args: Any = None) -> None:
    context.add_taskpool(apply_taskpool(A, operation, uplo, op_args))
    context.wait()


# --------------------------------------------------------------------------
# map_operator: generic two-collection tile map  (ref: map_operator.c)
# --------------------------------------------------------------------------

_MAP_JDF = """
src [ type="collection" ]
dest [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]

MAP(m, n)

m = 0 .. MT-1
n = 0 .. NT-1

: dest( m, n )

READ S <- src( m, n )
RW   D <- dest( m, n )
       -> dest( m, n )

BODY
{
    D = operation(S, D, m, n, op_args)
}
END
"""

_map_factory: Optional[Any] = None


def map_operator_taskpool(src: TiledMatrix, dest: TiledMatrix,
                          operation: Callable, op_args: Any = None,
                          rank: int = 0, nb_ranks: int = 1):
    """``operation(src_tile, dest_tile, m, n, op_args) -> new dest tile``
    over the common tile grid of ``src`` and ``dest``."""
    global _map_factory
    if _map_factory is None:
        _map_factory = ptg.compile_jdf(_MAP_JDF, name="map_operator")
    mt, nt = min(src.mt, dest.mt), min(src.nt, dest.nt)
    tp = _map_factory.new(src=src, dest=dest, MT=mt, NT=nt,
                          rank=rank, nb_ranks=nb_ranks)
    tp.global_env["operation"] = operation
    tp.global_env["op_args"] = op_args
    return tp


def map_operator(context, src: TiledMatrix, dest: TiledMatrix,
                 operation: Callable, op_args: Any = None) -> None:
    context.add_taskpool(map_operator_taskpool(src, dest, operation, op_args))
    context.wait()


# --------------------------------------------------------------------------
# tree reductions  (ref: reduce_col.jdf / reduce_row.jdf / reduce.jdf)
#
# One task class; leaf loads fold into level 1. Node (level, index) combines
# children (2i, 2i+1) of level-1; a missing right child passes through.
# --------------------------------------------------------------------------

# Leaf tasks copy the source tile into a NEW scratch buffer before the
# fold (ref: the reduce_in_col input task class, reduce_col.jdf:36-43) so
# the reduction never mutates the source collection: an RW flow sourced
# straight from memory is in-place on that tile (dpotrf-style semantics).
# {dt} is the element dtype literal; factories are cached per dtype.

_REDUCE_COL_JDF = """
descA [ type="collection" ]
dest [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
MB [ type="int" ]
NB [ type="int" ]
DEPTH [ type="int" ]

LEAF(i, col)

i = 0 .. MT-1
col = 0 .. NT-1

: descA( i, col )

READ  S <- descA( i, col )
WRITE R <- NEW  [shape="MB x NB" dtype="{dt}"]
        -> (i % 2 == 0) ? Rtop LEAF_REDUCE( 1, i >> 1, col )
        -> (i % 2 == 1) ? Rbottom LEAF_REDUCE( 1, i >> 1, col )

BODY
{{
    R = S
}}
END

LEAF_REDUCE(level, index, col)

level = 1 .. DEPTH
index = 0 .. ((MT + (1 << level) - 1) >> level) - 1
col = 0 .. NT-1
nprev = (MT + (1 << (level-1)) - 1) >> (level-1)
hasr = 1 if 2*index+1 < nprev else 0

: descA( index << level, col )

RW Rtop <- (level == 1) ? R LEAF( 2*index, col ) : Rtop LEAF_REDUCE( level-1, 2*index, col )
        -> (level < DEPTH and index % 2 == 0) ? Rtop LEAF_REDUCE( level+1, index >> 1, col )
        -> (level < DEPTH and index % 2 == 1) ? Rbottom LEAF_REDUCE( level+1, index >> 1, col )
        -> (level == DEPTH) ? dest( 0, col )

READ Rbottom <- (hasr and level == 1) ? R LEAF( 2*index+1, col )
             <- (hasr and level > 1) ? Rtop LEAF_REDUCE( level-1, 2*index+1, col )

BODY
{{
    Rtop = operation(Rtop, Rbottom, op_args) if hasr else Rtop
}}
END
"""

_REDUCE_ROW_JDF = """
descA [ type="collection" ]
dest [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
MB [ type="int" ]
NB [ type="int" ]
DEPTH [ type="int" ]

LEAF(i, row)

i = 0 .. NT-1
row = 0 .. MT-1

: descA( row, i )

READ  S <- descA( row, i )
WRITE R <- NEW  [shape="MB x NB" dtype="{dt}"]
        -> (i % 2 == 0) ? Rtop LEAF_REDUCE( 1, i >> 1, row )
        -> (i % 2 == 1) ? Rbottom LEAF_REDUCE( 1, i >> 1, row )

BODY
{{
    R = S
}}
END

LEAF_REDUCE(level, index, row)

level = 1 .. DEPTH
index = 0 .. ((NT + (1 << level) - 1) >> level) - 1
row = 0 .. MT-1
nprev = (NT + (1 << (level-1)) - 1) >> (level-1)
hasr = 1 if 2*index+1 < nprev else 0

: descA( row, index << level )

RW Rtop <- (level == 1) ? R LEAF( 2*index, row ) : Rtop LEAF_REDUCE( level-1, 2*index, row )
        -> (level < DEPTH and index % 2 == 0) ? Rtop LEAF_REDUCE( level+1, index >> 1, row )
        -> (level < DEPTH and index % 2 == 1) ? Rbottom LEAF_REDUCE( level+1, index >> 1, row )
        -> (level == DEPTH) ? dest( row, 0 )

READ Rbottom <- (hasr and level == 1) ? R LEAF( 2*index+1, row )
             <- (hasr and level > 1) ? Rtop LEAF_REDUCE( level-1, 2*index+1, row )

BODY
{{
    Rtop = operation(Rtop, Rbottom, op_args) if hasr else Rtop
}}
END
"""

_REDUCE_ALL_JDF = """
descA [ type="collection" ]
dest [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
MB [ type="int" ]
NB [ type="int" ]
NLEAF [ type="int" ]
DEPTH [ type="int" ]

LEAF(t)

t = 0 .. NLEAF-1

: descA( int(t / NT), t % NT )

READ  S <- descA( int(t / NT), t % NT )
WRITE R <- NEW  [shape="MB x NB" dtype="{dt}"]
        -> (t % 2 == 0) ? Rtop LEAF_REDUCE( 1, t >> 1 )
        -> (t % 2 == 1) ? Rbottom LEAF_REDUCE( 1, t >> 1 )

BODY
{{
    R = S
}}
END

LEAF_REDUCE(level, index)

level = 1 .. DEPTH
index = 0 .. ((NLEAF + (1 << level) - 1) >> level) - 1
nprev = (NLEAF + (1 << (level-1)) - 1) >> (level-1)
hasr = 1 if 2*index+1 < nprev else 0

: descA( int((index << level) / NT), (index << level) % NT )

RW Rtop <- (level == 1) ? R LEAF( 2*index ) : Rtop LEAF_REDUCE( level-1, 2*index )
        -> (level < DEPTH and index % 2 == 0) ? Rtop LEAF_REDUCE( level+1, index >> 1 )
        -> (level < DEPTH and index % 2 == 1) ? Rbottom LEAF_REDUCE( level+1, index >> 1 )
        -> (level == DEPTH) ? dest( 0, 0 )

READ Rbottom <- (hasr and level == 1) ? R LEAF( 2*index+1 )
             <- (hasr and level > 1) ? Rtop LEAF_REDUCE( level-1, 2*index+1 )

BODY
{{
    Rtop = operation(Rtop, Rbottom, op_args) if hasr else Rtop
}}
END
"""

_reduce_factories: dict = {}


def _reduce_factory(kind: str, dtype: np.dtype):
    key = (kind, str(dtype))
    if key not in _reduce_factories:
        src = {"col": _REDUCE_COL_JDF, "row": _REDUCE_ROW_JDF,
               "all": _REDUCE_ALL_JDF}[kind].format(dt=str(dtype))
        _reduce_factories[key] = ptg.compile_jdf(src, name=f"reduce_{kind}")
    return _reduce_factories[key]


def _depth(n: int) -> int:
    return max(1, math.ceil(math.log2(n))) if n > 1 else 1


def _default_dest(A: TiledMatrix, mt: int, nt: int) -> TiledMatrix:
    return TwoDimBlockCyclic(mt * A.mb, nt * A.nb, A.mb, A.nb, dtype=A.dtype,
                             nodes=A.nodes, rank=A.rank)


def reduce_col_taskpool(A: TiledMatrix, operation: Callable,
                        dest: Optional[TiledMatrix] = None,
                        op_args: Any = None, rank: int = 0, nb_ranks: int = 1):
    """Fold tiles down every column: ``dest(0, col) = op-fold of
    A(0..MT-1, col)``. Returns (taskpool, dest)."""
    dest = dest if dest is not None else _default_dest(A, 1, A.nt)
    tp = _reduce_factory("col", A.dtype).new(
        descA=A, dest=dest, MT=A.mt, NT=A.nt, MB=A.mb, NB=A.nb,
        DEPTH=_depth(A.mt), rank=rank, nb_ranks=nb_ranks)
    tp.global_env["operation"] = operation
    tp.global_env["op_args"] = op_args
    return tp, dest


def reduce_row_taskpool(A: TiledMatrix, operation: Callable,
                        dest: Optional[TiledMatrix] = None,
                        op_args: Any = None, rank: int = 0, nb_ranks: int = 1):
    """Fold tiles across every row: ``dest(row, 0) = op-fold of
    A(row, 0..NT-1)``. Returns (taskpool, dest)."""
    dest = dest if dest is not None else _default_dest(A, A.mt, 1)
    tp = _reduce_factory("row", A.dtype).new(
        descA=A, dest=dest, MT=A.mt, NT=A.nt, MB=A.mb, NB=A.nb,
        DEPTH=_depth(A.nt), rank=rank, nb_ranks=nb_ranks)
    tp.global_env["operation"] = operation
    tp.global_env["op_args"] = op_args
    return tp, dest


def reduce_all_taskpool(A: TiledMatrix, operation: Callable,
                        dest: Optional[TiledMatrix] = None,
                        op_args: Any = None, rank: int = 0, nb_ranks: int = 1):
    """Fold every tile of A into ``dest(0, 0)``. Returns (taskpool, dest)."""
    nleaf = A.mt * A.nt
    dest = dest if dest is not None else _default_dest(A, 1, 1)
    tp = _reduce_factory("all", A.dtype).new(
        descA=A, dest=dest, MT=A.mt, NT=A.nt, MB=A.mb, NB=A.nb,
        NLEAF=nleaf, DEPTH=_depth(nleaf), rank=rank, nb_ranks=nb_ranks)
    tp.global_env["operation"] = operation
    tp.global_env["op_args"] = op_args
    return tp, dest


def reduce_col(context, A, operation, dest=None, op_args=None):
    tp, dest = reduce_col_taskpool(A, operation, dest, op_args)
    context.add_taskpool(tp)
    context.wait()
    return dest


def reduce_row(context, A, operation, dest=None, op_args=None):
    tp, dest = reduce_row_taskpool(A, operation, dest, op_args)
    context.add_taskpool(tp)
    context.wait()
    return dest


def reduce_all(context, A, operation, dest=None, op_args=None):
    tp, dest = reduce_all_taskpool(A, operation, dest, op_args)
    context.add_taskpool(tp)
    context.wait()
    return dest


def allreduce_taskpool(A: TiledMatrix, operation: Callable,
                       op_args: Any = None, rank: int = 0,
                       nb_ranks: int = 1):
    """Every tile of A folds to one value which then lands back in every
    tile of A — the reduce+broadcast composition the reference's DTD
    allreduce test builds by hand (no allreduce primitive exists in the
    runtime; reductions and broadcasts are task graphs, SURVEY.md §2.4).
    Returns one compound taskpool (reduce ; broadcast)."""
    from ..runtime.compound import compose
    red, scratch = reduce_all_taskpool(A, operation, None, op_args,
                                       rank=rank, nb_ranks=nb_ranks)
    bc = broadcast_taskpool(scratch, A, root=(0, 0), rank=rank,
                            nb_ranks=nb_ranks)
    return compose(red, bc)


def allreduce(context, A, operation, op_args=None):
    """In-place allreduce over A's tiles. Blocking."""
    tp = allreduce_taskpool(A, operation, op_args)
    context.add_taskpool(tp)
    context.wait()


# --------------------------------------------------------------------------
# broadcast: one source tile to every tile of dest  (ref: broadcast.jdf —
# a root datum propagated to a rank set; the fan-out edge rides the
# remote-dep broadcast topology in multi-rank runs)
# --------------------------------------------------------------------------

_BCAST_JDF = """
src [ type="collection" ]
dest [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
RM [ type="int" ]
RN [ type="int" ]

ROOT(z)

z = 0 .. 0

: src( RM, RN )

READ S <- src( RM, RN )
       -> S BCAST( 0 .. MT-1, 0 .. NT-1 )

BODY
{
    pass
}
END

BCAST(m, n)

m = 0 .. MT-1
n = 0 .. NT-1

: dest( m, n )

READ S <- S ROOT( 0 )
RW   D <- dest( m, n )
       -> dest( m, n )

BODY
{
    D = S
}
END
"""

_bcast_factory: Optional[Any] = None


def broadcast_taskpool(src: TiledMatrix, dest: TiledMatrix,
                       root: tuple = (0, 0), rank: int = 0, nb_ranks: int = 1):
    """Copy tile ``src(root)`` into every tile of ``dest``."""
    global _bcast_factory
    if _bcast_factory is None:
        _bcast_factory = ptg.compile_jdf(_BCAST_JDF, name="broadcast")
    return _bcast_factory.new(src=src, dest=dest, MT=dest.mt, NT=dest.nt,
                              RM=root[0], RN=root[1],
                              rank=rank, nb_ranks=nb_ranks)


def broadcast(context, src: TiledMatrix, dest: TiledMatrix,
              root: tuple = (0, 0)) -> None:
    context.add_taskpool(broadcast_taskpool(src, dest, root))
    context.wait()


# --------------------------------------------------------------------------
# diag_band_to_rect: copy the tridiagonal tile band of a band-stored matrix
# into a rectangular (2 × NT) matrix  (ref: diag_band_to_rect.jdf)
# --------------------------------------------------------------------------

_BAND_JDF = """
band [ type="collection" ]
rect [ type="collection" ]
NT [ type="int" ]

DIAG(k)

k = 0 .. NT-1

: band( k, k )

READ D <- band( k, k )
RW   R <- rect( 0, k )
       -> rect( 0, k )

BODY
{
    R = D
}
END

SUPER(k)

k = 1 .. NT-1

: band( k-1, k )

READ D <- band( k-1, k )
RW   R <- rect( 1, k )
       -> rect( 1, k )

BODY
{
    R = D
}
END
"""

_band_factory: Optional[Any] = None


def band_to_rect_taskpool(band: TiledMatrix, rect: TiledMatrix,
                          rank: int = 0, nb_ranks: int = 1):
    """Diagonal tiles of ``band`` → row 0 of ``rect``; superdiagonal tiles
    → row 1 (columns 1..NT-1)."""
    global _band_factory
    if _band_factory is None:
        _band_factory = ptg.compile_jdf(_BAND_JDF, name="diag_band_to_rect")
    nt = min(band.mt, band.nt)
    return _band_factory.new(band=band, rect=rect, NT=nt,
                             rank=rank, nb_ranks=nb_ranks)
