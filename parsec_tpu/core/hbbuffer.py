"""Hierarchical bounded buffers (hbbuffer) and the max-heap.

Reference behavior: ``parsec_hbbuffer_t`` — a bounded per-thread buffer whose
overflow spills to a parent push function (ultimately the global system
dequeue); used by all local-queue schedulers (ref: parsec/hbbuffer.c:1-277).
``parsec_maxheap`` orders tasks by priority for heap-based stealing
(ref: parsec/maxheap.c:1-384).

Like the list containers (core/lists.py), both are implemented in C++
(native/_native.cpp) and rebound here when the native core builds; the
Python classes below are the documented fallbacks (PARSEC_TPU_NATIVE=0)
and the reference implementations for the native parity tests. The
native HBBuffer reads ``item.priority`` directly when ``prio_fn`` is
omitted — the schedulers' fast path.
"""
from __future__ import annotations

import heapq
import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional


class HBBuffer:
    """Bounded buffer; pushes that do not fit go to ``parent_push``.

    ``ranking`` mirrors the reference's priority-aware insertion: the buffer
    keeps the best tasks locally and spills the rest.
    """

    def __init__(self, size: int, parent_push: Callable[[Iterable[Any], int], None],
                 prio_fn: Callable[[Any], int] = lambda t: getattr(t, "priority", 0)) -> None:
        assert size > 0
        self.size = size
        self.parent_push = parent_push
        self.prio_fn = prio_fn
        self._items: List = []
        self._ctr = itertools.count()
        self._lock = threading.Lock()

    def push_all(self, items: Iterable[Any], distance: int = 0) -> None:
        spill: List[Any] = []
        with self._lock:
            for it in items:
                if len(self._items) < self.size:
                    heapq.heappush(self._items, (-self.prio_fn(it), next(self._ctr), it))
                else:
                    # keep the highest-priority tasks local, spill the lowest
                    lowest = max(self._items)
                    if (-self.prio_fn(it)) < lowest[0]:
                        idx = self._items.index(lowest)
                        spill.append(self._items[idx][2])
                        self._items[idx] = (-self.prio_fn(it), next(self._ctr), it)
                        heapq.heapify(self._items)
                    else:
                        spill.append(it)
        if spill:
            self.parent_push(spill, distance + 1)

    def pop_best(self) -> Optional[Any]:
        with self._lock:
            if not self._items:
                return None
            return heapq.heappop(self._items)[2]

    def is_empty(self) -> bool:
        return not self._items

    def __len__(self) -> int:
        return len(self._items)


class MaxHeap:
    """Priority max-heap of tasks (ref: parsec/maxheap.c)."""

    def __init__(self) -> None:
        self._h: List = []
        self._ctr = itertools.count()
        self._lock = threading.Lock()

    def insert(self, item: Any, priority: int = 0) -> None:
        with self._lock:
            heapq.heappush(self._h, (-priority, next(self._ctr), item))

    def pop_max(self) -> Optional[Any]:
        with self._lock:
            if not self._h:
                return None
            return heapq.heappop(self._h)[2]

    def split(self) -> "MaxHeap":
        """Steal roughly half the heap (heap-split stealing)."""
        out = type(self)()
        # share the tie-break counter: stolen entries keep their seq, so a
        # fresh counter would collide with them (TypeError on heapq tuple
        # comparison) and break FIFO-within-priority; the native split
        # does the same by continuing from self->seq
        out._ctr = self._ctr
        with self._lock:
            half = len(self._h) // 2
            if half:
                stolen = self._h[-half:]
                del self._h[-half:]
                heapq.heapify(self._h)
                out._h = stolen
                heapq.heapify(out._h)
        return out

    def __len__(self) -> int:
        return len(self._h)


PyHBBuffer, PyMaxHeap = HBBuffer, MaxHeap
try:  # rebind to the native C++ core when it is available
    from ..native import native as _native
    if _native is not None and hasattr(_native, "HBBuffer"):
        HBBuffer = _native.HBBuffer      # type: ignore[misc,assignment]
        MaxHeap = _native.MaxHeap        # type: ignore[misc,assignment]
except ImportError:  # pragma: no cover
    pass
