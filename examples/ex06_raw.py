"""Ex06: read-after-write hazard, visible under dataflow alone.

Teaches: one producer (TaskBcast) feeding both a reader fan-out (TaskRecv
over a stepped range ``0 .. NB .. 2``) and a writer (TaskUpdate). All
consumers share the producer's copy, and nothing orders readers vs the
writer — on shared memory a reader scheduled after the update observes
the updated value. That *is* the demonstrated hazard; Ex07 adds a CTL
flow to force readers-before-writer (ref: examples/Ex06_RAW.jdf; derived
locals ``loc = k + n``).
"""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu
from parsec_tpu.collections import LocalArrayCollection
from parsec_tpu.dsl import ptg

RAW_JDF = """
mydata [ type="collection" ]
NB     [ type="int" ]

TaskBcast(k)

k = 0 .. 0

: mydata( k )

RW  A <- mydata( k )
      -> A TaskUpdate( k )
      -> A TaskRecv( k, 0 .. NB .. 2 )

BODY
{
    A[...] = k + 1
    print(f"send {k + 1}")
}
END

TaskRecv(k, n)

k = 0 .. 0
n = 0 .. NB .. 2
loc = k + n

: mydata( loc )

READ A <- A TaskBcast( k )

BODY
{
    print(f"recv {int(A.ravel()[0])} at loc {loc}")
}
END

TaskUpdate(k)

k = 0 .. 0

: mydata( k )

RW  A <- A TaskBcast( k )
      -> mydata( k )

BODY
{
    A[...] += 100
    print(f"update -> {int(A.ravel()[0])}")
}
END
"""


def main(NB: int = 6) -> int:
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        mydata = LocalArrayCollection(np.zeros((NB + 1, 1), dtype=np.int64),
                                      NB + 1)
        tp = ptg.compile_jdf(RAW_JDF, name="raw").new(mydata=mydata, NB=NB)
        ctx.add_taskpool(tp)
        ctx.wait()
        # writeback: mydata(0) holds the updated value
        assert mydata.array[0, 0] == 101, mydata.array[:, 0]
    finally:
        ctx.fini()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
