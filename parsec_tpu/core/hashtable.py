"""Resizable, striped-lock hash table.

Reference behavior: bucket-locked resizable hash table used for dependency
tracking, DTD task/tile registries, and data repos
(ref: parsec/class/parsec_hash_table.h:93-145, parsec_hash_table.c:1-745).

Semantics preserved: insert-if-absent (``find_or_insert``), lock/unlock of a
key's bucket for atomic read-modify-write, removal returning the item.
Striped locks bound contention the way per-bucket locks do in the reference.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

_NSTRIPES = 64


class HashTable:
    def __init__(self, nb_stripes: int = _NSTRIPES) -> None:
        self._stripes = [dict() for _ in range(nb_stripes)]
        self._locks = [threading.RLock() for _ in range(nb_stripes)]
        self._n = nb_stripes

    def _idx(self, key: Any) -> int:
        return hash(key) % self._n

    # -- bucket locking (parsec_hash_table_lock_bucket) --------------------
    def lock_bucket(self, key: Any) -> None:
        self._locks[self._idx(key)].acquire()

    def unlock_bucket(self, key: Any) -> None:
        self._locks[self._idx(key)].release()

    # -- nolock variants: caller holds the bucket lock ---------------------
    def nolock_find(self, key: Any) -> Optional[Any]:
        return self._stripes[self._idx(key)].get(key)

    def nolock_insert(self, key: Any, value: Any) -> None:
        self._stripes[self._idx(key)][key] = value

    def nolock_remove(self, key: Any) -> Optional[Any]:
        return self._stripes[self._idx(key)].pop(key, None)

    # -- locked operations --------------------------------------------------
    def find(self, key: Any) -> Optional[Any]:
        i = self._idx(key)
        with self._locks[i]:
            return self._stripes[i].get(key)

    def insert(self, key: Any, value: Any) -> None:
        i = self._idx(key)
        with self._locks[i]:
            self._stripes[i][key] = value

    def find_or_insert(self, key: Any, factory: Callable[[], Any]) -> Tuple[Any, bool]:
        """Return (value, inserted). factory() runs under the bucket lock."""
        i = self._idx(key)
        with self._locks[i]:
            if key in self._stripes[i]:
                return self._stripes[i][key], False
            v = factory()
            self._stripes[i][key] = v
            return v, True

    def remove(self, key: Any) -> Optional[Any]:
        i = self._idx(key)
        with self._locks[i]:
            return self._stripes[i].pop(key, None)

    def update(self, key: Any, fn: Callable[[Optional[Any]], Any]) -> Any:
        """Atomic read-modify-write of one entry."""
        i = self._idx(key)
        with self._locks[i]:
            v = fn(self._stripes[i].get(key))
            self._stripes[i][key] = v
            return v

    def __len__(self) -> int:
        return sum(len(s) for s in self._stripes)

    def items(self) -> Iterator[Tuple[Any, Any]]:
        """Snapshot iteration (not linearizable across stripes)."""
        for i in range(self._n):
            with self._locks[i]:
                snap = list(self._stripes[i].items())
            yield from snap

    def clear(self) -> None:
        for i in range(self._n):
            with self._locks[i]:
                self._stripes[i].clear()


class HashTable64(HashTable):
    """Hash table restricted to 64-bit integer keys (the reference's
    ``parsec_key_t`` is a 64-bit word, parsec_hash_table.h:93). Rebound to
    the native C++ bucket-locked resizable table when available."""

    def keys(self):
        return [k for k, _ in self.items()]


try:
    from ..native import native as _native
    if _native is not None:
        PyHashTable64 = HashTable64
        HashTable64 = _native.HashTable64  # type: ignore[misc,assignment]
except ImportError:  # pragma: no cover
    pass
