"""Deterministic fault injection: a seeded chaos layer for the runtime.

Robustness claims need tests, and tests need failures on demand — in
process, reproducibly, without real process kills. The injector is
installed from the ``ft_inject`` MCA param and hooks two layers:

- the **wire layer**: every transport's ``_transport_post`` consults
  :meth:`FaultInjector.on_send` (one never-taken branch when no
  injector is installed), which can DROP a frame, DUPLICATE it, DELAY
  it, or FAIL the Nth send outright (``RankFailedError``);
- the **task boundary**: an :class:`FTInjectModule` PINS module (the
  ``COMPLETE_EXEC_END`` site) kills this rank after its Nth task
  completes — the engine goes dark (``ft_silence``: no goodbye, no
  replies, sockets left dangling) and the worker raises
  :class:`InjectedKill`, exactly the observable footprint of a
  SIGKILL'd process — or raises a transient
  :class:`InjectedTaskFault` (the retry-able failure the restart
  driver exercises).

Spec grammar (``--mca ft_inject "..."``): comma-separated directives,
each ``op:key=val:key=val``::

    kill:rank=1:after=3        # rank 1 goes dark at its 3rd task boundary
    taskfail:rank=0:nth=5      # transient task error at the 5th boundary
    drop:rank=*:peer=2:pct=2:seed=7   # drop 2% of frames toward rank 2
    dup:pct=1:seed=7           # duplicate 1% of frames
    delay:pct=5:ms=2:seed=7    # delay 5% of frames by 2 ms
    failsend:rank=0:nth=10     # rank 0's 10th send raises RankFailedError
    flap:rank=2:nth=30:duration=0.3   # rank 2's 30th send hard-closes
                               # the socket(s); the link stays DOWN
                               # (reconnects rejected) for 0.3 s
    disconnect:rank=2:nth=30   # like flap, but the link never comes
                               # back — a permanent fault that must
                               # exhaust the reconnect budget

``rank`` selects which rank's engine acts (default ``*`` = every
rank); ``seed`` makes percentage draws reproducible (the stream is
also salted by rank, so SPMD ranks draw independently but
deterministically). Wire directives never touch heartbeat traffic
unless ``hb=1`` — chaos under test must not blind the detector that
the test is asserting on. ``kill``/``taskfail``/``failsend`` are
one-shot; percentage directives apply for the engine's lifetime.

``flap``/``disconnect`` tear the LINK, not the process (the
transient-vs-permanent distinction the reliable session layer exists
for, comm/tcp.py): the socket(s) toward the directive's ``peer``
filter (default every peer) hard-close with nothing flushed, and the
engine's reconnect attempts — dialing out or accepting the peer's
re-dial — are rejected while the link is down. With sessions enabled
a flap is absorbed by reconnect + replay; a disconnect (or a flap
longer than ``comm_reconnect_timeout``) escalates to the ordinary
rank-failure path. On transports without sockets both are no-ops.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..profiling.pins import PinsEvent, PinsModule

__all__ = ["FaultInjector", "FTInjectModule", "InjectedKill",
           "InjectedTaskFault", "parse_inject_spec"]


class InjectedKill(RuntimeError):
    """This rank was chaos-killed at a task boundary (its engine is
    already dark); the local DAG aborts like a crash would."""

    def __init__(self, rank: int, after: int) -> None:
        super().__init__(
            f"rank {rank}: injected kill after {after} task completions")
        self.rank = rank


class InjectedTaskFault(RuntimeError):
    """A transient injected task failure (survives a retry)."""


_WIRE_OPS = ("drop", "dup", "delay", "failsend", "flap", "disconnect")
_TASK_OPS = ("kill", "taskfail")


def parse_inject_spec(spec: str) -> List[Dict[str, Any]]:
    """Parse the ``ft_inject`` grammar into directive dicts; raises
    ValueError on unknown ops/keys so typos fail at install, not by
    silently injecting nothing."""
    out: List[Dict[str, Any]] = []
    for raw in spec.replace(";", ",").split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        op = parts[0].strip()
        if op not in _WIRE_OPS + _TASK_OPS:
            raise ValueError(
                f"ft_inject: unknown op {op!r} in {raw!r} "
                f"(have {', '.join(_WIRE_OPS + _TASK_OPS)})")
        d: Dict[str, Any] = {"op": op, "rank": "*", "peer": "*",
                             "pct": 0.0, "nth": 0, "seed": 0,
                             "after": 1, "ms": 1.0, "hb": False,
                             "duration": 0.0}
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(f"ft_inject: expected key=val, got {kv!r}")
            k, v = kv.split("=", 1)
            k = k.strip()
            if k not in d:
                raise ValueError(
                    f"ft_inject: unknown key {k!r} for op {op!r}")
            if k in ("rank", "peer"):
                d[k] = "*" if v.strip() == "*" else int(v)
            elif k in ("pct", "ms", "duration"):
                d[k] = float(v)
            elif k == "hb":
                d[k] = v.strip().lower() in ("1", "true", "yes", "on")
            else:
                d[k] = int(v)
        if op in _WIRE_OPS and d["nth"] <= 0 and d["pct"] <= 0:
            raise ValueError(
                f"ft_inject: {raw!r} would never fire — wire ops need "
                f"nth=N or pct>0")
        out.append(d)
    return out


class FaultInjector:
    """Per-rank injector instance: directives from one spec, counters
    and RNG streams salted by rank (SPMD ranks built from the same
    spec draw deterministically but independently)."""

    def __init__(self, directives: List[Dict[str, Any]], rank: int) -> None:
        self.rank = rank
        self._lock = threading.Lock()
        self._sends = 0        # matching wire events seen
        self._completions = 0  # task boundaries seen
        self._dirs = []
        for d in directives:
            if d["rank"] != "*" and d["rank"] != rank:
                continue
            ent = dict(d)
            ent["fired"] = False
            ent["rng"] = np.random.RandomState(
                (int(d["seed"]) + 1000003 * rank) & 0x7FFFFFFF)
            self._dirs.append(ent)
        self.has_task_actions = any(
            d["op"] in _TASK_OPS for d in self._dirs)
        # link-down intervals from flap/disconnect directives:
        # peer (or "*") -> monotonic deadline (inf = disconnect).
        # Consulted by the transport's reconnect machinery — dial
        # attempts and accepted resumes both fail while down.
        self._link_down: Dict[Any, float] = {}
        self.stats = {"dropped": 0, "duplicated": 0, "delayed": 0,
                      "failed_sends": 0, "kills": 0, "task_faults": 0,
                      "flaps": 0}

    def link_down(self, peer: int) -> bool:
        """Is the (virtual) link toward ``peer`` currently torn by a
        flap/disconnect directive?"""
        with self._lock:
            until = max(self._link_down.get(peer, 0.0),
                        self._link_down.get("*", 0.0))
        return time.monotonic() < until

    @classmethod
    def from_spec(cls, spec: str, rank: int) -> "FaultInjector":
        return cls(parse_inject_spec(spec), rank)

    # -- wire layer (transports call this on every remote post) ---------
    def on_send(self, dst: int, tag: int) -> str:
        """Verdict for one outgoing frame: "ok" | "drop" | "dup"
        (delays sleep in place; failsend raises). ``nth`` counts per
        directive over the sends its filters MATCH, so e.g.
        ``failsend:nth=3`` fires on exactly the 3rd matching send even
        with unmatched (heartbeat, other-peer) traffic interleaved."""
        from ..comm.engine import RankFailedError, TAG_HEARTBEAT
        is_hb = tag == TAG_HEARTBEAT
        with self._lock:
            self._sends += 1
            for d in self._dirs:
                if d["op"] not in _WIRE_OPS or d["fired"] and d["nth"]:
                    continue
                if is_hb and not d["hb"]:
                    continue   # chaos must not blind the detector
                if d["peer"] != "*" and d["peer"] != dst:
                    continue
                d["seen"] = n = d.get("seen", 0) + 1
                hit = (n == d["nth"] if d["nth"]
                       else d["pct"] > 0
                       and d["rng"].rand() * 100.0 < d["pct"])
                if not hit:
                    continue
                if d["nth"]:
                    d["fired"] = True
                op = d["op"]
                if op == "drop":
                    self.stats["dropped"] += 1
                    return "drop"
                if op == "dup":
                    self.stats["duplicated"] += 1
                    return "dup"
                if op == "delay":
                    self.stats["delayed"] += 1
                    delay_s = d["ms"] / 1e3
                    break   # sleep outside the lock
                if op in ("flap", "disconnect"):
                    self.stats["flaps"] += 1
                    until = (float("inf") if op == "disconnect"
                             else time.monotonic() + max(0.0,
                                                         d["duration"]))
                    key = d["peer"] if d["peer"] != "*" else "*"
                    self._link_down[key] = max(
                        self._link_down.get(key, 0.0), until)
                    return "flap"
                # failsend
                self.stats["failed_sends"] += 1
                raise RankFailedError(
                    dst, f"injected failure of send #{n} from rank "
                         f"{self.rank}")
            else:
                return "ok"
        time.sleep(delay_s)
        return "ok"

    # -- task boundary (FTInjectModule calls this per completion) -------
    def on_task_complete(self, context: Any) -> None:
        with self._lock:
            self._completions += 1
            n = self._completions
            trigger = None
            for d in self._dirs:
                if d["op"] not in _TASK_OPS or d["fired"]:
                    continue
                at = d["after"] if d["op"] == "kill" else d["nth"]
                if n >= max(1, at):
                    d["fired"] = True
                    trigger = d
                    break
        if trigger is None:
            return
        if trigger["op"] == "kill":
            self.stats["kills"] += 1
            # go dark FIRST: the abort that follows must leak nothing
            # (no goodbye, no final messages) — peers may only learn of
            # this death proactively, via the heartbeat detector
            comm = getattr(context, "comm", None)
            ce = getattr(comm, "ce", comm)
            if ce is not None and hasattr(ce, "ft_silence"):
                ce.ft_silence()
            raise InjectedKill(self.rank, n)
        self.stats["task_faults"] += 1
        raise InjectedTaskFault(
            f"rank {self.rank}: injected task fault at completion #{n}")


class FTInjectModule(PinsModule):
    """PINS module binding one injector's task-boundary directives to
    one context (the ``COMPLETE_EXEC_END`` site — the reference's
    task-boundary hook). Context-filtered like TaskProfilerModule: with
    several in-process SPMD ranks, each rank's module must see only its
    own completions."""

    name = "ft_inject"
    events = [PinsEvent.COMPLETE_EXEC_END]

    def __init__(self, injector: FaultInjector, context: Any) -> None:
        self.injector = injector
        self.context = context

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        if es.context is not self.context:
            return
        self.injector.on_task_complete(self.context)
