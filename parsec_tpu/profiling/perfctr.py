"""Hardware performance counters via perf_event_open — the PAPI analog.

Reference behavior: parsec/mca/pins/papi/ attaches PAPI event sets per
execution stream and samples them at task begin/end into the trace.
PAPI isn't available here; Linux's ``perf_event_open(2)`` gives the same
PMU access with no dependency: one fd per (thread, event), counting
user-space cycles/instructions/cache-misses, read as 8-byte values.

Availability is environment-dependent (``kernel.perf_event_paranoid``,
seccomp in containers, PMU virtualization): ``PerfCounterSet.open``
raises OSError when the kernel refuses, and the PINS module disables
itself gracefully — exactly like the reference builds without PAPI.
"""
from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["PERF_EVENTS", "PerfCounterSet", "perf_available"]

_NR_PERF_EVENT_OPEN = {"x86_64": 298, "aarch64": 241}.get(os.uname().machine)

PERF_TYPE_HARDWARE = 0
# config values for PERF_TYPE_HARDWARE (linux/perf_event.h)
PERF_EVENTS: Dict[str, int] = {
    "cycles": 0,
    "instructions": 1,
    "cache_references": 2,
    "cache_misses": 3,
    "branches": 4,
    "branch_misses": 5,
}

# perf_event_attr flag bits (low qword after read_format)
_FLAG_DISABLED = 1 << 0
_FLAG_EXCLUDE_KERNEL = 1 << 5
_FLAG_EXCLUDE_HV = 1 << 6

_ATTR_SIZE = 128  # PERF_ATTR_SIZE_VER ≥ 5; kernel accepts zero-padded


def _attr_bytes(config: int) -> bytes:
    """A minimal perf_event_attr: hardware event, counting mode,
    user-space only, starts enabled."""
    buf = bytearray(_ATTR_SIZE)
    struct.pack_into("<IIQ", buf, 0, PERF_TYPE_HARDWARE, _ATTR_SIZE,
                     config)
    # offset 16: sample_period(8) sample_type(8) read_format(8) flags(8)
    struct.pack_into("<Q", buf, 40, _FLAG_EXCLUDE_KERNEL | _FLAG_EXCLUDE_HV)
    return bytes(buf)


_libc = ctypes.CDLL(None, use_errno=True)


def _perf_event_open(attr: bytes, pid: int, cpu: int, group_fd: int,
                     flags: int) -> int:
    if _NR_PERF_EVENT_OPEN is None:
        raise OSError("perf_event_open: unsupported architecture")
    buf = ctypes.create_string_buffer(attr, len(attr))
    fd = _libc.syscall(_NR_PERF_EVENT_OPEN, buf, pid, cpu, group_fd,
                       flags)
    if fd < 0:
        err = ctypes.get_errno()
        raise OSError(err, f"perf_event_open failed: {os.strerror(err)}")
    return fd


class PerfCounterSet:
    """Counters for the CALLING thread (pid=0/tid semantics: counts this
    thread wherever it runs). read() returns current values; deltas are
    the caller's business."""

    def __init__(self, fds: List[int], names: List[str]) -> None:
        self._fds = fds
        self.names = names

    @classmethod
    def open(cls, events: List[str]) -> "PerfCounterSet":
        fds: List[int] = []
        try:
            for name in events:
                fds.append(_perf_event_open(
                    _attr_bytes(PERF_EVENTS[name]), 0, -1, -1, 0))
        except OSError:
            for fd in fds:
                os.close(fd)
            raise
        return cls(fds, list(events))

    def read(self) -> Tuple[int, ...]:
        return tuple(struct.unpack("<Q", os.read(fd, 8))[0]
                     for fd in self._fds)

    def close(self) -> None:
        for fd in self._fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._fds = []

    def __del__(self) -> None:  # fd hygiene for dropped sets
        self.close()


def perf_available(events: Optional[List[str]] = None) -> bool:
    """Can this environment open the given (default: instructions)
    hardware counters?"""
    try:
        s = PerfCounterSet.open(events or ["instructions"])
    except OSError:
        return False
    s.close()
    return True
