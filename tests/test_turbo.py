"""Turbo static dispatch (dsl/ptg/turbo.py): the native per-task fast
path — C priority-heap select/release (NativeDAG.run_loop), precompiled
slot binding, one XLA call per task, lazy device-resident writebacks.
Differential vs numpy and vs the classic runtime path, plus the
integration contract (context flow, error abort, lazy reads, kernel
cache reuse across taskpool instantiations)."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.ops import (dgetrf_nopiv_taskpool, dpotrf_taskpool,
                            make_spd, pdgemm_taskpool)
from parsec_tpu.utils.params import params


@pytest.fixture
def static_ctx():
    params.set_cmdline("ptg_dep_management", "static")
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        yield ctx
    finally:
        ctx.fini()
        params.unset_cmdline("ptg_dep_management")


def _tpu_dev(ctx):
    return next(d for d in ctx.devices if d.device_type == "tpu")


def test_turbo_dpotrf_matches_numpy(static_ctx):
    n, nb = 512, 128
    M = make_spd(n, dtype=np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    assert tp._turbo is not None, "turbo did not engage on a static pool"
    assert tp._turbo.stats["tasks"] == 20
    assert tp._turbo.stats["kernel_calls"] == 20   # per-task dispatch
    L = np.tril(A.to_numpy()).astype(np.float64)
    assert np.allclose(L, np.linalg.cholesky(M.astype(np.float64)),
                       atol=1e-3)


def test_turbo_dgetrf_ragged(static_ctx):
    """LU over a ragged tiling: turbo inherits shape-split pools."""
    n, nb = 200, 64
    M = make_spd(n, dtype=np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dgetrf_nopiv_taskpool(A)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    assert tp._turbo is not None
    LU = A.to_numpy().astype(np.float64)
    L = np.tril(LU, -1) + np.eye(n)
    U = np.triu(LU)
    assert np.abs(L @ U - M).max() / np.abs(M).max() < 1e-5


def test_turbo_pdgemm_static_body_locals(static_ctx):
    """pdgemm's GEMM body branches on local k in Python: per-task specs
    carry it as a static, like wave's sub-chunking."""
    n, nb = 256, 64
    rng = np.random.RandomState(5)
    Am = rng.rand(n, n).astype(np.float32)
    Bm = rng.rand(n, n).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(Am)
    B = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(Bm)
    C = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(
        np.zeros((n, n), np.float32))
    tp = pdgemm_taskpool(A, B, C)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    assert tp._turbo is not None
    ref = Am.astype(np.float64) @ Bm.astype(np.float64)
    assert np.abs(C.to_numpy().astype(np.float64) - ref).max() / n < 1e-6


def test_turbo_lazy_writeback_single_tile_pull(static_ctx):
    """Results stay device-resident; reading ONE tile materializes
    exactly one pool slice (VERDICT r3 weak #7: never bulk-pull)."""
    from parsec_tpu.dsl.ptg.turbo import LazyPoolCopy

    n, nb = 512, 128
    M = make_spd(n, dtype=np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    didx = _tpu_dev(static_ctx).device_index
    lazies = [A.data_of(*c).get_copy(didx) for c in A.tiles()]
    lazies = [c for c in lazies if isinstance(c, LazyPoolCopy)]
    assert lazies, "no lazy device copies attached"
    assert not any(c._mat for c in lazies), "writeback was eager"
    A.data_of(1, 0).sync_to_host()
    assert sum(c._mat for c in lazies) == 1, \
        "one host read materialized more than one tile"


def test_turbo_body_error_aborts(static_ctx):
    jdf = """
descA [ type="collection" ]
NT [ type="int" ]

Boom(k)
k = 0 .. NT-1
: descA( k, 0 )
RW X <- descA( k, 0 )
     -> descA( k, 0 )
BODY
{
    X = X / jnp.zeros_like(X)[0, 0]
    raise_check = [][0]
}
END
"""
    fac = ptg.compile_jdf(jdf, name="boom")
    A = TwoDimBlockCyclic(8, 4, 4, 4, dtype=np.float32).from_numpy(
        np.ones((8, 4), np.float32))
    static_ctx.add_taskpool(fac.new(NT=2, descA=A))
    with pytest.raises(RuntimeError, match="task body failed"):
        static_ctx.wait()


def test_turbo_kernel_cache_survives_taskpool(static_ctx):
    """Bench-rep pattern: a second taskpool with the same signature
    reuses the lowered DAG AND its compiled kernels + entries."""
    n, nb = 512, 128
    M = make_spd(n, dtype=np.float32)
    tps = []
    for _ in range(2):
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        tp = dpotrf_taskpool(A)
        static_ctx.add_taskpool(tp)
        static_ctx.wait()
        tps.append(tp)
    assert tps[0]._turbo.dag is tps[1]._turbo.dag, "lowering cache miss"
    assert tps[1]._turbo._entries is tps[0]._turbo._entries, \
        "turbo entries rebuilt for an identical signature"


def test_turbo_off_by_param(static_ctx):
    params.set_cmdline("ptg_dispatch", "classic")
    try:
        n, nb = 256, 128
        M = make_spd(n, dtype=np.float32)
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        tp = dpotrf_taskpool(A)
        static_ctx.add_taskpool(tp)
        static_ctx.wait()
        assert tp._turbo is None        # classic static path served it
        assert tp._engine is not None
        L = np.tril(A.to_numpy()).astype(np.float64)
        assert np.allclose(L, np.linalg.cholesky(M.astype(np.float64)),
                           atol=1e-3)
    finally:
        params.unset_cmdline("ptg_dispatch")


WAR_JDF = """
descA [ type="collection" ]

P(j)
j = 0 .. 0
: descA( 0, 0 )
RW X <- descA( 0, 0 )
     -> A R( 0 )
     -> B W( 0 )
     -> descA( 0, 0 )
BODY
{
    X = X + 1.0
}
END

R(j)
j = 0 .. 0
: descA( 1, 0 )
READ A <- X P( 0 )
RW   O <- descA( 1, 0 )
     -> descA( 1, 0 )
BODY
{
    O = A * 10.0
}
END

W(j)
j = 0 .. 0
: descA( 0, 0 )
RW B <- X P( 0 )
     -> descA( 0, 0 )
; 1000
BODY
{
    B = B + 100.0
}
END
"""


def test_turbo_war_ordering(static_ctx):
    """Reader R and in-place writer W of the same slot, both ready
    after P, with W's priority HIGHER: without the static WAR edge the
    heap runs W first and R reads the clobbered value. The augmented
    CSR must order R before W (wave's _split_war semantics)."""
    fac = ptg.compile_jdf(WAR_JDF, name="warj")
    M0 = np.full((8, 4), 5.0, np.float32)
    A = TwoDimBlockCyclic(8, 4, 4, 4, dtype=np.float32).from_numpy(
        M0.copy())
    tp = fac.new(descA=A)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    assert tp._turbo is not None
    out = A.to_numpy()
    np.testing.assert_allclose(out[:4], 5.0 + 1.0 + 100.0)  # P then W
    np.testing.assert_allclose(out[4:], (5.0 + 1.0) * 10.0)  # R saw P's X


def test_turbo_cached_kernels_do_not_pin_runner(static_ctx):
    """The DAG-level kernel cache outlives taskpools: its traces must
    not keep the runner (and its device pools) alive after the
    taskpool and collection are gone."""
    import gc
    import weakref

    n, nb = 256, 128
    M = make_spd(n, dtype=np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    ref = weakref.ref(tp._turbo)
    del tp, A
    gc.collect()
    assert ref() is None, ("turbo runner (and its HBM pools) pinned "
                           "after the taskpool died — a kernel-cache "
                           "closure captured it")


def test_turbo_cyclic_war_falls_back_to_classic(static_ctx):
    """A co-ready swap (cyclic WAR) is unservable by per-task in-place
    scatters: TurboRunner must refuse at build (cycle in the augmented
    CSR — a silent deadlock otherwise) and the startup gate must fall
    back to the classic static path. NOTE the classic per-task runtime
    gives such DAGs order-dependent results too (memory-sourced reads
    bind the home copies, which the co-ready writer mutates in place) —
    only fused wave's gather-before-scatter serves a true swap
    (test_wave_cyclic_war); properly synchronized JDFs use CTL edges.
    The contract here: no turbo, no deadlock, run completes."""
    jdf = """
descA [ type="collection" ]

SA(j)
j = 0 .. 0
: descA( 0, 0 )
READ  X <- descA( 1, 0 )
RW    Z <- descA( 0, 0 )
      -> descA( 0, 0 )
BODY
{
    Z = X
}
END

SB(j)
j = 0 .. 0
: descA( 1, 0 )
READ  X <- descA( 0, 0 )
RW    Z <- descA( 1, 0 )
      -> descA( 1, 0 )
BODY
{
    Z = X
}
END
"""
    fac = ptg.compile_jdf(jdf, name="swapt")
    M0 = np.arange(32, dtype=np.float32).reshape(8, 4)
    A = TwoDimBlockCyclic(8, 4, 4, 4, dtype=np.float32).from_numpy(
        M0.copy())
    tp = fac.new(descA=A)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    assert tp._turbo is None, "turbo must refuse a cyclic-WAR DAG"
    out = A.to_numpy()
    # one of the two classic serializations (order-dependent by design)
    half = np.vstack([M0[4:], M0[:4]])
    assert np.array_equal(out, half) or \
        np.array_equal(out[:4], M0[4:]) or \
        np.array_equal(out[4:], M0[:4]), out


def test_turbo_dgeqrf_scratch_and_rename(static_ctx):
    """QR exercises NEW scratch pools (T factors) and rename slots
    under PER-TASK priority order — the WAR/WAW edge machinery's
    hardest customer. R's diagonal must match numpy's up to sign."""
    from parsec_tpu.ops import dgeqrf_taskpool

    n, nb = 256, 64
    rng = np.random.RandomState(3)
    M = rng.rand(n, n).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dgeqrf_taskpool(A)
    static_ctx.add_taskpool(tp)
    static_ctx.wait()
    assert tp._turbo is not None
    R = np.triu(A.to_numpy())
    Rref = np.linalg.qr(M.astype(np.float64), mode="r")
    np.testing.assert_allclose(np.abs(np.diag(R)),
                               np.abs(np.diag(Rref)), rtol=1e-3)
