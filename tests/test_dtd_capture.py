"""DTD graph capture (dsl/dtd/capture.py): record an insert sequence,
execute it as one jitted XLA program; insertion order is the
serialization DTD semantics already guarantee."""
import numpy as np
import pytest

from parsec_tpu.dsl.dtd import INOUT, INPUT, OUTPUT, VALUE
from parsec_tpu.dsl.dtd.capture import dtd_capture


def test_chain_scales_once_dispatch():
    g = dtd_capture()
    a = g.tile_of_array(np.ones((8, 8), np.float32))
    for _ in range(10):
        g.insert_task(lambda x, s: x * s, (a, INOUT), (2.0, VALUE))
    assert g.nb_tasks == 10
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(a)), 1024.0)


def test_gemm_accumulate_graph():
    import jax.numpy as jnp
    n = 16
    rng = np.random.RandomState(0)
    An = rng.rand(n, n).astype(np.float32)
    Bn = rng.rand(n, n).astype(np.float32)
    g = dtd_capture()
    A = g.tile_of_array(An)
    B = g.tile_of_array(Bn)
    C = g.tile(("C",), shape=(n, n))

    def gemm(a, b, c):
        return c + jnp.matmul(a, b)

    for _ in range(3):
        g.insert_task(gemm, (A, INPUT), (B, INPUT), (C, INOUT))
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(C)), 3 * (An @ Bn),
                               rtol=1e-4, atol=1e-4)


def test_multiple_written_flows():
    g = dtd_capture()
    x = g.tile_of_array(np.full((4,), 3.0, np.float32))
    y = g.tile_of_array(np.full((4,), 4.0, np.float32))

    def swap_scale(a, b, s):
        return b * s, a * s

    g.insert_task(swap_scale, (x, INOUT), (y, INOUT), (10.0, VALUE))
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(x)), 40.0)
    np.testing.assert_allclose(np.asarray(g.value(y)), 30.0)


def test_output_only_flow_and_war():
    """WAR over a tile: a read inserted before an overwrite sees the old
    value — insertion order is the serialization."""
    g = dtd_capture()
    src = g.tile_of_array(np.full((4,), 7.0, np.float32))
    cpy = g.tile(("copy",), shape=(4,))
    # chore convention: one positional arg per param, OUTPUT tiles
    # included (their incoming array is ignored)
    g.insert_task(lambda s, _c: s + 0, (src, INPUT), (cpy, OUTPUT))
    g.insert_task(lambda s: s * 0, (src, INOUT))  # overwrite after the read
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(cpy)), 7.0)
    np.testing.assert_allclose(np.asarray(g.value(src)), 0.0)


def test_matches_runtime_dtd_execution():
    """Captured replay == the live DTD runtime on the same program."""
    import parsec_tpu
    from parsec_tpu import dtd
    from parsec_tpu.dsl.dtd import unpack_args

    steps = [1.5, 2.0, 0.5, 3.0]

    # runtime execution
    ctx = parsec_tpu.Context(nb_cores=2, enable_tpu=False)
    try:
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        tile = tp.tile_of_array(np.full((4, 4), 2.0, np.float32))

        def scale(es, task):
            x, s = unpack_args(task)
            x *= s

        for s in steps:
            tp.insert_task(scale, (tile, INOUT), (s, VALUE))
        tp.data_flush_all()
        tp.wait()
        runtime_out = np.array(tile.data.get_copy(0).payload)
    finally:
        ctx.fini()

    # captured execution
    g = dtd_capture()
    t = g.tile_of_array(np.full((4, 4), 2.0, np.float32))
    for s in steps:
        g.insert_task(lambda x, s: x * s, (t, INOUT), (s, VALUE))
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(t)), runtime_out,
                               rtol=1e-6)


def test_mixed_anon_and_named_tile_keys():
    """anon tuple keys + user string keys in one graph (jit pytree keys
    are uniform internal indices, so mixed user key types are fine)."""
    g = dtd_capture()
    a = g.tile_of_array(np.full((4,), 2.0, np.float32))       # anon key
    c = g.tile("named", shape=(4,))                            # str key
    g.insert_task(lambda x, _c: x * 5, (a, INPUT), (c, OUTPUT))
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(c)), 10.0)


def test_output_first_tile_needs_no_initial():
    """A tile whose first access is pure OUTPUT needs no shape/initial;
    its placeholder is the conventionally-ignored positional arg."""
    g = dtd_capture()
    src = g.tile_of_array(np.full((4,), 2.0, np.float32))
    dst = g.tile("dst")  # no shape, no initial
    g.insert_task(lambda s, _d: s + 1, (src, INPUT), (dst, OUTPUT))
    g.insert_task(lambda d: d * 2, (dst, INOUT))  # read after the write
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(dst)), 6.0)


def test_insert_after_run_retraces():
    g = dtd_capture()
    a = g.tile_of_array(np.ones((4,), np.float32))
    g.insert_task(lambda x: x + 1, (a, INOUT))
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(a)), 2.0)
    g.insert_task(lambda x: x * 10, (a, INOUT))
    g.run()
    np.testing.assert_allclose(np.asarray(g.value(a)), 20.0)


def test_errors():
    g = dtd_capture()
    a = g.tile(("uninit",))
    g.insert_task(lambda x: x, (a, INOUT))
    with pytest.raises(ValueError, match="no initial array"):
        g.run()

    g2 = dtd_capture()
    with pytest.raises(TypeError, match="CaptureTile"):
        g2.insert_task(lambda x: x, (np.ones(3), INOUT))

    g3 = dtd_capture()
    b = g3.tile_of_array(np.ones((2,), np.float32))
    c = g3.tile_of_array(np.ones((2,), np.float32))
    g3.insert_task(lambda x, y: x, (b, INOUT), (c, INOUT))  # 1 out, 2 written
    with pytest.raises(ValueError, match="written"):
        g3.run()
    with pytest.raises(RuntimeError, match="run"):
        g3.value(b)


def test_rebinding_tile_key_raises():
    import pytest
    from parsec_tpu.dsl.dtd.capture import CapturedDTDGraph

    g = CapturedDTDGraph()
    a = np.ones((4,), np.float32)
    t = g.tile_of_array(a, key="x")
    assert g.tile_of_array(a, key="x") is t          # same binding: fine
    with pytest.raises(ValueError):
        g.tile_of_array(np.zeros((4,), np.float32), key="x")
    g.tile("z", shape=(2, 2))
    with pytest.raises(ValueError):
        g.tile("z", shape=(3, 3))


def test_shapeless_tile_binds_shape_on_redeclare():
    from parsec_tpu.dsl.dtd.capture import CapturedDTDGraph

    g = CapturedDTDGraph()
    t = g.tile("w")                                  # OUTPUT-first intent
    t2 = g.tile("w", shape=(2, 3))                   # late shape binding
    assert t2 is t and t.initial.shape == (2, 3)
    # repeating the shape with the default dtype stays idempotent even
    # for non-default-dtype tiles
    g2 = CapturedDTDGraph()
    g2.tile("k", shape=(4,), dtype=np.float64)
    assert g2.tile("k", shape=(4,)).initial.dtype == np.float64
