"""Arenas: sized freelist allocators for task/communication buffers.

Reference behavior: per-(type, shape) freelists of buffers used for
communication and NEW-tile allocation, with MCA caps ``arena_max_used`` /
``arena_max_cached`` (ref: parsec/arena.c, parsec/parsec.c:681-686).

TPU-native re-design: an arena vends numpy host buffers (or, via a device
module hook, HBM-backed buffers) for a fixed Datatype. Freed buffers are
cached for reuse up to max_cached; max_used caps total live allocations.
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

from ..utils.params import params
from .data import Data, DataCopy, Coherency
from .datatype import Datatype


class Arena:
    def __init__(self, dtt: Datatype, max_used: Optional[int] = None,
                 max_cached: Optional[int] = None, allocator=None) -> None:
        self.dtt = dtt
        mu = params.get("arena_max_used") if max_used is None else max_used
        mc = params.get("arena_max_cached") if max_cached is None else max_cached
        self.max_used = None if mu in (-1, None) else mu
        self.max_cached = None if mc in (-1, None) else mc
        self._free: List[Any] = []
        self._used = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # allocator(dtt) -> backing buffer; default host numpy
        self._alloc = allocator or (lambda d: np.empty(d.shape, dtype=d.dtype))

    def allocate(self, block: bool = True) -> Any:
        with self._cond:
            while True:
                if self._free:
                    self._used += 1
                    return self._free.pop()
                if self.max_used is None or self._used < self.max_used:
                    self._used += 1
                    break
                if not block:
                    return None
                self._cond.wait()
        return self._alloc(self.dtt)

    def free(self, buf: Any) -> None:
        with self._cond:
            self._used -= 1
            if self.max_cached is None or len(self._free) < self.max_cached:
                self._free.append(buf)
            self._cond.notify()

    @property
    def used(self) -> int:
        return self._used

    @property
    def cached(self) -> int:
        return len(self._free)

    # -- data-copy integration ---------------------------------------------
    def new_copy(self, data: Data, device_id: int = 0) -> DataCopy:
        """Allocate an arena-backed DataCopy (recycled on copy destruct)."""
        buf = self.allocate()
        copy = DataCopy(data, device_id, payload=buf, dtt=self.dtt)
        copy.arena_chunk = _ArenaChunk(self, buf)
        data.attach_copy(copy)
        return copy


class _ArenaChunk:
    __slots__ = ("arena", "buf")

    def __init__(self, arena: Arena, buf: Any) -> None:
        self.arena = arena
        self.buf = buf

    def release_copy(self, copy: DataCopy) -> None:
        self.arena.free(self.buf)
        self.buf = None


class ZoneMalloc:
    """Segment-based arena allocator for device-heap offset bookkeeping
    (ref: parsec/utils/zone_malloc.c — the GPU heap sub-allocator).

    ``malloc(nbytes) -> offset`` (-1 when full, caller evicts), ``free``,
    with first-fit + coalescing. Backed by the native C++ implementation
    when available; this Python fallback keeps identical semantics.
    """

    def __init__(self, total: int, align: int = 512) -> None:
        if total <= 0 or align <= 0 or (align & (align - 1)):
            raise ValueError("total must be > 0, align a positive power of two")
        self.total = total
        self.align = align
        self._used = 0
        self._lock = threading.Lock()
        self._segs: List[List[int]] = [[0, total, 1]]  # [off, size, free]

    def malloc(self, nbytes: int) -> int:
        if nbytes <= 0:
            raise ValueError("nbytes must be > 0")
        want = (nbytes + self.align - 1) & ~(self.align - 1)
        with self._lock:
            for i, seg in enumerate(self._segs):
                off, size, free = seg
                if not free or size < want:
                    continue
                if size > want:
                    self._segs.insert(i + 1, [off + want, size - want, 1])
                    seg[1] = want
                seg[2] = 0
                self._used += want
                return off
        return -1

    def free(self, offset: int) -> None:
        with self._lock:
            for i, seg in enumerate(self._segs):
                if seg[0] == offset and not seg[2]:
                    seg[2] = 1
                    self._used -= seg[1]
                    if i + 1 < len(self._segs) and self._segs[i + 1][2]:
                        seg[1] += self._segs[i + 1][1]
                        del self._segs[i + 1]
                    if i > 0 and self._segs[i - 1][2]:
                        self._segs[i - 1][1] += seg[1]
                        del self._segs[i]
                    return
        raise ValueError("invalid or double free")

    def used(self) -> int:
        return self._used

    def available(self) -> int:
        return self.total - self._used

    def largest_free(self) -> int:
        with self._lock:
            return max((s[1] for s in self._segs if s[2]), default=0)


try:  # prefer the native C++ zone allocator
    from ..native import native as _native
    if _native is not None:
        PyZoneMalloc = ZoneMalloc
        ZoneMalloc = _native.ZoneMalloc  # type: ignore[misc,assignment]
except ImportError:  # pragma: no cover
    pass
