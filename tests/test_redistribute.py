"""Redistribution engine tests (ref coverage model:
tests/collections/redistribute/ — PTG redistribution with checking
variants incl. random sizes, SURVEY.md §4).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import (TwoDimBlockCyclic, TwoDimTabular,
                                    redistribute, reshard_array)
from parsec_tpu.comm import RemoteDepEngine

from test_comm_multirank import spmd


def _check(source_np, target_np_before, target_after,
           size_row, size_col, diY, djY, diT, djT):
    expect = target_np_before.copy()
    expect[diT:diT + size_row, djT:djT + size_col] = \
        source_np[diY:diY + size_row, djY:djY + size_col]
    np.testing.assert_array_equal(target_after, expect)


@pytest.mark.parametrize("geometry", [
    # (lmY, lnY, mbY, nbY, lmT, lnT, mbT, nbT, M, N, diY, djY, diT, djT)
    (8, 8, 4, 4, 8, 8, 4, 4, 8, 8, 0, 0, 0, 0),        # aligned same-tile
    (12, 12, 4, 4, 12, 12, 3, 3, 12, 12, 0, 0, 0, 0),  # different tile sizes
    (16, 12, 5, 4, 12, 16, 3, 5, 7, 9, 2, 1, 3, 4),    # unaligned submatrix
])
def test_redistribute_single_process(ctx, geometry):
    (lmY, lnY, mbY, nbY, lmT, lnT, mbT, nbT,
     M, N, diY, djY, diT, djT) = geometry
    rng = np.random.RandomState(42)
    src_np = rng.rand(lmY, lnY)
    tgt_np = rng.rand(lmT, lnT)
    Y = TwoDimBlockCyclic(lmY, lnY, mbY, nbY, dtype=np.float64).from_numpy(src_np)
    T = TwoDimBlockCyclic(lmT, lnT, mbT, nbT, dtype=np.float64).from_numpy(tgt_np)
    redistribute(Y, T, M, N, diY, djY, diT, djT, context=ctx)
    _check(src_np, tgt_np, T.to_numpy(), M, N, diY, djY, diT, djT)


def test_redistribute_random_sizes(ctx):
    rng = np.random.RandomState(7)
    for trial in range(4):
        lmY, lnY = rng.randint(6, 20, size=2)
        lmT, lnT = rng.randint(6, 20, size=2)
        mbY, nbY = rng.randint(2, 6, size=2)
        mbT, nbT = rng.randint(2, 6, size=2)
        M = rng.randint(1, min(lmY, lmT) + 1)
        N = rng.randint(1, min(lnY, lnT) + 1)
        diY = rng.randint(0, lmY - M + 1)
        djY = rng.randint(0, lnY - N + 1)
        diT = rng.randint(0, lmT - M + 1)
        djT = rng.randint(0, lnT - N + 1)
        src_np = rng.rand(lmY, lnY)
        tgt_np = rng.rand(lmT, lnT)
        Y = TwoDimBlockCyclic(int(lmY), int(lnY), int(mbY), int(nbY),
                              dtype=np.float64).from_numpy(src_np)
        T = TwoDimBlockCyclic(int(lmT), int(lnT), int(mbT), int(nbT),
                              dtype=np.float64).from_numpy(tgt_np)
        redistribute(Y, T, int(M), int(N), int(diY), int(djY),
                     int(diT), int(djT), context=ctx)
        _check(src_np, tgt_np, T.to_numpy(), M, N, diY, djY, diT, djT)


@pytest.mark.parametrize("nb_ranks", [2, 4])
def test_redistribute_multirank(nb_ranks):
    """Block-cyclic P×1 source -> 1×Q target with different tile sizes:
    most fragments cross ranks."""
    lm = ln = 12
    rng = np.random.RandomState(3)
    src_np = rng.rand(lm, ln)
    tgt_np = rng.rand(lm, ln)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            Y = TwoDimBlockCyclic(lm, ln, 4, 4, P=nb_ranks, Q=1,
                                  nodes=nb_ranks, rank=rank,
                                  dtype=np.float64).from_numpy(src_np)
            T = TwoDimBlockCyclic(lm, ln, 3, 3, P=1, Q=nb_ranks,
                                  nodes=nb_ranks, rank=rank,
                                  dtype=np.float64).from_numpy(tgt_np)
            redistribute(Y, T, 10, 10, disi_Y=1, disj_Y=2,
                         disi_T=2, disj_T=1, context=ctx)
            # collect this rank's local target tiles
            out = {}
            for (m, n) in T.local_tiles():
                out[(m, n)] = np.array(T.tile(m, n))
            return out
        finally:
            ctx.fini()

    results, _ = spmd(nb_ranks, rank_fn)
    # assemble the distributed result
    expect = tgt_np.copy()
    expect[2:12, 1:11] = src_np[1:11, 2:12]
    got = np.zeros_like(expect)
    T_geom = TwoDimBlockCyclic(lm, ln, 3, 3, P=1, Q=nb_ranks, nodes=nb_ranks)
    for r, tiles in enumerate(results):
        for (m, n), arr in tiles.items():
            tm, tn = T_geom.tile_shape(m, n)
            got[m * 3:m * 3 + tm, n * 3:n * 3 + tn] = arr
    np.testing.assert_array_equal(got, expect)


def test_redistribute_tabular_target(ctx):
    """Irregular per-tile rank table target (single process)."""
    lm = ln = 10
    rng = np.random.RandomState(11)
    src_np = rng.rand(lm, ln)
    Y = TwoDimBlockCyclic(lm, ln, 3, 3, dtype=np.float64).from_numpy(src_np)
    T = TwoDimTabular.random(lm, ln, 4, 4, nodes=1, dtype=np.float64)
    tgt_np = np.zeros((lm, ln))
    T.from_numpy(tgt_np)
    redistribute(Y, T, lm, ln, context=ctx)
    np.testing.assert_array_equal(T.to_numpy(), src_np)


def test_reshard_array_roundtrip():
    import jax
    from jax.sharding import PartitionSpec as P
    from parsec_tpu.parallel import make_mesh
    mesh = make_mesh(sizes={"dp": 2, "tp": 2},
                     devices=jax.devices("cpu")[:4])
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    a = reshard_array(jax.numpy.asarray(x), mesh, P("dp", "tp"))
    b = reshard_array(a, mesh, P("tp", "dp"))
    c = reshard_array(b, mesh, P())
    np.testing.assert_array_equal(np.asarray(c), x)
