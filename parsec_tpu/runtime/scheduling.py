"""The progress engine: select → prepare_input → execute → complete → release.

Reference behavior: the worker-thread main loop ``__parsec_context_wait``
(select with scheduler, exponential backoff when idle), task progress
``__parsec_task_progress`` (prepare_input may return ASYNC; execute walks the
incarnation list honoring ``evaluate`` vetoes; CPU hooks run inline while
accelerator hooks hand off and return ASYNC), completion runs the generated
``release_deps`` which feeds freshly-enabled tasks back to ``__parsec_schedule``
— keeping the single highest-priority one on the releasing thread
(ref: parsec/scheduling.c:124-203, 284-328, 439-533, 535-666, 610-615).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import logging as plog
from ..utils.params import params
from ..profiling.grapher import grapher
from ..profiling.pins import PINS, PinsEvent
from ..profiling.sde import TASKS_ENABLED, TASKS_RETIRED
from .profile import TENANT_PRIO_SCALE
from .taskpool import HookReturn, Task, TaskStatus, ACTION_RELEASE_ALL

_sched_log = plog.sched_stream

#: declared lock discipline, enforced by the concurrency lint
#: (parsec_tpu/analysis/lock_check.py).  The audit result for this
#: module is deliberately EMPTY: the progress loop owns no locked
#: shared state — ``es.next_task`` and the backoff are worker-private,
#: taskpool counters delegate to the termination detector, and the
#: scheduler queues are declared in sched/modules.py (rnd) or ride the
#: internally-synchronized containers of core/lists.py.  Keeping the
#: (empty) map here keeps the module inside the lint's contract: any
#: future lock added to this file must register its fields or fail the
#: tier-1 self-lint gate's review convention.
_GUARDED_BY: Dict[str, str] = {}


class ExecutionStream:
    """Per-worker execution stream (ref: parsec_execution_stream_t)."""

    def __init__(self, context, th_id: int, vp_id: int = 0,
                 vp_local_id: int = 0) -> None:
        self.context = context
        self.th_id = th_id
        self.vp_id = vp_id
        self.vp_local_id = vp_local_id  # position within the VP's stream list
        self.next_task: Optional[Task] = None   # scheduler-bypass slot
        self.sched_obj: Any = None               # scheduler-private queues
        self.rnd_seed = (th_id * 2654435761) & 0xFFFFFFFF
        self.profiling_stream = None
        self.nb_tasks_executed = 0

    @property
    def virtual_process(self):
        return self.context.vps[self.vp_id]

    def rand(self) -> int:
        # xorshift for scheduler tie-breaks / steal targets
        x = self.rnd_seed or 0x9E3779B9
        x ^= (x << 13) & 0xFFFFFFFF
        x ^= x >> 17
        x ^= (x << 5) & 0xFFFFFFFF
        self.rnd_seed = x
        return x


def stamp_dynamic_priority(ctx, tasks: List[Task]) -> None:
    """Critical-path-driven priorities (ISSUE 7): re-stamp each task's
    scheduling priority from the online class profile's upward-rank
    boost, with the DSL's static priority expression as the tiebreak
    (``runtime/profile.py``).  Idempotent — recomputed from the task's
    immutable ``base_priority`` — so a rescheduled (AGAIN) task is not
    boosted twice, and a no-op when ``sched_dynamic_priority`` is off
    or the class is unknown to the profile (DTD bodies keep their
    static priority untouched).

    Multi-tenant fairness (serve/, ISSUE 18) folds on TOP through the
    same seam: when a SessionServer attached a ``TenantFairness`` to
    the context, each task additionally gains its tenant's deficit
    boost packed above the class-profile band (TENANT_PRIO_SCALE), so
    starved tenants rise and saturating tenants yield while the
    critical-path boost stays the within-tenant order.  The untouched
    ap/spq/pbq schedulers consume the combined integer unchanged; both
    hooks None (no profile, no server) keeps the exact pre-ISSUE-7
    fast path."""
    prof = ctx.class_profile
    fair = ctx.serve_fairness
    if prof is None and fair is None:
        return
    for t in tasks:
        p = (prof.effective(t.task_class.name, t.base_priority)
             if prof is not None else t.base_priority)
        if fair is not None:
            b = fair.boost_of_task(t)
            if b:
                p += b * TENANT_PRIO_SCALE
        t.priority = p


def schedule(es: ExecutionStream, tasks: List[Task], distance: int = 0) -> None:
    """ref: __parsec_schedule (scheduling.c:284-328) — hand a ring of ready
    tasks to the scheduler module; paranoid checks that every task really is
    ready (all input refs fulfilled)."""
    if not tasks:
        return
    ctx = es.context
    stamp_dynamic_priority(ctx, tasks)
    if __debug__:
        for t in tasks:
            assert t.status in (TaskStatus.NONE, TaskStatus.PREPARE_INPUT), \
                f"scheduling task {t.snprintf()} in state {t.status}"
    PINS(es, PinsEvent.SCHEDULE_BEGIN, tasks)
    ctx.scheduler.schedule(es, tasks, distance)
    PINS(es, PinsEvent.SCHEDULE_END, tasks)
    ctx.sde.inc(TASKS_ENABLED, len(tasks))
    ctx.wake_workers(len(tasks))


def schedule_keep_best(es: ExecutionStream, tasks: List[Task], distance: int = 0) -> None:
    """Keep the highest-priority freshly-enabled task on the releasing thread
    (es.next_task) and hand the rest to the scheduler
    (ref: scheduling.c:610-615, parsec_internal.h:463-470)."""
    if not tasks:
        return
    # stamp BEFORE picking the bypass task so "highest priority" and the
    # scheduler's queue order agree on the same (dynamic) priority
    stamp_dynamic_priority(es.context, tasks)
    if es.context.keep_highest_priority_task and es.next_task is None:
        best = max(range(len(tasks)), key=lambda i: tasks[i].priority)
        es.next_task = tasks.pop(best)
        es.context.sde.inc(TASKS_ENABLED, 1)  # bypasses schedule()'s count
    schedule(es, tasks, distance)


def execute(es: ExecutionStream, task: Task) -> HookReturn:
    """ref: __parsec_execute (scheduling.c:124-203) — walk incarnations by
    chore mask; evaluate() may veto a chore; the first willing hook runs."""
    tc = task.task_class
    task.status = TaskStatus.HOOK
    PINS(es, PinsEvent.EXEC_BEGIN, task)
    try:
        for idx in tc.chore_order():
            chore = tc.incarnations[idx]
            if not (task.chore_mask & (1 << idx)):
                continue
            if chore.evaluate is not None and not chore.evaluate(task):
                continue
            task.selected_chore = idx
            rc = chore.hook(es, task)
            if rc == HookReturn.NEXT:
                task.chore_mask &= ~(1 << idx)
                continue
            if rc == HookReturn.DISABLE:
                task.chore_mask &= ~(1 << idx)
                continue
            return rc
        plog.warning("task %s has no eligible chore left", task.snprintf())
        return HookReturn.ERROR
    finally:
        PINS(es, PinsEvent.EXEC_END, task)


def complete_execution(es: ExecutionStream, task: Task) -> None:
    """ref: __parsec_complete_execution (scheduling.c:439-468)."""
    tc = task.task_class
    task.status = TaskStatus.COMPLETE
    PINS(es, PinsEvent.COMPLETE_EXEC_BEGIN, task)
    if tc.prepare_output is not None:
        tc.prepare_output(es, task)
    if tc.complete_execution is not None:
        tc.complete_execution(es, task)
    if tc.release_deps is not None:
        PINS(es, PinsEvent.RELEASE_DEPS_BEGIN, task)
        ready = tc.release_deps(es, task, ACTION_RELEASE_ALL)
        PINS(es, PinsEvent.RELEASE_DEPS_END, task)
    else:
        ready = []
    es.nb_tasks_executed += 1
    es.context.sde.inc(TASKS_RETIRED)
    grapher.task_executed(es, task)
    tp = task.taskpool
    if tc.release_task is not None:
        tc.release_task(es, task)
    tp.task_completed()
    if ready:
        schedule_keep_best(es, list(ready))
    PINS(es, PinsEvent.COMPLETE_EXEC_END, task)


def task_progress(es: ExecutionStream, task: Task, distance: int = 0) -> None:
    """ref: __parsec_task_progress (scheduling.c:470-533)."""
    tc = task.task_class
    if task.status < TaskStatus.PREPARE_INPUT:
        task.status = TaskStatus.PREPARE_INPUT
        if tc.prepare_input is not None:
            PINS(es, PinsEvent.PREPARE_INPUT_BEGIN, task)
            rc = tc.prepare_input(es, task)
            PINS(es, PinsEvent.PREPARE_INPUT_END, task)
            if rc == HookReturn.ASYNC:
                return  # a future/stage-in will reschedule the task
            if rc == HookReturn.AGAIN:
                schedule(es, [task], distance + 1)
                return
            assert rc == HookReturn.DONE, f"prepare_input returned {rc}"
    prof = es.context.class_profile
    t0 = time.perf_counter_ns() if prof is not None else 0
    rc = execute(es, task)
    if rc == HookReturn.DONE:
        if prof is not None:
            # synchronous (CPU-chore) execution: feed the class profile
            # with the measured body time — the host half of the
            # duration-weighted EWMA (the device half comes from the
            # device module's dispatch timings)
            prof.note(tc.name, (time.perf_counter_ns() - t0) / 1e3)
        complete_execution(es, task)
    elif rc == HookReturn.ASYNC:
        pass  # device module owns completion now (SURVEY.md §3.4)
    elif rc == HookReturn.AGAIN:
        task.status = TaskStatus.PREPARE_INPUT
        schedule(es, [task], distance + 1)
    else:
        plog.fatal("task %s execution failed (rc=%s)", task.snprintf(), rc)


class _Backoff:
    """Exponential idle backoff (ref: scheduling.c idle loop + utils/backoff)."""

    __slots__ = ("misses",)
    MAX_SLEEP = 2e-3

    def __init__(self) -> None:
        self.misses = 0

    def hit(self) -> None:
        self.misses = 0

    def miss(self, context) -> None:
        self.misses += 1
        if self.misses < 4:
            return  # spin
        sleep = min(1e-5 * (1 << min(self.misses - 4, 8)), self.MAX_SLEEP)
        context.park(sleep)


def es_rusage_report(es: ExecutionStream) -> dict:
    """Per-ES thread resource usage delta since the last call on the SAME
    OS thread (ref: the per-ES getrusage reports, scheduling.c:45-90);
    logged at verbosity >= 3 from each wait-loop exit. Baselines are kept
    per calling thread: ES 0 runs on whichever thread drives wait(), so a
    baseline from another thread must not pollute the delta. maxrss_kb is
    reported as the absolute process high-water mark (getrusage has no
    per-thread rss)."""
    import resource
    ru = resource.getrusage(getattr(resource, "RUSAGE_THREAD",
                                    resource.RUSAGE_SELF))
    tid = threading.get_ident()
    cur = {"utime_s": ru.ru_utime, "stime_s": ru.ru_stime,
           "vcsw": ru.ru_nvcsw, "ivcsw": ru.ru_nivcsw,
           "minflt": ru.ru_minflt, "maxrss_kb": ru.ru_maxrss}
    prevs = getattr(es, "_last_rusage", None)
    if prevs is None:
        prevs = es._last_rusage = {}
    prev = prevs.get(tid)
    prevs[tid] = cur
    if prev is None:
        return dict(cur)
    out = {k: cur[k] - prev[k] for k in cur if k != "maxrss_kb"}
    out["maxrss_kb"] = cur["maxrss_kb"]
    return out


def context_wait_loop(es: ExecutionStream) -> None:
    """The worker main loop (ref: __parsec_context_wait scheduling.c:535-666).

    Runs until the context signals completion of all active taskpools.
    Idle cycles progress device managers and the communication engine.
    """
    ctx = es.context
    backoff = _Backoff()
    busy_spins = 0
    while not ctx.all_tasks_done():
        task = es.next_task
        es.next_task = None
        if task is None:
            PINS(es, PinsEvent.SELECT_BEGIN, None)
            task = ctx.scheduler.select(es)
            PINS(es, PinsEvent.SELECT_END, task)
        try:
            if task is not None:
                backoff.hit()
                task_progress(es, task)
                # bounded device poll on the BUSY path: a sub-batch-max
                # accumulation on a device must not starve behind a
                # long run of CPU-bound tasks that never lets this
                # worker reach the idle-cycle engine progress (an empty
                # device queue makes this a try-lock + two list checks)
                busy_spins += 1
                if busy_spins & 63 == 0:
                    for dev in ctx.devices:
                        dev.progress(es)
                continue
            # engines before native loops: a claimed native loop owns
            # this worker for a whole lowered DAG, and the device
            # managers' accumulated ready batches / deferred prefetches
            # must flush first so they overlap it (SURVEY.md §3.4; the
            # batched-dispatch pipeline defers flushes to idle cycles)
            progressed = ctx.progress_engines(es)
            if ctx.run_native_loops(es):
                backoff.hit()
                continue
        except BaseException as exc:  # a task body blew up: abort the DAG,
            ctx.record_task_error(exc, task)  # don't silently kill the worker
            continue
        if progressed:
            backoff.hit()
        else:
            backoff.miss(ctx)
    if plog.debug.verbosity >= 3:
        plog.debug.verbose(3, "es %d rusage: %s", es.th_id,
                           es_rusage_report(es))
