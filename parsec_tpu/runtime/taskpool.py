"""Task model: task classes, flows, chores, tasks, taskpools.

Reference behavior: ``parsec_task_class_t`` carries in/out flows, parameter
symbols, a priority expression, an ``incarnations`` chore list (one per
device type, each with optional ``evaluate`` + ``hook``), and the generated
lifecycle functions ``prepare_input`` / ``release_deps`` /
``iterate_successors`` (ref: parsec/parsec_internal.h:380-437).
``parsec_taskpool_t`` tracks pending tasks + actions and its termination
detector (ref: parsec/parsec_internal.h:119-161).

TPU-native notes: a chore's hook for device type "tpu" typically wraps a
jax-jit executable; the device module owns stage-in/out and asynchronous
completion (HOOK_RETURN_ASYNC), mirroring the CUDA chore handoff
(SURVEY.md §3.4).
"""
from __future__ import annotations

import itertools
import threading
from enum import IntEnum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..core.object import Obj
from ..data.data import FlowAccess
from ..data.datarepo import DataRepo
from ..utils import logging as plog


class HookReturn(IntEnum):
    """ref: parsec_hook_return_t"""
    DONE = 0        # body ran, task complete
    ASYNC = 1       # a device/async engine took ownership of completion
    NEXT = 2        # this chore declined; try the next incarnation
    AGAIN = 3       # re-schedule the task later
    DISABLE = 4     # disable this chore for the whole task class
    ERROR = 5


class TaskStatus(IntEnum):
    """ref: parsec_task_status_t parsec/parsec_internal.h:476-481"""
    NONE = 0
    PREPARE_INPUT = 1
    EVAL = 2
    HOOK = 3
    COMPLETE = 4


#: release_deps action masks (ref: PARSEC_ACTION_* parsec/parsec_internal.h)
ACTION_RELEASE_LOCAL_DEPS = 0x1
ACTION_RELEASE_REMOTE_DEPS = 0x2
ACTION_SEND_REMOTE_DEPS = 0x4
ACTION_RELEASE_ALL = 0xFFFF


class Chore:
    """One incarnation of a task class on one device type.

    ref: __parsec_chore_t {type, evaluate, hook} parsec/parsec_internal.h:380-392
    """
    __slots__ = ("device_type", "evaluate", "hook", "dyld_fn", "batch_spec")

    def __init__(self, device_type: str,
                 hook: Callable[["ExecutionStream", "Task"], HookReturn],
                 evaluate: Optional[Callable[["Task"], bool]] = None,
                 dyld_fn: Any = None, batch_spec: Any = None) -> None:
        self.device_type = device_type
        self.hook = hook
        self.evaluate = evaluate
        self.dyld_fn = dyld_fn  # device payload: e.g. the jax callable for tpu
        # batched-dispatch recipe (devices/batching.DeviceBatchSpec):
        # lets the device stack same-class ready tasks into one jitted
        # call; None = per-task dispatch only
        self.batch_spec = batch_spec


class Dep:
    """One dependency edge on a flow (ref: parsec_dep_t).

    ``guard`` decides applicability from the task's locals; ``target`` names
    the peer task class (or None for memory access via the collection);
    ``target_locals`` computes the peer's assignments; ``flow_name`` is the
    peer flow.
    """
    __slots__ = ("target", "flow_name", "guard", "target_locals", "dtt", "ctl")

    def __init__(self, target: Optional[str], flow_name: Optional[str] = None,
                 guard: Optional[Callable[..., bool]] = None,
                 target_locals: Optional[Callable[..., Any]] = None,
                 dtt: Any = None, ctl: bool = False) -> None:
        self.target = target
        self.flow_name = flow_name
        self.guard = guard
        self.target_locals = target_locals
        self.dtt = dtt
        self.ctl = ctl


class Flow:
    """A named data flow of a task class (ref: parsec_flow_t,
    parsec/include/parsec/parsec_description_structures.h:92)."""
    __slots__ = ("name", "access", "flow_index", "deps_in", "deps_out", "ctl")

    def __init__(self, name: str, access: FlowAccess, flow_index: int,
                 deps_in: Optional[List[Dep]] = None,
                 deps_out: Optional[List[Dep]] = None, ctl: bool = False) -> None:
        self.name = name
        self.access = access
        self.flow_index = flow_index
        self.deps_in = deps_in or []
        self.deps_out = deps_out or []
        self.ctl = ctl


class TaskDataRef:
    """Per-flow data binding of one task instance (ref: parsec_data_pair_t)."""
    __slots__ = ("source_repo", "source_repo_key", "data_in", "data_out", "fulfilled")

    def __init__(self) -> None:
        self.source_repo: Optional[DataRepo] = None
        self.source_repo_key: Any = None
        self.data_in = None    # DataCopy consumed
        self.data_out = None   # DataCopy produced
        self.fulfilled = False


class TaskClass:
    """ref: parsec_task_class_t"""

    def __init__(self, name: str, task_class_id: int, nb_flows: int,
                 flows: Optional[List[Flow]] = None,
                 incarnations: Optional[List[Chore]] = None,
                 nb_locals: int = 0,
                 priority_fn: Optional[Callable[["Task"], int]] = None) -> None:
        self.name = name
        self.task_class_id = task_class_id
        self.nb_flows = nb_flows
        self.flows = flows or []
        self.incarnations: List[Chore] = incarnations or []
        self.nb_locals = nb_locals
        self.priority_fn = priority_fn
        self.repo = DataRepo(nb_flows) if nb_flows else None
        # lifecycle hooks; DSLs fill these in
        self.prepare_input: Optional[Callable] = None
        self.prepare_output: Optional[Callable] = None
        self.release_deps: Optional[Callable] = None
        self.iterate_successors: Optional[Callable] = None
        self.iterate_predecessors: Optional[Callable] = None
        self.complete_execution: Optional[Callable] = None
        self.release_task: Optional[Callable] = None
        self.key_fn: Callable[[Tuple], Any] = lambda locals_: locals_
        self.time_estimate: Optional[Callable[["Task", Any], float]] = None

    def chore_mask_all(self) -> int:
        # open-ended: chores appended later (DTD add_chore) stay eligible
        return 0xFFFFFFFF

    def chore_order(self) -> List[int]:
        """Execution preference: accelerator incarnations first (the
        generated code lists the CUDA chore before CPU; ref jdf2c.c:6557)."""
        return sorted(range(len(self.incarnations)),
                      key=lambda i: self.incarnations[i].device_type == "cpu")

    def __repr__(self) -> str:  # pragma: no cover
        return f"<TaskClass {self.name}#{self.task_class_id} flows={self.nb_flows}>"


class Task(Obj):
    """One task instance (ref: parsec_task_t)."""

    __slots__ = ("taskpool", "task_class", "locals", "priority",
                 "base_priority", "status",
                 "chore_mask", "selected_device", "selected_chore", "data",
                 "repo_entry", "body_args", "user", "es_hint", "dtd",
                 "flow_access")

    def __init__(self, taskpool: "Taskpool", task_class: TaskClass,
                 locals_: Tuple = (), priority: int = 0) -> None:
        super().__init__()
        self.taskpool = taskpool
        self.task_class = task_class
        self.locals = locals_
        self.priority = priority
        # the DSL's static priority expression, kept apart from
        # ``priority`` (which the dynamic critical-path profile may
        # re-stamp at every schedule — runtime/profile.py): re-stamping
        # recomputes from this base, so it stays idempotent
        self.base_priority = priority
        self.status = TaskStatus.NONE
        self.chore_mask = task_class.chore_mask_all()
        self.selected_device = None      # devices.Device once placed
        self.selected_chore: Optional[int] = None
        self.data: List[TaskDataRef] = [TaskDataRef() for _ in range(task_class.nb_flows)]
        self.repo_entry = None
        self.body_args: Any = None       # DSL-specific payload (DTD param list)
        self.user: Any = None
        self.es_hint: int = -1
        self.dtd: Any = None             # DTD bookkeeping record
        # per-instance access override (DTD: same body, different modes per
        # insertion; PTG instances inherit the class flows and leave it None)
        self.flow_access: Optional[List[FlowAccess]] = None

    def access_of(self, flow: "Flow") -> FlowAccess:
        if self.flow_access is not None:
            return self.flow_access[flow.flow_index]
        return flow.access

    @property
    def key(self) -> Any:
        return self.task_class.key_fn(self.locals)

    def snprintf(self) -> str:
        args = ", ".join(map(str, self.locals))
        return f"{self.task_class.name}({args})"

    def __repr__(self) -> str:  # pragma: no cover
        return f"<Task {self.snprintf()} prio={self.priority}>"


class Taskpool(Obj):
    """ref: parsec_taskpool_t — a DAG instance submitted to a context."""

    _id_iter = itertools.count(1)

    def __init__(self, name: str = "taskpool", nb_task_classes: int = 0) -> None:
        super().__init__()
        self.taskpool_id = next(Taskpool._id_iter)
        self.name = name
        self.context = None
        self.task_classes: List[TaskClass] = []
        self.nb_task_classes = nb_task_classes
        self.devices_index_mask = ~0
        self.priority = 0
        self.tdm = None                   # termination detector, set on enqueue
        self.on_enqueue: Optional[Callable] = None
        self.on_complete: Optional[Callable] = None
        self.startup_hook: Optional[Callable] = None  # (context, tp) -> [ready tasks]
        self._complete_cbs: List[Callable] = []
        # run from abort() (ft/ eviction), NOT on normal termination —
        # observers that charge state per live pool (the serving
        # layer's admission accounting) hook both lists
        self._abort_cbs: List[Callable] = []
        self._lock = threading.Lock()
        self._completed = threading.Event()
        self.aborted = False    # ft/: rank eviction aborted this DAG
        self._finishing = False  # abort/termination claimed (see _claim)
        # lazily-constructed per-taskpool info items (ref: info object
        # arrays hanging off parsec_taskpool_t; torn down on completion)
        from ..core.info import InfoObjectArray, taskpool_infos
        self.info = InfoObjectArray(taskpool_infos, self)

    # -- task accounting (delegated to the termination detector) ------------
    def add_tasks(self, n: int) -> None:
        self.tdm.taskpool_addto_nb_tasks(n)

    def task_completed(self, n: int = 1) -> None:
        self.tdm.taskpool_addto_nb_tasks(-n)

    def add_pending_action(self, n: int = 1) -> None:
        self.tdm.taskpool_addto_runtime_actions(n)

    def pending_action_done(self, n: int = 1) -> None:
        self.tdm.taskpool_addto_runtime_actions(-n)

    def set_nb_tasks(self, n: int) -> None:
        self.tdm.taskpool_set_nb_tasks(n)

    # -- completion ---------------------------------------------------------
    def _claim_finish(self, abort: bool) -> bool:
        """Atomically claim the ONE finish of this pool. An abort (the
        ft/ eviction path, fired from a detector/transport thread) and
        a termdet settle (a worker thread) can race; whoever claims
        first decides whether completion callbacks run — an unlocked
        check-then-act would let callbacks fire on a pool the runtime
        is simultaneously declaring failed."""
        with self._lock:
            if self._finishing:
                return False
            self._finishing = True
            self.aborted = abort
            return True

    def abort(self) -> None:
        """FT eviction path (ft/): the DAG cannot finish (a
        participating rank is gone). Unblock ``wait_completed`` WITHOUT
        running the completion callbacks — the pool did not complete,
        and a waiter must consult the context's recorded errors. The
        dedicated ``_abort_cbs`` DO run, so per-pool charges held by
        observers (serve/ admission) are released either way. A late
        termination_detected (counters settling after the abort) is a
        no-op; losing the claim to a real termination is fine too (the
        pool DID complete — nothing to abort)."""
        if not self._claim_finish(abort=True):
            return
        plog.warning("taskpool %d (%s) aborted (rank eviction)",
                     self.taskpool_id, self.name)
        for cb in self._abort_cbs:
            cb(self)
        ctx = self.context
        self._completed.set()
        if ctx is not None:
            ctx._taskpool_done(self)

    def termination_detected(self) -> None:
        """ref: parsec_taskpool_termination_detected (scheduling.c:212-230)"""
        if not self._claim_finish(abort=False):
            return
        plog.debug.verbose(5, "taskpool %d (%s) terminated", self.taskpool_id, self.name)
        if self.on_complete is not None:
            self.on_complete(self)
        for cb in self._complete_cbs:
            cb(self)
        ctx = self.context
        self._completed.set()
        if ctx is not None:
            ctx._taskpool_done(self)

    def wait_completed(self, timeout: Optional[float] = None) -> bool:
        return self._completed.wait(timeout)

    @property
    def completed(self) -> bool:
        return self._completed.is_set()
