"""MCA parameter system tests (ref: parsec/utils/mca_param.c behavior)."""
import os

import pytest

from parsec_tpu.utils.params import ParamRegistry


@pytest.fixture
def reg():
    return ParamRegistry()


def test_default_resolution(reg):
    reg.reg_int("x", 7)
    assert reg.get("x") == 7
    assert reg.source("x") == "default"


def test_env_overrides_default(reg, monkeypatch):
    reg.reg_int("window", 100)
    monkeypatch.setenv("PARSEC_MCA_window", "42")
    assert reg.get("window") == 42
    assert reg.source("window") == "env"


def test_cmdline_overrides_env(reg, monkeypatch):
    reg.reg_string("sched", "lfq")
    monkeypatch.setenv("PARSEC_MCA_sched", "gd")
    rest = reg.parse_argv(["prog", "--mca", "sched", "ap", "positional"])
    assert rest == ["prog", "positional"]
    assert reg.get("sched") == "ap"
    assert reg.source("sched") == "cmdline"


def test_parse_argv_forms(reg):
    reg.reg_int("a", 0)
    reg.reg_int("b", 0)
    rest = reg.parse_argv(["--mca=a=1", "--parsec", "b=2", "keep"])
    assert rest == ["keep"]
    assert reg.get("a") == 1 and reg.get("b") == 2


def test_typed_coercion(reg, monkeypatch):
    reg.reg_bool("flag", False)
    reg.reg_sizet("sz", 0)
    monkeypatch.setenv("PARSEC_MCA_flag", "yes")
    monkeypatch.setenv("PARSEC_MCA_sz", "0x100")
    assert reg.get("flag") is True
    assert reg.get("sz") == 256


def test_sizet_rejects_negative(reg):
    reg.reg_sizet("n", 0)
    reg.set_cmdline("n", "-5")
    with pytest.raises(ValueError):
        reg.get("n")


def test_unknown_param_raises(reg):
    with pytest.raises(KeyError):
        reg.get("nope")


def test_file_values(reg, tmp_path, monkeypatch):
    conf = tmp_path / "mca.conf"
    conf.write_text("# comment\nfoo = 13\n")
    monkeypatch.setenv("PARSEC_SYSCONF_PARAMS", str(conf))
    reg.reg_int("foo", 1)
    assert reg.get("foo") == 13
    assert reg.source("foo") == "file"


def test_thread_binding_param():
    """bind_threads MCA param (ref: --parsec_bind / bindthread.c)."""
    import os
    import parsec_tpu
    from parsec_tpu.runtime.vpmap import binding_for, bind_current_thread

    parsec_tpu.params.reset()
    assert binding_for(0, 4) is None  # off by default
    allowed = sorted(os.sched_getaffinity(0))
    parsec_tpu.params.set_cmdline("bind_threads", "rr")
    try:
        assert binding_for(0, 4) == allowed[0]
        assert binding_for(1, 4) == allowed[1 % len(allowed)]
        parsec_tpu.params.set_cmdline("bind_threads",
                                      f"{allowed[0]},{allowed[-1]}")
        assert binding_for(0, 2) == allowed[0]
        assert binding_for(1, 2) == allowed[-1]
        # binding the calling thread really takes effect and is undoable
        before = os.sched_getaffinity(0)
        try:
            assert bind_current_thread(allowed[0])
            assert os.sched_getaffinity(0) == {allowed[0]}
        finally:
            os.sched_setaffinity(0, before)
    finally:
        parsec_tpu.params.reset()


def test_workers_bound_when_enabled():
    import parsec_tpu
    import os
    allowed = sorted(os.sched_getaffinity(0))
    if len(allowed) < 2:
        import pytest
        pytest.skip("needs >= 2 allowed cores")
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("bind_threads", "rr")
    try:
        ctx = parsec_tpu.Context(nb_cores=2, enable_tpu=False)
        from parsec_tpu import dtd
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        seen = {}

        def probe(es, task):
            seen[es.th_id] = os.sched_getaffinity(0)

        for _ in range(8):
            tp.insert_task(probe)
        # keep inserting until worker thread 1 has actually run a task
        # (otherwise the assertion would be vacuous)
        for _ in range(40):
            tp.insert_task(probe)
            if 1 in seen:
                break
        tp.wait()
        ctx.fini()
        assert 1 in seen, "worker thread never ran a task"
        assert seen[1] == {allowed[1 % len(allowed)]}
    finally:
        parsec_tpu.params.reset()
