"""XLA device module tests: stage-in/out, coherency across host/device,
async completion, LRU accounting (mirrors reference tests/dsl/dtd CUDA
variants, e.g. dtd_test_task_insert_cuda — run here on the virtual CPU
platform; the same path drives real TPU chips).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu import dtd
from parsec_tpu.dsl.dtd import INOUT, INPUT, VALUE, unpack_args


@pytest.fixture
def jctx():
    c = parsec_tpu.init(nb_cores=2, enable_tpu=True)
    yield c
    c.fini()


def _jax_devices(ctx):
    return [d for d in ctx.devices if d.device_type == "tpu"]


def test_devices_attached(jctx):
    devs = _jax_devices(jctx)
    assert len(devs) >= 1  # conftest forces 8 virtual CPU devices
    assert jctx.devices[0].device_type == "cpu"


def test_tpu_chore_runs_and_writes_back(jctx):
    import jax.numpy as jnp
    tp = dtd.taskpool_new()
    jctx.add_taskpool(tp)
    a = np.arange(16.0, dtype=np.float32).reshape(4, 4)
    tile = tp.tile_of_array(a.copy())

    def body(es, task):  # CPU fallback
        (x,) = unpack_args(task)
        x *= 2.0

    tp.insert_task(body, (tile, INOUT))  # creates the class, runs on CPU
    tp.wait()

    tp2 = dtd.taskpool_new()
    jctx.add_taskpool(tp2)
    tile2 = tp2.tile_of_data(tile.data)

    def body2(es, task):
        (x,) = unpack_args(task)
        x *= 2.0

    tp2.insert_task(body2, (tile2, INOUT))
    tp2.add_chore(body2, "tpu", lambda x: x * 2.0)
    # chore added after the first insert applies to subsequent executions:
    tp2.insert_task(body2, (tile2, INOUT))
    tp2.data_flush(tile2)
    tp2.wait()
    np.testing.assert_allclose(np.asarray(tile.data.get_copy(0).payload),
                               a * 8.0)


def test_device_write_then_host_read_pulls_back(jctx):
    """Coherency: host body after a device body must see the new version."""
    tp = dtd.taskpool_new()
    jctx.add_taskpool(tp)
    tile = tp.tile_of_array(np.ones((8, 8), dtype=np.float32))
    seen = []

    def dev_body(es, task):
        (x,) = unpack_args(task)
        x += 1.0

    tp.insert_task(dev_body, (tile, INOUT))
    tp.add_chore(dev_body, "tpu", lambda x: x + 1.0)

    def host_body(es, task):
        (x,) = unpack_args(task)
        seen.append(np.asarray(x).copy())

    tp.insert_task(dev_body, (tile, INOUT))   # runs on device
    tp.insert_task(host_body, (tile, INPUT))  # must pull newest to host
    tp.wait()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], np.full((8, 8), 3.0))


def test_chain_on_device_stays_on_device(jctx):
    """A chain of device tasks should not bounce through the host."""
    tp = dtd.taskpool_new()
    jctx.add_taskpool(tp)
    tile = tp.tile_of_array(np.zeros((4,), dtype=np.float32))

    def body(es, task):
        (x,) = unpack_args(task)
        x += 1.0

    tp.insert_task(body, (tile, INOUT))
    tp.add_chore(body, "tpu", lambda x: x + 1.0)
    for _ in range(9):
        tp.insert_task(body, (tile, INOUT))
    tp.data_flush(tile)
    tp.wait()
    np.testing.assert_allclose(np.asarray(tile.data.get_copy(0).payload),
                               np.full((4,), 10.0))
    devs = _jax_devices(jctx)
    total_in = sum(d.stats["stage_in_bytes"] for d in devs)
    # first stage-in is 16 bytes; a host bounce per task would be 10x that
    assert total_in <= 16 * len(devs) * 2


def test_load_balancing_spreads_independent_tiles(jctx):
    devs = _jax_devices(jctx)
    if len(devs) < 2:
        pytest.skip("needs multiple XLA devices")
    tp = dtd.taskpool_new()
    jctx.add_taskpool(tp)
    tiles = [tp.tile_of_array(np.zeros((16, 16), dtype=np.float32))
             for _ in range(16)]

    def body(es, task):
        (x,) = unpack_args(task)
        x += 1.0

    tp.insert_task(body, (tiles[0], INOUT))
    tp.add_chore(body, "tpu", lambda x: x + 1.0)
    for t in tiles[1:]:
        tp.insert_task(body, (t, INOUT))
    tp.wait()
    used = sum(1 for d in devs if d.executed_tasks > 0)
    assert used >= 2, f"all tasks landed on one device: {[d.executed_tasks for d in devs]}"
