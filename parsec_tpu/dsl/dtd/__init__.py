"""DTD — Dynamic Task Discovery front end.

Reference behavior: a sequential task-insertion API that discovers the DAG at
runtime from data access modes (IN/OUT/INOUT + AFFINITY/DONT_TRACK), with
per-tile last-user tracking (WAR/WAW chaining, read-after-read fan-out),
sliding-window backpressure (window 8000 / threshold 4000), per-taskpool
registries of task classes and tiles, NEW-tile support, accelerator chores
via ``add_chore``, and explicit data flush back home
(ref: parsec/interfaces/dtd/insert_function.c, insert_function.h:284-425,
overlap_strategies.c:1-356, parsec_dtd_data_flush.c:1-397; call stack
SURVEY.md §3.5).

Public surface mirrors the reference:
``DTDTaskpool.insert_task(fn, args...)``, ``tile_of(collection, key)``,
``tile_new(...)``, ``data_flush/data_flush_all``, ``add_chore``, ``wait``.
"""
from __future__ import annotations

import threading
from enum import IntFlag
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...core.hashtable import HashTable
from ...data.data import (Coherency, Data, DataCopy, FlowAccess,
                          data_new_with_payload)
from ...data.datatype import dtt_of_array
from ...runtime.scheduling import schedule, schedule_keep_best, task_progress
from ...runtime.taskpool import (Chore, Flow, HookReturn, Task, TaskClass,
                                 Taskpool)
from ...runtime.termdet import termdet_new
from ...utils import logging as plog
from ...utils.params import params


class AccessMode(IntFlag):
    """ref: parsec_dtd_op_t / flags in insert_function.h"""
    INPUT = 0x1
    OUTPUT = 0x2
    INOUT = 0x3
    VALUE = 0x10         # pass-by-value scalar argument
    SCRATCH = 0x20       # per-task scratch buffer
    REF = 0x40           # opaque reference, no tracking
    AFFINITY = 0x100     # place the task where this tile lives
    DONT_TRACK = 0x200   # do not build dependencies on this argument


INPUT = AccessMode.INPUT
OUTPUT = AccessMode.OUTPUT
INOUT = AccessMode.INOUT
VALUE = AccessMode.VALUE
SCRATCH = AccessMode.SCRATCH
REF = AccessMode.REF
AFFINITY = AccessMode.AFFINITY
DONT_TRACK = AccessMode.DONT_TRACK


class DTDTile:
    """ref: parsec_dtd_tile_t — tracked unit of data with last-user state."""

    __slots__ = ("key", "rank", "data", "home_collection", "last_writer",
                 "readers", "lock", "flushed")

    def __init__(self, key: Any, data: Data, rank: int = 0,
                 home_collection: Any = None) -> None:
        self.key = key
        self.rank = rank
        self.data = data
        self.home_collection = home_collection
        self.last_writer: Optional["_DTDRecord"] = None
        self.readers: List["_DTDRecord"] = []
        self.lock = threading.Lock()
        self.flushed = False


class _DTDRecord:
    """Per-task DTD bookkeeping: dependency counter + successor list."""

    __slots__ = ("task", "deps_remaining", "successors", "completed", "lock")

    def __init__(self, task: Task) -> None:
        self.task = task
        self.deps_remaining = 1   # +1 insertion guard, dropped when fully parsed
        self.successors: List["_DTDRecord"] = []
        self.completed = False
        self.lock = threading.Lock()

    def add_successor(self, succ: "_DTDRecord") -> bool:
        """Register succ; returns False if we already completed (no dep)."""
        with self.lock:
            if self.completed:
                return False
            self.successors.append(succ)
            return True

    def dep_satisfied(self) -> bool:
        with self.lock:
            self.deps_remaining -= 1
            assert self.deps_remaining >= 0
            return self.deps_remaining == 0


class _Param:
    __slots__ = ("value", "mode", "tile", "flow_index")

    def __init__(self, value: Any, mode: AccessMode, tile: Optional[DTDTile],
                 flow_index: int = -1) -> None:
        self.value = value
        self.mode = mode
        self.tile = tile
        self.flow_index = flow_index


def _dtd_cpu_hook(es, task: Task) -> HookReturn:
    """Run the user body; host copies were resolved by prepare_input."""
    fn = task.task_class.user_body
    rc = fn(es, task)
    return HookReturn.DONE if rc is None else rc


class DTDTaskClass(TaskClass):
    def __init__(self, name: str, tc_id: int, nb_flows: int,
                 body: Callable, flows: List[Flow]) -> None:
        super().__init__(name, tc_id, nb_flows, flows=flows,
                         incarnations=[Chore("cpu", _dtd_cpu_hook)])
        self.user_body = body
        self.prepare_input = _dtd_prepare_input
        self.release_deps = _dtd_release_deps


def _dtd_prepare_input(es, task: Task) -> HookReturn:
    """Resolve data_in copies (ref: data_lookup_of_dtd_task,
    insert_function.c:2014). Accelerator chores stage in themselves; the host
    path must pull the newest version back to the host copy."""
    will_run_on_device = any(
        ch.device_type != "cpu" and (task.chore_mask & (1 << i))
        for i, ch in enumerate(task.task_class.incarnations))
    for flow in task.task_class.flows:
        p: _Param = task.body_args[flow.flow_index]
        if p is None:
            continue
        if p.tile is None:
            continue
        data = p.tile.data
        if will_run_on_device:
            task.data[flow.flow_index].data_in = \
                data.newest_copy() or data.host_copy()
        else:
            task.data[flow.flow_index].data_in = \
                data.sync_to_host(es.context.devices)
        task.data[flow.flow_index].fulfilled = True
    return HookReturn.DONE


def _dtd_release_deps(es, task: Task, action_mask: int) -> List[Task]:
    """ref: dtd_release_dep_fct (insert_function.c:1603) — mark written
    copies, wake satisfied successors."""
    rec: _DTDRecord = task.dtd
    # version bump for host-written flows (device epilog bumps its own)
    if task.selected_device is None or task.selected_device.device_type == "cpu":
        for flow in task.task_class.flows:
            p: _Param = task.body_args[flow.flow_index]
            if p is not None and p.tile is not None and \
                    (task.access_of(flow) & FlowAccess.WRITE):
                p.tile.data.version_bump(0)
    ready: List[Task] = []
    with rec.lock:
        rec.completed = True
        succs, rec.successors = rec.successors, []
    for s in succs:
        if s.dep_satisfied():
            ready.append(s.task)
    tp: DTDTaskpool = task.taskpool
    tp._on_task_done()
    return ready


class DTDTaskpool(Taskpool):
    """ref: parsec_dtd_taskpool_new (insert_function.c)"""

    MAX_TASK_CLASSES = 25  # ref: insert_function_internal.h:30

    def __init__(self, name: str = "dtd") -> None:
        super().__init__(name=name)
        self.window_size = params.get("dtd_window_size")
        self.threshold_size = params.get("dtd_threshold_size")
        self._task_classes: Dict[Any, DTDTaskClass] = {}
        self._tiles = HashTable()
        self._outstanding = 0
        self._out_lock = threading.Lock()
        self._inserted = 0
        # keep-alive action until wait() (so an empty pool doesn't terminate)
        self.tdm = termdet_new(params.get("termdet") if params.get("termdet") != "fourcounter" else "local", self)
        self.tdm.taskpool_addto_runtime_actions(1)
        self._alive = True

    # ------------------------------------------------------------------ #
    # tiles                                                              #
    # ------------------------------------------------------------------ #
    def tile_of(self, collection, key: Any) -> DTDTile:
        """ref: parsec_dtd_tile_of (insert_function.h:219) — one DTDTile per
        (collection, key), memoized."""
        tkey = (id(collection), key)

        def factory() -> DTDTile:
            data = collection.data_of_key(key)
            rank = collection.rank_of_key(key)
            return DTDTile(key, data, rank=rank, home_collection=collection)
        tile, _ = self._tiles.find_or_insert(tkey, factory)
        return tile

    def tile_of_data(self, data: Data) -> DTDTile:
        tkey = ("data", data.key)

        def factory() -> DTDTile:
            return DTDTile(data.key, data, rank=0)
        tile, _ = self._tiles.find_or_insert(tkey, factory)
        return tile

    def tile_of_array(self, arr: Any, key: Any = None) -> DTDTile:
        """Wrap a host array as a tracked tile."""
        data = data_new_with_payload(arr, device_id=0, key=key)
        return self.tile_of_data(data)

    def tile_new(self, shape: Tuple[int, ...], dtype=np.float32,
                 key: Any = None) -> DTDTile:
        """ref: NEW-tile support (dtd_test_new_tile) — runtime-allocated."""
        return self.tile_of_array(np.zeros(shape, dtype=dtype), key=key)

    # ------------------------------------------------------------------ #
    # task classes + chores                                              #
    # ------------------------------------------------------------------ #
    def _task_class_of(self, body: Callable, nb_flows: int,
                       name: Optional[str]) -> DTDTaskClass:
        key = body
        tc = self._task_classes.get(key)
        if tc is None:
            assert len(self._task_classes) < self.MAX_TASK_CLASSES, \
                "too many DTD task classes (ref limit 25)"
            flows = [Flow(f"flow{i}", FlowAccess.NONE, i) for i in range(nb_flows)]
            tc = DTDTaskClass(name or getattr(body, "__name__", "dtd_task"),
                              len(self._task_classes), nb_flows, body, flows)
            self._task_classes[key] = tc
            self.task_classes.append(tc)
        assert tc.nb_flows == nb_flows, \
            f"task class {tc.name} re-inserted with different flow count"
        return tc

    def add_chore(self, body: Callable, device_type: str, fn: Any) -> None:
        """ref: parsec_dtd_task_class_add_chore (insert_function.c:2432).
        ``fn`` for device_type "tpu" is a jax callable taking one argument
        per inserted parameter in insertion order — device arrays for tiles,
        raw Python values for VALUE params (same order as unpack_args); it
        returns arrays for the written flows, in order."""
        tc = self._task_classes.get(body)
        assert tc is not None, "add_chore before first insert_task of this body"

        def wrapped(task: Task, arrays: List[Any]) -> Any:
            args = [arrays[p.flow_index] if p.tile is not None else p.value
                    for p in task.user
                    if p.tile is not None or (p.mode & VALUE)]
            return fn(*args)

        from ...devices.tpu import tpu_chore_hook
        tc.incarnations.append(Chore(device_type, tpu_chore_hook(), dyld_fn=wrapped))

    # ------------------------------------------------------------------ #
    # insertion                                                          #
    # ------------------------------------------------------------------ #
    def insert_task(self, body: Callable, *args, name: Optional[str] = None,
                    priority: int = 0) -> Task:
        """ref: parsec_dtd_insert_task (insert_function.h:284, impl :3506).

        ``args`` are (value, VALUE) / (tile, INPUT|INOUT|OUTPUT [|AFFINITY...])
        pairs, or bare Python values (implicitly VALUE).
        """
        assert self._alive, "insert_task after wait()"
        self._backpressure()
        # parse the vararg list (ref: __parsec_dtd_taskpool_create_task :3219)
        parsed: List[_Param] = []
        flow_count = 0
        for a in args:
            if isinstance(a, tuple) and len(a) == 2 and isinstance(a[1], AccessMode):
                val, mode = a
            else:
                val, mode = a, AccessMode.VALUE
            if mode & (VALUE | REF | SCRATCH) or (mode & DONT_TRACK):
                parsed.append(_Param(val, mode, None))
                continue
            assert isinstance(val, DTDTile), \
                f"tracked argument must be a DTDTile, got {type(val)}"
            p = _Param(val, mode, val, flow_index=flow_count)
            flow_count += 1
            parsed.append(p)

        tc = self._task_class_of(body, flow_count, name)
        task = Task(self, tc, locals_=(self._inserted,), priority=priority)
        self._inserted += 1
        rec = _DTDRecord(task)
        task.dtd = rec
        # per-INSTANCE access modes (the same body may be inserted with
        # different modes; the shared class Flow objects stay untouched)
        tracked = [p for p in parsed if p.tile is not None]
        task.body_args = tracked
        task.user = parsed
        task.flow_access = [FlowAccess(int(p.mode) & 0x3) for p in tracked]
        self.add_tasks(1)
        with self._out_lock:
            self._outstanding += 1

        # dependency discovery from tile last-user state
        # (ref: overlap_strategies.c WAR/fan-out resolution)
        def _chain_after(pred: "_DTDRecord") -> None:
            # take the dep BEFORE publishing rec to the predecessor: if the
            # increment came after add_successor, a concurrently-completing
            # predecessor could consume the insertion guard and schedule a
            # half-built task (then the guard drop would schedule it twice)
            with rec.lock:
                rec.deps_remaining += 1
            if not pred.add_successor(rec):
                rec.dep_satisfied()  # already completed; cannot hit zero here

        for p in tracked:
            tile = p.tile
            acc = int(p.mode) & 0x3
            with tile.lock:
                if acc == int(AccessMode.INPUT):
                    lw = tile.last_writer
                    if lw is not None and lw is not rec:
                        _chain_after(lw)
                    # prune completed readers so read-mostly tiles don't
                    # retain every historical reader record
                    tile.readers = [r for r in tile.readers if not r.completed]
                    tile.readers.append(rec)
                else:  # OUTPUT or INOUT: chain after writer and all readers
                    preds = []
                    if tile.last_writer is not None and tile.last_writer is not rec:
                        preds.append(tile.last_writer)
                    preds.extend(r for r in tile.readers if r is not rec)
                    for pr in preds:
                        _chain_after(pr)
                    tile.last_writer = rec
                    tile.readers = []

        # affinity placement hint
        for p in tracked:
            if p.mode & AFFINITY:
                task.taskpool_affinity_rank = p.tile.rank
                break

        # drop the insertion guard; schedule if ready
        if rec.dep_satisfied():
            self._schedule_new(task)
        return task

    def _schedule_new(self, task: Task) -> None:
        ctx = self.context
        assert ctx is not None, "insert_task before context.add_taskpool"
        es = ctx.execution_streams[0]
        schedule(es, [task])

    def _on_task_done(self) -> None:
        with self._out_lock:
            self._outstanding -= 1

    def _backpressure(self) -> None:
        """ref: parsec_dtd_block_if_threshold_reached (insert_function.c:3215)
        — over the window, the inserting thread helps execute."""
        if self._outstanding <= self.window_size:
            return
        ctx = self.context
        es = ctx.execution_streams[0]
        while self._outstanding > self.threshold_size:
            task = es.next_task
            es.next_task = None
            if task is None:
                task = ctx.scheduler.select(es)
            if task is not None:
                task_progress(es, task)
            elif ctx.progress_engines(es) == 0:
                break  # nothing runnable; don't deadlock the inserter

    # ------------------------------------------------------------------ #
    # flush + wait                                                       #
    # ------------------------------------------------------------------ #
    def data_flush(self, tile: DTDTile) -> None:
        """ref: parsec_dtd_data_flush — order a writeback of the tile to its
        home (host copy / collection storage) after its last user. One shared
        task class serves every flush (a per-call closure would exhaust the
        25-class limit)."""
        self.insert_task(_dtd_flush_body, (tile, INOUT), (tile, VALUE | REF),
                         name="dtd_flush")

    def data_flush_all(self) -> None:
        for _, tile in self._tiles.items():
            if not tile.flushed:
                self.data_flush(tile)

    def wait(self) -> None:
        """ref: parsec_dtd_taskpool_wait — drop the keep-alive and help
        execute until this taskpool terminates."""
        assert self.context is not None
        if self._alive:
            self._alive = False
            self.tdm.taskpool_addto_runtime_actions(-1)
        ctx = self.context
        ctx.start()
        es = ctx.execution_streams[0]
        from ...runtime.scheduling import _Backoff
        backoff = _Backoff()
        while not self.completed and not ctx._task_errors:
            task = es.next_task
            es.next_task = None
            if task is None:
                task = ctx.scheduler.select(es)
            try:
                if task is not None:
                    task_progress(es, task)
                    backoff.hit()
                elif ctx.progress_engines(es):
                    backoff.hit()
                else:
                    backoff.miss(ctx)
            except BaseException as exc:
                ctx.record_task_error(exc, task)
        ctx.raise_pending_error()


def _dtd_flush_body(es, task: Task) -> None:
    """Shared flush task body: pull the newest copy back to the host."""
    tile: DTDTile = next(p.value for p in task.user if p.tile is None)
    tile.data.sync_to_host(es.context.devices)
    tile.flushed = True


def taskpool_new(name: str = "dtd") -> DTDTaskpool:
    return DTDTaskpool(name=name)


def unpack_args(task: Task) -> List[Any]:
    """ref: parsec_dtd_unpack_args — values for VALUE params, host ndarrays
    for tracked tiles (in the original insertion order)."""
    out: List[Any] = []
    for p in task.user:
        if p.tile is not None:
            host = p.tile.data.get_copy(0)
            out.append(host.payload if host is not None else None)
        else:
            out.append(p.value)
    return out
