"""tune — closed-loop self-tuning (ISSUE 17).

The live health monitor (obs/live.py, ISSUE 16) *watches* the knobs
this package *turns*: a per-rank :class:`Controller` subscribes to the
monitor's window ticks and adapts three knob families at runtime —

- per-link quantized wire codec (lossless -> qbf16 -> qint8) within the
  ``tune_residual_budget``, escalating on bandwidth-bound links and
  de-escalating when compression shows no win, renegotiated live over
  the K_TUNE control frame toward "tn"-capable peers;
- device pipeline shape (``batch_max`` / ``prefetch_depth`` /
  ``flush_segments``), hill-climbed per device from batch occupancy,
  prefetch hit rate and the overlap fraction, with hysteresis and
  revert-on-regress against a us/task dispatch objective;
- stage-compile exclusion: a class whose compiled stage keeps firing
  the straggler detector is fed to ``stage_compile_exclude`` so the
  next taskpool over the same spec replans around it.

Everything lives behind the ``tune_auto`` MCA param: unset constructs
no controller, starts no subscription, and is bit-for-bit inert on the
wire (proven by the frame-capture identity differential in bench.py).
Every adaptation emits a ``tune:*`` instant annotation on the health
trace stream plus the ``PARSEC::TUNE::*`` gauges.
"""
from .controller import (CODEC_COST, CODEC_LADDER, Controller,
                         register_tune_gauges)

__all__ = ["Controller", "CODEC_LADDER", "CODEC_COST",
           "register_tune_gauges"]
