"""PTG runtime: JDF AST → executable task classes ("the generated code").

Reference behavior reproduced from the jdf2c code generator
(ref: parsec/interfaces/ptg/ptg-compiler/jdf2c.c): the taskpool constructor
``parsec_<name>_new(globals...)`` (jdf2c.c:4576), the startup-task enumerator
walking the iteration space for tasks with no task-sourced inputs
(jdf2c.c:2975-3385), ``iterate_successors`` evaluating guards/ranges per out
dep (jdf2c.c:44), ``release_deps`` updating the dynamic dependency hash
table and building the ready ring (jdf2c.c:7161; dynamic dep management is
the default, ptg-compiler/main.c:37), per-device BODY hooks incl. the
accelerator chore (jdf2c.c:6557), and inline expressions (jdf2c.c:8038).

TPU-native notes: BODY code is Python; ``BODY [type=tpu]`` code runs under
the XLA device module — flow names are bound to device arrays and the code's
final assignments to written flow names become the staged-out results.
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ...core.hashtable import HashTable
from ...profiling.grapher import grapher
from ...data.data import Coherency, Data, DataCopy, FlowAccess
from ...data.datatype import Datatype, dtt_of_array
from ...data.data import is_device_array as _is_dev_arr
from ...data.reshape import ReshapeRepo, reshape_array as reshape_to
from ...runtime.scheduling import schedule_keep_best
from ...runtime.taskpool import (Chore, Flow, HookReturn, Task, TaskClass,
                                 Taskpool)
from ...utils import logging as plog
from ...utils.params import params
from .ast import (BodyAST, DepAST, DepTarget, Expr, FlowAST, JDFFile,
                  LocalDef, RangeExpr, TaskClassAST)

_ACCESS_MAP = {"RW": FlowAccess.RW, "READ": FlowAccess.READ,
               "WRITE": FlowAccess.WRITE, "CTL": FlowAccess.NONE}


class _DepEntry:
    """Dynamic dependency-tracking entry (ref: parsec_hashable_dependency_t,
    parsec/parsec_internal.h:229)."""

    __slots__ = ("remaining", "bindings", "spawned")

    def __init__(self, goal: int) -> None:
        self.remaining = goal
        self.bindings: Dict[str, Any] = {}   # flow name -> DataCopy
        self.spawned = False


class PTGTaskClass(TaskClass):
    """One generated task class bound to a PTGTaskpool instance."""

    def __init__(self, tp: "PTGTaskpool", ast: TaskClassAST, tc_id: int) -> None:
        flows = [Flow(f.name, _ACCESS_MAP[f.access], i, ctl=f.is_ctl)
                 for i, f in enumerate(ast.flows)]
        super().__init__(ast.name, tc_id, len(flows), flows=flows)
        self.tp = tp
        self.ast = ast
        self.dep_table = HashTable()
        # generated specializations (the jdf2c analog, codegen.py);
        # interpreted AST walk below remains the fallback
        self._gen_goal = self._gen_succ = None
        if params.get("ptg_codegen"):
            try:
                from .codegen import build_fns
                self._gen_goal, self._gen_succ = build_fns(ast, tp.global_env)
            except Exception as exc:  # pragma: no cover - defensive
                plog.debug.verbose(
                    1, "ptg codegen failed for %s (%s); interpreting",
                    ast.name, exc)
        self.prepare_input = self._prepare_input
        self.release_deps = self._release_deps
        self.iterate_successors = self._iterate_successors
        self.key_fn = lambda locals_: (tc_id, locals_)
        self.prepare_output = lambda es, task: tp.writeback_outputs(es, task)
        self.incarnations = self._build_chores(ast.bodies)

    # ------------------------------------------------------------------ #
    # iteration space                                                    #
    # ------------------------------------------------------------------ #
    def env_of(self, locals_: Tuple) -> Dict[str, Any]:
        """globals + named locals (incl. derived) for an instance."""
        env = dict(self.tp.global_env)
        it = iter(locals_)
        for ld in self.ast.locals:
            if ld.range is not None:
                env[ld.name] = next(it)
            else:
                env[ld.name] = ld.expr(env)
        return env

    def iter_space(self) -> Iterator[Tuple]:
        """Walk the (range) locals' iteration space in definition order;
        later ranges/derived locals may depend on earlier ones
        (ref: jdf2c startup loops)."""
        locals_ = self.ast.locals

        def rec(li: int, env: Dict[str, Any], acc: List[int]):
            if li == len(locals_):
                yield tuple(acc)
                return
            ld = locals_[li]
            if ld.range is None:
                env[ld.name] = ld.expr(env)
                yield from rec(li + 1, env, acc)
                return
            for v in ld.range.values(env):
                env2 = dict(env)
                env2[ld.name] = v
                acc.append(v)
                yield from rec(li + 1, env2, acc)
                acc.pop()

        yield from rec(0, dict(self.tp.global_env), [])

    def rank_of_instance(self, env: Dict[str, Any]) -> int:
        if self.ast.affinity_collection is None:
            return self.tp.rank
        coll = self.tp.global_env[self.ast.affinity_collection]
        args = [a(env) for a in self.ast.affinity_args]
        return coll.rank_of(*args)

    # ------------------------------------------------------------------ #
    # dependency analysis per instance                                   #
    # ------------------------------------------------------------------ #
    def input_goal(self, env: Dict[str, Any]) -> int:
        """#input deps that resolve to task sources (activation count).

        A ranged input target (CTL gather, ``ctl <- ctl R( 0 .. N )``)
        produces one activation per expanded predecessor instance, so the
        goal must count the expansion, not the dep line (ref: generated
        dependency counters cover each control-gather edge, jdf2c.c)."""
        goal = 0
        for f in self.ast.flows:
            for d in f.deps_in():
                t = d.resolve(env)
                if t is not None and t.kind == "task":
                    goal += sum(1 for _ in _expand_args(t.args, env))
        return goal

    def goal_of(self, locals_: Tuple, env: Optional[Dict[str, Any]] = None) -> int:
        """input_goal via the generated counter when available."""
        if self._gen_goal is not None:
            return self._gen_goal(locals_)
        return self.input_goal(env if env is not None else self.env_of(locals_))

    def is_startup(self, locals_: Tuple,
                   env: Optional[Dict[str, Any]] = None) -> bool:
        return self.goal_of(locals_, env) == 0

    # ------------------------------------------------------------------ #
    # task lifecycle                                                     #
    # ------------------------------------------------------------------ #
    def make_task(self, locals_: Tuple, entry: Optional[_DepEntry]) -> Task:
        env = self.env_of(locals_)
        prio = int(self.ast.priority(env)) if self.ast.priority is not None else 0
        task = Task(self.tp, self, locals_, priority=prio)
        if entry is not None:
            for fname, copy in entry.bindings.items():
                fl = self.ast.flow_by_name(fname)
                idx = self.ast.flows.index(fl)
                task.data[idx].data_in = copy
                task.data[idx].fulfilled = True
        return task

    def _prepare_input(self, es, task: Task) -> HookReturn:
        """Bind memory-sourced inputs; task-sourced ones arrived with the
        activation (ref: generated data_lookup, jdf2c.c:42)."""
        env = self.env_of(task.locals)
        for i, f in enumerate(self.ast.flows):
            ref = task.data[i]
            if ref.fulfilled or f.is_ctl:
                continue
            deps_in = f.deps_in()
            if not deps_in:
                # pure-output flow: write-into-memory target or NEW scratch
                ref.data_in = self._output_binding(f, env, es)
                ref.fulfilled = True
                continue
            bound = False
            for d in deps_in:
                t = d.resolve(env)
                if t is None:
                    continue
                if t.kind == "memory":
                    coll = self.tp.global_env[t.collection]
                    args = [a(env) for a in t.args]
                    data = coll.data_of(*args)
                    hc = self.tp.host_copy_of(es, data)
                    if self._flow_masked_writeback(f, env):
                        # a region-masked [type_data] writeback must see
                        # the destination's OLD out-of-region values —
                        # the body may not mutate the home buffer. The
                        # clone detaches from the Data, so the newest
                        # version must land on host FIRST (the lazy
                        # already-home path may have left it on a device;
                        # a stale snapshot here is silent wrong results)
                        hc = _detached_clone(
                            self.tp.pull_newest_to_host(es, data))
                    ref.data_in = hc
                    ref.fulfilled = True
                elif t.kind == "new":
                    ref.data_in = self.tp.new_scratch_copy(f, env)
                    ref.fulfilled = True
                elif t.kind == "null":
                    ref.data_in = None
                    ref.fulfilled = True
                bound = True
                break
            if not bound and not ref.fulfilled:
                # every input dep's guard evaluated false with no
                # alternative: a NULL input (reference: a guarded dep with
                # no ':' alternative yields NULL in that instance;
                # DepAST.resolve returns None, parser.py `cond ? a` form)
                ref.data_in = None
                ref.fulfilled = True
        # reshape pass: a consumer-declared [type=...] differing from the
        # producer's datatype converts through a shared reshape promise —
        # activation-sourced (remote) and memory/task-sourced (local) flows
        # alike (ref: parsec_reshape.c; receiver-side datatype lookup,
        # remote_dep_mpi.c:766)
        for i, f in enumerate(self.ast.flows):
            ref = task.data[i]
            if f.is_ctl or ref.data_in is None:
                continue
            dtt = self._input_dtt(f, env, ref.data_in)
            if dtt is not None:
                ref.data_in = self.tp.reshape_repo.reshaped_copy(
                    ref.data_in, dtt, es)
        return HookReturn.DONE

    def _input_dtt(self, f: FlowAST, env: Dict[str, Any], copy):
        """The datatype this instance's input edge declares, or None.

        The first in-dep applicable under ``env`` is the edge that bound
        the input (same rule as the binding loop — SPMD-consistent on
        both ends of a remote edge). Property semantics mirror the
        reference (parsec_reshape.c; tests/collections/reshape/):
        - ``type``        — LOCAL reshape: consumers get a converted copy
                            regardless of where the data came from;
        - ``type_remote`` — wire datatype only: applied when the
                            producer lives on ANOTHER rank, ignored for
                            local edges (local_no_reshape /
                            avoidable_reshape semantics);
        - ``type_data``   — datatype when reading from the matrix
                            (memory-sourced edges)."""
        for d in f.deps_in():
            t = d.resolve(env)
            if t is None:
                continue
            props = d.properties
            if t.kind == "memory":
                tname = props.get("type_data") or props.get("type")
            elif t.kind == "task":
                tname = props.get("type")
                if tname is None:
                    rname = props.get("type_remote")
                    if rname is not None and self._edge_is_remote(t, env):
                        tname = rname
            else:
                tname = props.get("type")
            if tname is None:
                return None
            return self.resolve_dtt_name(tname, copy, f.name)
        return None

    def producer_rank_of(self, t, env: Dict[str, Any]) -> Optional[int]:
        """Rank of a task-sourced dep target's FIRST expanded producer
        instance; None when unresolvable. Shared by _edge_is_remote and
        the distributed wave's wire-type decision — both ends of an
        edge must resolve identically (the reference's both-ends
        remote_dep_mpi_retrieve_datatype lookup)."""
        try:
            ptc = self.tp.class_by_name(t.task_class)
            args = next(iter(_expand_args(t.args, env)))
            penv = ptc.env_of(ptc.ast.locals_from_param_args(args))
            return ptc.rank_of_instance(penv)
        except (KeyError, StopIteration):
            return None

    def _edge_is_remote(self, t, env: Dict[str, Any]) -> bool:
        """Does this task-sourced in-dep cross ranks?"""
        if self.tp.nb_ranks == 1:
            return False
        pr = self.producer_rank_of(t, env)
        return pr is not None and pr != self.tp.rank

    def resolve_dtt_name(self, tname: str, copy, flow_name: str) -> Datatype:
        """A [type*=NAME] property: a Datatype global, or one of the
        region shorthands applied to the copy's base type."""
        val = self.tp.global_env.get(tname)
        if isinstance(val, Datatype):
            return val
        if tname in ("lower", "upper", "full"):
            base = (copy.dtt if copy is not None and copy.dtt is not None
                    else dtt_of_array(copy.payload))
            return dataclasses.replace(base, region=tname)
        raise TypeError(
            f"{self.name}.{flow_name}: [type={tname}] is neither a "
            f"Datatype global nor a region shorthand")

    def _flow_masked_writeback(self, f: FlowAST, env: Dict[str, Any]) -> bool:
        """Does any memory out-dep of this flow declare a (possibly
        region-masked) writeback type? Those flows bind detached clones
        so the body cannot clobber the destination's out-of-region
        values before the masked writeback runs."""
        for d in f.deps_out():
            t = d.resolve(env)
            if t is None or t.kind != "memory":
                continue
            nm = d.properties.get("type_data") or d.properties.get("type")
            if nm is not None and nm != "full":
                return True
        return False

    def _output_binding(self, f: FlowAST, env: Dict[str, Any], es=None):
        """WRITE-only flow: bind to its memory out-target or a NEW buffer."""
        for d in f.deps_out():
            t = d.resolve(env)
            if t is not None and t.kind == "memory":
                coll = self.tp.global_env[t.collection]
                args = [a(env) for a in t.args]
                data = coll.data_of(*args)
                hc = self.tp.host_copy_of(None, data)
                if self._flow_masked_writeback(f, env):
                    # detached snapshot: sync the newest version home
                    # first (see _prepare_input's masked-writeback note)
                    hc = _detached_clone(
                        self.tp.pull_newest_to_host(es, data))
                return hc
        return self.tp.new_scratch_copy(f, env)

    def _iterate_successors(self, es, task: Task, cb: Callable) -> None:
        """cb(succ_tc, succ_locals, succ_flow_name, copy, out_flow_idx) per
        satisfied output edge (ref: generated iterate_successors)."""
        if self._gen_succ is not None:
            copies = [None if f.is_ctl
                      else (task.data[i].data_out or task.data[i].data_in)
                      for i, f in enumerate(self.ast.flows)]
            resolve = self.tp.class_by_name
            self._gen_succ(
                task.locals, copies,
                lambda name, loc, fl, cp, idx, tys=None: cb(
                    resolve(name), loc, fl, cp, idx, tys))
            return
        env = self.env_of(task.locals)
        for i, f in enumerate(self.ast.flows):
            copy = None if f.is_ctl else (task.data[i].data_out or task.data[i].data_in)
            for d in f.deps_out():
                t = d.resolve(env)
                if t is None or t.kind in ("null", "new"):
                    continue
                if t.kind == "memory":
                    continue  # handled in prepare_output (writeback)
                lt = d.properties.get("type")
                succ_tc = self.tp.class_by_name(t.task_class)
                for succ_locals in _expand_args(t.args, env):
                    cb(succ_tc, succ_locals, t.flow, copy, i, lt)

    def _release_deps(self, es, task: Task, action_mask: int) -> List[Task]:
        """Local successors activate in place; remote ones accumulate into a
        per-rank batch handed to the comm engine as one activation per output
        flow (ref: parsec_remote_deps_t accumulation, remote_dep.h:143-160).

        With static dep management active the whole walk is ONE native
        call: the lowered CSR edges route copies and decrement dense
        counters in C (ref: --dep-management=index-array)."""
        if self.tp._engine is not None:
            copies = tuple(
                None if f.is_ctl
                else (task.data[i].data_out or task.data[i].data_in)
                for i, f in enumerate(self.ast.flows))
            tid = self.tp._dag.id_of[(self.ast.name, task.locals)]
            return [self.tp._make_task_static(r)
                    for r in self.tp._engine.complete(tid, copies)]
        ready: List[Task] = []
        remote_edges: Dict[int, List[Tuple]] = {}
        flow_payloads: Dict[int, Any] = {}
        flow_dtts: Dict[int, Any] = {}

        def activate(succ_tc: "PTGTaskClass", succ_locals: Tuple,
                     flow_name: str, copy, out_idx: int,
                     edge_type=None) -> None:
            if grapher.enabled:
                # must match Task.snprintf() so DOT edges hit real nodes
                grapher.dep(task, f"{succ_tc.name}"
                            f"({', '.join(map(str, succ_locals))})", flow_name)
            env = succ_tc.env_of(succ_locals)
            dst = succ_tc.rank_of_instance(env)
            if dst == self.tp.rank:
                if edge_type is not None and copy is not None:
                    # [type=...] on the OUT dep: producer-side local
                    # reshape — successors receive the converted copy
                    # (local_output_reshape semantics)
                    dtt = self.resolve_dtt_name(edge_type, copy, flow_name)
                    copy = self.tp.reshape_repo.reshaped_copy(copy, dtt, es)
                t = succ_tc.activate(succ_locals, flow_name, copy)
                if t is not None:
                    ready.append(t)
                return
            if self.tp.comm is None:
                raise RuntimeError(
                    f"{self.tp.name}: task {task.snprintf()} has a remote "
                    f"successor {succ_tc.name}{succ_locals} but no comm "
                    f"engine is attached (nb_ranks={self.tp.nb_ranks})")
            remote_edges.setdefault(dst, []).append(
                (succ_tc.task_class_id, succ_locals, flow_name, out_idx))
            if out_idx not in flow_payloads and copy is not None:
                ce = getattr(self.tp.comm, "ce", None)
                plane = getattr(ce, "device_plane", None)
                # mesh-local peers (one XLA client) take device buffers
                # by reference — offering the device copy here is what
                # lets remote_dep's fast path skip the D2H sync below
                mesh_local = (getattr(self.tp.comm, "_mesh_local", False)
                              and ce is not None
                              and ce.mesh_local_with(dst))
                newest = (copy.data.newest_copy()
                          if copy.data is not None else copy)
                if (plane is not None or mesh_local) \
                        and newest is not None \
                        and newest.payload is not None \
                        and _is_dev_arr(newest.payload):
                    # device data plane attached and the newest version
                    # lives on device: ship the device buffer itself —
                    # the consumer pulls it device-to-device, no D2H
                    flow_payloads[out_idx] = newest.payload
                    flow_dtts[out_idx] = newest.dtt
                elif copy.data is not None:
                    host = copy.data.sync_to_host(es.context.devices)
                    flow_payloads[out_idx] = np.asarray(host.payload)
                    flow_dtts[out_idx] = host.dtt
                else:
                    flow_payloads[out_idx] = np.asarray(copy.payload)
                    flow_dtts[out_idx] = copy.dtt  # rides the wire: a
                    # matching consumer type must not reconvert

        self._iterate_successors(es, task, activate)
        if remote_edges:
            self.tp.comm.activate_batch(self.tp, task, flow_payloads,
                                        remote_edges, flow_dtts)
        return ready

    def activate(self, locals_: Tuple, flow_name: str, copy) -> Optional[Task]:
        """One input of instance ``locals_`` became available; spawn the task
        when the dynamic dep counter reaches its goal."""
        sc = self.tp._stagec
        if sc is not None:
            # stage-compile seam (stagec/, ISSUE 12): activations for
            # instances fused into a compiled stage count toward the
            # STAGE's external goal instead; local residue, other
            # stages, and remote ranks all arrive through this one
            # funnel, so no wire/protocol change is needed.  Downgraded
            # stages pass through to the dynamic table below.
            handled, task = sc.on_activate(self, locals_, flow_name, copy)
            if handled:
                return task
        key = locals_
        task = None
        self.dep_table.lock_bucket(key)
        try:
            entry = self.dep_table.nolock_find(key)
            if entry is None:
                entry = _DepEntry(self.goal_of(locals_))
                self.dep_table.nolock_insert(key, entry)
            if copy is not None:
                entry.bindings[flow_name] = copy
            entry.remaining -= 1
            assert entry.remaining >= 0, \
                f"{self.name}{locals_}: more activations than inputs"
            if entry.remaining == 0 and not entry.spawned:
                entry.spawned = True
                self.dep_table.nolock_remove(key)
                task = self.make_task(locals_, entry)
        finally:
            self.dep_table.unlock_bucket(key)
        if task is not None and sc is not None:
            # compiled residue schedule (stagec/, ISSUE 13): a ready
            # task of a pre-planned residue group buffers with the
            # compiler and dispatches with its whole group as one
            # device burst — returns None here (routed, not lost)
            task = sc.on_residue_ready(task)
        return task

    # ------------------------------------------------------------------ #
    # bodies → chores                                                    #
    # ------------------------------------------------------------------ #
    def _build_chores(self, bodies: List[BodyAST]) -> List[Chore]:
        chores: List[Chore] = []
        for b in bodies:
            if b.device_type in ("cpu", "recursive"):
                code = compile(b.code, f"<jdf:{self.name}:BODY>", "exec")
                chores.append(Chore("cpu", self._cpu_hook_factory(code)))
            elif b.device_type == "tpu":
                from ...devices.tpu import tpu_chore_hook
                fn, spec = self._device_fn_factory(b)
                chores.append(Chore(b.device_type, tpu_chore_hook(),
                                    dyld_fn=fn, batch_spec=spec))
            else:
                # any other accelerator type routes to its attached
                # device module (ref: per-device-type chore lists,
                # parsec_internal.h:380-437; see devices/template.py)
                from ...devices.template import template_chore_hook
                fn, spec = self._device_fn_factory(b)
                chores.append(Chore(b.device_type,
                                    template_chore_hook(b.device_type),
                                    dyld_fn=fn, batch_spec=spec))
        if not any(c.device_type == "cpu" for c in chores):
            # always provide a host fallback interpreting the first body
            b = bodies[0]
            code = compile(b.code, f"<jdf:{self.name}:BODY>", "exec")
            chores.append(Chore("cpu", self._cpu_hook_factory(code)))
        return chores

    def _body_env(self, task: Task, payloads: Dict[str, Any]) -> Dict[str, Any]:
        env = self.env_of(task.locals)
        env.update(payloads)
        env["es_rank"] = self.tp.rank
        env["this_task"] = task
        try:
            import jax.numpy as jnp
            env["jnp"] = jnp
        except Exception:
            pass
        env["np"] = np
        return env

    def _cpu_hook_factory(self, code):
        def hook(es, task: Task) -> HookReturn:
            payloads = {}
            for i, f in enumerate(self.ast.flows):
                if f.is_ctl:
                    continue
                copy = task.data[i].data_in
                if copy is None:
                    payloads[f.name] = None
                    continue
                if copy.data is not None:
                    # host execution needs the newest version on device 0
                    host = self.tp.pull_newest_to_host(es, copy.data)
                    payloads[f.name] = Data.materialize_host(host)
                    task.data[i].data_in = host
                else:
                    payloads[f.name] = Data.materialize_host(copy)
            env = self._body_env(task, payloads)
            exec(code, env)
            for i, f in enumerate(self.ast.flows):
                if f.is_ctl or not (self.flows[i].access & FlowAccess.WRITE):
                    continue
                copy = task.data[i].data_in
                if copy is None:
                    continue
                # functional-style bodies (device BODY run as host fallback)
                # rebind the flow name instead of mutating in place: write
                # the rebound value back into the host payload
                new_val = env.get(f.name)
                if new_val is not None and new_val is not copy.payload:
                    arr = np.asarray(new_val)
                    if copy.payload is None:
                        copy.payload = arr
                    else:
                        np.copyto(copy.payload, arr)
                if copy.data is not None:
                    copy.data.version_bump(copy.device_id)
            return HookReturn.DONE
        return hook

    def _device_fn_factory(self, body: BodyAST):
        """Build the accelerator executable: flow names are device arrays;
        assignments to written flow names are returned (in flow order).
        Returns ``(fn, batch_spec)`` — the per-task wrapper plus the
        batched-dispatch recipe (devices/batching.py), or spec=None when
        the body reads per-task runtime state (``this_task``)."""
        code = compile(body.code, f"<jdf:{self.name}:BODY[tpu]>", "exec")
        written = [(i, f.name) for i, f in enumerate(self.ast.flows)
                   if not f.is_ctl and (self.flows[i].access & FlowAccess.WRITE)]

        def fn(task: Task, arrays: List[Any]):
            payloads = {}
            for i, f in enumerate(self.ast.flows):
                if not f.is_ctl:
                    payloads[f.name] = arrays[i]
            env = self._body_env(task, payloads)
            exec(code, env)
            return tuple(env[name] for i, name in written
                         if task.data[i].data_in is not None)
        return fn, self._device_batch_spec(body, code, written)

    def _device_batch_spec(self, body: BodyAST, code, written):
        """Batching recipe for a JDF device body: present flow arrays
        form the batch axis; the locals the body actually READS
        (co_names ∩ declared locals) go into the static group key, so
        e.g. every GEMM(k, m, n) of a wave stacks into one dispatch
        (the body references no locals) while a body indexing on ``k``
        still batches within equal ``k``."""
        from ...devices.batching import DeviceBatchSpec
        names = set(code.co_names)
        if "this_task" in names:
            return None   # reads per-task runtime state: never batchable
        nonctl = [(i, f.name) for i, f in enumerate(self.ast.flows)
                  if not f.is_ctl]
        flow_name = dict(nonctl)
        refd = [ld.name for ld in self.ast.locals if ld.name in names]

        def extract(task: Task, arrays: List[Any]):
            bargs: List[Any] = []
            fidx: List[int] = []
            absent: List[str] = []
            for i, nm in nonctl:
                a = arrays[i]
                if a is None:
                    absent.append(nm)
                else:
                    bargs.append(a)
                    fidx.append(i)
            if refd:
                env = self.env_of(task.locals)
                try:
                    loc = tuple((nm, env[nm]) for nm in refd)
                    hash(loc)
                except (KeyError, TypeError):
                    return None
            else:   # body reads no locals: one group per shape signature
                loc = ()
            out_present = tuple(i for i, nm in written
                                if task.data[i].data_in is not None)
            static = (loc, tuple(absent), tuple(fidx), out_present)
            return tuple(bargs), tuple(fidx), static

        def call(bargs, static):
            loc, absent, fidx, out_present = static
            env = dict(self.tp.global_env)
            env.update(loc)
            for nm in absent:
                env[nm] = None
            for a, i in zip(bargs, fidx):
                env[flow_name[i]] = a
            env["es_rank"] = self.tp.rank
            try:
                import jax.numpy as jnp
                env["jnp"] = jnp
            except Exception:
                pass
            env["np"] = np
            exec(code, env)
            return tuple(env[nm] for i, nm in written if i in out_present)

        return DeviceBatchSpec(f"{self.name}[{body.device_type}]",
                               extract, call)


def _detached_clone(copy: DataCopy) -> DataCopy:
    """A private host copy of ``copy``'s payload, detached from its Data
    (body mutations stay private until the writeback applies them)."""
    payload = (None if copy is None or copy.payload is None
               else np.array(np.asarray(copy.payload)))
    d = Data(nb_elts=0 if payload is None else payload.size)
    c = DataCopy(d, 0, payload=payload,
                 dtt=None if copy is None else copy.dtt)
    c.version = 1
    c.coherency = Coherency.OWNED
    d.attach_copy(c)
    return c


def _expand_args(args: List[Any], env: Dict[str, Any]) -> Iterator[Tuple]:
    """Expand Expr/RangeExpr argument lists into concrete locals tuples
    (a range arg == broadcast edge, ref Ex05 ``TaskRecv(k, 0 .. NB .. 2)``)."""
    dims: List[List[int]] = []
    for a in args:
        if isinstance(a, RangeExpr):
            dims.append(list(a.values(env)))
        else:
            dims.append([a(env)])
    for combo in itertools.product(*dims):
        yield tuple(combo)


class PTGTaskpool(Taskpool):
    """One instantiated JDF taskpool (ref: the generated
    parsec_<name>_taskpool_t + constructor, jdf2c.c:4576)."""

    def __init__(self, jdf: JDFFile, global_env: Dict[str, Any],
                 rank: int = 0, nb_ranks: int = 1) -> None:
        super().__init__(name=jdf.name, nb_task_classes=len(jdf.task_classes))
        self.jdf = jdf
        self.rank = rank
        self.nb_ranks = nb_ranks
        self.global_env: Dict[str, Any] = {"np": np}
        # run prologue blocks IN global_env (globals == locals, so helper
        # functions can see each other, recurse, and read JDF globals)
        for block in jdf.prologue:
            exec(compile(block, f"<jdf:{jdf.name}:prologue>", "exec"),
                 self.global_env)
        # bind globals: hidden ones take defaults, others must be supplied
        for g in jdf.globals:
            if g.name in global_env:
                self.global_env[g.name] = global_env[g.name]
            elif g.default is not None:
                self.global_env[g.name] = g.default(self.global_env)
            else:
                raise TypeError(f"{jdf.name}: missing global {g.name!r}")
        unknown = set(global_env) - {g.name for g in jdf.globals}
        if unknown:
            raise TypeError(f"{jdf.name}: unknown globals {sorted(unknown)}")
        self._classes: Dict[str, PTGTaskClass] = {}
        for i, tc_ast in enumerate(jdf.task_classes):
            tc = PTGTaskClass(self, tc_ast, i)
            self._classes[tc_ast.name] = tc
            self.task_classes.append(tc)
        self._scratch_lock = threading.Lock()
        self.reshape_repo = ReshapeRepo()
        self.startup_hook = self._startup
        self.nb_local_tasks = 0
        self.comm = None  # remote-dep driver, attached by the comm engine
        self._dag = None      # LoweredDAG when static dep management is on
        self._turbo = None    # TurboRunner when the native loop took it
        self._engine = None   # NativeDAG / PyDAG ready-tracking engine
        self._stagec = None   # StageCompiler when stage_compile is on

    def class_by_name(self, name: str) -> PTGTaskClass:
        return self._classes[name]

    # ------------------------------------------------------------------ #
    # startup (ref: generated startup enumerator jdf2c.c:2975-3385)       #
    # ------------------------------------------------------------------ #
    def _startup(self, context, tp) -> List[Task]:
        if params.get("stage_compile") and not grapher.enabled:
            # whole-stage DAG->XLA compilation (stagec/, ISSUE 12):
            # compilable stages execute as single fused chores, the
            # residue stays on the interpreted path below.  Takes
            # precedence over the static/turbo engines — the compiled
            # stage IS the static fast path here.
            from ...stagec.runtime import try_install
            self._stagec = try_install(self, context)
        if (self._stagec is None
                and params.get("ptg_dep_management") == "static"
                and self.nb_ranks == 1 and not grapher.enabled
                and not self._has_out_edge_types()):
            turbo = self._startup_turbo(context)
            if turbo is not None:
                return turbo
            return self._startup_static()
        total = 0
        startup: List[Task] = []
        sc = self._stagec
        count_foreign = self.nb_ranks > 1 and self.comm is not None
        expected_mem_puts = 0
        if sc is not None:
            # plan-cached startup enumeration (ISSUE 13): the stage
            # plan already walked the full instance space — local
            # totals, goal-0 residue, and the foreign mem-put
            # expectation are pure functions of its identity, so a
            # repeat pool skips the per-instance iteration-space walk
            total = sc.plan.n_local
            expected_mem_puts = sc.plan.startup_mem_puts
            for (name, locals_) in sc.plan.startup_goal0:
                t = self.class_by_name(name).make_task(locals_, None)
                t = sc.on_residue_ready(t)
                if t is not None:
                    startup.append(t)
            # stages with no external task inputs start the DAG (their
            # members are counted in n_local; a stage completion
            # retires every member's count)
            startup.extend(sc.startup_tasks())
        else:
            for tc in self._classes.values():
                for locals_ in tc.iter_space():
                    env = tc.env_of(locals_)
                    if tc.rank_of_instance(env) != self.rank:
                        if count_foreign:
                            # a foreign task whose out-dep targets MY
                            # memory will ship a writeback: hold
                            # termination for it
                            expected_mem_puts += \
                                self._count_mem_puts_to_me(tc, env)
                        continue
                    total += 1
                    if tc.goal_of(locals_, env) == 0:
                        startup.append(tc.make_task(locals_, None))
        # counts FIRST, delivery second: activations/puts released by
        # counts_ready may schedule tasks that complete on a worker
        # thread immediately — nb_tasks must already hold the total or
        # the decrement goes negative (or is overwritten into a hang)
        self.nb_local_tasks = total
        self.set_nb_tasks(total)
        if expected_mem_puts:
            self.add_pending_action(expected_mem_puts)
        if count_foreign:
            # expectations credited: buffered early arrivals may deliver
            self.comm.counts_ready(self)
        if sc is not None:
            # cross-pool chaining (stagec/chain.py, ISSUE 13): when an
            # earlier pool's chained program pre-computed this pool's
            # first stage, adopt its stashed outputs now — AFTER the
            # counts above, so the members' completions cannot go
            # negative.  Successors it releases join the startup set.
            startup.extend(sc.consume_chain(
                context.execution_streams[0]))
        plog.debug.verbose(4, "ptg %s: %d local tasks, %d startup",
                           self.name, total, len(startup))
        return startup

    def _startup_turbo(self, context) -> Optional[List[Task]]:
        """The static mode's native fast path (VERDICT r3 missing #4):
        data binding precompiled into slot tables, select->release in a
        C priority heap, one XLA call per task, lazy device-resident
        writebacks. Falls back to the classic static path (None) when
        the pool is turbo-ineligible (unresolvable slots), unless
        ptg_dispatch=turbo demands it. Runs on a worker claimed from
        the wait loop; errors surface through record_task_error like
        any task-body failure."""
        mode = str(params.get_or("ptg_dispatch", "string", "auto"))
        if mode not in ("auto", "turbo"):
            return None
        tpu_devs = [d for d in context.devices
                    if d.device_type == "tpu"]
        if not tpu_devs:
            if mode == "turbo":
                raise RuntimeError(
                    "ptg_dispatch=turbo demands the native loop but the "
                    "context has no accelerator device module")
            return None
        from .turbo import TurboRunner
        from .wave import WaveError
        try:
            runner = TurboRunner(self)
        except WaveError as exc:
            if mode == "turbo":
                raise
            plog.debug.verbose(
                2, "ptg %s: turbo ineligible (%s); classic static path",
                self.name, exc)
            return None
        dev = tpu_devs[0]
        self._turbo = runner
        n = runner.dag.n_tasks
        self.nb_local_tasks = n
        self.set_nb_tasks(n)

        def _run(es):
            pools = runner.build_pools(device=dev.jax_device)
            runner.execute_per_task(pools, device=dev.jax_device)
            runner.attach_lazy_results(dev.device_index)
            dev.stats["tasks"] += n
            for _ in range(n):
                self.task_completed()

        context.submit_native_loop(_run)
        plog.debug.verbose(4, "ptg %s (turbo): %d tasks queued on the "
                           "native loop", self.name, n)
        return []

    def _startup_static(self) -> List[Task]:
        """Static dep management (ref: --dep-management=index-array):
        lower the task space once into flat arrays + a native counter
        engine; startup = the zero-indegree set. Single-rank only —
        multi-rank and DOT capture stay on the dynamic hash path."""
        from .lower import lower, make_engine
        self._dag = lower(self)
        self._engine = make_engine(self._dag)
        self.nb_local_tasks = self._dag.n_tasks
        self.set_nb_tasks(self._dag.n_tasks)
        startup = [self._make_task_static(t) for t in self._engine.start()]
        plog.debug.verbose(4, "ptg %s (static): %d tasks, %d edges, "
                           "%d startup", self.name, self._dag.n_tasks,
                           self._dag.n_edges, len(startup))
        return startup

    def _has_out_edge_types(self) -> bool:
        """[type=...] on OUT deps reshapes copies during release — the
        static engine routes copies in C without property handling, so
        such taskpools stay on the dynamic path. (type_remote is
        consumer-resolved and does not affect the release walk.)"""
        for tc in self.task_classes:
            for f in tc.ast.flows:
                for d in f.deps_out():
                    if "type" in d.properties:
                        return True
        return False

    def _make_task_static(self, tid: int) -> Task:
        """Spawn a lowered task: class/locals/priority from the flat
        arrays; inputs routed by the engine land in flow order."""
        dag = self._dag
        tc = self.task_classes[int(dag.class_of[tid])]
        task = Task(self, tc, dag.locals_of[tid],
                    priority=int(dag.priority[tid]))
        bindings = self._engine.take_bindings(tid)
        for i in range(len(tc.ast.flows)):
            copy = bindings[i]
            if copy is not None:
                task.data[i].data_in = copy
                task.data[i].fulfilled = True
        return task

    # ------------------------------------------------------------------ #
    # data helpers                                                       #
    # ------------------------------------------------------------------ #
    def host_copy_of(self, es, data: Data) -> DataCopy:
        return data.host_copy()

    def pull_newest_to_host(self, es, data: Data) -> DataCopy:
        if es is None:
            return data.host_copy()
        return data.sync_to_host(es.context.devices)

    def _count_mem_puts_to_me(self, tc: "PTGTaskClass",
                              env: Dict[str, Any]) -> int:
        """#memory out-deps of one FOREIGN instance that land on a tile
        this rank owns (must mirror writeback_outputs' emission)."""
        n = 0
        for i, f in enumerate(tc.ast.flows):
            if f.is_ctl or not (tc.flows[i].access & FlowAccess.WRITE):
                continue
            for d in f.deps_out():
                t = d.resolve(env)
                if t is None or t.kind != "memory":
                    continue
                coll = self.global_env[t.collection]
                if coll.rank_of(*[a(env) for a in t.args]) == self.rank:
                    n += 1
        return n

    def new_scratch_copy(self, f: FlowAST, env: Dict[str, Any]) -> DataCopy:
        """NEW target: a runtime-allocated buffer (ref: arena-backed NEW
        tiles). Shape comes from the flow's [shape=...] property: either
        the ``AxB`` dimension form or (quoted) one Python expression
        evaluating to an int/tuple — instance-dependent shapes like
        partial edge tiles need the latter."""
        shape = scratch_shape(f, env)
        if shape is None:
            raise RuntimeError(
                f"flow {f.name}: NEW target needs a [shape=...] property")
        dt = np.dtype(f_prop(f, "dtype", "float32"))
        data = Data(nb_elts=int(np.prod(shape)))
        copy = DataCopy(data, 0, payload=np.zeros(shape, dtype=dt))
        copy.coherency = Coherency.OWNED
        copy.version = 1
        data.attach_copy(copy)
        return copy

    # memory writeback of out deps targeting collections
    def writeback_outputs(self, es, task: Task) -> None:
        tc: PTGTaskClass = task.task_class
        env = tc.env_of(task.locals)
        for i, f in enumerate(tc.ast.flows):
            if f.is_ctl or not (tc.flows[i].access & FlowAccess.WRITE):
                continue
            copy = task.data[i].data_out or task.data[i].data_in

            # lazy: a D2H pull only when some dep really needs host bytes —
            # the dominant case (tile already home, newest copy on device)
            # must not pay a device->host transfer per task (at tunnel
            # bandwidths that serializes the whole DAG on PCIe/DCN)
            _src_host_cell: List[Any] = []

            def src_host_of():
                if not _src_host_cell:
                    if copy is None or copy.device_id == 0:
                        _src_host_cell.append(copy)
                    elif copy.data is not None:
                        _src_host_cell.append(
                            self.pull_newest_to_host(es, copy.data))
                    else:
                        # detached device copy (Data destructed): no host
                        # source exists; remote path sends a release-only
                        # notification, local path errors loudly below
                        _src_host_cell.append(None)
                return _src_host_cell[0]

            for d in f.deps_out():
                t = d.resolve(env)
                if t is None or t.kind != "memory":
                    continue
                coll = self.global_env[t.collection]
                args = [a(env) for a in t.args]
                dst_rank = coll.rank_of(*args)
                if dst_rank != self.rank:
                    # cross-rank memory writeback: ship to the owner, who
                    # counted this arrival as a pending runtime action at
                    # startup; a copy-less flow still sends a release-only
                    # notification so the owner's count retires (the
                    # static count cannot see dynamic copy-None)
                    assert self.comm is not None, \
                        "remote memory target without a comm engine"
                    sh = src_host_of()
                    payload = sh.payload if sh is not None else None
                    self.comm.mem_writeback(self, t.collection, tuple(args),
                                            payload, dst_rank)
                    continue
                if copy is None:
                    continue
                # [type_data=...] / [type=...] on a memory OUT dep: only
                # the declared region's elements land in memory, the rest
                # of the destination tile keeps its old values (ref:
                # local_input_reshape.jdf WRITE_A -> descA [type=LOWER])
                wb_name = (d.properties.get("type_data")
                           or d.properties.get("type"))
                if wb_name is not None and copy is not None:
                    # a no-op annotation ([type=full] / a full-region
                    # Datatype with the copy's own dtype) must NOT
                    # defeat the lazy already-home path below — that
                    # would force a per-task D2H pull (fatal at tunnel
                    # rates)
                    if wb_name == "full":
                        wb_name = None
                    else:
                        val = self.global_env.get(wb_name)
                        pdt = getattr(copy.payload, "dtype", None)
                        if (isinstance(val, Datatype)
                                and val.region == "full" and pdt is not None
                                and np.dtype(val.dtype) == np.dtype(pdt)):
                            wb_name = None
                dest = coll.data_of(*args)
                if copy.data is dest and wb_name is None:
                    # already home: the Data owns the newest (device) copy;
                    # do NOT force a device->host transfer here — readers
                    # sync lazily (a per-task d2h pull would serialize the
                    # DAG on transfer latency)
                    continue
                sh = src_host_of()
                if sh is None:
                    raise RuntimeError(
                        f"{task.snprintf()}: memory writeback of flow "
                        f"{f.name} from a detached device copy")
                src_arr = np.asarray(sh.payload)
                mask = None
                if wb_name is not None:
                    dtt = tc.resolve_dtt_name(wb_name, sh, f.name)
                    src_arr = np.asarray(reshape_to(src_arr, dtt))
                    mask = dtt.mask()
                # a masked writeback preserves the destination's
                # out-of-region values — those must be the NEWEST ones,
                # which may live on a device (the lazy already-home path);
                # an unmasked writeback fully overwrites, so the plain
                # host copy suffices
                dh = (self.pull_newest_to_host(es, dest) if mask is not None
                      else self.host_copy_of(es, dest))
                if dh.payload is None:
                    dh.payload = np.array(src_arr)
                elif mask is None:
                    np.copyto(dh.payload, src_arr)
                else:
                    np.copyto(dh.payload, src_arr, where=mask)
                dest.version_bump(0)


def f_prop(f: FlowAST, key: str, default: str) -> str:
    for d in f.deps:
        if key in d.properties:
            return d.properties[key]
    return default


def scratch_shape(f: FlowAST, env: Dict[str, Any]) -> Optional[Tuple[int, ...]]:
    """Shape a flow's [shape=...] property declares for this instance
    (``AxB`` dims or one Python expression -> int/tuple), or None when
    the property is absent. Shared by the runtime's NEW allocation and
    wave scratch pools so both accept the same JDFs."""
    shape_src = None
    for d in f.deps:
        if "shape" in d.properties:
            shape_src = d.properties["shape"]
            break
    if shape_src is None:
        return None
    try:
        val = Expr(shape_src)(env)
    except (SyntaxError, NameError, TypeError):
        val = None
    if isinstance(val, (tuple, list)):
        return tuple(int(v) for v in val)
    if isinstance(val, (int, np.integer)):
        return (int(val),)
    return tuple(int(Expr(x)(env)) for x in shape_src.split("x"))
