"""Distributed wave execution over the in-process fabric (SPMD threads,
one CE per rank — the reference's oversubscribed-mpiexec analog).

Covers the three transfer kinds of the static schedule (wave-0
pre-exchange of home tiles, post-wave producer->reader pushes, final
write->home returns) plus the north-star shape: dpotrf over a 2D
block-cyclic distribution on 2 and 4 ranks, numerics-checked against
numpy Cholesky.
"""
import numpy as np
import pytest

from parsec_tpu.comm import LocalFabric
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.dsl.ptg.wave import WaveError
from parsec_tpu.ops import dpotrf_taskpool, make_spd

from test_comm_multirank import spmd


def _gather_owned(coll, rank):
    out = {}
    for c in coll.tiles():
        if coll.rank_of(*c) == rank:
            out[c] = np.asarray(
                coll.data_of(*c).sync_to_host().payload).copy()
    return out


def _dpotrf_rank(rank, fabric, nb_ranks, M, n, nb, P, Q):
    ce = fabric.engine(rank)
    coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                             P=P, Q=Q, nodes=nb_ranks, rank=rank)
    coll.name = "descA"
    coll.from_numpy(M.copy())
    tp = dpotrf_taskpool(coll, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=ce)
    w.run()
    return _gather_owned(coll, rank)


@pytest.mark.parametrize("nb_ranks,P,Q", [(2, 2, 1), (4, 2, 2)])
def test_dist_wave_dpotrf(nb_ranks, P, Q):
    n, nb = 512, 64
    M = make_spd(n, dtype=np.float64)
    results, _ = spmd(
        nb_ranks,
        lambda r, f: _dpotrf_rank(r, f, nb_ranks, M, n, nb, P, Q),
        timeout=180)
    L = np.zeros((n, n))
    for owned in results:
        for (m, k), t in owned.items():
            L[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = t
    L = np.tril(L)
    ref = np.linalg.cholesky(M)
    np.testing.assert_allclose(L, ref, rtol=0, atol=1e-8 * n)


# --------------------------------------------------------------------- #
# wave-0 pre-exchange: every rank's task reads a tile whose HOME is the #
# other rank, before anyone writes it                                   #
# --------------------------------------------------------------------- #
PREX_JDF = """
descA [ type="collection" ]
descB [ type="collection" ]
M [ type="int" ]

Sweep(m)
m = 0 .. M-1
: descB( m, 0 )
RW B <- descB( m, 0 )
     -> descB( m, 0 )
READ L <- descA( (m+1) % M, 0 )
BODY
{
    B = B + 2.0 * L
}
END
"""


def _prex_rank(rank, fabric, nb_ranks, A0, B0, M, nb):
    ce = fabric.engine(rank)
    mk = lambda: TwoDimBlockCyclic(M * nb, nb, nb, nb, dtype=np.float64,
                                   P=nb_ranks, Q=1, nodes=nb_ranks,
                                   rank=rank)
    dA, dB = mk(), mk()
    dA.name, dB.name = "descA", "descB"
    dA.from_numpy(A0.copy())
    dB.from_numpy(B0.copy())
    tp = ptg.compile_jdf(PREX_JDF, name="prex").new(
        descA=dA, descB=dB, M=M, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=ce)
    w.run()
    return _gather_owned(dB, rank)


def test_dist_wave_zero_exchange_of_home_tiles():
    M, nb = 4, 8
    rng = np.random.RandomState(1)
    A0 = rng.rand(M * nb, nb)
    B0 = rng.rand(M * nb, nb)
    results, _ = spmd(2, lambda r, f: _prex_rank(r, f, 2, A0, B0, M, nb))
    got = {}
    for owned in results:
        got.update(owned)
    for m in range(M):
        exp = (B0[m * nb:(m + 1) * nb]
               + 2.0 * A0[((m + 1) % M) * nb:(((m + 1) % M) + 1) * nb])
        np.testing.assert_allclose(got[(m, 0)], exp, rtol=1e-6)


# --------------------------------------------------------------------- #
# producer->reader edge transfer + final write->home return: Phase1     #
# writes its own tile, Phase2 on the OTHER rank consumes it via a task  #
# edge; Write2 runs on descB's rank but its slot tile lives in descA    #
# (last write returns home before scatter)                              #
# --------------------------------------------------------------------- #
EDGE_JDF = """
descA [ type="collection" ]
descB [ type="collection" ]
M [ type="int" ]

Phase1(m)
m = 0 .. M-1
: descA( m, 0 )
RW A <- descA( m, 0 )
     -> L Phase2( (m+1) % M )
     -> descA( m, 0 )
BODY
{
    A = A * 10.0
}
END

Phase2(m)
m = 0 .. M-1
: descB( m, 0 )
RW B <- descB( m, 0 )
     -> descB( m, 0 )
READ L <- A Phase1( (m+M-1) % M )
BODY
{
    B = B + L
}
END
"""


def _edge_rank(rank, fabric, nb_ranks, A0, B0, M, nb):
    ce = fabric.engine(rank)
    dA = TwoDimBlockCyclic(M * nb, nb, nb, nb, dtype=np.float64,
                           P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
    # descB's distribution is SHIFTED: tile m of B lives on the rank
    # that does NOT own tile m of A, so every edge crosses ranks
    class Shifted(TwoDimBlockCyclic):
        def rank_of(self, m, n=0):
            return (super().rank_of(m, n) + 1) % nb_ranks
    dB = Shifted(M * nb, nb, nb, nb, dtype=np.float64,
                 P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
    dA.name, dB.name = "descA", "descB"
    dA.from_numpy(A0.copy())
    dB.from_numpy(B0.copy())
    tp = ptg.compile_jdf(EDGE_JDF, name="edge").new(
        descA=dA, descB=dB, M=M, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=ce)
    w.run()
    return _gather_owned(dA, rank), _gather_owned(dB, rank)


def test_dist_wave_edge_transfer_and_home_return():
    M, nb = 4, 8
    rng = np.random.RandomState(2)
    A0 = rng.rand(M * nb, nb)
    B0 = rng.rand(M * nb, nb)
    results, _ = spmd(2, lambda r, f: _edge_rank(r, f, 2, A0, B0, M, nb))
    gotA, gotB = {}, {}
    for a, b in results:
        gotA.update(a)
        gotB.update(b)
    for m in range(M):
        sl = slice(m * nb, (m + 1) * nb)
        np.testing.assert_allclose(gotA[(m, 0)], 10.0 * A0[sl], rtol=1e-6)
        prev = slice(((m - 1) % M) * nb, (((m - 1) % M) + 1) * nb)
        np.testing.assert_allclose(gotB[(m, 0)], B0[sl] + 10.0 * A0[prev],
                                   rtol=1e-6)


def test_dist_wave_requires_affinity():
    """A class without affinity has no owner — must be rejected, not
    silently executed everywhere (divergent schedules would hang)."""
    NOAFF = """
descA [ type="collection" ]
M [ type="int" ]

T(m)
m = 0 .. M-1
RW A <- descA( m, 0 )
     -> descA( m, 0 )
BODY
{
    A = A + 1.0
}
END
"""

    def run(rank, fabric):
        ce = fabric.engine(rank)
        dA = TwoDimBlockCyclic(16, 8, 8, 8, dtype=np.float64,
                               P=2, Q=1, nodes=2, rank=rank)
        dA.name = "descA"
        dA.from_numpy(np.zeros((16, 8)))
        tp = ptg.compile_jdf(NOAFF, name="noaff").new(
            descA=dA, M=2, rank=rank, nb_ranks=2)
        with pytest.raises(WaveError, match="affinity"):
            ptg.wave(tp, comm=ce)
        return True

    results, _ = spmd(2, run)
    assert all(results)


def _getrf_rank(rank, fabric, nb_ranks, M0, n, nb):
    from parsec_tpu.ops import dgetrf_nopiv_taskpool

    ce = fabric.engine(rank)
    coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                             P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
    coll.name = "descA"
    coll.from_numpy(M0.copy())
    tp = dgetrf_nopiv_taskpool(coll, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=ce)
    w.run()
    return _gather_owned(coll, rank)


def test_dist_wave_dgetrf(nb_ranks=2):
    """LU (no pivoting) distributed: a DIFFERENT dataflow shape than
    Cholesky (row+column panels) through the same static schedule."""
    n, nb = 256, 64
    M = make_spd(n, dtype=np.float64)   # SPD: no-pivot LU is stable
    results, _ = spmd(
        nb_ranks, lambda r, f: _getrf_rank(r, f, nb_ranks, M, n, nb))
    LU = np.zeros((n, n))
    for owned in results:
        for (m, k), t in owned.items():
            LU[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = t
    L = np.tril(LU, -1) + np.eye(n)
    U = np.triu(LU)
    assert np.abs(L @ U - M).max() / np.abs(M).max() < 1e-5


def _pdgemm_rank(rank, fabric, nb_ranks, Am, Bm, n, nb):
    from parsec_tpu.ops import pdgemm_taskpool

    ce = fabric.engine(rank)

    def dist(src, name):
        d = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                              P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
        d.name = name
        d.from_numpy(src.copy())
        return d

    A = dist(Am, "descA")
    B = dist(Bm, "descB")
    C = dist(np.zeros((n, n)), "descC")
    tp = pdgemm_taskpool(A, B, C, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=ce)
    w.run()
    return _gather_owned(C, rank)


def test_dist_wave_pdgemm(nb_ranks=2):
    """SUMMA-style GEMM distributed: three collections, broadcast-heavy
    cross-rank edges, k-loop accumulation."""
    n, nb = 256, 64
    rng = np.random.RandomState(5)
    Am = rng.rand(n, n)
    Bm = rng.rand(n, n)
    results, _ = spmd(
        nb_ranks, lambda r, f: _pdgemm_rank(r, f, nb_ranks, Am, Bm, n, nb))
    C = np.zeros((n, n))
    for owned in results:
        for (m, k), t in owned.items():
            C[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = t
    ref = Am @ Bm
    assert np.abs(C - ref).max() / np.abs(ref).max() < 1e-5


def test_dist_wave_stats():
    """Distributed runs expose exchange counters; SPMD ranks agree on
    the schedule so sent == recv across the job."""
    n, nb = 256, 64
    M = make_spd(n, dtype=np.float64)

    def run(rank, fabric):
        ce = fabric.engine(rank)
        coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                 P=2, Q=1, nodes=2, rank=rank)
        coll.name = "descA"
        coll.from_numpy(M.copy())
        tp = dpotrf_taskpool(coll, rank=rank, nb_ranks=2)
        w = ptg.wave(tp, comm=ce)
        w.run()
        return w.stats

    results, _ = spmd(2, run)
    s0, s1 = results
    assert s0["tasks"] == s1["tasks"]
    assert s0["local_tasks"] + s1["local_tasks"] == s0["tasks"]
    assert s0["transfers_scheduled"] == s1["transfers_scheduled"] > 0
    assert s0["tiles_sent"] + s1["tiles_sent"] \
        == s0["tiles_recv"] + s1["tiles_recv"] > 0


def test_dist_wave_dgeqrf(nb_ranks=2):
    """QR distributed: scratch-flow (T factor) forwarding crosses ranks
    through the same static schedule (scratch pools are replicated and
    exchanged like real tiles, minus home transfers)."""
    from parsec_tpu.ops import dgeqrf_taskpool

    n, nb = 256, 64
    rng = np.random.RandomState(4)
    Am = rng.rand(n, n).astype(np.float64)

    def run(rank, fabric):
        ce = fabric.engine(rank)
        coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                 P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
        coll.name = "descA"
        coll.from_numpy(Am.copy())
        tp = dgeqrf_taskpool(coll, rank=rank, nb_ranks=nb_ranks)
        w = ptg.wave(tp, comm=ce)
        w.run()
        return _gather_owned(coll, rank)

    results, _ = spmd(nb_ranks, run)
    out = np.zeros((n, n))
    for owned in results:
        for (m, k), t in owned.items():
            out[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = t
    # single-rank wave is the reference (parity there is tested
    # separately); the distributed run must reproduce it exactly
    A1 = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64).from_numpy(
        Am.copy())
    from parsec_tpu.dsl.ptg.wave import WaveRunner
    WaveRunner(dgeqrf_taskpool(A1)).run()
    np.testing.assert_allclose(out, A1.to_numpy(), rtol=1e-6, atol=1e-9)


def test_dist_wave_pools_are_sliced():
    """Each rank stages only its touched tiles + halo — summed over
    ranks that's less than 2x the matrix (full replication would be
    exactly 2x the tile count at 2 ranks)."""
    n, nb = 512, 64           # NT=8: 36 lower tiles in play
    M = make_spd(n, dtype=np.float64)

    def run(rank, fabric):
        ce = fabric.engine(rank)
        coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                 P=2, Q=1, nodes=2, rank=rank)
        coll.name = "descA"
        coll.from_numpy(M.copy())
        tp = dpotrf_taskpool(coll, rank=rank, nb_ranks=2)
        w = ptg.wave(tp, comm=ce)
        w.run()
        return w.stats["local_tiles"], len(list(coll.tiles()))

    results, _ = spmd(2, run)
    total_local = sum(r[0] for r in results)
    full = results[0][1]
    assert total_local < 2 * full, (total_local, full)
    # and each rank holds strictly less than the whole collection
    assert all(r[0] < full for r in results), results


# --------------------------------------------------------------------- #
# ragged tilings distributed: shape-split pools + the static exchange   #
# schedule (pool ids are SPMD-deterministic, so the wire protocol is    #
# unchanged; edge tiles ship at their true size)                        #
# --------------------------------------------------------------------- #
def _ragged_assemble(results, coll_proto, n):
    out = np.zeros((n, n))
    nb = coll_proto.mb
    for owned in results:
        for (m, k), t in owned.items():
            out[m * nb:m * nb + t.shape[0],
                k * nb:k * nb + t.shape[1]] = t
    return out


@pytest.mark.parametrize("n,nb", [(232, 64), (200, 64)])
def test_dist_wave_dpotrf_ragged(n, nb, nb_ranks=2):
    M = make_spd(n, dtype=np.float64)
    results, _ = spmd(
        nb_ranks,
        lambda r, f: _dpotrf_rank(r, f, nb_ranks, M, n, nb, nb_ranks, 1),
        timeout=180)
    proto = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64)
    L = np.tril(_ragged_assemble(results, proto, n))
    np.testing.assert_allclose(L, np.linalg.cholesky(M),
                               rtol=0, atol=1e-8 * n)


def test_dist_wave_dgetrf_ragged(nb_ranks=2):
    n, nb = 200, 64
    M = make_spd(n, dtype=np.float64)
    results, _ = spmd(
        nb_ranks, lambda r, f: _getrf_rank(r, f, nb_ranks, M, n, nb))
    proto = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64)
    LU = _ragged_assemble(results, proto, n)
    L = np.tril(LU, -1) + np.eye(n)
    U = np.triu(LU)
    assert np.abs(L @ U - M).max() / np.abs(M).max() < 1e-5


# --------------------------------------------------------------------- #
# collective lanes: a tile read by P remote ranks propagates along a    #
# static broadcast tree (re-forwarded by receivers, the reference's     #
# remote_dep.c:272-358 collective propagation) instead of P sends from  #
# the source                                                            #
# --------------------------------------------------------------------- #
BCAST_JDF = """
descA [ type="collection" ]
descB [ type="collection" ]
R [ type="int" ]

Read(r)
r = 0 .. R-1
: descB( r, 0 )
RW B <- descB( r, 0 )
     -> descB( r, 0 )
READ L <- descA( 0, 0 )
BODY
{
    B = B + L
}
END
"""


def _bcast_rank(rank, fabric, nb_ranks, A0, B0, nb):
    ce = fabric.engine(rank)
    mk = lambda: TwoDimBlockCyclic(nb_ranks * nb, nb, nb, nb,
                                   dtype=np.float64, P=nb_ranks, Q=1,
                                   nodes=nb_ranks, rank=rank)
    dA, dB = mk(), mk()
    dA.name, dB.name = "descA", "descB"
    dA.from_numpy(A0.copy())
    dB.from_numpy(B0.copy())
    tp = ptg.compile_jdf(BCAST_JDF, name="bcastw").new(
        descA=dA, descB=dB, R=nb_ranks, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=ce)
    w.run()
    return w.stats, _gather_owned(dB, rank)


def _run_bcast(nb_ranks, topo):
    from parsec_tpu.utils.params import params
    nb = 8
    rng = np.random.RandomState(3)
    A0 = rng.rand(nb_ranks * nb, nb)
    B0 = rng.rand(nb_ranks * nb, nb)
    params.set_cmdline("wave_dist_bcast", topo)
    try:
        results, _ = spmd(
            nb_ranks,
            lambda r, f: _bcast_rank(r, f, nb_ranks, A0, B0, nb))
    finally:
        params.unset_cmdline("wave_dist_bcast")
    # numerics: every rank's row block got A's first tile added
    for r, (_st, owned) in enumerate(results):
        np.testing.assert_allclose(
            owned[(r, 0)], B0[r * nb:(r + 1) * nb] + A0[:nb],
            rtol=1e-6)
    return [st for st, _o in results]


def test_dist_wave_bcast_tree_offloads_root(nb_ranks=4):
    """descA(0,0) is read by all 4 ranks: star ships 3 tiles from the
    root; the binomial tree ships 2 from the root and 1 re-forward from
    an interior rank — the root's send count scales sub-linearly."""
    star = _run_bcast(nb_ranks, "star")
    assert star[0]["tiles_sent"] == nb_ranks - 1
    assert sum(s["tiles_forwarded"] for s in star) == 0

    tree = _run_bcast(nb_ranks, "binomial")
    assert tree[0]["bcast_topology"] == "binomial"
    assert tree[0]["tiles_sent"] < nb_ranks - 1      # root offloaded
    assert sum(s["tiles_forwarded"] for s in tree) >= 1
    # same tile volume reaches the readers either way
    assert sum(s["tiles_recv"] for s in tree) == \
        sum(s["tiles_recv"] for s in star) == nb_ranks - 1


def test_dist_wave_collective_lane_bcast(nb_ranks=4):
    """A full-broadcast tile rides ONE compiled XLA collective (sum over
    the lane mesh's rank axis == broadcast) instead of P descriptor
    sends (round-4 VERDICT Missing #2; SURVEY §5.8 target;
    ref /root/reference/parsec/remote_dep.c:272-358). Differential: the
    tree path and the lane produce identical results (numerics asserted
    inside _run_bcast for both), and the lane run ships ZERO p2p tiles."""
    from parsec_tpu.utils.params import params

    tree = _run_bcast(nb_ranks, "binomial")
    assert sum(s["tiles_sent"] for s in tree) == nb_ranks - 1
    assert all(s["collective_calls"] == 0 for s in tree)

    params.set_cmdline("wave_dist_collective", "on")
    try:
        lane = _run_bcast(nb_ranks, "binomial")
    finally:
        params.unset_cmdline("wave_dist_collective")
    assert all(s["collective_lane"] == "inproc" for s in lane), lane
    # every rank took part in exactly one collective op carrying the
    # one broadcast tile; no point-to-point tile moved at all
    assert all(s["collective_calls"] == 1 for s in lane), lane
    assert all(s["collective_tiles"] == 1 for s in lane), lane
    assert sum(s["tiles_sent"] for s in lane) == 0, lane
    assert sum(s["tiles_recv"] for s in lane) == 0, lane


def _lane_differential(nb_ranks, n, nb, P, check_runner=None):
    """Shared scaffold for the lane differential tests: run dist-wave
    dpotrf twice on the same SPD input — trees, then the compiled
    collective lane — and assert the tree factor matches numpy
    cholesky, the lane factor is bit-identical to the trees, the lane
    fired, and it displaced p2p sends. Tile assembly is shape-aware so
    ragged (shape-split) tilings ride the same helper. Returns
    (st_tree, st_lane) for per-test extra asserts."""
    from parsec_tpu.utils.params import params

    M = make_spd(n, dtype=np.float64)

    def run(lane_on):
        def rank_fn(r, f):
            ce = f.engine(r)
            coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                     P=P, Q=nb_ranks // P,
                                     nodes=nb_ranks, rank=r)
            coll.name = "descA"
            coll.from_numpy(M.copy())
            tp = dpotrf_taskpool(coll, rank=r, nb_ranks=nb_ranks)
            w = ptg.wave(tp, comm=ce)
            if check_runner is not None:
                check_runner(w, lane_on)
            w.run()
            return w.stats, _gather_owned(coll, rank=r)

        if lane_on:
            params.set_cmdline("wave_dist_collective", "on")
        try:
            results, _ = spmd(nb_ranks, rank_fn, timeout=180)
        finally:
            if lane_on:
                params.unset_cmdline("wave_dist_collective")
        L = np.zeros((n, n))
        for (_st, owned) in results:
            for (m, k), t in owned.items():    # edge tiles may be short
                L[m * nb:m * nb + t.shape[0],
                  k * nb:k * nb + t.shape[1]] = t
        return np.tril(L), [st for (st, _o) in results]

    L_tree, st_tree = run(False)
    L_lane, st_lane = run(True)
    ref = np.linalg.cholesky(M)
    np.testing.assert_allclose(L_tree, ref, rtol=0, atol=1e-8 * n)
    np.testing.assert_allclose(L_lane, L_tree, rtol=0, atol=0)
    assert sum(s["collective_calls"] for s in st_lane) > 0, st_lane
    assert sum(s["tiles_sent"] for s in st_lane) < \
        sum(s["tiles_sent"] for s in st_tree), (st_lane, st_tree)
    return st_tree, st_lane


def test_dist_wave_collective_lane_dpotrf_matches(nb_ranks=4):
    """dpotrf on a 4-rank row-cyclic distribution: every POTRF/TRSM
    panel tile is read by all other ranks, so the lane carries the
    panel broadcasts as FULL groups. Differential vs the tree path on
    the same input: identical factor, fewer p2p sends."""
    _st_tree, st_lane = _lane_differential(nb_ranks, 256, 32, P=nb_ranks)
    assert sum(s["collective_tiles"] for s in st_lane) > 0


def test_dist_wave_collective_lane_ragged_dpotrf(nb_ranks=4):
    """The lane over SHAPE-SPLIT pools: a ragged tiling (N % nb != 0)
    splits descA into multiple pools with distinct tile shapes; each
    (wave, pool, member set) broadcast group gets its own collective
    call with its own shapes. Differential vs the tree path on the
    same ragged input."""
    _lane_differential(nb_ranks, 232, 32, P=nb_ranks)  # NT=8, edge 8 rows


def test_dist_wave_collective_lane_partial_groups(nb_ranks=4):
    """PARTIAL broadcast groups on a 2D block-cyclic distribution: at
    P=2 x Q=2 a dpotrf panel tile is read by a row/column SUBSET of
    ranks, never by all three others — the full-broadcast-only lane
    scheduled NOTHING here (northstar at 2x4 recorded
    collective_calls=0). Groups of >= 3 members must reduce over a
    member-device sub-mesh; the remaining 1-dst edges stay p2p."""
    def check(w, lane_on):
        if lane_on:
            # the member sets really are partial: no group spans
            # every rank on this distribution
            groups = {m for by_g in w._lane_sched.values()
                      for (_c, m) in by_g}
            assert groups, "no lane groups scheduled at P=2xQ=2"
            assert all(len(m) < nb_ranks for m in groups), groups

    _lane_differential(nb_ranks, 256, 32, P=2, check_runner=check)


def test_dist_wave_collective_lane_ragged_partial(nb_ranks=4):
    """Composition of the two lane generalizations: SHAPE-SPLIT pools
    (ragged N % nb != 0) x PARTIAL member groups (P=2 x Q=2). Each
    (wave, pool, member set) gets its own sub-mesh collective with its
    own tile shape; differential vs the tree path."""
    _lane_differential(nb_ranks, 232, 32, P=2)


def test_dist_wave_bcast_chain_root_sends_once(nb_ranks=4):
    """Chain topology: the root ships each broadcast tile exactly ONCE
    regardless of reader count (O(1) in P), the chain re-forwards."""
    chain = _run_bcast(nb_ranks, "chain")
    assert chain[0]["tiles_sent"] == 1
    assert sum(s["tiles_forwarded"] for s in chain) == nb_ranks - 2


def test_dist_wave_lazy_writeback_single_tile_pull(nb_ranks=2):
    """scatter_pools keeps results device-resident (lazy pool-slice
    copies); a single owned-tile host read materializes exactly ONE
    slice — VERDICT r3 weak #7: never bulk-pull through a thin link."""
    from parsec_tpu.dsl.ptg.turbo import LazyPoolCopy

    n, nb = 256, 64
    M = make_spd(n, dtype=np.float64)

    def rank_fn(rank, fabric):
        ce = fabric.engine(rank)
        coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                 P=nb_ranks, Q=1, nodes=nb_ranks,
                                 rank=rank)
        coll.name = "descA"
        coll.from_numpy(M.copy())
        tp = dpotrf_taskpool(coll, rank=rank, nb_ranks=nb_ranks)
        w = ptg.wave(tp, comm=ce)
        w.run()
        lazies = []
        for c in coll.tiles():
            if coll.rank_of(*c) != rank:
                continue
            for cp in coll.data_of(*c).copies():
                if isinstance(cp, LazyPoolCopy):
                    lazies.append((c, cp))
        assert lazies, "no lazy writeback copies on owned tiles"
        assert not any(cp._mat for _c, cp in lazies), "writeback was eager"
        c0, _cp0 = lazies[0]
        coll.data_of(*c0).sync_to_host()
        assert sum(cp._mat for _c, cp in lazies) == 1
        return _gather_owned(coll, rank)   # full read via sync_to_host

    results, _ = spmd(nb_ranks, rank_fn, timeout=180)
    L = np.zeros((n, n))
    for owned in results:
        for (m, k), t in owned.items():
            L[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = t
    np.testing.assert_allclose(np.tril(L), np.linalg.cholesky(M),
                               rtol=0, atol=1e-8 * n)


# --------------------------------------------------------------------- #
# [type_remote] wire conversion: applies per instance on CROSS-RANK     #
# edges only (consumer-side masked cast in the kernel; raw tiles ride   #
# the exchange), ignored on local edges — parsec_reshape.c +            #
# remote_dep_mpi.c:766 semantics, previously rejected by dist-wave      #
# --------------------------------------------------------------------- #
WIRE_JDF = """
descA [ type="collection" ]

Prod(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A ConsR( 0 )
     -> A ConsL( 0 )
     -> descA( 0, 0 )
BODY
{
    A = A + 1.0
}
END

ConsR(k)
k = 0 .. 0
: descA( 1, 0 )
READ A <- A Prod( 0 )      [type_remote=lower]
RW   B <- descA( 1, 0 )
       -> descA( 1, 0 )
BODY
{
    B = A
}
END

ConsL(k)
k = 0 .. 0
: descA( 2, 0 )
READ A <- A Prod( 0 )      [type_remote=lower]
RW   B <- descA( 2, 0 )
       -> descA( 2, 0 )
BODY
{
    B = A
}
END
"""


def test_dist_wave_type_remote_wire_conversion(nb_ranks=2):
    """ConsR lives on rank 1 (remote edge: sees tril of Prod's output);
    ConsL lives on rank 0 with Prod (local edge: [type_remote] must be
    ignored — full tile). P=2 row-cyclic: rows 0,2 -> rank 0, row 1 ->
    rank 1."""
    nb = 8
    rng = np.random.RandomState(11)
    A0 = rng.rand(3 * nb, nb)

    def rank_fn(rank, fabric):
        ce = fabric.engine(rank)
        coll = TwoDimBlockCyclic(3 * nb, nb, nb, nb, dtype=np.float64,
                                 P=nb_ranks, Q=1, nodes=nb_ranks,
                                 rank=rank)
        coll.name = "descA"
        coll.from_numpy(A0.copy())
        tp = ptg.compile_jdf(WIRE_JDF, name="wirejdf").new(
            descA=coll, rank=rank, nb_ranks=nb_ranks)
        w = ptg.wave(tp, comm=ce)
        assert w._wconv, "no wire conversion was planned"
        w.run()
        return _gather_owned(coll, rank)

    results, _ = spmd(nb_ranks, rank_fn, timeout=120)
    got = {}
    for r in results:
        got.update(r)
    prod = A0[:nb] + 1.0
    np.testing.assert_allclose(got[(1, 0)], np.tril(prod), rtol=1e-6)
    np.testing.assert_allclose(got[(2, 0)], prod, rtol=1e-6)
    np.testing.assert_allclose(got[(0, 0)], prod, rtol=1e-6)


def test_dist_wave_hybrid_process_mesh_sharding(nb_ranks=2):
    """HYBRID layout (SURVEY §5.8): ranks partition the DAG by the
    data distribution while each rank's sliced pools shard over its
    OWN sub-mesh — wave kernels run GSPMD across the rank's devices,
    the static exchange moves tiles between ranks (host-byte hop:
    gathered tiles from sharded pools are multi-device)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n, nb = 256, 64
    M = make_spd(n, dtype=np.float64)
    cpus = jax.devices("cpu")
    if len(cpus) < 2 * 4:
        pytest.skip("needs 8 virtual cpu devices")

    def rank_fn(rank, fabric):
        ce = fabric.engine(rank)
        coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                 P=nb_ranks, Q=1, nodes=nb_ranks,
                                 rank=rank)
        coll.name = "descA"
        coll.from_numpy(M.copy())
        tp = dpotrf_taskpool(coll, rank=rank, nb_ranks=nb_ranks)
        w = ptg.wave(tp, comm=ce)
        mesh = Mesh(np.array(cpus[rank * 4:(rank + 1) * 4])
                    .reshape(2, 2), ("tp", "sp"))
        sh = NamedSharding(mesh, P(None, "tp", "sp"))
        pools = w.build_pools(sharding=sh)
        assert any(getattr(p, "ndim", 0) == 3 and len(p.devices()) == 4
                   for p in pools), "no pool was sharded over the sub-mesh"
        pools = w.execute(pools)
        w.scatter_pools(pools)
        return _gather_owned(coll, rank)

    results, _ = spmd(nb_ranks, rank_fn, timeout=240)
    L = np.zeros((n, n))
    for owned in results:
        for (m, k), t in owned.items():
            L[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = t
    np.testing.assert_allclose(np.tril(L), np.linalg.cholesky(M),
                               rtol=0, atol=1e-8 * n)


def test_collective_lane_issuer_failure_wakes_peers():
    """In-process lane rendezvous: when the issuing rank's collective
    call raises, waiting peers must get a WaveError promptly (not hang
    to the timeout), and the failure entry must not leak refcounts."""
    import threading

    import jax.numpy as jnp

    from parsec_tpu.dsl.ptg.wave_dist import _CollectiveLane

    rdv = ({}, {}, threading.Condition())
    lanes = [_CollectiveLane("inproc", 2, r, rendezvous=rdv, timeout=15)
             for r in range(2)]

    class Boom(RuntimeError):
        pass

    def exploding_sum(_garr):
        raise Boom("collective died")

    results = {}

    def waiter():
        try:
            lanes[0].reduce(("p", 1, 0, 0), jnp.zeros((1, 4, 4)))
            results[0] = "ok"
        except WaveError as e:
            results[0] = f"waveerror: {e}"

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    # let rank 0 deposit and park
    import time
    deadline = time.monotonic() + 10
    slots, res, cv = rdv
    while time.monotonic() < deadline:
        with cv:
            if ("p", 1, 0, 0) in slots and 0 in slots[("p", 1, 0, 0)]:
                break
        time.sleep(0.01)
    lanes[1]._sum = exploding_sum
    with pytest.raises(Boom):
        lanes[1].reduce(("p", 1, 0, 0), jnp.zeros((1, 4, 4)))
    t.join(10)
    assert not t.is_alive(), "peer hung after issuer failure"
    assert results[0].startswith("waveerror"), results
    assert not slots and not res, "rendezvous state leaked"


def test_collective_lane_waiter_timeout_withdraws_deposit():
    """A lone depositor whose peers never arrive times out with a
    WaveError and withdraws its deposit so the shared rendezvous holds
    no stale state."""
    import threading

    import jax.numpy as jnp

    from parsec_tpu.dsl.ptg.wave_dist import _CollectiveLane

    rdv = ({}, {}, threading.Condition())
    lane = _CollectiveLane("inproc", 2, 0, rendezvous=rdv, timeout=1.5)
    with pytest.raises(WaveError, match="timed out"):
        lane.reduce(("p", 1, 0, 0), jnp.zeros((1, 4, 4)))
    slots, res, _cv = rdv
    assert not slots and not res, "rendezvous state leaked after timeout"
