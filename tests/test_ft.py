"""Fault-tolerance subsystem (parsec_tpu/ft/): proactive heartbeat
detection, deterministic fault injection, checkpoint-integrated restart.

All in-process (no real process kills): the injector silences a rank's
engine at a task boundary — the observable footprint of a SIGKILL — and
the survivors must DETECT it via heartbeats, abort with RankFailedError
instead of hanging in termdet, and a restarted run from the last
snapshot must reproduce the failure-free result.
"""
import os
import time

import numpy as np
import pytest

import parsec_tpu
from conftest import spmd
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.comm import LocalFabric, RankFailedError, RemoteDepEngine
from parsec_tpu.comm.engine import TAG_HEARTBEAT
from parsec_tpu.dsl import ptg
from parsec_tpu.ft import (FaultInjector, HeartbeatDetector, InjectedKill,
                           InjectedTaskFault, RestartPolicy,
                           run_with_restart)
from parsec_tpu.ft.inject import parse_inject_spec
from parsec_tpu.utils.params import params


@pytest.fixture(autouse=True)
def _clean_params():
    params.reset()
    yield
    params.reset()


def _establish_all(ctx, eng, nb_ranks, rank):
    """Pump until this rank's detector has heartbeat contact with every
    peer, then barrier. On the in-process fabrics only ESTABLISHED
    peers are ever evicted (an unanswered probe may just be a
    not-yet-pumping startup), so kill tests must establish contact
    BEFORE the workload — exactly what a long-running job has."""
    det = ctx._ft_detector
    if det is None:
        return
    deadline = time.monotonic() + 15.0
    while any(not det.is_established(p)
              for p in range(nb_ranks) if p != rank):
        assert time.monotonic() < deadline, "heartbeat never established"
        eng.ce.progress()
        time.sleep(0.002)
    eng.ce.sync()


def _pump(engines, secs, until=None):
    deadline = time.monotonic() + secs
    while time.monotonic() < deadline:
        for e in engines:
            e.progress()
        if until is not None and until():
            return True
        time.sleep(0.002)
    return until() if until is not None else True


# --------------------------------------------------------------------- #
# detector                                                              #
# --------------------------------------------------------------------- #
def test_detection_latency_within_timeout():
    """A silenced (kill-injected) peer is declared dead within the
    configured heartbeat timeout — the core detection-latency bound."""
    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    det = HeartbeatDetector(e0, interval=0.02, timeout=0.3).start()
    try:
        assert _pump([e0, e1], 5.0, until=lambda: det.is_established(1))
        assert det.rtt_s(1) is not None and det.rtt_s(1) < 1.0
        assert det.alive_count() == 1
        e1.ft_silence()                      # goes dark, sockets "open"
        t0 = time.monotonic()
        assert _pump([e0], 5.0, until=lambda: 1 in e0.dead_peers)
        latency = time.monotonic() - t0
        # timeout + one probe interval + scheduling slack
        assert latency < 0.3 + 0.02 + 0.6, f"detected in {latency:.3f}s"
        assert det.alive_count() == 0
        assert det.evictions == 1
    finally:
        det.stop()


def test_kill_before_first_contact_still_detected_tcp():
    """On TCP a rank that dies right after startup — before the first
    heartbeat exchange — must still be evicted: a successful probe
    implies the peer's receiver thread was alive (it processed our
    HELLO), so probed-but-silent is genuinely dead, baselined at the
    start of probing."""
    import concurrent.futures as cf

    from parsec_tpu.comm.tcp import TCPCommEngine, free_ports

    eps = [("127.0.0.1", p) for p in free_ports(2)]
    with cf.ThreadPoolExecutor(2) as ex:
        e0, e1 = list(ex.map(lambda r: TCPCommEngine(r, eps), range(2)))
    det = HeartbeatDetector(e0, interval=0.02, timeout=0.3)
    try:
        # dark BEFORE any probe could be answered (HELLO already
        # exchanged at connection setup — the support gate is satisfied)
        deadline = time.monotonic() + 5.0
        while not e0._peers.get(1) or not e0._peers[1].hb_ok:
            assert time.monotonic() < deadline, "HELLO never processed"
            time.sleep(0.005)
        e1.ft_silence()
        det.start()
        t0 = time.monotonic()
        assert _pump([], 5.0, until=lambda: 1 in e0.dead_peers)
        assert time.monotonic() - t0 < 0.3 + 0.02 + 0.6
        assert not det.is_established(1)
    finally:
        det.stop()
        e0.fini()
        e1.fini()


def test_unresponsive_local_peer_not_evicted_before_contact():
    """On the in-process fabrics an unanswered probe may just mean the
    peer is not pumping progress yet (startup, a cold jit compile) —
    only ESTABLISHED peers are ever judged there, so a slow-starting
    healthy rank is never false-evicted."""
    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    det = HeartbeatDetector(e0, interval=0.02, timeout=0.1).start()
    try:
        _pump([e0], 0.5)        # e1 never progresses: "still starting"
        assert 1 not in e0.dead_peers
        # the moment it answers once, normal silence judgment applies
        assert _pump([e0, e1], 5.0, until=lambda: det.is_established(1))
        e1.ft_silence()
        assert _pump([e0], 5.0, until=lambda: 1 in e0.dead_peers)
    finally:
        det.stop()


def test_mixed_version_peer_never_declared_dead():
    """A peer that cannot speak the heartbeat protocol (mixed version:
    its TAG_HEARTBEAT handler never existed) is never ESTABLISHED and
    therefore never evicted, no matter how long it stays silent."""
    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    e1.tag_unregister(TAG_HEARTBEAT)       # simulate a pre-ft build
    det = HeartbeatDetector(e0, interval=0.02, timeout=0.1).start()
    try:
        _pump([e0, e1], 0.5)               # >> timeout, pings unanswered
        assert not det.is_established(1)
        assert 1 not in e0.dead_peers
    finally:
        det.stop()


def test_cleanly_finished_peer_never_declared_dead():
    """Finishing early is not failing: a rank that fini'd cleanly stops
    heartbeating but must not be evicted (local-fabric finish mark; the
    TCP GOODBYE plays the same role there)."""
    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    det = HeartbeatDetector(e0, interval=0.02, timeout=0.15).start()
    try:
        assert _pump([e0, e1], 5.0, until=lambda: det.is_established(1))
        e1.fini()                           # clean shutdown, not a crash
        _pump([e0], 0.5)                    # >> timeout
        assert 1 not in e0.dead_peers
        assert e0.peer_finished(1)
    finally:
        det.stop()


def test_detector_phi_mode_and_bad_config():
    fab = LocalFabric(2)
    e0 = fab.engine(0)
    with pytest.raises(ValueError, match="must exceed"):
        HeartbeatDetector(e0, interval=0.1, timeout=0.1)
    with pytest.raises(ValueError, match="ft_detector_mode"):
        HeartbeatDetector(e0, interval=0.1, timeout=1.0, mode="psychic")
    det = HeartbeatDetector(e0, interval=0.02, timeout=0.2, mode="phi")
    # phi: with no gap history the fixed timeout is the floor
    st = det._peers[1]
    assert det._deadline_for(st) == 0.2
    st.gap_s = 0.05
    assert det._deadline_for(st) == pytest.approx(0.4)  # 8x gap EWMA


def test_uniform_on_peer_failure_across_transports():
    """Satellite: local/mesh engines carry the same report_peer_failure
    / on_peer_failure / dead_peers surface the TCP engine had, and
    remote_dep wires the context abort unconditionally."""
    fab = LocalFabric(2)
    eng = RemoteDepEngine(fab.engine(0))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
    try:
        assert eng.ce.on_peer_failure is not None   # no hasattr guard
        eng.ce.report_peer_failure(1, "unit test")
        assert 1 in eng.ce.dead_peers
        with pytest.raises(RankFailedError):
            eng.ce.send_am(1, 100, {"x": 1})
        # idempotent: a second report records no second error
        n_errs = len(ctx._task_errors)
        eng.ce.report_peer_failure(1, "again")
        assert len(ctx._task_errors) == n_errs
        with pytest.raises(RuntimeError) as ei:
            ctx.wait()
        assert isinstance(ei.value.__cause__, RankFailedError)
        ctx.clear_task_errors()             # let fini see a clean context
    finally:
        ctx.fini()
    # the mesh engine (device-plane transport) carries the same surface
    from parsec_tpu.comm import MeshFabric
    mesh_eng = MeshFabric(2).engine(0)
    mesh_eng.report_peer_failure(1, "unit test")
    assert 1 in mesh_eng.dead_peers
    with pytest.raises(RankFailedError):
        mesh_eng.send_am(1, 100, {"x": 1})


# --------------------------------------------------------------------- #
# injector                                                              #
# --------------------------------------------------------------------- #
def test_inject_spec_parser():
    ds = parse_inject_spec(
        "kill:rank=1:after=3, drop:pct=2.5:seed=7:peer=2; failsend:nth=4")
    assert [d["op"] for d in ds] == ["kill", "drop", "failsend"]
    assert ds[0]["rank"] == 1 and ds[0]["after"] == 3
    assert ds[1]["pct"] == 2.5 and ds[1]["peer"] == 2
    with pytest.raises(ValueError, match="unknown op"):
        parse_inject_spec("explode:rank=1")
    with pytest.raises(ValueError, match="unknown key"):
        parse_inject_spec("kill:when=later")
    # a wire directive that could never fire is a config error, not a
    # silent no-op (the chaos run would validate nothing)
    with pytest.raises(ValueError, match="never fire"):
        parse_inject_spec("drop:rank=1")


def test_inject_wire_ops_deterministic():
    inj_a = FaultInjector.from_spec("drop:pct=30:seed=42", rank=0)
    inj_b = FaultInjector.from_spec("drop:pct=30:seed=42", rank=0)
    va = [inj_a.on_send(1, 100) for _ in range(200)]
    vb = [inj_b.on_send(1, 100) for _ in range(200)]
    assert va == vb                          # seeded: reproducible
    assert 20 < va.count("drop") < 100       # ~30% of 200
    # rank-salted: another rank draws a different (but fixed) stream
    inj_c = FaultInjector.from_spec("drop:pct=30:seed=42", rank=1)
    vc = [inj_c.on_send(1, 100) for _ in range(200)]
    assert vc != va
    # heartbeat traffic is exempt unless hb=1
    inj_d = FaultInjector.from_spec("drop:pct=100:seed=1", rank=0)
    assert inj_d.on_send(1, TAG_HEARTBEAT) == "ok"
    assert inj_d.on_send(1, 100) == "drop"
    # the Nth send fails exactly once
    inj_e = FaultInjector.from_spec("failsend:nth=3", rank=0)
    assert inj_e.on_send(1, 100) == "ok"
    assert inj_e.on_send(1, 100) == "ok"
    with pytest.raises(RankFailedError):
        inj_e.on_send(1, 100)
    assert inj_e.on_send(1, 100) == "ok"


def test_injected_drop_on_local_fabric():
    """drop:pct=100 makes the local fabric a black hole toward peers
    (messages vanish at the wire layer, self-sends untouched)."""
    params.set_cmdline("ft_inject", "drop:pct=100:seed=1")
    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    got = []
    e1.tag_register(100, lambda s, p: got.append(p))
    e0.send_am(1, 100, {"i": 1})
    e1.progress()
    assert got == []
    assert e0._ft.stats["dropped"] == 1


# --------------------------------------------------------------------- #
# kill a rank: detection + survivor abort (the acceptance scenario)     #
# --------------------------------------------------------------------- #
CHAIN_JDF = """
descA [ type="collection" ]
NB [ type="int" ]

Step(k)

k = 0 .. NB

: descA( k, 0 )

RW A <- (k == 0) ? descA( k, 0 ) : A Step( k-1 )
     -> (k == NB) ? descA( k, 0 ) : A Step( k+1 )

BODY
{
    A[0, 0] += 1.0
}
END
"""


def test_killed_rank_detected_survivors_raise():
    """kill:rank=1:after=2 over a 3-rank PTG chain: rank 1 goes dark at
    its 2nd task boundary; the survivors' detectors evict it within the
    heartbeat timeout and their waits raise RankFailedError instead of
    hanging in termdet; the victim aborts with InjectedKill."""
    nb_ranks, NB, tile = 3, 12, 4
    params.set_cmdline("ft_heartbeat_interval", "0.05")
    params.set_cmdline("ft_heartbeat_timeout", "1.0")
    params.set_cmdline("ft_inject", "kill:rank=1:after=2")

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            assert ctx._ft_detector is not None
            coll = TwoDimBlockCyclic((NB + 1) * tile, tile, tile, tile,
                                     P=nb_ranks, Q=1, nodes=nb_ranks,
                                     rank=rank)
            coll.name = "descA"
            tp = ptg.compile_jdf(CHAIN_JDF, name="chain").new(
                descA=coll, NB=NB, rank=rank, nb_ranks=nb_ranks)
            _establish_all(ctx, eng, nb_ranks, rank)
            t0 = time.monotonic()
            try:
                ctx.add_taskpool(tp)
                ctx.wait()
                return ("completed", time.monotonic() - t0)
            except RuntimeError as e:
                return (type(e.__cause__).__name__, time.monotonic() - t0)
        finally:
            ctx.clear_task_errors()
            ctx.fini()

    results, _ = spmd(nb_ranks, rank_fn, timeout=60)
    outcomes = {r: results[r][0] for r in range(nb_ranks)}
    assert outcomes[1] == "InjectedKill"
    for r in (0, 2):
        assert outcomes[r] == "RankFailedError", outcomes
        # detection bound: timeout + probe + generous sched slack —
        # far below the spmd hang timeout this replaces
        assert results[r][1] < 10.0, results[r]


def test_taskfail_injection_and_restart_driver(tmp_path):
    """A transient injected task fault aborts the stage; the restart
    driver rolls back to the last snapshot, retries with backoff, and
    the final result matches the failure-free run exactly."""
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    n, nb = 96, 32
    M = make_spd(n)

    # failure-free reference
    ctx = parsec_tpu.init(nb_cores=2, enable_tpu=False)
    try:
        A_ref = TwoDimBlockCyclic(n, n, nb, nb,
                                  dtype=np.float32).from_numpy(M)
        ctx.add_taskpool(dpotrf_taskpool(A_ref))
        ctx.wait()
        ref = A_ref.to_numpy()
    finally:
        ctx.fini()

    params.set_cmdline("ft_inject", "taskfail:nth=4")
    ctx = parsec_tpu.init(nb_cores=2, enable_tpu=False)
    try:
        assert ctx.ft_injector is not None and ctx._ft_pins is not None
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        stats = run_with_restart(
            ctx, [lambda: dpotrf_taskpool(A)], [A],
            str(tmp_path / "ck"),
            policy=RestartPolicy("restart", retries=2, backoff=0.01))
        assert stats["retries"] == 1
        assert stats["snapshots"] == 2      # initial + final
        assert ctx.ft_injector.stats["task_faults"] == 1
        np.testing.assert_array_equal(A.to_numpy(), ref)
    finally:
        ctx.fini()


def test_restart_policy_abort_and_exhaustion(tmp_path):
    """abort mode never retries; restart mode re-raises once retries
    are exhausted, leaving the context clean for fini."""
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    n, nb = 64, 32
    M = make_spd(n)
    with pytest.raises(ValueError, match="unknown restart mode"):
        RestartPolicy("panic")
    pol = RestartPolicy.parse("restart:retries=3:backoff=0.5:every=2")
    assert (pol.mode, pol.retries, pol.backoff, pol.every) == \
        ("restart", 3, 0.5, 2)

    params.set_cmdline("ft_inject", "taskfail:nth=1")
    ctx = parsec_tpu.init(nb_cores=2, enable_tpu=False)
    try:
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        with pytest.raises(RuntimeError) as ei:
            run_with_restart(ctx, [lambda: dpotrf_taskpool(A)], [A],
                             str(tmp_path / "ab"),
                             policy=RestartPolicy("abort"))
        assert isinstance(ei.value.__cause__, InjectedTaskFault)
        assert not ctx._task_errors          # guaranteed-clean abort
        # the same context is reusable after the clean abort
        A2 = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        ctx.add_taskpool(dpotrf_taskpool(A2))
        ctx.wait()
    finally:
        ctx.fini()


def test_retry_bound_holds_across_rollback_replays(tmp_path):
    """With every>1 a rollback replays earlier (succeeding) stages;
    their completion must NOT reset the failing stage's attempt count,
    or a persistent fault retries forever (attempts are per stage)."""
    from parsec_tpu.runtime.taskpool import Taskpool

    ctx = parsec_tpu.init(nb_cores=1, enable_tpu=False)
    try:
        calls = {"ok": 0, "bad": 0}

        def ok_stage():
            calls["ok"] += 1
            return Taskpool("ok-stage")     # zero tasks: completes

        def bad_stage():
            calls["bad"] += 1
            raise RuntimeError("persistent fault")

        with pytest.raises(RuntimeError, match="persistent fault"):
            run_with_restart(
                ctx, [ok_stage, bad_stage], [], str(tmp_path / "rb"),
                policy=RestartPolicy("restart", retries=1,
                                     backoff=0.01, every=2))
        # initial run + exactly ONE bounded retry, then abort
        assert calls["bad"] == 2
        assert calls["ok"] == 2              # replayed once by rollback
    finally:
        ctx.fini()


def test_injected_kill_is_hard_never_retried(tmp_path):
    """A kill is a loss of THIS rank: even with retries budgeted, the
    restart driver must abort immediately — retrying a stage on a
    permanently silenced engine would hang termdet forever (the
    failure mode ft/ exists to eliminate)."""
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    n, nb = 64, 32
    params.set_cmdline("ft_inject", "kill:rank=0:after=1")
    ctx = parsec_tpu.init(nb_cores=1, enable_tpu=False)
    try:
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(
            make_spd(n))
        t0 = time.monotonic()
        with pytest.raises(RuntimeError) as ei:
            run_with_restart(
                ctx, [lambda: dpotrf_taskpool(A)], [A],
                str(tmp_path / "kill"),
                policy=RestartPolicy("restart", retries=5, backoff=0.5))
        assert isinstance(ei.value.__cause__, InjectedKill)
        # no retry, no backoff burn: it aborted on the first failure
        assert time.monotonic() - t0 < 0.5 * 5
    finally:
        ctx.fini()


def test_dpotrf_kill_checkpoint_restart_identical(tmp_path):
    """The acceptance scenario end to end: distributed dpotrf, rank 1
    chaos-killed mid-factorization; every rank aborts (no termdet
    hang); a fresh incarnation restores the pre-stage snapshot and
    re-runs — numerically identical to a failure-free run."""
    from parsec_tpu.ops import make_spd

    nb_ranks, n, nb = 2, 128, 32
    M = make_spd(n)
    prefix = str(tmp_path / "ck")

    def dist(rank):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=nb_ranks, Q=1,
                              nodes=nb_ranks, rank=rank, dtype=np.float32)
        for (i, j) in d.local_tiles():
            np.copyto(d.tile(i, j),
                      M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
        return d

    def run_rank(rank, fabric, inject):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            A = dist(rank)
            A.name = "descA"
            _establish_all(ctx, eng, nb_ranks, rank)
            from parsec_tpu.ops import dpotrf_taskpool
            stages = [lambda: dpotrf_taskpool(A, rank=rank,
                                              nb_ranks=nb_ranks)]
            try:
                stats = run_with_restart(
                    ctx, stages, [A], prefix,
                    policy=RestartPolicy("restart", retries=0),
                    resume_from=0 if not inject else None)
                local = {t: np.array(A.tile(*t)) for t in A.local_tiles()}
                return ("ok", local, stats)
            except RuntimeError as e:
                return (type(e.__cause__).__name__, None, None)
        finally:
            ctx.clear_task_errors()
            ctx.fini()

    # incarnation 1: snapshot at stage 0, then rank 1 dies mid-DAG
    params.set_cmdline("ft_heartbeat_interval", "0.05")
    params.set_cmdline("ft_heartbeat_timeout", "1.0")
    params.set_cmdline("ft_inject", "kill:rank=1:after=2")
    results, _ = spmd(nb_ranks,
                      lambda r, f: run_rank(r, f, inject=True), timeout=60)
    assert results[1][0] == "InjectedKill"
    assert results[0][0] == "RankFailedError"   # no termdet hang

    # incarnation 2: fresh fabric, restore stage-0 snapshot, run clean
    params.set_cmdline("ft_inject", "")
    results, _ = spmd(nb_ranks,
                      lambda r, f: run_rank(r, f, inject=False), timeout=60)
    merged = {}
    for st, local, stats in results:
        assert st == "ok"
        merged.update(local)

    # failure-free reference on the same grid (no ft knobs at all)
    params.reset()
    ref_results, _ = spmd(nb_ranks,
                          lambda r, f: run_rank(r, f, inject=False),
                          timeout=60)
    ref = {}
    for st, local, _ in ref_results:
        assert st == "ok"
        ref.update(local)
    assert set(merged) == set(ref)
    for t in ref:
        np.testing.assert_array_equal(merged[t], ref[t])


# --------------------------------------------------------------------- #
# termdet correction: taskpool-level waiters unblock on eviction        #
# --------------------------------------------------------------------- #
def test_taskpool_abort_unblocks_wait():
    fab = LocalFabric(2)
    eng = RemoteDepEngine(fab.engine(0))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
    try:
        from parsec_tpu.runtime.taskpool import Taskpool
        from parsec_tpu.runtime.termdet import termdet_new
        tp = Taskpool("ft-abort")
        tp.tdm = termdet_new("user_trigger", tp)  # held open until trigger
        ctx.add_taskpool(tp)
        assert not tp.wait_completed(timeout=0.05)
        eng.ce.report_peer_failure(1, "unit")
        assert tp.wait_completed(timeout=5.0)
        assert tp.aborted
        # the late counter settle is a no-op, not a second completion
        tp.tdm.user_trigger()
        assert tp.aborted
        ctx.clear_task_errors()
    finally:
        ctx.fini()


def test_ft_gauges_registered():
    """Satellite: PEER_ALIVE / HB_RTT::R<peer> appear in the context's
    SDE registry when a detector is installed."""
    from parsec_tpu.obs import FT_HB_RTT_PREFIX, FT_PEER_ALIVE

    params.set_cmdline("ft_heartbeat_interval", "0.05")
    params.set_cmdline("ft_heartbeat_timeout", "30")   # no evictions here
    fab = LocalFabric(2)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            snap = ctx.sde.snapshot()
            assert FT_PEER_ALIVE in snap
            peer = 1 - rank
            assert f"{FT_HB_RTT_PREFIX}::R{peer}" in snap
            deadline = time.monotonic() + 5.0
            alive = 0
            while time.monotonic() < deadline:
                eng.ce.progress()          # an idle context answers from
                alive = ctx.sde.snapshot()[FT_PEER_ALIVE]  # its workers
                if alive == 1:
                    break
                time.sleep(0.01)
            # hold the engine alive until BOTH ranks measured: fini
            # marks this rank finished, which drops it from the peer's
            # alive gauge
            eng.ce.sync()
            return alive
        finally:
            ctx.fini()

    counts, _ = spmd(2, rank_fn, fabric=fab)
    assert counts == [1, 1]


# --------------------------------------------------------------------- #
# elastic grid recovery (ISSUE 9): shrink, grow, agreement, fallback    #
# --------------------------------------------------------------------- #
SCALE_JDF = """
descA [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]

Scale(m, n)

m = 0 .. MT
n = 0 .. NT

: descA( m, n )

RW A <- descA( m, n )
     -> descA( m, n )

BODY
{
    A *= 2.0
    A += 1.0
}
END
"""


def test_elastic_policy_validation():
    from parsec_tpu.ft import ElasticPolicy

    with pytest.raises(ValueError, match="ft_elastic"):
        ElasticPolicy(lambda g: ([], []), mode="sideways")
    pol = ElasticPolicy(lambda g: ([], []), mode="both", grow_min=2)
    assert pol.allows_shrink and pol.allows_grow and pol.grow_min == 2
    # knob unset -> mode "" -> strict (run_with_restart nulls it)
    assert ElasticPolicy(lambda g: ([], [])).mode == ""


def test_plan_grid_deterministic_most_square():
    from parsec_tpu.ft import plan_grid

    g4 = plan_grid((0, 1, 2, 3), 4, 0)
    assert (g4.P, g4.Q) == (2, 2)
    g3 = plan_grid((2, 0, 5), 6, 5)
    assert (g3.P, g3.Q) == (3, 1)
    assert g3.members == (0, 2, 5)      # sorted, world ranks preserved
    # every member derives the identical layout — the agreement shortcut
    assert plan_grid((2, 5, 0), 6, 0).members == g3.members


def test_elastic_agreement_reconciles_divergent_votes():
    """Coordinator-level: two survivors enter a shrink round ONE
    SNAPSHOT APART with different taskpool wire-id counters; the commit
    must carry the min stage (both provably wrote it) and the max
    tp_next (so the laggard skips the ids it never assigned)."""
    from parsec_tpu.ft.elastic import ElasticCoordinator

    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    c0, c1 = ElasticCoordinator(e0), ElasticCoordinator(e1)
    out = [None, None]

    def voter(co, eng, stage, tp_next, slot):
        out[slot] = co.agree("shrink", (0, 1), stage, deadline_s=10.0,
                             tp_next=tp_next)

    import threading
    ts = [threading.Thread(target=voter, args=(c0, e0, 3, 7, 0)),
          threading.Thread(target=voter, args=(c1, e1, 2, 9, 1))]
    for t in ts:
        t.start()
    deadline = time.monotonic() + 10.0
    while any(t.is_alive() for t in ts) and time.monotonic() < deadline:
        e0.progress()
        e1.progress()
        time.sleep(0.001)
    for t in ts:
        t.join(1.0)
        assert not t.is_alive(), "agreement did not converge"
    for got in out:
        assert got["members"] == (0, 1)
        assert got["stage"] == 2        # min over the divergent votes
        assert got["tp_base"] == 9      # max over the wire-id counters
    e0.fini()
    e1.fini()


def test_restart_falls_back_past_torn_snapshot(tmp_path):
    """ISSUE 9 satellite: a snapshot torn by a rank dying mid-write
    must not poison the next recovery — resume_from walks back to the
    previous COMPLETE snapshot and replays from there."""
    from parsec_tpu.dsl import ptg
    from parsec_tpu.utils import checkpoint as ckpt

    n, nb = 64, 32
    M0 = np.arange(n * n, dtype=np.float32).reshape(n, n) / (n * n)
    factory = ptg.compile_jdf(SCALE_JDF, name="scale_fb")
    prefix = str(tmp_path / "fb")

    def run(resume_from=None):
        ctx = parsec_tpu.init(nb_cores=1, enable_tpu=False)
        try:
            A = TwoDimBlockCyclic(n, n, nb, nb,
                                  dtype=np.float32).from_numpy(M0)
            A.name = "descA"
            mk = lambda: factory.new(descA=A, MT=A.mt - 1, NT=A.nt - 1)
            return run_with_restart(
                ctx, [mk, mk, mk], [A], prefix,
                policy=RestartPolicy("restart", retries=0, every=1),
                resume_from=resume_from), A.to_numpy()
        finally:
            ctx.fini()

    stats, final = run()
    assert stats["last_snapshot"] == 3
    # tear the stage-2 snapshot the way a dying writer would
    path = ckpt.checkpoint_path(f"{prefix}.stage2.c0", 0)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[: len(raw) // 3])
    stats2, final2 = run(resume_from=2)
    np.testing.assert_array_equal(final2, final)   # replayed 1->3
    assert stats2["last_snapshot"] == 3


def test_elastic_shrink_3rank_dpotrf_recovers(tmp_path):
    """The ISSUE 9 acceptance scenario: 3-rank checkpointed dpotrf,
    rank 2 chaos-killed mid-factorization, ft_elastic=shrink. The
    survivors agree on the 2-rank grid, reshard the last snapshot over
    the DTD data plane, replay, and produce a verifiable factor — no
    operator in the loop. Exactly one resize, reshard bytes > 0."""
    from parsec_tpu.ft import ElasticPolicy
    from parsec_tpu.ft.elastic import GridSpec
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    nb_ranks, n, nb = 3, 256, 32
    M = make_spd(n)
    prefix = str(tmp_path / "es")

    def run_rank(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            def rebuild(grid: GridSpec):
                A = grid.collection(n, n, nb, nb, dtype=np.float32)
                A.name = "descA"
                for (i, j) in A.local_tiles():
                    np.copyto(A.tile(i, j),
                              M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
                return [lambda: dpotrf_taskpool(
                    A, rank=rank, nb_ranks=nb_ranks)], [A]

            _establish_all(ctx, eng, nb_ranks, rank)
            pol = ElasticPolicy(rebuild, timeout=30.0)
            try:
                stats = run_with_restart(
                    ctx, None, None, prefix,
                    policy=RestartPolicy("restart", retries=1),
                    elastic=pol)
            except RuntimeError as e:
                return (type(e.__cause__ or e).__name__, None, None, None)
            grid = stats["grid"]
            from parsec_tpu.ft.elastic import plan_grid
            A = rebuild(plan_grid(grid, nb_ranks, rank))[1][0]
            from parsec_tpu.utils import checkpoint as ckpt
            ckpt.restore_collection(A, f"{prefix}.stage1.c0",
                                    reshard=True, context=ctx)
            local = {t: np.array(A.tile(*t)) for t in A.local_tiles()}
            return ("ok", local, stats, dict(eng.ce.elastic_stats))
        finally:
            ctx.clear_task_errors()
            ctx.fini()

    params.set_cmdline("ft_heartbeat_interval", "0.05")
    params.set_cmdline("ft_heartbeat_timeout", "4.0")
    params.set_cmdline("ft_inject", "kill:rank=2:after=4")
    params.set_cmdline("ft_elastic", "shrink")
    results, _ = spmd(nb_ranks, run_rank, timeout=300)

    assert results[2][0] in ("InjectedKill", "RankFailedError")
    L = np.zeros_like(M)
    for r in (0, 1):
        st, local, stats, es = results[r]
        assert st == "ok", results[r]
        assert stats["grid"] == (0, 1)
        assert stats["resizes"] == 1
        assert es["elastic_resizes"] == 1
        assert es["reshard_bytes"] > 0
        for (i, j), tile in local.items():
            L[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb] = tile
    L = np.tril(L)
    resid = np.abs(L @ L.T - M).max() / (np.abs(M).max() * n)
    assert resid < 1e-5, f"shrunk-grid factor residual {resid:.2e}"


def test_elastic_grow_folds_in_late_joiner(tmp_path):
    """Grow: two incumbents run staged scaling while rank 2 announces
    late; at a stage boundary the grid grows to 3, the joiner reshards
    the fresh snapshot, and the final state is bit-identical to the
    sequential reference."""
    from parsec_tpu.dsl import ptg
    from parsec_tpu.ft import ElasticPolicy
    from parsec_tpu.ft.elastic import GridSpec
    from parsec_tpu.utils import checkpoint as ckpt

    world, n, nb, nstages = 3, 96, 16, 6
    M = np.arange(n * n, dtype=np.float32).reshape(n, n) / (n * n)
    factory = ptg.compile_jdf(SCALE_JDF, name="scale_grow")
    prefix = str(tmp_path / "eg")

    def run_rank(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            def rebuild(grid: GridSpec):
                A = grid.collection(n, n, nb, nb, dtype=np.float32)
                A.name = "descA"
                for (i, j) in A.local_tiles():
                    np.copyto(A.tile(i, j),
                              M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
                mk = lambda: factory.new(descA=A, MT=A.mt - 1,
                                         NT=A.nt - 1, rank=rank,
                                         nb_ranks=world)
                return [mk] * nstages, [A]

            _establish_all(ctx, eng, world, rank)
            pol = ElasticPolicy(rebuild, mode="grow", members=(0, 1),
                                timeout=30.0, join=(rank == 2))
            stats = run_with_restart(
                ctx, None, None, prefix,
                policy=RestartPolicy("restart", retries=0, every=1),
                elastic=pol)
            return ("ok", stats, dict(eng.ce.elastic_stats))
        finally:
            ctx.clear_task_errors()
            ctx.fini()

    params.set_cmdline("ft_heartbeat_interval", "0.05")
    params.set_cmdline("ft_heartbeat_timeout", "15")
    results, _ = spmd(world, run_rank, timeout=300)

    for r in range(world):
        st, stats, es = results[r]
        assert st == "ok", results[r]
        assert stats["grid"] == (0, 1, 2)
        assert stats["resizes"] >= 1
        assert es["elastic_joins"] >= 1
        assert es["reshard_bytes"] > 0
    # the joiner really joined (not a fresh full run)
    assert results[2][1]["snapshots"] < results[0][1]["snapshots"]

    ref = M.copy()
    for _ in range(nstages):
        ref = ref * 2.0 + 1.0
    d = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    d.name = "descA"
    ckpt.restore_collection(d, f"{prefix}.stage{nstages}.c0",
                            reshard=True)
    np.testing.assert_allclose(d.to_numpy(), ref, rtol=1e-6)


def test_elastic_gauges_registered():
    """FT::ELASTIC_RESIZES / ELASTIC_JOINS / RESHARD_BYTES / RESHARD_US
    ride the engine gauge registration like every other FT gauge."""
    from parsec_tpu.obs import (FT_ELASTIC_JOINS, FT_ELASTIC_RESIZES,
                                FT_RESHARD_BYTES, FT_RESHARD_US)

    fab = LocalFabric(2)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            snap = ctx.sde.snapshot()
            for name in (FT_ELASTIC_RESIZES, FT_ELASTIC_JOINS,
                         FT_RESHARD_BYTES, FT_RESHARD_US):
                assert name in snap and snap[name] == 0
            # the gauge is LIVE against the engine counter, not a copy
            eng.ce.elastic_stats["reshard_bytes"] += 4096
            assert ctx.sde.snapshot()[FT_RESHARD_BYTES] == 4096
            return True
        finally:
            ctx.fini()

    oks, _ = spmd(2, rank_fn, fabric=fab)
    assert oks == [True, True]
