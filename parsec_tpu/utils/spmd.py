"""In-process SPMD thread harness.

One thread per rank over a shared fabric — the reference's CI strategy
(distributed behavior validated by oversubscribed mpiexec on one node,
SURVEY.md §4), except the "node" is one process. This is the single
canonical copy: the test conftest, the driver's multichip dryrun, and
the north-star tool all delegate here so fixes to the join/propagation
logic reach every harness.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Tuple


def spmd_threads(nb_ranks: int, fn: Callable[[int, Any], Any],
                 timeout: float = 120.0,
                 fabric: Optional[Any] = None) -> Tuple[List[Any], Any]:
    """Run ``fn(rank, fabric)`` on one daemon thread per rank.

    ``fabric`` defaults to a fresh ``LocalFabric``; pass e.g. a
    MeshFabric to change the transport. Joins every thread with
    ``timeout`` (a still-alive thread is a hang — asserted), then
    re-raises the first rank's error. Returns (results, fabric).
    """
    from ..comm import LocalFabric

    if fabric is None:
        fabric = LocalFabric(nb_ranks)
    assert fabric.nb_ranks == nb_ranks
    results: List[Any] = [None] * nb_ranks
    errors: List[Optional[BaseException]] = [None] * nb_ranks

    def runner(r: int) -> None:
        try:
            results[r] = fn(r, fabric)
        except BaseException as e:  # noqa: BLE001 - propagated below
            errors[r] = e

    threads = [threading.Thread(target=runner, args=(r,), daemon=True)
               for r in range(nb_ranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "rank thread hung"
    for e in errors:
        if e is not None:
            raise e
    return results, fabric
