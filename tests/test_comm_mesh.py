"""Mesh transport tests: the data plane moves payloads device-to-device
across the ranks' mesh devices (ICI on real slices; the 8-virtual-device
CPU mesh here), control AMs stay host-side (SURVEY.md §5.8).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.comm import MeshFabric, RemoteDepEngine
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg

CHAIN_JDF = """
descA [ type="collection" ]
NB [ type="int" ]

T(k)

k = 0 .. NB

: descA( k, 0 )

RW X <- (k == 0) ? descA( 0, 0 ) : X T( k-1 )
     -> (k < NB) ? X T( k+1 )
     -> (k == NB) ? descA( NB, 0 )

BODY
{
    X = np.asarray(X) + 1.0
}
END
"""


def _mesh_fabric(nb_ranks):
    import jax
    return MeshFabric(devices=jax.devices("cpu")[:nb_ranks])


def _run_chain(nb_ranks, mb=48):
    """Chain crossing ranks every hop; payload above the short limit so
    every hop is a GET rendezvous riding the mesh data plane."""
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("runtime_comm_short_limit", "64")

    fabric = _mesh_fabric(nb_ranks)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            nhops = 2 * nb_ranks
            coll = TwoDimBlockCyclic((nhops + 1) * mb, mb, mb, mb,
                                     P=nb_ranks, Q=1, nodes=nb_ranks,
                                     rank=rank, dtype=np.float32)
            coll.name = "descA"
            tp = ptg.compile_jdf(CHAIN_JDF, name="meshchain").new(
                descA=coll, NB=nhops, rank=rank, nb_ranks=nb_ranks)
            ctx.add_taskpool(tp)
            ctx.wait()
            last = nhops
            if coll.rank_of(last, 0) == rank:
                return float(np.asarray(coll.tile(last, 0))[0, 0])
        finally:
            ctx.fini()

    from conftest import spmd
    results, fabric = spmd(nb_ranks, rank_fn, fabric=fabric)
    parsec_tpu.params.reset()
    return results, fabric


@pytest.mark.parametrize("nb_ranks", [2, 4])
def test_mesh_chain_data_plane(nb_ranks):
    results, fabric = _run_chain(nb_ranks)
    vals = [v for v in results if v is not None]
    assert vals == [float(2 * nb_ranks + 1)]
    # the payload hops actually used device-to-device transfers
    assert fabric.d2d_transfers >= 2 * nb_ranks
    assert fabric.d2d_bytes > 0
    assert fabric.msg_count > 0  # control plane still host-side AMs


def test_mesh_engine_get_lands_on_requester_device():
    """A GET-served buffer must be committed to the requester's device."""
    import jax
    fabric = _mesh_fabric(2)
    e0, e1 = fabric.engine(0), fabric.engine(1)
    src = jax.device_put(np.arange(16.0, dtype=np.float32).reshape(4, 4),
                         fabric.devices[0])
    h = e0.mem_register(src)
    got = []
    e1.get(0, h.handle_id, got.append)
    e0.progress()  # serve the GET request
    e1.progress()  # deliver the data
    assert len(got) == 1
    arr = got[0]
    assert set(arr.devices()) == {fabric.devices[1]}
    np.testing.assert_allclose(np.asarray(arr), np.asarray(src))


def test_mesh_put_device_region_rebinds():
    import jax
    fabric = _mesh_fabric(2)
    e0, e1 = fabric.engine(0), fabric.engine(1)
    region = jax.device_put(np.zeros((4, 4), np.float32), fabric.devices[1])
    h = e1.mem_register(region)
    e0.put(1, h.handle_id, np.full((4, 4), 7.0, np.float32))
    e1.progress()
    arr = e1._mem[h.handle_id].array
    assert set(arr.devices()) == {fabric.devices[1]}
    np.testing.assert_allclose(np.asarray(arr), 7.0)


def test_mesh_fabric_needs_enough_devices():
    with pytest.raises(RuntimeError):
        MeshFabric(nb_ranks=10 ** 6)


def test_dtd_chain_over_mesh():
    """The DTD cross-rank (tile, seq) data plane also rides the mesh
    transport: a chain alternating between 2 device-pinned ranks, with
    the payload above the short limit so hops move device-to-device."""
    from conftest import spmd
    from parsec_tpu import dtd
    from parsec_tpu.collections import DictCollection
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, VALUE, unpack_args

    nb_ranks, N = 2, 6
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("runtime_comm_short_limit", "64")
    fabric = _mesh_fabric(nb_ranks)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            coll = DictCollection(nodes=nb_ranks, rank=rank)
            coll.name = "C"
            # 128-element payload: above the 64-byte short limit
            coll.add("x", 0, np.zeros(128) if rank == 0 else None)
            anchors = {}
            for r in range(nb_ranks):
                a = DictCollection(nodes=nb_ranks, rank=rank)
                a.name = f"anchor{r}"
                a.add("a", r, np.zeros(1) if r == rank else None)
                anchors[r] = a
            tp = dtd.taskpool_new("meshchain")
            ctx.add_taskpool(tp)
            tile = tp.tile_of(coll, "x")

            def bump(es, task):
                x, anchor, k = unpack_args(task)
                assert x[0] == k, f"task {k} saw {x[0]}"
                x[0] += 1.0

            for k in range(N):
                at = tp.tile_of(anchors[k % nb_ranks], "a")
                tp.insert_task(bump, (tile, INOUT),
                               (at, INPUT | AFFINITY), (k, VALUE))
            tp.data_flush_all()
            tp.wait()
            ctx.wait()
            if rank == 0:
                return float(coll.data_of("x").get_copy(0).payload[0])
        finally:
            ctx.fini()

    results, fabric = spmd(nb_ranks, rank_fn, fabric=fabric)
    parsec_tpu.params.reset()
    assert results[0] == float(N)
    # the 1KB payload exceeded the 64B short limit: hops rode the GET
    # rendezvous, i.e. the mesh device-to-device data plane
    assert fabric.d2d_transfers > 0
    assert fabric.d2d_bytes > 0
