"""Wire fast-path tests (ISSUE 2): coalesced framing, chunked
pipelining, per-link compression, GET aggregation, and the adaptive
eager/rendezvous cutoff — plus framing robustness against partial
reads, mixed-version peers, and desync.

The loopback two-rank fixture is ``_engines`` (in-process TCP engines
over real sockets); the raw-socket fixture speaks the frame format
by hand to exercise receiver robustness.
"""
import pickle
import socket
import struct
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from parsec_tpu.comm import wire
from parsec_tpu.comm.tcp import TCPCommEngine, free_ports
from parsec_tpu.utils.params import params


def _engines(n=2, **knobs):
    ports = free_ports(n)
    eps = [("127.0.0.1", p) for p in ports]
    import concurrent.futures as cf
    with cf.ThreadPoolExecutor(n) as ex:
        return list(ex.map(lambda r: TCPCommEngine(r, eps, **knobs),
                           range(n)))


def _drain_until(eng, pred, timeout=15.0):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        if not eng.progress():
            time.sleep(0.0005)
    assert pred(), "condition not reached before timeout"


def _raw_peer(engine, as_rank=1):
    """A hand-driven socket posing as ``as_rank`` toward ``engine``
    (handshake only — NO hello, i.e. a mixed-version peer)."""
    host, port = engine.endpoints[engine.rank]
    sock = socket.create_connection((host, port))
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.sendall(struct.pack("<I", as_rank))
    # wait until the engine registered us (its hello lands in our rx
    # buffer; we never parse it — a v1 peer wouldn't)
    deadline = time.time() + 10
    while as_rank not in engine._conns and time.time() < deadline:
        time.sleep(0.005)
    assert as_rank in engine._conns
    return sock


def _frame(body: bytes) -> bytes:
    return struct.pack("<Q", len(body)) + body


def _batch_frame(msgs):
    segs = []
    for (src, tag, payload) in msgs:
        bufs = []
        fr = pickle.dumps((src, tag, payload), protocol=5,
                          buffer_callback=bufs.append)
        segs.append(wire.pack_segment(fr, [b.raw() for b in bufs]))
    return _frame(b"".join(wire.pack_batch(segs)))


# ---------------------------------------------------------------------- #
# receiver robustness (raw-socket fixture)                               #
# ---------------------------------------------------------------------- #
def test_partial_frame_recv_reassembles():
    """A frame trickling in across many partial reads must reassemble
    byte-exactly (recv returning short is the TCP norm, not an edge)."""
    (e0,) = _engines(1)
    # widen the fixture: a 2-endpoint view so a fake rank 1 may dial in
    e0.endpoints.append(("127.0.0.1", 0))
    e0.fabric.nb_ranks = e0.nb_ranks = 2
    sock = _raw_peer(e0)
    try:
        got = []
        e0.tag_register(100, lambda src, p: got.append((src, p)))
        data = _batch_frame([(1, 100, {"x": 42,
                                       "arr": np.arange(5.0)})])
        for i in range(0, len(data), 7):     # 7-byte dribble
            sock.sendall(data[i:i + 7])
            time.sleep(0.001)
        _drain_until(e0, lambda: got)
        assert got[0][0] == 1 and got[0][1]["x"] == 42
        np.testing.assert_array_equal(got[0][1]["arr"], np.arange(5))
    finally:
        sock.close()
        e0.fini()


def test_multi_message_coalesced_frame_delivers_in_order():
    """One K_BATCH frame carrying several messages delivers each, in
    order, with out-of-band buffers correctly re-sliced."""
    (e0,) = _engines(1)
    e0.endpoints.append(("127.0.0.1", 0))
    e0.fabric.nb_ranks = e0.nb_ranks = 2
    sock = _raw_peer(e0)
    try:
        got = []
        e0.tag_register(77, lambda src, p: got.append(p))
        msgs = [(1, 77, {"i": i, "arr": np.full((4,), i, np.float32)})
                for i in range(5)]
        sock.sendall(_batch_frame(msgs))
        _drain_until(e0, lambda: len(got) == 5)
        assert [p["i"] for p in got] == list(range(5))
        np.testing.assert_array_equal(got[3]["arr"], np.full((4,), 3))
    finally:
        sock.close()
        e0.fini()


def test_unknown_frame_kind_marks_peer_dead():
    """Garbage after the length prefix is a desync: the receiver must
    fail LOUDLY (peer marked dead) instead of hanging both ranks."""
    (e0,) = _engines(1)
    e0.endpoints.append(("127.0.0.1", 0))
    e0.fabric.nb_ranks = e0.nb_ranks = 2
    sock = _raw_peer(e0)
    try:
        sock.sendall(_frame(b"\xfagarbage"))
        deadline = time.time() + 10
        while 1 not in e0.dead_peers and time.time() < deadline:
            time.sleep(0.005)
        assert 1 in e0.dead_peers
    finally:
        sock.close()
        e0.fini()


def test_goodbye_mid_chunked_transfer_is_a_failure():
    """A clean GOODBYE while a chunked transfer is incomplete is a
    protocol violation — the peer owes data."""
    (e0,) = _engines(1)
    e0.endpoints.append(("127.0.0.1", 0))
    e0.fabric.nb_ranks = e0.nb_ranks = 2
    sock = _raw_peer(e0)
    try:
        payload = np.zeros(1 << 16, np.float64)     # 512 KB, chunked
        bufs = []
        fr = pickle.dumps((1, 90, {"arr": payload}), protocol=5,
                          buffer_callback=bufs.append)
        v = bufs[0].raw()
        hdr = wire.pack_xfer_hdr(7, fr, [(True, v.nbytes, None)])
        sock.sendall(_frame(hdr))
        # one chunk of the announced buffer, then a "clean" goodbye
        sock.sendall(_frame(wire.pack_chunk_hdr(7, 0, 0)
                            + bytes(v[:1024])))
        sock.sendall(struct.pack("<Q", wire.GOODBYE))
        deadline = time.time() + 10
        while 1 not in e0.dead_peers and time.time() < deadline:
            time.sleep(0.005)
        assert 1 in e0.dead_peers
        assert 1 not in e0.finished_peers
    finally:
        sock.close()
        e0.fini()


# ---------------------------------------------------------------------- #
# chunked pipelining (engine pair)                                       #
# ---------------------------------------------------------------------- #
def test_chunked_buffer_reassembly_roundtrip():
    e0, e1 = _engines(2, chunk_bytes=1 << 16)
    try:
        big = np.random.RandomState(3).rand(1 << 19)      # 4 MB
        small = np.arange(7, dtype=np.int64)
        got = []
        e1.tag_register(200, lambda src, p: got.append(p))
        e0.send_am(1, 200, {"big": big, "small": small, "k": 9})
        _drain_until(e1, lambda: got)
        np.testing.assert_array_equal(got[0]["big"], big)
        np.testing.assert_array_equal(got[0]["small"], small)
        assert got[0]["k"] == 9
        assert e0.wire_stats["chunks_sent"] >= 64   # really chunked
        assert e0.wire_stats["msgs_chunked"] == 1
    finally:
        e0.fini()
        e1.fini()


def test_control_am_interleaves_with_bulk_payload():
    """The acceptance probe: a small control AM enqueued while a >= 4 MB
    payload is in flight must NOT wait behind it — its delivery
    interleaves between chunks and lands before the bulk message."""
    e0, e1 = _engines(2, chunk_bytes=1 << 16)
    try:
        order = []
        lat = {}
        e1.tag_register(300, lambda src, p: order.append("bulk"))

        def on_ctrl(src, p):
            order.append("ctrl")
            lat["ctrl_ms"] = (time.perf_counter() - p["t0"]) * 1e3

        e1.tag_register(301, on_ctrl)
        big = np.random.RandomState(0).rand(1 << 21)      # 16 MB
        e0.send_am(1, 300, {"arr": big})
        e0.send_am(1, 301, {"t0": time.perf_counter()})
        _drain_until(e1, lambda: len(order) == 2, timeout=60)
        assert order[0] == "ctrl", order       # overtook the bulk tile
        # bounded latency: the control AM waited for at most a chunk or
        # two, not the whole 16 MB drain (generous CI margin)
        assert lat["ctrl_ms"] < 2000, lat
    finally:
        e0.fini()
        e1.fini()


def test_bounded_send_buffer_backpressures_without_deadlock():
    """With a tiny send buffer, a burst of bulk messages must stall the
    sender (bounded memory) yet drain completely — and a message larger
    than the whole buffer is still admitted alone."""
    params.set_cmdline("comm_send_buffer_bytes", str(1 << 18))  # 256 KB
    try:
        e0, e1 = _engines(2, chunk_bytes=1 << 16)
    finally:
        params.unset_cmdline("comm_send_buffer_bytes")
    try:
        assert e0.send_buffer_bytes == 1 << 18
        got = []
        e1.tag_register(950, lambda src, p: got.append(p["i"]))
        rng = np.random.RandomState(9)
        payloads = [rng.rand(1 << 17) for _ in range(8)]   # 1 MB each
        for i, arr in enumerate(payloads):
            e0.send_am(1, 950, {"i": i, "arr": arr})       # > buffer
        _drain_until(e1, lambda: len(got) == 8, timeout=60)
        assert got == list(range(8))
        assert all(p.queued_bytes == 0 for p in e0._peers.values())
    finally:
        e0.fini()
        e1.fini()


def test_chunked_transfer_after_control_burst():
    """Regression: a burst of control AMs followed by a chunked payload
    (and more control traffic racing it) must deliver everything — the
    transfer header precedes its first chunk STRUCTURALLY (both ride
    the FIFO bulk lane), whatever the anti-starvation streak says."""
    e0, e1 = _engines(2, chunk_bytes=1 << 16)
    try:
        got, bulk = [], []
        e1.tag_register(900, lambda src, p: got.append(p))
        e1.tag_register(901, lambda src, p: bulk.append(p))
        for i in range(64):
            e0.send_am(1, 900, {"i": i})
        big = np.random.RandomState(5).rand(1 << 18)      # 2 MB
        e0.send_am(1, 901, {"arr": big})
        for i in range(64):
            e0.send_am(1, 900, {"i": 64 + i})
        _drain_until(e1, lambda: len(got) == 128 and bulk, timeout=60)
        np.testing.assert_array_equal(bulk[0]["arr"], big)
        assert 1 not in e0.dead_peers and 0 not in e1.dead_peers
    finally:
        e0.fini()
        e1.fini()


def test_mutable_bulk_payload_snapshots_at_enqueue():
    """A writable buffer on the chunked path is snapshotted when
    send_am returns (the historical copy-at-send contract): mutating it
    right after the call must not tear the bytes on the wire. Only
    read-only buffers (marked by the rendezvous/wave producers) ride
    zero-copy."""
    e0, e1 = _engines(2, chunk_bytes=1 << 16)
    try:
        got = []
        e1.tag_register(800, lambda src, p: got.append(p))
        big = np.ones(1 << 19)                 # 4 MB, writable
        e0.send_am(1, 800, {"arr": big})
        big[:] = -1.0                          # mutate immediately
        _drain_until(e1, lambda: got, timeout=60)
        np.testing.assert_array_equal(got[0]["arr"], np.ones(1 << 19))
    finally:
        e0.fini()
        e1.fini()


# ---------------------------------------------------------------------- #
# compression                                                            #
# ---------------------------------------------------------------------- #
def test_compressed_frame_roundtrip():
    """With the bandwidth threshold forced sky-high, compressible bulk
    traffic engages the negotiated codec after the first bandwidth
    sample and round-trips intact; the ratio gauge moves below 1."""
    e0, e1 = _engines(2, chunk_bytes=1 << 16,
                      compress_threshold_mbps=10 ** 7)
    try:
        deadline = time.time() + 10           # negotiation done first
        # _peer_to waits for the accept thread's registration: under
        # full-suite load the connection may not be in _peers yet
        peer = e0._peer_to(1)
        while peer.codec is None and time.time() < deadline:
            time.sleep(0.005)
        assert peer.codec is not None
        got = []
        e1.tag_register(400, lambda src, p: got.append(p))
        z = np.zeros(1 << 19)                 # 4 MB of zeros: compresses
        for rep in range(3):                  # rep 1 measures bw, later
            got.clear()                       # reps ride compressed
            e0.send_am(1, 400, {"arr": z, "rep": rep})
            _drain_until(e1, lambda: got, timeout=60)
            np.testing.assert_array_equal(got[0]["arr"], z)
        assert e0.wire_stats["frames_compressed"] > 0, e0.wire_stats
        ratio = e0.compress_ratio()
        assert ratio is not None and ratio < 0.5, ratio
    finally:
        e0.fini()
        e1.fini()


def test_mixed_version_peer_stays_uncompressed():
    """A peer that never advertised codecs (no HELLO — an older wire
    version) must never receive compressed frames, whatever the knobs
    say; traffic still round-trips."""
    e0, e1 = _engines(2, chunk_bytes=1 << 16,
                      compress_threshold_mbps=10 ** 7)
    try:
        # simulate the failed negotiation: as if peer 1's HELLO never
        # carried codecs we know. Wait for the real HELLO first — the
        # override must not be raced and re-negotiated by its arrival.
        deadline = time.time() + 10
        peer = e0._peer_to(1)      # waits for the accept registration
        while peer.codec is None and time.time() < deadline:
            time.sleep(0.005)
        assert peer.codec is not None
        peer.codec = None
        got = []
        e1.tag_register(500, lambda src, p: got.append(p))
        z = np.zeros(1 << 19)
        for rep in range(3):
            got.clear()
            e0.send_am(1, 500, {"arr": z})
            _drain_until(e1, lambda: got, timeout=60)
            np.testing.assert_array_equal(got[0]["arr"], z)
        assert e0.wire_stats["frames_compressed"] == 0, e0.wire_stats
    finally:
        e0.fini()
        e1.fini()


def test_codec_negotiation():
    assert wire.negotiate_codec(["zlib"], ["zlib"]) == "zlib"
    assert wire.negotiate_codec(["zlib"], []) is None
    assert wire.negotiate_codec([], ["zlib"]) is None
    assert wire.negotiate_codec(["zlib", "lz4"],
                                ["lz4", "zlib"]) in ("lz4", "zlib")


# ---------------------------------------------------------------------- #
# codec table round-trips — parameterized over EVERY registered codec    #
# (incl. lz4 when the module is present: its registration branch is no   #
# longer uncovered), lossless exactly, quantized within tolerance        #
# ---------------------------------------------------------------------- #
@pytest.mark.parametrize("codec", sorted(wire.CODECS))
def test_codec_roundtrip(codec):
    ent = wire.CODECS[codec]
    if ent.lossless:
        body = b"an eminently compressible control payload " * 199
        pieces = wire.compress_body(body, codec)
        assert pieces is not None, f"{codec} did not shrink zeros"
        assert bytes(pieces[0])[0] == wire.K_COMP
        out = wire.decompress_body(memoryview(b"".join(
            bytes(p) for p in pieces)))
        assert out == body                      # lossless: exact bytes
    else:
        arr = (np.random.RandomState(3).randn(4097) * 5).astype(np.float64)
        enc = wire.quantize_buffer(
            memoryview(np.ascontiguousarray(arr)).cast("B"), "d", codec)
        assert len(enc) < arr.nbytes // 2       # really smaller
        raw = wire.dequantize_buffer(enc)
        assert len(raw) == arr.nbytes           # exact layout back
        out = np.frombuffer(raw, np.float64)
        rel = np.abs(out - arr).max() / np.abs(arr).max()
        assert rel < 0.01, rel                  # lossy within tolerance


def test_lz4_advertised_only_when_installed():
    assert ("lz4" in wire.available_codecs()) == \
        (wire._lz4_mod() is not None)


def test_quant_codec_never_compresses_frame_bodies():
    with pytest.raises(ValueError):
        wire.compress_body(b"x" * 2048, "qint8")
    assert wire.available_quant_codecs() == ["qbf16", "qint8"]
    assert all(c not in wire.available_codecs()
               for c in wire.available_quant_codecs())


def test_quant_codec_negotiation():
    assert wire.normalize_quant_codec("") is None
    assert wire.normalize_quant_codec("bf16") == "qbf16"
    assert wire.normalize_quant_codec("qint8") == "qint8"
    with pytest.raises(ValueError):
        wire.normalize_quant_codec("zlib")   # lossless: wrong family
    with pytest.raises(ValueError):
        wire.normalize_quant_codec("int4")   # unknown
    assert wire.negotiate_quant_codec("qint8", ["qbf16", "qint8"]) \
        == "qint8"
    assert wire.negotiate_quant_codec("qint8", []) is None
    assert wire.negotiate_quant_codec("qint8", ["qbf16"]) is None
    assert wire.negotiate_quant_codec(None, ["qint8"]) is None


def test_quantized_bufspec_roundtrip_through_rx_xfer():
    """A transfer header announcing a BUF_QUANT buffer reassembles and
    DECODES transparently: the unpickled array has the original
    dtype/shape with quantized values."""
    arr = np.random.RandomState(9).rand(1 << 12)          # 32 KB f64
    bufs = []
    fr = pickle.dumps((0, 7, {"arr": arr}), protocol=5,
                      buffer_callback=bufs.append)
    v = bufs[0].raw()
    enc = memoryview(wire.quantize_buffer(v, "d", "qint8"))
    hdr = wire.pack_xfer_hdr(
        11, fr, [(wire.BUF_CHUNKED | wire.BUF_QUANT, enc.nbytes, None)])
    xid, frame, specs = wire.parse_xfer_hdr(
        memoryview(hdr).toreadonly())
    assert xid == 11 and specs[0][0] == (wire.BUF_CHUNKED
                                         | wire.BUF_QUANT)
    rx = wire.RxXfer(frame, specs)
    done = rx.feed(0, 0, enc)
    assert done
    src, tag, payload = rx.message()
    out = np.asarray(payload["arr"])
    assert out.dtype == arr.dtype and out.shape == arr.shape
    np.testing.assert_array_equal(out, wire.qdq_array(arr, "qint8"))


def test_quantized_transfer_over_tcp_and_eligibility():
    """End to end over real sockets: an ``_qz_ok``-marked bulk float
    message delivers EXACTLY the qdq values (deterministic codec), an
    unmarked one stays bit-exact lossless, and the per-link labeled
    ratio gauge moves above 1."""
    e0, e1 = _engines(2, chunk_bytes=1 << 14, quantize="int8")
    try:
        peer = e0._peer_to(1)
        deadline = time.time() + 10
        while time.time() < deadline:
            with peer.cond:
                if peer.qz_codec:
                    break
            time.sleep(0.005)
        with peer.cond:
            assert peer.qz_codec == "qint8"
        got = []
        e1.tag_register(700, lambda src, p: got.append(p))
        arr = np.random.RandomState(11).rand(1 << 15)     # 256 KB
        e0.send_am(1, 700, {"arr": arr, "_qz_ok": True})
        _drain_until(e1, lambda: got, timeout=30)
        out = np.asarray(got[0]["arr"])
        np.testing.assert_array_equal(out, wire.qdq_array(arr, "qint8"))
        assert e0.wire_stats["bufs_quantized"] == 1
        assert e0.codec_ratio(1, "qint8") > 1.0
        assert e0.quantize_ratio() > 1.0
        # eligibility: the UNMARKED twin of the same payload is exact
        got.clear()
        e0.send_am(1, 700, {"arr": arr})
        _drain_until(e1, lambda: got, timeout=30)
        np.testing.assert_array_equal(np.asarray(got[0]["arr"]), arr)
        assert e0.wire_stats["bufs_quantized"] == 1   # unchanged
        # non-float bulk stays lossless even when marked
        got.clear()
        ints = np.arange(1 << 15, dtype=np.int64)
        e0.send_am(1, 700, {"arr": ints, "_qz_ok": True})
        _drain_until(e1, lambda: got, timeout=30)
        np.testing.assert_array_equal(np.asarray(got[0]["arr"]), ints)
        assert e0.wire_stats["bufs_quantized"] == 1   # still unchanged
    finally:
        e0.fini()
        e1.fini()


def test_quantize_default_knobs_keep_wire_lossless():
    """Off-by-default safety (the acceptance differential): at default
    knobs an ``_qz_ok``-marked bulk message still travels lossless —
    nothing advertises "qz", nothing negotiates, nothing encodes."""
    e0, e1 = _engines(2, chunk_bytes=1 << 14)
    try:
        assert e0._quantize is None
        got = []
        e1.tag_register(800, lambda src, p: got.append(p))
        arr = np.random.RandomState(13).rand(1 << 15)
        e0.send_am(1, 800, {"arr": arr, "_qz_ok": True})
        _drain_until(e1, lambda: got, timeout=30)
        np.testing.assert_array_equal(np.asarray(got[0]["arr"]), arr)
        assert e0.wire_stats["bufs_quantized"] == 0
        assert e0.codec_ratio(1, "qint8") == 1.0
    finally:
        e0.fini()
        e1.fini()


def test_default_knobs_keep_compression_off():
    """Off-by-default safety: at default knobs nothing ever compresses
    and the wire carries plain frames on a fast link."""
    e0, e1 = _engines(2)
    try:
        assert e0.compress_threshold_mbps == 0
        got = []
        e1.tag_register(600, lambda src, p: got.append(p))
        e0.send_am(1, 600, {"arr": np.zeros(1 << 18)})
        _drain_until(e1, lambda: got)
        assert e0.wire_stats["frames_compressed"] == 0
    finally:
        e0.fini()
        e1.fini()


# ---------------------------------------------------------------------- #
# coalescing throughput (the >= 2x acceptance gate)                      #
# ---------------------------------------------------------------------- #
def test_coalescing_improves_small_am_throughput_2x():
    """Small-AM msgs/s with coalescing on vs the per-message path on
    the same fixture (bench.bench_comm_small_am): the batched frames
    must be at least 2x faster (measured ~6x on a quiet host; the
    margin absorbs CI noise)."""
    import bench
    fast = bench.bench_comm_small_am(3000, coalesce=True, reps=2)
    slow = bench.bench_comm_small_am(3000, coalesce=False, reps=2)
    assert fast >= 2.0 * slow, (fast, slow)


# ---------------------------------------------------------------------- #
# GET aggregation                                                        #
# ---------------------------------------------------------------------- #
def test_gets_issued_in_one_progress_cycle_batch_per_peer():
    """Three GETs triggered by one delivered message ride ONE request
    frame and ONE reply frame (msg_count proves it), and every callback
    still fires with its own data."""
    from parsec_tpu.comm.local import LocalFabric

    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    handles = [e0.mem_register(np.full((4,), i, np.float64))
               for i in range(3)]
    got = {}

    def trigger(src, payload):
        for i, h in enumerate(handles):
            e1.get(0, h.handle_id,
                   lambda arr, i=i: got.__setitem__(i, arr))

    e1.tag_register(700, trigger)
    e0.send_am(1, 700, {"go": 1})
    e1.progress()           # delivers trigger; flush batches the 3 GETs
    before = fab.msg_count  # trigger + 1 batched GET request
    assert before == 2, before
    e0.progress()           # serves all three in one reply
    assert fab.msg_count == 3
    e1.progress()           # callbacks fire
    assert set(got) == {0, 1, 2}
    for i in range(3):
        np.testing.assert_array_equal(got[i], np.full((4,), i))


def test_get_outside_progress_sends_immediately():
    from parsec_tpu.comm.local import LocalFabric

    fab = LocalFabric(2)
    e0, e1 = fab.engine(0), fab.engine(1)
    h = e0.mem_register(np.arange(6, dtype=np.float64))
    got = []
    e1.get(0, h.handle_id, got.append)
    assert fab.msg_count == 1       # the request left right away
    e0.progress()
    e1.progress()
    assert got and np.array_equal(got[0], np.arange(6))


# ---------------------------------------------------------------------- #
# adaptive eager/rendezvous cutoff                                       #
# ---------------------------------------------------------------------- #
def _remote_dep_pair(adaptive):
    from parsec_tpu.comm.local import LocalFabric
    from parsec_tpu.comm.remote_dep import RemoteDepEngine

    if adaptive:
        params.set_cmdline("comm_adaptive_short_limit", "1")
    try:
        fab = LocalFabric(2)
        eng = RemoteDepEngine(fab.engine(0))
    finally:
        if adaptive:
            params.unset_cmdline("comm_adaptive_short_limit")
    return eng


def test_adaptive_short_limit_tracks_bandwidth_delay_product():
    eng = _remote_dep_pair(adaptive=True)
    static = eng.short_limit
    # no measurements yet: static cutoff
    assert eng.short_limit_for(1) == static
    # 50 MB/s link, 10 ms GET round-trip -> BDP 500 KB
    eng.ce.link_bw_mbps = lambda peer: 50.0
    eng._note_get_rtt(1, 0.010)
    assert eng.short_limit_for(1) == 500_000
    assert eng.adaptive_limits[1] == 500_000
    # the static knob is the floor...
    eng._note_get_rtt(1, 0.010)
    eng.ce.link_bw_mbps = lambda peer: 0.001   # 1 KB/s: BDP ~10 bytes
    assert eng.short_limit_for(1) == static
    # ...and comm_short_limit_max the ceiling
    eng.ce.link_bw_mbps = lambda peer: 1e6     # absurd link
    assert eng.short_limit_for(1) == eng._short_limit_max


def test_adaptive_off_by_default_keeps_static_cutoff():
    eng = _remote_dep_pair(adaptive=False)
    eng.ce.link_bw_mbps = lambda peer: 50.0
    eng._note_get_rtt(1, 0.010)
    assert eng.short_limit_for(1) == eng.short_limit


def test_get_rtt_ewma_feeds_from_rendezvous():
    """A real rendezvous through _timed_get populates the per-peer RTT
    EWMA the adaptive cutoff reads."""
    from parsec_tpu.comm.local import LocalFabric
    from parsec_tpu.comm.remote_dep import RemoteDepEngine

    fab = LocalFabric(2)
    r0 = RemoteDepEngine(fab.engine(0))
    r1 = RemoteDepEngine(fab.engine(1))
    h = r0.ce.mem_register(np.ones((8,), np.float64))
    got = []
    r1._timed_get(0, h.handle_id, got.append)
    r0.ce.progress()
    r1.ce.progress()
    assert got and 0 in r1._get_rtt
    assert r1._get_rtt[0] > 0


# ---------------------------------------------------------------------- #
# lane-schedule uniformity (wave_dist satellite)                         #
# ---------------------------------------------------------------------- #
def test_lane_schedule_uniformity_matching_digests_pass():
    from parsec_tpu.comm.local import LocalFabric
    from parsec_tpu.dsl.ptg.wave_dist import check_lane_schedule_uniformity
    from parsec_tpu.utils.spmd import spmd_threads

    def rank_fn(r, fab):
        check_lane_schedule_uniformity(fab.engine(r), "same", timeout=20)
        return "ok"

    results, _f = spmd_threads(2, rank_fn, timeout=60)
    assert results == ["ok", "ok"]


def test_lane_schedule_uniformity_mismatch_fails_fast():
    from parsec_tpu.comm.local import LocalFabric
    from parsec_tpu.dsl.ptg.wave import WaveError
    from parsec_tpu.dsl.ptg.wave_dist import check_lane_schedule_uniformity
    from parsec_tpu.utils.spmd import spmd_threads

    def rank_fn(r, fab):
        try:
            check_lane_schedule_uniformity(
                fab.engine(r), f"digest-{r}", timeout=20)
            return "no-error"
        except WaveError as exc:
            return f"raised: {exc}"

    results, _f = spmd_threads(2, rank_fn, timeout=60)
    assert all(r.startswith("raised") for r in results), results
    assert "diverge" in results[0]


# ---------------------------------------------------------------------- #
# pool-tile-spec ownership guard (wave_dist satellite)                   #
# ---------------------------------------------------------------------- #
def test_pool_tile_spec_requires_contract_or_owned_tile():
    """A rank owning no tile of a pool whose collection lacks the
    static tile_shape/dtype contract gets a clear error, not a remote
    fetch or an opaque failure."""
    import types
    from parsec_tpu.dsl.ptg.wave import WaveError
    from parsec_tpu.dsl.ptg.wave_dist import DistWaveRunner

    class NoContractColl:
        dtype = None

        def rank_of(self, m, n):
            return 1          # every tile owned elsewhere

        def data_of(self, m, n):  # pragma: no cover - must not be hit
            raise AssertionError("data_of reached for unowned tile")

    shim = types.SimpleNamespace(
        rank=0, _n_real_colls=1, pool_names=["descA"],
        collections={"descA": NoContractColl()},
        _pool_shapes=[None], _pool_coords=[[(0, 0), (1, 0)]],
        _scratch={})
    with pytest.raises(WaveError, match="static"):
        DistWaveRunner._pool_tile_spec(shim, 0)


def test_pool_tile_spec_uses_locally_owned_coord():
    import types
    from parsec_tpu.dsl.ptg.wave_dist import DistWaveRunner

    probed = []

    class HalfOwnedColl:
        dtype = None

        def rank_of(self, m, n):
            return 0 if (m, n) == (1, 0) else 1

        def data_of(self, m, n):
            probed.append((m, n))
            payload = np.zeros((4, 4), np.float32)
            host = types.SimpleNamespace(payload=payload)
            return types.SimpleNamespace(sync_to_host=lambda: host)

    shim = types.SimpleNamespace(
        rank=0, _n_real_colls=1, pool_names=["descA"],
        collections={"descA": HalfOwnedColl()},
        _pool_shapes=[None], _pool_coords=[[(0, 0), (1, 0)]],
        _scratch={})
    sh, dt = DistWaveRunner._pool_tile_spec(shim, 0)
    assert sh == (4, 4) and dt == np.float32
    assert probed == [(1, 0)]       # the owned coord, not coords[0]
