"""Unified metrics registry: counters + gauges + histograms.

The runtime already has two counter islands — the per-context SDE
registry (``profiling.sde``, owned counters + poll gauges) and ad-hoc
``stats`` dicts on engines/devices. ``MetricsRegistry`` wraps an
SDERegistry (so every existing ``PARSEC::*`` counter shows up
unchanged) and adds the one kind neither island has: **histograms**
(task-execution and transfer latency distributions), plus Prometheus
text exposition through ``obs.prometheus``.

Naming follows the reference's ``PARSEC::``-style namespace; exposition
sanitizes it to ``parsec_*`` metric names.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Sequence

from ..profiling.pins import PinsEvent, PinsModule
from ..profiling.sde import SDERegistry

__all__ = ["Histogram", "MetricsRegistry", "MetricsTaskModule", "ExecTimer",
           "TASK_EXEC_SECONDS", "COMM_XFER_SECONDS"]

TASK_EXEC_SECONDS = "PARSEC::TASK::EXEC_SECONDS"
COMM_XFER_SECONDS = "PARSEC::COMM::XFER_SECONDS"

#: default latency buckets (seconds): 1 us .. 10 s, decade steps with a
#: midpoint — wide enough for both Python task bodies and DCN transfers
DEFAULT_BUCKETS = (1e-6, 1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2,
                   1e-1, 5e-1, 1.0, 10.0)


class Histogram:
    """Fixed-bucket cumulative histogram (the Prometheus model: each
    bucket counts observations <= its upper bound)."""

    __slots__ = ("name", "bounds", "counts", "sum", "count", "_lock")

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        self.name = name
        self.bounds = tuple(sorted(buckets))
        self.counts = [0] * (len(self.bounds) + 1)  # +1 = +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if value <= b:
                idx = i
                break
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        cum, buckets = 0, []
        for b, c in zip(self.bounds, counts):
            cum += c
            buckets.append((b, cum))
        buckets.append((float("inf"), total))
        return {"buckets": buckets, "sum": s, "count": total}

    def mean(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else 0.0


class MetricsRegistry:
    """One façade over counters (SDE owned), gauges (SDE polls), and
    histograms. Always constructed per Context (cheap: two dicts); the
    hot-path *feeders* — the PINS latency module, comm span hooks — are
    only enabled when metrics/profiling are switched on, so disabled
    runs keep the near-free fast path."""

    def __init__(self, sde: Optional[SDERegistry] = None) -> None:
        self.sde = sde if sde is not None else SDERegistry()
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    # -- counters / gauges (delegate to the SDE registry) -------------------
    def inc(self, name: str, v: int = 1) -> None:
        self.sde.inc(name, v)

    def gauge(self, name: str, fn: Callable[[], Any]) -> None:
        self.sde.register_poll(name, fn)

    def read(self, name: str) -> Any:
        return self.sde.read(name)

    # -- histograms ----------------------------------------------------------
    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, Histogram(name, buckets))
        return h

    def observe(self, name: str, value: float) -> None:
        self.histogram(name).observe(value)

    def histograms(self) -> Dict[str, Histogram]:
        with self._lock:
            return dict(self._hists)

    # -- reading -------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = self.sde.snapshot()
        for name, h in self.histograms().items():
            out[name] = h.snapshot()
        return out

    def render_prometheus(self, labels: Optional[Dict[str, str]] = None) -> str:
        from .prometheus import render
        return render(self, labels=labels)


class ExecTimer:
    """The single exec-latency feed: per-thread begin timestamps into a
    histogram. Shared by MetricsTaskModule (metrics without profiling)
    and TaskProfilerModule.exec_timer (metrics + profiling, one PINS
    callback instead of two) so the measurement exists exactly once.
    When an ``OverlapTracker`` is attached the same intervals also feed
    the live overlap gauge's COMPUTE channel (obs/spans.py)."""

    __slots__ = ("hist", "_open", "_time", "tracker", "live")

    def __init__(self, hist: Histogram, tracker: Any = None,
                 live: Any = None) -> None:
        import time
        self._time = time
        self.hist = hist
        self._open: Dict[int, int] = {}
        self.tracker = tracker
        # obs_live (ISSUE 16): the same closed exec intervals also feed
        # the streaming health monitor's compute channel
        self.live = live

    def begin(self, th_id: int) -> None:
        self._open[th_id] = self._time.monotonic_ns()

    def end(self, th_id: int) -> None:
        t0 = self._open.pop(th_id, None)
        if t0 is not None:
            t1 = self._time.monotonic_ns()
            self.hist.observe((t1 - t0) / 1e9)
            if self.tracker is not None:
                self.tracker.note("compute", t0, t1)
            if self.live is not None:
                self.live.note_compute(t0, t1)


class MetricsTaskModule(PinsModule):
    """PINS module feeding the per-task execution-latency histogram —
    rides the existing ``_active == 0`` fast-path guard, so with metrics
    off the EXEC sites stay near-free."""

    name = "metrics_task"
    events = [PinsEvent.EXEC_BEGIN, PinsEvent.EXEC_END]

    def __init__(self, metrics: MetricsRegistry, context: Any = None,
                 tracker: Any = None, live: Any = None) -> None:
        self.metrics = metrics
        # context filter: several in-process SPMD ranks share the global
        # PINS sites, but each rank's histogram must only see its own
        # tasks (same isolation as the per-context SDE registry)
        self.context = context
        self.timer = ExecTimer(metrics.histogram(TASK_EXEC_SECONDS),
                               tracker=tracker, live=live)

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        if self.context is not None and es.context is not self.context:
            return
        if event == PinsEvent.EXEC_BEGIN:
            self.timer.begin(es.th_id)
        else:
            self.timer.end(es.th_id)
