#!/usr/bin/env python
"""obs_top — live terminal view of a running fleet's health.

Polls the aggregator's ``GET /health`` endpoint (the fleet-merged
obs_live snapshot: per-rank overlap fraction, per-link exposed-wait,
detector firings) and renders it through the SAME formatter the
offline report uses (``obs/live.format_health``), so what you read
live is what ``tools/obs_report.py --live`` prints after the run::

    # terminal 1: the aggregator (or any run with --mca sde_push)
    python tools/aggregator_server.py --port 9876

    # terminal 2: the workload, pushing health snapshots
    PARSEC_MCA_obs_live=1 PARSEC_MCA_sde_push=127.0.0.1:9876 \\
        python examples/ex05_broadcast.py

    # terminal 3: watch it
    python tools/obs_top.py http://127.0.0.1:9876/health

``--once`` prints a single snapshot and exits (scripting / CI);
``--json`` emits the raw fleet document instead of text.
"""
import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.obs import format_health  # noqa: E402


def fetch(url: str, timeout: float = 2.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("url",
                    help="aggregator health endpoint, e.g. "
                         "http://127.0.0.1:9876/health (a bare "
                         "host:port gets /health appended)")
    ap.add_argument("--interval", type=float, default=1.0, metavar="SECS",
                    help="poll cadence (default 1s)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw fleet JSON instead of text")
    ap.add_argument("--tenant", default=None, metavar="NAME",
                    help="focus the per-tenant attribution section "
                         "(serve/, ISSUE 18) on one tenant; snapshots "
                         "from pre-serve builds simply have no such "
                         "section and render unchanged")
    args = ap.parse_args(argv)

    url = args.url
    if not url.startswith("http"):
        url = f"http://{url}"
    if not url.rstrip("/").endswith("/health"):
        url = url.rstrip("/") + "/health"

    while True:
        try:
            doc = fetch(url)
        except OSError as e:
            print(f"obs_top: {url} unreachable: {e}", file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if args.tenant is not None and isinstance(doc, dict):
            tenants = doc.get("per_tenant")
            if isinstance(tenants, dict):
                doc = dict(doc)
                doc["per_tenant"] = {k: v for k, v in tenants.items()
                                     if k == args.tenant}
        if args.json:
            print(json.dumps(doc))
        else:
            if not args.once:
                # clear + home, keeping scrollback for firing history
                sys.stdout.write("\033[H\033[J")
            print(time.strftime("%H:%M:%S"), url)
            print(format_health(doc))
        sys.stdout.flush()
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
