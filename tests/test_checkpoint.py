"""Checkpoint/resume of collections (SURVEY.md §5.4 — absent in the
reference; here: quiescent-point tile snapshots per rank).
"""
import numpy as np
import pytest

import parsec_tpu
from conftest import spmd
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.utils import checkpoint as ckpt


def test_roundtrip_single_rank(tmp_path):
    rng = np.random.RandomState(0)
    M = rng.rand(96, 96).astype(np.float32)
    A = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32).from_numpy(M)
    prefix = str(tmp_path / "ck")
    path = ckpt.save_collection(A, prefix)
    B = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32)
    n = ckpt.restore_collection(B, prefix)
    assert n == 9
    np.testing.assert_array_equal(B.to_numpy(), M)
    assert path.endswith(".rank0.npz")


def test_restore_rejects_incompatible_geometry(tmp_path):
    A = TwoDimBlockCyclic(64, 64, 32, 32).from_numpy(
        np.ones((64, 64), np.float32))
    prefix = str(tmp_path / "ck")
    ckpt.save_collection(A, prefix)
    wrong = TwoDimBlockCyclic(64, 64, 16, 16)
    with pytest.raises(ValueError, match="incompatible"):
        ckpt.restore_collection(wrong, prefix)


def test_restore_rejects_wrong_rank_count_and_grid(tmp_path):
    """A snapshot written on a 4-rank 2x2 grid must fail FAST (clear
    manifest-mismatch error) when restored onto a 2-rank 2x1 grid —
    each shard holds only the tiles its writer owned under ITS
    distribution, so loading the wrong shard set would silently drop
    tiles."""
    nb_ranks, n, nb = 4, 128, 32
    prefix = str(tmp_path / "grid")

    def save_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=2, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        return ckpt.save_collection(d, prefix)

    spmd(nb_ranks, save_rank)

    wrong = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=1, nodes=2, rank=0,
                              dtype=np.float32)
    with pytest.raises(ckpt.CheckpointMismatchError) as ei:
        ckpt.restore_collection(wrong, prefix)
    msg = str(ei.value)
    # names every mismatched field and both grids, so the operator sees
    # WHAT diverged without replaying the save
    assert "nodes" in msg and "Q" in msg
    assert "4 rank(s), grid 2x2" in msg
    assert "2 rank(s), grid 2x1" in msg

    # a single-rank collection can't swallow a 4-rank shard either
    single = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    with pytest.raises(ckpt.CheckpointMismatchError):
        ckpt.restore_collection(single, prefix)


def test_mismatch_error_aggregates_all_keys(tmp_path):
    """One error listing EVERY divergent key (tile size and dtype here)
    beats a fix-one-rerun loop."""
    A = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(
        np.ones((64, 64), np.float32))
    prefix = str(tmp_path / "agg")
    ckpt.save_collection(A, prefix)
    wrong = TwoDimBlockCyclic(64, 64, 16, 16, dtype=np.float64)
    with pytest.raises(ckpt.CheckpointMismatchError) as ei:
        ckpt.restore_collection(wrong, prefix)
    msg = str(ei.value)
    assert "mb" in msg and "dtype" in msg


def test_restore_accepts_pre_ft_manifest(tmp_path):
    """Snapshots written before the manifest carried nodes/rank (the
    pre-ft format) still restore: those keys are only compared when the
    snapshot recorded them."""
    import json

    rng = np.random.RandomState(3)
    M = rng.rand(64, 64).astype(np.float32)
    A = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(M)
    prefix = str(tmp_path / "oldfmt")
    path = ckpt.save_collection(A, prefix)
    # rewrite the manifest without the new keys (the old writer)
    with np.load(path, allow_pickle=False) as z:
        man = json.loads(str(z["__manifest__"]))
        tiles = {k: z[k] for k in z.files if k.startswith("t")}
    for k in ("nodes", "rank"):
        man.pop(k, None)
    np.savez(path, __manifest__=json.dumps(man), **tiles)
    B = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32)
    assert ckpt.restore_collection(B, prefix) == 4
    np.testing.assert_array_equal(B.to_numpy(), M)


def test_checkpoint_resume_mid_computation(ctx, tmp_path):
    """Factor, checkpoint at the quiescent point, clobber, restore, and
    continue with a solve — the resume path a failed run would take."""
    from parsec_tpu.ops import (dpotrf_taskpool, dtrsm_lower_taskpool,
                                dtrsm_lower_trans_taskpool, make_spd)
    n, nb = 96, 32
    M = make_spd(n)
    rng = np.random.RandomState(1)
    Bm = (rng.rand(n, 16) - 0.5).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    ctx.add_taskpool(dpotrf_taskpool(A))
    ctx.wait()
    prefix = str(tmp_path / "factored")
    ckpt.save_collection(A, prefix, context=ctx)

    # "restart": fresh collection restored from the checkpoint
    A2 = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    assert ckpt.restore_collection(A2, prefix) == 9
    B = TwoDimBlockCyclic(n, 16, nb, nb, dtype=np.float32).from_numpy(Bm)
    ctx.add_taskpool(dtrsm_lower_taskpool(A2, B))
    ctx.wait()
    ctx.add_taskpool(dtrsm_lower_trans_taskpool(A2, B))
    ctx.wait()
    ref = np.linalg.solve(M.astype(np.float64), Bm.astype(np.float64))
    np.testing.assert_allclose(B.to_numpy(), ref, atol=5e-3)


def test_spmd_per_rank_shards(tmp_path):
    """Each rank writes only its own tiles; restore on the same grid
    reads them back rank-locally."""
    nb_ranks, n, nb = 4, 128, 32
    rng = np.random.RandomState(2)
    M = rng.rand(n, n).astype(np.float32)
    prefix = str(tmp_path / "shards")

    def save_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=2, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        for (i, j) in d.local_tiles():
            np.copyto(d.tile(i, j),
                      M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
        return ckpt.save_collection(d, prefix)

    paths, _ = spmd(nb_ranks, save_rank)
    assert len(set(paths)) == nb_ranks

    def restore_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=2, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        count = ckpt.restore_collection(d, prefix)
        ok = all(np.array_equal(
            d.tile(i, j), M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
            for (i, j) in d.local_tiles())
        return count, ok

    results, _ = spmd(nb_ranks, restore_rank)
    assert sum(c for c, _ in results) == 16
    assert all(ok for _, ok in results)


def test_loose_array_roundtrip(tmp_path):
    prefix = str(tmp_path / "state")
    ckpt.save_arrays(prefix, step=np.int64(7),
                     w=np.arange(6.0).reshape(2, 3))
    back = ckpt.load_arrays(prefix)
    assert back["step"] == 7
    np.testing.assert_array_equal(back["w"], np.arange(6.0).reshape(2, 3))


# --------------------------------------------------------------------- #
# durability: atomic writes, torn-file detection (ISSUE 9 satellite)    #
# --------------------------------------------------------------------- #
def test_manifest_records_format_version(tmp_path):
    A = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(
        np.ones((64, 64), np.float32))
    prefix = str(tmp_path / "ver")
    path = ckpt.save_collection(A, prefix)
    assert ckpt.read_manifest(path)["version"] == ckpt.CHECKPOINT_VERSION


def test_atomic_save_survives_midwrite_crash(tmp_path, monkeypatch):
    """A crash mid-``np.savez`` must leave the PUBLISHED path holding
    the previous complete snapshot, never a torn mix — the crashing
    rank's next incarnation recovers from it."""
    M0 = np.full((64, 64), 7.0, np.float32)
    A = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(M0)
    prefix = str(tmp_path / "atomic")
    path = ckpt.save_collection(A, prefix)

    real_savez = np.savez

    def dying_savez(f, **arrays):
        real_savez(f, **{k: arrays[k] for k in list(arrays)[:1]})
        raise KeyboardInterrupt("rank killed mid-snapshot")

    A2 = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(
        np.zeros((64, 64), np.float32))
    monkeypatch.setattr(np, "savez", dying_savez)
    with pytest.raises(KeyboardInterrupt):
        ckpt.save_collection(A2, prefix)
    monkeypatch.undo()

    # published file: still the OLD complete snapshot; no .tmp debris
    B = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32)
    assert ckpt.restore_collection(B, prefix) == 4
    np.testing.assert_array_equal(B.to_numpy(), M0)
    import glob as _glob
    assert not _glob.glob(str(tmp_path / "*.tmp.*"))


def test_torn_snapshot_raises_corrupt_not_mismatch(tmp_path):
    """A truncated .npz surfaces as CheckpointCorruptError (skippable:
    fall back to the previous snapshot), distinct from both a manifest
    mismatch and a missing file."""
    A = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32).from_numpy(
        np.ones((64, 64), np.float32))
    prefix = str(tmp_path / "torn")
    path = ckpt.save_collection(A, prefix)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:len(raw) // 3])   # the torn tail of a dead writer
    B = TwoDimBlockCyclic(64, 64, 32, 32, dtype=np.float32)
    with pytest.raises(ckpt.CheckpointCorruptError, match="torn"):
        ckpt.restore_collection(B, prefix)
    with pytest.raises(FileNotFoundError):
        ckpt.restore_collection(B, str(tmp_path / "never_written"))


def test_mismatch_error_names_reshard_escape_hatch(tmp_path):
    """Distribution-only mismatches (grid/rank keys) point the operator
    at reshard=True / ft_elastic; geometry mismatches must NOT (a tile
    size change is unrecoverable by resharding)."""
    nb_ranks, n, nb = 4, 128, 32
    prefix = str(tmp_path / "hatch")

    def save_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=2, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        return ckpt.save_collection(d, prefix)

    spmd(nb_ranks, save_rank)
    wrong_grid = TwoDimBlockCyclic(n, n, nb, nb, P=2, Q=1, nodes=2,
                                   rank=0, dtype=np.float32)
    with pytest.raises(ckpt.CheckpointMismatchError) as ei:
        ckpt.restore_collection(wrong_grid, prefix)
    assert "reshard=True" in str(ei.value)
    assert "ft_elastic" in str(ei.value)

    wrong_geom = TwoDimBlockCyclic(n, n, 16, 16, P=2, Q=2, nodes=4,
                                   rank=0, dtype=np.float32)
    with pytest.raises(ckpt.CheckpointMismatchError) as ei:
        ckpt.restore_collection(wrong_geom, prefix)
    assert "reshard=True" not in str(ei.value)


# --------------------------------------------------------------------- #
# cross-grid reshard restore (ISSUE 9 tentpole)                         #
# --------------------------------------------------------------------- #
def _write_grid_snapshot(tmp_path, M, n, nb, nb_ranks, P, Q, name):
    prefix = str(tmp_path / name)

    def save_rank(rank, fabric):
        d = TwoDimBlockCyclic(n, n, nb, nb, P=P, Q=Q, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        d.name = "descA"
        for (i, j) in d.local_tiles():
            np.copyto(d.tile(i, j),
                      M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
        return ckpt.save_collection(d, prefix)

    spmd(nb_ranks, save_rank)
    return prefix


def _reshard_onto(prefix, M, n, nb, nb_ranks, P, Q):
    """Restore with reshard=True on a fresh grid; golden-check every
    landed tile against the source matrix."""
    from parsec_tpu.comm import RemoteDepEngine

    def restore_rank(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            d = TwoDimBlockCyclic(n, n, nb, nb, P=P, Q=Q, nodes=nb_ranks,
                                  rank=rank, dtype=np.float32)
            d.name = "descA"
            got = ckpt.restore_collection(d, prefix, reshard=True,
                                          context=ctx)
            local = {t: np.array(d.tile(*t)) for t in d.local_tiles()}
            return got, local, dict(eng.ce.elastic_stats)
        finally:
            ctx.fini()

    results, _ = spmd(nb_ranks, restore_rank)
    merged = {}
    for got, local, stats in results:
        assert got == len(local)
        assert stats["reshard_bytes"] > 0   # the reshard path really ran
        merged.update(local)
    assert len(merged) == (n // nb) ** 2
    for (i, j), arr in merged.items():
        np.testing.assert_array_equal(
            arr, M[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])


def test_reshard_restore_4rank_to_2rank(tmp_path):
    """The shrink shape: a 4-rank snapshot lands bit-identical on a
    2-rank grid (each survivor loads the writer shards folded onto it
    and the redistribution moves tiles to their new owners)."""
    n, nb = 128, 32
    rng = np.random.RandomState(11)
    M = rng.rand(n, n).astype(np.float32)
    prefix = _write_grid_snapshot(tmp_path, M, n, nb, 4, 4, 1, "s42")
    _reshard_onto(prefix, M, n, nb, 2, 2, 1)


def test_reshard_restore_1x4_to_2x2(tmp_path):
    """Grid-SHAPE change at the same rank count: 1x4 -> 2x2 is a pure
    ownership permutation and must also be bit-identical."""
    n, nb = 128, 32
    rng = np.random.RandomState(12)
    M = rng.rand(n, n).astype(np.float32)
    prefix = _write_grid_snapshot(tmp_path, M, n, nb, 4, 1, 4, "s14")
    _reshard_onto(prefix, M, n, nb, 4, 2, 2)


def test_reshard_restore_to_single_rank(tmp_path):
    """A 4-rank snapshot folds onto ONE process with no comm machinery
    (the operator's salvage path: pull a dead job's state anywhere)."""
    n, nb = 128, 32
    rng = np.random.RandomState(13)
    M = rng.rand(n, n).astype(np.float32)
    prefix = _write_grid_snapshot(tmp_path, M, n, nb, 4, 2, 2, "s41")
    d = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    d.name = "descA"
    assert ckpt.restore_collection(d, prefix, reshard=True) == 16
    np.testing.assert_array_equal(d.to_numpy(), M)


def test_reshard_rejects_geometry_mismatch(tmp_path):
    """reshard=True relaxes the DISTRIBUTION only: a tile-size change
    still hard-fails (resharding moves tiles, it cannot re-tile
    bytes)."""
    n = 128
    rng = np.random.RandomState(14)
    M = rng.rand(n, n).astype(np.float32)
    prefix = _write_grid_snapshot(tmp_path, M, n, 32, 4, 4, 1, "sgm")
    wrong = TwoDimBlockCyclic(n, n, 16, 16, dtype=np.float32)
    wrong.name = "descA"
    with pytest.raises(ckpt.CheckpointMismatchError, match="GEOMETRY"):
        ckpt.restore_collection(wrong, prefix, reshard=True)


def test_reshard_rejects_mixed_stale_shards(tmp_path):
    """A stale shard from a DIFFERENT grid sitting beside a newer save
    must be rejected, not silently blended into the restore."""
    n, nb = 128, 32
    rng = np.random.RandomState(15)
    M = rng.rand(n, n).astype(np.float32)
    prefix = _write_grid_snapshot(tmp_path, M, n, nb, 2, 2, 1, "mix")
    # rank 1's shard clobbered by a leftover from an older 4-rank
    # incarnation of the same job (same prefix, different grid)
    stale = TwoDimBlockCyclic(n, n, nb, nb, P=4, Q=1, nodes=4, rank=1,
                              dtype=np.float32)
    stale.name = "descA"
    ckpt.save_collection(stale, prefix)
    d = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    d.name = "descA"
    with pytest.raises(ckpt.CheckpointCorruptError, match="stale"):
        ckpt.restore_collection(d, prefix, reshard=True)
