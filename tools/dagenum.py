#!/usr/bin/env python
"""Enumerate a compiled JDF's task DAG without executing it
(ref: tools/dagenum.c + tools/grapher.c — offline DAG enumeration and
rendering; here built on the capture planner's symbolic dep resolution).

    python tools/dagenum.py graph.jdf -g NB=4 -g N=16
    python tools/dagenum.py graph.jdf -g NB=4 --dot dag.dot

Globals of collection type are synthesized as dummy tile holders sized
from --tiles MTxNT (default 4x4). Prints per-class instance counts, edge
count, and the critical-path length (depth of the DAG); --dot writes a
Graphviz rendering of the full instance graph.

--sim adds the simulated-date walk the reference builds as PARSEC_SIM
(parsec_internal.h:524,674 — every task carries a sim_exec_date =
max over predecessors + its duration): per-class durations come from
repeated --cost CLASS=SECONDS (default 1.0), and the report gives the
critical path in simulated time, the serial time, the achievable-
parallelism profile (average + peak concurrency on infinite
processors), and the WAVE schedule's makespan/slack — wave execution
barriers at dependence levels, so its makespan is the sum of each
level's longest task; slack vs the critical path is the price of
level-synchronous batching.

    python tools/dagenum.py parsec_tpu/ops/jdf/dpotrf.jdf -g NT=64 \\
        --tiles 64x64 --sim --cost POTRF=2.5 --cost GEMM=1.0
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.collections.collection import DataCollection  # noqa: E402


class _DummyCollection(DataCollection):
    """Stands in for any collection global: data_of is never touched by
    planning (only rank_of via affinity, and tiles() for I/O shapes)."""

    def __init__(self, mt: int, nt: int) -> None:
        super().__init__(1, 0)
        self.mt, self.nt = mt, nt

    def rank_of(self, *a) -> int:
        return 0

    def tiles(self):
        return [(i, j) for i in range(self.mt) for j in range(self.nt)]

    def data_of(self, *a):
        raise RuntimeError("dagenum never materializes data")


def enumerate_factory(factory, env: dict, mt: int = 4, nt: int = 4):
    """Enumerate a compiled JDF factory's instance DAG without executing
    it: bind ``env`` globals (declared collection globals not in ``env``
    get dummy mt x nt holders), instantiate, and run the capture
    planner's symbolic dep resolution.  Returns ``(tp, order)`` where
    ``order`` is the topologically-sorted instance list (each with
    resolved ``preds``).  Raises ``CaptureError`` on a dependency cycle
    — the importable core behind this script, reused by the static
    verifier's cycle pass (parsec_tpu/analysis/ptg_check.py)."""
    env = dict(env)
    # bind every declared collection global not supplied to a dummy
    for g in factory.jdf.globals:
        if g.name not in env and g.properties.get("type") == "collection":
            env[g.name] = _DummyCollection(mt, nt)
    tp = factory.new(**env)
    from parsec_tpu.dsl.ptg.capture import plan
    return tp, plan(tp)


def enumerate_text(text: str, env: dict, mt: int = 4, nt: int = 4,
                   name: str = "jdf"):
    """``enumerate_factory`` over raw JDF source text."""
    from parsec_tpu.dsl import ptg
    return enumerate_factory(ptg.compile_jdf(text, name=name), env, mt, nt)


def enumerate_dag(jdf_path: str, globals_kv, mt: int, nt: int):
    from parsec_tpu.dsl import ptg

    factory = ptg.compile_jdf_file(jdf_path)
    env = {}
    for name, val in globals_kv:
        try:
            env[name] = int(val)
        except ValueError:
            env[name] = val
    return enumerate_factory(factory, env, mt, nt)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jdf", help="JDF source file")
    ap.add_argument("-g", "--globals", action="append", default=[],
                    metavar="NAME=VALUE", help="bind a JDF global")
    ap.add_argument("--tiles", default="4x4",
                    help="MTxNT of synthesized collections (default 4x4)")
    ap.add_argument("--dot", default=None, help="write a Graphviz file")
    ap.add_argument("--sim", action="store_true",
                    help="simulated-date schedule analysis (PARSEC_SIM)")
    ap.add_argument("--cost", action="append", default=[],
                    metavar="CLASS=SECONDS",
                    help="per-class task duration for --sim (default 1.0)")
    args = ap.parse_args(argv)
    parts = args.tiles.lower().split("x")
    if len(parts) != 2 or not all(p.isdigit() for p in parts):
        ap.error(f"--tiles {args.tiles!r}: expected MTxNT (e.g. 4x4)")
    mt, nt = int(parts[0]), int(parts[1])
    kv = []
    for g in args.globals:
        if "=" not in g:
            ap.error(f"-g {g!r}: expected NAME=VALUE")
        kv.append(tuple(g.split("=", 1)))
    tp, order = enumerate_dag(args.jdf, kv, mt, nt)

    counts = {}
    for inst in order:
        counts[inst.tc.ast.name] = counts.get(inst.tc.ast.name, 0) + 1
    edges = sum(len(i.preds) for i in order)
    # critical path (depth): longest pred chain
    depth = {}
    for inst in order:  # topo order: preds resolved first
        depth[inst.key] = 1 + max((depth[p] for p in inst.preds), default=0)
    print(f"{tp.name}: {len(order)} tasks, {edges} dependence edges, "
          f"critical path {max(depth.values(), default=0)}")
    for name in sorted(counts):
        print(f"  {name:<12} {counts[name]:>6}")

    if args.sim:
        cost = {}
        for c in args.cost:
            if "=" not in c:
                ap.error(f"--cost {c!r}: expected CLASS=SECONDS")
            name, v = c.split("=", 1)
            if name not in counts:
                ap.error(f"--cost {c!r}: no task class {name!r} in this "
                         f"JDF (classes: {', '.join(sorted(counts))})")
            cost[name] = float(v)
        # sim_exec_date walk (parsec_internal.h:674): a task starts at
        # the max end date of its predecessors and runs its class's
        # duration — the end-date max is the schedule-independent
        # critical path (infinite processors, zero comm)
        end = {}
        lvl = {}
        lvl_max = {}     # dependence level -> longest member (wave cost)
        serial = 0.0
        for inst in order:  # topo order: preds resolved first
            d = cost.get(inst.tc.ast.name, 1.0)
            serial += d
            s = max((end[p] for p in inst.preds), default=0.0)
            end[inst.key] = s + d
            lv = 1 + max((lvl[p] for p in inst.preds), default=0)
            lvl[inst.key] = lv
            lvl_max[lv] = max(lvl_max.get(lv, 0.0), d)
        cp = max(end.values(), default=0.0)
        # achievable-parallelism profile: concurrency sweep over the
        # as-soon-as-possible schedule's start/end events
        events = []
        for inst in order:
            d = cost.get(inst.tc.ast.name, 1.0)
            events.append((end[inst.key] - d, 1))
            events.append((end[inst.key], -1))
        events.sort()
        cur = peak = 0
        for _t, e in events:
            cur += e
            peak = max(peak, cur)
        # wave execution barriers at dependence levels: its makespan is
        # the sum of each level's longest task; the slack vs the
        # critical path is the price of level-synchronous batching
        wave_ms = sum(lvl_max.values())
        print(f"  sim: critical path {cp:.3f}s, serial {serial:.3f}s, "
              f"avg parallelism {serial / cp if cp else 0.0:.1f}, "
              f"peak {peak}")
        print(f"  sim: wave makespan {wave_ms:.3f}s over "
              f"{len(lvl_max)} levels, slack vs critical path "
              f"{((wave_ms - cp) / cp * 100.0) if cp else 0.0:+.1f}%")

    if args.dot:
        with open(args.dot, "w") as fh:
            fh.write(f'digraph "{tp.name}" {{\n')
            for inst in order:
                label = f"{inst.tc.ast.name}{inst.locals}"
                fh.write(f'  "{label}";\n')
                for p in inst.preds:
                    fh.write(f'  "{p[0]}{p[1]}" -> "{label}";\n')
            fh.write("}\n")
        print(f"DOT written to {args.dot}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
