"""Pallas kernel tests (interpret mode on the virtual-CPU mesh).

The kernels are the TPU analog of the reference's device-side chores
(ref: jdf2c.c:6557 CUDA chore codegen); here we validate numerics and
gradients of the exact kernel code path against the jnp references.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

pltpu = pytest.importorskip("jax.experimental.pallas.tpu",
                            reason="pallas TPU dialect not importable")
if not hasattr(pltpu, "CompilerParams"):
    # the kernels target the renamed pallas compiler-params API; older
    # jax only ships TPUCompilerParams with different fields
    pytest.skip("jax.experimental.pallas.tpu.CompilerParams not available",
                allow_module_level=True)

from parsec_tpu.ops import pallas_kernels as pk
from parsec_tpu.parallel.ring_attention import local_attention


def _qkv(B=2, H=2, T=64, D=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, T, D), dtype=dtype) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    out = pk.flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = local_attention(q, k, v, causal=causal, use_pallas=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_uneven_blocks():
    # T not a multiple of the preferred block: _pick_block must adapt
    q, k, v = _qkv(T=48)
    out = pk.flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = local_attention(q, k, v, causal=True, use_pallas=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_grads(causal):
    q, k, v = _qkv(B=1, H=2, T=32, D=8)

    def loss_flash(q, k, v):
        o = pk.flash_attention(q, k, v, causal=causal,
                               block_q=16, block_k=16)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = local_attention(q, k, v, causal=causal, use_pallas=False)
        return jnp.sum(o * o)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_matmul_matches_reference():
    rng = np.random.RandomState(1)
    a = jnp.asarray(rng.randn(96, 128), dtype=jnp.float32)
    b = jnp.asarray(rng.randn(128, 64), dtype=jnp.float32)
    out = pk.matmul(a, b, block_m=32, block_n=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(a @ b),
                               rtol=1e-4, atol=1e-4)


def test_matmul_jit_and_grad():
    rng = np.random.RandomState(2)
    a = jnp.asarray(rng.randn(32, 48), dtype=jnp.float32)
    b = jnp.asarray(rng.randn(48, 32), dtype=jnp.float32)
    f = jax.jit(lambda a, b: pk.matmul(a, b, 16, 16, 16))
    np.testing.assert_allclose(np.asarray(f(a, b)), np.asarray(a @ b),
                               rtol=1e-4, atol=1e-4)
    # gradient: VJP reruns the kernel on transposes (dA = g@B^T, dB = A^T@g)
    ga, gb = jax.grad(lambda a, b: jnp.sum(pk.matmul(a, b, 16, 16, 16) ** 2),
                      argnums=(0, 1))(a, b)
    g = 2.0 * np.asarray(a @ b)
    np.testing.assert_allclose(np.asarray(ga), g @ np.asarray(b).T,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(a).T @ g,
                               rtol=1e-4, atol=1e-4)


def test_flash_stats_merge_across_blocks():
    """flash_attention_stats + the documented merge rule == attention
    over the concatenated key/value sets (the ring-attention building
    block), including causal stats conventions."""
    import jax
    import jax.numpy as jnp
    B, H, T, D = 2, 2, 64, 16
    rng = np.random.RandomState(3)
    mk = lambda: jnp.asarray(rng.rand(B, H, T, D), dtype=jnp.float32)
    q, k1, v1, k2, v2 = mk(), mk(), mk(), mk(), mk()
    o1, m1, l1 = pk.flash_attention_stats(q, k1, v1, block_q=32, block_k=32)
    o2, m2, l2 = pk.flash_attention_stats(q, k2, v2, block_q=32, block_k=32)
    m = jnp.maximum(m1, m2)
    w1 = jnp.exp(m1 - m) * l1
    w2 = jnp.exp(m2 - m) * l2
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / (w1 + w2)[..., None]
    kf = jnp.concatenate([k1, k2], 2)
    vf = jnp.concatenate([v1, v2], 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kf) * (D ** -0.5)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), vf)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # causal stats: row 0 attends 1 key -> l == 1, fully-unmasked rows
    # accumulate T keys' worth of mass
    oc, mc, lc = pk.flash_attention_stats(q, k1, v1, causal=True,
                                          block_q=32, block_k=32)
    assert np.allclose(np.asarray(lc)[..., 0], 1.0, atol=1e-5)
