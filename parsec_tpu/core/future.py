"""Futures: base, countable, and datacopy (lazy-trigger) futures.

Reference behavior: parsec_future_t / parsec_countable_future_t /
parsec_datacopy_future_t (ref: parsec/class/parsec_future.h:62-105,
parsec/class/parsec_datacopy_future.c:1-319). The datacopy future is the
substrate of the reshape engine: it is *triggered* lazily by the first
consumer, runs a conversion callback once, dedups concurrent triggers, and
cleans up the payload when released.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from .object import Obj


class Future(Obj):
    """Single-assignment future with completion callbacks."""

    def __init__(self) -> None:
        super().__init__()
        self._cond = threading.Condition()
        self._ready = False
        self._value: Any = None
        self._cbs: List[Callable[["Future"], None]] = []

    def is_ready(self) -> bool:
        return self._ready

    def set(self, value: Any) -> None:
        with self._cond:
            assert not self._ready, "future set twice"
            self._value = value
            self._ready = True
            cbs, self._cbs = self._cbs, []
            self._cond.notify_all()
        for cb in cbs:
            cb(self)

    def get(self, timeout: Optional[float] = None) -> Any:
        with self._cond:
            if not self._cond.wait_for(lambda: self._ready, timeout=timeout):
                raise TimeoutError("future wait timed out")
            return self._value

    def peek(self) -> Any:
        return self._value if self._ready else None

    def on_ready(self, cb: Callable[["Future"], None]) -> None:
        run = False
        with self._cond:
            if self._ready:
                run = True
            else:
                self._cbs.append(cb)
        if run:
            cb(self)


class CountableFuture(Future):
    """Completes when ``count`` contributions have arrived."""

    def __init__(self, count: int) -> None:
        super().__init__()
        assert count > 0
        self._count = count

    def contribute(self, value: Any = None) -> bool:
        with self._cond:
            assert self._count > 0
            self._count -= 1
            done = self._count == 0
        if done:
            self.set(value)
        return done


class DataCopyFuture(Future):
    """Lazily-triggered future holding a (converted) data copy.

    ``trigger_cb(spec)`` builds the payload on first request; concurrent
    requesters dedup on the started flag; ``cleanup_cb`` runs at destruct.
    A nested future chain is supported: if trigger returns another
    DataCopyFuture, completion is forwarded (matches the reference's
    chained reshape promises).
    """

    def __init__(self, spec: Any = None,
                 trigger_cb: Optional[Callable[[Any], Any]] = None,
                 cleanup_cb: Optional[Callable[[Any], None]] = None) -> None:
        super().__init__()
        self.spec = spec
        self._trigger_cb = trigger_cb
        self._cleanup_cb = cleanup_cb
        self._started = False

    def trigger(self) -> None:
        """First caller runs the conversion; everyone else just waits."""
        with self._cond:
            if self._started or self._ready:
                return
            self._started = True
        assert self._trigger_cb is not None, "untriggerable datacopy future"
        result = self._trigger_cb(self.spec)
        if isinstance(result, DataCopyFuture):
            result.on_ready(lambda f: self.set(f.peek()))
            result.trigger()
        else:
            self.set(result)

    def get_or_trigger(self, timeout: Optional[float] = None) -> Any:
        self.trigger()
        return self.get(timeout=timeout)

    def _destruct(self) -> None:
        if self._cleanup_cb is not None and self._ready:
            self._cleanup_cb(self._value)
        super()._destruct()
