"""profiling subpackage."""
