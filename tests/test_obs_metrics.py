"""MetricsRegistry + Prometheus exposition: histograms, the strict
line-format parser, mempool accounting gauges, the aggregator's
/metrics HTTP endpoint, and the context-level metrics switch."""
import math
import socket

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.obs import (MetricsRegistry, parse_exposition, render,
                            sanitize_name)
from parsec_tpu.obs.prometheus import fleet_to_prometheus


def test_histogram_buckets_and_mean():
    m = MetricsRegistry()
    h = m.histogram("PARSEC::TEST::LAT", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.05, 0.5):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(0.5555)
    # cumulative: <=1ms: 1, <=10ms: 2, <=100ms: 3, +Inf: 4
    assert [c for _le, c in snap["buckets"]] == [1, 2, 3, 4]
    assert math.isinf(snap["buckets"][-1][0])
    assert h.mean() == pytest.approx(0.5555 / 4)


def test_sanitize_name():
    assert sanitize_name("PARSEC::COMM::BYTES_SENT") == "parsec_comm_bytes_sent"
    assert sanitize_name("PARSEC::DEVICE::cpu:0::MEM_USED") == \
        "parsec_device_cpu_0_mem_used"
    assert sanitize_name("9bad") == "m_9bad"


def test_render_parses_and_roundtrips_values():
    m = MetricsRegistry()
    m.inc("PARSEC::COMM::BYTES_SENT", 4096)
    m.gauge("PARSEC::SCHEDULER::PENDING_TASKS", lambda: 3)
    m.histogram("PARSEC::TASK::EXEC_SECONDS",
                buckets=(0.01, 1.0)).observe(0.5)
    text = render(m, labels={"rank": "2"})
    samples = parse_exposition(text)  # the line-format check
    lbl = (("rank", "2"),)
    assert samples[("parsec_comm_bytes_sent", lbl)] == 4096
    assert samples[("parsec_scheduler_pending_tasks", lbl)] == 3
    assert samples[("parsec_task_exec_seconds_count", lbl)] == 1
    assert samples[("parsec_task_exec_seconds_sum", lbl)] == 0.5
    assert samples[("parsec_task_exec_seconds_bucket",
                    (("le", "+Inf"), ("rank", "2")))] == 1
    assert samples[("parsec_task_exec_seconds_bucket",
                    (("le", "0.01"), ("rank", "2")))] == 0
    # counter vs gauge typing comes from the SDE owned/poll split
    assert "# TYPE parsec_comm_bytes_sent counter" in text
    assert "# TYPE parsec_scheduler_pending_tasks gauge" in text


def test_render_cross_kind_collision_single_type():
    """A name owned as a counter in one registry and polled as a gauge
    in another must expose exactly once (duplicate # TYPE lines make
    Prometheus reject the whole scrape)."""
    from parsec_tpu.profiling.sde import SDERegistry
    m = MetricsRegistry()
    m.inc("PARSEC::X", 7)
    extra = SDERegistry()
    extra.register_poll("PARSEC::X", lambda: 99)
    text = render(m, extra_sde=extra)
    assert text.count("# TYPE parsec_x ") == 1
    assert parse_exposition(text)[("parsec_x", ())] == 7  # counter wins


@pytest.mark.parametrize("bad", [
    "no_value_here",
    "1leading_digit 5",
    'metric{unterminated="x} 1',
    "# BOGUS comment kind",
    "name{a=1} 2",           # unquoted label value
])
def test_parse_exposition_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_exposition(bad + "\n")


def test_mempool_named_gauges_and_highwater():
    from parsec_tpu.core.mempool import Mempool
    from parsec_tpu.profiling.sde import sde
    pool = Mempool(lambda: np.empty((4,), np.float32), name="test_scratch")
    try:
        a = pool.allocate()
        b = pool.allocate()
        assert pool.nb_allocs == 2 and pool.nb_hits == 0
        assert pool.outstanding_hwm == 2
        pool.free(a)
        assert pool.nb_outstanding == 1
        c = pool.allocate()   # freelist hit
        assert pool.nb_hits == 1
        assert sde.read("PARSEC::MEMPOOL::TEST_SCRATCH::ALLOCS") == 3
        assert sde.read("PARSEC::MEMPOOL::TEST_SCRATCH::OUTSTANDING_HWM") == 2
        assert sde.read("PARSEC::MEMPOOL::TEST_SCRATCH::OUTSTANDING") == 2
        pool.free(b)
        pool.free(c)
        assert sde.read("PARSEC::MEMPOOL::TEST_SCRATCH::OUTSTANDING") == 0
        # only two elements were ever constructed (c reused a's slot)
        assert sde.read("PARSEC::MEMPOOL::TEST_SCRATCH::CACHED") == 2
        assert sde.read("PARSEC::MEMPOOL::TEST_SCRATCH::CONSTRUCTED") == 2
        # the gauges hold only WEAK refs to the pool (a strong ref would
        # pin every cached buffer for the process lifetime)
        import weakref
        wr = weakref.ref(pool)
        del a, b, c
    finally:
        pool.unregister_gauges()
    assert "PARSEC::MEMPOOL::TEST_SCRATCH::ALLOCS" not in sde.names()
    del pool
    import gc
    gc.collect()
    assert wr() is None, "SDE gauges kept the pool alive"


def test_mempool_gauges_visible_in_context_exposition():
    """Named-pool gauges live on the process-global registry but must
    surface through the per-context exposition (guide §9.1 table)."""
    from parsec_tpu.core.mempool import Mempool
    pool = Mempool(lambda: np.empty((4,), np.float32), name="ctx_vis")
    try:
        pool.free(pool.allocate())
        ctx = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
        try:
            text = ctx.obs.render_prometheus(labels={"rank": "0"})
        finally:
            ctx.fini()
        samples = parse_exposition(text)
        assert samples[("parsec_mempool_ctx_vis_allocs",
                        (("rank", "0"),))] == 1
    finally:
        pool.unregister_gauges()


def test_aggregator_http_metrics_endpoint():
    from parsec_tpu.profiling.aggregator import AggregatorServer
    srv = AggregatorServer("127.0.0.1", 0).start()
    try:
        srv._ingest({"rank": 0, "ts": 1.0,
                     "counters": {"PARSEC::TASKS_RETIRED": 11}})
        srv._ingest({"rank": 1, "ts": 1.0,
                     "counters": {"PARSEC::TASKS_RETIRED": 31}})
        with socket.create_connection((srv.host, srv.port), timeout=5) as s:
            s.sendall(b"GET /metrics HTTP/1.0\r\nHost: x\r\n\r\n")
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        head, _, body = buf.partition(b"\r\n\r\n")
        assert b"200 OK" in head
        samples = parse_exposition(body.decode())
        assert samples[("parsec_tasks_retired", (("rank", "0"),))] == 11
        assert samples[("parsec_tasks_retired", (("rank", "1"),))] == 31
        # the same body parses as what fleet_to_prometheus renders
        assert body.decode() == fleet_to_prometheus(srv.fleet())
    finally:
        srv.stop()


def test_context_metrics_param_without_profile():
    """metrics=1 alone (no trace capture) feeds the task-latency
    histogram and renders parseable exposition; the PINS sites go quiet
    again after fini."""
    from parsec_tpu.profiling.pins import pins_is_active
    parsec_tpu.params.set_cmdline("metrics", "1")
    try:
        ctx = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    finally:
        parsec_tpu.params.unset_cmdline("metrics")
    try:
        assert ctx.obs.enabled and ctx.profile is None
        tp = parsec_tpu.dtd.taskpool_new()
        ctx.add_taskpool(tp)
        for _ in range(4):
            tp.insert_task(lambda es, task: None)
        tp.wait()
        hist = ctx.metrics.histogram("PARSEC::TASK::EXEC_SECONDS")
        assert hist.count >= 4
        parse_exposition(ctx.obs.render_prometheus(labels={"rank": "0"}))
    finally:
        ctx.fini()
    assert not pins_is_active()


def test_context_disabled_fast_path():
    """Without profile/metrics the engine gets NO span sink (the
    one-attribute fast path) while pull gauges still answer."""
    from parsec_tpu.comm import LocalFabric, RemoteDepEngine
    fabric = LocalFabric(1)
    eng = RemoteDepEngine(fabric.engine(0))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
    try:
        assert not ctx.obs.enabled
        assert eng.ce._obs is None
        assert all(dev._obs is None for dev in ctx.devices)
        assert ctx.sde.read("PARSEC::COMM::PENDING_MESSAGES") == 0
        assert "PARSEC::COMM::ACTIVATES_SENT" in ctx.sde.snapshot()
        assert any(n.startswith("PARSEC::DEVICE::") for n in ctx.sde.names())
    finally:
        ctx.fini()


def test_device_pipeline_gauges_in_exposition():
    """The batched-dispatch pipeline gauges (guide §9.1: batch
    occupancy, prefetch hit rate, dispatch us/task) must surface in the
    Prometheus exposition after a dpotrf run, with live values."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    from parsec_tpu.utils.params import params

    with params.cmdline_override("device_tpu_max", "1"):
        ctx = parsec_tpu.Context(nb_cores=2)
        try:
            M = make_spd(192)
            A = TwoDimBlockCyclic(192, 192, 32, 32,
                                  dtype=np.float32).from_numpy(M)
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            text = ctx.obs.render_prometheus(labels={"rank": "0"})
        finally:
            ctx.fini()
    samples = parse_exposition(text)
    rows = {n for (n, _l) in samples}
    for want in ("batch_occupancy", "prefetch_hit_rate", "dispatch_us"):
        assert any(n.startswith("parsec_device_") and n.endswith(want)
                   for n in rows), (want, sorted(rows))
    occ = [v for (n, _l), v in samples.items()
           if n.startswith("parsec_device_") and n.endswith("batch_occupancy")]
    assert max(occ) >= 2.0, f"dpotrf run never batched: occupancy={occ}"
    disp = [v for (n, _l), v in samples.items()
            if n.startswith("parsec_device_") and n.endswith("dispatch_us")]
    assert max(disp) > 0.0


def test_mesh_gauges_in_exposition():
    """A mesh-device run (device_mesh_shape; ISSUE 6) must surface the
    MESH_SHARDS / COLLECTIVE_BYTES / MESH_DISPATCHES gauges live in the
    Prometheus exposition — the mesh's health is measurable, not
    inferred."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    from parsec_tpu.parallel.mesh import has_shard_map
    from parsec_tpu.utils.params import params

    if not has_shard_map():
        pytest.skip("no shard_map spelling in this jax build")
    with params.cmdline_override("device_mesh_shape", "2x2"):
        ctx = parsec_tpu.Context(nb_cores=2)
        try:
            assert ctx.device_mesh is not None
            M = make_spd(192)
            A = TwoDimBlockCyclic(192, 192, 32, 32,
                                  dtype=np.float32).from_numpy(M)
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            text = ctx.obs.render_prometheus(labels={"rank": "0"})
        finally:
            ctx.fini()
    samples = parse_exposition(text)

    def vals(suffix):
        return [v for (n, _l), v in samples.items()
                if n.startswith("parsec_device_") and n.endswith(suffix)]

    shards = vals("mesh_shards")
    assert shards and max(shards) == 4.0, (shards, sorted(
        n for (n, _l) in samples if n.startswith("parsec_device_")))
    assert max(vals("mesh_dispatches")) > 0.0
    assert max(vals("mesh_tasks")) >= 4.0
    # collective_bytes counts intra-mesh dependency hops; a block-
    # cyclic dpotrf always reads panels across chip rows
    assert max(vals("collective_bytes")) > 0.0


def test_per_codec_compress_ratio_gauges_in_exposition():
    """ISSUE 14 satellite: COMPRESS_RATIO is labeled per codec and per
    link — ``PARSEC::COMM::COMPRESS_RATIO::R<peer>::<codec>`` — so
    lossless-vs-quantized engagement is distinguishable in /metrics.
    Both families must be LIVE on one link: the zlib row moves below
    raw bytes when compression engages, the qint8 row moves above 1
    when quantization does; codecs that never engaged read 1.0."""
    import concurrent.futures as cf
    import time as _time

    from parsec_tpu.obs import CommObs
    from parsec_tpu.comm.tcp import TCPCommEngine, free_ports

    ports = free_ports(2)
    eps = [("127.0.0.1", p) for p in ports]
    with cf.ThreadPoolExecutor(2) as ex:
        e0, e1 = list(ex.map(
            lambda r: TCPCommEngine(
                r, eps, chunk_bytes=1 << 16, quantize="int8",
                compress_threshold_mbps=10 ** 7),
            range(2)))
    try:
        m = MetricsRegistry()
        obs = CommObs(m)
        obs.register_engine_gauges(e0)
        got = []
        e1.tag_register(900, lambda src, p: got.append(p))
        peer = e0._peer_to(1)
        deadline = _time.time() + 10
        while _time.time() < deadline:
            with peer.cond:
                if peer.qz_codec and peer.codec:
                    break
            _time.sleep(0.005)
        # quantized leg: bulk float marked eligible
        arr = np.random.RandomState(17).rand(1 << 15)
        e0.send_am(1, 900, {"arr": arr, "_qz_ok": True})
        # lossless-compression leg: compressible ctrl payload repeated
        # (rep 1 samples the bandwidth EWMA, later reps compress)
        z = np.zeros(1 << 15)
        for rep in range(3):
            e0.send_am(1, 900, {"z": z, "rep": rep})
        deadline = _time.time() + 30
        while len(got) < 4 and _time.time() < deadline:
            if not e1.progress():
                _time.sleep(0.0005)
        assert len(got) == 4
        text = render(m, labels={"rank": "0"})
    finally:
        e0.fini()
        e1.fini()
    samples = parse_exposition(text)

    def val(name):
        hits = [v for (n, _l), v in samples.items() if n == name]
        assert hits, (name, sorted(n for (n, _l) in samples
                                   if "compress" in n))
        return hits[0]

    # both families live on the SAME link, distinguishable by label
    assert val("parsec_comm_compress_ratio_r1_qint8") > 1.0
    assert val("parsec_comm_compress_ratio_r1_zlib") > 1.0
    # a codec that never engaged reads the 1.0 idle value
    assert val("parsec_comm_compress_ratio_r1_qbf16") == 1.0


def test_overlap_gauges_in_exposition():
    """ISSUE 7 acceptance: the live OVERLAP_FRACTION / EXPOSED_COMM_US
    gauges and the prefetch/segment counters must surface in the
    Prometheus exposition during a dpotrf run — the overlap pipeline's
    health is measurable while it runs, not only in the offline
    critpath report."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.comm import LocalFabric, RemoteDepEngine
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    from parsec_tpu.utils.params import params

    with params.cmdline_override("metrics", "1"), \
         params.cmdline_override("device_tpu_max", "1"), \
         params.cmdline_override("device_flush_segments", "4"):
        fab = LocalFabric(1)
        eng = RemoteDepEngine(fab.engine(0))
        ctx = parsec_tpu.Context(nb_cores=2, comm=eng)
        try:
            M = make_spd(256)
            A = TwoDimBlockCyclic(256, 256, 32, 32,
                                  dtype=np.float32).from_numpy(M)
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            text = ctx.obs.render_prometheus(labels={"rank": "0"})
        finally:
            ctx.fini()
    samples = parse_exposition(text)

    def val(name):
        got = [v for (n, _l), v in samples.items() if n == name]
        assert got, (name, sorted(n for (n, _l) in samples))
        return got[0]

    frac = val("parsec_obs_overlap_fraction")
    assert 0.0 <= frac <= 1.0
    assert val("parsec_obs_exposed_comm_us") >= 0.0
    # the segment counters prove the pipelined flush path really ran
    segd = [v for (n, _l), v in samples.items()
            if n.startswith("parsec_device_")
            and n.endswith("segmented_flushes")]
    segs = [v for (n, _l), v in samples.items()
            if n.startswith("parsec_device_")
            and n.endswith("flush_segments")]
    assert segd and max(segd) > 0.0, "dpotrf run never segmented a flush"
    assert segs and max(segs) >= 2 * max(segd)
    # prefetched-GET outcomes are distinct gauges (a single-rank run
    # never prefetches — the live >0 case rides test_overlap_pipeline)
    for suffix in ("gets", "hits", "misses", "cancels"):
        assert val(f"parsec_comm_prefetch_{suffix}") == 0.0


def test_flow_and_clock_gauges_in_exposition():
    """ISSUE 15 acceptance: the FLOW_SENT/FLOW_RECV counters and the
    per-peer CLOCK_OFFSET_US gauges surface in the Prometheus
    exposition during a flow-traced 2-rank run."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.comm import LocalFabric, RemoteDepEngine
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    from parsec_tpu.utils.params import params
    from tests.conftest import spmd

    n, nb, ranks = 128, 32, 2
    M = make_spd(n, dtype=np.float32)
    with params.cmdline_override("metrics", "1"), \
            params.cmdline_override("obs_flow", "1"), \
            params.cmdline_override("comm_mesh_local", "0"):
        def rank_fn(r, fab):
            eng = RemoteDepEngine(fab.engine(r))
            ctx = parsec_tpu.Context(nb_cores=1, comm=eng)
            try:
                coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32,
                                         P=ranks, Q=1, nodes=ranks,
                                         rank=r)
                coll.name = "descA"
                coll.from_numpy(M.copy())
                ctx.add_taskpool(dpotrf_taskpool(coll, rank=r,
                                                 nb_ranks=ranks))
                ctx.wait()
                return ctx.obs.render_prometheus(
                    labels={"rank": str(r)})
            finally:
                ctx.fini()
        texts, _fab = spmd(ranks, rank_fn)
    total_sent = total_recv = 0.0
    for r, text in enumerate(texts):
        samples = parse_exposition(text)

        def val(name, samples=samples):
            got = [v for (n_, _l), v in samples.items() if n_ == name]
            assert got, name
            return got[0]

        total_sent += val("parsec_obs_flow_sent")
        total_recv += val("parsec_obs_flow_recv")
        # the per-peer clock gauge exists (same-clock fabric: 0.0)
        assert val(f"parsec_obs_clock_offset_us_r{1 - r}") == 0.0
    assert total_sent > 0, "flow tracing never stamped a message"
    assert total_sent == total_recv, (total_sent, total_recv)
