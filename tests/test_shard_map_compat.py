"""Direct coverage for the parallel/mesh.py shard_map seams (ISSUE 6
satellite): axis-name plumbing through ``shard_map_compat``, the
``vary_on`` / ``match_vma`` VMA-promotion helpers, and the forward-only
``shard_map_fwd`` fallback the mesh device dispatches through.

Module-level skip on jax builds without the VMA-tracking
``jax.shard_map`` (the PR-5 pattern from test_parallel): the compat
wrapper deliberately refuses the ``jax.experimental`` spelling because
it transposes psum differently — gradients would be silently wrong.
``shard_map_fwd`` / ``has_shard_map`` get their no-VMA coverage in
test_device_mesh.py, which runs on either spelling.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

if not hasattr(jax, "shard_map"):
    pytest.skip("jax.shard_map (VMA tracking) not available in this jax",
                allow_module_level=True)

from parsec_tpu.parallel import make_mesh, shard_map_compat  # noqa: E402
from parsec_tpu.parallel.mesh import (has_shard_map, match_vma,  # noqa: E402
                                      shard_map_fwd, vary_on)


def _mesh22():
    return make_mesh(sizes={"tp": 2, "sp": 2},
                     devices=jax.devices("cpu")[:4])


def test_has_shard_map_true_here():
    assert has_shard_map()


def test_axis_name_plumbing_psum_per_axis():
    """psum inside the compat wrapper must see the mesh's axis names
    and reduce over EXACTLY the named axis — 'tp' sums pairs of
    tp-shards, 'sp' sums pairs of sp-shards."""
    mesh = _mesh22()
    x = np.arange(16, dtype=np.float32).reshape(4, 4)

    def body_tp(xs):
        return jax.lax.psum(xs, "tp")

    # psum over tp leaves the value tp-replicated, so dim0 comes back
    # unsharded: each (2, 2) block summed with the other tp row's
    f = shard_map_compat(body_tp, mesh,
                         in_specs=P("tp", "sp"), out_specs=P(None, "sp"))
    got = np.asarray(f(jnp.asarray(x)))
    np.testing.assert_allclose(got, x[:2] + x[2:])

    def body_sp(xs):
        return jax.lax.psum(xs, "sp")

    g = shard_map_compat(body_sp, mesh,
                         in_specs=P("tp", "sp"), out_specs=P("tp", None))
    np.testing.assert_allclose(np.asarray(g(jnp.asarray(x))),
                               x[:, :2] + x[:, 2:])


def test_replicated_output_spec():
    """P() output must come out identical on every shard (a full
    reduction over both axes)."""
    mesh = _mesh22()
    x = np.arange(8, dtype=np.float32)

    def body(xs):
        return jax.lax.psum(xs.sum(), ("tp", "sp"))

    f = shard_map_compat(body, mesh,
                         in_specs=P(("tp", "sp")), out_specs=P())
    assert float(f(jnp.asarray(x))) == float(x.sum())


def test_vary_on_promotes_scan_carry():
    """A fresh-zeros scan carry is 'unvarying' under check_vma while
    the loop body makes it varying; vary_on must promote it so the
    scan's carry types match (the ring-attention/pipeline pattern)."""
    mesh = make_mesh(sizes={"sp": 4}, devices=jax.devices("cpu")[:4])
    x = np.arange(16, dtype=np.float32)

    def body(xs):
        acc0 = vary_on(jnp.zeros((), jnp.float32), ("sp",), like=xs)

        def step(acc, v):
            return acc + v, acc

        acc, _ = jax.lax.scan(step, acc0, xs)
        return jax.lax.psum(acc, "sp")

    f = shard_map_compat(body, mesh, in_specs=P("sp"), out_specs=P())
    assert float(f(jnp.asarray(x))) == float(x.sum())


def test_match_vma_promotes_to_reference():
    """match_vma must lift a constant to the reference's varying axes
    (and be the identity on values) so mixed carries scan cleanly."""
    mesh = make_mesh(sizes={"sp": 4}, devices=jax.devices("cpu")[:4])
    x = np.arange(8, dtype=np.float32).reshape(4, 2)

    def body(xs):
        m0 = match_vma(jnp.full((2,), -1.0, jnp.float32), xs)

        def step(m, row):
            return jnp.maximum(m, row), ()

        m, _ = jax.lax.scan(step, m0, xs)
        return jax.lax.pmax(m, "sp")

    f = shard_map_compat(body, mesh, in_specs=P("sp", None), out_specs=P())
    np.testing.assert_allclose(np.asarray(f(jnp.asarray(x))),
                               x.max(axis=0))


def test_match_vma_identity_outside_tracing():
    """Outside a shard_map trace there is no VMA to match: both helpers
    must be value-identity no-ops."""
    x = jnp.ones((3,))
    assert match_vma(x, x) is x
    np.testing.assert_allclose(np.asarray(vary_on(x, ())), np.asarray(x))


def test_grad_of_replicated_leaf_is_presummed():
    """The reason shard_map_compat insists on check_vma: jax.grad of a
    REPLICATED leaf through a psum'd forward must come out already
    summed over the axes its contributions were partial on."""
    mesh = make_mesh(sizes={"sp": 4}, devices=jax.devices("cpu")[:4])
    x = np.arange(4, dtype=np.float32) + 1.0

    def loss(w, xs):
        def body(w, xs):
            return jax.lax.psum((w * xs).sum(), "sp")
        f = shard_map_compat(body, mesh,
                             in_specs=(P(), P("sp")), out_specs=P())
        return f(w, xs)

    g = jax.grad(loss)(jnp.float32(2.0), jnp.asarray(x))
    # d/dw sum(w * x) = sum(x), gathered across every shard exactly once
    np.testing.assert_allclose(float(g), float(x.sum()), rtol=1e-6)


def test_shard_map_fwd_matches_compat_forward():
    """The forward-only seam must produce the same forward values as
    the compat wrapper on builds where both exist (the fallback only
    ever changes grad transposition, which dispatch never uses)."""
    mesh = _mesh22()
    x = np.arange(16, dtype=np.float32)

    def body(xs):
        return xs * 2.0

    a = shard_map_compat(body, mesh, in_specs=P(("tp", "sp")),
                         out_specs=P(("tp", "sp")))(jnp.asarray(x))
    b = shard_map_fwd(body, mesh, in_specs=P(("tp", "sp")),
                      out_specs=P(("tp", "sp")))(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
