"""utils subpackage."""
