#!/usr/bin/env python
"""parsec_lint — static analysis over JDF specs, DTD bodies, and the
parsec_tpu runtime source (the parsec_ptgpp sanity-check battery, run
as a linter; see parsec_tpu/analysis/).

Three passes:

1. PTG/JDF dataflow verification (PTG1xx) over every ``*_JDF`` string
   constant found in the target files — endpoint existence/direction,
   arity, dependency reciprocity, unused globals/locals, unsatisfiable
   guards, and cycle detection by enumerating a small concrete
   instantiation (tools/dagenum.py).
2. Batch/donation-safety lint (BDY2xx) over the same specs' accelerator
   BODY code — predicts the device layer's per-class trace-time
   downgrades (this_task, untraceable constructs, nondeterminism,
   aliased tiles) before the first run.
3. Concurrency lint (LCK3xx) over modules declaring a ``_GUARDED_BY``
   map — guarded fields only under their lock, no blocking calls while
   holding an engine/data lock.

Default targets: parsec_tpu/ops, examples/ (spec passes) and
parsec_tpu/ (concurrency pass).  ``--strict`` exits non-zero on any
error/warn finding — the tier-1 self-lint gate (tests/test_analysis.py)
runs exactly that over the repo.

    python tools/parsec_lint.py --strict
    python tools/parsec_lint.py path/to/specs.py --no-cycles
"""
from __future__ import annotations

import argparse
import ast as pyast
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from parsec_tpu.analysis import Finding, gate  # noqa: E402
from parsec_tpu.analysis import body_check, lock_check, ptg_check  # noqa: E402


def find_jdf_specs(path: str) -> List[Tuple[str, int, str]]:
    """Module-level ``NAME_JDF = \"...\"`` string constants in a .py
    file: [(spec_name, assign_lineno, text)]."""
    with open(path) as fh:
        src = fh.read()
    try:
        tree = pyast.parse(src)
    except SyntaxError:
        return []
    out = []
    for node in tree.body:
        if not isinstance(node, pyast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (isinstance(t, pyast.Name) and t.id.endswith("_JDF")):
            continue
        if isinstance(node.value, pyast.Constant) and \
                isinstance(node.value.value, str):
            out.append((t.id, node.value.lineno, node.value.value))
    return out


def lint_spec_text(text: str, name: str,
                   enum_env: Optional[Dict[str, Any]] = None,
                   cycles: bool = True) -> List[Finding]:
    """All spec passes over one JDF text: dataflow verification, body
    lint, cycle enumeration.  The text is parsed once and the AST shared
    across every pass."""
    from parsec_tpu.dsl.ptg.parser import JDFParseError, parse_jdf
    try:
        jdf = parse_jdf(text, name=name)
    except (JDFParseError, SyntaxError):
        # unparseable: verify_jdf_text re-parses only to classify the
        # failure into a PTG100/PTG101 finding (rare error path)
        return ptg_check.verify_jdf_text(text, name=name,
                                         enum_env=enum_env, cycles=cycles)
    findings = ptg_check.verify_jdf_text(text, name=name, enum_env=enum_env,
                                         cycles=cycles, jdf=jdf)
    findings.extend(body_check.check_jdf_bodies(jdf, name=name))
    return findings


def lint_spec_file(path: str, cycles: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    rel = os.path.relpath(path, _ROOT)
    for spec_name, lineno, text in find_jdf_specs(path):
        # pad so Expr origins ("file:line task.flow") carry TRUE file
        # line numbers: string line k sits at file line (lineno - 1 + k)
        padded = "\n" * (lineno - 1) + text
        findings.extend(lint_spec_text(padded, name=rel, cycles=cycles))
    return findings


#: default spec targets relative to the repo root
SPEC_DIRS = (os.path.join("parsec_tpu", "ops"), "examples")
#: default concurrency-lint target
SOURCE_DIR = "parsec_tpu"


def default_spec_files() -> List[str]:
    files: List[str] = []
    for d in SPEC_DIRS:
        full = os.path.join(_ROOT, d)
        if not os.path.isdir(full):
            continue
        for fn in sorted(os.listdir(full)):
            if fn.endswith(".py"):
                files.append(os.path.join(full, fn))
    return files


def collect_spec_files(paths: List[str]) -> Tuple[List[str], List[str]]:
    """Resolve CLI targets into ``(spec_files, lock_targets)`` — the
    one walker every pass (and ``--lower-report``) shares, so they can
    never disagree about which files a target covers.  No ``paths``
    means the shipped defaults."""
    spec_files: List[str] = []
    lock_targets: List[str] = []
    if paths:
        for p in paths:
            if os.path.isdir(p):
                lock_targets.append(p)
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"]
                    spec_files.extend(os.path.join(dirpath, f)
                                      for f in sorted(filenames)
                                      if f.endswith(".py"))
            else:
                spec_files.append(p)
                lock_targets.append(p)
    else:
        spec_files = default_spec_files()
        lock_targets = [os.path.join(_ROOT, SOURCE_DIR)]
    return spec_files, lock_targets


def run(paths: List[str], cycles: bool = True,
        locks: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    spec_files, lock_targets = collect_spec_files(paths)
    for f in spec_files:
        findings.extend(lint_spec_file(f, cycles=cycles))
    if locks:
        for t in lock_targets:
            if os.path.isdir(t):
                findings.extend(lock_check.lint_tree(t))
            elif t.endswith(".py"):
                lf = lock_check.lint_file(t)
                # avoid double-reporting files passed once
                findings.extend(x for x in lf if x not in findings)
    return findings


def _toy_taskpool(text: str, name: str, shared_colls: Dict[str, Any]):
    """A small concrete instantiation of one spec for per-stage
    planning (the dagenum enumeration env): int globals bind to the
    tile count, collection globals bind to dummy 4x4 holders SHARED by
    name across the file's specs — the chain planner proves dataflow
    by collection IDENTITY, exactly as sequential pools share real
    collections (dpotrf's descA is dtrsm's descL)."""
    from parsec_tpu.analysis.ptg_check import (_load_dagenum,
                                               default_enum_env)
    from parsec_tpu.dsl import ptg
    dagenum = _load_dagenum()
    factory = ptg.compile_jdf(text, name=name)
    env = default_enum_env(factory.jdf)
    for g in factory.jdf.globals:
        if g.properties.get("type") == "collection":
            env[g.name] = shared_colls.setdefault(
                g.name, dagenum._DummyCollection(4, 4))
    return factory.new(**env)


def lower_report_main(paths: List[str], quiet: bool = False) -> int:
    """``--lower-report``: the stage compiler's verdicts (stagec/plan —
    the SAME passes the runtime partitions with, so what this prints is
    what ``stage_compile`` will and won't fuse) over every ``*_JDF``
    spec in the targets:

    - per-CLASS lowerability (compilable / fallback + the reason);
    - per-STAGE partition of a small concrete instantiation (stage
      sizes, level spans, class mix, residue split + pre-planned
      residue groups);
    - for files holding several specs, the CHAIN verdict of each
      consecutive pair — fusable, or the chain-rejection reason two
      pools fail to fuse for (stagec/chain.boundary_verdict).

    Exit 0 always: the report is informational — residue classes run
    interpreted, unchained pools flush between stages; neither is an
    error."""
    from parsec_tpu.dsl.ptg.parser import JDFParseError, parse_jdf
    from parsec_tpu.stagec.plan import lower_report, plan_stages, \
        stage_report

    files, _lock_targets = collect_spec_files(paths)
    n_specs = 0
    for path in files:
        rel = os.path.relpath(path, _ROOT) if path.startswith(_ROOT) \
            else path
        shared_colls: Dict[str, Any] = {}
        planned = []   # [(spec_name, tp, StagePlan)] for chain verdicts
        for spec_name, _lineno, text in find_jdf_specs(path):
            n_specs += 1
            try:
                jdf = parse_jdf(text, name=f"{rel}:{spec_name}")
            except (JDFParseError, SyntaxError) as exc:
                print(f"{rel}:{spec_name}: unparseable ({exc})")
                continue
            for line in lower_report(jdf):
                print(line)
            try:
                tp = _toy_taskpool(text, spec_name, shared_colls)
                plan = _prepared_toy_plan(tp)
                for line in stage_report(tp, plan=plan):
                    print(line)
                planned.append((spec_name, tp, plan))
            except Exception as exc:  # noqa: BLE001 - informational
                print(f"  (stage partition not enumerable: "
                      f"{type(exc).__name__}: {exc})")
                continue
            try:
                for line in _xrank_column(text, spec_name):
                    print(line)
            except Exception as exc:  # noqa: BLE001 - informational
                print(f"  (xrank column not enumerable: "
                      f"{type(exc).__name__}: {exc})")
        # chain verdicts over consecutive specs of the same file (the
        # declared-sequence analog: dtrsm.py's FWD ; BWD), walking the
        # SAME cumulative segments declare_chain builds — a boundary is
        # proven against every pool already fused into the segment, so
        # the report cannot claim a cascade the runtime would reject
        from parsec_tpu.stagec.chain import _stage_verdict, \
            boundary_verdict
        seg = []   # [(tp, plan, fused member-key set)], host first
        for (na, tpa, pa), (nb_, tpb, pb) in zip(planned, planned[1:]):
            if not seg:
                if pa is None or not pa.stages:
                    print(f"  chain {na} -> {nb_}: rejected — no "
                          f"compilable final stage in the earlier pool")
                    continue
                seg = [(tpa, pa, set(pa.stages[-1].member_keys))]
            reason = boundary_verdict(seg, tpb, pb)
            if reason is None:
                # walk the fusable stage PREFIX exactly like
                # declare_chain (ISSUE 20a): stage 0 memory-fed,
                # later stages bound to already-fused producers
                fused_b, eavail_b = set(), set()
                n_fused = 0
                for (stage_k, layout_k, _prio) in pb.prepared:
                    if n_fused:
                        v = _stage_verdict(seg, tpb, pb, stage_k,
                                           layout_k, fused_b, eavail_b)
                        if isinstance(v, str):
                            break
                    n_fused += 1
                    fused_b |= stage_k.member_keys
                    eavail_b.update(layout_k.edge_outs)
                print(f"  chain {na} -> {nb_}: fusable "
                      f"({n_fused}/{len(pb.stages)} stage(s) "
                      f"in-program)")
                if n_fused == len(pb.stages):
                    seg.append((tpb, pb, fused_b))
                else:
                    seg = []   # segment ends; next pool hosts anew
            else:
                print(f"  chain {na} -> {nb_}: rejected — {reason}")
                seg = []
    if not quiet:
        print(f"parsec_lint --lower-report: {n_specs} spec(s)")
    return 0


def _xrank_column(text: str, spec_name: str) -> List[str]:
    """Cross-rank eligibility column (ISSUE 20 satellite): replay the
    spec over a 2-rank row-cyclic toy instantiation and run the SAME
    cross-rank planner pass the runtime uses (stagec/xrank.plan_xwaves)
    — one line per (level, class) wave: spanning ranks (participant
    and boundary-edge counts, collective kind) or the reason the wave
    stays rank-local."""
    from parsec_tpu.analysis.ptg_check import (_load_dagenum,
                                               default_enum_env)
    from parsec_tpu.dsl import ptg
    from parsec_tpu.stagec.plan import plan_stages
    from parsec_tpu.stagec.xrank import plan_xwaves
    from parsec_tpu.utils.params import params
    dagenum = _load_dagenum()

    class _TwoRankDummy(dagenum._DummyCollection):
        """Row-cyclic over 2 ranks, so every multi-row wave front has
        members on both — the eligibility question becomes purely
        structural (body/layout/boundary), like the runtime's."""

        def rank_of(self, *a) -> int:
            return int(a[0]) % 2 if a else 0

        def tile_shape(self, *a):
            return (4, 4)

    factory = ptg.compile_jdf(text, name=f"{spec_name}@2r")
    env = default_enum_env(factory.jdf)
    for g in factory.jdf.globals:
        if g.properties.get("type") == "collection":
            env[g.name] = _TwoRankDummy(4, 4)
    tp2 = factory.new(rank=0, nb_ranks=2, **env)
    max_tasks = int(params.get("stage_compile_max_tasks"))
    plan2 = plan_stages(tp2, rank=0, max_tasks=max_tasks,
                        wavefront=True)
    plan_xwaves(tp2, plan2, max_tasks)
    return [f"  xrank level {lv} {cls}: {txt}"
            for (lv, cls, txt) in plan2.xwave_report]


def _prepared_toy_plan(tp):
    """plan_stages + layouts for the chain verdict (mirrors the
    runtime's prepared_plan without the process-wide cache — toy pools
    are throwaway)."""
    from parsec_tpu.stagec.lower import build_layout
    from parsec_tpu.stagec.plan import plan_stages
    plan = plan_stages(tp)
    for stage in plan.stages:
        layout = build_layout(tp, plan, stage)
        plan.prepared.append((stage, layout, 0))
    return plan


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="static analysis over JDF specs and parsec_tpu source")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: shipped specs, "
                         "examples, and parsec_tpu/)")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on any error/warn finding")
    ap.add_argument("--no-cycles", action="store_true",
                    help="skip the (slower) cycle-enumeration pass")
    ap.add_argument("--no-locks", action="store_true",
                    help="skip the concurrency lint")
    ap.add_argument("--lower-report", action="store_true",
                    help="per-task-class stage-compile lowerability "
                         "report (stagec/, ISSUE 12): compilable / "
                         "fallback + the BDY2xx/PTG1xx/STG3xx reason "
                         "a class won't fuse")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no summary")
    args = ap.parse_args(argv)

    if args.lower_report:
        return lower_report_main(args.paths, quiet=args.quiet)
    findings = run(args.paths, cycles=not args.no_cycles,
                   locks=not args.no_locks)
    for f in findings:
        print(f)
    gating = gate(findings)
    if not args.quiet:
        notes = len(findings) - len(gating)
        print(f"parsec_lint: {len(gating)} finding(s)"
              + (f", {notes} note(s)" if notes else "")
              + (" [strict]" if args.strict else ""))
    return 1 if (args.strict and gating) else 0


if __name__ == "__main__":
    sys.exit(main())
