#!/usr/bin/env python
"""Dump/summarize binary .ptt traces (ref: tools/profiling/dbpreader.c,
dbp2xml.c).

    python tools/ptt_dump.py trace.rank0.ptt [more.ptt ...]
    python tools/ptt_dump.py --format xml trace.rank0.ptt
    python tools/ptt_dump.py --format json trace.rank0.ptt

``summary`` prints per-stream event counts and per-event-class interval
statistics (count, total/mean/max duration) the way dbpreader's report
does; ``xml`` mirrors dbp2xml's full event dump; ``json`` emits the raw
events for scripting.
"""
import argparse
import json
import os
import sys
from collections import defaultdict
from xml.sax.saxutils import escape, quoteattr

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.profiling.binfmt import read_profile  # noqa: E402


def intervals_of(stream):
    """Pair B/E events per key (LIFO nesting, like the dbp readers);
    complete ("X") events — comm/device spans — carry their own
    duration in info["dur_ns"]."""
    out = []
    open_ev = defaultdict(list)
    for ts, ph, key, info in stream.events:
        if ph == "B":
            open_ev[key].append((ts, info))
        elif ph == "E" and open_ev.get(key):
            b, binfo = open_ev[key].pop()
            out.append((key, b, ts, binfo))
        elif ph == "X":
            dur = (info or {}).get("dur_ns", 0)
            out.append((key, ts, ts + dur, info))
    return out


def cmd_summary(profiles, out=None):
    out = out or sys.stdout
    for path, prof in profiles:
        print(f"== {path}: rank {prof.rank}, {len(prof._streams)} streams, "
              f"{prof.nb_events()} events", file=out)
        for k, v in sorted(prof.info.items()):
            print(f"   info {k} = {v}", file=out)
        for tid, st in sorted(prof._streams.items()):
            stats = defaultdict(lambda: [0, 0, 0])  # count, total, max
            for key, b, e, _ in intervals_of(st):
                s = stats[key]
                s[0] += 1
                s[1] += e - b
                s[2] = max(s[2], e - b)
            counters = sum(1 for ev in st.events if ev[1] == "C")
            print(f"   stream {tid} ({st.name}): {len(st.events)} events, "
                  f"{counters} counter samples", file=out)
            for key in sorted(stats):
                c, tot, mx = stats[key]
                print(f"     {key:32s} n={c:6d} total={tot/1e6:10.3f}ms "
                      f"mean={tot/c/1e3:8.1f}us max={mx/1e3:8.1f}us",
                      file=out)


def cmd_xml(profiles, out=None):
    out = out or sys.stdout
    print('<?xml version="1.0"?>', file=out)
    print("<profiles>", file=out)
    for path, prof in profiles:
        print(f'  <profile file="{escape(path)}" rank="{prof.rank}">',
              file=out)
        for tid, st in sorted(prof._streams.items()):
            print(f'    <stream tid="{tid}" name="{escape(st.name)}">',
                  file=out)
            for ts, ph, key, info in st.events:
                attr = f" info={quoteattr(json.dumps(info))}" if info is not None else ""
                print(f'      <event ts="{ts}" ph="{ph}" '
                      f"key={quoteattr(key)}{attr}/>", file=out)
            print("    </stream>", file=out)
        print("  </profile>", file=out)
    print("</profiles>", file=out)


def cmd_json(profiles, out=None):
    out = out or sys.stdout
    doc = []
    for path, prof in profiles:
        doc.append({
            "file": path, "rank": prof.rank, "info": prof.info,
            "streams": [
                {"tid": tid, "name": st.name,
                 "events": [{"ts": ts, "ph": ph, "key": key, "info": info}
                            for ts, ph, key, info in st.events]}
                for tid, st in sorted(prof._streams.items())],
        })
    json.dump(doc, out, indent=1)
    out.write("\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+", help=".ptt trace files")
    ap.add_argument("--format", choices=["summary", "xml", "json"],
                    default="summary")
    args = ap.parse_args(argv)
    profiles = [(p, read_profile(p)) for p in args.paths]
    {"summary": cmd_summary, "xml": cmd_xml, "json": cmd_json}[args.format](
        profiles)
    return 0


if __name__ == "__main__":
    sys.exit(main())
