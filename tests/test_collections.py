"""Collection tests (mirrors reference tests/collections/: distribution
math, storage variants, kcyclic, band)."""
import numpy as np
import pytest

from parsec_tpu.collections import (DictCollection, LocalArrayCollection,
                                    SymTwoDimBlockCyclic, TiledMatrix,
                                    TwoDimBlockCyclic, TwoDimBlockCyclicBand,
                                    TwoDimTabular, VectorTwoDimCyclic,
                                    SymTwoDimBlockCyclicBand)


def test_tiled_matrix_geometry():
    A = TiledMatrix(100, 60, 32, 16)
    assert (A.mt, A.nt) == (4, 4)
    assert A.tile_shape(0, 0) == (32, 16)
    assert A.tile_shape(3, 3) == (4, 12)  # partial edge tiles
    assert len(list(A.tiles())) == 16


def test_tiled_roundtrip_numpy():
    A = TiledMatrix(48, 48, 16, 16, dtype=np.float64)
    M = np.arange(48 * 48, dtype=np.float64).reshape(48, 48)
    A.from_numpy(M)
    np.testing.assert_array_equal(A.to_numpy(), M)
    np.testing.assert_array_equal(A.tile(1, 2), M[16:32, 32:48])


def test_block_cyclic_rank_math():
    """2x2 grid, no k-cyclicity: classic round-robin both dims."""
    A = TwoDimBlockCyclic(64, 64, 8, 8, P=2, Q=2)
    assert A.nodes == 4
    assert A.rank_of(0, 0) == 0
    assert A.rank_of(0, 1) == 1
    assert A.rank_of(1, 0) == 2
    assert A.rank_of(1, 1) == 3
    assert A.rank_of(2, 2) == 0
    # every rank owns exactly 1/4 of the 8x8 tiles
    counts = {}
    for t in A.tiles():
        counts[A.rank_of(*t)] = counts.get(A.rank_of(*t), 0) + 1
    assert counts == {0: 16, 1: 16, 2: 16, 3: 16}


def test_block_cyclic_kcyclic():
    """krows=2: pairs of consecutive tile-rows land on the same P row."""
    A = TwoDimBlockCyclic(64, 64, 8, 8, P=2, Q=1, krows=2)
    assert A.rank_of(0, 0) == A.rank_of(1, 0) == 0
    assert A.rank_of(2, 0) == A.rank_of(3, 0) == 1
    assert A.rank_of(4, 0) == 0


def test_sym_storage_rejects_wrong_triangle():
    A = SymTwoDimBlockCyclic(64, 64, 16, 16, uplo="lower")
    assert len(list(A.tiles())) == 10  # 4x4 lower triangle incl diagonal
    A.data_of(2, 1)
    with pytest.raises(AssertionError):
        A.data_of(1, 2)


def test_sym_to_numpy_mirrors():
    A = SymTwoDimBlockCyclic(32, 32, 16, 16, uplo="lower")
    t = np.random.RandomState(0).rand(16, 16).astype(np.float32)
    A.set_tile(1, 0, t)
    M = A.to_numpy()
    np.testing.assert_allclose(M[16:32, 0:16], t)
    np.testing.assert_allclose(M[0:16, 16:32], t.T)


def test_band_distribution():
    A = TwoDimBlockCyclicBand(64, 64, 8, 8, band_size=2, P=2, Q=2)
    assert A.in_band(3, 3) and A.in_band(3, 4) and not A.in_band(3, 5)
    with pytest.raises(AssertionError):
        A.data_of(0, 5)
    assert all(abs(m - n) < 2 for m, n in A.tiles())


def test_tabular_distribution():
    A = TwoDimTabular.random(32, 32, 8, 8, nodes=3, seed=42)
    for (m, n) in A.tiles():
        assert 0 <= A.rank_of(m, n) < 3
    # table is what rank_of reports
    assert A.rank_of(1, 2) == A.rank_table[1, 2]


def test_vector_cyclic():
    v = VectorTwoDimCyclic(100, 10, P=4)
    assert v.mt == 10
    assert [v.rank_of(k) for k in range(8)] == [0, 1, 2, 3, 0, 1, 2, 3]
    d = v.data_of(3)
    assert d.get_copy(0).payload.shape == (10, 1)


def test_dict_collection_remote_entries():
    c = DictCollection(nodes=2, rank=0)
    c.add("x", 0, np.zeros(3))
    c.add("y", 1)  # remote, no local payload
    assert c.rank_of("x") == 0 and c.rank_of("y") == 1
    with pytest.raises(KeyError):
        c.data_of("y")


def test_local_array_collection_views_alias():
    base = np.zeros((8, 2))
    c = LocalArrayCollection(base, 4)
    d = c.data_of(1)
    d.get_copy(0).payload[:] = 7.0
    assert np.all(base[2:4] == 7.0)  # tiles are views, not copies


def test_sym_band_collection():
    """Band + triangular storage (ref: sym_two_dim_rectangle_cyclic_band)."""
    A = SymTwoDimBlockCyclicBand(8 * 8, 8 * 8, 8, 8, band_size=2,
                                 uplo="lower", P=2, Q=1, nodes=2)
    ts = list(A.tiles())
    # lower-triangular AND within the band
    assert all(n <= m and m - n < 2 for (m, n) in ts)
    assert (3, 2) in ts and (3, 3) in ts
    assert (3, 0) not in ts and (2, 3) not in ts
    # distribution math still block-cyclic over P
    assert A.rank_of(2, 2) != A.rank_of(3, 3)
    with pytest.raises(AssertionError):
        A.data_of(7, 0)
