"""serve/ (ISSUE 18): multi-tenant persistent serving.

Covers the wire envelopes, local admission + submission lifecycle on a
persistent context, the remote ServeClient <-> SessionServer path over
an in-process AM fabric, tenant-stamped flow contexts feeding the
cross-rank tooling, per-tenant live-health attribution, and the
knob-unset inertness contract (no server constructed = nothing changes).
"""
import threading
import time

import pytest

import parsec_tpu
from parsec_tpu import dtd
from parsec_tpu.comm import LocalFabric, wire
from parsec_tpu.comm.engine import FlowIds, TAG_ACTIVATE
from parsec_tpu.dsl.dtd import VALUE
from parsec_tpu.obs import (CommObs, MetricsRegistry, analyze,
                            load_flow_events, merge_trace_docs,
                            stitch_flows)
from parsec_tpu.obs.live import LiveHealth, fleet_health, format_health
from parsec_tpu.obs.spans import (SERVE_INFLIGHT_PREFIX,
                                  SERVE_P99_LATENCY_PREFIX,
                                  SERVE_QUOTA_BYTES_PREFIX, SERVE_TENANTS)
from parsec_tpu.profiling.trace import Profile
from parsec_tpu.serve import (AdmissionError, ServeClient, SessionServer)
from parsec_tpu.utils.params import params


# ---------------------------------------------------------------------- #
# wire envelopes                                                         #
# ---------------------------------------------------------------------- #
def test_serve_envelope_roundtrip():
    req = wire.serve_request("submit", 7, tenant="acme", ntasks=3)
    assert wire.parse_serve(req) is req
    assert req["op"] == "submit" and req["req"] == 7
    assert req["tenant"] == "acme" and req["ntasks"] == 3
    rep = wire.serve_reply(7, True, ticket=12)
    assert wire.parse_serve(rep)["ok"] is True
    assert rep["ticket"] == 12 and rep["sv"] == wire.SERVE_PROTO_VERSION


def test_serve_envelope_rejects_malformed():
    with pytest.raises(ValueError):
        wire.parse_serve(b"not a dict")
    with pytest.raises(ValueError):
        wire.parse_serve({"op": "open", "req": 1})        # no version
    with pytest.raises(ValueError):
        wire.parse_serve({"sv": wire.SERVE_PROTO_VERSION + 1, "req": 1})
    with pytest.raises(ValueError):
        wire.parse_serve({"sv": 1, "op": "open"})         # no req id


# ---------------------------------------------------------------------- #
# local lifecycle on one persistent context                              #
# ---------------------------------------------------------------------- #
def _count_build(ctx, counter, n_tasks=4):
    """A DTD closure submission: build returns a sealed, not-yet-added
    pool whose tasks bump ``counter`` (a list cell)."""
    def build():
        tp = dtd.taskpool_new()

        def body(es, task):
            counter[0] += 1

        for k in range(n_tasks):
            tp.insert_task(body, (k, VALUE))
        return tp
    return build


def test_local_submit_lifecycle_and_gauges(ctx):
    done = [0]
    with SessionServer(ctx) as srv:
        assert ctx.serve_fairness is srv.fairness
        assert SERVE_TENANTS in ctx.sde.names()
        srv.open_tenant("acme", weight=4)
        names = ctx.sde.names()
        for prefix in (SERVE_INFLIGHT_PREFIX, SERVE_QUOTA_BYTES_PREFIX,
                       SERVE_P99_LATENCY_PREFIX):
            assert f"{prefix}::acme" in names
        subs = [srv.submit("acme", _count_build(ctx, done), ntasks=4)
                for _ in range(3)]
        for sub in subs:
            assert sub.wait(30), "served pool never completed"
            assert sub.error is None
            assert sub.lat_us > 0
        assert done[0] == 12
        st = srv.stats()["tenants"]["acme"]
        assert st["weight"] == 4 and st["pools_done"] == 3
        assert st["inflight_pools"] == 0 and st["queued"] == 0
        assert st["p50_lat_us"] > 0 and st["p99_lat_us"] >= st["p50_lat_us"]
    # close() detaches everything it hooked
    assert ctx.serve_fairness is None
    names = ctx.sde.names()
    assert SERVE_TENANTS not in names
    assert f"{SERVE_INFLIGHT_PREFIX}::acme" not in names


def test_local_admission_errors(ctx):
    srv = SessionServer(ctx)
    try:
        with pytest.raises(AdmissionError, match="unknown tenant"):
            srv.submit("ghost", lambda: None)
        srv.open_tenant("t", max_tasks=2)
        with pytest.raises(AdmissionError, match="max in-flight tasks"):
            srv.submit("t", lambda: None, ntasks=3)
        # idempotent re-open keeps the original caps
        t2 = srv.open_tenant("t", max_tasks=99)
        assert t2.max_tasks == 2
    finally:
        srv.close()
    with pytest.raises(AdmissionError, match="closed"):
        srv.submit("t", lambda: None)


def test_failed_build_releases_admission(ctx):
    """A submission whose build raises must un-charge the tenant's
    in-flight counters (capacity would otherwise leak forever) and
    still drain the queue."""
    done = [0]
    with SessionServer(ctx, admission="queue") as srv:
        srv.open_tenant("t", max_pools=1)

        def boom():
            raise RuntimeError("nope")

        bad = srv.submit("t", boom)
        assert bad.wait(10), "failed build must finish the submission"
        assert bad.error and "build failed" in bad.error
        st = srv.stats()["tenants"]["t"]
        assert st["inflight_pools"] == 0 and st["queued"] == 0
        # capacity actually came back: the next submission admits + runs
        ok = srv.submit("t", _count_build(ctx, done, n_tasks=2), ntasks=2)
        assert ok.wait(30) and ok.error is None
    assert done[0] == 2


def test_abort_releases_admission_and_promotes_queue(ctx):
    """Taskpool.abort (FT eviction) must run the serve abort hook:
    charges release, the submission fails (waiters unblock), and the
    tenant's queued work is promoted."""
    gate = threading.Event()

    def gated_build():
        tp = dtd.taskpool_new()

        def body(es, task):
            gate.wait(10)

        tp.insert_task(body, (0, VALUE))
        return tp

    done2 = [0]
    with SessionServer(ctx, admission="queue") as srv:
        srv.open_tenant("t", max_pools=1)
        sub1 = srv.submit("t", gated_build)
        sub2 = srv.submit("t", _count_build(ctx, done2, n_tasks=2))
        assert srv.stats()["tenants"]["t"]["queued"] == 1
        sub1.taskpool.abort()
        assert sub1.wait(10), "abort must finish the submission"
        assert sub1.error and "abort" in sub1.error
        gate.set()                     # release the parked worker
        assert sub2.wait(30), "queued pool must promote on abort"
        assert sub2.error is None and done2[0] == 2
        st = srv.stats()["tenants"]["t"]
        assert st["inflight_pools"] == 0 and st["queued"] == 0
    gate.set()


class _Tile:
    """Attribute-capable mempool element (owner back-pointer rides it)."""


def test_mempool_free_kicks_queued_submission(ctx):
    """A submission queued on the Mempool-fed byte quota while the
    tenant has ZERO in-flight pools has no _pool_done event to drain
    it — the bound pool's free path must kick re-admission."""
    from parsec_tpu.core.mempool import Mempool
    mp = Mempool(_Tile)
    done = [0]
    with SessionServer(ctx, admission="queue") as srv:
        srv.open_tenant("t", quota_bytes=100)
        srv.bind_mempool("t", mp, item_bytes=60)
        elt = mp.allocate()            # 60 outstanding bytes
        sub = srv.submit("t", _count_build(ctx, done, n_tasks=2),
                         nbytes=50)    # 60 + 50 > 100 -> queued
        assert srv.stats()["tenants"]["t"]["queued"] == 1
        mp.free(elt)                   # headroom appears -> kick drains
        assert sub.wait(30), "mempool free must re-admit queued work"
        assert sub.error is None and done[0] == 2
    assert mp.on_free is None          # close() unhooks the pool


def test_latency_window_knob_sizes_rings(ctx):
    """serve_latency_window must actually size the per-tenant latency
    rings in both the server and the live monitor."""
    with params.cmdline_override("serve_latency_window", "3"):
        srv = SessionServer(ctx)
        try:
            t = srv.open_tenant("t")
            assert t.lat_us.maxlen == 3
        finally:
            srv.close()
        lh = LiveHealth(rank=0)
        assert lh.TENANT_LAT_RING == 3
        for us in (1.0, 2.0, 3.0, 4.0):
            lh.note_tenant_latency("t", us)
        assert lh._tenants["t"]["lat"].maxlen == 3
        assert list(lh._tenants["t"]["lat"]) == [2.0, 3.0, 4.0]


# ---------------------------------------------------------------------- #
# remote client over the AM layer                                        #
# ---------------------------------------------------------------------- #
_REMOTE = {"ctx": None, "hits": 0}


def _remote_build():
    """Module-level so it survives the pickled submit path."""
    tp = dtd.taskpool_new()

    def body(es, task):
        _REMOTE["hits"] += 1

    for k in range(5):
        tp.insert_task(body, (k, VALUE))
    return tp


def _serve_pair(ctx):
    """Server on engine 0 (bound to the real context), client on
    engine 1, with a pump thread draining both engines' progress — the
    role the comm thread plays in a TCP deployment."""
    fabric = LocalFabric(2)
    e0, e1 = fabric.engine(0), fabric.engine(1)
    srv = SessionServer(ctx)
    srv.attach_engine(e0)
    cli = ServeClient(e1, server_rank=0, timeout=30.0)
    stop = threading.Event()

    def _pump():
        while not stop.is_set():
            e0.progress()
            e1.progress()
            time.sleep(0.002)

    th = threading.Thread(target=_pump, daemon=True)
    th.start()
    return srv, cli, e0, e1, stop, th


def test_remote_open_submit_wait_stats(ctx):
    _REMOTE["ctx"], _REMOTE["hits"] = ctx, 0
    srv, cli, _e0, _e1, stop, th = _serve_pair(ctx)
    try:
        msg = cli.open_tenant("acme", weight=8)
        assert msg["tenant"] == "acme" and msg["weight"] == 8
        ticket = cli.submit("acme", _remote_build, ntasks=5)
        done = cli.wait(ticket)          # deferred server-side reply
        assert done["ticket"] == ticket and done["lat_us"] > 0
        assert _REMOTE["hits"] == 5
        st = cli.stats()["tenants"]["acme"]
        assert st["pools_done"] == 1 and st["weight"] == 8
        with pytest.raises(RuntimeError, match="unknown tenant"):
            cli.submit("ghost", _remote_build)
    finally:
        stop.set()
        th.join(5)
        srv.close()


def test_remote_capability_gate(ctx):
    srv, cli, e0, e1, stop, th = _serve_pair(ctx)
    try:
        # client side: a peer that never negotiated "sv" is refused
        # locally, before any bytes move
        e1.serve_to = lambda dst: False
        with pytest.raises(RuntimeError, match="sv capability"):
            cli.open_tenant("acme")
        # server side: the gate answers with a versioned error reply
        del e1.serve_to
        e0.serve_to = lambda src: False
        with pytest.raises(RuntimeError, match="did not negotiate"):
            cli.open_tenant("acme")
    finally:
        stop.set()
        th.join(5)
        srv.close()


def test_serve_client_owns_reply_tag_exclusively():
    """The engine keeps one handler per tag: a second ServeClient
    would silently detach the first, so construction refuses until the
    first is closed; close() also fails parked callers promptly."""
    fabric = LocalFabric(2)
    e1 = fabric.engine(1)
    c1 = ServeClient(e1, server_rank=0, timeout=30.0)
    with pytest.raises(RuntimeError, match="one ServeClient per engine"):
        ServeClient(e1, server_rank=0)
    errs = []

    def _blocked():
        try:
            c1.stats()                 # no server attached: never replies
        except Exception as exc:       # noqa: BLE001
            errs.append(exc)

    th = threading.Thread(target=_blocked, daemon=True)
    th.start()
    time.sleep(0.05)
    c1.close()
    th.join(5)
    assert errs and "closed" in str(errs[0])
    with pytest.raises(RuntimeError, match="closed"):
        c1.stats()
    # the tag is free again: a successor attaches cleanly
    with ServeClient(e1, server_rank=0) as c2:
        assert c2 is not None


# ---------------------------------------------------------------------- #
# tenant-stamped flow contexts -> cross-rank tooling                     #
# ---------------------------------------------------------------------- #
def _tenant_flow_pair():
    fabric = LocalFabric(2)
    engines, profiles = [], []
    for r in range(2):
        eng = fabric.engine(r)
        p = Profile(rank=r)
        eng._obs = CommObs(MetricsRegistry(), profile=p)
        fl = FlowIds(r)
        fl.live = True
        eng._flow = fl
        engines.append(eng)
        profiles.append(p)
    return engines, profiles


def test_tenant_rides_flow_context_and_stitches():
    (e0, e1), (p0, p1) = _tenant_flow_pair()
    e0._flow.tenants = {42: "acme"}       # what attach/ctor install
    got = []
    e1.tag_register(TAG_ACTIVATE, lambda src, pl: got.append(pl))
    e0.send_am(1, TAG_ACTIVATE,
               {"tp_id": 42, "root": 0, "ranks": [1], "edges": {1: []}})
    e0.send_am(1, TAG_ACTIVATE,
               {"tp_id": 99, "root": 0, "ranks": [1], "edges": {1: []}})
    e1.progress()
    assert got[0]["_tr"][4] == "acme"     # owned pool: attributed
    assert got[1]["_tr"][4] is None       # foreign pool: unattributed
    docs = [p0.to_chrome_trace(), p1.to_chrome_trace()]
    edges, unmatched = stitch_flows(load_flow_events(merge_trace_docs(docs)))
    assert unmatched == 0
    tagged = [e for e in edges if e.get("tenant") == "acme"]
    assert len(tagged) == 1
    assert sum(1 for e in edges if "tenant" in e) == 1
    # the offline report narrows to one tenant and rolls it up
    report = analyze(docs, tenant="acme")
    per = report["cross_rank"]["per_tenant"]
    assert set(per) == {"acme"}
    assert per["acme"]["flow_edges"] == 1


# ---------------------------------------------------------------------- #
# live-health attribution                                                #
# ---------------------------------------------------------------------- #
def test_live_health_per_tenant_merge_and_render():
    lh0, lh1 = LiveHealth(rank=0), LiveHealth(rank=1)
    assert "per_tenant" not in lh0.snapshot()   # pre-serve shape intact
    for us in (1000.0, 2000.0, 3000.0):
        lh0.note_tenant_latency("acme", us)
    lh1.note_tenant_latency("acme", 9000.0)
    lh1.note_tenant_latency("bulk", 500.0)
    s0, s1 = lh0.snapshot(), lh1.snapshot()
    assert s0["per_tenant"]["acme"]["pools_done"] == 3
    assert s0["per_tenant"]["acme"]["p99_lat_us"] == 3000.0
    fleet = fleet_health({0: s0, 1: s1})
    acme = fleet["per_tenant"]["acme"]
    assert acme["pools_done"] == 4
    assert acme["p99_lat_us"] == 9000.0         # fleet-worst, not a sum
    text = format_health(fleet)
    assert "acme" in text and "bulk" in text
    # a pre-serve fleet document renders with no tenant section
    pre = fleet_health({0: LiveHealth(rank=0).snapshot()})
    assert "per_tenant" not in pre
    format_health(pre)


# ---------------------------------------------------------------------- #
# knob contract: unset constructs nothing, set implies the monitor      #
# ---------------------------------------------------------------------- #
def test_serve_knob_unset_is_inert(ctx):
    assert ctx.serve_fairness is None
    assert not any(n.startswith("PARSEC::SERVE")
                   for n in ctx.sde.names())
    assert ctx.obs.live is None


def test_serve_knob_implies_live_monitor():
    with params.cmdline_override("serve", "1"):
        c = parsec_tpu.init(nb_cores=2)
        try:
            assert c.obs.live is not None, \
                "serve=1 must arm obs_live (tenant SLO attribution)"
        finally:
            c.fini()
