"""Arenas: sized freelist allocators for task/communication buffers.

Reference behavior: per-(type, shape) freelists of buffers used for
communication and NEW-tile allocation, with MCA caps ``arena_max_used`` /
``arena_max_cached`` (ref: parsec/arena.c, parsec/parsec.c:681-686).

TPU-native re-design: an arena vends numpy host buffers (or, via a device
module hook, HBM-backed buffers) for a fixed Datatype. Freed buffers are
cached for reuse up to max_cached; max_used caps total live allocations.
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

from ..utils.params import params
from .data import Data, DataCopy, Coherency
from .datatype import Datatype


class Arena:
    def __init__(self, dtt: Datatype, max_used: Optional[int] = None,
                 max_cached: Optional[int] = None, allocator=None) -> None:
        self.dtt = dtt
        mu = params.get("arena_max_used") if max_used is None else max_used
        mc = params.get("arena_max_cached") if max_cached is None else max_cached
        self.max_used = None if mu in (-1, None) else mu
        self.max_cached = None if mc in (-1, None) else mc
        self._free: List[Any] = []
        self._used = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # allocator(dtt) -> backing buffer; default host numpy
        self._alloc = allocator or (lambda d: np.empty(d.shape, dtype=d.dtype))

    def allocate(self, block: bool = True) -> Any:
        with self._cond:
            while True:
                if self._free:
                    self._used += 1
                    return self._free.pop()
                if self.max_used is None or self._used < self.max_used:
                    self._used += 1
                    break
                if not block:
                    return None
                self._cond.wait()
        return self._alloc(self.dtt)

    def free(self, buf: Any) -> None:
        with self._cond:
            self._used -= 1
            if self.max_cached is None or len(self._free) < self.max_cached:
                self._free.append(buf)
            self._cond.notify()

    @property
    def used(self) -> int:
        return self._used

    @property
    def cached(self) -> int:
        return len(self._free)

    # -- data-copy integration ---------------------------------------------
    def new_copy(self, data: Data, device_id: int = 0) -> DataCopy:
        """Allocate an arena-backed DataCopy (recycled on copy destruct)."""
        buf = self.allocate()
        copy = DataCopy(data, device_id, payload=buf, dtt=self.dtt)
        copy.arena_chunk = _ArenaChunk(self, buf)
        data.attach_copy(copy)
        return copy


class _ArenaChunk:
    __slots__ = ("arena", "buf")

    def __init__(self, arena: Arena, buf: Any) -> None:
        self.arena = arena
        self.buf = buf

    def release_copy(self, copy: DataCopy) -> None:
        self.arena.free(self.buf)
        self.buf = None
