"""Offline trace toolchain tests (ref: tools/profiling — dbpreader,
dbp2xml, pbt2ptt/profile2h5, aggregator_visu; trace-validating tests
mirror tests/profiling/check-async.py / check-comms.py).
"""
import json
import os
import sys

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu import dtd
from parsec_tpu.dsl.dtd import INOUT, VALUE, unpack_args
from parsec_tpu.profiling.binfmt import read_profile, write_profile

TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import counter_aggregate  # noqa: E402
import ptt2h5  # noqa: E402
import ptt_dump  # noqa: E402
import trace_merge  # noqa: E402


def _traced_run(rank=0):
    """Run a tiny DTD graph with profiling on; return the live Profile."""
    ctx = parsec_tpu.Context(nb_cores=2, enable_tpu=False, profile=True)
    try:
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        tile = tp.tile_of_array(np.zeros((4, 4), np.float32))

        def bump(es, task):
            x, a = unpack_args(task)
            x += a

        for i in range(5):
            tp.insert_task(bump, (tile, INOUT), (1.0, VALUE))
        tp.data_flush_all()
        tp.wait()
        prof = ctx.profile
        prof.rank = rank
        ctx.sample_sde_counters()
    finally:
        ctx.fini()
    return prof


@pytest.fixture(scope="module")
def trace_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("traces")
    paths = []
    for rank in (0, 1):
        prof = _traced_run(rank)
        p = str(d / f"t.rank{rank}.ptt")
        write_profile(prof, p)
        paths.append((p, prof))
    return paths


def test_binary_roundtrip(trace_files):
    for path, prof in trace_files:
        back = read_profile(path)
        assert back.rank == prof.rank
        assert back.nb_events() == prof.nb_events()
        assert sorted(back._streams) == sorted(prof._streams)
        for tid, st in prof._streams.items():
            rst = back._streams[tid]
            # timestamps re-based at t0, everything else identical
            for (ts, ph, key, info), (rts, rph, rkey, rinfo) in zip(
                    st.events, rst.events):
                assert rts == ts - prof._t0
                assert (rph, rkey, rinfo) == (ph, key, info)


def test_bad_magic_rejected(tmp_path):
    p = tmp_path / "junk.ptt"
    p.write_bytes(b"NOTATRACE")
    with pytest.raises(ValueError, match="bad magic"):
        read_profile(str(p))


def test_exec_intervals_present(trace_files):
    """The task profiler must have produced one exec interval per task
    (5 bump tasks + flush tasks), with positive durations."""
    path, _ = trace_files[0]
    prof = read_profile(path)
    ivals = []
    for st in prof._streams.values():
        ivals += [iv for iv in ptt_dump.intervals_of(st)
                  if iv[0].startswith("exec:")]
    assert len(ivals) >= 5
    assert all(e > b for _, b, e, _ in ivals)


def test_ptt_dump_formats(trace_files, capsys):
    paths = [p for p, _ in trace_files]
    assert ptt_dump.main(paths) == 0
    out = capsys.readouterr().out
    assert "rank 0" in out and "exec:" in out and "n=" in out

    assert ptt_dump.main(["--format", "xml"] + paths[:1]) == 0
    out = capsys.readouterr().out
    assert out.startswith('<?xml') and "<stream" in out and "<event" in out
    import xml.etree.ElementTree as ET
    root = ET.fromstring(out)  # must be well-formed, incl. quoted JSON info
    assert root.tag == "profiles" and root.find(".//event") is not None

    assert ptt_dump.main(["--format", "json"] + paths[:1]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc[0]["rank"] == 0 and doc[0]["streams"]


def test_ptt2h5_and_load(trace_files, tmp_path, capsys):
    paths = [p for p, _ in trace_files]
    out = str(tmp_path / "t.h5")
    assert ptt2h5.main([out] + paths) == 0
    df = ptt2h5.load(out)
    assert set(df.columns) >= {"rank", "tid", "name", "begin_ns", "end_ns",
                               "duration_ns"}
    assert sorted(df["rank"].unique()) == [0, 1]
    assert (df["duration_ns"] > 0).all()
    assert df["name"].str.startswith("exec:").any()


def test_ptt2parquet(trace_files, tmp_path):
    paths = [p for p, _ in trace_files]
    out = str(tmp_path / "t.parquet")
    assert ptt2h5.main(["--format", "parquet", out] + paths) == 0
    df = ptt2h5.load(out)
    assert len(df) > 0 and sorted(df["rank"].unique()) == [0, 1]


def test_trace_merge(trace_files, tmp_path):
    paths = [p for p, _ in trace_files]
    out = str(tmp_path / "merged.json")
    assert trace_merge.main([out] + paths) == 0
    doc = json.load(open(out))
    pids = {ev["pid"] for ev in doc["traceEvents"]}
    assert pids == {0, 1}
    names = {ev["name"] for ev in doc["traceEvents"] if ev.get("ph") == "M"}
    assert "process_name" in names


def test_counter_aggregate(trace_files, tmp_path, capsys):
    paths = [p for p, _ in trace_files]
    series = counter_aggregate.collect(paths)
    assert any("TASKS" in k for k in series), series.keys()
    agg = counter_aggregate.aggregate(series)
    key = next(k for k in agg if "RETIRED" in k)
    assert set(agg[key]["ranks"]) == {0, 1}
    assert agg[key]["fleet"]["n"] >= 2
    # CLI with timeline + json out
    out = str(tmp_path / "agg.json")
    assert counter_aggregate.main(
        ["--timeline", "4", "--json", out] + paths) == 0
    doc = json.load(open(out))
    assert "aggregate" in doc and "timeline" in doc
    assert capsys.readouterr().out.strip()


def test_context_fini_writes_both_formats(tmp_path, monkeypatch):
    """profile=<prefix> MCA param: fini writes chrome JSON + binary ptt."""
    parsec_tpu.params.reset()
    prefix = str(tmp_path / "prof")
    parsec_tpu.params.set_cmdline("profile", prefix)
    try:
        ctx = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        tp.insert_task(lambda es, task: None)
        tp.wait()
        ctx.fini()
    finally:
        parsec_tpu.params.reset()
    json_files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
    ptt_files = [f for f in os.listdir(tmp_path) if f.endswith(".ptt")]
    assert json_files and ptt_files
    back = read_profile(str(tmp_path / ptt_files[0]))
    assert back.nb_events() > 0


# --------------------------------------------------------------------- #
# ptgpp CLI (ref: parsec_ptgpp build-time compiler, main.c:46-78)       #
# --------------------------------------------------------------------- #
SMALL_JDF = """
descA [ type="collection" ]
NT [ type="int" ]

STEP(k)
k = 0 .. NT-1
: descA( 0, 0 )
RW A <- (k == 0) ? descA( 0, 0 ) : A STEP( k-1 )
     -> (k < NT-1) ? A STEP( k+1 )
     -> (k == NT-1) ? descA( 0, 0 )
BODY
{
    A = A + 1.0
}
END
"""


def test_ptgpp_check_and_generate(tmp_path, capsys):
    import importlib.util

    import ptgpp

    src = tmp_path / "stepper.jdf"
    src.write_text(SMALL_JDF)
    # validate-only
    assert ptgpp.main(["--check", str(src)]) == 0
    assert "1 task classes" in capsys.readouterr().out

    out = tmp_path / "stepper_gen.py"
    assert ptgpp.main([str(src), "-o", str(out)]) == 0
    spec = importlib.util.spec_from_file_location("stepper_gen", out)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "STEP(k)" in mod.__doc__

    import parsec_tpu
    from parsec_tpu.collections import TwoDimBlockCyclic
    A = TwoDimBlockCyclic(4, 4, 4, 4).from_numpy(np.zeros((4, 4), np.float32))
    ctx = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    try:
        tp = mod.stepper_new(descA=A, NT=5)
        ctx.add_taskpool(tp)
        ctx.wait()
    finally:
        ctx.fini()
    np.testing.assert_allclose(A.to_numpy(), 5.0)


def test_ptgpp_rejects_bad_jdf(tmp_path, capsys):
    import ptgpp
    bad = tmp_path / "bad.jdf"
    bad.write_text("STEP(k)\nk = 0 .. 3\n: nowhere( k )\nBODY\n{\n pass\n}\nEND\n")
    assert ptgpp.main(["--check", str(bad)]) == 1
    assert "bad.jdf" in capsys.readouterr().err


def test_counter_aggregate_watch_mode(trace_files, capsys):
    paths = [p for p, _ in trace_files]
    assert counter_aggregate.main(
        ["--watch", "0.05", "--watch-rounds", "2"] + paths) == 0
    out = capsys.readouterr().out
    assert out.count("rank files") == 2  # two refreshes printed


def test_dagenum_enumerates_without_executing(tmp_path, capsys):
    """tools/dagenum.py: symbolic DAG enumeration (dagenum.c analog) —
    counts, edges, critical path, DOT — with no task ever executed."""
    import dagenum
    from parsec_tpu.ops.dpotrf import DPOTRF_L_JDF

    jdf = tmp_path / "dpotrf.jdf"
    jdf.write_text(DPOTRF_L_JDF)
    dot = tmp_path / "dag.dot"
    assert dagenum.main([str(jdf), "-g", "NT=4", "--dot", str(dot)]) == 0
    out = capsys.readouterr().out
    assert "20 tasks, 30 dependence edges, critical path 10" in out
    assert dot.read_text().count("->") == 30


def test_dagenum_sim_schedule(tmp_path, capsys):
    """--sim: the PARSEC_SIM analog (simulated task dates over the
    symbolic DAG). Invariants: serial >= wave makespan >= critical
    path (level-synchronous slack is never negative)."""
    import re

    import dagenum
    from parsec_tpu.ops.dpotrf import DPOTRF_L_JDF

    jdf = tmp_path / "dpotrf.jdf"
    jdf.write_text(DPOTRF_L_JDF)
    assert dagenum.main([str(jdf), "-g", "NT=4", "--sim",
                         "--cost", "POTRF=2.0", "--cost", "GEMM=0.5"]) == 0
    out = capsys.readouterr().out
    cp = float(re.search(r"critical path ([\d.]+)s", out).group(1))
    serial = float(re.search(r"serial ([\d.]+)s", out).group(1))
    wave = float(re.search(r"wave makespan ([\d.]+)s", out).group(1))
    peak = int(re.search(r"peak (\d+)", out).group(1))
    assert serial >= wave >= cp > 0
    assert peak >= 3    # NT=4 exposes at least the 3-wide TRSM wave
