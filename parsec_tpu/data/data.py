"""Data substrate: versioned per-device data copies with coherency.

Reference behavior: ``parsec_data_t`` holds one ``parsec_data_copy_t`` per
device, each with a version, a reader count, and a coherency state in
{INVALID, OWNED, EXCLUSIVE, SHARED}; ownership moves to a copy on write
access and readers attach to valid copies
(ref: parsec/data_internal.h:57-81, parsec/data.h:27-31,
parsec_data_transfer_ownership_to_copy parsec/data.c:286-370).

TPU-native re-design: a copy's payload is a numpy array on the host device
or a jax.Array on an accelerator device. Transfers are jax.device_put /
np.asarray — asynchronous on TPU (dispatch returns immediately; readiness is
polled via jax's async semantics by the device module).
"""
from __future__ import annotations

import itertools
import threading
from enum import IntEnum
from typing import Any, Dict, List, Optional

from ..core.object import Obj

#: declared lock discipline, enforced by the concurrency lint
#: (parsec_tpu/analysis/lock_check.py; tools/parsec_lint.py runs it):
#: the copy map is read by worker, comm, and device threads while
#: stage-in/eviction/writeback mutate it — every touch goes through
#: Data._lock (construction and refcount-zero teardown are exempt)
_GUARDED_BY = {
    "Data._copies": "_lock",
}


def is_device_array(x: Any) -> bool:
    """A jax array (device-resident payload): stays on device through
    transports/stage-in; numpy arrays and scalars take host paths."""
    try:
        import jax
        return isinstance(x, jax.Array)
    except Exception:  # pragma: no cover - jax always present in-tree
        return False


class Coherency(IntEnum):
    INVALID = 0
    OWNED = 1       # only valid version; other copies may be stale
    EXCLUSIVE = 2   # owned and no other copies exist
    SHARED = 3      # multiple valid copies


class FlowAccess(IntEnum):
    NONE = 0
    READ = 1
    WRITE = 2
    RW = 3


class DataCopy(Obj):
    """One incarnation of a Data on one device."""

    __slots__ = ("data", "device_id", "version", "readers", "coherency",
                 "payload", "flags", "dtt", "arena_chunk")

    def __init__(self, data: "Data", device_id: int, payload: Any = None,
                 dtt: Any = None) -> None:
        super().__init__()
        self.data = data
        self.device_id = device_id
        self.version = 0
        self.readers = 0
        self.coherency = Coherency.INVALID
        self.payload = payload
        self.dtt = dtt          # datatype/shape descriptor (see data/datatype.py)
        self.arena_chunk = None  # owning arena, for recycling on destruct

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"<DataCopy dev={self.device_id} v={self.version} "
                f"{Coherency(self.coherency).name} readers={self.readers}>")

    def _destruct(self) -> None:
        if self.arena_chunk is not None:
            self.arena_chunk.release_copy(self)
            self.arena_chunk = None
        if self.data is not None:
            self.data._detach_copy(self)
        self.payload = None
        super()._destruct()


class Data(Obj):
    """A logical datum with per-device copies (ref: parsec_data_t)."""

    _key_iter = itertools.count()

    def __init__(self, key: Any = None, collection: Any = None,
                 nb_elts: int = 0) -> None:
        super().__init__()
        self.key = key if key is not None else next(Data._key_iter)
        self.collection = collection  # owning data collection, if any
        self.nb_elts = nb_elts        # logical payload size in elements/bytes
        self.owner_device: int = -1
        self.preferred_device: int = -1
        self._copies: Dict[int, DataCopy] = {}
        self._lock = threading.RLock()

    # -- copy management ----------------------------------------------------
    def attach_copy(self, copy: DataCopy) -> None:
        with self._lock:
            assert copy.device_id not in self._copies, \
                f"data {self.key} already has a copy on device {copy.device_id}"
            self._copies[copy.device_id] = copy
            copy.data = self

    def _detach_copy(self, copy: DataCopy) -> None:
        with self._lock:
            cur = self._copies.get(copy.device_id)
            if cur is copy:
                del self._copies[copy.device_id]

    def get_copy(self, device_id: int) -> Optional[DataCopy]:
        with self._lock:
            return self._copies.get(device_id)

    def copies(self) -> List[DataCopy]:
        with self._lock:
            return list(self._copies.values())

    def newest_version(self) -> int:
        with self._lock:
            return max((c.version for c in self._copies.values()
                        if c.coherency != Coherency.INVALID), default=-1)

    def newest_copy(self, exclude_device: int = -1) -> Optional[DataCopy]:
        """A valid copy holding the newest version (transfer source)."""
        with self._lock:
            best = None
            for c in self._copies.values():
                if c.coherency == Coherency.INVALID or c.device_id == exclude_device:
                    continue
                if best is None or c.version > best.version:
                    best = c
            return best

    # -- coherency protocol -------------------------------------------------
    def start_transfer_ownership(self, device_id: int, access: FlowAccess) -> Optional[DataCopy]:
        """Phase 1 (ref parsec_data_start_transfer_ownership_to_copy,
        parsec/data.c:318): decide whether device_id's copy needs a transfer
        and from where. Returns the source copy to pull from, or None if the
        local copy is already valid.
        """
        with self._lock:
            dst = self._copies.get(device_id)
            assert dst is not None, "transfer ownership to a non-attached copy"
            newest = self.newest_version()
            if dst.coherency != Coherency.INVALID and dst.version == newest:
                return None
            src = self.newest_copy(exclude_device=device_id)
            return src

    def complete_transfer_ownership(self, device_id: int, access: FlowAccess) -> DataCopy:
        """Phase 2: dst copy now holds the newest payload; fix states.

        Write access: dst becomes OWNED, all other copies SHARED (stale-able);
        read access: dst joins the SHARED set (or OWNED copy stays owner).
        """
        with self._lock:
            dst = self._copies[device_id]
            newest = self.newest_version()
            if dst.version < newest:
                dst.version = newest
            if access & FlowAccess.WRITE:
                for c in self._copies.values():
                    if c is not dst and c.coherency != Coherency.INVALID:
                        c.coherency = Coherency.SHARED
                dst.coherency = Coherency.OWNED
                self.owner_device = device_id
            else:
                if dst.coherency == Coherency.INVALID:
                    dst.coherency = Coherency.SHARED
                dst.readers += 1
            return dst

    def version_bump(self, device_id: int) -> int:
        """After a write completes: the writer's copy advances the version
        (ref: CUDA epilog OWNED handback, device_cuda_module.c:2365-2430)."""
        with self._lock:
            dst = self._copies[device_id]
            dst.version = self.newest_version() + 1
            dst.coherency = Coherency.OWNED
            self.owner_device = device_id
            for c in self._copies.values():
                if c is not dst and c.coherency != Coherency.INVALID:
                    c.coherency = Coherency.SHARED
            return dst.version

    def release_reader(self, device_id: int) -> None:
        with self._lock:
            c = self._copies.get(device_id)
            if c is not None and c.readers > 0:
                c.readers -= 1

    def invalidate_others(self, device_id: int) -> None:
        with self._lock:
            for c in self._copies.values():
                if c.device_id != device_id:
                    c.coherency = Coherency.INVALID

    # -- host-side helpers shared by the DSLs -------------------------------
    @staticmethod
    def materialize_host(copy: "DataCopy") -> Any:
        """Ensure ``copy.payload`` is a writable host ndarray and return it.

        A host (device-0) copy can transiently hold an immutable device
        array — e.g. a payload that arrived over the mesh transport's
        device-to-device data plane (comm/mesh.py). Host task bodies
        mutate payloads in place, so the first host consumer materializes
        a writable numpy buffer here; device consumers keep the zero-copy
        device array."""
        import numpy as _np
        p = copy.payload
        if p is not None and not (isinstance(p, _np.ndarray)
                                  and p.flags.writeable):
            copy.payload = _np.array(p)
        return copy.payload

    def host_copy(self) -> DataCopy:
        """The device-0 copy, attached on demand."""
        with self._lock:
            host = self._copies.get(0)
            if host is None:
                host = DataCopy(self, 0, payload=None)
                self._copies[0] = host
            return host

    def sync_to_host(self, devices=None) -> DataCopy:
        """Make the host copy hold the newest version, pulling from the
        owning accelerator if needed. ``devices`` is the context device list
        indexed by device_id (None: direct conversion, no device-module
        stats/LRU bookkeeping)."""
        host = self.host_copy()
        newest = self.newest_copy()
        if newest is not None and newest.device_id != 0 and \
                newest.version > host.version:
            if devices is not None:
                devices[newest.device_id].pull_to_host(self)
                host = self.get_copy(0)
            else:
                import numpy as np
                host.payload = np.array(newest.payload)
                host.version = newest.version
                host.coherency = Coherency.SHARED
        return host

    def _destruct(self) -> None:
        for c in list(self._copies.values()):
            c.data = None
        self._copies.clear()
        super()._destruct()


def data_new_with_payload(payload: Any, device_id: int = 0, key: Any = None) -> Data:
    """Convenience: wrap an existing host array as a Data with one OWNED copy."""
    d = Data(key=key, nb_elts=getattr(payload, "size", 0))
    c = DataCopy(d, device_id, payload=payload)
    c.coherency = Coherency.OWNED
    c.version = 1
    d.attach_copy(c)
    d.owner_device = device_id
    return d
