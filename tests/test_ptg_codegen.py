"""Generated task-class code vs the interpreted AST walk
(ref: the jdf2c-generated iterate_successors/dependency counters must
agree with the JDF semantics; here the interpreter IS the executable
spec, so equivalence over whole iteration spaces is the check).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.dsl.ptg.codegen import generate_source


def _edges_interpreted(tc, locals_):
    """Successor edges via the AST walk (mirrors _iterate_successors)."""
    from parsec_tpu.dsl.ptg.runtime import _expand_args
    env = tc.env_of(locals_)
    out = []
    for i, f in enumerate(tc.ast.flows):
        for d in f.deps_out():
            t = d.resolve(env)
            if t is None or t.kind in ("null", "new", "memory"):
                continue
            for succ_locals in _expand_args(t.args, env):
                out.append((t.task_class, succ_locals, t.flow, i))
    return out


def _edges_generated(tc, locals_):
    copies = [None] * len(tc.ast.flows)
    out = []
    tc._gen_succ(locals_, copies,
                 lambda name, loc, fl, cp, idx, tys=None: out.append(
                     (name, loc, fl, idx)))
    return out


def _taskpool_for(which):
    if which == "dpotrf":
        from parsec_tpu.ops.dpotrf import dpotrf_taskpool
        A = TwoDimBlockCyclic(5 * 8, 5 * 8, 8, 8, dtype=np.float32)
        return dpotrf_taskpool(A)
    if which == "dgeqrf":
        from parsec_tpu.ops.dgeqrf import dgeqrf_taskpool
        A = TwoDimBlockCyclic(4 * 8, 3 * 8, 8, 8, dtype=np.float32)
        return dgeqrf_taskpool(A)
    if which == "dgetrf":
        from parsec_tpu.ops.dgetrf import dgetrf_nopiv_taskpool
        A = TwoDimBlockCyclic(4 * 8, 4 * 8, 8, 8, dtype=np.float32)
        return dgetrf_nopiv_taskpool(A)
    if which == "stencil":
        from tests.test_apps import STENCIL_JDF
        from parsec_tpu.collections import VectorTwoDimCyclic
        U = VectorTwoDimCyclic(4 * 8, 8)
        return ptg.compile_jdf(STENCIL_JDF, name="stencil").new(
            descU=U, NT=4, NI=3)
    raise KeyError(which)


@pytest.mark.parametrize("which", ["dpotrf", "dgeqrf", "dgetrf", "stencil"])
def test_generated_matches_interpreted(which):
    """goal + successor edges agree for EVERY instance of every class."""
    tp = _taskpool_for(which)
    checked = 0
    for tc in tp.task_classes:
        assert tc._gen_goal is not None, f"{tc.name}: codegen did not run"
        for locals_ in tc.iter_space():
            env = tc.env_of(locals_)
            assert tc._gen_goal(locals_) == tc.input_goal(env), \
                f"{tc.name}{locals_}: goal mismatch"
            assert _edges_generated(tc, locals_) == \
                _edges_interpreted(tc, locals_), \
                f"{tc.name}{locals_}: successor edges mismatch"
            checked += 1
    assert checked >= 16  # whole space walked


def test_codegen_source_is_plausible():
    from parsec_tpu.ops.dpotrf import dpotrf_factory
    jdf = dpotrf_factory().jdf
    gemm = jdf.task_class_by_name("GEMM")
    src = generate_source(gemm)
    assert "__ptg_goal_GEMM" in src and "__ptg_succ_GEMM" in src
    compile(src, "<test>", "exec")  # must be valid Python


def test_codegen_disabled_falls_back(ctx):
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("ptg_codegen", "0")
    try:
        M = make_spd(64)
        A = TwoDimBlockCyclic(64, 64, 16, 16, dtype=np.float32).from_numpy(M)
        tp = dpotrf_taskpool(A)
        assert tp.task_classes[0]._gen_succ is None
        ctx.add_taskpool(tp)
        ctx.wait()
        L = np.tril(A.to_numpy())
        np.testing.assert_allclose(L @ L.T, M, atol=5e-4)
    finally:
        parsec_tpu.params.reset()


# --------------------------------------------------------------------- #
# unparse roundtrip (ref: jdf_unparse.c)                                #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("which", ["dpotrf", "dgeqrf", "dgetrf"])
def test_unparse_roundtrip(which):
    """parse(unparse(ast)) preserves the whole structure: classes,
    locals, flows, deps (guards/targets), priorities, bodies."""
    from parsec_tpu.dsl.ptg.parser import parse_jdf
    from parsec_tpu.dsl.ptg.unparse import unparse

    tp = _taskpool_for(which)
    jdf1 = tp.jdf
    text = unparse(jdf1)
    jdf2 = parse_jdf(text, name=jdf1.name)
    assert [t.name for t in jdf2.task_classes] == \
        [t.name for t in jdf1.task_classes]
    for t1, t2 in zip(jdf1.task_classes, jdf2.task_classes):
        assert t1.params == t2.params
        assert [l.name for l in t1.locals] == [l.name for l in t2.locals]
        assert [f.name for f in t1.flows] == [f.name for f in t2.flows]
        for f1, f2 in zip(t1.flows, t2.flows):
            assert f1.access == f2.access
            assert len(f1.deps) == len(f2.deps)
            for d1, d2 in zip(f1.deps, f2.deps):
                assert d1.direction == d2.direction
                assert (d1.guard is None) == (d2.guard is None)
                assert d1.target.kind == d2.target.kind
                assert d1.target.task_class == d2.target.task_class
        assert (t1.priority is None) == (t2.priority is None)
        assert len(t1.bodies) == len(t2.bodies)
    # and the unparsed text is itself compilable into a working factory
    import parsec_tpu
    from parsec_tpu.dsl import ptg as ptg_mod
    ptg_mod.compile_jdf(text, name="roundtrip")


FANCY_JDF = """
extern "PYTHON" %{
def helper(x):
    return x + 1
%}

descA [ type="collection" ]
NT [ type="int" default="4" ]
LBL [ type="string" default="'two words'" ]

T(k)  [ high_priority=on note="two words" ]

k = 0 .. NT-1
kk = helper(k)

: descA( 0, 0 )

RW A <- (k == 0) ? descA( 0, 0 ) : A T( k-1 )
     -> (k < NT-1) ? A T( k+1 )
     -> (k == NT-1) ? descA( 0, 0 )

; NT - k

BODY
{
    A = A + kk
}
END

extern "PYTHON" %{
EPILOGUE_MARK = 1
%}
"""


def test_unparse_roundtrip_prologue_props_epilogue():
    """Prologue/epilogue externs, header properties, and quoted property
    values must survive the roundtrip."""
    from parsec_tpu.dsl.ptg.parser import parse_jdf
    from parsec_tpu.dsl.ptg.unparse import unparse

    j1 = parse_jdf(FANCY_JDF, name="fancy")
    text = unparse(j1)
    j2 = parse_jdf(text, name="fancy")
    assert j2.prologue == j1.prologue
    assert j2.epilogue == j1.epilogue
    t1, t2 = j1.task_classes[0], j2.task_classes[0]
    assert t2.properties == t1.properties
    assert t2.properties.get("note") == "two words"
    assert [l.name for l in t2.locals] == ["k", "kk"]
    # and the roundtripped JDF still runs
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.dsl import ptg as ptg_mod
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_numpy(np.zeros((2, 2), np.float32))
    c = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    try:
        tp = ptg_mod.compile_jdf(text, name="fancy2").new(descA=A, NT=3)
        c.add_taskpool(tp)
        c.wait()
    finally:
        c.fini()
    # sum of helper(k)=k+1 for k=0..2 is 6
    np.testing.assert_allclose(A.to_numpy(), 6.0)
