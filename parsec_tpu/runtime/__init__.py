"""runtime subpackage."""
