"""Training step: shard_map'd fwd+bwd+Adam over the 5-axis mesh.

Gradients of replicated leaves are psum'd over exactly the axes the leaf is
replicated on (parallel.mesh.sync_axes) — the manual-collective discipline
that keeps dp/sp/pp-distributed compute correct. The optimizer state
mirrors the parameter sharding, so optimizer math is purely local.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import shard_map_compat, sync_axes
from .transformer import (TransformerConfig, init_params, loss_shard,
                          param_specs)


def adam_init(params: Any) -> Dict[str, Any]:
    zeros = jax.tree.map(jnp.zeros_like, params)
    return {"mu": zeros, "nu": jax.tree.map(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32)}


def adam_update(params, grads, state, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8):
    count = state["count"] + 1
    mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state["mu"], grads)
    nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g,
                      state["nu"], grads)
    c1 = 1 - b1 ** count.astype(jnp.float32)
    c2 = 1 - b2 ** count.astype(jnp.float32)
    new_params = jax.tree.map(
        lambda p, m, v: p - lr * (m / c1) / (jnp.sqrt(v / c2) + eps),
        params, mu, nu)
    return new_params, {"mu": mu, "nu": nu, "count": count}


def opt_state_specs(pspecs: Any) -> Dict[str, Any]:
    return {"mu": pspecs, "nu": pspecs, "count": P()}


def make_train_step(cfg: TransformerConfig, mesh, lr: float = 1e-3):
    """Returns train_step(params, opt_state, tokens, labels) ->
    (params, opt_state, loss), jit-compiled over the mesh."""
    _check_attention_mesh(cfg, mesh)
    pspecs = param_specs(cfg)
    ospecs = opt_state_specs(pspecs)
    data_spec = P("dp", "sp")

    def step_shard(params, opt_state, tokens, labels):
        # under check_vma=True shard_map, jax.grad of a replicated leaf is
        # already reduced over exactly the right axes (see shard_map_compat)
        loss, grads = jax.value_and_grad(
            lambda p: loss_shard(cfg, p, tokens, labels))(params)
        new_params, new_state = adam_update(params, grads, opt_state, lr=lr)
        return new_params, new_state, loss

    smapped = shard_map_compat(
        step_shard, mesh,
        in_specs=(pspecs, ospecs, data_spec, data_spec),
        out_specs=(pspecs, ospecs, P()))
    return jax.jit(smapped)


def _check_attention_mesh(cfg: TransformerConfig, mesh) -> None:
    """flash attention is shard-local: over sp>1 it would silently compute
    block-diagonal attention instead of global causal — reject loudly
    (use attention='ring' for sequence parallelism)."""
    sp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sp", 1)
    if cfg.attention == "flash" and sp > 1:
        raise ValueError(
            "attention='flash' is single-shard in the sequence dimension; "
            f"mesh has sp={sp} — use attention='ring' (or 'ulysses') for "
            "sequence-parallel meshes")


def make_forward(cfg: TransformerConfig, mesh):
    """Jittable forward: (params, tokens) -> logits (for inference/entry)."""
    from .transformer import forward_shard
    _check_attention_mesh(cfg, mesh)
    pspecs = param_specs(cfg)

    def fwd_shard(params, tokens):
        logits, _ = forward_shard(cfg, params, tokens)
        from ..parallel.pipeline import last_stage_value
        return last_stage_value(logits, "pp")

    return shard_map_compat(fwd_shard, mesh,
                            in_specs=(pspecs, P("dp", "sp")),
                            out_specs=P("dp", "sp"))


def shard_params(params, mesh, pspecs):
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params, pspecs)
