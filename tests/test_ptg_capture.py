"""Graph capture: whole-PTG-taskpool compilation into one XLA
executable (dsl/ptg/capture.py — TPU-first feature, no reference analog;
the fused-executable answer to SURVEY.md §7.3 hard-part 7)."""
import numpy as np
import pytest

from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.ops import (dgetrf_nopiv_taskpool, dgeqrf_taskpool,
                            dpotrf_taskpool, make_spd, pdgemm_taskpool)
from parsec_tpu.ops.dgetrf import make_diag_dominant


def _spd_collection(n, nb, seed=0):
    M = make_spd(n, seed=seed)
    return M, TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)


def test_capture_plan_matches_runtime_task_count():
    _, A = _spd_collection(256, 64)
    cg = ptg.capture(dpotrf_taskpool(A))
    nt = A.nt
    expect = (nt                                # POTRF
              + nt * (nt - 1) // 2              # TRSM
              + nt * (nt - 1) // 2              # SYRK
              + nt * (nt - 1) * (nt - 2) // 6)  # GEMM
    assert cg.nb_tasks == expect


def test_captured_dpotrf_matches_cholesky():
    M, A = _spd_collection(256, 64)
    cg = ptg.capture(dpotrf_taskpool(A))
    cg.run()
    L = np.tril(A.to_numpy())
    assert np.linalg.norm(L @ L.T - M) / np.linalg.norm(M) < 1e-5


def test_captured_matches_runtime_execution():
    """Same taskpool, both execution paths, same answer."""
    import parsec_tpu
    M, A1 = _spd_collection(192, 64, seed=3)
    ptg.capture(dpotrf_taskpool(A1)).run()
    _, A2 = _spd_collection(192, 64, seed=3)
    ctx = parsec_tpu.Context(nb_cores=2, enable_tpu=False)
    try:
        ctx.add_taskpool(dpotrf_taskpool(A2))
        ctx.wait()
    finally:
        ctx.fini()
    np.testing.assert_allclose(np.tril(A1.to_numpy()),
                               np.tril(A2.to_numpy()), rtol=2e-4, atol=2e-4)


def test_captured_dgetrf_nopiv():
    n, nb = 192, 64
    M = make_diag_dominant(n, dtype=np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    ptg.capture(dgetrf_nopiv_taskpool(A)).run()
    LU = A.to_numpy()
    L = np.tril(LU, -1) + np.eye(n, dtype=np.float32)
    U = np.triu(LU)
    assert np.linalg.norm(L @ U - M) / np.linalg.norm(M) < 1e-4


def test_captured_dgeqrf():
    n, nb = 192, 64
    rng = np.random.RandomState(5)
    M = rng.rand(n, n).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dgeqrf_taskpool(A)
    try:
        cg = ptg.capture(tp)
    except ptg.CaptureError as e:
        pytest.skip(f"dgeqrf not capturable: {e}")
    cg.run()
    R = np.triu(A.to_numpy())
    # R from a QR factorization satisfies ||R^T R - M^T M|| ~ 0
    assert np.linalg.norm(R.T @ R - M.T @ M) / np.linalg.norm(M.T @ M) < 1e-3


def test_captured_pdgemm_two_collections():
    n, nb = 128, 64
    rng = np.random.RandomState(7)
    An, Bn = rng.rand(n, n).astype(np.float32), rng.rand(n, n).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(An)
    B = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(Bn)
    C = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(
        np.zeros((n, n), np.float32))
    tp = pdgemm_taskpool(A, B, C, alpha=1.0, beta=0.0)
    cg = ptg.capture(tp)
    cg.run()
    np.testing.assert_allclose(C.to_numpy(), An @ Bn, rtol=1e-3, atol=1e-3)


def test_captured_dpotrf_sharded_over_mesh():
    """Multi-chip capture: every tile pinned to a 2x4 mesh sharding, the
    DAG executes SPMD with XLA-inserted collectives, outputs keep the
    sharding (conftest provides the virtual 8-device CPU mesh)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    M, A = _spd_collection(512, 128, seed=2)
    cg = ptg.capture(dpotrf_taskpool(A))
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("x", "y"))
    sh = NamedSharding(mesh, P("x", "y"))
    fn = cg.sharded_fn(sh)
    tiles = {"descA": {c: jax.device_put(A.tile(*c), sh)
                       for c in A.tiles()}}
    out = fn(tiles)
    jax.block_until_ready(out)
    n, nb = 512, 128
    for arr in out["descA"].values():
        assert arr.sharding.spec == P("x", "y")  # stayed distributed
    Lf = np.zeros((n, n), np.float32)
    for (m, k), arr in out["descA"].items():
        Lf[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = np.asarray(arr)
    L = np.tril(Lf)
    assert np.linalg.norm(L @ L.T - M) / np.linalg.norm(M) < 1e-5


def test_captured_sequence_dposv():
    """dposv = dpotrf ; trsm_lower ; trsm_lower^T fused into ONE XLA
    program via capture_sequence; result matches numpy solve."""
    from parsec_tpu.ops.dtrsm import (dtrsm_lower_taskpool,
                                      dtrsm_lower_trans_taskpool)
    n, nb, nrhs = 192, 64, 64
    M = make_spd(n, seed=9)
    rng = np.random.RandomState(9)
    Bn = rng.rand(n, nrhs).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    B = TwoDimBlockCyclic(n, nrhs, nb, nb, dtype=np.float32).from_numpy(Bn)
    A.name, B.name = "descA", "descB"
    seq = ptg.capture_sequence([
        dpotrf_taskpool(A),
        dtrsm_lower_taskpool(A, B),
        dtrsm_lower_trans_taskpool(A, B),
    ])
    assert seq.nb_tasks > 0
    seq.run()
    X = B.to_numpy()
    ref = np.linalg.solve(M.astype(np.float64), Bn.astype(np.float64))
    assert np.abs(X - ref).max() < 5e-2


def test_captured_sequence_rejects_conflicting_names():
    _, A1 = _spd_collection(128, 64)
    _, A2 = _spd_collection(128, 64)
    A1.name = A2.name = "descA"
    with pytest.raises(ptg.CaptureError, match="different"):
        ptg.capture_sequence([dpotrf_taskpool(A1), dpotrf_taskpool(A2)])


def test_capture_rejects_multirank():
    _, A = _spd_collection(128, 64)
    tp = dpotrf_taskpool(A, rank=0, nb_ranks=4)
    with pytest.raises(ptg.CaptureError, match="single-rank"):
        ptg.capture(tp)


def test_capture_run_keeps_results_on_device():
    """run(device=...) stores result tiles as device copies — no host
    round-trip of intermediate or output tiles."""
    import jax
    import parsec_tpu
    M, A = _spd_collection(256, 64, seed=1)
    ctx = parsec_tpu.init(nb_cores=1)
    try:
        devs = [d for d in ctx.devices if d.device_type == "tpu"]
        if not devs:
            pytest.skip("no accelerator device module")
        dev = devs[0]
        cg = ptg.capture(dpotrf_taskpool(A))
        cg.run(device=dev)
        # every lower tile's newest copy lives on the device
        for (m, k) in A.tiles():
            if m >= k:
                data = A.data_of(m, k)
                assert data.newest_copy().device_id == dev.device_index
        # and the host gather (one sync) is still correct
        L = np.tril(A.to_numpy())
        assert np.linalg.norm(L @ L.T - M) / np.linalg.norm(M) < 1e-5
    finally:
        ctx.fini()
