"""DTD graph capture: record an insert-task sequence, compile it into
ONE jitted XLA executable.

Counterpart of dsl/ptg/capture.py for the dynamic front end. DTD's
correctness model is sequential consistency: the inserted order is by
definition a valid serialization of the discovered DAG (ref: the
insert-loop semantics of parsec_dtd_insert_task, insert_function.h:284 —
deps are derived from tile access order). So capture needs no dependency
analysis at all: replay the recorded tasks in insertion order with jax
tracers as tile payloads and let XLA re-discover the real parallelism
from data flow — the compiler sees exactly the DAG the runtime would
have scheduled, minus the per-task host dispatch.

Scope: task bodies must be the *functional* chore form (the
``add_chore`` convention: one positional arg per inserted param — arrays
for tiles, raw values for VALUE — returning arrays for written flows in
order). Host bodies that mutate numpy arrays in place go through the
runtime instead. Single rank, like PTG capture.

    g = dtd_capture()
    a = g.tile_of_array(np.ones((n, n), np.float32))
    g.insert_task(lambda x, s: x * s, (a, INOUT), (2.0, VALUE))
    # one positional arg per param, OUTPUT tiles included (their
    # incoming array may be None when the tile starts write-only)
    g.insert_task(lambda x, y, _c: x @ y, (a, INPUT), (b, INPUT), (c, OUTPUT))
    g.run()                      # one XLA dispatch for the whole graph
    result = g.value(c)
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import INPUT, OUTPUT, VALUE, AccessMode

__all__ = ["CapturedDTDGraph", "CaptureTile", "dtd_capture"]


class CaptureTile:
    """Handle for one logical tile in a captured graph (the
    parsec_dtd_tile_of analog; identity is the user key; ``idx`` is the
    uniform internal state key — user keys may mix types, which jax's
    pytree key sorting cannot order)."""

    __slots__ = ("key", "idx", "initial")
    _fresh = itertools.count()

    def __init__(self, key: Any, idx: int, initial: Optional[Any]) -> None:
        self.key = key
        self.idx = idx
        self.initial = initial


class CapturedDTDGraph:
    def __init__(self) -> None:
        self._tiles: Dict[Any, CaptureTile] = {}
        # (fn, [(kind, payload)]) where kind in {"tile","value"} and for
        # tiles payload = (tile, written?)
        self._tasks: List[Tuple[Callable, List[Tuple[str, Any]]]] = []
        self._jitted = None
        self._result: Optional[Dict[Any, Any]] = None

    # ------------------------------------------------------------------ #
    # recording (the insert-task surface)                                #
    # ------------------------------------------------------------------ #
    def tile_of_array(self, array: Any, key: Any = None) -> CaptureTile:
        if key is None:
            key = ("anon", next(CaptureTile._fresh))
        t = self._tiles.get(key)
        if t is None:
            t = CaptureTile(key, len(self._tiles), array)
            self._tiles[key] = t
        elif t.initial is not array:
            # re-binding an existing key keeps the FIRST initial; a caller
            # expecting fresh contents would silently compute on stale data
            raise ValueError(
                f"tile key {key!r} already registered with a different "
                f"initial array; captured tiles bind their initial once")
        return t

    def tile(self, key: Any, shape=None, dtype=None) -> CaptureTile:
        """NEW-tile analog: zeros when a shape is given; with no shape
        the tile's first access must be write-only (OUTPUT). A shapeless
        tile may later be re-declared WITH a shape (binds zeros then);
        conflicting shape/dtype re-declarations raise."""
        t = self._tiles.get(key)
        if t is None:
            init = None if shape is None else np.zeros(
                shape, dtype if dtype is not None else np.float32)
            t = CaptureTile(key, len(self._tiles), init)
            self._tiles[key] = t
        elif shape is not None:
            if t.initial is None:
                t.initial = np.zeros(
                    shape, dtype if dtype is not None else np.float32)
            elif (tuple(t.initial.shape) != tuple(shape)
                    or (dtype is not None
                        and t.initial.dtype != np.dtype(dtype))):
                raise ValueError(
                    f"tile key {key!r} already registered with "
                    f"shape={t.initial.shape} dtype={t.initial.dtype}; "
                    f"got shape={tuple(shape)} dtype={dtype}")
        return t

    def insert_task(self, fn: Callable, *args) -> None:
        """``fn`` is the functional chore; ``args`` follow the DTD
        convention: (tile, INPUT|INOUT|OUTPUT) or (value, VALUE) pairs,
        bare values implying VALUE. The capture is invalidated (will be
        re-traced) by any insert after a run."""
        parsed: List[Tuple[str, Any]] = []
        for a in args:
            if isinstance(a, tuple) and len(a) == 2 \
                    and isinstance(a[1], AccessMode):
                val, mode = a
            else:
                val, mode = a, VALUE
            if mode & VALUE:
                parsed.append(("value", val))
                continue
            if not isinstance(val, CaptureTile):
                raise TypeError(
                    f"tracked argument must be a CaptureTile, got {type(val)}")
            parsed.append(("tile", (val, bool(mode & OUTPUT),
                                    bool(mode & INPUT))))
        self._tasks.append((fn, parsed))
        self._jitted = None
        self._result = None

    @property
    def nb_tasks(self) -> int:
        return len(self._tasks)

    # ------------------------------------------------------------------ #
    # execution                                                          #
    # ------------------------------------------------------------------ #
    def _execute(self, state: Dict[Any, Any]) -> Dict[Any, Any]:
        state = dict(state)
        for fn, parsed in self._tasks:
            call_args = []
            written: List[CaptureTile] = []
            for kind, payload in parsed:
                if kind == "value":
                    call_args.append(payload)
                else:
                    tile, writes, _reads = payload
                    call_args.append(state[tile.idx])
                    if writes:
                        written.append(tile)
            outs = fn(*call_args)
            if written:
                if not isinstance(outs, (tuple, list)):
                    outs = (outs,)
                if len(outs) != len(written):
                    raise ValueError(
                        f"{getattr(fn, '__name__', fn)}: returned "
                        f"{len(outs)} outputs for {len(written)} written "
                        f"flows")
                for tile, out in zip(written, outs):
                    state[tile.idx] = out
        return state

    def _initial_state(self) -> Dict[int, Any]:
        # a tile with no initial array is fine iff its first access is
        # write-only (pure OUTPUT): the incoming value is never read, so
        # its placeholder None only ever reaches the body as the
        # conventionally-ignored positional arg
        first_read: Dict[int, bool] = {}
        for _fn, parsed in self._tasks:
            for kind, payload in parsed:
                if kind != "tile":
                    continue
                tile, _writes, reads = payload
                if tile.idx not in first_read:
                    first_read[tile.idx] = reads
        missing = [t.key for t in self._tiles.values()
                   if t.initial is None and first_read.get(t.idx, False)]
        if missing:
            raise ValueError(f"tiles {missing!r} have no initial array")
        return {t.idx: t.initial for t in self._tiles.values()}

    @property
    def fn(self):
        """The jitted executable: {tile_idx: array} in, same out
        (indices are uniform ints so jax can sort the pytree keys)."""
        if self._jitted is None:
            import jax
            self._jitted = jax.jit(self._execute)
        return self._jitted

    def run(self, state: Optional[Dict[Any, Any]] = None) -> Dict[Any, Any]:
        """Execute the captured graph (one XLA dispatch); results are
        readable per tile via :meth:`value`."""
        self._result = self.fn(state or self._initial_state())
        return self._result

    def value(self, tile: CaptureTile) -> Any:
        """The tile's array after the last run (the data_flush analog)."""
        if self._result is None:
            raise RuntimeError("run() the captured graph first")
        return self._result[tile.idx]


def dtd_capture() -> CapturedDTDGraph:
    return CapturedDTDGraph()
