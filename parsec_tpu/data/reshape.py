"""Reshape engine: lazy type/shape conversion across dataflow edges.

Reference behavior: when a producer's datatype differs from what a
consumer declares (e.g. full tile -> lower triangle), a *reshape promise*
(``parsec_datacopy_future_t``) is attached to the edge; the FIRST consumer
to need the data triggers the conversion, concurrent consumers of the same
(copy, type) dedup onto one promise, and the converted copy is released
with the promise (ref: parsec/parsec_reshape.c:1-771, promise structs
parsec/remote_dep.h:86-117; 18 dedicated tests under
tests/collections/reshape/).

TPU-native re-design: a "datatype" is a (dtype, shape, region) descriptor
(data/datatype.py); conversion is an XLA-fusable masked cast instead of an
MPI pack/unpack. Local and remote variants share the promise machinery:
the local trigger converts an existing host/device copy; the remote
variant is armed before the payload exists and converts on arrival.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..core.future import DataCopyFuture
from .data import Coherency, Data, DataCopy
from .datatype import Datatype, dtt_of_array


def reshape_array(arr: Any, dst: Datatype, src: Optional[Datatype] = None) -> Any:
    """Convert ``arr`` to datatype ``dst``: cast + region mask (+ reshape
    when element counts match). The conversion body is pure jnp/numpy —
    under jit XLA fuses it into the consumer (the relayout-kernel analog
    of ce.reshape)."""
    if src is None:
        src = dtt_of_array(arr)
    if arr.shape != tuple(dst.shape):
        if src.nb_elts != dst.nb_elts:
            raise ValueError(
                f"reshape {src.shape}->{dst.shape}: element counts differ")
        arr = arr.reshape(dst.shape)
    if np.dtype(src.dtype) != np.dtype(dst.dtype):
        arr = arr.astype(dst.dtype)
    if dst.region != "full" and dst.region != src.region:
        mask = dst.mask()
        if mask is not None:
            if isinstance(arr, np.ndarray):
                arr = np.where(mask, arr, np.zeros((), dtype=arr.dtype))
            else:
                import jax.numpy as jnp
                arr = jnp.where(jnp.asarray(mask), arr,
                                jnp.zeros((), dtype=arr.dtype))
    return arr


def _needs_reshape(copy: DataCopy, dst: Datatype) -> bool:
    src = copy.dtt
    if src is None:
        payload = copy.payload
        if payload is None:
            return True  # cannot prove compatibility; promise will decide
        src = dtt_of_array(payload)
    return not src.compatible_wire(dst)


class ReshapeRepo:
    """Per-taskpool table of reshape promises with dedup.

    Keyed by (source copy identity, destination datatype): N consumers of
    one produced copy that declare the same [type=...] share ONE converted
    copy, converted once (ref: reshape dedup of concurrent promises,
    parsec_reshape.c setup_matching_reshape paths).
    """

    def __init__(self) -> None:
        self._promises: Dict[Tuple, DataCopyFuture] = {}
        self._lock = threading.Lock()
        self.stats = {"local_promises": 0, "remote_promises": 0,
                      "conversions": 0, "hits": 0}

    # -- local reshape ------------------------------------------------------
    def reshaped_copy(self, copy: Optional[DataCopy], dst: Datatype,
                      es: Any = None) -> Optional[DataCopy]:
        """Return a copy matching ``dst``, converting lazily via a shared
        promise. Non-matching copies are never mutated — the original
        stays valid for consumers that want the producer's type."""
        if copy is None or copy.payload is None:
            return copy
        if not _needs_reshape(copy, dst):
            return copy
        fut = self.promise(copy, dst)
        return fut.get_or_trigger()

    def promise(self, copy: DataCopy, dst: Datatype) -> DataCopyFuture:
        """The shared promise converting ``copy`` to ``dst`` (local
        variant: the source payload already exists)."""
        key = (id(copy), dst)
        with self._lock:
            fut = self._promises.get(key)
            if fut is not None:
                self.stats["hits"] += 1
                return fut

            def trigger(_spec, _copy=copy, _dst=dst):
                self.stats["conversions"] += 1
                src_dtt = _copy.dtt or dtt_of_array(_copy.payload)
                arr = reshape_array(_copy.payload, _dst, src_dtt)
                return _detached_copy(arr, _dst, version=_copy.version)

            fut = DataCopyFuture(spec=dst, trigger_cb=trigger)
            self._promises[key] = fut
            self.stats["local_promises"] += 1
            return fut

    # -- remote reshape -----------------------------------------------------
    def incoming_promise(self, edge_key: Tuple, dst: Datatype
                         ) -> Tuple[DataCopyFuture, Callable[[Any], None]]:
        """Remote variant: the promise is armed BEFORE the payload exists
        (the receiver knows the consumer's type from its own dep lookup,
        ref: remote_dep_mpi_retrieve_datatype both-ends lookup). Returns
        (future, deliver); call ``deliver(ndarray)`` when the wire data
        arrives — consumers already waiting convert exactly once."""
        key = ("remote", edge_key, dst)
        with self._lock:
            ent = self._promises.get(key)
            if ent is not None:
                self.stats["hits"] += 1
                return ent, getattr(ent, "_deliver", lambda a: None)

            arrival = DataCopyFuture(spec=None)

            def trigger(_spec, _dst=dst):
                arr = arrival.get()  # blocks until wire data delivered
                self.stats["conversions"] += 1
                return _detached_copy(reshape_array(arr, _dst), _dst,
                                      version=1)

            fut = DataCopyFuture(spec=dst, trigger_cb=trigger)

            def deliver(arr: Any) -> None:
                if not arrival.is_ready():
                    arrival.set(arr)
                fut.trigger()

            fut._deliver = deliver  # type: ignore[attr-defined]
            self._promises[key] = fut
            self.stats["remote_promises"] += 1
            return fut, deliver

    def clear(self) -> None:
        with self._lock:
            self._promises.clear()


def _detached_copy(arr: Any, dtt: Datatype, version: int = 1) -> DataCopy:
    d = Data(nb_elts=getattr(arr, "size", dtt.nb_elts))
    c = DataCopy(d, 0, payload=arr, dtt=dtt)
    c.version = version
    c.coherency = Coherency.OWNED
    d.attach_copy(c)
    return c
