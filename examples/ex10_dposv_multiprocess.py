"""Ex10: distributed Cholesky solve, launcher-deployed.

Teaches: the multi-process deployment path. The SAME program runs
single-process (`python examples/ex10_dposv_multiprocess.py`) or SPMD
across real OS processes under the launcher:

    python tools/launch.py -n 4 examples/ex10_dposv_multiprocess.py

Each rank's Context auto-wires a TCPCommEngine from the launcher's
PARSEC_MCA_comm_* env (runtime/context.py _comm_from_params — the
analog of mpiexec + MPI_Init handing each process its communicator,
ref: parsec/parsec_mpi_funnelled.c:245-365). The three taskpools of
dposv (dpotrf, two dtrsm sweeps) then run with cross-rank activations,
panel broadcasts, and memory writebacks over the sockets.
"""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.ops import dposv, make_spd


def main(n: int = 128, nb: int = 32, nrhs: int = 16) -> int:
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        rank, nb_ranks = ctx.rank, ctx.nb_ranks
        M = make_spd(n)
        rng = np.random.RandomState(1)
        Bm = (rng.rand(n, nrhs) - 0.5).astype(np.float32)

        def dist(lm, ln, src):
            d = TwoDimBlockCyclic(lm, ln, nb, nb, P=nb_ranks, Q=1,
                                  nodes=nb_ranks, rank=rank,
                                  dtype=np.float32)
            for (i, j) in d.local_tiles():
                np.copyto(d.tile(i, j),
                          src[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
            return d

        A, B = dist(n, n, M), dist(n, nrhs, Bm)
        A.name, B.name = "descA", "descB"
        dposv(ctx, A, B, rank=rank, nb_ranks=nb_ranks)

        ref = np.linalg.solve(M.astype(np.float64), Bm.astype(np.float64))
        err = 0.0
        for (i, j) in B.local_tiles():
            err = max(err, float(np.abs(
                B.tile(i, j) - ref[i * nb:(i + 1) * nb,
                                   j * nb:(j + 1) * nb]).max()))
        assert err < 5e-3, f"rank {rank}: residual {err}"
        print(f"rank {rank}/{nb_ranks}: dposv ok, max_err={err:.2e}")
    finally:
        ctx.fini()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
