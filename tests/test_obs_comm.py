"""Comm-engine telemetry: byte counters balancing across ranks, matched
get/put spans, pending-message gauges (ISSUE 1 tentpole — span tracing
and SDE counters in the comm layer; ref: the T3 premise that
compute/collective overlap must be *measured* before it can be
optimized, arXiv:2401.16677).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.comm import LocalFabric, RemoteDepEngine
from parsec_tpu.dsl import ptg
from parsec_tpu.obs import (COMM_ACTIVE_TRANSFERS, COMM_BYTES_RECEIVED,
                            COMM_BYTES_SENT, COMM_MSGS_RECEIVED,
                            COMM_MSGS_SENT, COMM_PENDING_MESSAGES, CommObs,
                            MetricsRegistry)
from parsec_tpu.profiling.trace import Profile

from tests.conftest import spmd


def _span_counts(profile, name):
    """(#complete spans, #with a valid begin+end) for one span name.
    Comm spans are complete ("X") events: ts is the begin, ts+dur the
    end — equal counts mean every transfer produced a matched pair."""
    doc = profile.to_chrome_trace()
    spans = [e for e in doc["traceEvents"]
             if e.get("name") == name and e.get("ph") == "X"]
    matched = sum(1 for e in spans
                  if isinstance(e.get("ts"), (int, float))
                  and isinstance(e.get("dur"), (int, float))
                  and e["dur"] >= 0)
    return len(spans), matched


def _instrumented_pair():
    fabric = LocalFabric(2)
    engines, metrics, profiles = [], [], []
    for r in range(2):
        eng = fabric.engine(r)
        m = MetricsRegistry()
        p = Profile(rank=r)
        obs = CommObs(m, profile=p)
        obs.register_engine_gauges(eng)
        eng._obs = obs
        engines.append(eng)
        metrics.append(m)
        profiles.append(p)
    return engines, metrics, profiles


def test_get_put_spans_and_byte_balance():
    """Every one-sided get/put produces one matched begin/end span, and
    sent/received byte totals balance across the two ranks."""
    (e0, e1), (m0, m1), (p0, p1) = _instrumented_pair()
    src = np.arange(16, dtype=np.float64).reshape(4, 4)
    h1 = e1.mem_register(src)
    got = []
    e0.get(1, h1.handle_id, got.append)
    # active-transfer gauge is live while the GET is outstanding
    assert m0.read(COMM_ACTIVE_TRANSFERS) == 1
    e1.progress()   # serve the GET request
    e0.progress()   # deliver the data reply
    assert got and np.array_equal(got[0], src)
    assert m0.read(COMM_ACTIVE_TRANSFERS) == 0

    dst = np.zeros((4, 4))
    h0 = e0.mem_register(dst)
    e1.put(0, h0.handle_id, np.ones((4, 4)))
    e0.progress()   # apply the PUT
    np.testing.assert_array_equal(dst, 1.0)

    assert _span_counts(p0, "comm:get") == (1, 1)
    assert _span_counts(p1, "comm:put") == (1, 1)
    # sends happened on both ranks (request one way, data back)
    sent = m0.read(COMM_BYTES_SENT) + m1.read(COMM_BYTES_SENT)
    recv = m0.read(COMM_BYTES_RECEIVED) + m1.read(COMM_BYTES_RECEIVED)
    assert sent > 0 and sent == recv
    msent = m0.read(COMM_MSGS_SENT) + m1.read(COMM_MSGS_SENT)
    mrecv = m0.read(COMM_MSGS_RECEIVED) + m1.read(COMM_MSGS_RECEIVED)
    assert msent == mrecv == 3  # get-req, get-data, put-data


def test_pending_message_gauge_counts_deferred():
    (e0, e1), (m0, m1), _ = _instrumented_pair()
    e0.send_am(1, 77, {"x": 1})   # tag 77 has no handler on rank 1
    e1.progress()
    assert m1.read(COMM_PENDING_MESSAGES) == 1
    # arrival was still counted so totals balance
    assert m1.read(COMM_MSGS_RECEIVED) == 1
    seen = []
    e1.tag_register(77, lambda s, p: seen.append((s, p)))
    assert seen == [(0, {"x": 1})]
    assert m1.read(COMM_PENDING_MESSAGES) == 0


CHAIN_JDF = """
descA [ type="collection" ]
NB [ type="int" ]

Step(k)

k = 0 .. NB

: descA( k, 0 )

RW A <- (k == 0) ? descA( k, 0 ) : A Step( k-1 )
     -> (k == NB) ? descA( k, 0 ) : A Step( k+1 )

BODY
{
    A[0, 0] += 1.0
}
END
"""


def _chain_rank(rank, fabric, nb_ranks, NB, tile=4):
    eng = RemoteDepEngine(fabric.engine(rank))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False,
                             profile=True)
    try:
        coll = TwoDimBlockCyclic((NB + 1) * tile, tile, tile, tile,
                                 P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
        coll.name = "descA"
        tp = ptg.compile_jdf(CHAIN_JDF, name="chain").new(
            descA=coll, NB=NB, rank=rank, nb_ranks=nb_ranks)
        ctx.add_taskpool(tp)
        ctx.wait()
        eng.ce.progress()  # drain any trailing replies before sampling
        snap = ctx.sde.snapshot()
        gets = _span_counts(ctx.profile, "comm:get")
        return snap, gets
    finally:
        ctx.fini()


@pytest.mark.parametrize("nb_ranks", [2, 4])
def test_multirank_chain_byte_balance(nb_ranks):
    """Every hop of the chain is a remote dep; with the short-message
    limit forced to 0 every payload goes through the GET rendezvous.
    Across ranks the sent and received totals must agree, and every
    rank's GETs show up as matched span pairs."""
    NB = 7
    parsec_tpu.params.set_cmdline("runtime_comm_short_limit", "0")
    try:
        results, _fabric = spmd(
            nb_ranks, lambda r, f: _chain_rank(r, f, nb_ranks, NB))
    finally:
        parsec_tpu.params.unset_cmdline("runtime_comm_short_limit")
    sent = sum(s.get(COMM_BYTES_SENT, 0) for s, _ in results)
    recv = sum(s.get(COMM_BYTES_RECEIVED, 0) for s, _ in results)
    assert sent > 0 and sent == recv
    msgs_s = sum(s.get(COMM_MSGS_SENT, 0) for s, _ in results)
    msgs_r = sum(s.get(COMM_MSGS_RECEIVED, 0) for s, _ in results)
    assert msgs_s == msgs_r
    total_gets = 0
    for _snap, (b, e) in results:
        assert b == e  # matched begin/end pairs on every rank
        total_gets += b
    # NB cross-rank hops, each a rendezvous GET (round-robin row
    # distribution makes every hop remote)
    assert total_gets == NB
