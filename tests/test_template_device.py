"""Template device module (ref: parsec/mca/device/template — the
skeleton cloned to bring up a new device type) and PTG routing of
non-tpu BODY types to their device modules."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.devices import TemplateDevice

JDF = """
descA [ type="collection" ]
NB [ type="int" ]

Scale(k)

k = 0 .. NB-1

: descA( k )

RW A <- descA( k )
     -> descA( k )

BODY [type=template]
{
    A = A * 3.0
}
END
"""


def _run(attach_template):
    from parsec_tpu.dsl import ptg
    from parsec_tpu.collections.collection import LocalArrayCollection

    ctx = parsec_tpu.Context(nb_cores=2, enable_tpu=False)
    try:
        dev = None
        if attach_template:
            dev = TemplateDevice(len(ctx.devices))
            ctx.devices.append(dev)
        base = np.concatenate(
            [np.full((4, 4), float(i + 1), np.float32) for i in range(5)])
        coll = LocalArrayCollection(base, nb_chunks=5)
        coll.name = "descA"
        tp = ptg.compile_jdf(JDF, name="scale").new(descA=coll, NB=5)
        ctx.add_taskpool(tp)
        ctx.wait()
        vals = [float(np.asarray(coll.data_of(i).newest_copy().payload)[0, 0])
                for i in range(5)]
        return vals, dev
    finally:
        ctx.fini()


def test_template_device_executes_chores():
    vals, dev = _run(attach_template=True)
    assert vals == [3.0 * (i + 1) for i in range(5)]
    assert dev.stats["tasks"] == 5
    assert dev.executed_tasks == 5


def test_template_body_falls_through_without_device():
    """No device of that type attached: HookReturn.NEXT falls through to
    the interpreted host chore (the reference's chore_mask walk)."""
    vals, _ = _run(attach_template=False)
    assert vals == [3.0 * (i + 1) for i in range(5)]


def test_custom_executor_is_used():
    calls = []

    def executor(fn, task, arrays):
        calls.append(task.task_class.name)
        return fn(task, arrays)

    from parsec_tpu.dsl import ptg
    from parsec_tpu.collections.collection import LocalArrayCollection

    ctx = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    try:
        ctx.devices.append(TemplateDevice(len(ctx.devices),
                                          executor=executor))
        coll = LocalArrayCollection(np.ones((2, 2), np.float32), nb_chunks=1)
        coll.name = "descA"
        tp = ptg.compile_jdf(JDF, name="scale").new(descA=coll, NB=1)
        ctx.add_taskpool(tp)
        ctx.wait()
    finally:
        ctx.fini()
    assert calls == ["Scale"]
