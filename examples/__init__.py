"""Tutorial examples, mirroring the reference's examples/Ex00-Ex07 series
(ref: examples/Ex00_StartStop.c .. Ex07_RAW_CTL.jdf). Each module is a
runnable script (``python examples/ex02_chain.py``) and exports ``main()``
so the test suite can execute it (tests/test_examples.py).
"""
