"""Foreign-language bindings (ref: parsec/fortran/ — here the host
runtime is Python, so the foreign side is C: parsec_tpu_c.h + the
libparsec_tpu_c embedding shim, with chelper.py as the marshalling
layer)."""
