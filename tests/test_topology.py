"""Topology discovery + locality-aware scheduling (the hwloc analog:
runtime/topology.py; lfq steal chain ref sched_lfq_module.c:59-199)."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.runtime.topology import CPUInfo, HostTopology, parse_cpulist


def _fake_topo():
    """2 packages x 1 NUMA each x 2 L3-sharing pairs x SMT-2:
    cpus 0-7; (0,1) SMT on core A share L2; (0,1,2,3) share L3/numa0/pkg0;
    (4..7) mirror on package 1."""
    cpus = {}
    for c in range(8):
        pkg = c // 4
        core = (pkg << 16) | ((c % 4) // 2)
        l2 = (c // 2) * 2          # SMT pair shares L2
        l3 = pkg * 4               # whole package shares L3
        cpus[c] = CPUInfo(cpu=c, core=core, l2=l2, l3=l3, numa=pkg,
                          package=pkg)
    return HostTopology(cpus)


def test_parse_cpulist():
    assert parse_cpulist("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
    assert parse_cpulist("") == []
    assert parse_cpulist("5") == [5]


def test_distance_ladder():
    t = _fake_topo()
    assert t.distance(0, 0) == 0
    assert t.distance(0, 1) == 1     # SMT sibling
    assert t.distance(0, 2) == 3     # same L3, different L2
    assert t.distance(0, 4) == 6     # other package (no shared level)
    assert t.distance(2, 3) == 1


def test_steal_order_is_locality_sorted():
    t = _fake_topo()
    order = t.steal_order(0, range(8))
    # sibling first, then L3-mates, then the far package
    assert order[0] == 1
    assert set(order[1:3]) == {2, 3}
    assert set(order[3:]) == {4, 5, 6, 7}
    d = [t.distance(0, c) for c in order]
    assert d == sorted(d), "steal order must be non-decreasing distance"


def test_discover_on_this_host():
    t = HostTopology.discover()
    assert len(t.cpus) >= 1
    for c in t.cpus:
        assert t.distance(c, c) == 0


def _ctx_with_fake_binding(nb_cores, sched, topo, binding):
    ctx = parsec_tpu.init(nb_cores=nb_cores)
    ctx._topology_override = topo
    ctx._topo_binding_override = binding
    from parsec_tpu.sched import sched_new
    ctx.scheduler = sched_new(sched)
    ctx.scheduler.install(ctx)
    for es in ctx.execution_streams:
        ctx.scheduler.flow_init(es)
    return ctx


def test_lfq_steal_chain_locality_ordered():
    """With bound threads the lfq steal chain must walk nearest-first —
    provably locality-ordered, not the id ring."""
    topo = _fake_topo()
    binding = {0: 0, 1: 4, 2: 1, 3: 2}   # th1 is FAR (pkg1), th2 SMT-near
    ctx = _ctx_with_fake_binding(4, "lfq", topo, binding)
    try:
        es0 = ctx.execution_streams[0]
        chain = ctx.scheduler.steal_chain(es0)
        cores = [binding[p.th_id] for p in chain]
        dists = [topo.distance(0, c) for c in cores]
        assert dists == sorted(dists)
        assert cores[0] == 1            # SMT sibling stolen from first
        assert cores[-1] == 4           # far package last
        # and this differs from the plain id ring (th1 would be first)
        assert chain[0].th_id != 1
    finally:
        ctx.fini()


def test_lhq_groups_by_l3_domain():
    """lhq's middle level must be the topology's L3 domain when bound —
    ESes on one package share a queue, the far package gets its own
    (lhq != lfq in structure, the round-1 VERDICT's complaint)."""
    topo = _fake_topo()
    binding = {0: 0, 1: 1, 2: 4, 3: 5}   # two per package
    ctx = _ctx_with_fake_binding(4, "lhq", topo, binding)
    try:
        sched = ctx.scheduler
        es = ctx.execution_streams
        assert es[0]._lhq_gid == es[1]._lhq_gid       # same L3 domain
        assert es[2]._lhq_gid == es[3]._lhq_gid
        assert es[0]._lhq_gid != es[2]._lhq_gid       # packages split
        assert len(sched._group_queues) == 2
    finally:
        ctx.fini()


def test_lhq_unbound_falls_back_to_vp():
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        from parsec_tpu.sched import sched_new
        sched = sched_new("lhq")
        sched.install(ctx)
        for es in ctx.execution_streams:
            sched.flow_init(es)
        assert all(es._lhq_gid[0] == "vp" for es in ctx.execution_streams)
    finally:
        ctx.fini()


def test_schedulers_still_run_dags():
    """All three locality policies still execute a real DAG correctly."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    from parsec_tpu.utils.params import params

    M = make_spd(512, dtype=np.float32)
    for name in ("lfq", "lhq", "ltq"):
        params.set_cmdline("sched", name)
        try:
            ctx = parsec_tpu.init(nb_cores=2)
            A = TwoDimBlockCyclic(512, 512, 128, 128,
                                  dtype=np.float32).from_numpy(M)
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            L = np.tril(A.to_numpy()).astype(np.float64)
            assert np.allclose(L, np.linalg.cholesky(M.astype(np.float64)),
                               atol=1e-2), name
            ctx.fini()
        finally:
            params.set_cmdline("sched", "lfq")
