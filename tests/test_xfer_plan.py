"""ISSUE 19 — the xfer/ redistribution planner and loopback transport.

Covers:
- plan determinism: the schedule is a pure function of the two
  distributions (byte-identical across repeated builds and across
  independently constructed geometry objects), with golden structure
  for the canonical 4->2, 1x4->2x2, and 4->1 reshards;
- coalescing: one Transfer per cross-rank (src, dst) pair, so rounds
  and transfers stay strictly below the per-tile GET storm count;
- execution: knob-gated redistribute() fast path is bit-identical to
  the classic DTD pool, repeated runs byte-identical, digest handshake
  asserted across ranks (and a diverging plan fails LOUDLY);
- the in-process loopback transfer backend that un-skips the
  jax.experimental.transfer tests on CPU-only builds.
"""
import threading

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.collections.redistribute import redistribute
from parsec_tpu.comm import RemoteDepEngine
from parsec_tpu.utils.params import params
from parsec_tpu.xfer import build_plan, run_redistribution
from test_comm_multirank import spmd


def _grid(lm, ln, mb, nb, P, Q, nodes, rank=0):
    return TwoDimBlockCyclic(lm, ln, mb, nb, P=P, Q=Q,
                             nodes=nodes, rank=rank, dtype=np.float64)


# --------------------------------------------------------------------- #
# plan construction                                                     #
# --------------------------------------------------------------------- #
def test_plan_golden_4_to_2():
    """P=4 -> P=2 row-cyclic reshard of a 4x4 tile grid: rows 0/1 stay
    local, rows 2/3 each coalesce into ONE transfer, and both pairs
    share the single (d - s) % 4 == 2 round."""
    src = _grid(8, 8, 2, 2, P=4, Q=1, nodes=4)
    tgt = _grid(8, 8, 2, 2, P=2, Q=1, nodes=4)
    plan = build_plan(src, tgt)
    assert plan.nb_ranks == 4
    assert len(plan.local) == 8            # tile rows 0 and 1
    assert plan.n_rounds == 1
    assert plan.n_transfers == 2           # (2->0) and (3->1), coalesced
    assert plan.tile_moves == 8
    (rnd,) = plan.rounds
    assert [(t.src, t.dst, len(t.tiles)) for t in rnd] == \
        [(2, 0, 4), (3, 1, 4)]


def test_plan_golden_1x4_to_2x2():
    """1x4 -> 2x2 grid flip: every coord whose owners differ moves,
    bucketed per (src, dst) pair — strictly fewer transfers than the
    per-tile storm would pay."""
    src = _grid(8, 8, 2, 2, P=1, Q=4, nodes=4)
    tgt = _grid(8, 8, 2, 2, P=2, Q=2, nodes=4)
    plan = build_plan(src, tgt)
    moved = plan.tile_moves
    assert moved + len(plan.local) == 16
    assert moved > 0
    assert plan.n_transfers < moved        # coalescing bought something
    for rnd in plan.rounds:
        # alltoall shape: within a round every sender/receiver is unique
        assert len({t.src for t in rnd}) == len(rnd)
        assert len({t.dst for t in rnd}) == len(rnd)
        for t in rnd:
            assert t.tiles == tuple(sorted(t.tiles))


def test_plan_golden_4_to_1():
    """Gather: P=4 -> P=1 concentrates everything on rank 0 — three
    coalesced transfers, one per source, spread over three rounds."""
    src = _grid(8, 8, 2, 2, P=4, Q=1, nodes=4)
    tgt = _grid(8, 8, 2, 2, P=1, Q=1, nodes=4)
    plan = build_plan(src, tgt)
    assert len(plan.local) == 4
    assert plan.n_transfers == 3
    assert plan.n_rounds == 3
    assert sorted((t.src, t.dst) for rnd in plan.rounds for t in rnd) \
        == [(1, 0), (2, 0), (3, 0)]


def test_plan_pure_function_of_distributions():
    """Two independently constructed geometry pairs produce
    byte-identical plans (and digests) — across ANY viewing rank: the
    schedule depends on the distributions, never on runtime state."""
    mk = lambda r: (_grid(12, 12, 3, 3, P=4, Q=1, nodes=4, rank=r),
                    _grid(12, 12, 3, 3, P=2, Q=2, nodes=4, rank=r))
    plans = [build_plan(*mk(r)) for r in range(4)] + [build_plan(*mk(0))]
    assert len({p.digest() for p in plans}) == 1
    assert all(p == plans[0] for p in plans)


# --------------------------------------------------------------------- #
# execution                                                             #
# --------------------------------------------------------------------- #
def _run_planned_reshard(nb_ranks, src_np, runs=1):
    """Knob-gated redistribute() on a whole-matrix reshard; returns
    (per-rank taskpool surrogates, assembled matrices, digests).
    24x24 over 3x3 tiles = an 8x8 tile grid, so every cross-rank
    (src, dst) pair coalesces SEVERAL tiles."""
    lm = ln = 24

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            outs = []
            for _ in range(runs):
                Y = _grid(lm, ln, 3, 3, P=nb_ranks, Q=1,
                          nodes=nb_ranks, rank=rank).from_numpy(src_np)
                T = _grid(lm, ln, 3, 3, P=1, Q=nb_ranks,
                          nodes=nb_ranks, rank=rank).from_numpy(
                              np.zeros((lm, ln)))
                tp = redistribute(Y, T, lm, ln, context=ctx)
                tiles = {c: np.array(T.tile(*c)) for c in T.local_tiles()}
                outs.append((tp, tiles))
            return outs
        finally:
            ctx.fini()

    results, _ = spmd(nb_ranks, rank_fn)
    assembled = []
    for run in range(runs):
        got = np.zeros((lm, ln))
        for r in range(nb_ranks):
            for (m, n), arr in results[r][run][1].items():
                got[m * 3:(m + 1) * 3, n * 3:(n + 1) * 3] = arr
        assembled.append(got)
    return results, assembled


def test_planned_redistribute_bit_identical_and_beats_storm():
    """xfer_collective_redist: the fast path must (a) deliver the
    bit-identical matrix, (b) return the planner surrogate whose round
    count is strictly below the per-tile move count (the GET storm's
    transfer count), (c) agree on the digest across every rank, and
    (d) stay byte-identical across repeated runs."""
    src_np = np.random.RandomState(7).rand(24, 24)
    params.set_cmdline("xfer_collective_redist", "1")
    try:
        results, assembled = _run_planned_reshard(4, src_np, runs=2)
    finally:
        params.unset_cmdline("xfer_collective_redist")
    for got in assembled:
        np.testing.assert_array_equal(got, src_np)
    digests = set()
    for r in range(4):
        for tp, _tiles in results[r]:
            assert hasattr(tp, "plan_digest"), \
                "knob set: planner surrogate expected, got DTD pool"
            assert tp.wire_lossless is True
            assert tp.redist_rounds < tp.redist_tile_moves
            assert tp.redist_transfers < tp.redist_tile_moves
            assert tp.redist_bytes > 0
            digests.add(tp.plan_digest)
    assert len(digests) == 1, digests


def test_planned_redistribute_knob_unset_keeps_dtd_pool():
    """Inertness: without the knob the classic DTD taskpool runs (no
    planner surface on the returned pool) and the result is identical."""
    src_np = np.random.RandomState(8).rand(24, 24)
    results, assembled = _run_planned_reshard(2, src_np)
    np.testing.assert_array_equal(assembled[0], src_np)
    for r in range(2):
        tp, _tiles = results[r][0]
        assert not hasattr(tp, "plan_digest")


def test_plan_digest_divergence_fails_loudly():
    """A rank whose target distribution disagrees must die in the
    digest handshake — never deadlock in a half-joined round."""
    nb = 2

    def rank_fn(rank, fabric):
        ce = fabric.engine(rank)
        src = _grid(8, 8, 2, 2, P=nb, Q=1, nodes=nb, rank=rank)
        src.from_numpy(np.zeros((8, 8)))
        # rank 1 flips the grid: plans diverge
        tgt = _grid(8, 8, 2, 2, P=1, Q=nb, nodes=nb, rank=rank) \
            if rank == 0 else _grid(8, 8, 2, 2, P=nb, Q=1,
                                    nodes=nb, rank=rank)
        tgt.from_numpy(np.zeros((8, 8)))
        run_redistribution(src, tgt, ce, timeout=30.0)

    with pytest.raises(RuntimeError, match="diverges"):
        spmd(nb, rank_fn)


def test_run_redistribution_bumps_round_gauge():
    """REDIST_ROUNDS: every executed plan adds its round count to the
    engine-owned dplane_stats the obs gauges poll."""
    nb = 2
    src_np = np.random.RandomState(9).rand(8, 8)

    def rank_fn(rank, fabric):
        ce = fabric.engine(rank)
        src = _grid(8, 8, 2, 2, P=nb, Q=1, nodes=nb,
                    rank=rank).from_numpy(src_np)
        tgt = _grid(8, 8, 2, 2, P=1, Q=nb, nodes=nb,
                    rank=rank).from_numpy(np.zeros((8, 8)))
        tp = run_redistribution(src, tgt, ce, timeout=30.0)
        return tp.redist_rounds, dict(ce.dplane_stats)

    results, _ = spmd(nb, rank_fn)
    for rounds, stats in results:
        assert rounds >= 1
        assert stats["redist_rounds"] == rounds


# --------------------------------------------------------------------- #
# loopback transfer backend                                             #
# --------------------------------------------------------------------- #
def test_loopback_roundtrip_and_one_pull_contract():
    pytest.importorskip("jax")
    import jax
    from parsec_tpu.xfer.loopback import LoopbackTransferServer
    a = LoopbackTransferServer("127.0.0.1:0")
    b = LoopbackTransferServer("127.0.0.1:0")
    try:
        arr = np.arange(24, dtype=np.float32).reshape(4, 6)
        a.await_pull(77, [arr])
        conn = b.connect(a.address())
        spec = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
        (out,) = conn.pull(77, [spec])
        np.testing.assert_array_equal(np.asarray(out), arr)
        # pop-on-serve: a parked buffer serves exactly one pull
        with pytest.raises(KeyError):
            conn.pull(77, [spec])
        with pytest.raises(KeyError):
            conn.pull(12345, [spec])   # never parked
    finally:
        a.close()
        b.close()


def test_loopback_concurrent_pulls():
    """Many uuids pulled concurrently over one connection (the lock
    serializes round-trips, so interleaved threads stay correct)."""
    pytest.importorskip("jax")
    import jax
    from parsec_tpu.xfer.loopback import LoopbackTransferServer
    a = LoopbackTransferServer("127.0.0.1:0")
    b = LoopbackTransferServer("127.0.0.1:0")
    try:
        arrs = {u: np.random.RandomState(u).rand(32).astype(np.float32)
                for u in range(1, 9)}
        for u, arr in arrs.items():
            a.await_pull(u, [arr])
        conn = b.connect(a.address())
        outs, errs = {}, []

        def puller(u):
            try:
                spec = jax.ShapeDtypeStruct((32,), np.float32)
                outs[u] = np.asarray(conn.pull(u, [spec])[0])
            except BaseException as e:
                errs.append(e)

        ts = [threading.Thread(target=puller, args=(u,)) for u in arrs]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert not errs, errs
        for u, arr in arrs.items():
            np.testing.assert_array_equal(outs[u], arr)
    finally:
        a.close()
        b.close()


def test_backend_resolution():
    from parsec_tpu.comm.xfer import _resolve_backend
    mod, name = _resolve_backend("loopback")
    assert name == "loopback"
    mod_auto, name_auto = _resolve_backend("auto")
    try:
        from jax.experimental import transfer  # noqa: F401
        assert name_auto == "native"
    except ImportError:
        assert name_auto == "loopback"
        with pytest.raises(ImportError):
            _resolve_backend("native")
    with pytest.raises(ValueError):
        _resolve_backend("dcn")
