/* C embedding API for the parsec_tpu runtime — the reference's
 * second-language bindings analog (ref: parsec/fortran/parsecf.F90:
 * init/fini, taskpool create/wait, profiling wrappers for F90 programs).
 * Here the host runtime is Python, so the foreign language is C/C++: this
 * header + libparsec_tpu_c let a C program initialize the runtime, build
 * a DTD taskpool, insert tasks whose bodies are C function pointers over
 * raw tile buffers, and wait for completion.
 *
 * Build: compile parsec_tpu_c.c against libpython (python3-config
 * --includes --embed --ldflags); or call
 * python -m parsec_tpu.bindings.build to produce libparsec_tpu_c.so.
 *
 * Threading: call all ptc_* functions from the thread that called
 * ptc_init (it owns the embedded interpreter's main state). Task bodies
 * run on runtime worker threads; the runtime marshals tile buffers in and
 * out around each call.
 */
#ifndef PARSEC_TPU_C_H
#define PARSEC_TPU_C_H

#ifdef __cplusplus
extern "C" {
#endif

typedef struct ptc_context ptc_context;
typedef struct ptc_taskpool ptc_taskpool;
typedef struct ptc_tile ptc_tile;

/* Tile access modes (ref: PARSEC_INPUT/OUTPUT/INOUT). */
enum { PTC_INPUT = 0, PTC_OUTPUT = 1, PTC_INOUT = 2 };

/* A task body: tiles[i] points at tile i's elements (row-major float32,
 * rows*cols elements, writable for OUTPUT/INOUT). */
typedef void (*ptc_body_fn)(float **tiles, int ntiles, void *user);

/* Runtime lifecycle. Returns NULL on failure. nb_cores <= 0 = default. */
ptc_context *ptc_init(int nb_cores);
void ptc_fini(ptc_context *ctx);

/* DTD taskpool lifecycle. The handle stays valid (and ptc_taskpool_wait
 * may be retried on failure) until ptc_taskpool_free. */
ptc_taskpool *ptc_dtd_taskpool_new(ptc_context *ctx);
int ptc_taskpool_wait(ptc_taskpool *tp);          /* 0 on success */
int ptc_data_flush_all(ptc_taskpool *tp);         /* 0 on success */
void ptc_taskpool_free(ptc_taskpool *tp);

/* Wrap caller-owned row-major float32 data as a tracked tile. The buffer
 * must outlive the taskpool; after ptc_data_flush_all + wait it holds the
 * final values. */
ptc_tile *ptc_tile_of_dense(ptc_taskpool *tp, float *data,
                            long rows, long cols);
/* Release a tile handle (after the owning taskpool completed). */
void ptc_tile_free(ptc_tile *tile);

/* Insert one task: fn(tile buffers..., user) with per-tile access modes
 * driving dependency discovery. Returns 0 on success. */
int ptc_insert_task(ptc_taskpool *tp, ptc_body_fn fn, void *user,
                    int ntiles, ptc_tile **tiles, const int *modes);

/* Last error message ("" when none), version string. */
const char *ptc_last_error(void);
const char *ptc_version(void);

#ifdef __cplusplus
}
#endif
#endif /* PARSEC_TPU_C_H */
