"""Ex12: turbo static dispatch — the native per-task fast path.

Teaches: ``ptg_dep_management=static`` lowers a single-rank PTG pool to
flat CSR arrays, and eligible pools then run on the TURBO engine
(dsl/ptg/turbo.py): select→release in a C priority heap
(NativeDAG.run_loop), data binding precompiled into (pool, row) slot
tables, ONE XLA call per task, lazy device-resident writebacks. This is
the reference's scheduling.c hot loop + index-array dep mode, rebuilt
TPU-first — per-task dispatch at native speed while keeping true
per-task execution semantics (priorities honored, in-place copy
mutation, any dependence-respecting order).

Read results through the coherency API (``A.to_numpy()`` /
``data.sync_to_host()``): tiles stay device-resident and pull lazily,
one tile per read.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.ops import dpotrf_taskpool, make_spd
from parsec_tpu.utils.params import params


def main(n: int = 512, nb: int = 128) -> int:
    params.set_cmdline("ptg_dep_management", "static")
    ctx = None
    try:
        ctx = parsec_tpu.init(nb_cores=2)
        M = make_spd(n, dtype=np.float32)
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        tp = dpotrf_taskpool(A)
        ctx.add_taskpool(tp)
        ctx.wait()

        r = tp._turbo
        assert r is not None, "turbo did not engage"
        print(f"turbo: {r.stats['tasks']} tasks, one XLA call each, "
              f"native loop={r.stats['native_loop']}, "
              f"dispatch {r.stats['dispatch_secs'] * 1e6 / r.stats['tasks']:.0f} us/task")

        L = np.tril(A.to_numpy())          # lazy per-tile pulls
        resid = float(np.abs(L @ L.T - M).max() / np.abs(M).max())
        print(f"||L L^T - M||/||M|| = {resid:.2e}")
        assert resid < 1e-4
        return 0
    finally:
        params.unset_cmdline("ptg_dep_management")
        if ctx is not None:
            ctx.fini()


if __name__ == "__main__":
    sys.exit(main())
