"""Cross-rank flow tracing (ISSUE 15): wire trace contexts stamped on
data-plane messages, Chrome-trace flow pairs shared between sender and
receiver, mixed-version/knob-unset wire bit-identity, the failure
forensics dump, and stage-task spans carrying member contexts.
"""
import json

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.comm import LocalFabric, RemoteDepEngine
from parsec_tpu.comm.engine import (FlowIds, RankFailedError, TAG_ACTIVATE,
                                    TAG_DTD_DATA, TAG_TERMDET)
from parsec_tpu.obs import (CommObs, MetricsRegistry, OBS_FLOW_RECV,
                            OBS_FLOW_SENT, flow_event_id,
                            validate_chrome_trace)
from parsec_tpu.ops import dpotrf_taskpool, make_spd
from parsec_tpu.profiling.trace import Profile
from parsec_tpu.utils.params import params

from tests.conftest import spmd


def _flow_pair():
    """Two local-fabric engines with telemetry AND the flow allocator
    armed (what the obs wiring does under ``obs_flow``)."""
    fabric = LocalFabric(2)
    engines, metrics, profiles = [], [], []
    for r in range(2):
        eng = fabric.engine(r)
        m = MetricsRegistry()
        p = Profile(rank=r)
        obs = CommObs(m, profile=p)
        eng._obs = obs
        eng._flow = FlowIds(r)
        engines.append(eng)
        metrics.append(m)
        profiles.append(p)
    return engines, metrics, profiles


def _flow_events(profile, phase=None):
    doc = profile.to_chrome_trace()
    return [e for e in doc["traceEvents"]
            if e.get("ph") in (("s", "f") if phase is None else (phase,))]


def test_flow_stamp_shares_one_id_across_ranks():
    """One activation send produces a ``ph:"s"`` on the sender and a
    ``ph:"f"`` on the receiver with the SAME flow id, the receiver's
    payload carries the context, and the caller's dict is unmutated."""
    (e0, e1), (m0, m1), (p0, p1) = _flow_pair()
    seen = []
    e1.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
    msg = {"tp_id": 0, "root": 0, "ranks": [1], "edges": {1: []},
           "data": np.ones((4, 4))}
    e0.send_am(1, TAG_ACTIVATE, msg)
    assert "_tr" not in msg, "sender's dict must never be mutated"
    e1.progress()
    assert seen and seen[0].get("_tr") == (0, 1)
    s_ev = _flow_events(p0, "s")
    f_ev = _flow_events(p1, "f")
    assert len(s_ev) == 1 and len(f_ev) == 1
    assert s_ev[0]["id"] == f_ev[0]["id"] == flow_event_id((0, 1))
    assert s_ev[0]["name"] == f_ev[0]["name"] == "flow:activate"
    assert m0.read(OBS_FLOW_SENT) == 1
    assert m1.read(OBS_FLOW_RECV) == 1
    # each rank's own export validates with the halves unmatched; the
    # two docs concatenated pair up
    d0, d1 = p0.to_chrome_trace(), p1.to_chrome_trace()
    assert validate_chrome_trace(d0)["unmatched_flows"] == 1
    both = {"traceEvents": d0["traceEvents"] + d1["traceEvents"]}
    v = validate_chrome_trace(both)
    assert v["flows"] == 1 and v["unmatched_flows"] == 0


def test_every_hop_gets_a_fresh_context():
    """The SAME payload dict sent to several destinations (the bcast
    fan-out) is stamped per hop — distinct span ids, one edge each."""
    fabric = LocalFabric(3)
    engines = []
    for r in range(3):
        eng = fabric.engine(r)
        eng._obs = CommObs(MetricsRegistry(), profile=Profile(rank=r))
        eng._flow = FlowIds(r)
        engines.append(eng)
    got = {}
    for r in (1, 2):
        engines[r].tag_register(
            TAG_DTD_DATA, lambda src, pl, r=r: got.setdefault(r, pl))
    msg = {"tp_id": 0, "tile": (0, 0), "seq": 1, "data": np.zeros(4)}
    engines[0].send_am(1, TAG_DTD_DATA, msg)
    engines[0].send_am(2, TAG_DTD_DATA, msg)
    engines[1].progress()
    engines[2].progress()
    assert got[1]["_tr"] != got[2]["_tr"]
    assert {got[1]["_tr"], got[2]["_tr"]} == {(0, 1), (0, 2)}


def test_declined_stamp_strips_forwarded_context():
    """A bcast hop re-sends the RECEIVED dict; when the stamp declines
    (e.g. the child peer never negotiated "tr"), the upstream context
    must be STRIPPED, not forwarded — a mixed-version peer's wire
    bytes stay knob-unset-identical and the upstream edge never gains
    a second receive half (code-review regression)."""
    (e0, _e1), _m, _p = _flow_pair()
    e0.flow_to = lambda dst: False          # every peer declines
    fwd = {"tp_id": 0, "edges": {}, "_tr": (9, 123)}
    out, ctx = e0._flow_stamp(1, TAG_ACTIVATE, fwd)
    assert ctx is None
    assert "_tr" not in out
    assert fwd["_tr"] == (9, 123), "caller's dict must not be mutated"
    # a self-send decline strips too; a control/user tag passes through
    # UNTOUCHED — an application payload's "_tr" is not ours to strip
    out2, _ = e0._flow_stamp(0, TAG_ACTIVATE, fwd)
    assert "_tr" not in out2
    out3, _ = e0._flow_stamp(1, TAG_TERMDET, fwd)
    assert out3 is fwd and out3["_tr"] == (9, 123)


def test_control_tags_and_self_sends_never_stamped():
    (e0, e1), _m, (p0, _p1) = _flow_pair()
    seen = []
    e1.tag_register(TAG_TERMDET, lambda src, pl: seen.append(pl))
    e0.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
    e0.send_am(1, TAG_TERMDET, {"op": "wave"})          # control tag
    e0.send_am(0, TAG_ACTIVATE, {"tp_id": 0, "edges": {}})  # self-send
    e1.progress()
    e0.progress()
    assert len(seen) == 2
    assert all("_tr" not in pl for pl in seen)
    assert not _flow_events(p0)


def test_flow_off_is_inert():
    """Without the allocator armed (knob unset), payloads and traces
    carry nothing."""
    fabric = LocalFabric(2)
    e0, e1 = fabric.engine(0), fabric.engine(1)
    p0 = Profile(rank=0)
    e0._obs = CommObs(MetricsRegistry(), profile=p0)
    e1._obs = CommObs(MetricsRegistry(), profile=Profile(rank=1))
    seen = []
    e1.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
    e0.send_am(1, TAG_ACTIVATE, {"tp_id": 0, "edges": {}})
    e1.progress()
    assert seen and "_tr" not in seen[0]
    assert not _flow_events(p0)


def test_tcp_mixed_version_peer_negotiates_down():
    """Over real TCP, a peer whose HELLO never advertised "tr" (knob
    unset there) receives UNstamped payloads even though the sender has
    flow tracing armed — the byte-level twin rides the bench capture
    differential (bench_trace_capture_identity)."""
    import time
    from parsec_tpu.comm.tcp import TCPCommEngine, free_ports

    eps = [("127.0.0.1", p) for p in free_ports(2)]
    import threading
    engines = [None, None]

    def boot(r):
        engines[r] = TCPCommEngine(r, eps, obs_flow=(r == 0))
    ts = [threading.Thread(target=boot, args=(r,)) for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    e0, e1 = engines
    try:
        e0._obs = CommObs(MetricsRegistry(), profile=Profile(rank=0))
        e0._flow = FlowIds(0)
        seen = []
        e1.tag_register(TAG_ACTIVATE, lambda src, pl: seen.append(pl))
        # wait for the HELLO exchange so negotiation is settled
        deadline = time.time() + 10
        while time.time() < deadline and not e0._peer_to(1).hello_seen:
            time.sleep(0.01)
        assert not e0.flow_to(1), "no-\"tr\" peer must negotiate down"
        e0.send_am(1, TAG_ACTIVATE, {"tp_id": 0, "edges": {},
                                     "data": np.ones(4)})
        deadline = time.time() + 10
        while time.time() < deadline and not seen:
            e1.progress()
            time.sleep(0.005)
        assert seen and "_tr" not in seen[0]
    finally:
        e0.fini()
        e1.fini()


def test_wire_capture_bit_identity():
    """The PR 14-pattern differential on the WIRE bytes themselves:
    the scripted deterministic exchange is byte-identical across two
    knob-unset runs AND toward a mixed-version peer (bench's capture
    harness — the same leg the dryrun gate asserts)."""
    import bench

    out = bench.bench_trace_capture_identity()
    assert out["trace_frames_captured"] > 0
    assert out["trace_unset_bit_identical"]
    assert out["trace_mixed_version_bit_identical"]


def test_dpotrf_flow_edges_stitch_across_ranks():
    """End to end on the in-process fabric: a 2-rank dpotrf under
    ``obs_flow`` produces matched cross-rank edges in BOTH directions
    with non-negative lag (same clock)."""
    from parsec_tpu.obs import load_flow_events, merge_trace_docs, \
        stitch_flows

    n, nb, ranks = 128, 32, 2
    M = make_spd(n, dtype=np.float32)
    with params.cmdline_override("obs_flow", "1"), \
            params.cmdline_override("comm_mesh_local", "0"):
        def rank_fn(r, fab):
            eng = RemoteDepEngine(fab.engine(r))
            ctx = parsec_tpu.Context(nb_cores=1, comm=eng, profile=True)
            try:
                coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32,
                                         P=ranks, Q=1, nodes=ranks, rank=r)
                coll.name = "descA"
                coll.from_numpy(M.copy())
                ctx.add_taskpool(dpotrf_taskpool(coll, rank=r,
                                                 nb_ranks=ranks))
                ctx.wait()
                ctx._stamp_profile_meta()
                return ctx.profile.to_chrome_trace()
            finally:
                ctx.fini()
        docs, _fab = spmd(ranks, rank_fn)
    edges, unmatched = stitch_flows(
        load_flow_events(merge_trace_docs(docs)))
    cross = [e for e in edges if e["src"] != e["dst"]]
    dirs = {(e["src"], e["dst"]) for e in cross}
    assert unmatched == 0
    assert (0, 1) in dirs and (1, 0) in dirs
    assert all(e["lag_us"] >= 0 for e in cross)


def test_forensics_dump_on_rank_failure(tmp_path):
    """A RankFailedError abort under an active file-backed profile
    flight-records the trace immediately (once), with the merge
    metadata stamped — fini may never run on an aborting fleet."""
    prefix = str(tmp_path / "post")
    with params.cmdline_override("profile", prefix):
        fab = LocalFabric(2)
        eng = RemoteDepEngine(fab.engine(0))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng)
        try:
            assert ctx.profile is not None
            ctx.record_task_error(RankFailedError(1, "chaos"))
            path = tmp_path / "post.forensics.rank0.trace.json"
            assert path.exists(), "no forensics trace written"
            with open(path) as fh:
                doc = json.load(fh)
            validate_chrome_trace(doc)
            assert doc["metadata"]["rank"] == 0
            assert "trace_t0_ns" in doc["metadata"]
            mtime = path.stat().st_mtime_ns
            # once per context: a second failure must not re-dump
            ctx.record_task_error(RankFailedError(1, "again"))
            assert path.stat().st_mtime_ns == mtime
        finally:
            ctx._task_errors.clear()
            ctx.fini()


def test_forensics_needs_active_profile(tmp_path):
    """Without a file-backed profile the abort dumps nothing (the
    flight recorder is opt-in via the profile knob)."""
    fab = LocalFabric(2)
    eng = RemoteDepEngine(fab.engine(0))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng)
    try:
        assert ctx.dump_forensics() == ""
        ctx.record_task_error(RankFailedError(1, "chaos"))
        assert not list(tmp_path.iterdir())
    finally:
        ctx._task_errors.clear()
        ctx.fini()


def test_chaos_run_collects_and_merges_forensics(tmp_path, capsys):
    """tools/chaos_run.py --forensics: the per-rank post-mortems merge
    into ONE timeline (unit leg: exercise the collector directly over
    traces a real abort wrote)."""
    from tools import chaos_run

    prefix = str(tmp_path / "post")
    with params.cmdline_override("profile", prefix):
        for r in range(2):
            fab = LocalFabric(2)
            eng = RemoteDepEngine(fab.engine(r))
            ctx = parsec_tpu.Context(nb_cores=1, comm=eng)
            try:
                ctx.record_task_error(RankFailedError(1 - r, "chaos"))
            finally:
                ctx._task_errors.clear()
                ctx.fini()
    chaos_run._collect_forensics(prefix)
    out = capsys.readouterr().out
    assert "collected 2 forensics trace(s)" in out
    merged = tmp_path / "post.forensics.merged.json"
    assert merged.exists()
    with open(merged) as fh:
        doc = json.load(fh)
    validate_chrome_trace(doc)
    assert doc["metadata"]["merged_ranks"] == [0, 1]


def test_stage_task_spans_carry_member_contexts():
    """stagec integration (ISSUE 15): a compiled stage fed by remote
    activations records the wire flow contexts that fed it and stamps
    them (plus its member list) onto the fused exec span."""
    n, nb, ranks = 192, 32, 2
    M = make_spd(n)
    with params.cmdline_override("obs_flow", "1"), \
            params.cmdline_override("stage_compile", "1"), \
            params.cmdline_override("comm_mesh_local", "0"):
        def rank_fn(r, fab):
            eng = RemoteDepEngine(fab.engine(r))
            ctx = parsec_tpu.Context(nb_cores=2, comm=eng, profile=True)
            try:
                coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                         P=ranks, Q=1, nodes=ranks, rank=r)
                coll.name = "descA"
                coll.from_numpy(M.copy())
                ctx.add_taskpool(dpotrf_taskpool(coll, rank=r,
                                                 nb_ranks=ranks))
                ctx.wait()
                stats = dict(ctx.stage_stats)
                return ctx.profile.to_chrome_trace(), stats
            finally:
                ctx.fini()
        results, _fab = spmd(ranks, rank_fn, timeout=300)
    assert any(st["stage_tasks"] > 0 for _d, st in results), \
        "stage compilation never engaged"
    stage_infos = [
        e.get("args") or {}
        for doc, _st in results
        for e in doc["traceEvents"]
        if e.get("ph") == "B" and str(e.get("name", "")).startswith(
            "exec:STAGE")]
    assert stage_infos, "no stage exec spans in the traces"
    assert any(info.get("member_tasks") for info in stage_infos)
    assert all("stage_members" in info for info in stage_infos)
    # at least one stage was fed by a remote activation: its span
    # names the wire flows that fed it
    assert any(info.get("wire_flows") for info in stage_infos), (
        "no stage span carried a wire flow context")
