"""Runtime integration: execute compiled stages as single chores
interleaved with the interpreted residue (ISSUE 12 tentpole, part 4).

A :class:`StageCompiler` attaches to a ``PTGTaskpool`` at startup when
the ``stage_compile`` MCA knob is on.  Each compilable stage becomes
ONE synthetic task on the ordinary runtime: its flows are the stage's
packed buffer slots, its chore is the fused jitted callable (or the
shard_map-compiled wave-front variant on a mesh device), and it rides
the untouched scheduler / device-module / eager-completion machinery —
stage-in, HBM accounting, donation guards, priority stamping and the
PR 7 eager-release window all apply to a stage exactly as they do to a
single task, which is what lets a compiled stage's cross-rank sends
overlap its own execution.

Dynamic dependency tracking for stages piggybacks on the existing
activation protocol: ``PTGTaskClass.activate`` consults the compiler
first (``on_activate``), so activations from local residue tasks,
other stages, AND remote ranks all count toward a stage's external
goal without any wire-format change; when the counter hits zero the
stage task spawns (its fused callable AOT-validated right there) and
is scheduled like any ready task.  On completion the stage's release
walk reuses each member's untouched ``_release_deps`` — remote
activations batch per rank, memory writebacks ride the device epilog —
with intra-stage edges swallowed by the same ``on_activate`` seam.

ISSUE 13 grew three fronts onto this engine, all behind the same knob:

- **Cross-pool chaining** (stagec/chain.py): when the context carries
  a declared chain, the host pool's final stage lowers into the
  CHAINED program (host stage + rider stages of later pools) and the
  rider pools CONSUME their pre-computed first-stage outputs at
  startup (``consume_chain``) — zero dispatch, tiles stay
  device-resident.  A chained build failure falls back to the plain
  host-only callable (``CHAIN_FALLBACKS``), and a rider whose stash
  never filled spawns its stage normally.
- **Compiled residue schedule**: residue tasks in a pre-planned
  per-(level, class) group (``plan.residue_groups``) are BUFFERED as
  they become ready and handed to the device batching pipeline as one
  contiguous burst when the group completes — no per-task scheduler
  round-trip, and the burst is guaranteed to flush as stacked calls.
- **Prestage/execute overlap**: buffered activation payloads H2D-stage
  at ARRIVAL (while the producing stage still executes or the wire
  still delivers), a spawning stage's own host-resident tiles stage
  under its trace/compile, and completed stages prestage the next
  pending stages' final-valued tiles — all through the §6.1
  prefetcher's device seam (``JaxDevice.prestage_data``), bounded by
  ``device_prefetch_depth``, counted in ``PRESTAGE_ISSUED``/
  ``PRESTAGE_HITS`` and visible to the live overlap gauge.

Fallback ladder (semantics are never at risk):

1. a class the lowerability pass rejects stays interpreted (residue);
2. a stage whose fused trace fails at spawn DOWNGRADES — its buffered
   activations replay through the normal dynamic path and its members
   execute via the PR 5/7 batched dispatch, permanently but only for
   that stage (the failure is cached, other stages keep compiling);
3. a chained program that fails to lower falls back to the host-only
   fused callable (riders spawn normally from their own pools);
4. a sharded (mesh) build/dispatch failure falls back to the fused
   single-chip callable for that stage;
5. ``stage_compile`` unset: ``tp._stagec`` is None and behavior is
   bit-for-bit the pre-stagec runtime.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..data.data import Coherency, Data, DataCopy, FlowAccess
from ..obs.spans import inbound_flow_ctx
from ..runtime.taskpool import (ACTION_RELEASE_ALL, Chore, Flow, Task,
                                TaskClass)
from ..utils import logging as plog
from ..utils.params import params
from .lower import (StageLayout, build_layout, build_stage_fn,
                    spec_token, stage_signature)
from .plan import StagePlan, plan_stages

#: declared lock discipline (analysis/lock_check.py): a stage record's
#: dependency counter, buffered activation events, and lifecycle status
#: are mutated from worker threads AND the comm delivery path — every
#: access goes through the record's own lock.  ``edge_copies`` is
#: single-owner by lifecycle (written by the dispatching manager, read
#: by the completing worker's release walk, ordered by the task
#: lifecycle) and deliberately unregistered.
_GUARDED_BY = {
    "_StageRec.remaining": "_lock",
    "_StageRec.events": "_lock",
    "_StageRec.status": "_lock",
    "_StageRec.flow_ctxs": "_lock",
    "StageCompiler._rg_left": "_rg_lock",
    "StageCompiler._rg_buf": "_rg_lock",
}

# _StageRec lifecycle
_PENDING, _SPAWNED, _DONE, _DOWNGRADED = range(4)

#: cache sentinel: a stage signature whose build already failed —
#: the next taskpool over the same spec downgrades instantly instead
#: of re-tracing the known failure ("permanent, but only for that
#: stage")
_FAILED = object()

#: consume_chain sentinel: no stash entry AT ALL (the host program
#: never ran) — distinct from a None marker (the host fell back and
#: already counted the fallback)
_NO_STASH = object()


class _StageRec:
    """One stage's dynamic state on one taskpool."""

    def __init__(self, stage, layout: StageLayout, priority: int) -> None:
        self.stage = stage
        self.layout = layout
        self.priority = priority
        self._lock = threading.Lock()
        self.remaining = layout.goal
        self.events: List[Tuple] = []   # (member_key, flow, copy) buffered
        # wire trace contexts of the remote activations that fed this
        # stage (ISSUE 15; only collected while a profile is live) —
        # stamped onto the stage task's exec span so the merged
        # timeline can attribute the fused span to its cross-rank
        # inputs
        self.flow_ctxs: List[Tuple] = []
        self.status = _PENDING
        self.fn = None                  # fused jitted callable
        self.sharded = None             # (fn, sharding, info) or None
        self.task: Optional[Task] = None
        self.edge_copies: Dict[Tuple, Any] = {}
        self.shapes: Tuple = ()
        self.donate: Tuple = ()
        self.chain = None               # HostChain when this rec hosts
        self.xwave = None               # XWave when this rec joins one
        #: Data objects prestaged for this stage and not yet counted
        #: (single-owner by lifecycle: the buffering/spawn path)
        self.prestaged: List[Any] = []


class StageTaskClass(TaskClass):
    """The synthetic task class of ONE compiled stage: flows are the
    stage's packed buffer slots.  Never registered on the taskpool's
    ``task_classes`` (remote activation ids index that list), so the
    wire protocol is untouched."""

    def __init__(self, compiler: "StageCompiler", rec: _StageRec) -> None:
        lay = rec.layout
        flows: List[Flow] = []
        for i, ((coll, coords), access) in enumerate(lay.mem_slots):
            flows.append(Flow(f"{coll}{coords}", access, i))
        base = len(lay.mem_slots)
        for j, (mkey, fname) in enumerate(lay.act_slots):
            flows.append(Flow(f"{mkey[0]}{mkey[1]}.{fname}",
                              FlowAccess.READ, base + j))
        if rec.chain is not None:
            # chained host stage (ISSUE 13): the riders' extra tiles
            # join the packed buffer as READ flows after the act slots
            base = len(flows)
            for j, (coll, coords) in enumerate(rec.chain.extra):
                flows.append(Flow(
                    f"chain:{getattr(coll, 'name', 'tile')}{coords}",
                    FlowAccess.READ, base + j))
        super().__init__(f"STAGE{rec.stage.index}[{compiler.tp.name}]",
                         -1 - rec.stage.index, len(flows), flows=flows)
        from ..devices.tpu import tpu_chore_hook
        self.incarnations = [Chore("tpu", tpu_chore_hook(),
                                   dyld_fn=compiler._make_dyld(rec))]
        self.release_deps = \
            lambda es, task, mask, c=compiler, r=rec: c._release(es, r)
        # one stage completion retires every member task's count (the
        # final unit comes from complete_execution's own decrement)
        n = rec.stage.n_tasks
        if n > 1:
            self.complete_execution = \
                lambda es, task, tp=compiler.tp: tp.task_completed(n - 1)


class StageCompiler:
    """Per-taskpool stage-compile engine (``tp._stagec``)."""

    def __init__(self, tp, context, plan: StagePlan) -> None:
        self.tp = tp
        self.context = context
        self.plan = plan
        self.stats = context.stage_stats
        # span-context collection (ISSUE 15) only while a profile is
        # live: the activate redirect is a hot path
        self._trace_on = context.profile is not None
        from .lower import spec_codes
        self._codes = spec_codes(tp)
        self._token = spec_token(tp)
        self._donate_on = bool(params.get("device_donate"))
        # donate-by-default (ISSUE 20c): donation is ON inside compiled
        # stages without the device_donate opt-in, EXCEPT for stages
        # whose member classes carry a BDY204 verdict (two flows read
        # the same tile — donating would hand XLA a buffer another
        # flow still needs)
        self._donate_default = bool(params.get("stage_compile_donate"))
        self._bdy_aliased: set = set()
        if self._donate_default:
            try:
                from ..analysis.body_check import check_jdf_bodies
                from .plan import _finding_class
                self._bdy_aliased = {
                    _finding_class(f) for f in check_jdf_bodies(tp.jdf)
                    if f.code == "BDY204"}
            except Exception:  # noqa: BLE001 - analysis is advisory
                self._donate_default = False
        # the mesh device, when this rank's accelerator is one (PR 6):
        # wave-front stages then compile through shard_map over it
        self._mesh_dev = next(
            (d for d in context.devices
             if d.device_type == "tpu" and getattr(d, "mesh", None)
             is not None and len(getattr(d, "chips", ())) > 1), None)
        self._dev = next(d for d in context.devices
                         if d.device_type == "tpu")
        self._recs: List[_StageRec] = []
        self._member_rec: Dict[Tuple, _StageRec] = {}
        self._rec_by_index: Dict[int, _StageRec] = {}
        for stage, layout, prio in plan.prepared:
            rec = _StageRec(stage, layout, prio)
            self._recs.append(rec)
            self._rec_by_index[stage.index] = rec
            for m in stage.members:
                self._member_rec[m.key] = rec

        # cross-pool chaining (ISSUE 13, stagec/chain.py): does this
        # pool HOST a chained program, or CONSUME a stash?  A rider may
        # contribute a multi-stage prefix (ISSUE 20a): one rec per
        # fused link, in stage order, all-or-nothing
        self._consume_recs: List[_StageRec] = []
        chain_state = getattr(context, "_stage_chain", None)
        if chain_state is not None:
            # pop: the HostChain moves onto the rec, so the registry
            # entry (and eventually the pool's strong ref) can retire
            hc = chain_state.hosts.pop(id(tp), None)
            if hc is not None:
                host_rec = self._rec_by_index.get(hc.host_stage_index)
                if host_rec is not None:
                    host_rec.chain = hc
            links = chain_state.consumes.get(id(tp))
            if links:
                recs = []
                for link in links:
                    rec0 = self._rec_by_index.get(link.stage.index)
                    if rec0 is None or rec0.stage is not link.stage:
                        recs = []
                        break
                    recs.append(rec0)
                self._consume_recs = recs

        # compiled residue schedule (ISSUE 13): per-(level, class)
        # groups pre-planned by the lowerability pass — ready members
        # buffer here and dispatch as ONE device burst when complete
        self._rg_lock = threading.Lock()
        self._rg_of: Dict[Tuple, int] = {}
        self._rg_left: List[int] = []
        self._rg_buf: List[List[Task]] = []
        self._rg_host: List[bool] = []
        if params.get("stage_residue_batch") and \
                (plan.residue_groups or plan.residue_groups_host):
            eligible = {
                tc.ast.name for tc in tp.task_classes
                if any(c.device_type == "tpu" and c.dyld_fn is not None
                       for c in tc.incarnations)}
            host_ok = {
                tc.ast.name for tc in tp.task_classes
                if any(c.device_type == "cpu" for c in tc.incarnations)}
            for host, groups in ((False, plan.residue_groups),
                                 (True, plan.residue_groups_host)):
                for keys in groups:
                    if keys[0][0] not in (host_ok if host else eligible):
                        continue
                    gi = len(self._rg_left)
                    self._rg_left.append(len(keys))
                    self._rg_buf.append([])
                    self._rg_host.append(host)
                    for k in keys:
                        self._rg_of[k] = gi

        # prestage/execute overlap (ISSUE 13): early H2D of stage
        # inputs through the §6.1 prefetcher's device seam, bounded by
        # device_prefetch_depth stages with outstanding prestages
        self._prestage_depth = int(getattr(self._dev, "prefetch_depth",
                                           0))
        self._prestage_recs: set = set()

        # cross-rank SPMD stages (ISSUE 20): negotiate "xs" with every
        # spanning peer, exchange + assert the plan digest, wire the
        # planned waves onto their stage recs.  Any soft failure keeps
        # every stage rank-local; a DIGEST mismatch raises (ranks
        # disagreeing on the wave partition is a plan bug, the
        # xfer/plan.py loud-failure contract).
        self._xrank = None
        if getattr(plan, "xwaves", None):
            from .xrank import install_xrank
            try:
                install_xrank(self)
            except RuntimeError:
                raise
            except Exception as exc:  # noqa: BLE001 - rank-local stands by
                plog.warning(
                    "stagec xrank: install failed on %s (%s: %s); "
                    "rank-local stages", tp.name, type(exc).__name__,
                    str(exc)[:200])
                self._xrank = None
                for r_ in self._recs:
                    r_.xwave = None

    def _tc(self, inst):
        """The LIVE taskpool's class for a (possibly cached-plan)
        instance: plans are cached per spec token across taskpools, so
        ``inst.tc`` may belong to an earlier pool — every runtime
        action rebinds by name."""
        return self.tp.class_by_name(inst.tc.ast.name)

    # ------------------------------------------------------------------ #
    # dependency tracking: the activate redirect                         #
    # ------------------------------------------------------------------ #
    def on_activate(self, tc, locals_: Tuple, flow_name: str,
                    copy) -> Tuple[bool, Optional[Task]]:
        """Called by ``PTGTaskClass.activate`` before its own dynamic
        dep table.  Returns ``(handled, ready_task)``; handled=False
        passes through to the interpreted path (non-members and
        downgraded stages)."""
        rec = self._member_rec.get((tc.ast.name, locals_))
        if rec is None:
            return False, None
        spawn = False
        with rec._lock:
            if rec.status == _DOWNGRADED:
                return False, None
            if rec.status != _PENDING:
                # an intra-stage edge emitted by the release walk of
                # this very stage: already computed inside the fused
                # program — swallow
                return True, None
            rec.events.append(((tc.ast.name, locals_), flow_name, copy))
            if self._trace_on:
                # which wire flow (if any) delivered this activation:
                # remote_dep publishes the inbound context thread-
                # locally around the activation walk (obs/spans.py)
                fctx = inbound_flow_ctx()
                if fctx is not None:
                    rec.flow_ctxs.append(fctx)
            rec.remaining -= 1
            assert rec.remaining >= 0, \
                f"{tc.ast.name}{locals_}: stage overshoot"
            if rec.remaining == 0:
                rec.status = _SPAWNED   # claim; build outside the lock
                spawn = True
        if not spawn:
            # prestage the buffered payload NOW (ISSUE 13): the stage
            # still awaits other inputs, so its H2D overlaps whatever
            # is producing them (the executing stage / the wire)
            self._prestage_activation(rec, copy)
            return True, None
        tasks = self._spawn(rec)
        if not tasks:
            return True, None
        if len(tasks) > 1:
            from ..runtime.scheduling import schedule
            schedule(self.context.execution_streams[0], tasks[1:])
        return True, tasks[0]

    def startup_tasks(self) -> List[Task]:
        """Stages with no external task inputs are startup tasks.  A
        stage another pool's chained program pre-computes stays PENDING
        here — ``consume_chain`` finalizes (or falls back) after the
        taskpool's counts are credited."""
        out: List[Task] = []
        for rec in self._recs:
            if rec in self._consume_recs:
                continue
            with rec._lock:
                if rec.status != _PENDING or rec.remaining > 0:
                    continue
                rec.status = _SPAWNED
            out.extend(self._spawn(rec))
        return out

    def is_member(self, class_name: str, locals_: Tuple) -> bool:
        rec = self._member_rec.get((class_name, locals_))
        if rec is None:
            return False
        with rec._lock:
            return rec.status != _DOWNGRADED

    # ------------------------------------------------------------------ #
    # cross-pool chaining: consume a stashed rider stage (ISSUE 13)      #
    # ------------------------------------------------------------------ #
    def consume_chain(self, es) -> List[Task]:
        """Finalize this pool's chained-in first stage: adopt the
        stashed device outputs as the newest tile copies, run the
        stage's release walk, retire its members' counts.  Called by
        ``PTGTaskpool._startup`` AFTER the task counts are credited (a
        completion before ``set_nb_tasks`` would go negative).  A
        missing stash (the host program downgraded, or never ran)
        falls back to spawning the stage normally."""
        recs = self._consume_recs
        if not recs:
            return []
        self._consume_recs = []
        st = getattr(self.context, "_stage_chain", None)
        stash = st.stash.pop(id(self.tp), _NO_STASH) if st is not None \
            else _NO_STASH
        if st is not None:
            st.consumes.pop(id(self.tp), None)
        if not isinstance(stash, list) and stash is not None \
                and stash is not _NO_STASH:
            stash = [stash]
        if isinstance(stash, list) and len(stash) != len(recs):
            # the host lowered a different prefix than this pool fused
            # (stale registry entry): dispatch everything normally
            stash = _NO_STASH
        if stash is None or stash is _NO_STASH:
            if stash is _NO_STASH:
                # the host program never ran at all (downgrade, knob
                # change); a None marker means the host already fell
                # back — and already counted the fallback
                self.stats["chain_fallbacks"] += 1
            plog.debug.verbose(
                2, "stagec chain: %s found no stash for stage %d; "
                "dispatching it normally", self.tp.name,
                recs[0].stage.index)
            out: List[Task] = []
            for rec in recs:
                # later prefix recs with remaining > 0 stay PENDING and
                # spawn through the ordinary activation path
                with rec._lock:
                    if rec.status != _PENDING or rec.remaining > 0:
                        continue
                    rec.status = _SPAWNED
                out.extend(self._spawn(rec))
            return out
        # mark EVERY fused rec spawned up front: an earlier rec's
        # release walk must not re-dispatch a later fused rec through
        # the activation path (its activations are in-program)
        for rec in recs:
            with rec._lock:
                rec.status = _SPAWNED
        ready: List[Task] = []
        total = 0
        for rec, part in zip(recs, stash):
            lay = rec.layout
            for arr, si in zip(part["tiles"], lay.out_mem):
                (coll_name, coords), _a = lay.mem_slots[si]
                data = self.tp.global_env[coll_name].data_of(*coords)
                self._dev.adopt_output(data, arr)
            for ek, arr in zip(lay.edge_outs, part["edges"]):
                if arr is not None:
                    rec.edge_copies[ek] = _edge_copy(arr)
            n = rec.stage.n_tasks
            self.stats["chain_links"] += 1
            self.stats["stage_tasks"] += n
            self._dev.stats["tasks"] += n
            ready.extend(self._release(es, rec))
            self.tp.task_completed(n)
            total += n
        plog.debug.verbose(
            3, "stagec chain: %s consumed %d stage(s) (%d task(s)) "
            "from the chained program", self.tp.name, len(recs), total)
        return ready

    # ------------------------------------------------------------------ #
    # compiled residue schedule (ISSUE 13)                               #
    # ------------------------------------------------------------------ #
    def on_residue_ready(self, task: Task) -> Optional[Task]:
        """A residue task just became ready (``PTGTaskClass.activate``
        routes every non-member spawn here).  Members of a pre-planned
        residue group BUFFER; the completed group is handed to the
        device batching pipeline as one contiguous burst — no per-task
        scheduler round-trip, and the burst flushes as stacked calls.
        Non-grouped tasks pass through untouched."""
        gi = self._rg_of.get((task.task_class.ast.name, task.locals))
        if gi is None:
            return task
        with self._rg_lock:
            self._rg_buf[gi].append(task)
            self._rg_left[gi] -= 1
            if self._rg_left[gi] > 0:
                return None
            group, self._rg_buf[gi] = self._rg_buf[gi], []
        if self._rg_host[gi]:
            self._dispatch_host_group(group)
        else:
            self._dispatch_residue_group(group)
        return None

    def _dispatch_residue_group(self, tasks: List[Task]) -> None:
        """Hand one complete residue group straight to the device:
        inputs bound (prepare_input), device chore selected, every
        task pushed onto the device queue back to back — the next
        manager flush drains them as ONE accumulated burst through the
        PR 5 stacked dispatch.  No scheduler enqueue/select per task."""
        es0 = self.context.execution_streams[0]
        dev = self._dev
        self.stats["residue_batches"] += 1
        self.stats["residue_batch_tasks"] += len(tasks)
        for task in tasks:
            tc = task.task_class
            if tc.prepare_input is not None:
                tc.prepare_input(es0, task)
            task.selected_chore = next(
                i for i, c in enumerate(tc.incarnations)
                if c.device_type == "tpu")
            task.selected_device = dev
            est = (tc.time_estimate(task, dev) if tc.time_estimate
                   else dev.time_estimate_default)
            dev.load_add(est)
            task.es_hint = es0.th_id
            dev.pending.push_back((task, est))
        # no inline progress: the next idle worker's manager cycle
        # drains the whole burst with ITS execution stream
        self.context.wake_workers(len(tasks))

    def _dispatch_host_group(self, tasks: List[Task]) -> None:
        """Host-bodied counterpart (ISSUE 20b): a complete pre-planned
        group of HOST residue tasks enters the scheduler as ONE
        contiguous burst — same-(level, class) members are an
        antichain, so nothing in the group depends on anything else in
        it and the whole batch is ready at once."""
        es0 = self.context.execution_streams[0]
        self.stats["residue_batches"] += 1
        self.stats["residue_batch_tasks"] += len(tasks)
        from ..runtime.scheduling import schedule
        schedule(es0, tasks)
        self.context.wake_workers(len(tasks))

    # ------------------------------------------------------------------ #
    # prestage/execute overlap (ISSUE 13)                                #
    # ------------------------------------------------------------------ #
    def _prestage_activation(self, rec: _StageRec, copy) -> None:
        """Early H2D of a buffered activation payload: the stage still
        awaits other inputs, so this transfer hides under whatever is
        producing them.  Budgeted: at most ``device_prefetch_depth``
        pending stages hold outstanding prestages at once."""
        if self._prestage_depth <= 0 or copy is None \
                or copy.data is None:
            return
        if id(rec) not in self._prestage_recs \
                and len(self._prestage_recs) >= self._prestage_depth:
            return
        if self._dev.prestage_data(copy.data, dtt=copy.dtt):
            self._prestage_recs.add(id(rec))
            rec.prestaged.append(copy.data)
            self.stats["prestage_issued"] += 1

    def _prestage_own_tiles(self, rec: _StageRec) -> None:
        """H2D the spawning stage's host-resident tiles NOW, so the
        transfers run under the stage's trace/compile below instead of
        serializing ahead of its dispatch.  Safe: the stage's
        activation goal is met, so every tile it reads holds its final
        value (memory ordering between tasks is dataflow-carried)."""
        if self._prestage_depth <= 0:
            return
        tiles = [self.tp.global_env[name].data_of(*coords)
                 for (name, coords), _a in rec.layout.mem_slots]
        if rec.chain is not None:
            tiles.extend(coll.data_of(*coords)
                         for coll, coords in rec.chain.extra)
        committed = self._dev.prestage_many(tiles)
        if committed:
            rec.prestaged.extend(committed)
            self.stats["prestage_issued"] += len(committed)

    def _prestage_lookahead(self) -> None:
        """A stage just completed: prestage the next PENDING stages'
        tiles whose writers are all retired (their host values are
        final), up to the device_prefetch_depth stage budget — stage
        N+1's packed-buffer stage-in overlaps what still executes."""
        if self._prestage_depth <= 0:
            return
        budget = self._prestage_depth
        writers = self.plan.mem_writers
        member_stage = self.plan.member_stage
        for rec in self._recs:
            if budget <= 0:
                break
            with rec._lock:
                if rec.status != _PENDING:
                    continue
            budget -= 1
            for (coll_name, coords), _access in rec.layout.mem_slots:
                final = True
                for wk in writers.get((coll_name, coords), ()):
                    wsi = member_stage.get(wk)
                    wrec = (self._rec_by_index.get(wsi)
                            if wsi is not None else None)
                    if wrec is None:
                        final = False   # residue or foreign writer
                        break
                    with wrec._lock:
                        if wrec.status != _DONE:
                            final = False   # value not yet final
                    if not final:
                        break
                if not final:
                    continue
                data = self.tp.global_env[coll_name].data_of(*coords)
                if self._dev.prestage_data(data):
                    self._prestage_recs.add(id(rec))
                    rec.prestaged.append(data)
                    self.stats["prestage_issued"] += 1

    def _count_prestage_hits(self, rec: _StageRec) -> None:
        """At spawn: every prestaged Data whose device copy is still
        current is a HIT — the fused stage's stage-in finds the buffer
        resident instead of paying a serial H2D."""
        for data in rec.prestaged:
            if self._dev.prestaged_current(data):
                self.stats["prestage_hits"] += 1
        rec.prestaged = []
        self._prestage_recs.discard(id(rec))

    # ------------------------------------------------------------------ #
    # spawn: AOT-validate the fused callable, bind slots, emit the task  #
    # ------------------------------------------------------------------ #
    def _spawn(self, rec: _StageRec) -> List[Task]:
        try:
            return [self._make_stage_task(rec)]
        except Exception as exc:  # noqa: BLE001 - any failure interprets
            plog.warning(
                "stagec: stage %d of %s failed to lower (%s: %s); its %d "
                "member task(s) run interpreted",
                rec.stage.index, self.tp.name, type(exc).__name__,
                str(exc)[:200], rec.stage.n_tasks)
            return self._downgrade(rec)

    def _slot_shapes(self, rec: _StageRec, bindings: Dict) -> Tuple:
        shapes = []
        for (coll_name, coords), _access in rec.layout.mem_slots:
            coll = self.tp.global_env[coll_name]
            data = coll.data_of(*coords)
            newest = data.newest_copy()
            if newest is not None and newest.payload is not None:
                shapes.append((tuple(newest.payload.shape),
                               str(newest.payload.dtype)))
            else:
                shapes.append((tuple(coll.tile_shape(*coords)),
                               str(np.dtype(coll.dtype))))
        for ak in rec.layout.act_slots:
            cp = bindings.get(ak)
            if cp is None or cp.payload is None:
                raise RuntimeError(
                    f"activation slot {ak} bound no payload")
            shapes.append((tuple(cp.payload.shape),
                           str(cp.payload.dtype)))
        return tuple(shapes)

    def _lowered(self, rec: _StageRec, donate: Tuple) -> Any:
        """The AOT-cached fused callable for this stage signature —
        alongside the bucket cache (devices/batching.py); a repeat
        taskpool over the same spec/NB/dtype hits it without
        re-tracing.  A cached failure re-raises instantly."""
        import jax
        from ..devices.batching import cached_stage_callable

        key = stage_signature(rec.stage, rec.shapes) + (donate, "fused")

        def build():
            t0 = time.perf_counter_ns()
            run = build_stage_fn(self.tp, rec.stage, rec.layout,
                                 self._codes)
            fn = jax.jit(run, donate_argnums=donate)
            # force the trace NOW: untraceable bodies must downgrade at
            # spawn, not poison the device dispatch path
            avals = tuple(jax.ShapeDtypeStruct(s, np.dtype(d))
                          for (s, d) in rec.shapes)
            jax.eval_shape(run, *avals)
            dt = time.perf_counter_ns() - t0
            self.stats["stage_compiles"] += 1
            self.stats["stage_compile_ns"] += dt
            return fn

        fn = cached_stage_callable(self._token, key, build)
        if fn is _FAILED:
            raise RuntimeError("stage lowering previously failed "
                               "(cached verdict)")
        return fn

    def _extra_shapes(self, rec: _StageRec) -> Tuple:
        shapes = []
        for coll, coords in rec.chain.extra:
            data = coll.data_of(*coords)
            newest = data.newest_copy()
            if newest is not None and newest.payload is not None:
                shapes.append((tuple(newest.payload.shape),
                               str(newest.payload.dtype)))
            else:
                shapes.append((tuple(coll.tile_shape(*coords)),
                               str(np.dtype(coll.dtype))))
        return tuple(shapes)

    def _lowered_chain(self, rec: _StageRec, donate: Tuple) -> Any:
        """The AOT-cached CHAINED program of a host stage (stagec/
        chain.py): host stage + rider stages of later pools, cached
        under the host pool's spec token.  A cached failure re-raises
        instantly (the caller falls back to the host-only callable)."""
        import jax
        from ..devices.batching import cached_stage_callable
        from .chain import build_chain_run, chain_signature

        key = chain_signature(rec.shapes, rec.stage, rec.chain, donate)

        def build():
            t0 = time.perf_counter_ns()
            try:
                run = build_chain_run(self.tp, rec.stage, rec.layout,
                                      self._codes, rec.chain)
                fn = jax.jit(run, donate_argnums=donate)
                avals = tuple(jax.ShapeDtypeStruct(s, np.dtype(d))
                              for (s, d) in rec.shapes)
                jax.eval_shape(run, *avals)
            except Exception:
                cached_stage_callable(self._token, key, lambda: _FAILED)
                raise
            self.stats["stage_compiles"] += 1
            self.stats["stage_compile_ns"] += \
                time.perf_counter_ns() - t0
            return fn

        fn = cached_stage_callable(self._token, key, build)
        if fn is _FAILED:
            raise RuntimeError("chained lowering previously failed "
                               "(cached verdict)")
        return fn

    def _make_stage_task(self, rec: _StageRec) -> Task:
        with rec._lock:
            events = list(rec.events)
        bindings: Dict[Tuple, Any] = {}
        for (mkey, fname, copy) in events:
            if copy is not None:
                bindings[(mkey, fname)] = copy
        # prestage the stage's host-resident tiles: their H2D runs
        # under the trace/compile below (ISSUE 13 overlap)
        self._prestage_own_tiles(rec)
        rec.shapes = self._slot_shapes(rec, bindings)
        if rec.chain is not None:
            rec.shapes = rec.shapes + self._extra_shapes(rec)
        donate_ok = self._donate_on or (
            self._donate_default
            and not any(m.tc.ast.name in self._bdy_aliased
                        for m in rec.stage.members))
        rec.donate = tuple(
            i for i, (_k, acc) in enumerate(rec.layout.mem_slots)
            if donate_ok and (acc & FlowAccess.WRITE))
        from ..devices.batching import cached_stage_callable
        try:
            if rec.chain is not None:
                try:
                    rec.fn = self._lowered_chain(rec, rec.donate)
                except Exception as exc:  # noqa: BLE001 - host stands by
                    self.stats["chain_fallbacks"] += 1
                    plog.warning(
                        "stagec chain: chained program of %s stage %d "
                        "failed to lower (%s: %s); host-only callable "
                        "(riders dispatch from their own pools)",
                        self.tp.name, rec.stage.index,
                        type(exc).__name__, str(exc)[:200])
                    st = getattr(self.context, "_stage_chain", None)
                    if st is not None:
                        # a None stash tells each rider "the host fell
                        # back, spawn normally" — counted HERE once,
                        # not once more per rider
                        for link in rec.chain.riders:
                            st.stash[id(link.tp)] = None
                    rec.chain = None
                    rec.shapes = self._slot_shapes(rec, bindings)
                    rec.fn = self._lowered(rec, rec.donate)
            else:
                rec.fn = self._lowered(rec, rec.donate)
        except Exception:
            # record the verdict so the next taskpool over the same
            # spec downgrades this stage instantly (permanent, but
            # only for this stage)
            cached_stage_callable(
                self._token,
                stage_signature(rec.stage, rec.shapes)
                + (rec.donate, "fused"),
                lambda: _FAILED)
            raise
        if self._mesh_dev is not None and rec.chain is None \
                and params.get("stage_compile_shard"):
            rec.sharded = self._try_sharded(rec)
        self._count_prestage_hits(rec)
        tc = StageTaskClass(self, rec)
        if self._trace_on:
            # stage-task spans carry member contexts (ISSUE 15): the
            # fused exec span lists its member tasks and the wire flow
            # ids that fed it, so the merged timeline can tie one
            # stage slice to its cross-rank inputs
            with rec._lock:
                ctxs = list(rec.flow_ctxs)
            tc.trace_info = {
                "stage_members": rec.stage.n_tasks,
                "member_tasks": [f"{m.key[0]}{tuple(m.key[1])}"
                                 for m in rec.stage.members[:16]],
                "wire_flows": [f"R{o}:{s}" for (o, s) in ctxs[:32]],
            }
        task = Task(self.tp, tc, locals_=(rec.stage.index,),
                    priority=rec.priority)
        task.user = rec
        for i, ((coll_name, coords), _a) in enumerate(rec.layout.mem_slots):
            coll = self.tp.global_env[coll_name]
            task.data[i].data_in = coll.data_of(*coords).host_copy()
            task.data[i].fulfilled = True
        base = len(rec.layout.mem_slots)
        for j, ak in enumerate(rec.layout.act_slots):
            task.data[base + j].data_in = bindings[ak]
            task.data[base + j].fulfilled = True
        if rec.chain is not None:
            base += len(rec.layout.act_slots)
            for j, (coll, coords) in enumerate(rec.chain.extra):
                task.data[base + j].data_in = \
                    coll.data_of(*coords).host_copy()
                task.data[base + j].fulfilled = True
        rec.task = task
        return task

    def _try_sharded(self, rec: _StageRec):
        """Wave-front stages on a mesh rank compile through shard_map
        over the rank's chips (stagec/sharded.py); any failure keeps
        the fused single-chip callable."""
        from .sharded import build_wavefront_callable, wavefront_info
        dev = self._mesh_dev
        k = len(dev.chips)
        n = rec.stage.n_tasks
        if n < k or n % k:
            return None
        try:
            info = wavefront_info(self.tp, rec.stage, rec.layout,
                                  self._codes)
            if info is None:
                return None
            row_shapes = tuple(
                rec.shapes[info.arg_slots[0][j]] for j in range(info.nargs))
            from ..devices.batching import cached_stage_callable
            key = stage_signature(rec.stage, rec.shapes) + \
                ("sharded", dev.mesh)

            def build():
                t0 = time.perf_counter_ns()
                fn_sh = build_wavefront_callable(dev.mesh, info,
                                                 self.tp.rank, row_shapes)
                self.stats["stage_compiles"] += 1
                self.stats["stage_compile_ns"] += \
                    time.perf_counter_ns() - t0
                return fn_sh

            fn, sharding = cached_stage_callable(self._token, key, build)
            return (fn, sharding, info)
        except Exception as exc:  # noqa: BLE001 - fused path stands by
            plog.debug.verbose(
                2, "stagec: sharded lowering of stage %d declined (%s); "
                "fused single-chip callable", rec.stage.index, exc)
            return None

    # ------------------------------------------------------------------ #
    # downgrade: replay into the interpreted dynamic path                #
    # ------------------------------------------------------------------ #
    def _downgrade(self, rec: _StageRec) -> List[Task]:
        """Transparent per-stage fallback: buffered external
        activations replay through the normal per-class dep tables and
        the members execute via the interpreted (batched, PR 5/7)
        dispatch.  Permanent only for this stage — other stages keep
        their compiled path."""
        with rec._lock:
            rec.status = _DOWNGRADED
            events, rec.events = rec.events, []
        if rec.xwave is not None:
            # peers are (or will be) waiting at this wave's rendezvous:
            # decline NOW so they fall back instead of timing out
            from .xrank import decline_rec
            decline_rec(self, rec)
            rec.xwave = None
            self.stats["xstage_fallbacks"] += 1
        rec.prestaged = []
        self._prestage_recs.discard(id(rec))
        self.stats["stage_fallbacks"] += 1
        ready: List[Task] = []
        for inst in rec.stage.members:
            tc = self._tc(inst)
            if tc.goal_of(inst.locals) == 0:
                ready.append(tc.make_task(inst.locals, None))
        for (mkey, fname, copy) in events:
            tc = self.tp.class_by_name(mkey[0])
            t = tc.activate(mkey[1], fname, copy)
            if t is not None:
                ready.append(t)
        return ready

    # ------------------------------------------------------------------ #
    # execution: the stage chore                                         #
    # ------------------------------------------------------------------ #
    def _make_dyld(self, rec: _StageRec):
        def dyld(task: Task, arrays: List[Any]):
            return self._execute_stage(task, rec, arrays)
        return dyld

    def _execute_stage(self, task: Task, rec: _StageRec,
                       arrays: List[Any]):
        lay = rec.layout
        tile_outs = edge_outs = None
        if rec.xwave is not None:
            from .xrank import decline_rec, dispatch_xrank
            try:
                tile_outs, edge_outs = dispatch_xrank(self, rec, arrays)
                self.stats["xstage_tasks"] += rec.stage.n_tasks
            except Exception as exc:  # noqa: BLE001 - rank-local ladder
                plog.warning(
                    "stagec xrank: cross-rank dispatch of stage %d "
                    "failed (%s: %s); rank-local path",
                    rec.stage.index, type(exc).__name__, str(exc)[:200])
                decline_rec(self, rec)
                rec.xwave = None
                self.stats["xstage_fallbacks"] += 1
                tile_outs = None
        if tile_outs is None and rec.sharded is not None:
            from .sharded import dispatch_sharded
            fn, sharding, info = rec.sharded
            try:
                tile_outs, edge_outs = dispatch_sharded(
                    self._mesh_dev, fn, sharding, info, arrays)
                self.stats["stage_sharded"] += 1
            except Exception as exc:  # noqa: BLE001 - fused fallback
                plog.warning(
                    "stagec: sharded dispatch of stage %d failed (%s); "
                    "fused single-chip dispatch", rec.stage.index, exc)
                rec.sharded = None
                tile_outs = None
        if tile_outs is None:
            fn = rec.fn
            if rec.donate and len({id(a) for a in arrays}) != len(arrays):
                # the same buffer at two slots: donation would trip
                # XLA's aliasing rule — use the undonated variant
                fn = (self._lowered_chain(rec, ())
                      if rec.chain is not None else self._lowered(rec, ()))
            outs = fn(*arrays)
            ntile = len(lay.out_mem)
            nhost = ntile + len(lay.edge_outs)
            tile_outs = list(outs[:ntile])
            edge_outs = list(outs[ntile:nhost])
            if rec.chain is not None:
                # stash each rider stage's outputs for its pool's
                # consume_chain (stagec/chain.py): tiles + edge
                # live-outs, still (possibly in-flight) device arrays.
                # A rider pool may own SEVERAL links (multi-stage
                # prefix, ISSUE 20a): its stash is the per-link list
                # in stage order
                st = getattr(self.context, "_stage_chain", None)
                rest = list(outs[nhost:])
                stash_by_tp: Dict[int, List[Dict[str, Any]]] = {}
                for link in rec.chain.riders:
                    nt = len(link.layout.out_mem)
                    part, rest = rest[:link.n_out], rest[link.n_out:]
                    stash_by_tp.setdefault(id(link.tp), []).append(
                        {"tiles": part[:nt], "edges": part[nt:]})
                if st is not None:
                    for tpid, parts in stash_by_tp.items():
                        st.stash[tpid] = parts
        dev = task.selected_device
        for ek, arr in zip(lay.edge_outs, edge_outs):
            if arr is None:
                continue   # a NULL-forwarded flow: successors bind None
            rec.edge_copies[ek] = _edge_copy(arr)
        self.stats["stage_dispatches"] += 1
        self.stats["stage_tasks"] += rec.stage.n_tasks
        if dev is not None:
            dev.stats["tasks"] += rec.stage.n_tasks - 1  # +1 from epilog
        return tuple(tile_outs)

    # ------------------------------------------------------------------ #
    # release: each member's untouched _release_deps over the stash      #
    # ------------------------------------------------------------------ #
    def _release(self, es, rec: _StageRec) -> List[Task]:
        with rec._lock:
            rec.status = _DONE
        # this stage's written tiles are final: prestage the next
        # pending stages' inputs (ISSUE 13 overlap)
        self._prestage_lookahead()
        ready: List[Task] = []
        for inst in rec.stage.members:
            if inst.key not in rec.layout.release_members:
                continue   # every successor is fused into this stage
            tc = self._tc(inst)
            shim = Task(self.tp, tc, inst.locals)
            for i, f in enumerate(tc.ast.flows):
                cp = rec.edge_copies.get((inst.key, f.name))
                if cp is not None:
                    shim.data[i].data_out = cp
            ready.extend(tc._release_deps(
                es, shim, ACTION_RELEASE_ALL) or [])
        rec.edge_copies.clear()
        return ready


def _edge_copy(arr) -> DataCopy:
    """Wrap a stage live-out device array as a deliverable DataCopy
    (the shape _deliver_activation builds for remote arrivals): a
    detached Data whose newest copy holds the (possibly still
    in-flight) device buffer — consumers chain on it like on any
    eager-completed task output."""
    d = Data(nb_elts=int(getattr(arr, "size", 0)))
    cp = DataCopy(d, 0, payload=arr)
    cp.version = 1
    cp.coherency = Coherency.OWNED
    d.attach_copy(cp)
    return cp


def prepared_plan(tp, context) -> StagePlan:
    """The cached, layout-prepared StagePlan of one taskpool under the
    current knobs.  The plan + layouts are a pure function of (spec,
    globals, geometry, distribution, rank) AND the partition knobs —
    max_tasks, wavefront mode, and the exclusion set all join the
    cache key, so a knob change can never hit a stale plan.  Shared by
    ``try_install`` and the chain planner (stagec/chain.declare_chain),
    which therefore always agree on stage identity."""
    from ..devices.batching import cached_stage_callable
    from .plan import _excluded_classes
    # cross-rank SPMD stages (ISSUE 20) need the wave-front partition
    # even without a local chip mesh: every rank must cut the SAME
    # (level, class) waves for the global program to line up
    xrank = bool(params.get("stage_compile_xrank")) \
        and tp.nb_ranks > 1 and bool(params.get("stage_compile_shard"))
    wavefront = xrank or any(
        d.device_type == "tpu" and getattr(d, "mesh", None) is not None
        and len(getattr(d, "chips", ())) > 1 for d in context.devices)
    max_tasks = int(params.get("stage_compile_max_tasks"))

    def build_plan():
        plan = plan_stages(tp, rank=tp.rank, max_tasks=max_tasks,
                           wavefront=wavefront)
        for stage in plan.stages:
            layout = build_layout(tp, plan, stage)
            # the max over the members' TRUE priorities (negative
            # included — a spec that deprioritizes a class must not
            # see its compiled stage boosted to 0)
            prios = [int(m.tc.ast.priority(m.env))
                     for m in stage.members
                     if m.tc.ast.priority is not None]
            plan.prepared.append((stage, layout,
                                  max(prios) if prios else 0))
        # plan-cached startup enumeration (ISSUE 13): goal-0 local
        # residue + the foreign mem-put expectation are pure functions
        # of the plan identity — a stagec _startup skips the whole
        # per-instance iteration-space walk on repeat pools
        for inst in plan.order:
            k = inst.key
            if k in plan.local_keys:
                if k not in plan.member_stage \
                        and inst.tc.goal_of(inst.locals, inst.env) == 0:
                    plan.startup_goal0.append(k)
            else:
                plan.startup_mem_puts += tp._count_mem_puts_to_me(
                    tp.class_by_name(k[0]), inst.env)
        if xrank:
            from .xrank import plan_xwaves
            plan_xwaves(tp, plan, max_tasks)
        return plan

    return cached_stage_callable(
        spec_token(tp),
        ("stageplan", wavefront, xrank, max_tasks,
         _excluded_classes()),
        build_plan)


def try_install(tp, context) -> Optional[StageCompiler]:
    """Build a StageCompiler for ``tp`` when the stage_compile knob is
    on and the pool is eligible; None keeps the interpreted runtime
    bit-for-bit (the knob's off-contract).  The plan + layouts are a
    pure function of (spec, globals, geometry, distribution, rank), so
    they cache under the spec token — a repeat taskpool skips the whole
    enumeration/partition walk, not just the retrace."""
    if not any(d.device_type == "tpu" for d in context.devices):
        return None
    try:
        plan = prepared_plan(tp, context)
    except Exception as exc:  # noqa: BLE001 - unenumerable: interpret
        plog.debug.verbose(
            2, "stagec: %s not plannable (%s: %s); interpreted path",
            tp.name, type(exc).__name__, exc)
        return None
    if not plan.stages:
        return None
    plog.debug.verbose(
        3, "stagec: %s rank %d -> %d stage(s) covering %d/%d local "
        "task(s), %d residue", tp.name, tp.rank, len(plan.stages),
        plan.n_staged, plan.n_local, plan.n_residue)
    return StageCompiler(tp, context, plan)
