"""Per-process SPMD driver for the TCP transport tests (launched as a
subprocess by test_comm_tcp.py — real process isolation, the reference's
mpiexec analog with an actual wire between ranks).

Usage: python tcp_rank_main.py <rank> <nb_ranks> <port0,...> <hops> [mode]
mode: "ptg" (default — chain JDF), "dtd" (insert-task chain),
"dposv" (distributed Cholesky solve: 3 sequential taskpools), or
"fail" (rank 1 hard-exits mid-chain; rank 0 must DETECT the failure and
abort its DAG instead of hanging — the §5.3 failure detector).
Prints one JSON line with this rank's observations.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PARSEC_MCA_device_tpu_platform", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import parsec_tpu  # noqa: E402
from parsec_tpu.comm import RemoteDepEngine  # noqa: E402
from parsec_tpu.comm.tcp import TCPCommEngine  # noqa: E402
from parsec_tpu.collections import TwoDimBlockCyclic  # noqa: E402
from parsec_tpu.dsl import ptg  # noqa: E402

CHAIN_JDF = """
descA [ type="collection" ]
NB [ type="int" ]

T(k)

k = 0 .. NB

: descA( k, 0 )

RW X <- (k == 0) ? descA( 0, 0 ) : X T( k-1 )
     -> (k < NB) ? X T( k+1 )
     -> (k == NB) ? descA( NB, 0 )

BODY
{
    X[0, 0] = X[0, 0] + 1.0
}
END
"""


def run_dtd(ctx, eng, rank, nb_ranks, hops):
    """Cross-rank DTD chain: tasks alternate ranks on one tile."""
    from parsec_tpu import dtd
    from parsec_tpu.collections import DictCollection
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, VALUE, unpack_args

    coll = DictCollection(nodes=nb_ranks, rank=rank)
    coll.name = "C"
    coll.add("x", 0, np.zeros(512) if rank == 0 else None)  # 4KB payload
    anchors = {}
    for r in range(nb_ranks):
        a = DictCollection(nodes=nb_ranks, rank=rank)
        a.name = f"anchor{r}"
        a.add("a", r, np.zeros(1) if r == rank else None)
        anchors[r] = a
    tp = dtd.taskpool_new("tcpdtd")
    ctx.add_taskpool(tp)
    tile = tp.tile_of(coll, "x")

    def bump(es, task):
        x, anchor, k = unpack_args(task)
        assert x[0] == k, f"task {k} saw {x[0]}"
        x[0] += 1.0

    for k in range(hops):
        at = tp.tile_of(anchors[k % nb_ranks], "a")
        tp.insert_task(bump, (tile, INOUT), (at, INPUT | AFFINITY),
                       (k, VALUE))
    tp.data_flush_all()
    tp.wait()
    ctx.wait()
    if rank == 0:
        return float(coll.data_of("x").get_copy(0).payload[0])
    return None


def run_dposv(ctx, eng, rank, nb_ranks, n=96, nb=32, nrhs=16,
              device=False):
    """Distributed Cholesky solve across real processes. With
    ``device`` the accelerator chores run (jax device arrays as tile
    payloads), so cross-rank edges take the device-to-device transfer
    plane when one is attached — read results via sync_to_host."""
    from parsec_tpu.ops import dposv, make_spd

    M = make_spd(n)
    rng = np.random.RandomState(1)
    Bm = (rng.rand(n, nrhs) - 0.5).astype(np.float32)

    def dist(lm, ln, src, P, Q):
        d = TwoDimBlockCyclic(lm, ln, nb, nb, P=P, Q=Q, nodes=nb_ranks,
                              rank=rank, dtype=np.float32)
        for (i, j) in d.local_tiles():
            np.copyto(d.tile(i, j),
                      src[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb])
        return d

    A = dist(n, n, M, 2, nb_ranks // 2)
    B = dist(n, nrhs, Bm, nb_ranks, 1)
    A.name, B.name = "descA", "descB"
    dposv(ctx, A, B, rank=rank, nb_ranks=nb_ranks)
    ref = np.linalg.solve(M.astype(np.float64), Bm.astype(np.float64))
    err = 0.0
    for (i, j) in B.local_tiles():
        if device:
            tile = np.asarray(
                B.data_of(i, j).sync_to_host(ctx.devices).payload)
        else:
            tile = B.tile(i, j)
        err = max(err, float(np.abs(
            tile - ref[i * nb:(i + 1) * nb,
                       j * nb:(j + 1) * nb]).max()))
    return err


def run_wave(eng, rank, nb_ranks, n=256, nb=64, use_plane=False):
    """Distributed WAVE dpotrf across real OS processes: every rank
    executes its block-cyclic slice as batched kernels, tile exchange
    rides TAG_WAVE messages over the sockets (dsl/ptg/wave_dist.py).
    With ``use_plane`` the runner's DEFAULT device-plane attach stands
    (tile payloads move device-to-device, TCP carries descriptors +
    acks); without it the host-byte fallback is forced via the
    wave_dist_plane MCA param."""
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    if not use_plane:
        from parsec_tpu.utils.params import params
        params.set_cmdline("wave_dist_plane", "off")

    M = make_spd(n, dtype=np.float64)
    coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64, P=nb_ranks,
                             Q=1, nodes=nb_ranks, rank=rank)
    coll.name = "descA"
    coll.from_numpy(M.copy())
    tp = dpotrf_taskpool(coll, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=eng)
    plane = getattr(eng, "device_plane", None)   # runner auto-attach
    w.run()
    ref = np.linalg.cholesky(M)
    err = 0.0
    for (i, j) in coll.tiles():
        if coll.rank_of(i, j) != rank or i < j:
            continue
        t = np.asarray(coll.data_of(i, j).sync_to_host().payload)
        if i == j:
            t = np.tril(t)
        err = max(err, float(np.abs(
            t - ref[i * nb:(i + 1) * nb, j * nb:(j + 1) * nb]).max()))
    stats = None
    if plane is not None:
        with plane._lock:
            leaked = len(plane._parked)
        stats = dict(plane.stats, leaked_parks=leaked)
    return err, stats


BCAST_JDF = """
descA [ type="collection" ]
descB [ type="collection" ]
R [ type="int" ]

Read(r)
r = 0 .. R-1
: descB( r, 0 )
RW B <- descB( r, 0 )
     -> descB( r, 0 )
READ L <- descA( 0, 0 )
BODY
{
    B = B + L
}
END
"""


def run_wave_bcast(eng, rank, nb_ranks, nb=32):
    """One tile read by every rank under the binomial broadcast tree
    with the device plane attached: interior tree nodes must re-forward
    from the DEVICE arrays the plane pulled (round-4 VERDICT Weak #5 —
    no host np.stack on the forward path when rows are device-resident)."""
    from parsec_tpu.utils.params import params

    params.set_cmdline("wave_dist_bcast", "binomial")
    A0 = np.random.RandomState(3).rand(nb_ranks * nb, nb)
    B0 = np.random.RandomState(4).rand(nb_ranks * nb, nb)
    mk = lambda: TwoDimBlockCyclic(  # noqa: E731
        nb_ranks * nb, nb, nb, nb, dtype=np.float64,
        P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
    dA, dB = mk(), mk()
    dA.name, dB.name = "descA", "descB"
    dA.from_numpy(A0.copy())
    dB.from_numpy(B0.copy())
    tp = ptg.compile_jdf(BCAST_JDF, name="bcastw").new(
        descA=dA, descB=dB, R=nb_ranks, rank=rank, nb_ranks=nb_ranks)
    w = ptg.wave(tp, comm=eng)
    w.run()
    want = B0[rank * nb:(rank + 1) * nb] + A0[:nb]
    got = np.asarray(dB.data_of(rank, 0).sync_to_host().payload)
    return float(np.abs(got - want).max()), w.stats


def run_xfer_stress(eng, rank, nb_ranks, n_tiles=96, nb=512, workers=8):
    """Device-plane soak: rank 0 parks n_tiles MB-scale device arrays,
    rank 1 pulls them all from a thread pool (concurrent pulls over one
    connection), verifies contents, acks; rank 0 asserts every park was
    reclaimed and the byte count matches."""
    import concurrent.futures as cf
    import threading
    import time as _time

    import jax
    from parsec_tpu.comm import DeviceDataPlane

    TAG_DESC = 100
    TAG_DONE = 101
    plane = DeviceDataPlane(eng)
    plane.exchange()
    tile_bytes = nb * nb * 4
    if rank == 0:
        arrays = [jax.device_put(np.full((nb, nb), i, np.float32))
                  for i in range(n_tiles)]
        jax.block_until_ready(arrays)
        descs = []
        for i, a in enumerate(arrays):
            u, shape, dt = plane.register(a)
            descs.append((i, u, shape, dt))
        eng.send_am(1, TAG_DESC, {"descs": descs})
        acked = []
        eng.tag_register(TAG_DONE, lambda src, p: (
            [plane.release(u) for u in p["uuids"]], acked.append(p)))
        deadline = _time.time() + 240
        while not acked and _time.time() < deadline:
            eng.progress()
            _time.sleep(0.001)
        assert acked, "no completion from consumer"
        assert acked[0]["errors"] == [], acked[0]["errors"]
        with plane._lock:
            leaked = len(plane._parked)
        eng.sync()
        return {"rank": 0, "leaked_parks": leaked,
                "serves": plane.stats["serves"]}
    # consumer
    inbox = []
    eng.tag_register(TAG_DESC, lambda src, p: inbox.append(p))
    deadline = _time.time() + 120
    while not inbox and _time.time() < deadline:
        eng.progress()
        _time.sleep(0.001)
    assert inbox, "no descriptors"
    descs = inbox[0]["descs"]
    errors = []
    lock = threading.Lock()

    def pull_one(ent):
        i, u, shape, dt = ent
        try:
            arr = plane.pull(0, u, tuple(shape), dt)
            jax.block_until_ready(arr)
            v = float(np.asarray(arr[0, 0]))
            if v != float(i):
                with lock:
                    errors.append(f"tile {i}: got {v}")
            return u
        except Exception as exc:  # noqa: BLE001
            with lock:
                errors.append(f"tile {i}: {type(exc).__name__}: {exc}")
            return None

    with cf.ThreadPoolExecutor(workers) as ex:
        uuids = [u for u in ex.map(pull_one, descs) if u is not None]
    eng.send_am(0, TAG_DONE, {"uuids": uuids, "errors": errors})
    eng.sync()
    return {"rank": 1, "pulls": plane.stats["pulls"],
            "bytes": plane.stats["bytes_pulled"],
            "expected_bytes": len(descs) * tile_bytes,
            "errors": errors}


FAIL_JDF = CHAIN_JDF.replace("X[0, 0] = X[0, 0] + 1.0", "X = hook(X, k)")


def run_fail(ctx, eng, rank, nb_ranks, hops):
    """Rank 1 kills itself mid-chain; rank 0's wait() must raise."""
    from parsec_tpu.comm.tcp import RankFailedError

    mb = 16
    coll = TwoDimBlockCyclic((hops + 1) * mb, mb, mb, mb, P=nb_ranks,
                             Q=1, nodes=nb_ranks, rank=rank,
                             dtype=np.float32)
    coll.name = "descA"

    # kill on a mid-chain task that rank 1 owns (block-cyclic: odd k)
    kill_k = hops // 2 + (1 - (hops // 2) % 2)

    def hook(X, k):
        if rank == 1 and k == kill_k:
            os._exit(3)  # simulated crash: no teardown, no goodbye
        X[0, 0] = X[0, 0] + 1.0
        return X

    tp = ptg.compile_jdf(FAIL_JDF, name="failchain").new(
        descA=coll, NB=hops, rank=rank, nb_ranks=nb_ranks)
    tp.global_env["hook"] = hook
    ctx.add_taskpool(tp)
    try:
        ctx.wait()
    except RuntimeError as exc:
        detected = isinstance(exc.__cause__, RankFailedError)
        return {"rank": rank, "detected": detected,
                "failed_rank": getattr(exc.__cause__, "rank", None)}
    return {"rank": rank, "detected": False}


def main() -> int:
    rank = int(sys.argv[1])
    nb_ranks = int(sys.argv[2])
    ports = [int(p) for p in sys.argv[3].split(",")]
    hops = int(sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else "ptg"
    # payloads above the short limit must take the GET rendezvous over TCP
    parsec_tpu.params.set_cmdline("runtime_comm_short_limit", "64")
    if mode == "fail":
        # a crashed peer may owe only an activation (no pending GET):
        # strict mode treats any live-context connection tear as failure
        parsec_tpu.params.set_cmdline("comm_failure_strict", "1")

    eng = TCPCommEngine(rank, [("127.0.0.1", p) for p in ports])
    if mode == "xfer_stress":
        try:
            out = run_xfer_stress(eng, rank, nb_ranks)
            print(json.dumps(out), flush=True)
            return 0
        finally:
            eng.fini()
    if mode == "wave_fail":
        # rank 1 dies before contributing its waves; rank 0 must abort
        # QUICKLY via the failure detector, not the full comm timeout
        import time as _time
        try:
            if rank == 1:
                os._exit(3)   # simulated crash, no goodbye
            from parsec_tpu.comm.tcp import RankFailedError
            t0 = _time.time()
            try:
                run_wave(eng, rank, nb_ranks)
                detected = False
            except RankFailedError:
                detected = True
            print(json.dumps({"rank": rank, "detected": detected,
                              "secs": _time.time() - t0}), flush=True)
            return 0 if detected else 7
        finally:
            eng.fini()
    if mode == "wave_bcast_xfer":
        try:
            err, stats = run_wave_bcast(eng, rank, nb_ranks)
            eng.sync()
            print(json.dumps({"rank": rank, "max_err": err,
                              "stats": stats,
                              "bytes": eng.fabric.bytes_count}),
                  flush=True)
            return 0
        finally:
            eng.fini()
    if mode in ("wave", "wave_xfer"):
        # distributed wave execution drives the CE directly (no context)
        try:
            err, xstats = run_wave(eng, rank, nb_ranks,
                                   use_plane=(mode == "wave_xfer"))
            eng.sync()
            out = {"rank": rank, "max_err": err,
                   "msgs": eng.fabric.msg_count,
                   "bytes": eng.fabric.bytes_count,
                   "wire": {k: eng.wire_stats[k] for k in
                            ("reconnects", "replayed_frames",
                             "dup_dropped")}}
            if xstats is not None:
                out["xfer"] = xstats
            print(json.dumps(out), flush=True)
            return 0
        finally:
            eng.fini()
    plane = None
    if mode == "dposv_xfer":
        # device data plane: TCP stays control, tile payloads move
        # device-to-device through the transfer server (comm/xfer.py)
        from parsec_tpu.comm import DeviceDataPlane
        plane = DeviceDataPlane(eng)
        plane.exchange()
    rdep = RemoteDepEngine(eng)
    ctx = parsec_tpu.Context(nb_cores=2, comm=rdep,
                             enable_tpu=(mode == "dposv_xfer"))
    try:
        if mode == "fail":
            out = run_fail(ctx, eng, rank, nb_ranks, hops)
            print(json.dumps(out), flush=True)
            return 0 if out.get("detected") else 7
        if mode in ("dposv", "dposv_xfer"):
            err = run_dposv(ctx, eng, rank, nb_ranks,
                            device=(mode == "dposv_xfer"))
            eng.sync()
            out = {"rank": rank, "max_err": err,
                   "msgs": eng.fabric.msg_count}
            if plane is not None:
                out["xfer"] = plane.stats
            print(json.dumps(out), flush=True)
            return 0
        if mode == "dtd":
            final = run_dtd(ctx, eng, rank, nb_ranks, hops)
            eng.sync()
            out = {"rank": rank, "msgs": eng.fabric.msg_count,
                   "bytes": eng.fabric.bytes_count}
            if final is not None:
                out["final"] = final
            print(json.dumps(out), flush=True)
            return 0
        mb = 16  # 16x16 f32 tile = 1KB > short limit
        coll = TwoDimBlockCyclic((hops + 1) * mb, mb, mb, mb, P=nb_ranks,
                                 Q=1, nodes=nb_ranks, rank=rank,
                                 dtype=np.float32)
        coll.name = "descA"
        tp = ptg.compile_jdf(CHAIN_JDF, name="tcpchain").new(
            descA=coll, NB=hops, rank=rank, nb_ranks=nb_ranks)
        ctx.add_taskpool(tp)
        ctx.wait()
        eng.sync()  # transport barrier before teardown
        out = {"rank": rank, "msgs": eng.fabric.msg_count,
               "bytes": eng.fabric.bytes_count}
        if coll.rank_of(hops, 0) == rank:
            out["final"] = float(coll.tile(hops, 0)[0, 0])
        print(json.dumps(out), flush=True)
        return 0
    finally:
        ctx.fini()


if __name__ == "__main__":
    sys.exit(main())
