"""Comm engine (CE) abstraction: transport-neutral messaging.

Reference behavior: ``parsec_comm_engine_t`` — tagged active messages
(callback per tag), ``mem_register/unregister``, one-sided put/get with
local+remote completion callbacks, pack/unpack, sync, capabilities
(ref: parsec/parsec_comm_engine.h:139-166). The only in-tree transport is
funnelled MPI emulating one-sided ops over two-sided sends
(parsec/parsec_mpi_funnelled.c).

TPU-native re-design: the data plane between ranks ultimately rides
ICI/DCN (XLA collectives / PJRT transfers — comm/collectives.py); the CE
here is the *control* plane and host-memory data plane. Transports:
LocalFabric (in-process ranks, the test fabric standing in for
oversubscribed mpiexec, SURVEY.md §4) and, on real deployments, a DCN
socket transport with the same interface.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


class FlowIds:
    """Per-rank allocator of wire trace contexts (ISSUE 15): a context
    is the compact ``(origin_rank, span_id)`` pair stamped on data-plane
    messages under the ``obs_flow`` knob, shared by the sender's and the
    receiver's flow events so the fleet merge can stitch the edge.
    Installed as ``ce._flow`` by the obs wiring — None keeps every send
    on the one-attribute-check fast path.

    ``live`` (obs_live, ISSUE 16) widens the stamped context to
    ``(origin, span, pool_tp_id, t_send_ns)`` — the taskpool wire id
    for per-pool attribution and the sender's monotonic send instant
    for live flow-lag — but ONLY toward peers whose ``live_to``
    capability negotiated it, so a plain obs_flow receiver keeps seeing
    the 2-tuple its ``origin, span = ctx`` unpacking expects.

    ``tenants`` (serve/, ISSUE 18) widens the live context once more to
    ``(origin, span, pool, t_send_ns, tenant)``: a SessionServer
    installs its taskpool-id -> tenant-name mapping here so data-plane
    traffic of a served pool carries the tenant that submitted it —
    but ONLY toward peers whose ``serve_to`` capability negotiated it,
    so a live-only receiver keeps the 4-tuple it expects.  None (no
    server) keeps the live behavior byte-identical."""

    __slots__ = ("rank", "_next", "_lock", "live", "tenants")

    def __init__(self, rank: int) -> None:
        self.rank = rank
        self._next = 0
        self._lock = threading.Lock()
        self.live = False
        self.tenants: Optional[Dict[Any, str]] = None

    def next_ctx(self) -> Tuple[int, int]:
        with self._lock:
            self._next += 1
            return (self.rank, self._next)


#: data-plane tags that carry a wire trace context when flow tracing is
#: negotiated: activations, GET request/reply, one-sided puts, DTD tile
#: traffic, and memory writebacks — every payload is a dict, so the
#: context rides a ``"_tr"`` key inside the pickled body (chunked
#: transfers inherit it for free).  Control traffic (termdet, barrier,
#: heartbeat, elastic) is never stamped.
_FLOW_TAGS = frozenset((1, 2, 3, 4, 6, 7))  # values asserted below


class Capabilities:
    def __init__(self, sided: int = 1, noncontig: bool = True,
                 multithread: bool = False) -> None:
        self.sided = sided
        self.supports_noncontiguous_datatypes = noncontig
        self.multithreaded = multithread


class RankFailedError(RuntimeError):
    """A peer rank failed mid-run (crash, kill, or heartbeat eviction).

    Failure *detection* is the explicit extension beyond the reference
    (SURVEY.md §5.3: PaRSEC has none — a dead MPI rank hangs the job).
    Two detectors feed this: the reactive one (a torn TCP connection
    while the engine is live, comm/tcp.py) and the proactive one (a
    peer that stops answering heartbeats, ft/detector.py). Either way
    the dead rank aborts this rank's DAG instead of hanging in termdet
    forever. Recovery is the ft/restart.py driver over the
    utils/checkpoint snapshots (or app-level, ex08)."""

    def __init__(self, rank: int, reason: str = "connection lost") -> None:
        super().__init__(f"rank {rank} failed: {reason}")
        self.rank = rank


class MemHandle:
    """Registered memory region handle (ref: parsec_ce_mem_reg_handle_t —
    wraps {ptr, count, datatype}); here it wraps a host array + metadata.

    ``quantize_ok`` is the registrant's per-flow eligibility mark for
    the lossy quantized wire codecs (ISSUE 14): True only for device-
    array TILE payloads (PTG/DTD rendezvous snapshots); checkpoint
    shards and anything else stay lossless. The GET reply propagates it
    so the transport may quantize the bulk buffer toward peers that
    negotiated a codec."""

    _iter = 0
    _lock = threading.Lock()

    def __init__(self, array: Any, meta: Any = None,
                 quantize_ok: bool = False) -> None:
        with MemHandle._lock:
            MemHandle._iter += 1
            self.handle_id = MemHandle._iter
        self.array = array
        self.meta = meta
        self.quantize_ok = bool(quantize_ok)


class CommEngine:
    """Transport interface (ref: parsec_comm_engine_t function table)."""

    #: May the heartbeat detector evict a peer that was PROBED but never
    #: answered? Only sound when a successful probe implies the peer was
    #: verifiably alive and able to reply at probe time — true for TCP
    #: (``hb_ok`` means its receiver thread processed our HELLO and
    #: answers pings without any progress pumping), FALSE for the
    #: in-process fabrics (a probe merely lands in an inbox; the peer
    #: may be healthy but still compiling/initializing, not yet pumping
    #: progress — evicting it would be a false positive).
    ft_probe_baseline = False

    def __init__(self, rank: int, nb_ranks: int) -> None:
        self.rank = rank
        self.nb_ranks = nb_ranks
        self.capabilities = Capabilities()
        self._tag_cbs: Dict[int, Callable] = {}
        self._mem: Dict[int, MemHandle] = {}
        self.on_get_served: Optional[Callable[[int], None]] = None
        # transports invoke this when a message lands in the inbox so a
        # parked worker wakes instead of finishing its backoff sleep
        self.on_arrival: Optional[Callable[[], None]] = None
        # late-bound tags: a message can land before its handler exists
        # (e.g. a fast peer's wave exchange reaching a rank that has not
        # built its runner yet — MPI's posted-recv semantics give this
        # for free); such messages wait here and replay at registration
        self._deferred: List[Tuple[int, int, Any]] = []
        self._deferred_lock = threading.Lock()
        self._deferred_warned: set = set()
        # telemetry sink (obs.spans.CommObs) — None keeps every
        # instrumented site on the one-attribute-check fast path
        # (the PINS ``_active == 0`` pattern)
        self._obs: Optional[Any] = None
        # cross-rank flow tracing (ISSUE 15): a FlowIds allocator when
        # the ``obs_flow`` knob is on AND telemetry is wired — the same
        # None-is-off pattern as ``_obs``
        self._flow: Optional[FlowIds] = None
        # -- fault tolerance (ft/) -------------------------------------
        # uniform failure surface across ALL transports: the TCP engine
        # used to be the only one carrying these, forcing hasattr guards
        # on every consumer (remote_dep, wave_dist)
        self.dead_peers: set = set()
        #: called (peer, reason) when a peer is declared failed;
        #: RemoteDepEngine.attach points this at the context's abort path
        self.on_peer_failure: Optional[Callable[[int, str], None]] = None
        #: HeartbeatDetector when one is installed (ft/detector.py)
        self.ft_detector: Optional[Any] = None
        #: ElasticCoordinator when one is attached (ft/elastic.py);
        #: TAG_ELASTIC traffic arriving before the attach is buffered
        #: (a joiner may announce while the incumbents are mid-stage)
        self.ft_elastic: Optional[Any] = None
        self._elastic_buf: List[Tuple[int, Any]] = []
        #: elastic-recovery counters (ft/elastic.py + ft/restart.py);
        #: polled by obs.register_engine_gauges as the FT::ELASTIC_* /
        #: FT::RESHARD_* gauges — plain dict, nothing on any hot path
        self.elastic_stats: Dict[str, int] = {
            "elastic_resizes": 0, "reshard_bytes": 0, "reshard_us": 0,
            "elastic_joins": 0}
        #: device-plane / planned-redistribution counters (xfer/, comm/
        #: xfer.py); polled by obs.register_engine_gauges as the
        #: COMM::DPLANE_* / COMM::REDIST_ROUNDS / COMM::TWO_LEVEL_*
        #: gauges — plain dict, bumped off the hot path
        self.dplane_stats: Dict[str, int] = {
            "dplane_bytes": 0, "dplane_xfers": 0, "redist_rounds": 0,
            "two_level_reduces": 0}
        #: injected-kill flag: the engine has gone dark (drops all
        #: traffic, answers no heartbeats) — simulates a crashed process
        self._ft_silenced = False
        #: deterministic fault injector (ft/inject.py), or None (the
        #: default: one never-taken branch on the send path)
        self._ft: Optional[Any] = None
        from ..utils.params import params
        spec = params.get("ft_inject")
        if spec:
            from ..ft.inject import FaultInjector
            self._ft = FaultInjector.from_spec(spec, rank=rank)
        # every current-version engine answers heartbeat pings from its
        # progress loop, detector installed or not — liveness proof
        # must not depend on the *local* configuration
        self.tag_register(TAG_HEARTBEAT, self._on_heartbeat)
        # elastic membership traffic (ft/elastic.py) is likewise always
        # receivable: a coordinator may attach later and drain the buffer
        self.tag_register(TAG_ELASTIC, self._on_elastic)

    def _notify_arrival(self) -> None:
        cb = self.on_arrival
        if cb is not None:
            cb()

    MAX_DEFERRED = 4096

    # -- active messages ----------------------------------------------------
    def tag_register(self, tag: int, cb: Callable[[int, Any], None]) -> None:
        """cb(src_rank, payload) runs during progress() on the receiver."""
        # handler install and deferred drain are one atomic step against
        # deliver_message's check-then-defer: without the shared lock a
        # message checked before the install but deferred after the
        # drain would strand forever
        with self._deferred_lock:
            self._tag_cbs[tag] = cb
            pending = [m for m in self._deferred if m[1] == tag]
            if pending:
                self._deferred = [m for m in self._deferred if m[1] != tag]
        for src, _tag, payload in pending if pending else ():
            cb(src, payload)

    def deliver_message(self, src: int, tag: int, payload: Any) -> bool:
        """Route one drained message to its handler, or hold it if the
        tag is not bound yet (replayed by tag_register — MPI's
        posted-recv semantics). Returns True when handled now.

        A tag that never gets a handler is a bug: warn once, and fail
        loudly if the hold queue grows past MAX_DEFERRED instead of
        leaking quietly."""
        obs = self._obs
        if obs is not None:
            # counted at ARRIVAL (deferred or not) so sent/received
            # totals balance across ranks
            obs.am_arrived(src, tag, payload)
            if tag in _FLOW_TAGS and isinstance(payload, dict):
                # the sender's wire trace context (ISSUE 15): record the
                # receive half of the flow edge at arrival — exactly
                # once per message even when the tag defers, so every
                # ``ph:"s"`` has its ``ph:"f"`` and the merged timeline
                # stitches sender and receiver spans by one id.  Only
                # data-plane tags: a USER payload's "_tr" key is the
                # application's business, never interpreted
                ctx = payload.get("_tr")
                if ctx is not None:
                    obs.flow_recv(src, tag, ctx)
        with self._deferred_lock:
            cb = self._tag_cbs.get(tag)
            if cb is None:
                if len(self._deferred) >= self.MAX_DEFERRED:
                    raise RuntimeError(
                        f"rank {self.rank}: {len(self._deferred)} messages "
                        f"deferred for unregistered tags (first tags: "
                        f"{sorted({m[1] for m in self._deferred[:50]})}) — "
                        f"a handler was never registered")
                self._deferred.append((src, tag, payload))
        if cb is None:
            if tag not in self._deferred_warned:
                self._deferred_warned.add(tag)
                from ..utils import logging as plog
                plog.debug.verbose(
                    1, "rank %d: deferring message(s) for unregistered "
                    "tag %d", self.rank, tag)
            return False
        if obs is not None:
            t0 = time.monotonic_ns()
            cb(src, payload)
            obs.delivered(src, self.rank, tag, t0)
            return True
        cb(src, payload)
        return True

    def tag_unregister(self, tag: int) -> None:
        self._tag_cbs.pop(tag, None)

    def tag_registered(self, tag: int) -> bool:
        """True if ``tag`` already has a handler installed — consumers
        that must own a tag exclusively (ServeClient on
        TAG_SERVE_REPLY) check before registering, since
        ``tag_register`` silently replaces."""
        with self._deferred_lock:
            return tag in self._tag_cbs

    def send_am(self, dst: int, tag: int, payload: Any) -> None:
        raise NotImplementedError

    # -- cross-rank flow tracing (ISSUE 15) ---------------------------------
    def flow_to(self, dst: int) -> bool:
        """May a wire trace context travel toward ``dst``?  In-process
        fabrics share this build (always True); the TCP engine gates on
        the peer's HELLO ``"tr"`` capability, so a mixed-version peer's
        wire bytes stay exactly what a knob-unset build would send."""
        return True

    def live_to(self, dst: int) -> bool:
        """May the EXTENDED obs_live context (pool id + send instant)
        travel toward ``dst``?  Same-build in-process fabrics: yes; the
        TCP engine gates on the peer's HELLO ``"lv"`` capability so an
        obs_flow-only receiver never sees a 4-tuple."""
        return True

    def serve_to(self, dst: int) -> bool:
        """May the serve-extended context (tenant name, ISSUE 18)
        travel toward ``dst``?  Same-build in-process fabrics: yes; the
        TCP engine gates on the peer's HELLO ``"sv"`` capability so a
        live-only receiver never sees a 5-tuple."""
        return True

    def dplane_to(self, dst: int) -> bool:
        """May bulk payload bytes toward ``dst`` ride the device plane
        (ISSUE 19)?  In-process fabrics: yes whenever a plane is
        attached (same build both ends); the TCP engine additionally
        gates on the peer's HELLO ``"dp"`` capability — both ends must
        run with ``xfer_dplane`` set, or the bytes stay on the session
        wire exactly as a knob-unset build would send them."""
        return getattr(self, "device_plane", None) is not None

    def _flow_stamp(self, dst: int, tag: int,
                    payload: Any) -> Tuple[Any, Optional[Tuple[int, int]]]:
        """Stamp one outbound data-plane message with a fresh trace
        context: returns ``(payload', ctx)`` where ``payload'`` is a
        SHALLOW copy carrying ``"_tr": (origin_rank, span_id)`` — the
        caller's dict is never mutated (one activation dict fans out to
        several bcast children; each hop is its own flow edge).  ctx is
        None for self-sends, control tags, non-dict payloads, and peers
        the capability negotiation excluded — and on THAT path any
        inbound ``"_tr"`` a re-forwarded message still carries is
        STRIPPED (again on a copy): a bcast hop re-sends the received
        dict, and the upstream context must neither leak to a
        mixed-version peer (whose wire bytes are contractually
        knob-unset-identical) nor fake a second receive half of the
        upstream edge."""
        if tag not in _FLOW_TAGS or not isinstance(payload, dict):
            # control/user tags pass through UNTOUCHED — an application
            # payload's "_tr" key is never ours to strip
            return payload, None
        fl = self._flow
        if fl is None or dst == self.rank or not self.flow_to(dst):
            if "_tr" in payload:
                payload = dict(payload)
                del payload["_tr"]
            return payload, None
        ctx = fl.next_ctx()
        if fl.live and self.live_to(dst):
            # obs_live extension: taskpool wire id (per-pool
            # attribution — the data-plane dicts already carry
            # "tp_id"; GET traffic does not, and attributes to None)
            # and the sender's monotonic send instant (flow lag)
            ctx = (ctx[0], ctx[1], payload.get("tp_id"),
                   time.monotonic_ns())
            tn = fl.tenants
            if tn and self.serve_to(dst):
                # serve extension (ISSUE 18): the tenant that submitted
                # the pool this message belongs to — None for pools the
                # server does not own (and for pool-less GET traffic),
                # so foreign workloads stay unattributed, not mislabeled
                ctx = ctx + (tn.get(ctx[2]),)
        payload = dict(payload)
        payload["_tr"] = ctx
        return payload, ctx

    def mesh_local_with(self, peer: int) -> bool:
        """True when ``peer`` shares this process's XLA client, so a
        device-array payload can ship BY REFERENCE (jax arrays are
        immutable) instead of serialize -> wire -> deserialize — the
        mesh-local fast path remote_dep short-circuits through
        (ISSUE 6). Cross-process transports stay False; in-process
        fabrics override."""
        return False

    # -- fault tolerance (ft/) ----------------------------------------------
    def report_peer_failure(self, peer: int, reason: str) -> None:
        """Uniform failure funnel: mark ``peer`` dead and notify the
        runtime. Reactive transports (tcp._peer_died) and the proactive
        heartbeat detector both end here, so every consumer sees ONE
        API regardless of transport. Idempotent."""
        if peer in self.dead_peers or self.peer_finished(peer):
            return
        self.dead_peers.add(peer)
        from ..utils import logging as plog
        plog.warning("rank %d: peer %d presumed FAILED (%s)",
                     self.rank, peer, reason)
        cb = self.on_peer_failure
        if cb is not None:
            cb(peer, reason)
        # a membership change invalidates any in-flight resize
        # agreement: wake the elastic coordinator so it re-proposes
        # from the reduced survivor set instead of waiting out its tick
        co = self.ft_elastic
        if co is not None:
            co.membership_changed()

    def peer_finished(self, peer: int) -> bool:
        """True when ``peer`` shut down CLEANLY (it finished its work
        and fini'd) — such a peer stops heartbeating but must never be
        declared failed. Transports that can observe orderly shutdown
        override this."""
        return False

    def peer_suspect(self, peer: int) -> bool:
        """True while ``peer``'s link is torn but a reliable session is
        still reconnecting inside its budget (comm/tcp.py, ISSUE 10) —
        a TRANSIENT fault, not a death. Consumers park instead of
        escalating: the heartbeat detector defers its verdict (probes
        cannot cross a torn link, so the silence proves nothing) and
        remote_dep skips prefetching from the peer. Transports without
        sessions never suspect."""
        return False

    def ft_link_fault(self, peer: int) -> None:
        """Chaos hook (ft/inject.py ``flap:``/``disconnect:``): tear
        this rank's link(s) toward ``peer`` without killing anything.
        Only socket transports have a link to tear; the in-process
        fabrics ignore it."""

    def ft_silence(self) -> None:
        """Injected kill (ft/inject.py): the engine goes dark — drops
        all inbound and outbound traffic and answers no heartbeats,
        simulating a crashed process whose sockets may still be open
        (so only PROACTIVE detection can find it)."""
        self._ft_silenced = True

    def ft_outbound(self, dst: int, tag: int) -> int:
        """Chaos consult for one outbound frame: how many copies to
        deliver — 0 (engine silenced, or injected drop), 1 (normal),
        or 2 (injected duplicate). Injected delays sleep inside
        ``on_send``; an injected failsend raises from here. The ONE
        copy of the verdict semantics every transport's
        ``_transport_post`` applies."""
        if self._ft_silenced:
            return 0
        ft = self._ft
        if ft is None or dst == self.rank:
            return 1
        verdict = ft.on_send(dst, tag)
        if verdict == "drop":
            return 0
        if verdict == "flap":
            # the injector marked the link down: hard-close the
            # socket(s) FIRST, so this frame is accepted-but-unsent —
            # under a session it parks and replays, without one the
            # loss is loud (lost_sends), exactly like a real link fault
            self.ft_link_fault(dst)
            return 1
        return 2 if verdict == "dup" else 1

    def ft_ping(self, peer: int, seq: int, t_ns: int) -> bool:
        """Send one heartbeat probe toward ``peer``; True when a probe
        actually left. The base path rides a TAG_HEARTBEAT active
        message (in-process fabrics); the TCP engine overrides with a
        wire-level K_PING frame answered by the peer's receiver thread,
        so TCP liveness is independent of the progress cadence."""
        if self._ft_silenced or peer in self.dead_peers \
                or self.peer_finished(peer):
            return False
        try:
            self.send_am(peer, TAG_HEARTBEAT,
                         {"op": "ping", "seq": seq, "t": t_ns})
        except Exception:  # noqa: BLE001 - a probe must never propagate
            return False
        return True

    MAX_ELASTIC_BUF = 256

    def ft_elastic_send(self, peer: int, payload: Any) -> bool:
        """Send one elastic membership frame toward ``peer``; True when
        it actually left. Mixed-version gated like ``ft_ping``: the
        base path rides a TAG_ELASTIC active message (in-process
        fabrics introspect the peer's handler); the TCP engine
        overrides with a wire-level K_ELASTIC frame delivered by the
        peer's receiver thread, gated on the HELLO ``el`` capability —
        a pre-elastic peer is never part of a resize agreement."""
        if self._ft_silenced or peer in self.dead_peers \
                or self.peer_finished(peer):
            return False
        try:
            self.send_am(peer, TAG_ELASTIC, dict(payload))
        except Exception:  # noqa: BLE001 - a proposal must never propagate
            return False
        return True

    def _on_elastic(self, src: int, payload: Any) -> None:
        """TAG_ELASTIC / K_ELASTIC arrival (progress drain or, on TCP,
        the receiver thread): hand to the attached coordinator, or
        buffer until one attaches (ElasticCoordinator.__init__ drains
        under the same lock, so no message can slip between the
        attach-check and the buffer append)."""
        if self._ft_silenced:
            return
        with self._deferred_lock:
            co = self.ft_elastic
            if co is None:
                if len(self._elastic_buf) < self.MAX_ELASTIC_BUF:
                    self._elastic_buf.append((src, payload))
                return
        co.deliver(src, payload)

    def _on_heartbeat(self, src: int, payload: Any) -> None:
        if self._ft_silenced:
            return
        op = payload.get("op")
        if op == "ping":
            # any heartbeat traffic FROM the peer proves it speaks the
            # protocol and is alive right now
            det = self.ft_detector
            if det is not None:
                det.note_alive(src)
            try:
                self.send_am(src, TAG_HEARTBEAT,
                             {"op": "pong", "seq": payload["seq"],
                              "t": payload["t"]})
            except Exception:  # noqa: BLE001 - peer died racing the reply
                pass
        elif op == "pong":
            det = self.ft_detector
            if det is not None:
                det.note_alive(
                    src, rtt=(time.monotonic_ns() - payload["t"]) / 1e9)

    # -- registered memory + one-sided emulation ----------------------------
    def mem_register(self, array: Any, meta: Any = None,
                     quantize_ok: bool = False) -> MemHandle:
        h = MemHandle(array, meta, quantize_ok=quantize_ok)
        self._mem[h.handle_id] = h
        return h

    def mem_unregister(self, handle: MemHandle) -> None:
        self._mem.pop(handle.handle_id, None)

    def get(self, src_rank: int, remote_handle_id: int,
            on_complete: Callable[[Any], None]) -> None:
        """One-sided get: fetch the remote registered region
        (emulated with a GET-request AM + data reply, like the funnelled
        MPI engine, parsec_mpi_funnelled.c:245-365).

        Aggregation contract: gets issued from message handlers during
        one progress() drain MAY be batched per peer into a single
        request/reply frame — on_complete still fires once per get,
        but callers must not assume one wire message per call."""
        raise NotImplementedError

    def put(self, dst_rank: int, remote_handle_id: int, array: Any,
            on_complete: Optional[Callable] = None) -> None:
        raise NotImplementedError

    # -- progress -----------------------------------------------------------
    def progress(self) -> int:
        """Drain incoming messages; returns #messages handled."""
        raise NotImplementedError

    def sync(self) -> None:
        """Barrier across ranks."""
        raise NotImplementedError

    def fini(self) -> None:
        pass


# wire tags (ref: parsec/remote_dep.h:41-48)
TAG_ACTIVATE = 1
TAG_GET_REQ = 2
TAG_GET_DATA = 3
TAG_PUT_DATA = 4
TAG_TERMDET = 5
TAG_DTD_DATA = 6
TAG_MEM_PUT = 7
TAG_HEARTBEAT = 8   # ft/ liveness probes (ping/pong AMs; tcp rides K_PING)
TAG_ELASTIC = 9     # ft/ elastic membership (grid resize / join; K_ELASTIC)
TAG_SERVE = 10      # serve/ session control: open/submit/wait requests
TAG_SERVE_REPLY = 11  # serve/ replies (admission verdicts, completions)
TAG_USER_BASE = 16

# the flow-traced data-plane tag set is spelled with literals above
# (the tags are defined after the class body); keep the two in sync
assert _FLOW_TAGS == {TAG_ACTIVATE, TAG_GET_REQ, TAG_GET_DATA,
                      TAG_PUT_DATA, TAG_DTD_DATA, TAG_MEM_PUT}
