"""ops subpackage."""
