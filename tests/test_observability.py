"""Observability: DOT grapher, SDE counters, PINS checker modules,
Chrome-trace export, and the ptg_to_dtd replay.

Reference analogs: parsec_prof_grapher.c (DOT capture), papi_sde.c
(software counters), pins/iterators_checker, pins/papi, pins/ptg_to_dtd,
profiling.c + tools/profiling (trace export / pandas tables),
tests/profiling/check-async.py.
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.collections import ops as cops
from parsec_tpu.dsl import ptg
from parsec_tpu.profiling import (IteratorsCheckerModule, TaskTimeModule,
                                  TASKS_RETIRED, grapher, sde)
from parsec_tpu.profiling.trace import Profile
from parsec_tpu.profiling.pins import TaskProfilerModule

TILE = 4

CHAIN_JDF = """
descA [ type="collection" ]
NT [ type="int" ]

STEP(k)
k = 0 .. NT-1
: descA( 0, 0 )
RW A <- (k == 0) ? descA( 0, 0 ) : A STEP( k-1 )
     -> (k < NT-1) ? A STEP( k+1 )
     -> (k == NT-1) ? descA( 0, 0 )
BODY
{
    A = A + 1.0
}
END
"""


def _chain_tp(nt=4):
    A = TwoDimBlockCyclic(TILE, TILE, TILE, TILE).from_numpy(
        np.zeros((TILE, TILE), np.float32))
    tp = ptg.compile_jdf(CHAIN_JDF, name="chain").new(descA=A, NT=nt)
    return tp, A


def test_grapher_captures_nodes_and_edges(ctx):
    grapher.enable()
    try:
        tp, A = _chain_tp(5)
        ctx.add_taskpool(tp)
        ctx.wait()
        assert grapher.nb_nodes() == 5
        assert grapher.nb_edges() == 4
        dot = grapher.to_dot()
        assert "digraph" in dot and "STEP_0_" in dot
        assert dot.count("->") == 4
    finally:
        grapher.disable()


def test_grapher_dtd_edges(ctx):
    from parsec_tpu.dsl import dtd
    from parsec_tpu.dsl.dtd import INOUT, unpack_args
    grapher.enable()
    try:
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        tile = tp.tile_of_array(np.zeros((2, 2), np.float32))

        def bump(es, task):
            (t,) = unpack_args(task)
            t += 1

        for _ in range(3):
            tp.insert_task(bump, (tile, INOUT), name="bump")
        tp.data_flush_all()
        tp.wait()
        assert grapher.nb_nodes() >= 3
        assert grapher.nb_edges() >= 2  # the INOUT chain
    finally:
        grapher.disable()


def test_sde_counters(ctx):
    """Counters are per-context: each in-process rank counts only its own
    tasks (the reference's registry is per-process == per-rank)."""
    before = ctx.sde.read(TASKS_RETIRED)
    tp, A = _chain_tp(6)
    ctx.add_taskpool(tp)
    ctx.wait()
    assert ctx.sde.read(TASKS_RETIRED) >= before + 6
    snap = ctx.sde.snapshot()
    assert TASKS_RETIRED in snap
    # the scheduler gauge answers (possibly -1 when unsupported)
    assert "PARSEC::SCHEDULER::PENDING_TASKS" in snap


def test_sde_counters_isolated_between_contexts():
    """A second context's work must not inflate the first's counters."""
    import parsec_tpu
    c1 = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    c2 = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    try:
        tp, _ = _chain_tp(4)
        c2.add_taskpool(tp)
        c2.wait()
        assert c1.sde.read(TASKS_RETIRED) == 0
        assert c2.sde.read(TASKS_RETIRED) >= 4
    finally:
        c1.fini()
        c2.fini()


def test_iterators_checker_clean_dag(ctx):
    from parsec_tpu.ops import dpotrf, make_spd
    mod = IteratorsCheckerModule()
    mod.enable()
    try:
        n = 4 * TILE
        M = make_spd(n)
        A = TwoDimBlockCyclic(n, n, TILE, TILE).from_numpy(M)
        dpotrf(ctx, A)
        assert mod.checked > 0
        assert mod.errors == [], mod.errors[:3]
    finally:
        mod.disable()


def test_task_time_module(ctx):
    mod = TaskTimeModule()
    mod.enable()
    try:
        tp, A = _chain_tp(4)
        ctx.add_taskpool(tp)
        ctx.wait()
        assert mod.count.get("STEP", 0) == 4
        assert mod.wall_ns.get("STEP", 0) > 0
    finally:
        mod.disable()


def test_chrome_trace_and_dataframe(ctx, tmp_path):
    prof = Profile(rank=0)
    mod = TaskProfilerModule(prof)
    mod.enable()
    try:
        tp, A = _chain_tp(3)
        ctx.add_taskpool(tp)
        ctx.wait()
    finally:
        mod.disable()
    doc = prof.to_chrome_trace()
    names = {e["name"] for e in doc["traceEvents"]}
    assert "exec:STEP" in names
    out = prof.dump(str(tmp_path / "t.json"))
    assert out.endswith(".json")
    df = prof.to_dataframe()
    assert (df["name"] == "exec:STEP").sum() == 3
    assert (df["duration_ns"] > 0).all()


def test_ptg_to_dtd_replay(ctx):
    """The GEMM k-chain JDF replayed through DTD matches numpy."""
    from parsec_tpu.dsl.ptg.to_dtd import ptg_to_dtd
    from tests.test_ptg_gemm import GEMM_JDF

    mt = nt = kt = 2
    rng = np.random.RandomState(11)
    Am = rng.rand(mt * TILE, kt * TILE).astype(np.float32)
    Bm = rng.rand(kt * TILE, nt * TILE).astype(np.float32)
    Cm = rng.rand(mt * TILE, nt * TILE).astype(np.float32)
    A = TwoDimBlockCyclic(mt * TILE, kt * TILE, TILE, TILE).from_numpy(Am)
    B = TwoDimBlockCyclic(kt * TILE, nt * TILE, TILE, TILE).from_numpy(Bm)
    C = TwoDimBlockCyclic(mt * TILE, nt * TILE, TILE, TILE).from_numpy(Cm)
    tp = ptg.compile_jdf(GEMM_JDF, name="gemm").new(
        descA=A, descB=B, descC=C, MT=mt, NT=nt, KT=kt)
    ptg_to_dtd(tp, ctx)
    np.testing.assert_allclose(C.to_numpy(), Cm + Am @ Bm, rtol=2e-5)


def test_ptg_to_dtd_replay_dpotrf(ctx):
    """Cross-DSL consistency on a non-trivial DAG: dpotrf via DTD."""
    from parsec_tpu.dsl.ptg.to_dtd import ptg_to_dtd
    from parsec_tpu.ops import make_spd
    from parsec_tpu.ops.dpotrf import dpotrf_taskpool

    n = 3 * TILE
    M = make_spd(n)
    A = TwoDimBlockCyclic(n, n, TILE, TILE).from_numpy(M)
    ptg_to_dtd(dpotrf_taskpool(A), ctx)
    L = np.tril(A.to_numpy())
    np.testing.assert_allclose(L @ L.T, M, atol=5e-4)


# --------------------------------------------------------------------- #
# debug history ring (ref: PARSEC_DEBUG_HISTORY, debug_marks.c, §5.2)   #
# --------------------------------------------------------------------- #
def test_debug_history_ring_wraps():
    from parsec_tpu.utils import debug_history as dh
    ring = dh.DebugHistory(capacity=4)
    for i in range(7):
        ring.mark("M", i)
    ents = ring.entries()
    assert len(ents) == 4
    assert [e[3] for e in ents] == [3, 4, 5, 6]  # oldest dropped, order kept
    assert "newest last" in ring.dump()
    assert len(ring) == 4


def test_debug_history_records_transitions(ctx):
    from parsec_tpu import dtd
    from parsec_tpu.utils import debug_history as dh
    dh.enable(256)
    try:
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        for _ in range(3):
            tp.insert_task(lambda es, task: None)
        tp.wait()
        names = {e[2] for e in dh.history.entries()}
        assert "EXEC_BEGIN" in names and "COMPLETE_EXEC_END" in names
    finally:
        dh.disable()
    assert not dh.enabled()


def test_debug_history_dumped_on_task_error(capsys):
    import parsec_tpu
    from parsec_tpu import dtd
    from parsec_tpu.utils import debug_history as dh
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("debug_history_size", "128")
    try:
        c = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
        try:
            tp = dtd.taskpool_new()
            c.add_taskpool(tp)

            def boom(es, task):
                raise ValueError("intentional")

            tp.insert_task(boom)
            with pytest.raises(RuntimeError):
                tp.wait()
        finally:
            c.fini()
        err = capsys.readouterr().err
        assert "debug history" in err and "TASK_ERROR" in err
    finally:
        dh.disable()
        parsec_tpu.params.reset()


def test_debug_history_unhooked_at_fini():
    """A fini'd context must not leave the global PINS feed enabled."""
    import parsec_tpu
    from parsec_tpu.utils import debug_history as dh
    from parsec_tpu.profiling.pins import pins_is_active
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("debug_history_size", "64")
    c = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    parsec_tpu.params.reset()
    assert dh.enabled()
    c.fini()
    assert not dh.enabled()
    assert not pins_is_active()


def test_es_rusage_report(ctx):
    """Per-ES rusage deltas (ref: getrusage reports, scheduling.c:45-90)."""
    from parsec_tpu.runtime.scheduling import es_rusage_report
    es = ctx.execution_streams[0]
    first = es_rusage_report(es)  # absolute thread counters at baseline
    assert {"utime_s", "stime_s", "vcsw", "ivcsw", "maxrss_kb"} <= set(first)
    tp, _ = _chain_tp(4)
    ctx.add_taskpool(tp)
    ctx.wait()
    delta = es_rusage_report(es)
    # deltas must be non-negative and bounded by the wall time of the
    # chain run — absolute counters leaking through would exceed this
    # (the baseline call above already accrued test-session utime)
    assert 0.0 <= delta["utime_s"] <= 5.0
    assert delta["vcsw"] >= 0 and delta["ivcsw"] >= 0
    sum(i * i for i in range(2_000_000))  # measurable cpu burn
    delta2 = es_rusage_report(es)
    # the burn happened on THIS thread: its delta sees it, stays small,
    # and a wrong-direction subtraction would go negative
    assert 0.0 <= delta2["utime_s"] <= 5.0


def test_hw_counters_module_graceful():
    """perf_event_open PINS module (pins/papi analog): counts real
    hardware events when the kernel allows, silently no-ops when the
    sandbox refuses PMU access."""
    import numpy as np
    import parsec_tpu
    from parsec_tpu.profiling.pins import HWCountersModule, pins_is_active

    mod = HWCountersModule()
    ctx = parsec_tpu.init(nb_cores=1)
    try:
        mod.enable()
        if not mod.available:
            assert not pins_is_active()   # refused: must be a no-op
            return
        from parsec_tpu import dtd
        from parsec_tpu.dsl.dtd import INOUT, unpack_args
        tp = dtd.taskpool_new()
        ctx.add_taskpool(tp)
        tile = tp.tile_of_array(np.ones((64, 64), np.float32))

        def square(es, task):
            (x,) = unpack_args(task)
            x @ x  # measurable instruction count

        for _ in range(4):
            tp.insert_task(square, (tile, INOUT))
        tp.data_flush_all()
        tp.wait()
        s = mod.summary()
        assert s and all(v["instructions"] > 0 for v in s.values())
    finally:
        mod.disable()
        ctx.fini()


def test_perfctr_wrapper_units():
    """The raw wrapper degrades with OSError (never crashes) and its
    attr layout parses."""
    import pytest
    from parsec_tpu.profiling import perfctr

    assert set(perfctr.PERF_EVENTS) >= {"instructions", "cycles"}
    if not perfctr.perf_available():
        with pytest.raises(OSError):
            perfctr.PerfCounterSet.open(["instructions"])
    else:
        s = perfctr.PerfCounterSet.open(["instructions"])
        a = s.read()
        sum(i * i for i in range(50000))
        b = s.read()
        assert b[0] > a[0]
        s.close()
