"""Closed-loop self-tuning controller (ISSUE 17, ``tune/``): the
decision matrix driven with synthetic window digests — deterministic
legs per family (escalation, budget cap, revert memory, hysteresis,
mixed-version peers) plus the knob-unset inertness contract.
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.tune import (CODEC_COST, CODEC_LADDER, Controller,
                             register_tune_gauges)
from parsec_tpu.utils.params import params


# ---------------------------------------------------------------------- #
# synthetic actuation targets                                            #
# ---------------------------------------------------------------------- #
class FakeEngine:
    """The transport tuning seams the controller actuates against."""

    def __init__(self, tunable=True):
        self.tunable = tunable
        self.sent = []          # (peer, payload) from tune_send (rx dir)
        self.codecs = {}        # peer -> codec from set_quant_codec (tx)
        self.rx_counts = {}     # peer -> (raw, quant) for rx_quant_ratio

    def tune_to(self, peer):
        return self.tunable

    def tune_send(self, peer, payload):
        self.sent.append((peer, dict(payload)))
        return True

    def set_quant_codec(self, peer, codec):
        self.codecs[peer] = codec
        return True

    def active_quant_codec(self, peer):
        return self.codecs.get(peer)

    def rx_quant_ratio(self, peer):
        return self.rx_counts.get(peer, (0, 0))


class FakeDevice:
    """A device exposing the hill-climbed knobs + the stats the
    controller differences per window."""

    def __init__(self, batch_max=16):
        self.name = "fake0"
        self.batch_max = batch_max
        self.prefetch_depth = 4
        self.flush_segments = 4
        self.stats = {"batches": 0, "batched_tasks": 0,
                      "dispatch_ns": 0, "dispatch_tasks": 0,
                      "prefetch_issued": 0, "prefetch_hits": 0,
                      "segmented_flushes": 0}

    def window(self, batches=0, tasks=0, ns=0, n=0,
               pf_issued=0, pf_hits=0, flushes=0):
        """Advance the cumulative stats by one window's worth."""
        self.stats["batches"] += batches
        self.stats["batched_tasks"] += tasks
        self.stats["dispatch_ns"] += ns
        self.stats["dispatch_tasks"] += n
        self.stats["prefetch_issued"] += pf_issued
        self.stats["prefetch_hits"] += pf_hits
        self.stats["segmented_flushes"] += flushes


class FakeLive:
    """The subscriber seam's annotate target."""

    def __init__(self):
        self.annotations = []

    def annotate(self, name, args):
        self.annotations.append((name, dict(args)))


def make_ctl(eng=None, devices=(), budget=1e-1, hysteresis=2, **kw):
    live = FakeLive()
    ctl = Controller(0, live, engine=eng, devices=devices,
                     residual_budget=budget, hysteresis=hysteresis, **kw)
    return ctl, live


def slow_bw_digest(win, peer=1, bw=1.0):
    return {"window": win, "links": {}, "bw": {peer: bw}, "fired": ()}


def hot_link_digest(win, src=1, z=9.0):
    return {"window": win,
            "links": {f"R{src}->R0": {"warm": True, "z": z}},
            "bw": {}, "fired": ()}


# ---------------------------------------------------------------------- #
# leg 1: a bandwidth-bound link escalates (both directions)              #
# ---------------------------------------------------------------------- #
def test_tx_bw_floor_escalates_one_rung_per_cooldown():
    eng = FakeEngine()
    ctl, live = make_ctl(eng, budget=1e-1, hysteresis=2)
    walls = []
    for w in range(12):
        ctl.on_window(slow_bw_digest(w))
        walls.append(eng.codecs.get(1))
    # two sustained-slow windows arm the move, then one rung per
    # cooldown period: qbf16 first, qint8 after, never in one jump
    assert walls[0] is None
    assert "qbf16" in walls
    assert eng.codecs[1] == "qint8"
    assert walls.index("qbf16") < walls.index("qint8")
    assert ctl.counts["codec_moves"] == 2
    assert ctl.counts["decisions"] == 2
    names = [n for n, _ in live.annotations]
    assert names.count("tune:codec") == 2
    dirs = {a["dir"] for n, a in live.annotations if n == "tune:codec"}
    assert dirs == {"tx"}


def test_rx_exposed_z_renegotiates_the_sender():
    eng = FakeEngine()
    ctl, live = make_ctl(eng, budget=1e-2, hysteresis=2)
    for w in range(4):
        ctl.on_window(hot_link_digest(w))
    # the rx direction actuates by ASKING the sender (K_TUNE payload),
    # never by touching this rank's own tx codec
    assert eng.sent and eng.sent[0][0] == 1
    assert eng.sent[0][1] == {"op": "codec", "codec": "qbf16"}
    assert eng.codecs == {}
    assert ctl.counts["codec_moves"] == 1


# ---------------------------------------------------------------------- #
# leg 2: the residual budget caps the ladder                             #
# ---------------------------------------------------------------------- #
def test_residual_budget_caps_the_ladder():
    # 1e-2 affords qbf16 (cost 1e-2) but not qint8 (cost 1e-1)
    eng = FakeEngine()
    ctl, _ = make_ctl(eng, budget=1e-2, hysteresis=1)
    assert ctl.max_rung == CODEC_LADDER.index("qbf16")
    for w in range(20):
        ctl.on_window(slow_bw_digest(w))
    assert eng.codecs[1] == "qbf16"        # stuck at the budget's rung
    assert ctl.counts["codec_moves"] == 1
    # zero budget affords nothing: the family is inert
    eng2 = FakeEngine()
    ctl2, live2 = make_ctl(eng2, budget=0.0, hysteresis=1)
    for w in range(10):
        ctl2.on_window(slow_bw_digest(w))
        ctl2.on_window(hot_link_digest(w))
    assert eng2.codecs == {} and eng2.sent == []
    assert ctl2.counts["codec_moves"] == 0
    assert live2.annotations == []


# ---------------------------------------------------------------------- #
# leg 3: a regressing device move is rolled back                         #
# ---------------------------------------------------------------------- #
def test_device_move_reverts_on_objective_regress():
    dev = FakeDevice(batch_max=16)
    ctl, live = make_ctl(devices=(dev,), hysteresis=2)
    # window 0 only establishes the stats baseline (deltas are zero);
    # then 2 windows of sparse occupancy (2 tasks/batch vs max 16) at
    # a healthy 10 us/task objective arm + commit the halving move
    for w in range(3):
        dev.window(batches=10, tasks=20, ns=200_000, n=20)
        ctl.on_window({"window": w, "links": {}, "bw": {}, "fired": ()})
    assert dev.batch_max == 8
    assert ctl.counts["device_moves"] == 1
    # the move is on probation: the objective EWMA now regresses far
    # past regress_pct, so the probation judgment restores the old value
    for w in range(3, 5):
        dev.window(batches=10, tasks=20, ns=2_000_000, n=20)
        ctl.on_window({"window": w, "links": {}, "bw": {}, "fired": ()})
    assert dev.batch_max == 16
    assert ctl.counts["reverts"] == 1
    names = [n for n, _ in live.annotations]
    assert names == ["tune:device", "tune:revert"]
    revert = live.annotations[1][1]
    assert revert["knob"] == "batch_max" and revert["to"] == 16


def test_device_move_sticks_when_objective_holds():
    dev = FakeDevice(batch_max=16)
    ctl, _ = make_ctl(devices=(dev,), hysteresis=2)
    for w in range(6):
        dev.window(batches=10, tasks=20, ns=200_000, n=20)
        ctl.on_window({"window": w, "links": {}, "bw": {}, "fired": ()})
    # steady objective: the halving survives probation and, after the
    # cooldown, the still-sparse signal earns the next halving
    assert dev.batch_max <= 8
    assert ctl.counts["reverts"] == 0


# ---------------------------------------------------------------------- #
# leg 4: hysteresis holds under an oscillating signal                    #
# ---------------------------------------------------------------------- #
def test_oscillating_signal_never_commits_a_move():
    eng = FakeEngine()
    dev = FakeDevice(batch_max=16)
    ctl, live = make_ctl(eng, devices=(dev,), hysteresis=2)
    for w in range(20):
        if w % 2 == 0:      # slow window ...
            ctl.on_window(slow_bw_digest(w))
            dev.window(batches=10, tasks=20, ns=200_000, n=20)
        else:               # ... then a healthy one: streaks never reach 2
            ctl.on_window({"window": w,
                           "links": {"R1->R0": {"warm": True, "z": 0.1}},
                           "bw": {1: 500.0}, "fired": ()})
            dev.window(batches=10, tasks=140, ns=200_000, n=140)
    assert eng.codecs == {} and eng.sent == []
    assert dev.batch_max == 16
    assert ctl.counts["decisions"] == 0
    assert live.annotations == []


# ---------------------------------------------------------------------- #
# leg 5: mixed-version peers are never renegotiated                      #
# ---------------------------------------------------------------------- #
def test_mixed_version_peer_never_renegotiated():
    eng = FakeEngine(tunable=False)      # peer without the "tn" HELLO cap
    ctl, live = make_ctl(eng, budget=1e-1, hysteresis=1)
    for w in range(10):
        ctl.on_window(slow_bw_digest(w))
        ctl.on_window(hot_link_digest(w))
    assert eng.sent == [] and eng.codecs == {}
    assert ctl.counts["codec_moves"] == 0
    assert live.annotations == []


# ---------------------------------------------------------------------- #
# leg 6: knob unset constructs nothing                                   #
# ---------------------------------------------------------------------- #
def test_tune_auto_unset_constructs_no_controller():
    ctx = parsec_tpu.Context(nb_cores=1)
    try:
        assert ctx.obs.tuner is None
        assert ctx.obs.live is None      # tune_auto is what implies it
    finally:
        ctx.fini()


def test_tune_auto_set_constructs_controller_and_gauges():
    with params.cmdline_override("tune_auto", "1"), \
            params.cmdline_override("tune_residual_budget", "1e-1"):
        ctx = parsec_tpu.Context(nb_cores=1)
        try:
            tn = ctx.obs.tuner
            assert tn is not None
            assert tn.max_rung == CODEC_LADDER.index("qint8")
            snap = ctx.sde.snapshot()
            for g in ("PARSEC::TUNE::DECISIONS", "PARSEC::TUNE::REVERTS",
                      "PARSEC::TUNE::OBJECTIVE_US"):
                assert g in snap, f"{g} gauge not registered: missing"
        finally:
            ctx.fini()


def test_wire_capture_tune_bit_identity():
    """The frame-level differential (dryrun gate leg E): toward a peer
    that never advertised "tn", a tune_auto sender's data frames are
    BIT-IDENTICAL to the knob-unset run — and the unset legs carry no
    tuning bytes at all."""
    import bench

    out = bench.bench_trace_capture_identity()
    assert out["trace_frames_captured"] > 0
    assert out["trace_unset_bit_identical"]
    assert out["tune_mixed_version_bit_identical"]


# ---------------------------------------------------------------------- #
# rx de-escalation: a codec that shows no win steps back down            #
# ---------------------------------------------------------------------- #
def test_rx_codec_without_win_steps_back_down():
    eng = FakeEngine()
    ctl, live = make_ctl(eng, budget=1e-2, hysteresis=1)
    ctl.on_window(hot_link_digest(0))
    assert eng.sent[-1][1]["codec"] == "qbf16"
    # the requested codec never moves a quantized byte: after
    # 2*hysteresis idle windows the controller walks it back
    for w in range(1, 6):
        ctl.on_window({"window": w, "links": {}, "bw": {}, "fired": ()})
    assert eng.sent[-1][1] == {"op": "codec", "codec": None}
    downs = [a for n, a in live.annotations
             if n == "tune:codec" and a["why"] == "no win"]
    assert downs and downs[-1]["codec"] == "lossless"


def test_rx_codec_with_real_win_is_kept():
    eng = FakeEngine()
    ctl, _ = make_ctl(eng, budget=1e-2, hysteresis=1)
    ctl.on_window(hot_link_digest(0))
    raw = quant = 0
    for w in range(1, 8):
        raw += 100_000
        quant += 25_000          # 4x compression: a clear win
        eng.rx_counts[1] = (raw, quant)
        ctl.on_window({"window": w, "links": {}, "bw": {}, "fired": ()})
    assert eng.sent[-1][1]["codec"] == "qbf16"   # never de-escalated
    assert len(eng.sent) == 1
