"""xfer/: transport- and topology-aware data movement (ISSUE 19).

Three pillars: the negotiated device-plane transport ladder (HELLO
``"dp"`` — comm/tcp.py + comm/xfer.py, with :mod:`.loopback` as the
everywhere-available backend), hierarchical two-level collectives for
the wave lane (dsl/ptg/wave_dist.py riding
parallel/mesh.two_level_allreduce), and the redistribution planner
(:mod:`.plan` — reshards as coalesced alltoall rounds instead of
per-tile GET storms).  Everything is gated behind the
``xfer_dplane`` / ``xfer_collective_redist`` MCA knob pair; unset, no
code here runs and the wire stays bit-for-bit identical.
"""
from .loopback import LoopbackTransferServer, start_transfer_server
from .plan import (RedistPlan, Transfer, TAG_REDIST, build_plan,
                   run_redistribution, PlannedRedistribution)

__all__ = ["LoopbackTransferServer", "start_transfer_server",
           "RedistPlan", "Transfer", "TAG_REDIST", "build_plan",
           "run_redistribution", "PlannedRedistribution"]
