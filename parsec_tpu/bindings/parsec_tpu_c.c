/* C embedding shim for the parsec_tpu runtime (see parsec_tpu_c.h).
 *
 * Thin CPython-API layer: owns the embedded interpreter, holds opaque
 * PyObject handles, and forwards every call to
 * parsec_tpu.bindings.chelper (the reference's Fortran bindings are the
 * same shape: a thin marshalling layer over the core runtime API,
 * parsec/fortran/parsecf.F90).
 */
#include <Python.h>
#include <stdio.h>
#include <string.h>

#include "parsec_tpu_c.h"

struct ptc_context { PyObject *ctx; int owns_interp; };
struct ptc_taskpool { PyObject *tp; };
struct ptc_tile { PyObject *tile; };

static char g_err[1024];
static PyObject *g_helper = NULL;

static void set_err_from_python(void) {
    PyObject *type = NULL, *value = NULL, *tb = NULL;
    PyErr_Fetch(&type, &value, &tb);
    PyErr_NormalizeException(&type, &value, &tb);
    g_err[0] = '\0';
    if (value != NULL) {
        PyObject *s = PyObject_Str(value);
        if (s != NULL) {
            const char *c = PyUnicode_AsUTF8(s);
            if (c != NULL) { strncpy(g_err, c, sizeof(g_err) - 1); }
            Py_DECREF(s);
        }
    }
    if (g_err[0] == '\0') strcpy(g_err, "unknown python error");
    Py_XDECREF(type); Py_XDECREF(value); Py_XDECREF(tb);
}

static PyObject *helper(void) {
    if (g_helper == NULL) {
        g_helper = PyImport_ImportModule("parsec_tpu.bindings.chelper");
        if (g_helper == NULL) set_err_from_python();
    }
    return g_helper;
}

const char *ptc_last_error(void) { return g_err; }

ptc_context *ptc_init(int nb_cores) {
    g_err[0] = '\0';
    int owns = 0;
    if (!Py_IsInitialized()) {
        Py_Initialize();
        /* drop the GIL acquired by Py_Initialize so runtime worker
         * threads can run task bodies while this thread is in C code;
         * every ptc_* entry point re-acquires via PyGILState_Ensure */
        (void)PyEval_SaveThread();
        owns = 1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    ptc_context *out = NULL;
    PyObject *mod = helper();
    if (mod != NULL) {
        PyObject *ctx = PyObject_CallMethod(mod, "init", "i", nb_cores);
        if (ctx == NULL) { set_err_from_python(); }
        else {
            out = (ptc_context *)malloc(sizeof(*out));
            out->ctx = ctx;
            out->owns_interp = owns;
        }
    }
    PyGILState_Release(st);
    return out;
}

void ptc_fini(ptc_context *ctx) {
    g_err[0] = '\0';
    if (ctx == NULL) return;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(helper(), "fini", "O", ctx->ctx);
    if (r == NULL) { set_err_from_python(); PyErr_Clear(); }
    Py_XDECREF(r);
    Py_DECREF(ctx->ctx);
    PyGILState_Release(st);
    /* the embedded interpreter stays up: worker threads may still be
     * parked in it, and a later ptc_init can reuse it */
    free(ctx);
}

ptc_taskpool *ptc_dtd_taskpool_new(ptc_context *ctx) {
    g_err[0] = '\0';
    if (ctx == NULL) return NULL;
    PyGILState_STATE st = PyGILState_Ensure();
    ptc_taskpool *out = NULL;
    PyObject *tp = PyObject_CallMethod(helper(), "taskpool_new", "O",
                                       ctx->ctx);
    if (tp == NULL) { set_err_from_python(); }
    else {
        out = (ptc_taskpool *)malloc(sizeof(*out));
        out->tp = tp;
    }
    PyGILState_Release(st);
    return out;
}

ptc_tile *ptc_tile_of_dense(ptc_taskpool *tp, float *data,
                            long rows, long cols) {
    g_err[0] = '\0';
    if (tp == NULL || data == NULL) return NULL;
    PyGILState_STATE st = PyGILState_Ensure();
    ptc_tile *out = NULL;
    PyObject *tile = PyObject_CallMethod(helper(), "tile_of_dense", "OKll",
                                         tp->tp, (unsigned long long)(size_t)data,
                                         rows, cols);
    if (tile == NULL) { set_err_from_python(); }
    else {
        out = (ptc_tile *)malloc(sizeof(*out));
        out->tile = tile;
    }
    PyGILState_Release(st);
    return out;
}

int ptc_insert_task(ptc_taskpool *tp, ptc_body_fn fn, void *user,
                    int ntiles, ptc_tile **tiles, const int *modes) {
    g_err[0] = '\0';
    if (tp == NULL || fn == NULL) return -1;
    PyGILState_STATE st = PyGILState_Ensure();
    int rc = -1;
    PyObject *tlist = PyList_New(ntiles);
    PyObject *mlist = PyList_New(ntiles);
    if (tlist != NULL && mlist != NULL) {
        for (int i = 0; i < ntiles; i++) {
            Py_INCREF(tiles[i]->tile);
            PyList_SET_ITEM(tlist, i, tiles[i]->tile);
            PyList_SET_ITEM(mlist, i, PyLong_FromLong(modes[i]));
        }
        PyObject *r = PyObject_CallMethod(
            helper(), "insert_task", "OKKOO", tp->tp,
            (unsigned long long)(size_t)fn,
            (unsigned long long)(size_t)user, tlist, mlist);
        if (r == NULL) { set_err_from_python(); }
        else { rc = 0; Py_DECREF(r); }
    }
    Py_XDECREF(tlist);
    Py_XDECREF(mlist);
    PyGILState_Release(st);
    return rc;
}

static int call_int_method(ptc_taskpool *tp, const char *name) {
    g_err[0] = '\0';
    if (tp == NULL) return -1;
    PyGILState_STATE st = PyGILState_Ensure();
    int rc = -1;
    PyObject *r = PyObject_CallMethod(helper(), name, "O", tp->tp);
    if (r == NULL) { set_err_from_python(); }
    else { rc = (int)PyLong_AsLong(r); Py_DECREF(r); }
    PyGILState_Release(st);
    return rc;
}

int ptc_data_flush_all(ptc_taskpool *tp) {
    return call_int_method(tp, "data_flush_all");
}

int ptc_taskpool_wait(ptc_taskpool *tp) {
    return call_int_method(tp, "taskpool_wait");
}

void ptc_taskpool_free(ptc_taskpool *tp) {
    if (tp == NULL) return;
    PyGILState_STATE st = PyGILState_Ensure();
    Py_DECREF(tp->tp);
    PyGILState_Release(st);
    free(tp);
}

void ptc_tile_free(ptc_tile *tile) {
    if (tile == NULL) return;
    PyGILState_STATE st = PyGILState_Ensure();
    Py_DECREF(tile->tile);
    PyGILState_Release(st);
    free(tile);
}

const char *ptc_version(void) {
    static char buf[64] = "";
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(helper(), "version", NULL);
    if (r != NULL) {
        const char *c = PyUnicode_AsUTF8(r);
        if (c != NULL) strncpy(buf, c, sizeof(buf) - 1);
        Py_DECREF(r);
    } else { set_err_from_python(); PyErr_Clear(); }
    PyGILState_Release(st);
    return buf;
}
