"""comm subpackage."""
