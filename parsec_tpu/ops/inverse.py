"""Matrix inverses — the DPLASMA potri-family slice (trtri, lauum,
potri, posv-based general inverse via getrf/getrs).

TPU-native design: these are MXU-shaped XLA programs, not task DAGs —
a triangular inverse is one ``triangular_solve`` against the identity
(XLA blocks it internally), and lauum/potri are single large GEMMs with
true-f32 input precision (factor chains compound the MXU's default
bf16-input error; see ops/dgetrf.py). Each shape compiles once
(lru-cached jit), like a captured taskpool.
"""
from __future__ import annotations

import functools

import numpy as np

__all__ = ["dtrtri", "dlauum", "dpotri", "dgetrs", "dgesv"]


@functools.lru_cache(maxsize=64)
def _jit_trtri(n: int, lower: bool, unit: bool, dtype_name: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def f(T):
        eye = jnp.eye(n, dtype=T.dtype)
        return lax.linalg.triangular_solve(
            T, eye, left_side=True, lower=lower, unit_diagonal=unit)
    return jax.jit(f)


def dtrtri(T, lower: bool = True, unit_diagonal: bool = False):
    """Inverse of a triangular matrix (ref algorithm: DPLASMA ztrtri)."""
    n = T.shape[0]
    return _jit_trtri(n, lower, unit_diagonal, np.dtype(T.dtype).name)(T)


@functools.lru_cache(maxsize=64)
def _jit_lauum(n: int, lower: bool, dtype_name: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def f(T):
        Tt = jnp.tril(T) if lower else jnp.triu(T)
        # accumulate in f32 for <=32-bit inputs; f64 inputs keep f64
        # accumulation (f32 would silently launder away 9 digits)
        acc = jnp.promote_types(T.dtype, jnp.float32)
        a, b = (Tt.T, Tt) if lower else (Tt, Tt.T)
        prod = jnp.matmul(a, b, precision=lax.Precision.HIGHEST,
                          preferred_element_type=acc)
        return prod.astype(T.dtype)
    return jax.jit(f)


def dlauum(T, lower: bool = True):
    """L^T L (lower) / U U^T (upper) — the lauum kernel of potri."""
    return _jit_lauum(T.shape[0], lower, np.dtype(T.dtype).name)(T)


def dpotri(L, lower: bool = True):
    """SPD inverse from the Cholesky factor: A^{-1} = L^{-T} L^{-1}
    (ref: DPLASMA zpotri = ztrtri + zlauum). ``L`` is dpotrf's output
    (lower triangle holds the factor)."""
    Linv = dtrtri(L, lower=lower)
    return dlauum(Linv, lower=lower)


@functools.lru_cache(maxsize=64)
def _jit_getrs(shape, dtype_name: str):
    import jax
    from jax import lax

    def f(LU, piv, B):
        Bp = B[piv]
        Y = lax.linalg.triangular_solve(LU, Bp, left_side=True, lower=True,
                                        unit_diagonal=True)
        return lax.linalg.triangular_solve(LU, Y, left_side=True,
                                           lower=False)
    return jax.jit(f)


def dgetrs(LU, piv, B):
    """Solve A X = B from dgetrf's packed factors + pivot vector."""
    if LU.shape[0] != LU.shape[1]:
        raise ValueError(
            f"dgetrs needs square packed factors, got {LU.shape} "
            f"(rectangular dgetrf output has no solve)")
    return _jit_getrs((LU.shape, B.shape), np.dtype(B.dtype).name)(
        LU, piv, B)


def dgesv(A, B, nb: int = 256):
    """General solve A X = B: pivoted LU + two triangular solves
    (ref: DPLASMA zgesv)."""
    from .dgetrf import dgetrf
    LU, piv = dgetrf(A, nb=nb)
    return dgetrs(LU, piv, B)
