"""Wave execution: run a lowered PTG taskpool as batched XLA calls.

The per-task runtime pays one Python/jax dispatch per task (~0.3 ms),
which bounds throughput at small tile sizes no matter how fast the chip
is; whole-DAG capture (capture.py) removes the host loop entirely but
unrolls every instance into one trace, which stops scaling around 10^4
tasks. Wave execution is the TPU-native midpoint, with no direct
reference analog (the reference amortizes dispatch with a ~us C loop,
parsec/scheduling.c:586-625; on TPU the idiomatic fix is batching onto
the MXU, not a faster scalar loop):

- the lowered DAG (lower.py) tracks readiness in dense native counters;
- every collection lives on device as ONE stacked tile pool
  ``[n_tiles, mb, nb]``;
- each ready antichain ("wave") is grouped by task class and executed as
  a few fixed-size chunked calls of a jitted, vmapped body kernel that
  gathers input tiles from the pools by index, runs the batched tile op
  on the MXU, and scatters written tiles back in place (donated buffers
  — no pool copies);
- dispatch cost is per *chunk* (~bounded by classes x log2(wave size)),
  not per task, and compiled programs are reused across waves and runs
  (at most ``1 + log2(max_chunk)`` sizes per class).

Semantics notes:
- priorities are ignored: execution is breadth-first by dependence
  level, which is exactly the dataflow order XLA would want anyway;
- a wave may contain a reader of a tile and the (dataflow-independent)
  writer of the same tile (WAR); readers are split into an earlier
  sub-wave in that case, so in-place scatters never clobber a
  same-wave read;
- supported flows are those whose values live in collection tiles
  (memory-sourced or forwarded from task to task). NEW scratch flows or
  writebacks to a different tile than the flow's slot raise WaveError —
  those run through the per-task runtime instead.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...utils import logging as plog
from .ast import Expr
from .lower import LoweredDAG, lower, make_engine
from .runtime import PTGTaskpool, _expand_args

__all__ = ["WaveError", "WaveRunner", "wave"]


class WaveError(RuntimeError):
    pass


def _pick_body(tc_ast):
    for b in tc_ast.bodies:
        if b.device_type not in ("cpu", "recursive"):
            return b
    return tc_ast.bodies[0]


class _ClassPlan:
    """Per-task-class kernel metadata: which flows carry data, where
    their slots live, and the compiled chunked kernels."""

    __slots__ = ("tc", "ast", "flow_idx", "flow_names", "flow_coll",
                 "written", "reads", "range_locals", "body_locals", "code",
                 "kernels")

    def __init__(self, tc) -> None:
        self.tc = tc
        self.ast = tc.ast
        self.flow_idx = [i for i, f in enumerate(tc.ast.flows)
                         if not f.is_ctl]
        self.flow_names = [tc.ast.flows[i].name for i in self.flow_idx]
        from ...data.data import FlowAccess
        self.flow_coll: List[int] = [-1] * len(self.flow_idx)
        self.written = [bool(tc.flows[i].access & FlowAccess.WRITE)
                        for i in self.flow_idx]
        # a flow with in-deps reads its slot's current value (RW reads
        # then writes; WRITE-only flows have no in-deps and may clobber)
        self.reads = [bool(tc.ast.flows[i].deps_in()) for i in self.flow_idx]
        self.range_locals = [ld.name for ld in tc.ast.locals
                             if ld.range is not None]
        self.code = compile(_pick_body(tc.ast).code,
                            f"<jdf:{tc.ast.name}:BODY[wave]>", "exec")
        # range locals the body references (co_names: exec reads them as
        # globals): bodies may branch on them in Python (`BETA if k == 0
        # else 1.0`), which a batch tracer cannot do — such locals are
        # made STATIC by sub-chunking the wave on their values
        names = set(self.code.co_names)
        self.body_locals = [i for i, nm in enumerate(self.range_locals)
                            if nm in names]
        self.kernels: Dict[Tuple, Any] = {}


class WaveRunner:
    """Executor for one single-rank PTG taskpool in wave mode."""

    _multirank = False   # DistWaveRunner (wave_dist.py) overrides

    def __init__(self, tp: PTGTaskpool, max_chunk: int = 256) -> None:
        if tp.nb_ranks != 1 and not self._multirank:
            raise WaveError("single-rank wave on a multi-rank taskpool; "
                            "use wave(tp, comm=...) / DistWaveRunner")
        self.tp = tp
        self.max_chunk = max(1, int(max_chunk))
        self.dag: LoweredDAG = lower(tp, allow_multirank=self._multirank)
        from ...collections.collection import DataCollection
        self.collections: Dict[str, Any] = {
            name: c for name, c in tp.global_env.items()
            if isinstance(c, DataCollection)}
        if not self.collections:
            raise WaveError("taskpool binds no data collections")
        self.coll_names = sorted(self.collections)
        self._coll_id = {n: i for i, n in enumerate(self.coll_names)}
        self._tile_index: List[Dict[Tuple, int]] = []
        for n in self.coll_names:
            coll = self.collections[n]
            coords = sorted(coll.tiles())
            self._tile_index.append({c: i for i, c in enumerate(coords)})
            # shape uniformity (pools are stacked arrays) is enforced by
            # np.stack in build_pools; ragged tilings raise there
        self.plans = [_ClassPlan(tc) for tc in tp.task_classes]
        # reshape property semantics ([type]/[type_data] conversions,
        # region-masked writeback) live in the per-task runtime; pools
        # scatter whole tiles, so accepting such JDFs would silently
        # clobber out-of-region values. type_remote alone is fine: wave
        # is single-rank and type_remote is wire-only (a no-op here).
        for tc in tp.task_classes:
            for f in tc.ast.flows:
                for d in f.deps:
                    for key in ("type", "type_data"):
                        nm = d.properties.get(key)
                        if nm is not None and nm != "full":
                            raise WaveError(
                                f"{tc.ast.name}.{f.name}: [{key}={nm}] "
                                f"reshape semantics need the per-task "
                                f"runtime; wave pools scatter whole tiles")
        # slot tables: per task, per (non-ctl) flow position in the
        # class's flow_idx list -> flat tile index (collection fixed per
        # class/flow, validated during assignment)
        self._assign_slots()

    # ------------------------------------------------------------------ #
    # slot assignment                                                    #
    # ------------------------------------------------------------------ #
    def _assign_slots(self) -> None:
        dag = self.dag
        n = dag.n_tasks
        max_df = max((len(p.flow_idx) for p in self.plans), default=0)
        slot = np.full((n, max_df), -1, np.int32)
        # topo order via Kahn over the lowered CSR
        indeg = dag.indegree.copy()
        head = 0
        order = [int(t) for t in np.nonzero(indeg == 0)[0]]
        while head < len(order):
            t = order[head]
            head += 1
            for e in range(int(dag.indptr[t]), int(dag.indptr[t + 1])):
                s = int(dag.succ[e])
                indeg[s] -= 1
                if indeg[s] == 0:
                    order.append(s)
        if len(order) != n:
            raise WaveError("cycle in lowered DAG")

        flow_pos = []  # per class: ast flow index -> dense position
        for p in self.plans:
            pos = {fi: k for k, fi in enumerate(p.flow_idx)}
            flow_pos.append(pos)

        for t in order:
            ci = int(dag.class_of[t])
            p = self.plans[ci]
            tc = p.tc
            env = tc.env_of(dag.locals_of[t])
            for k, fi in enumerate(p.flow_idx):
                f = tc.ast.flows[fi]
                s = self._slot_of_flow(t, f, env, flow_pos, slot)
                if s is None:
                    raise WaveError(
                        f"{p.ast.name}{dag.locals_of[t]}.{f.name}: flow "
                        f"does not resolve to a collection tile (NEW/NULL "
                        f"flows need the per-task runtime)")
                coll_id, idx = s
                if p.flow_coll[k] == -1:
                    p.flow_coll[k] = coll_id
                elif p.flow_coll[k] != coll_id:
                    raise WaveError(
                        f"{p.ast.name}.{f.name}: instances bind tiles from "
                        f"different collections; wave batching needs one")
                slot[t, k] = idx
                if p.written[k]:
                    self._check_writeback(p, f, env, coll_id, idx)
        self._slot = slot
        # only collections the DAG actually touches are staged; only
        # written ones are scattered back (D2H can be ~4 MB/s — a full
        # gather of an untouched pool costs minutes)
        self._used_colls = {cid for p in self.plans
                            for cid in p.flow_coll if cid >= 0}
        self._written_colls = {p.flow_coll[k] for p in self.plans
                               for k in range(len(p.flow_idx))
                               if p.written[k] and p.flow_coll[k] >= 0}

    def _slot_of_flow(self, tid, f, env, flow_pos, slot):
        deps_in = f.deps_in()
        for d in deps_in:
            t = d.resolve(env)
            if t is None:
                continue
            if t.kind == "memory":
                coll_id = self._coll_id.get(t.collection)
                if coll_id is None:
                    return None
                coords = tuple(int(a(env)) for a in t.args)
                return coll_id, self._tile_lookup(coll_id, coords)
            if t.kind == "task":
                for args in _expand_args(t.args, env):
                    past = self.tp.jdf.task_class_by_name(t.task_class)
                    pkey = (t.task_class, past.locals_from_param_args(args))
                    pid = self.dag.id_of.get(pkey)
                    if pid is None:
                        continue  # out-of-space producer: inapplicable
                    pci = int(self.dag.class_of[pid])
                    pplan = self.plans[pci]
                    pfi = next(i for i, pf in enumerate(pplan.ast.flows)
                               if pf.name == t.flow)
                    k = flow_pos[pci].get(pfi)
                    if k is None:
                        return None
                    idx = int(slot[pid, k])
                    if idx < 0:
                        return None
                    return pplan.flow_coll[k], idx
                continue
            return None  # new / null
        if not deps_in:
            # WRITE-only flow: bind to its memory out-target
            for d in f.deps_out():
                t = d.resolve(env)
                if t is not None and t.kind == "memory":
                    coll_id = self._coll_id.get(t.collection)
                    if coll_id is None:
                        return None
                    coords = tuple(int(a(env)) for a in t.args)
                    return coll_id, self._tile_lookup(coll_id, coords)
        return None

    def _tile_lookup(self, coll_id: int, coords: Tuple[int, ...]) -> int:
        """Map dep-target args to the flat tile index; vector-style
        1-arg targets pad a trailing 0 (data_of(m) == data_of(m, 0))."""
        idx = self._tile_index[coll_id]
        hit = idx.get(coords)
        while hit is None and len(coords) < 2:
            coords = coords + (0,)
            hit = idx.get(coords)
        if hit is None:
            raise WaveError(f"no tile {coords} in collection "
                            f"{self.coll_names[coll_id]}")
        return hit

    def _check_writeback(self, p, f, env, coll_id, idx) -> None:
        for d in f.deps_out():
            t = d.resolve(env)
            if t is None or t.kind != "memory":
                continue
            tc_id = self._coll_id.get(t.collection)
            if tc_id is None:
                raise WaveError(
                    f"{p.ast.name}.{f.name}: writes back to unbound "
                    f"collection {t.collection!r}")
            coords = tuple(int(a(env)) for a in t.args)
            if tc_id != coll_id or self._tile_lookup(tc_id, coords) != idx:
                raise WaveError(
                    f"{p.ast.name}.{f.name}: writes back to a different "
                    f"tile than its slot; unsupported in wave mode")

    # ------------------------------------------------------------------ #
    # kernels                                                            #
    # ------------------------------------------------------------------ #
    def _kernel(self, ci: int, k: int, statics: Tuple = ()):
        """The jitted chunk kernel for class ``ci``, chunk size ``k`` and
        static body-local values ``statics``:
        fn(pools_tuple, locals_i32[k, n_locals], idx_i32[n_flows, k])
        -> pools_tuple with written slots scattered in place."""
        p = self.plans[ci]
        kern = p.kernels.get((k, statics))
        if kern is not None:
            return kern
        import jax
        import jax.numpy as jnp

        global_env = self.tp.global_env
        flow_names = p.flow_names
        written = p.written
        flow_coll = p.flow_coll
        range_locals = p.range_locals
        derived = [(ld.name, ld.expr) for ld in p.ast.locals
                   if ld.range is None]
        code = p.code

        static_pairs = [(range_locals[i], v)
                        for i, v in zip(p.body_locals, statics)]

        def one(loc_row, *flow_vals):
            env = dict(global_env)
            for nm, v in zip(range_locals, loc_row):
                env[nm] = v
            for nm, v in static_pairs:  # concrete: bodies may branch
                env[nm] = v
            for nm, ex in derived:
                env[nm] = ex(env)
            for nm, v in zip(flow_names, flow_vals):
                env[nm] = v
            env["np"] = np
            env["jnp"] = jnp
            env["es_rank"] = 0
            env["this_task"] = None
            exec(code, env)
            return tuple(env[nm] for nm, w in zip(flow_names, written) if w)

        def chunk_fn(pools, locs, idx):
            gathered = [pools[flow_coll[j]][idx[j]]
                        for j in range(len(flow_names))]
            outs = jax.vmap(one)(locs, *gathered)
            pools = list(pools)
            oi = 0
            for j, w in enumerate(written):
                if not w:
                    continue
                cid = flow_coll[j]
                pools[cid] = pools[cid].at[idx[j]].set(outs[oi])
                oi += 1
            return tuple(pools)

        kern = jax.jit(chunk_fn, donate_argnums=(0,))
        p.kernels[(k, statics)] = kern
        return kern

    @staticmethod
    def _chunks(k: int, max_chunk: int) -> List[int]:
        """Binary decomposition of k bounded by max_chunk: exact sizes
        from a fixed set, so compiled programs are reused."""
        out = []
        while k >= max_chunk:
            out.append(max_chunk)
            k -= max_chunk
        b = 1
        while k:
            if k & 1:
                out.append(b)
            k >>= 1
            b <<= 1
        return out

    # ------------------------------------------------------------------ #
    # execution                                                          #
    # ------------------------------------------------------------------ #
    def _execute_frontier(self, ids: np.ndarray, classes: np.ndarray,
                          pools: Tuple) -> Tuple[Tuple, int]:
        """Execute one ready antichain (or the local slice of one) as
        batched per-class chunk kernels; returns (pools, n_calls)."""
        dag = self.dag
        slot = self._slot
        n_calls = 0
        for sub in self._split_war(ids, classes):
            sids, cls = sub
            for ci in np.unique(cls):
                members = sids[cls == ci]
                p = self.plans[int(ci)]
                nf = len(p.flow_idx)
                # (no priority ordering: a wave is an antichain and
                # every member executes before the next readiness
                # update — order has no observable effect)
                # body-referenced locals become static kernel args:
                # group members by their values (uniform per wave in
                # the common panel-structured DAGs)
                groups: Dict[Tuple, List[int]] = {}
                for t in members:
                    sv = tuple(int(dag.locals_of[t][i])
                               for i in p.body_locals)
                    groups.setdefault(sv, []).append(int(t))
                for statics, g in groups.items():
                    garr = np.asarray(g, np.int64)
                    off = 0
                    for k in self._chunks(len(garr), self.max_chunk):
                        chunk = garr[off:off + k]
                        off += k
                        lrows = [dag.locals_of[t] for t in chunk]
                        nl = len(lrows[0])
                        locs = (np.asarray(lrows, np.int32)
                                .reshape(k, nl)
                                if nl else np.zeros((k, 0), np.int32))
                        idx = slot[chunk, :nf].T.copy()  # [n_flows, k]
                        try:
                            pools = self._kernel(int(ci), k, statics)(
                                pools, locs, idx)
                        except Exception as exc:
                            if "Tracer" in type(exc).__name__ or \
                                    "Concretization" in type(exc).__name__:
                                raise WaveError(
                                    f"{p.ast.name}: body cannot be "
                                    f"batch-traced (it branches on a "
                                    f"derived local or data value in "
                                    f"Python); run this taskpool "
                                    f"through the per-task runtime"
                                ) from exc
                            raise
                        n_calls += 1
        return pools, n_calls

    def execute(self, pools: Tuple) -> Tuple:
        """Run the DAG over device tile pools (one stacked array per
        collection, ordered by self.coll_names); returns final pools."""
        dag = self.dag
        eng = make_engine(dag)
        ready = np.asarray(eng.start(), np.int32)
        n_waves = n_calls = 0
        while ready.size:
            n_waves += 1
            pools, nc = self._execute_frontier(ready, dag.class_of[ready],
                                               pools)
            n_calls += nc
            ready = np.asarray(eng.complete_batch(ready), np.int32)
        done = eng.completed() if hasattr(eng, "completed") else dag.n_tasks
        if int(done) != dag.n_tasks:
            raise WaveError(
                f"wave execution stalled: {done}/{dag.n_tasks} tasks ran")
        plog.debug.verbose(3, "wave %s: %d tasks in %d waves, %d kernel "
                           "calls", self.tp.name, dag.n_tasks, n_waves,
                           n_calls)
        return pools

    def _split_war(self, ids: np.ndarray, classes: np.ndarray):
        """Split a frontier so no in-place scatter clobbers a same-wave
        read. Anti-dependence edges (reader R of a tile that a different
        frontier task W writes: R must run before W) are layered with
        Kahn's algorithm; each layer is anti-dep-free and executes as one
        batched sub-wave. A cyclic frontier (two tasks each reading the
        tile the other writes — legal dataflow, but unservable by
        in-place scatters) raises WaveError: run it through the per-task
        runtime, whose copies rename WAR hazards away."""
        slot = self._slot
        reads: Dict[Tuple[int, int], List[int]] = {}
        writes: Dict[Tuple[int, int], int] = {}
        for pos, t in enumerate(ids):
            p = self.plans[int(classes[pos])]
            for k in range(len(p.flow_idx)):
                key = (p.flow_coll[k], int(slot[t, k]))
                if p.written[k]:
                    prev = writes.get(key)
                    if prev is not None and prev != int(t):
                        raise WaveError(
                            f"frontier holds two writers of the same "
                            f"tile (tasks {prev} and {int(t)}): the DAG "
                            f"races — in-place scatters would keep an "
                            f"arbitrary one")
                    writes[key] = int(t)
                else:
                    reads.setdefault(key, []).append(int(t))
        out_edges: Dict[int, List[int]] = {}
        indeg: Dict[int, int] = {int(t): 0 for t in ids}
        n_conf = 0
        for key, ts in reads.items():
            w = writes.get(key)
            if w is None:
                continue
            for r in ts:
                if r == w:
                    continue
                out_edges.setdefault(r, []).append(w)
                indeg[w] += 1
                n_conf += 1
        if n_conf == 0:
            return [(ids, classes)]
        cls_of = {int(t): int(c) for t, c in zip(ids, classes)}
        layer = [t for t in indeg if indeg[t] == 0]
        done = 0
        layers = []
        while layer:
            layers.append(layer)
            done += len(layer)
            nxt: List[int] = []
            for t in layer:
                for w in out_edges.get(t, ()):
                    indeg[w] -= 1
                    if indeg[w] == 0:
                        nxt.append(w)
            layer = nxt
        if done != len(ids):
            raise WaveError(
                "frontier has cyclic write-after-read conflicts; this DAG "
                "needs the per-task runtime (copies rename WAR hazards)")
        return [(np.asarray(ls, np.int64),
                 np.asarray([cls_of[t] for t in ls], np.int32))
                for ls in layers]

    # ------------------------------------------------------------------ #
    # convenience: run against the bound collections                     #
    # ------------------------------------------------------------------ #
    def build_pools(self, device=None, sharding=None) -> Tuple:
        """Stage each collection as one stacked [n_tiles, mb, nb] device
        array. ``sharding`` (a jax.sharding.Sharding over the tile dims,
        e.g. NamedSharding(mesh, P(None, "tp", "sp"))) runs every wave
        kernel SPMD over the mesh — GSPMD partitions the batched tile
        ops and inserts the collectives (the scaling-book recipe); right
        for large NB where one tile's FLOPs span several chips."""
        import jax
        import jax.numpy as jnp
        pools = []
        for cid, name in enumerate(self.coll_names):
            if cid not in self._used_colls:
                pools.append(jnp.zeros((0,), np.float32))  # placeholder
                continue
            coll = self.collections[name]
            coords = sorted(coll.tiles())
            tiles = []
            for c in coords:
                data = coll.data_of(*c)
                tiles.append(np.asarray(data.sync_to_host().payload))
            stacked = np.stack(tiles)
            if sharding is not None:
                arr = jax.device_put(stacked, sharding)
            elif device is not None:
                arr = jax.device_put(stacked, device)
            else:
                arr = jnp.asarray(stacked)
            pools.append(arr)
        return tuple(pools)

    def scatter_pools(self, pools: Tuple) -> None:
        for cid, name in enumerate(self.coll_names):
            if cid not in self._written_colls:
                continue  # no task wrote this pool: home copies stand
            coll = self.collections[name]
            coords = sorted(coll.tiles())
            host = np.asarray(pools[cid])
            for i, c in enumerate(coords):
                data = coll.data_of(*c)
                hc = data.host_copy()
                if hc.payload is None:
                    hc.payload = host[i].copy()
                else:
                    np.copyto(hc.payload, host[i])
                data.version_bump(0)

    def run(self, device=None) -> None:
        pools = self.execute(self.build_pools(device))
        self.scatter_pools(pools)

    @property
    def nb_tasks(self) -> int:
        return self.dag.n_tasks


def wave(tp: PTGTaskpool, max_chunk: int = 256, comm=None) -> WaveRunner:
    """Build a wave-mode executor. Single-rank taskpools get the local
    WaveRunner; multi-rank taskpools (or an explicit ``comm``) get the
    distributed runner (wave_dist.py), which partitions the DAG by the
    data distribution and exchanges tiles between waves."""
    if tp.nb_ranks != 1 or comm is not None:
        from .wave_dist import DistWaveRunner
        return DistWaveRunner(tp, max_chunk=max_chunk, comm=comm)
    return WaveRunner(tp, max_chunk=max_chunk)
