#!/usr/bin/env python
"""Fuse N per-rank Chrome traces into ONE offset-corrected timeline.

Each rank's Profile export sits on its own monotonic clock (and its own
``t0`` normalization). This tool re-bases every document onto the
reference rank's clock using the ``trace_t0_ns`` + ``clock_offsets_us``
metadata the context stamps at export (the ping/pong midpoint estimates
of ``obs_flow`` mode, comm/tcp.py; in-process fabrics are same-clock)
and concatenates the events into one JSON — rank rows stay distinct
(pid = rank) and flow pairs (``ph:"s"``/``"f"``, same id) become arrows
CROSSING rank rows when loaded in Perfetto::

    python my_app.py --mca profile /tmp/run --mca obs_flow 1
    python tools/obs_trace_merge.py /tmp/run.rank*.trace.json \\
        -o /tmp/run.merged.json

The merged file feeds straight into ``tools/obs_report.py`` (whose
cross-rank section also accepts the UNmerged per-rank files — analyze()
applies the same alignment internally).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.obs import (merge_trace_docs, load_flow_events,  # noqa: E402
                            stitch_flows, validate_chrome_trace)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+",
                    help="per-rank Chrome-trace JSON files")
    ap.add_argument("-o", "--output", default=None,
                    help="merged output path (default: "
                         "<first input's prefix>.merged.json)")
    ap.add_argument("--tenant", default=None, metavar="NAME",
                    help="keep only the flow halves a serve/ "
                         "SessionServer attributed to tenant NAME "
                         "(spans and counters are kept; other tenants' "
                         "arrows are dropped from the merged timeline)")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero when any stitched cross-rank "
                         "edge has a NEGATIVE offset-corrected lag "
                         "(recv before send = bad clock alignment) or "
                         "when flow halves are left unmatched")
    args = ap.parse_args(argv)

    docs = []
    for path in args.traces:
        with open(path) as fh:
            docs.append(json.load(fh))
    merged = merge_trace_docs(docs)
    if args.tenant is not None:
        # flow halves of OTHER tenants go; untagged halves (runtime
        # traffic a server never owned) go too — what remains is one
        # customer's arrows over the shared fleet's span rows
        def _keep(e):
            if e.get("ph") not in ("s", "f"):
                return True
            a = e.get("args")
            return isinstance(a, dict) and a.get("tenant") == args.tenant
        merged["traceEvents"] = [e for e in merged["traceEvents"]
                                 if _keep(e)]
    edges, unmatched = stitch_flows(load_flow_events(merged))
    cross = [e for e in edges if e["src"] != e["dst"]]
    neg = [e for e in cross if e["lag_us"] < 0]

    out = args.output
    if out is None:
        base = args.traces[0]
        for suffix in (".trace.json", ".json"):
            if base.endswith(suffix):
                base = base[:-len(suffix)]
                break
        out = base + ".merged.json"
    # write FIRST, validate after: forensics flight-records (dumped
    # mid-abort, ISSUE 15) legitimately hold in-flight B-without-E
    # spans — Perfetto tolerates them, and a post-mortem merge must
    # never be lost to its own schema check
    with open(out, "w") as fh:
        json.dump(merged, fh)
    try:
        n_events = validate_chrome_trace(merged)["events"]
    except ValueError as exc:
        n_events = len(merged["traceEvents"])
        print(f"note: merged trace has schema irregularities ({exc}) — "
              f"expected for mid-abort flight records", file=sys.stderr)
    ranks = merged["metadata"]["merged_ranks"]
    lags = sorted(e["lag_us"] for e in cross)
    print(f"merged {len(docs)} trace(s) (ranks {ranks}) -> {out}: "
          f"{n_events} events, {len(cross)} cross-rank flow "
          f"edge(s) ({unmatched} unmatched half/halves)"
          + (f", lag min/median/max = {lags[0]:.0f}/"
             f"{lags[len(lags) // 2]:.0f}/{lags[-1]:.0f} us"
             if lags else ""))
    by_tenant = {}
    for e in cross:
        if "tenant" in e:
            by_tenant[e["tenant"]] = by_tenant.get(e["tenant"], 0) + 1
    if by_tenant:
        print("tenant-attributed edges: "
              + ", ".join(f"{t}={n}"
                          for t, n in sorted(by_tenant.items())))
    if args.strict and (neg or unmatched):
        print(f"STRICT: {len(neg)} negative-lag edge(s), {unmatched} "
              f"unmatched flow half/halves", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
