"""PINS — Performance INStrumentation callback framework.

Reference behavior: typed callback sites compiled into the hot path
(SELECT / PREPARE_INPUT / RELEASE_DEPS / EXEC / COMPLETE_EXEC / SCHEDULE
begin/end pairs), with pluggable modules subscribing per event type
(ref: parsec/mca/pins/pins.h:27-52, invoked as PARSEC_PINS(es, EXEC_BEGIN, task)
from parsec/scheduling.c:152,182,447-456). Modules in-tree: task_profiler,
papi, alperf, print_steals, iterators_checker, ptg_to_dtd.

Here the sites are function-call hooks that are near-free when no module is
registered (a module-count fast path).
"""
from __future__ import annotations

import threading
from enum import IntEnum
from typing import Any, Callable, Dict, List


class PinsEvent(IntEnum):
    SELECT_BEGIN = 0
    SELECT_END = 1
    PREPARE_INPUT_BEGIN = 2
    PREPARE_INPUT_END = 3
    RELEASE_DEPS_BEGIN = 4
    RELEASE_DEPS_END = 5
    DATA_FLUSH_BEGIN = 6
    DATA_FLUSH_END = 7
    EXEC_BEGIN = 8
    EXEC_END = 9
    COMPLETE_EXEC_BEGIN = 10
    COMPLETE_EXEC_END = 11
    SCHEDULE_BEGIN = 12
    SCHEDULE_END = 13


_N_EVENTS = len(PinsEvent)
_subscribers: List[List[Callable]] = [[] for _ in range(_N_EVENTS)]
_active = 0
_lock = threading.Lock()


def PINS(es: Any, event: PinsEvent, payload: Any) -> None:
    """The instrumentation site; inlined fast path when inactive."""
    if _active == 0:
        return
    for cb in _subscribers[event]:
        cb(es, event, payload)


def pins_is_active() -> bool:
    return _active > 0


class PinsModule:
    """Base class for PINS modules; override ``events`` + ``callback``."""

    name = "base"
    events: List[PinsEvent] = []

    def enable(self) -> None:
        global _active
        with _lock:
            for ev in self.events:
                _subscribers[ev].append(self.callback)
                _active_incr()

    def disable(self) -> None:
        with _lock:
            for ev in self.events:
                try:
                    _subscribers[ev].remove(self.callback)
                except ValueError:
                    continue
                _active_decr()

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        raise NotImplementedError


def _active_incr() -> None:
    global _active
    _active += 1


def _active_decr() -> None:
    global _active
    _active -= 1


class TaskProfilerModule(PinsModule):
    """Turns EXEC/SELECT/COMPLETE PINS events into trace events
    (ref: pins/task_profiler)."""

    name = "task_profiler"
    events = [PinsEvent.EXEC_BEGIN, PinsEvent.EXEC_END,
              PinsEvent.PREPARE_INPUT_BEGIN, PinsEvent.PREPARE_INPUT_END,
              PinsEvent.COMPLETE_EXEC_BEGIN, PinsEvent.COMPLETE_EXEC_END]

    def __init__(self, profile, context: Any = None) -> None:
        self.profile = profile  # profiling.trace.Profile
        # PINS sites are process-global but profiles are per-rank: with
        # several in-process SPMD contexts, a context-bound module must
        # ignore the other ranks' events or every profile records every
        # rank's tasks (interleaved B/E pairs corrupt the durations)
        self.context = context
        # optional latency sink (an obs.metrics.ExecTimer): with metrics
        # on, the exec duration feeds the histogram from THIS module's
        # existing hook instead of a second PINS callback per task
        self.exec_timer: Any = None

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        if self.context is not None and es.context is not self.context:
            return
        stream = self.profile.thread_stream(es)
        name = payload.task_class.name if payload is not None and hasattr(payload, "task_class") else "runtime"
        if event in (PinsEvent.EXEC_BEGIN,):
            if self.exec_timer is not None:
                self.exec_timer.begin(es.th_id)
            info = {"task": payload.snprintf()} if payload is not None else None
            # a task class may pin extra span context (stagec/runtime:
            # a compiled stage's member list + the wire trace contexts
            # that fed it, so the merged timeline can attribute the
            # fused span to its cross-rank inputs)
            extra = getattr(payload.task_class, "trace_info", None) \
                if payload is not None else None
            if extra:
                info = {**(info or {}), **extra}
            stream.begin("exec:" + name, info=info)
        elif event in (PinsEvent.EXEC_END,):
            stream.end("exec:" + name)
            if self.exec_timer is not None:
                self.exec_timer.end(es.th_id)
        elif event == PinsEvent.PREPARE_INPUT_BEGIN:
            stream.begin("prep:" + name)
        elif event == PinsEvent.PREPARE_INPUT_END:
            stream.end("prep:" + name)
        elif event == PinsEvent.COMPLETE_EXEC_BEGIN:
            stream.begin("complete:" + name)
        elif event == PinsEvent.COMPLETE_EXEC_END:
            stream.end("complete:" + name)


class PrintStealsModule(PinsModule):
    """Counts scheduler selects per thread (ref: pins/print_steals)."""

    name = "print_steals"
    events = [PinsEvent.SELECT_END]

    def __init__(self) -> None:
        self.counts: Dict[int, int] = {}

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        if payload is not None:
            self.counts[es.th_id] = self.counts.get(es.th_id, 0) + 1


class AlperfModule(PinsModule):
    """Algorithmic performance counters: tasks enabled/retired per class
    (ref: pins/alperf)."""

    name = "alperf"
    events = [PinsEvent.COMPLETE_EXEC_END, PinsEvent.SCHEDULE_END]

    def __init__(self) -> None:
        self.retired: Dict[str, int] = {}
        self.enabled: Dict[str, int] = {}
        self._lock = threading.Lock()

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        with self._lock:
            if event == PinsEvent.COMPLETE_EXEC_END and payload is not None:
                k = payload.task_class.name
                self.retired[k] = self.retired.get(k, 0) + 1
            elif event == PinsEvent.SCHEDULE_END and payload:
                for t in payload:
                    k = t.task_class.name
                    self.enabled[k] = self.enabled.get(k, 0) + 1


class IteratorsCheckerModule(PinsModule):
    """Race/correctness checker: re-runs a PTG task's iterate_successors at
    release time and validates (a) every successor instance lies inside its
    class's iteration space and (b) the successor's input dep on that flow
    resolves back to this producer instance (ref: pins/iterators_checker —
    "validates iterate_successors consistency", SURVEY.md §5.1)."""

    name = "iterators_checker"
    events = [PinsEvent.RELEASE_DEPS_BEGIN]

    def __init__(self) -> None:
        self.errors: List[str] = []
        self.checked = 0

    def callback(self, es: Any, event: PinsEvent, task: Any) -> None:
        tc = task.task_class
        if not hasattr(tc, "ast") or not hasattr(tc, "_iterate_successors"):
            return  # PTG-only checker, like the reference
        self.checked += 1

        def check(succ_tc, succ_locals, flow_name, copy, out_idx,
                  edge_types=None):
            # (a) successor locals within its iteration-space ranges
            env = dict(succ_tc.tp.global_env)
            it = iter(succ_locals)
            for ld in succ_tc.ast.locals:
                if ld.range is not None:
                    v = next(it)
                    if v not in ld.range.values(env):
                        self.errors.append(
                            f"{task.snprintf()} -> {succ_tc.name}{succ_locals}"
                            f": local {ld.name}={v} outside its range")
                    env[ld.name] = v
                else:
                    env[ld.name] = ld.expr(env)
            # (b) reciprocal input dep resolves back to the producer
            fl = succ_tc.ast.flow_by_name(flow_name)
            for d in fl.deps_in():
                t = d.resolve(env)
                if t is None or t.kind != "task":
                    continue
                if t.task_class == tc.name:
                    # dep-target args follow the producer's PARAM order;
                    # task.locals is declaration order — translate
                    args = tc.ast.locals_from_param_args(
                        tuple(a(env) for a in t.args))
                    if args == tuple(task.locals):
                        return
            self.errors.append(
                f"{succ_tc.name}{succ_locals}.{flow_name}: no input dep "
                f"resolving back to producer {task.snprintf()}")

        tc._iterate_successors(es, task, check)


class TaskTimeModule(PinsModule):
    """Per-task-class wall + thread-CPU time accumulation — the software
    stand-in for the reference's papi PINS module (hardware counters per
    event, ref: pins/papi; no PMU access from userspace here, so the
    counters are clock-based)."""

    name = "task_time"
    events = [PinsEvent.EXEC_BEGIN, PinsEvent.EXEC_END]

    def __init__(self) -> None:
        import time
        self._time = time
        self._open: Dict[int, tuple] = {}
        self.wall_ns: Dict[str, int] = {}
        self.cpu_ns: Dict[str, int] = {}
        self.count: Dict[str, int] = {}
        self._lock = threading.Lock()

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        t = self._time
        if event == PinsEvent.EXEC_BEGIN:
            self._open[es.th_id] = (t.monotonic_ns(), t.thread_time_ns())
            return
        opened = self._open.pop(es.th_id, None)
        if opened is None or payload is None:
            return
        name = payload.task_class.name
        dw = t.monotonic_ns() - opened[0]
        dc = t.thread_time_ns() - opened[1]
        with self._lock:
            self.wall_ns[name] = self.wall_ns.get(name, 0) + dw
            self.cpu_ns[name] = self.cpu_ns.get(name, 0) + dc
            self.count[name] = self.count.get(name, 0) + 1


class HWCountersModule(PinsModule):
    """Hardware counters per task via perf_event_open — the pins/papi
    analog (ref: parsec/mca/pins/papi/). One counter set per worker
    thread (opened lazily on that thread, like PAPI's per-ES event
    sets); EXEC begin/end deltas accumulate per task class.

    ``available`` is False when the kernel refuses PMU access
    (perf_event_paranoid, container seccomp) — enable() then no-ops,
    matching a reference build without PAPI."""

    name = "hw_counters"
    events = [PinsEvent.EXEC_BEGIN, PinsEvent.EXEC_END]
    DEFAULT_EVENTS = ["instructions", "cycles", "cache_misses"]

    def __init__(self, counter_names: Any = None) -> None:
        from .perfctr import perf_available
        self.counter_names = list(counter_names or self.DEFAULT_EVENTS)
        self.available = perf_available(self.counter_names)
        self._tls = threading.local()
        self.totals: Dict[str, Dict[str, int]] = {}
        self.count: Dict[str, int] = {}
        self._lock = threading.Lock()

    def enable(self) -> None:
        if not self.available:
            from ..utils import logging as _plog
            _plog.debug.verbose(
                1, "hw_counters: perf_event_open unavailable; disabled")
            return
        super().enable()

    def _set(self):
        s = getattr(self._tls, "set", None)
        if s is False:       # this thread's open already failed: stay off
            return None
        if s is None:
            from .perfctr import PerfCounterSet
            try:
                s = self._tls.set = PerfCounterSet.open(self.counter_names)
            except OSError as exc:
                self._tls.set = False
                # the init-time availability probe can pass and a
                # per-thread open still fail (fd exhaustion, thread-scoped
                # PMU refusal): degrade gracefully — instrumentation must
                # never take down the task execution path
                self.available = False
                from ..utils import logging as _plog
                _plog.debug.verbose(
                    1, "hw_counters: per-thread open failed (%s); disabled",
                    exc)
                return None
        return s

    def callback(self, es: Any, event: PinsEvent, payload: Any) -> None:
        s = self._set()
        if s is None:
            return
        if event == PinsEvent.EXEC_BEGIN:
            self._tls.begin = s.read()
            return
        begin = getattr(self._tls, "begin", None)
        if begin is None or payload is None:
            return
        self._tls.begin = None
        end = s.read()
        name = payload.task_class.name
        with self._lock:
            tot = self.totals.setdefault(
                name, {k: 0 for k in self.counter_names})
            for k, b, e in zip(self.counter_names, begin, end):
                tot[k] += e - b
            self.count[name] = self.count.get(name, 0) + 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-class mean counter values (e.g. instructions/task)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, tot in self.totals.items():
                n = max(1, self.count.get(name, 0))
                out[name] = {k: v / n for k, v in tot.items()}
        return out


# discoverable by (framework="pins", name) like the reference's MCA
# component tables (mca_repository.c); out-of-tree modules load by
# dotted path or entry point through the same repository
from ..utils import mca as _mca  # noqa: E402

for _cls in (TaskProfilerModule, PrintStealsModule, AlperfModule,
             IteratorsCheckerModule, TaskTimeModule, HWCountersModule):
    _mca.register("pins", _cls.name, _cls)
