#!/usr/bin/env python
"""Live counter-aggregation server (the aggregator_visu demo_server
analog). Run it, point ranks at it with ``--mca sde_push host:port``,
and it reprints the fleet counter table every ``--interval`` seconds.
The same port also answers ``GET /metrics`` with Prometheus text
exposition (per-rank last values, ``rank`` label), so a scraper can sit
directly on a running job.

    python tools/aggregator_server.py --port 9321
    # in the job's environment:
    PARSEC_MCA_sde_push=127.0.0.1:9321 python my_app.py
    # scrape:
    curl http://127.0.0.1:9321/metrics
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.profiling.aggregator import AggregatorServer  # noqa: E402


def print_fleet(fleet) -> None:
    counters = fleet["counters"]
    print(f"\n== {time.strftime('%H:%M:%S')} — {fleet['nb_pushes']} pushes, "
          f"{len(counters)} counters ==")
    if not counters:
        return
    wid = max(len(n) for n in counters)
    print(f"{'counter':<{wid}}  ranks      min        max        sum(last)")
    for name, agg in counters.items():
        f = agg["fleet"]
        print(f"{name:<{wid}}  {f['nb_ranks']:>5}  {f['min']:>9g}  "
              f"{f['max']:>9g}  {f['sum_of_last']:>9g}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9321)
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--max-seconds", type=float, default=0.0,
                    help="exit after this long (0 = run until ^C)")
    args = ap.parse_args(argv)
    srv = AggregatorServer(args.host, args.port).start()
    print(f"aggregator listening on {srv.address} "
          f"(PARSEC_MCA_sde_push={srv.address})")
    t0 = time.time()
    try:
        while True:
            time.sleep(args.interval)
            print_fleet(srv.fleet())
            if args.max_seconds and time.time() - t0 > args.max_seconds:
                break
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
