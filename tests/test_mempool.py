"""Mempool: per-thread freelists with owner-returning frees
(ref: parsec/mempool.c, private_mempool.c)."""
import threading

import numpy as np

from parsec_tpu.core.mempool import Mempool


def test_allocate_recycles():
    made = []

    def ctor():
        b = np.empty((64,), np.float32)
        made.append(b)
        return b

    pool = Mempool(ctor)
    a = pool.allocate()
    pool.free(a)
    b = pool.allocate()
    assert b is a                   # recycled, not re-constructed
    assert pool.nb_constructed() == 1
    pool.free(b)
    assert pool.nb_cached() == 1


def test_cross_thread_free_returns_to_owner():
    pool = Mempool(lambda: np.empty((8,), np.float32))
    elt = pool.allocate()           # owned by the main thread's freelist
    owner = pool.thread_mempool()

    def worker():
        pool.free(elt)              # freed from another thread

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert len(owner) == 1          # landed in the OWNER's list
    assert pool.allocate() is elt   # main thread gets it back


def test_max_cached_bounds_retention():
    pool = Mempool(lambda: object(), max_cached=2)
    elts = [pool.allocate() for _ in range(4)]
    for e in elts:
        pool.free(e)
    assert pool.nb_cached() == 2    # the rest went to GC


def test_foreign_element_free_is_noop():
    pool = Mempool(lambda: object())
    pool.free(object())             # not pool-constructed: dropped quietly
    assert pool.nb_cached() == 0


def test_per_thread_freelists_are_private():
    pool = Mempool(lambda: object())
    got = {}
    barrier = threading.Barrier(3)  # overlap: thread idents are reused
    # after join, which would alias freelists

    def worker(name):
        barrier.wait()
        e = pool.allocate()
        pool.free(e)
        got[name] = pool.thread_mempool()
        barrier.wait()

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    lists = set(id(tm) for tm in got.values())
    assert len(lists) == 3          # one freelist per thread
    assert pool.nb_cached() == 3
