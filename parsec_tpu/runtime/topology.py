"""Host topology discovery — the hwloc analog over Linux sysfs.

Reference behavior: parsec_hwloc.c builds an hwloc tree (machine >
package > NUMA > L3 > L2 > core > PU) that the schedulers consult for
locality-aware stealing (lfq's NUMA-neighbor steal chain,
parsec/mca/sched/lfq/sched_lfq_module.c:59-199; lhq's hwloc-level
hierarchy). This module reads the same facts from
``/sys/devices/system/cpu`` and ``/sys/devices/system/node`` without an
hwloc dependency: SMT siblings, L2/L3 sharing domains, NUMA nodes and
packages, reduced to an integer distance and a locality-sorted steal
order.

Distances (smaller = closer):
  0 same PU | 1 SMT sibling (same core) | 2 shares L2 | 3 shares L3 |
  4 same NUMA node | 5 same package | 6 same machine
"""
from __future__ import annotations

import glob
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

__all__ = ["CPUInfo", "HostTopology", "parse_cpulist"]


def parse_cpulist(text: str) -> List[int]:
    """'0-3,8,10-11' -> [0,1,2,3,8,10,11] (sysfs cpulist format)."""
    out: List[int] = []
    for part in text.strip().split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


def _read(path: str) -> Optional[str]:
    try:
        with open(path) as fh:
            return fh.read().strip()
    except OSError:
        return None


@dataclass(frozen=True)
class CPUInfo:
    """One logical PU. Group ids are arbitrary but equal iff shared;
    -1 = unknown (treated as its own singleton group)."""
    cpu: int
    core: int = -1        # (package, core_id) collapsed to a group id
    l2: int = -1
    l3: int = -1
    numa: int = -1
    package: int = -1


class HostTopology:
    """Locality oracle over a set of CPUInfo records."""

    def __init__(self, cpus: Dict[int, CPUInfo]) -> None:
        self.cpus = dict(cpus)

    # ------------------------------------------------------------------ #
    @classmethod
    def discover(cls, cpu_root: str = "/sys/devices/system/cpu",
                 node_root: str = "/sys/devices/system/node"
                 ) -> "HostTopology":
        cpus: Dict[int, CPUInfo] = {}
        numa_of: Dict[int, int] = {}
        for npath in sorted(glob.glob(os.path.join(node_root, "node[0-9]*"))):
            nid = int(os.path.basename(npath)[4:])
            lst = _read(os.path.join(npath, "cpulist"))
            if lst:
                for c in parse_cpulist(lst):
                    numa_of[c] = nid
        for cpath in sorted(glob.glob(os.path.join(cpu_root, "cpu[0-9]*"))):
            try:
                cpu = int(os.path.basename(cpath)[3:])
            except ValueError:
                continue
            topo = os.path.join(cpath, "topology")
            pkg = _read(os.path.join(topo, "physical_package_id"))
            core_id = _read(os.path.join(topo, "core_id"))
            package = int(pkg) if pkg is not None else -1
            # core group: same (package, core_id) == SMT siblings
            core = (package << 16) | int(core_id) \
                if core_id is not None and package >= 0 else -1
            l2 = l3 = -1
            for idx in sorted(glob.glob(os.path.join(cpath, "cache",
                                                     "index[0-9]*"))):
                lvl = _read(os.path.join(idx, "level"))
                typ = _read(os.path.join(idx, "type")) or ""
                shared = _read(os.path.join(idx, "shared_cpu_list"))
                if lvl is None or shared is None or typ == "Instruction":
                    continue
                group = min(parse_cpulist(shared), default=-1)
                if lvl == "2":
                    l2 = group
                elif lvl == "3":
                    l3 = group
            cpus[cpu] = CPUInfo(cpu=cpu, core=core, l2=l2, l3=l3,
                                numa=numa_of.get(cpu, -1), package=package)
        if not cpus:  # sysfs unavailable: flat machine
            n = os.cpu_count() or 1
            cpus = {c: CPUInfo(cpu=c) for c in range(n)}
        return cls(cpus)

    # ------------------------------------------------------------------ #
    def distance(self, a: int, b: int) -> int:
        if a == b:
            return 0
        ia = self.cpus.get(a)
        ib = self.cpus.get(b)
        if ia is None or ib is None:
            return 6
        if ia.core != -1 and ia.core == ib.core:
            return 1
        if ia.l2 != -1 and ia.l2 == ib.l2:
            return 2
        if ia.l3 != -1 and ia.l3 == ib.l3:
            return 3
        if ia.numa != -1 and ia.numa == ib.numa:
            return 4
        if ia.package != -1 and ia.package == ib.package:
            return 5
        return 6

    def steal_order(self, cpu: int,
                    candidates: Iterable[int]) -> List[int]:
        """Candidates sorted nearest-first (stable by id within a
        distance level) — lfq's NUMA-neighbor chain generalized."""
        return sorted((c for c in candidates if c != cpu),
                      key=lambda c: (self.distance(cpu, c), c))

    def group_of(self, cpu: int, level: str = "l3") -> int:
        """The sharing-domain id of ``cpu`` at ``level`` (l2|l3|numa|
        package); unknown -> the cpu's own id (singleton group)."""
        info = self.cpus.get(cpu)
        if info is None:
            return cpu
        val = getattr(info, level, -1)
        return val if val != -1 else cpu

    def levels_of(self, cpu: int) -> Dict[str, int]:
        return {lvl: self.group_of(cpu, lvl)
                for lvl in ("core", "l2", "l3", "numa", "package")}


_cached: Optional[HostTopology] = None


def host_topology() -> HostTopology:
    global _cached
    if _cached is None:
        _cached = HostTopology.discover()
    return _cached
