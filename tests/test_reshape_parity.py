"""Reshape scenario parity with the reference's dedicated suite.

Scenario <-> test map (ref: /root/reference/tests/collections/reshape/):

| reference scenario file                              | test here                                   |
|------------------------------------------------------|---------------------------------------------|
| local_no_reshape.jdf                                 | test_local_no_reshape_type_remote_ignored   |
| avoidable_reshape.jdf                                | test_avoidable_reshape_no_spurious_copy     |
| local_input_reshape.jdf                              | test_local_input_reshape_masked_writeback   |
| local_output_reshape.jdf                             | test_local_output_reshape_on_out_dep        |
| local_read_reshape.jdf                               | test_local_read_reshape_from_memory         |
| local_input_LU_LL.jdf                                | test_local_input_LU_LL_chained_reshapes     |
| input_dep_single_copy_reshape.jdf                    | test_input_dep_single_copy_shared           |
| remote_read_reshape.jdf                              | test_remote_read_reshape                    |
| remote_no_re_reshape.jdf                             | test_remote_no_re_reshape                   |
| remote_multiple_outs_same_pred_flow.jdf              | test_remote_multiple_outs_same_pred_flow    |
| remote_multiple_outs_same_pred_flow_multiple_deps.jdf| test_remote_multiple_outs_multiple_deps     |

Property semantics under test (parsec_reshape.c; dsl/ptg/runtime.py
_input_dtt):
- ``[type=T]``        local reshape: consumers get a converted copy;
- ``[type_remote=T]`` wire type only: reshapes cross-rank edges, is
                      IGNORED on local edges (pointer semantics);
- ``[type_data=T]``   datatype reading from / writing back to the matrix
                      (masked writeback: elements outside the region keep
                      their old values).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.comm import RemoteDepEngine
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg

from test_comm_multirank import spmd

N = 4


def _base():
    return (np.arange(N * N, dtype=np.float64).reshape(N, N) + 1.0)


def _run_local(jdf_text, name, base=None, extra=None):
    ctx = parsec_tpu.init(nb_cores=1)
    try:
        coll = TwoDimBlockCyclic(N, N, N, N, dtype=np.float64)
        coll.name = "descA"
        base = _base() if base is None else base
        coll.from_numpy(base.copy())
        out = {}
        env = {"descA": coll, "out": out}
        if extra:
            env.update(extra)
        tp = ptg.compile_jdf(jdf_text, name=name).new(**env)
        ctx.add_taskpool(tp)
        ctx.wait()
        return coll.data_of(0, 0).host_copy().payload, out, tp
    finally:
        ctx.fini()


# --------------------------------------------------------------------- #
# local_no_reshape.jdf: only type_remote on the edges -> the ORIGINAL   #
# copy is passed (no conversion); zeroing it zeroes the full tile       #
# --------------------------------------------------------------------- #
LOCAL_NO_RESHAPE = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A SET_ZEROS( 0 )   [type_remote=lower]
BODY
{
}
END

SET_ZEROS(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- A READ_A( 0 )      [type_remote=lower]
     -> descA( 0, 0 )
BODY
{
    A[:] = 0.0
}
END
"""


def test_local_no_reshape_type_remote_ignored():
    tile, _, tp = _run_local(LOCAL_NO_RESHAPE, "local_no_reshape")
    np.testing.assert_array_equal(tile, np.zeros((N, N)))
    assert tp.reshape_repo.stats["conversions"] == 0


# --------------------------------------------------------------------- #
# avoidable_reshape.jdf: DEFAULT type everywhere -> no spurious copies  #
# --------------------------------------------------------------------- #
AVOIDABLE = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )      [type_data=full]
     -> A WRITE_A( 0 )
BODY
{
}
END

WRITE_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- A READ_A( 0 )
     -> descA( 0, 0 )      [type=full type_data=full]
BODY
{
    A[:] = 0.0
}
END
"""


def test_avoidable_reshape_no_spurious_copy():
    tile, _, tp = _run_local(AVOIDABLE, "avoidable")
    np.testing.assert_array_equal(tile, np.zeros((N, N)))
    assert tp.reshape_repo.stats["conversions"] == 0


# --------------------------------------------------------------------- #
# local_input_reshape.jdf: [type] on an input dep -> converted copy to  #
# successors; masked [type_data] writeback leaves the upper part intact #
# --------------------------------------------------------------------- #
LOCAL_INPUT_RESHAPE = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A SET_ZEROS( 0 )
BODY
{
}
END

SET_ZEROS(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- A READ_A( 0 )      [type=lower]
     -> A WRITE_A( 0 )
BODY
{
    out['seen_by_zeros'] = np.array(A)
    A[:] = 0.0
}
END

WRITE_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- A SET_ZEROS( 0 )
     -> descA( 0, 0 )      [type=lower type_data=lower]
BODY
{
}
END
"""


def test_local_input_reshape_masked_writeback():
    base = _base()
    tile, out, tp = _run_local(LOCAL_INPUT_RESHAPE, "local_input_reshape")
    # the consumer saw the lower-masked conversion...
    np.testing.assert_array_equal(out["seen_by_zeros"], np.tril(base))
    # ...and the masked writeback zeroed ONLY the lower region
    expect = np.triu(base, 1)
    np.testing.assert_array_equal(tile, expect)
    assert tp.reshape_repo.stats["conversions"] == 1


# --------------------------------------------------------------------- #
# local_output_reshape.jdf: [type] on the producer's OUT dep            #
# --------------------------------------------------------------------- #
LOCAL_OUTPUT_RESHAPE = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A SET_ZEROS( 0 )   [type=lower]
BODY
{
}
END

SET_ZEROS(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- A READ_A( 0 )
     -> descA( 0, 0 )      [type=lower type_data=lower]
BODY
{
    out['seen_by_zeros'] = np.array(A)
    A[:] = 0.0
}
END
"""


def test_local_output_reshape_on_out_dep():
    base = _base()
    tile, out, tp = _run_local(LOCAL_OUTPUT_RESHAPE, "local_output_reshape")
    np.testing.assert_array_equal(out["seen_by_zeros"], np.tril(base))
    np.testing.assert_array_equal(tile, np.triu(base, 1))
    assert tp.reshape_repo.stats["conversions"] == 1


# --------------------------------------------------------------------- #
# local_read_reshape.jdf: [type_data] reading from the matrix           #
# --------------------------------------------------------------------- #
LOCAL_READ_RESHAPE = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )      [type_data=lower]
     -> descA( 0, 0 )      [type=lower type_data=lower]
BODY
{
    out['seen'] = np.array(A)
    A[:] = 0.0
}
END
"""


def test_local_read_reshape_from_memory():
    base = _base()
    tile, out, tp = _run_local(LOCAL_READ_RESHAPE, "local_read_reshape")
    np.testing.assert_array_equal(out["seen"], np.tril(base))
    np.testing.assert_array_equal(tile, np.triu(base, 1))
    # the home tile never got mutated by the read-side conversion
    assert tp.reshape_repo.stats["conversions"] == 1


# --------------------------------------------------------------------- #
# local_input_LU_LL.jdf: chained different reshapes of the same flow    #
# --------------------------------------------------------------------- #
LU_LL = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A UP( 0 )
     -> A LO( 0 )
BODY
{
}
END

UP(k)
k = 0 .. 0
: descA( 0, 0 )
READ A <- A READ_A( 0 )    [type=upper]
BODY
{
    out['upper'] = np.array(A)
}
END

LO(k)
k = 0 .. 0
: descA( 0, 0 )
READ A <- A READ_A( 0 )    [type=lower]
BODY
{
    out['lower'] = np.array(A)
}
END
"""


def test_local_input_LU_LL_chained_reshapes():
    base = _base()
    _, out, tp = _run_local(LU_LL, "lu_ll")
    np.testing.assert_array_equal(out["upper"], np.triu(base))
    np.testing.assert_array_equal(out["lower"], np.tril(base))
    # two DIFFERENT types of the same copy: two conversions
    assert tp.reshape_repo.stats["conversions"] == 2


# --------------------------------------------------------------------- #
# input_dep_single_copy_reshape.jdf: N consumers, one shared conversion #
# --------------------------------------------------------------------- #
SINGLE_COPY = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A CONS( 0 .. 3 )
BODY
{
}
END

CONS(k)
k = 0 .. 3
: descA( 0, 0 )
READ A <- A READ_A( 0 )    [type=lower]
BODY
{
    out[('seen', k)] = np.array(A)
}
END
"""


def test_input_dep_single_copy_shared():
    base = _base()
    _, out, tp = _run_local(SINGLE_COPY, "single_copy")
    for k in range(4):
        np.testing.assert_array_equal(out[("seen", k)], np.tril(base))
    # all four consumers shared ONE converted copy
    assert tp.reshape_repo.stats["conversions"] == 1
    assert tp.reshape_repo.stats["hits"] >= 3


# --------------------------------------------------------------------- #
# remote scenarios: 2 ranks over the in-process fabric                  #
# --------------------------------------------------------------------- #
def _run_remote(jdf_text, name, base=None):
    outs = [dict() for _ in range(2)]
    tiles = [None, None]

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            coll = TwoDimBlockCyclic(2 * N, N, N, N, P=2, Q=1, nodes=2,
                                     rank=rank, dtype=np.float64)
            coll.name = "descA"
            b = _base() if base is None else base
            coll.from_numpy(np.vstack([b, np.zeros((N, N))]))
            tp = ptg.compile_jdf(jdf_text, name=name).new(
                descA=coll, out=outs[rank], rank=rank, nb_ranks=2)
            ctx.add_taskpool(tp)
            ctx.wait()
            if coll.rank_of(1, 0) == rank:
                tiles[1] = np.array(coll.data_of(1, 0).host_copy().payload)
            return tp.reshape_repo.stats.copy()
        finally:
            ctx.fini()

    results, _ = spmd(2, rank_fn)
    return outs, tiles, results


REMOTE_READ = """
descA [ type="collection" ]
out [ type="object" ]

Prod(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A Cons( 0 )
BODY
{
    A += 1.0
}
END

Cons(k)
k = 0 .. 0
: descA( 1, 0 )
READ A <- A Prod( 0 )      [type_remote=lower]
BODY
{
    out['seen'] = np.array(A)
}
END
"""


def test_remote_read_reshape():
    base = _base()
    outs, _, results = _run_remote(REMOTE_READ, "remote_read")
    np.testing.assert_array_equal(outs[1]["seen"], np.tril(base + 1.0))
    assert "seen" not in outs[0]
    # conversion happened exactly once, on the wire path
    assert results[0]["conversions"] + results[1]["conversions"] == 1


REMOTE_NO_RE_RESHAPE = """
descA [ type="collection" ]
out [ type="object" ]

Prod(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )      [type_data=lower]
     -> A Cons( 0 )        [type=lower]
BODY
{
}
END

Cons(k)
k = 0 .. 0
: descA( 1, 0 )
READ A <- A Prod( 0 )      [type_remote=lower]
BODY
{
    out['seen'] = np.array(A)
}
END
"""


def test_remote_no_re_reshape():
    """The producer's copy is already lower-typed; the matching
    type_remote on the consumer edge must NOT reconvert."""
    base = _base()
    outs, _, results = _run_remote(REMOTE_NO_RE_RESHAPE, "no_re_reshape")
    np.testing.assert_array_equal(outs[1]["seen"], np.tril(base))
    # exactly one conversion total (producer side); the consumer's
    # type_remote found a compatible copy
    assert results[0]["conversions"] + results[1]["conversions"] == 1


REMOTE_MULTI_OUTS = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A TASK_A( 0 )      [type_remote=upper]
     -> B TASK_A( 0 )      [type_remote=lower]
BODY
{
}
END

TASK_A(k)
k = 0 .. 0
: descA( 1, 0 )
RW A <- A READ_A( 0 )      [type_remote=upper]
     -> descA( 1, 0 )
READ B <- A READ_A( 0 )    [type_remote=lower]
BODY
{
    out['A'] = np.array(A)
    out['B'] = np.array(B)
    A[:] = np.triu(A) + np.tril(B, -1)
}
END
"""


def test_remote_multiple_outs_same_pred_flow():
    """One producer flow shipped under TWO wire types to two flows of the
    same consumer (the reference's upper+lower merge)."""
    base = _base()
    outs, tiles, _ = _run_remote(REMOTE_MULTI_OUTS, "multi_outs")
    np.testing.assert_array_equal(outs[1]["A"], np.triu(base))
    np.testing.assert_array_equal(outs[1]["B"], np.tril(base))
    np.testing.assert_array_equal(tiles[1],
                                  np.triu(base) + np.tril(base, -1))


REMOTE_MULTI_DEPS = """
descA [ type="collection" ]
out [ type="object" ]

READ_A(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A UP( 0 )          [type_remote=upper]
     -> A LO( 0 )          [type_remote=lower]
BODY
{
}
END

UP(k)
k = 0 .. 0
: descA( 1, 0 )
READ A <- A READ_A( 0 )    [type_remote=upper]
BODY
{
    out['upper'] = np.array(A)
}
END

LO(k)
k = 0 .. 0
: descA( 1, 0 )
READ A <- A READ_A( 0 )    [type_remote=lower]
BODY
{
    out['lower'] = np.array(A)
}
END
"""


def test_remote_multiple_outs_multiple_deps():
    """Same producer flow feeding DIFFERENT consumer classes under
    different wire types."""
    base = _base()
    outs, _, _ = _run_remote(REMOTE_MULTI_DEPS, "multi_deps")
    np.testing.assert_array_equal(outs[1]["upper"], np.triu(base))
    np.testing.assert_array_equal(outs[1]["lower"], np.tril(base))


# --------------------------------------------------------------------- #
# masked writeback must survive IN-PLACE mutation of a home-bound flow  #
# (no conversion on the input side: the body would otherwise clobber    #
# the destination's out-of-region values before the mask applies)       #
# --------------------------------------------------------------------- #
HOME_MASKED_WB = """
descA [ type="collection" ]
out [ type="object" ]

ZERO_LOWER(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> descA( 0, 0 )      [type_data=lower]
BODY
{
    A[:] = 0.0
}
END
"""


def test_masked_writeback_of_home_bound_flow():
    base = _base()
    tile, _, _ = _run_local(HOME_MASKED_WB, "home_masked")
    # body zeroed its (detached) view in place; only the lower region
    # lands in memory — the upper part keeps the ORIGINAL values
    np.testing.assert_array_equal(tile, np.triu(base, 1))


# --------------------------------------------------------------------- #
# the detached clone for a masked writeback must hold the NEWEST tile   #
# version — a prior device chore may have left it on the accelerator    #
# (the lazy already-home path); a stale host snapshot is silent wrong   #
# results (round-2 advisor finding, dsl/ptg/runtime.py masked binding)  #
# --------------------------------------------------------------------- #
DEVICE_THEN_MASKED_WB = """
descA [ type="collection" ]
out [ type="object" ]

Dev(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> descA( 0, 0 )
CTL C -> C WB( 0 )
BODY [type=tpu]
{
    A = A * 3.0
}
END

WB(k)
k = 0 .. 0
: descA( 0, 0 )
CTL C <- C Dev( 0 )
RW A <- descA( 0, 0 )
     -> descA( 0, 0 )      [type_data=lower]
BODY
{
    A = A + 10.0
}
END
"""


def test_masked_writeback_sees_device_resident_newest():
    base = _base()
    tile, _, _ = _run_local(DEVICE_THEN_MASKED_WB, "dev_masked")
    # Dev's chore leaves A*3 newest ON DEVICE (already-home lazy path);
    # WB's masked binding must pull that version before detaching:
    # lower gets 3*base+10, the preserved upper region must be 3*base
    # (NOT the stale pre-device values)
    expect = np.where(np.tril(np.ones((N, N), bool)),
                      3.0 * base + 10.0, 3.0 * base)
    np.testing.assert_array_equal(tile, expect)
