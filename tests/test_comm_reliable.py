"""Reliable TCP sessions (ISSUE 10): reconnect + seq-numbered replay.

A transient link fault (flap, idle-timeout RST, NAT drop) must not
masquerade as rank death: with ``comm_reconnect_timeout`` set the torn
peer goes SUSPECT, a reconnector re-establishes the link, the sender
replays the unacked gap and the receiver dedups by seq — exactly-once
delivery across the fault, bit-identical to a failure-free run. Only
budget exhaustion (or a protocol violation) escalates to the
``RankFailedError`` fail-fast/elastic path, and a mixed-version peer
(no ``"rs"`` capability) keeps today's fail-fast bit for bit.
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from parsec_tpu.comm.tcp import RankFailedError, TCPCommEngine, free_ports
from parsec_tpu.comm import wire
from parsec_tpu.ft.inject import FaultInjector

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TAG = 100


def _engines(n, **kw):
    ports = free_ports(n)
    eps = [("127.0.0.1", p) for p in ports]
    import concurrent.futures as cf
    with cf.ThreadPoolExecutor(n) as ex:
        return list(ex.map(lambda r: TCPCommEngine(r, eps, **kw), range(n)))


def _wait(pred, timeout=10.0, step=0.005):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _peer_obj(e, r):
    with e._conn_cond:
        return e._peers.get(r)


def _wait_session(e0, e1, timeout=10.0):
    """Both directions negotiated the "rs" capability."""
    ok = _wait(lambda: (_peer_obj(e0, e1.rank) is not None
                        and _peer_obj(e0, e1.rank).rs_ok
                        and _peer_obj(e1, e0.rank) is not None
                        and _peer_obj(e1, e0.rank).rs_ok), timeout)
    assert ok, "session capability never negotiated"


def test_session_flap_delivers_exactly_once():
    """A hard socket close mid-stream is absorbed: the peers reconnect
    (RECONNECTS >= 1), every message before and after the flap arrives
    exactly once and in order, and nobody is declared dead."""
    e0, e1 = _engines(2, reconnect_timeout=10.0)
    got = []
    e1.tag_register(TAG, lambda src, p: got.append(p["i"]))
    try:
        _wait_session(e0, e1)
        for i in range(5):
            e0.send_am(1, TAG, {"i": i})
        assert _wait(lambda: (e1.progress(), len(got) >= 5)[1])
        # flap: hard-close the established socket (both ends see it)
        _peer_obj(e0, 1).sock.shutdown(socket.SHUT_RDWR)
        assert _wait(lambda: e0.wire_stats["reconnects"] >= 1
                     and e1.wire_stats["reconnects"] >= 1)
        for i in range(5, 8):
            e0.send_am(1, TAG, {"i": i})
        assert _wait(lambda: (e1.progress(), len(got) >= 8)[1])
        assert got == list(range(8))   # exactly once, in order
        assert not e0.dead_peers and not e1.dead_peers
        assert not e0.peer_suspect(1) and not e1.peer_suspect(0)
        assert e0.suspect_ms() > 0   # the episode was accounted
    finally:
        e0.fini()
        e1.fini()


def test_replay_after_flap_bit_identical():
    """Frames lost in flight (sent into a peer whose kernel already
    tore the connection) are REPLAYED from the window after the
    reconnect: the receiver observes the exact same payload sequence,
    bit for bit, as a failure-free run."""
    e0, e1 = _engines(2, reconnect_timeout=10.0)
    got = []
    e1.tag_register(TAG, lambda src, p: got.append(np.array(p["arr"])))
    rng = np.random.RandomState(7)
    sent = [rng.rand(64).astype(np.float64) for _ in range(12)]
    try:
        _wait_session(e0, e1)
        # tear the RECEIVER side first: the sender's next writes land
        # in a dead connection (accepted-but-lost) and must replay
        _peer_obj(e1, 0).sock.shutdown(socket.SHUT_RDWR)
        for a in sent:
            e0.send_am(1, TAG, {"arr": a})
        assert _wait(lambda: (e1.progress(), len(got) >= 12)[1], 15.0)
        assert len(got) == 12
        for a, b in zip(sent, got):
            np.testing.assert_array_equal(a, b)   # bit-identical
        assert e0.wire_stats["reconnects"] >= 1
        assert not e0.dead_peers and not e1.dead_peers
    finally:
        e0.fini()
        e1.fini()


def test_injected_dup_delivers_am_exactly_once():
    """``ft_inject dup`` on a session link duplicates the FRAME (same
    seq) at the wire: the receiver's dedup keeps the active message
    exactly-once and counts the duplicate."""
    e0, e1 = _engines(2, reconnect_timeout=10.0)
    got = []
    e1.tag_register(TAG, lambda src, p: got.append(p["i"]))
    try:
        _wait_session(e0, e1)
        e0._ft = FaultInjector.from_spec("dup:rank=0:nth=2", rank=0)
        for i in range(4):
            e0.send_am(1, TAG, {"i": i})
        assert _wait(lambda: (e1.progress(), len(got) >= 4)[1])
        assert got == [0, 1, 2, 3]   # the duplicated AM ran ONCE
        assert _wait(lambda: e1.wire_stats["dup_dropped"] >= 1)
        assert e0._ft.stats["duplicated"] == 1
    finally:
        e0.fini()
        e1.fini()


def test_mixed_version_peer_keeps_fail_fast():
    """One end without the knob never advertises "rs": a torn socket
    is rank death on the spot, exactly the pre-session contract."""
    e0, e1 = _engines(2, reconnect_timeout=0.0)
    # e0 re-creates nothing: BOTH engines came up session-less; flip
    # e0's local enable to prove the gate is the NEGOTIATION, not the
    # local knob alone
    try:
        assert _wait(lambda: _peer_obj(e0, 1) is not None
                     and _peer_obj(e0, 1).hello_seen)
        assert not _peer_obj(e0, 1).rs_ok
        _peer_obj(e1, 0).sock.shutdown(socket.SHUT_RDWR)
        assert _wait(lambda: 1 in e0.dead_peers or 0 in e1.dead_peers)
        assert e0.wire_stats["reconnects"] == 0
        assert e1.wire_stats["reconnects"] == 0
        assert not e0.peer_suspect(1) and not e1.peer_suspect(0)
        dead_side = e0 if 1 in e0.dead_peers else e1
        with pytest.raises(RankFailedError):
            dead_side.send_am(1 - dead_side.rank, TAG, {"x": 1})
    finally:
        e0._closing = True
        e1._closing = True
        e0.fini()
        e1.fini()


def test_budget_exhaustion_escalates_to_rank_failed():
    """A link that never comes back exhausts ``comm_reconnect_timeout``
    and escalates through the SAME failure funnel a torn session-less
    socket takes: dead_peers + on_peer_failure + RankFailedError."""
    e0, e1 = _engines(2, reconnect_timeout=0.6, reconnect_backoff=0.05)
    failures = []
    e1.on_peer_failure = lambda peer, reason: failures.append((peer, reason))
    try:
        _wait_session(e0, e1)
        # a PERMANENT link fault: the disconnect directive hard-closes
        # the socket and rejects every reconnect (dial-out and
        # accepted resume alike) forever
        e0._ft = FaultInjector.from_spec("disconnect:rank=0:nth=1", rank=0)
        t0 = time.time()
        e0.send_am(1, TAG, {"x": 0})   # triggers the disconnect
        assert _wait(lambda: 0 in e1.dead_peers and 1 in e0.dead_peers,
                     15.0)
        assert time.time() - t0 < 12.0
        assert failures and failures[0][0] == 0
        assert "budget exhausted" in failures[0][1]
        with pytest.raises(RankFailedError):
            e1.send_am(0, TAG, {"x": 1})
        assert not e1.peer_suspect(0) and not e0.peer_suspect(1)
        assert e0.wire_stats["reconnects"] == 0
        assert e1.wire_stats["reconnects"] == 0
    finally:
        e0._closing = True
        e1._closing = True
        e1.fini()
        e0.fini()


def test_detector_defers_during_in_budget_flap():
    """With heartbeats ON and a flap LONGER than the heartbeat timeout
    but inside the reconnect budget, the detector must NOT evict: the
    session layer owns the verdict while the link is torn, and the
    resume resets the silence baseline."""
    from parsec_tpu.ft.detector import HeartbeatDetector
    e0, e1 = _engines(2, reconnect_timeout=10.0)
    det = HeartbeatDetector(e0, interval=0.05, timeout=0.3).start()
    got = []
    e1.tag_register(TAG, lambda src, p: got.append(p["i"]))
    try:
        _wait_session(e0, e1)
        assert _wait(lambda: det.is_established(1), 10.0)
        # flap with the link held DOWN for 0.6 s (> 2x the hb timeout):
        # the injector rejects reconnects until the duration elapses
        e0._ft = FaultInjector.from_spec(
            "flap:rank=0:nth=1:duration=0.6", rank=0)
        e0.send_am(1, TAG, {"i": 0})
        assert _wait(lambda: e0.peer_suspect(1), 5.0)
        time.sleep(0.8)   # well past the heartbeat deadline
        assert det.evictions == 0
        assert 1 not in e0.dead_peers
        assert _wait(lambda: e0.wire_stats["reconnects"] >= 1, 10.0)
        e0.send_am(1, TAG, {"i": 1})
        assert _wait(lambda: (e1.progress(), len(got) >= 2)[1])
        assert got == [0, 1]   # the flapped frame itself was not lost
        time.sleep(0.5)        # a few detector ticks after the resume
        assert det.evictions == 0 and 1 not in e0.dead_peers
    finally:
        det.stop()
        e0.fini()
        e1.fini()


def test_chunked_transfer_survives_flap():
    """A flap in the middle of a stream of chunked (multi-frame) bulk
    messages: half-landed transfers stay parked on the peer, the
    replayed chunks complete them, and every payload arrives intact."""
    e0, e1 = _engines(2, reconnect_timeout=10.0, chunk_bytes=1 << 12)
    got = []
    e1.tag_register(TAG, lambda src, p: got.append(
        (p["i"], np.array(p["arr"]))))
    rng = np.random.RandomState(3)
    payloads = [rng.rand(8192).astype(np.float64) for _ in range(16)]
    try:
        _wait_session(e0, e1)

        def sender():
            for i, a in enumerate(payloads):
                e0.send_am(1, TAG, {"i": i, "arr": a})

        t = threading.Thread(target=sender, daemon=True)
        t.start()
        time.sleep(0.002)   # land the tear somewhere inside the stream
        _peer_obj(e1, 0).sock.shutdown(socket.SHUT_RDWR)
        t.join(10)
        assert not t.is_alive()
        assert _wait(lambda: (e1.progress(), len(got) >= 16)[1], 20.0)
        assert [i for i, _ in got] == list(range(16))
        for i, arr in got:
            np.testing.assert_array_equal(arr, payloads[i])
        assert e0.wire_stats["reconnects"] >= 1
        assert not e0.dead_peers and not e1.dead_peers
    finally:
        e0.fini()
        e1.fini()


def test_quantized_transfer_replays_bit_identical_after_flap():
    """Session-layer x quantized-codec interplay (ISSUE 14): the lossy
    encoding happens at ENQUEUE, before the K_SEQ envelope, so the
    replay window retains the ENCODED bytes — a flap mid-stream
    replays them and the receiver observes byte-for-byte the same
    quantized values a failure-free quantized run delivers (asserted
    against wire.qdq_array, which IS that value by construction)."""
    e0, e1 = _engines(2, reconnect_timeout=10.0, chunk_bytes=1 << 12,
                      quantize="int8")
    got = []
    e1.tag_register(TAG, lambda src, p: got.append(
        (p["i"], np.array(p["arr"]))))
    rng = np.random.RandomState(21)
    payloads = [rng.rand(8192).astype(np.float64) for _ in range(16)]
    try:
        _wait_session(e0, e1)
        p0 = _peer_obj(e0, 1)
        assert _wait(lambda: (lambda: p0.qz_codec == "qint8")()), \
            "quantized codec never negotiated"

        def sender():
            for i, a in enumerate(payloads):
                e0.send_am(1, TAG, {"i": i, "arr": a, "_qz_ok": True})

        t = threading.Thread(target=sender, daemon=True)
        t.start()
        time.sleep(0.002)   # land the tear somewhere inside the stream
        _peer_obj(e1, 0).sock.shutdown(socket.SHUT_RDWR)
        t.join(10)
        assert not t.is_alive()
        assert _wait(lambda: (e1.progress(), len(got) >= 16)[1], 20.0)
        assert [i for i, _ in got] == list(range(16))
        for i, arr in got:
            np.testing.assert_array_equal(
                arr, wire.qdq_array(payloads[i], "qint8"))
        assert e0.wire_stats["reconnects"] >= 1
        assert e0.wire_stats["bufs_quantized"] == 16
        assert not e0.dead_peers and not e1.dead_peers
    finally:
        e0.fini()
        e1.fini()


def test_quantize_mixed_version_peer_negotiates_down_to_lossless():
    """A peer whose HELLO carries no "qz" capability (mixed version /
    knob unset on its side) must NEVER receive quantized buffers —
    the link silently stays lossless, bit for bit."""
    # e0 wants int8; e1 runs with the knob unset and advertises no "qz"
    ports = free_ports(2)
    eps = [("127.0.0.1", p) for p in ports]
    import concurrent.futures as cf
    with cf.ThreadPoolExecutor(2) as ex:
        e0, e1 = list(ex.map(
            lambda r: TCPCommEngine(
                r, eps, reconnect_timeout=10.0, chunk_bytes=1 << 12,
                quantize="int8" if r == 0 else ""),
            range(2)))
    got = []
    e1.tag_register(TAG, lambda src, p: got.append(np.array(p["arr"])))
    try:
        _wait_session(e0, e1)
        p = _peer_obj(e0, 1)
        with p.cond:
            assert p.qz_codec is None   # negotiated down
        arr = np.random.RandomState(23).rand(8192)
        e0.send_am(1, TAG, {"arr": arr, "_qz_ok": True})
        assert _wait(lambda: (e1.progress(), got)[1], 15.0)
        np.testing.assert_array_equal(got[0], arr)   # bit-exact
        assert e0.wire_stats["bufs_quantized"] == 0
    finally:
        e0.fini()
        e1.fini()


def test_partial_frame_resume_claim():
    """The receiver's byte-level resume claim (satellite: `_recv_exact`
    truncation offset feeds the session instead of being discarded):
    only a partial that provably is the NEXT expected data frame may
    resume mid-body; anything else falls back to whole-frame replay."""
    e0, e1 = _engines(2, reconnect_timeout=5.0)
    try:
        _wait_session(e0, e1)
        p = _peer_obj(e0, 1)
        body = wire.pack_seq(0, 7) + b"x" * 32
        with p.cond:
            p.rs_rx_seq = 6
            # next expected frame (seq 7), truncated at 20 of 41 bytes
            p.rs_rx_partial = (len(body), bytearray(body[:20]))
            claim = e0._partial_claim_locked(p)
        assert claim == {"seq": 7, "off": 20}
        with p.cond:
            # NOT the next expected frame: claim refused and discarded
            p.rs_rx_seq = 7
            p.rs_rx_partial = (len(body), bytearray(body[:20]))
            assert e0._partial_claim_locked(p) is None
            assert p.rs_rx_partial is None
            # truncated inside the 9-byte K_SEQ header: no claim
            p.rs_rx_seq = 6
            p.rs_rx_partial = (len(body), bytearray(body[:4]))
            assert e0._partial_claim_locked(p) is None
    finally:
        e0.fini()
        e1.fini()


def _run_wave_ranks(nb_ranks, env_extra, timeout=240):
    ports = free_ports(nb_ranks)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra)
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "tests", "tcp_rank_main.py"),
         str(r), str(nb_ranks), ",".join(map(str, ports)), "0", "wave"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(nb_ranks)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, (p.returncode, out, err)
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return outs


def test_dpotrf_2rank_flap_matches_failure_free():
    """Acceptance leg: a 2-rank distributed-wave dpotrf over real OS
    processes with a chaos-injected link flap completes with ZERO rank
    evictions and numerics BIT-IDENTICAL to the failure-free run (the
    replay path re-delivers the exact bytes, so the factor cannot
    drift)."""
    clean = _run_wave_ranks(2, {})
    flapped = _run_wave_ranks(2, {
        "PARSEC_MCA_comm_reconnect_timeout": "20",
        "PARSEC_MCA_ft_inject": "flap:rank=0:nth=2:duration=0.05",
    })
    assert sum(o["wire"]["reconnects"] for o in flapped) >= 1, flapped
    for c, f in zip(clean, flapped):
        assert f["max_err"] == c["max_err"]   # bit-identical factor
    assert all(o["wire"]["reconnects"] == 0 for o in clean)


def test_redistribution_survives_flap_bit_identical():
    """ISSUE 19 chaos leg: a ``flap:rank=*`` landing in the MIDDLE of
    a planned collective redistribution (xfer/plan.py rounds over the
    session wire) is absorbed by reconnect + replay — the reshard
    completes bit-identical to the source, the exchanged plan digests
    agree, and nobody is declared dead."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.xfer import run_redistribution
    nb = 2
    lm = ln = 32
    src_np = np.random.RandomState(11).rand(lm, ln)
    engines = _engines(nb, reconnect_timeout=10.0)
    e0, e1 = engines
    try:
        _wait_session(e0, e1)
        # rank 0's 2nd post-install send is its round-1 bulk transfer:
        # the link tears with the frame unflushed — replay must carry it
        e0._ft = FaultInjector.from_spec(
            "flap:rank=*:nth=2:duration=0.05", rank=0)
        outs = [None] * nb
        errs = []

        def run(r):
            try:
                src = TwoDimBlockCyclic(
                    lm, ln, 4, 4, P=nb, Q=1, nodes=nb, rank=r,
                    dtype=np.float64).from_numpy(src_np)
                tgt = TwoDimBlockCyclic(
                    lm, ln, 4, 4, P=1, Q=nb, nodes=nb, rank=r,
                    dtype=np.float64).from_numpy(np.zeros((lm, ln)))
                tp = run_redistribution(src, tgt, engines[r],
                                        timeout=30.0)
                outs[r] = (tp.plan_digest,
                           {c: np.array(tgt.tile(*c))
                            for c in tgt.local_tiles()})
            except BaseException as exc:
                errs.append(exc)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(nb)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not any(t.is_alive() for t in ts), "redistribution hung"
        assert not errs, errs
        got = np.zeros((lm, ln))
        for r in range(nb):
            for (m, n), arr in outs[r][1].items():
                got[m * 4:m * 4 + arr.shape[0],
                    n * 4:n * 4 + arr.shape[1]] = arr
        np.testing.assert_array_equal(got, src_np)   # bit-identical
        assert outs[0][0] == outs[1][0]              # digests agree
        assert e0._ft.stats["flaps"] >= 1            # the fault fired
        assert e0.wire_stats["reconnects"] >= 1
        assert not e0.dead_peers and not e1.dead_peers
    finally:
        for e in engines:
            e.fini()
